"""Scale-out patterns: hierarchical (slice x worker) topology, per-worker
sharded ingest, and out-of-core streaming from files.

These are the paths that carry datasets no single host could hold
(parity: the reference's per-rank reads, table.cpp:788-795, its UCX
second transport tier, net/ucx/ucx_communicator.cpp:50-97, and its
streaming op-graph raison d'etre, ops/dis_join_op.cpp:21-72).
"""

import _mesh

_mesh.setup()

import os
import tempfile

import numpy as np
import pandas as pd

import cylon_tpu as ct
from cylon_tpu.ops_graph import DisJoinOp
from cylon_tpu.parallel import dist_aggregate, dist_to_pandas, scatter_table

# --- hierarchical mesh: 2 slices x 4 workers -------------------------
# On a real multi-host pod this happens automatically (one slice per
# process, DCN between slices); devices_per_slice forces the split so
# the two-stage exchange runs on the virtual mesh too.
env = ct.CylonEnv(ct.TPUConfig(devices_per_slice=4))
print(f"mesh: {dict(env.mesh.shape)}  hierarchical={env.is_hierarchical}")

rng = np.random.default_rng(5)
n = 5000
left = pd.DataFrame({"k": rng.integers(0, 300, n), "a": rng.normal(size=n)})
right = pd.DataFrame({"k": rng.integers(0, 300, n), "b": rng.normal(size=n)})

lt = ct.DataFrame(left)
rt = ct.DataFrame(right)
j = lt.merge(rt, on="k", env=env)   # intra-slice a2a, then DCN stage
print("hierarchical join rows:", len(j.to_pandas()),
      "(pandas:", len(left.merge(right, on="k")), ")")

# --- per-worker sharded ingest: one file per worker, no global buffer
with tempfile.TemporaryDirectory() as d:
    paths = []
    for s in range(env.world_size):
        p = os.path.join(d, f"part{s}.csv")
        pd.DataFrame({
            "k": rng.integers(0, 50, 400), "v": rng.normal(size=400),
        }).to_csv(p, index=False)
        paths.append(p)
    sharded = ct.read_csv_sharded(paths, env)
    total = float(dist_aggregate(env, sharded.table, "v", "sum"))
    print(f"sharded ingest: {env.world_size} files, v.sum() = {total:.3f}")

    # --- out-of-core: stream file chunks through the graph engine ----
    big = os.path.join(d, "big.csv")
    pd.DataFrame({"k": rng.integers(0, 200, 20_000),
                  "a": rng.normal(size=20_000)}).to_csv(big, index=False)
    g = DisJoinOp("k", how="inner", env=env)
    nchunks = 0
    for chunk in ct.read_csv_chunks(big, chunk_rows=2048):
        g.insert_left(chunk)        # each chunk mesh-shuffles on arrival
        nchunks += 1
    for chunk in ct.read_csv_chunks(paths[0], chunk_rows=2048):
        g.insert_right(chunk)
    res = g.result()
    print(f"out-of-core join: {nchunks} streamed chunks ->",
          len(dist_to_pandas(env, res)), "rows")

# --- approximate quantile without gathering the column ---------------
dt = scatter_table(env, ct.Table.from_pandas(left))
med = float(dist_aggregate(env, dt, "a", "median", exact=False))
print(f"sketch median: {med:.4f} (pandas {left['a'].median():.4f})")
