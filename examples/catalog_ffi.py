"""String-id catalog + native C-ABI registry (parity: the table_api.cpp
registry the reference's Java binding drives over JNI —
Table.java:289-307)."""

import _mesh

_mesh.setup()

import cylon_tpu as ct
from cylon_tpu import catalog, native

catalog.put_table("orders", ct.Table.from_pydict(
    {"id": [1, 2, 3], "item": ["ax", "bolt", "ax"]}))
catalog.put_table("prices", ct.Table.from_pydict(
    {"item": ["ax", "bolt"], "price": [9.5, 1.25]}))

# id-keyed op mirror (JoinTables(ctx, "left", "right", ...) analog)
catalog.join_tables("orders", "prices", "priced", on="item")
print(catalog.table_to_pydict("priced"))

if native.available():
    # publish through the C ABI — any FFI host (JNI, cffi, ...) can now
    # read `orders` via the cylon_catalog_* symbols
    catalog.to_native("orders")
    print("native registry ids:", native.catalog_ids())
    print("round-trip:", native.catalog_get("orders").to_pydict())
else:
    print("native runtime unavailable:", native.build_error())
