"""Local relational surface (parity: python/examples/dataframe/{merge,
join,groupby,sort,drop_duplicates,concat}.py)."""

import _mesh

_mesh.setup()

import numpy as np
import cylon_tpu as ct

rng = np.random.default_rng(0)
df = ct.DataFrame({"k": rng.integers(0, 5, 20),
                   "v": rng.normal(size=20).round(2)})
other = ct.DataFrame({"k": [1, 2, 3], "w": [10., 20., 30.]})

print("--- merge (inner) ---")
print(df.merge(other, on="k").head(5).to_pandas())

print("--- groupby agg ---")
print(df.groupby("k").agg({"v": ["sum", "mean", "count"]}).to_pandas())

print("--- sort / dedup / concat ---")
print(df.sort_values("v").head(3).to_pandas())
print(df.drop_duplicates(subset=["k"]).to_pandas())
print(ct.concat([df.head(2), df.head(2)]).to_pandas())

print("--- elementwise + reductions ---")
print((df["v"] * 2 + 1).head(3).to_pandas())
print("sum:", df.sum(), " median:", df.median())
