/* Pure-C consumer of the cylon_tpu native runtime ABI.
 *
 * The proof that the catalog/FFI surface works from a foreign (non-
 * Python) runtime: put two tables, run the native hash join, read the
 * result back — the same round trip the reference's Java binding does
 * over its JNI bridge (java/.../Table.java:43,289-307 ->
 * java/src/main/native/src/Table.cpp -> table_api JoinTables).
 *
 * Build (see tests/test_native.py, which compiles and runs this):
 *   gcc -O2 catalog_client.c -o catalog_client \
 *       -L$LIBDIR -lcylon_host -Wl,-rpath,$LIBDIR
 */
#include <stdint.h>
#include <stdio.h>
#include <string.h>

#include "../../cylon_tpu/native/cylon_host.h"

static int fail(const char *what, long long detail) {
  fprintf(stderr, "FAIL %s (%lld)\n", what, detail);
  return 1;
}

int main(void) {
  /* orders(k int64, amount f64) — one null amount via validity */
  int64_t ok[] = {1, 2, 2, 3, 5};
  double amount[] = {10.0, 20.0, 21.0, 30.0, 50.0};
  uint8_t amount_valid[] = {1, 1, 1, 1, 0};
  const char *onames[] = {"k", "amount"};
  int32_t odt[] = {0, 1};
  const void *obufs[] = {ok, amount};
  int64_t olens[] = {sizeof ok, sizeof amount};
  const uint8_t *ovalid[] = {NULL, amount_valid};
  if (cylon_catalog_put("orders", 2, onames, odt, 5, obufs, olens, ovalid))
    return fail("put orders", 0);

  /* customers(k int64, name dict-codes int32) */
  int64_t ck[] = {2, 3, 4};
  int32_t name_code[] = {7, 8, 9};
  const char *cnames[] = {"k", "name"};
  int32_t cdt[] = {0, 2};
  const void *cbufs[] = {ck, name_code};
  int64_t clens[] = {sizeof ck, sizeof name_code};
  if (cylon_catalog_put("customers", 2, cnames, cdt, 3, cbufs, clens, NULL))
    return fail("put customers", 0);

  int32_t lkey = 0, rkey = 0;
  int32_t rc = cylon_catalog_join("orders", "customers", "joined", 1,
                                  &lkey, &rkey, /*inner=*/0);
  if (rc) return fail("join rc", rc);

  long long n = (long long)cylon_catalog_rows("joined");
  if (n != 3) return fail("row count", n);
  if (cylon_catalog_ncols("joined") != 3) return fail("col count", 0);

  /* probe is left-driven, so row order is deterministic:
   * (k=2,20.0,code 7), (k=2,21.0,code 7), (k=3,30.0,code 8) */
  int64_t kout[3];
  double aout[3];
  int32_t nout[3];
  if (cylon_catalog_col_read("joined", 0, kout, sizeof kout, NULL) < 0)
    return fail("read k", 0);
  if (cylon_catalog_col_read("joined", 1, aout, sizeof aout, NULL) < 0)
    return fail("read amount", 0);
  if (cylon_catalog_col_read("joined", 2, nout, sizeof nout, NULL) < 0)
    return fail("read name", 0);
  int64_t kexp[] = {2, 2, 3};
  double aexp[] = {20.0, 21.0, 30.0};
  int32_t nexp[] = {7, 7, 8};
  for (int i = 0; i < 3; ++i) {
    if (kout[i] != kexp[i]) return fail("k value", i);
    if (aout[i] != aexp[i]) return fail("amount value", i);
    if (nout[i] != nexp[i]) return fail("name code", i);
  }

  /* left join keeps the null-amount row and the unmatched k=1 */
  if (cylon_catalog_join("orders", "customers", "joined_l", 1, &lkey,
                         &rkey, /*left=*/1))
    return fail("left join", 0);
  if (cylon_catalog_rows("joined_l") != 5) return fail("left rows", 0);

  cylon_catalog_clear();
  if (cylon_catalog_size() != 0) return fail("clear", 0);
  printf("NATIVE-FFI-OK rows=%lld\n", n);
  return 0;
}
