"""TPC-H: generate tables (dbgen-style) and run Q3/Q5 with a pandas
cross-check (parity: the reference's TPC-H-flavoured join benchmarks)."""

import _mesh

_mesh.setup()

import time

from cylon_tpu.tpch import dbgen, queries

t0 = time.perf_counter()
data = dbgen.generate(sf=0.01, seed=0)
print(f"dbgen sf=0.01: {time.perf_counter() - t0:.2f}s "
      f"({data['lineitem']['l_orderkey'].shape[0]} lineitems)")

for name, q in (("Q3", queries.q3), ("Q5", queries.q5)):
    t0 = time.perf_counter()
    res = q(data).to_pandas()
    print(f"{name}: {len(res)} rows in {time.perf_counter() - t0:.2f}s")
    print(res.head(3))
