"""TPC-H: generate tables (dbgen-style) and run the full 22-query
suite (parity+: the reference only ships synthetic join benchmarks)."""

import _mesh

_mesh.setup()

import time

from cylon_tpu.tpch import dbgen, queries

t0 = time.perf_counter()
data = dbgen.generate(sf=0.01, seed=0)
print(f"dbgen sf=0.01: {time.perf_counter() - t0:.2f}s "
      f"({data['lineitem']['l_orderkey'].shape[0]} lineitems)")

frame_qs = [(f"Q{i}", getattr(queries, f"q{i}"))
            for i in (1, 2, 3, 4, 5, 7, 8, 9, 10, 11, 12, 13, 15, 16,
                      18, 20, 21, 22)]
for name, q in frame_qs:
    t0 = time.perf_counter()
    res = q(data).to_pandas()
    print(f"{name}: {len(res)} rows in {time.perf_counter() - t0:.2f}s")
for name, q in [("Q6", queries.q6), ("Q14", queries.q14),
                ("Q17", queries.q17), ("Q19", queries.q19)]:
    t0 = time.perf_counter()
    val = float(q(data))
    print(f"{name}: scalar {val:.2f} in {time.perf_counter() - t0:.2f}s")
