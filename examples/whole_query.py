"""Whole-query compilation: a multi-operator query as ONE XLA program.

The compiled reimagining of the reference's streaming op-graph
(`cpp/src/cylon/ops/dis_join_op.cpp`): instead of hand-scheduled
operator threads, the whole filter -> join -> groupby -> sort pipeline
traces into a single executable (one dispatch + one result fetch), and
capacity bounds regrow automatically if a join blows past its default
budget (`cylon_tpu.plan`).
"""

import _mesh

_mesh.setup()

import time

import numpy as np

import cylon_tpu as ct
from cylon_tpu.ops.groupby import groupby_aggregate
from cylon_tpu.ops.join import join
from cylon_tpu.ops.selection import filter_table, sort_table
from cylon_tpu.plan import compile_query


@compile_query
def revenue_by_key(orders: ct.Table, items: ct.Table, cutoff=None):
    recent = filter_table(orders, orders.column("day").data >= cutoff)
    j = join(recent, items, on="k", how="inner")
    g = groupby_aggregate(j, ["k"], [("amount", "sum", "revenue")])
    return sort_table(g, ["revenue"], ascending=False)


rng = np.random.default_rng(0)
n = 50_000
orders = ct.Table.from_pydict({
    "k": rng.integers(0, 500, n).astype(np.int64),
    "day": rng.integers(0, 365, n).astype(np.int64),
    "amount": rng.uniform(1.0, 100.0, n),
})
items = ct.Table.from_pydict({
    "k": np.arange(500, dtype=np.int64),
    "label": rng.integers(0, 9, 500).astype(np.int64),
})

t0 = time.time()
out = revenue_by_key(orders, items, cutoff=180)
print(f"first call (trace + compile + regrow probe): "
      f"{time.time() - t0:.2f}s")
t0 = time.time()
out = revenue_by_key(orders, items, cutoff=180)
top = out.to_pandas().head(5)
print(f"steady-state (one dispatch + one fetch): {time.time() - t0:.3f}s")
print(top)

# the same mechanism powers the TPC-H suite: tpch.compiled("q3")(data)
from cylon_tpu import tpch

data = tpch.generate(0.005, seed=0)
q3 = tpch.compiled("q3")
print("\nTPC-H q3 (whole-query compiled):")
print(q3(data).to_pandas().head(3))
