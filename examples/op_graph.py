"""Streaming op-graph execution (parity: cpp/src/examples/ops/ and the
DisJoinOP chain of ops/dis_join_op.cpp:21-72): chunks stream through
partition -> join with a pluggable scheduler."""

import _mesh

_mesh.setup()

import numpy as np
import cylon_tpu as ct
from cylon_tpu.ops_graph import DisJoinOp, RoundRobinExecution, chunk_stream

rng = np.random.default_rng(2)
n = 4000
left = ct.Table.from_pydict({"k": rng.integers(0, 100, n).astype(np.int64),
                             "a": rng.normal(size=n)})
right = ct.Table.from_pydict({"k": rng.integers(0, 100, n).astype(np.int64),
                              "b": rng.normal(size=n)})

op = DisJoinOp("k", n_partitions=4, out_capacity=16 * n)
for chunk in chunk_stream(left, 512):
    op.insert_left(chunk)
for chunk in chunk_stream(right, 512):
    op.insert_right(chunk)
op.finish()
result = op.result(RoundRobinExecution())
print("streamed join rows:", result.num_rows)
print(result.to_pandas().head())

# --- distributed mode: the same graph over the device mesh -----------
# every chunk all-to-alls over the mesh as it arrives (ShuffleOp); the
# finalize join is shard-local on the co-located accumulation — the
# reference's incremental exchange with its comm/compute overlap
env = ct.CylonEnv(ct.TPUConfig())
dop = DisJoinOp("k", env=env, how="inner")
for chunk in chunk_stream(left, 512):
    dop.insert_left(chunk)
for chunk in chunk_stream(right, 512):
    dop.insert_right(chunk)
dist_result = dop.result()
from cylon_tpu.parallel import dist_num_rows

print("streamed join over the mesh:", dist_num_rows(dist_result), "rows",
      "on", env)
