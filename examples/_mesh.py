"""Shared example bootstrap: a virtual 8-device CPU mesh by default (the
reference's `mpirun --oversubscribe` analog). Set CYLON_EXAMPLES_TPU=1
to run on real chips instead — kept opt-in because probing for TPUs
initialises (and exclusively leases) the backend."""

import os
import sys

# runnable from a source checkout without installing
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def setup():
    import jax

    if os.environ.get("CYLON_EXAMPLES_TPU"):
        return jax
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    jax.config.update("jax_platforms", "cpu")
    return jax
