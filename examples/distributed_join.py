"""Distributed merge + groupby + sort over a device mesh (parity:
python/examples/dataframe/join.py + cpp join_example.cpp, run under
mpirun there — here one process, SPMD over the mesh)."""

import _mesh

_mesh.setup()

import numpy as np
import cylon_tpu as ct
from cylon_tpu.utils import tracing

env = ct.CylonEnv(ct.TPUConfig())
print(env)

rng = np.random.default_rng(1)
n = 10_000
left = ct.DataFrame({"k": rng.integers(0, 500, n), "a": rng.normal(size=n)})
right = ct.DataFrame({"k": rng.integers(0, 500, n), "b": rng.normal(size=n)})

joined = left.merge(right, on="k", env=env, out_capacity=64 * n)
gb = joined.groupby("k", env=env).agg({"a": "sum", "b": "mean"})
top = gb.sort_values("a_sum", ascending=False, env=env).head(5)
print(top.to_pandas())

print("--- op spans ---")
print(tracing.report())
