package org.cylondata.cylon;

/**
 * Execution context for the Java binding (parity:
 * {@code java/src/main/java/org/cylondata/cylon/CylonContext.java} of
 * the reference — init/barrier/finalize over the native layer).
 *
 * <p>The native layer here is the host runtime's C ABI
 * ({@code cylon_tpu/native/cylon_host.h}): a string-id table catalog
 * plus host kernels, the same surface the reference's JNI bridge drives
 * through {@code table_api} ({@code Table.java:289-307}). Device
 * (TPU/mesh) execution stays on the Python/JAX side; the Java binding
 * is a host-runtime consumer exactly like the reference's (whose JNI
 * also never touches MPI directly — ranks come from the context).</p>
 */
public final class CylonContext {

  private static boolean loaded = false;
  private boolean finalized = false;

  private CylonContext() {
  }

  /**
   * Initialise the context, loading the JNI bridge
   * ({@code libcylon_jni.so}). Library search order: the
   * {@code CYLON_JNI_LIB} environment variable (full path), then
   * {@code java.library.path}.
   */
  public static synchronized CylonContext init() {
    if (!loaded) {
      String explicit = System.getenv("CYLON_JNI_LIB");
      if (explicit != null && !explicit.isEmpty()) {
        System.load(explicit);
      } else {
        System.loadLibrary("cylon_jni");
      }
      loaded = true;
    }
    return new CylonContext();
  }

  /** Single-process host context: rank 0 of world 1 (parity:
   *  {@code getRank}/{@code getWorldSize}). */
  public int getRank() {
    return 0;
  }

  public int getWorldSize() {
    return 1;
  }

  /** No-op on the single-process host context (parity: Barrier). */
  public void barrier() {
  }

  /** Parity: {@code CylonContext.finalizeCtx}. */
  public void finalizeCtx() {
    this.finalized = true;
  }

  public boolean isFinalized() {
    return this.finalized;
  }
}
