package org.cylondata.cylon;

/**
 * One row's typed host view, handed to {@link
 * org.cylondata.cylon.ops.Selector#select}.
 *
 * <p>Parity: the reference's {@code Row} (java/.../Row.java — typed
 * getters over a native row handle). Here the row is a view over
 * columns the binding already fetched from the catalog (one bulk read
 * per column for the whole {@code select}, not one JNI call per cell —
 * the catalog ABI is column-oriented, so per-cell native getters would
 * be quadratic traffic).
 */
public final class Row {

  private final String[] names;
  private final Object[] columns;  // long[] | double[] | String[] per col
  private int index;

  Row(String[] names, Object[] columns) {
    this.names = names;
    this.columns = columns;
  }

  void seek(int i) {
    this.index = i;
  }

  public int getColumnCount() {
    return names.length;
  }

  public String getColumnName(int col) {
    return names[col];
  }

  /** Throws {@code NullPointerException} on a null cell (use
   *  {@link #get} / {@link #isNull} for nullable columns). */
  public long getInt64(int col) {
    Object a = columns[col];
    return a instanceof long[] ? ((long[]) a)[index]
        : ((Long[]) a)[index];
  }

  public double getFloat64(int col) {
    Object a = columns[col];
    return a instanceof double[] ? ((double[]) a)[index]
        : ((Double[]) a)[index];
  }

  public String getString(int col) {
    return ((String[]) columns[col])[index];
  }

  public boolean isNull(int col) {
    return get(col) == null;
  }

  /** Boxed cell value: {@code Long}, {@code Double} or {@code String}
   *  ({@code null} for a null cell). */
  public Object get(int col) {
    Object a = columns[col];
    if (a instanceof long[]) {
      return ((long[]) a)[index];
    }
    if (a instanceof double[]) {
      return ((double[]) a)[index];
    }
    return ((Object[]) a)[index];
  }
}
