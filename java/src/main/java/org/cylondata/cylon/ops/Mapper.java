package org.cylondata.cylon.ops;

/**
 * Elementwise cell transform for
 * {@link org.cylondata.cylon.Table#mapColumn}.
 *
 * <p>Parity contract: the reference's {@code ops.Mapper} interface —
 * name and shape are the compatibility surface.
 */
public interface Mapper<I, O> {
  O map(I cellValue);
}
