package org.cylondata.cylon.ops;

import org.cylondata.cylon.Row;

/**
 * Whole-row predicate for {@link org.cylondata.cylon.Table#select}.
 *
 * <p>Parity contract: the reference's {@code ops.Selector} interface —
 * name and shape are the compatibility surface.
 */
public interface Selector {
  boolean select(Row row);
}
