package org.cylondata.cylon.ops;

/**
 * Single-column row predicate for {@link org.cylondata.cylon.Table#filter}.
 *
 * <p>Parity contract: the reference's {@code ops.Filter} interface
 * (java/src/main/java/org/cylondata/cylon/ops/Filter.java) — the
 * method name and shape ARE the compatibility surface, so user lambdas
 * written against the reference compile unchanged.
 */
public interface Filter<I> {
  boolean filter(I value);
}
