package org.cylondata.cylon.examples;

import org.cylondata.cylon.CylonContext;
import org.cylondata.cylon.Table;

/**
 * The reference's canonical Java flow (its {@code examples/} join
 * demos): build two tables, native hash join, read the result back.
 * Exits 0 and prints {@code JAVA-OK <rows>} on success — the
 * assertion the CI test checks.
 */
public final class JoinExample {

  private JoinExample() {
  }

  public static void main(String[] args) {
    CylonContext ctx = CylonContext.init();

    Table orders = Table.fromColumns(ctx,
        new String[] {"k", "amount"},
        new Object[] {new long[] {1, 2, 2, 3, 5},
                      new double[] {10.0, 20.0, 21.0, 30.0, 50.0}});
    Table customers = Table.fromColumns(ctx,
        new String[] {"k", "score"},
        new Object[] {new long[] {2, 3, 4},
                      new double[] {0.5, 0.25, 0.125}});

    Table joined = orders.join(customers, 0, 0, Table.JoinType.INNER);
    int rows = joined.getRowCount();
    int cols = joined.getColumnCount();
    // probe is left-driven: (2,20,.5), (2,21,.5), (3,30,.25)
    long[] k = joined.readLongColumn(0);
    double[] amount = joined.readDoubleColumn(1);
    double[] score = joined.readDoubleColumn(2);
    boolean ok = rows == 3 && cols == 3
        && k[0] == 2 && k[1] == 2 && k[2] == 3
        && amount[0] == 20.0 && amount[1] == 21.0 && amount[2] == 30.0
        && score[0] == 0.5 && score[1] == 0.5 && score[2] == 0.25;

    // ops.* interfaces (parity: the reference's Filter/Selector/Mapper)
    Table big = joined.filter(1, (Double v) -> v > 20.0);
    ok = ok && big.getRowCount() == 2;
    Table key2 = joined.select(row -> row.getInt64(0) == 2);
    ok = ok && key2.getRowCount() == 2;
    ok = ok && joined.<Double, Double>mapColumn(1, v -> v * 2.0)
        .get(0) == 40.0;

    // String[] columns dictionary-encode through the catalog's
    // sidecar convention (shared with the Python binding)
    Table named = Table.fromColumns(ctx,
        new String[] {"name", "x"},
        new Object[] {new String[] {"carol", "alice", "bob"},
                      new long[] {1, 2, 3}});
    String[] back = named.readStringColumn(0);
    ok = ok && back[0].equals("carol") && back[1].equals("alice")
        && back[2].equals("bob");
    Table alice = named.filter(0, (String s) -> s.startsWith("a"));
    ok = ok && alice.getRowCount() == 1
        && alice.readStringColumn(0)[0].equals("alice");

    joined.print(10);
    orders.clear();
    customers.clear();
    joined.clear();
    big.clear();
    key2.clear();
    named.clear();
    alice.clear();
    ctx.finalizeCtx();

    if (!ok) {
      System.err.println("JAVA-FAIL");
      System.exit(1);
    }
    System.out.println("JAVA-OK " + rows);
  }
}
