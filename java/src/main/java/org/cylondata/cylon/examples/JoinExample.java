package org.cylondata.cylon.examples;

import org.cylondata.cylon.CylonContext;
import org.cylondata.cylon.Table;

/**
 * The reference's canonical Java flow (its {@code examples/} join
 * demos): build two tables, native hash join, read the result back.
 * Exits 0 and prints {@code JAVA-OK <rows>} on success — the
 * assertion the CI test checks.
 */
public final class JoinExample {

  private JoinExample() {
  }

  public static void main(String[] args) {
    CylonContext ctx = CylonContext.init();

    Table orders = Table.fromColumns(ctx,
        new String[] {"k", "amount"},
        new Object[] {new long[] {1, 2, 2, 3, 5},
                      new double[] {10.0, 20.0, 21.0, 30.0, 50.0}});
    Table customers = Table.fromColumns(ctx,
        new String[] {"k", "score"},
        new Object[] {new long[] {2, 3, 4},
                      new double[] {0.5, 0.25, 0.125}});

    Table joined = orders.join(customers, 0, 0, Table.JoinType.INNER);
    int rows = joined.getRowCount();
    int cols = joined.getColumnCount();
    // probe is left-driven: (2,20,.5), (2,21,.5), (3,30,.25)
    long[] k = joined.readLongColumn(0);
    double[] amount = joined.readDoubleColumn(1);
    double[] score = joined.readDoubleColumn(2);
    boolean ok = rows == 3 && cols == 3
        && k[0] == 2 && k[1] == 2 && k[2] == 3
        && amount[0] == 20.0 && amount[1] == 21.0 && amount[2] == 30.0
        && score[0] == 0.5 && score[1] == 0.5 && score[2] == 0.25;

    joined.print(10);
    orders.clear();
    customers.clear();
    joined.clear();
    ctx.finalizeCtx();

    if (!ok) {
      System.err.println("JAVA-FAIL");
      System.exit(1);
    }
    System.out.println("JAVA-OK " + rows);
  }
}
