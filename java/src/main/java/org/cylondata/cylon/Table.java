package org.cylondata.cylon;

import java.util.UUID;

/**
 * Java consumer of the native table catalog (parity: the reference's
 * {@code org.cylondata.cylon.Table}, {@code Table.java:43} — an
 * id-keyed mediator whose data lives entirely in the native layer, with
 * transformations dispatched through native methods,
 * {@code Table.java:289-307}).
 *
 * <p>Tables are immutable; every transformation creates a new catalog
 * entry under a fresh UUID, exactly like the reference's
 * {@code nativeJoin(..., destination)} convention.</p>
 *
 * <p>Column dtypes mirror the catalog ABI ({@code cylon_host.h}):
 * {@code 0} = int64, {@code 1} = float64, {@code 2} = int32 dictionary
 * codes.</p>
 */
public final class Table {

  public static final int DTYPE_INT64 = 0;
  public static final int DTYPE_FLOAT64 = 1;
  public static final int DTYPE_STRING_CODES = 2;

  /** Join types, numbering shared with {@code cylon_catalog_join}. */
  public enum JoinType {
    INNER, LEFT, RIGHT, FULL_OUTER
  }

  private final String id;
  private final CylonContext ctx;

  private Table(String id, CylonContext ctx) {
    this.id = id;
    this.ctx = ctx;
  }

  public String getId() {
    return id;
  }

  // ----------------- methods to generate a table -----------------

  /**
   * Load a CSV file through the native chunk-parallel reader and
   * register it in the catalog (parity: {@code Table.fromCSV} →
   * {@code nativeLoadCSV}, {@code Table.java:81-85,309}).
   */
  public static Table fromCSV(CylonContext ctx, String path) {
    String uuid = UUID.randomUUID().toString();
    nativeLoadCSV(path, uuid);
    return new Table(uuid, ctx);
  }

  /** Register int64/float64 columns directly (column i is
   *  {@code long[]} or {@code double[]}). */
  public static Table fromColumns(CylonContext ctx, String[] names,
                                  Object[] columns) {
    String uuid = UUID.randomUUID().toString();
    nativePutColumns(uuid, names, columns);
    return new Table(uuid, ctx);
  }

  // ----------------- table properties -----------------

  /**
   * Parity: {@code getColumnCount} → {@code nativeColumnCount}. String
   * columns carry their dictionaries in trailing sidecar entries
   * (names containing {@code \u0001}); those are implementation
   * columns, excluded here — user columns are always the leading
   * indices.
   */
  public int getColumnCount() {
    int nc = nativeColumnCount(id);
    int real = 0;
    for (int i = 0; i < nc; i++) {
      if (nativeColumnName(id, i).indexOf('\u0001') < 0) {
        real++;
      }
    }
    return real;
  }

  /** Parity: {@code getRowCount} → {@code nativeRowCount}. Throws when
   *  the (int64) native count exceeds {@code Integer.MAX_VALUE};
   *  {@link #getRowCountLong()} has no such limit. */
  public int getRowCount() {
    long n = nativeRowCount(id);
    if (n > Integer.MAX_VALUE) {
      throw new ArithmeticException("row count " + n + " exceeds int");
    }
    return (int) n;
  }

  public long getRowCountLong() {
    return nativeRowCount(id);
  }

  public String getColumnName(int col) {
    return nativeColumnName(id, col);
  }

  /** One of the {@code DTYPE_*} constants. */
  public int getColumnType(int col) {
    return nativeColumnType(id, col);
  }

  // ----------------- data access -----------------

  public long[] readLongColumn(int col) {
    return nativeReadI64(id, col);
  }

  public double[] readDoubleColumn(int col) {
    return nativeReadF64(id, col);
  }

  /** int32 dictionary codes of a string column. */
  public int[] readCodesColumn(int col) {
    return nativeReadCodes(id, col);
  }

  /** The dictionary values of a string column (null when the column
   *  carries no dictionary sidecars). */
  public String[] readDictValues(int col) {
    return nativeReadDictValues(id, col);
  }

  /** Decoded string column: codes mapped through the dictionary
   *  (null entries for invalid rows/codes). */
  public String[] readStringColumn(int col) {
    int[] codes = readCodesColumn(col);
    String[] dict = readDictValues(col);
    byte[] valid = readValidity(col);
    String[] out = new String[codes.length];
    for (int i = 0; i < codes.length; i++) {
      boolean ok = valid == null || valid[i] != 0;
      out[i] = (ok && dict != null && codes[i] >= 0
                && codes[i] < dict.length) ? dict[codes[i]] : null;
    }
    return out;
  }

  /** Validity flags (1 = present), or null when the column has no
   *  nulls. */
  public byte[] readValidity(int col) {
    return nativeReadValidity(id, col);
  }

  // ----------------- transformations -----------------

  /**
   * Native hash join on one key column per side (parity:
   * {@code Table.join} → {@code nativeJoin},
   * {@code Table.java:132-160,289}; algorithm fixed to hash — the
   * build/probe of {@code join/hash_join.cpp:22-31} reimplemented in
   * the host runtime).
   */
  public Table join(Table right, int leftCol, int rightCol,
                    JoinType joinType) {
    String uuid = UUID.randomUUID().toString();
    int rc = nativeJoin(this.id, right.id, uuid, leftCol, rightCol,
                        joinType.ordinal());
    if (rc != 0) {
      throw new RuntimeException("native join failed rc=" + rc);
    }
    return new Table(uuid, ctx);
  }

  /** Remove this table from the catalog (parity: {@code clear}). */
  public void clear() {
    nativeClear(id);
  }

  /** Host-side print of up to {@code maxRows} rows (parity:
   *  {@code Table.print}). */
  public void print(int maxRows) {
    int nc = getColumnCount();
    int nr = Math.min(getRowCount(), maxRows);
    StringBuilder sb = new StringBuilder();
    for (int c = 0; c < nc; c++) {
      sb.append(getColumnName(c)).append(c + 1 < nc ? "," : "\n");
    }
    Object[] cols = new Object[nc];
    for (int c = 0; c < nc; c++) {
      int t = getColumnType(c);
      cols[c] = t == DTYPE_FLOAT64 ? (Object) readDoubleColumn(c)
          : t == DTYPE_STRING_CODES ? (Object) readCodesColumn(c)
          : (Object) readLongColumn(c);
    }
    for (int r = 0; r < nr; r++) {
      for (int c = 0; c < nc; c++) {
        Object a = cols[c];
        if (a instanceof double[]) {
          sb.append(((double[]) a)[r]);
        } else if (a instanceof int[]) {
          sb.append(((int[]) a)[r]);
        } else {
          sb.append(((long[]) a)[r]);
        }
        sb.append(c + 1 < nc ? "," : "\n");
      }
    }
    System.out.print(sb);
  }

  // ----------------- native methods (cylon_jni.c) -----------------

  private static native void nativeLoadCSV(String path, String id);

  private static native void nativePutColumns(String id, String[] names,
                                              Object[] columns);

  private static native int nativeColumnCount(String id);

  private static native long nativeRowCount(String id);

  private static native String nativeColumnName(String id, int col);

  private static native int nativeColumnType(String id, int col);

  private static native long[] nativeReadI64(String id, int col);

  private static native double[] nativeReadF64(String id, int col);

  private static native int[] nativeReadCodes(String id, int col);

  private static native byte[] nativeReadValidity(String id, int col);

  private static native String[] nativeReadDictValues(String id, int col);

  private static native int nativeJoin(String left, String right,
                                       String dest, int leftCol,
                                       int rightCol, int joinType);

  private static native void nativeClear(String id);
}
