package org.cylondata.cylon;

import java.util.ArrayList;
import java.util.List;
import java.util.UUID;

import org.cylondata.cylon.ops.Filter;
import org.cylondata.cylon.ops.Mapper;
import org.cylondata.cylon.ops.Selector;

/**
 * Java consumer of the native table catalog (parity: the reference's
 * {@code org.cylondata.cylon.Table}, {@code Table.java:43} — an
 * id-keyed mediator whose data lives entirely in the native layer, with
 * transformations dispatched through native methods,
 * {@code Table.java:289-307}).
 *
 * <p>Tables are immutable; every transformation creates a new catalog
 * entry under a fresh UUID, exactly like the reference's
 * {@code nativeJoin(..., destination)} convention.</p>
 *
 * <p>Column dtypes mirror the catalog ABI ({@code cylon_host.h}):
 * {@code 0} = int64, {@code 1} = float64, {@code 2} = int32 dictionary
 * codes.</p>
 */
public final class Table {

  public static final int DTYPE_INT64 = 0;
  public static final int DTYPE_FLOAT64 = 1;
  public static final int DTYPE_STRING_CODES = 2;

  /** Join types, numbering shared with {@code cylon_catalog_join}. */
  public enum JoinType {
    INNER, LEFT, RIGHT, FULL_OUTER
  }

  private final String id;
  private final CylonContext ctx;

  private Table(String id, CylonContext ctx) {
    this.id = id;
    this.ctx = ctx;
  }

  public String getId() {
    return id;
  }

  // ----------------- methods to generate a table -----------------

  /**
   * Load a CSV file through the native chunk-parallel reader and
   * register it in the catalog (parity: {@code Table.fromCSV} →
   * {@code nativeLoadCSV}, {@code Table.java:81-85,309}).
   */
  public static Table fromCSV(CylonContext ctx, String path) {
    String uuid = UUID.randomUUID().toString();
    nativeLoadCSV(path, uuid);
    return new Table(uuid, ctx);
  }

  /** Register columns directly (column i is {@code long[]},
   *  {@code double[]} or {@code String[]}). String columns are
   *  dictionary-encoded in the JNI layer (sorted-unique values, int32
   *  codes, null elements -> validity) and carry their dictionaries as
   *  the catalog's sidecar convention — the same wire format the
   *  Python binding writes, so joins across the two bindings compare
   *  string VALUES. */
  public static Table fromColumns(CylonContext ctx, String[] names,
                                  Object[] columns) {
    String uuid = UUID.randomUUID().toString();
    nativePutColumns(uuid, names, columns);
    return new Table(uuid, ctx);
  }

  // ----------------- table properties -----------------

  /**
   * Parity: {@code getColumnCount} → {@code nativeColumnCount}. String
   * columns carry their dictionaries in trailing sidecar entries
   * (names containing {@code \u0001}); those are implementation
   * columns, excluded here — user columns are always the leading
   * indices.
   */
  public int getColumnCount() {
    int nc = nativeColumnCount(id);
    int real = 0;
    for (int i = 0; i < nc; i++) {
      if (nativeColumnName(id, i).indexOf('\u0001') < 0) {
        real++;
      }
    }
    return real;
  }

  /** Parity: {@code getRowCount} → {@code nativeRowCount}. Throws when
   *  the (int64) native count exceeds {@code Integer.MAX_VALUE};
   *  {@link #getRowCountLong()} has no such limit. */
  public int getRowCount() {
    long n = nativeRowCount(id);
    if (n > Integer.MAX_VALUE) {
      throw new ArithmeticException("row count " + n + " exceeds int");
    }
    return (int) n;
  }

  public long getRowCountLong() {
    return nativeRowCount(id);
  }

  public String getColumnName(int col) {
    return nativeColumnName(id, col);
  }

  /** One of the {@code DTYPE_*} constants. */
  public int getColumnType(int col) {
    return nativeColumnType(id, col);
  }

  // ----------------- data access -----------------

  public long[] readLongColumn(int col) {
    return nativeReadI64(id, col);
  }

  public double[] readDoubleColumn(int col) {
    return nativeReadF64(id, col);
  }

  /** int32 dictionary codes of a string column. */
  public int[] readCodesColumn(int col) {
    return nativeReadCodes(id, col);
  }

  /** The dictionary values of a string column (null when the column
   *  carries no dictionary sidecars). */
  public String[] readDictValues(int col) {
    return nativeReadDictValues(id, col);
  }

  /** Decoded string column: codes mapped through the dictionary
   *  (null entries for invalid rows/codes). */
  public String[] readStringColumn(int col) {
    int[] codes = readCodesColumn(col);
    String[] dict = readDictValues(col);
    byte[] valid = readValidity(col);
    String[] out = new String[codes.length];
    for (int i = 0; i < codes.length; i++) {
      boolean ok = valid == null || valid[i] != 0;
      out[i] = (ok && dict != null && codes[i] >= 0
                && codes[i] < dict.length) ? dict[codes[i]] : null;
    }
    return out;
  }

  /** Validity flags (1 = present), or null when the column has no
   *  nulls. */
  public byte[] readValidity(int col) {
    return nativeReadValidity(id, col);
  }

  // ----------------- transformations -----------------

  /**
   * Native hash join on one key column per side (parity:
   * {@code Table.join} → {@code nativeJoin},
   * {@code Table.java:132-160,289}; algorithm fixed to hash — the
   * build/probe of {@code join/hash_join.cpp:22-31} reimplemented in
   * the host runtime).
   */
  public Table join(Table right, int leftCol, int rightCol,
                    JoinType joinType) {
    String uuid = UUID.randomUUID().toString();
    int rc = nativeJoin(this.id, right.id, uuid, leftCol, rightCol,
                        joinType.ordinal());
    if (rc != 0) {
      throw new RuntimeException("native join failed rc=" + rc);
    }
    return new Table(uuid, ctx);
  }

  // ----------------- relational ops over ops.* interfaces -----------------

  /** One user column as a host array — ONE bulk catalog read (the ABI
   *  is column-oriented; per-cell native getters would be quadratic
   *  JNI traffic). Nullable numeric columns come back BOXED
   *  ({@code Long[]}/{@code Double[]}, null elements for null cells)
   *  so ops never see a null cell's garbage payload; all-valid
   *  columns keep the primitive fast path. */
  private Object materializeColumn(int c) {
    int t = getColumnType(c);
    if (t == DTYPE_STRING_CODES) {
      return readStringColumn(c);  // null cells -> null elements
    }
    byte[] valid = readValidity(c);
    if (t == DTYPE_FLOAT64) {
      double[] raw = readDoubleColumn(c);
      if (valid == null) {
        return raw;
      }
      Double[] boxed = new Double[raw.length];
      for (int i = 0; i < raw.length; i++) {
        boxed[i] = valid[i] != 0 ? (Double) raw[i] : null;
      }
      return boxed;
    }
    long[] raw = readLongColumn(c);
    if (valid == null) {
      return raw;
    }
    Long[] boxed = new Long[raw.length];
    for (int i = 0; i < raw.length; i++) {
      boxed[i] = valid[i] != 0 ? (Long) raw[i] : null;
    }
    return boxed;
  }

  private Object[] materializeColumns(int nc) {
    Object[] cols = new Object[nc];
    for (int c = 0; c < nc; c++) {
      cols[c] = materializeColumn(c);
    }
    return cols;
  }

  private static Object cell(Object col, int r) {
    if (col instanceof long[]) {
      return ((long[]) col)[r];
    }
    if (col instanceof double[]) {
      return ((double[]) col)[r];
    }
    return ((Object[]) col)[r];  // Long[] / Double[] / String[]
  }

  private Table rebuild(Object[] cols, boolean[] keep, int kept) {
    int nc = cols.length;
    String[] names = new String[nc];
    Object[] out = new Object[nc];
    int nr = getRowCount();
    for (int c = 0; c < nc; c++) {
      names[c] = getColumnName(c);
      Object a = cols[c];
      if (a instanceof long[]) {
        long[] src = (long[]) a;
        long[] dst = new long[kept];
        for (int r = 0, w = 0; r < nr; r++) {
          if (keep[r]) dst[w++] = src[r];
        }
        out[c] = dst;
      } else if (a instanceof double[]) {
        double[] src = (double[]) a;
        double[] dst = new double[kept];
        for (int r = 0, w = 0; r < nr; r++) {
          if (keep[r]) dst[w++] = src[r];
        }
        out[c] = dst;
      } else if (a instanceof String[]) {
        String[] src = (String[]) a;
        String[] dst = new String[kept];
        for (int r = 0, w = 0; r < nr; r++) {
          if (keep[r]) dst[w++] = src[r];
        }
        out[c] = dst;
      } else if (a instanceof Long[]) {
        Long[] src = (Long[]) a;
        Long[] dst = new Long[kept];
        for (int r = 0, w = 0; r < nr; r++) {
          if (keep[r]) dst[w++] = src[r];
        }
        out[c] = dst;
      } else {
        Double[] src = (Double[]) a;
        Double[] dst = new Double[kept];
        for (int r = 0, w = 0; r < nr; r++) {
          if (keep[r]) dst[w++] = src[r];
        }
        out[c] = dst;
      }
    }
    return fromColumns(ctx, names, out);
  }

  /**
   * Keep rows where {@code filterLogic} holds on one column's value
   * (boxed {@code Long}/{@code Double}/{@code String} per dtype).
   *
   * <p>Parity: {@code Table.filter(int, Filter)} of the reference
   * ({@code Table.java:229}). The reference evaluates the user lambda
   * per row through a JNI callback into the JVM; here the predicate
   * runs over ONE bulk-read column and the surviving rows re-enter the
   * catalog as a fresh table — same contract, no per-row JNI
   * crossings.</p>
   */
  @SuppressWarnings("unchecked")
  public <I> Table filter(int columnIndex, Filter<I> filterLogic) {
    int nr = getRowCount();
    int nc = getColumnCount();
    Object[] cols = materializeColumns(nc);
    Object a = cols[columnIndex];
    boolean[] keep = new boolean[nr];
    int kept = 0;
    for (int r = 0; r < nr; r++) {
      if (filterLogic.filter((I) cell(a, r))) {
        keep[r] = true;
        kept++;
      }
    }
    return rebuild(cols, keep, kept);
  }

  /**
   * Keep rows the {@link Selector} accepts (whole-row predicate;
   * parity: {@code Table.select(Selector)}, {@code Table.java:240} /
   * native {@code select}, {@code Table.java:307}).
   */
  public Table select(Selector selector) {
    int nr = getRowCount();
    int nc = getColumnCount();
    Object[] cols = materializeColumns(nc);
    String[] names = new String[nc];
    for (int c = 0; c < nc; c++) {
      names[c] = getColumnName(c);
    }
    Row row = new Row(names, cols);
    boolean[] keep = new boolean[nr];
    int kept = 0;
    for (int r = 0; r < nr; r++) {
      row.seek(r);
      if (selector.select(row)) {
        keep[r] = true;
        kept++;
      }
    }
    return rebuild(cols, keep, kept);
  }

  /**
   * Map one column elementwise through {@code mapper} (parity:
   * {@code Table.mapColumn}, {@code Table.java:170}). Returns the
   * mapped values as a host {@link Column}, like the reference.
   */
  @SuppressWarnings("unchecked")
  public <I, O> Column<O> mapColumn(int colIndex, Mapper<I, O> mapper) {
    int nr = getRowCount();
    Object a = materializeColumn(colIndex);  // only the mapped column
    List<O> out = new ArrayList<O>(nr);
    for (int r = 0; r < nr; r++) {
      out.add(mapper.map((I) cell(a, r)));
    }
    return new Column<O>(getColumnName(colIndex), out);
  }

  /** Remove this table from the catalog (parity: {@code clear}). */
  public void clear() {
    nativeClear(id);
  }

  /** Host-side print of up to {@code maxRows} rows (parity:
   *  {@code Table.print}). */
  public void print(int maxRows) {
    int nc = getColumnCount();
    int nr = Math.min(getRowCount(), maxRows);
    StringBuilder sb = new StringBuilder();
    for (int c = 0; c < nc; c++) {
      sb.append(getColumnName(c)).append(c + 1 < nc ? "," : "\n");
    }
    Object[] cols = new Object[nc];
    for (int c = 0; c < nc; c++) {
      int t = getColumnType(c);
      cols[c] = t == DTYPE_FLOAT64 ? (Object) readDoubleColumn(c)
          : t == DTYPE_STRING_CODES ? (Object) readCodesColumn(c)
          : (Object) readLongColumn(c);
    }
    for (int r = 0; r < nr; r++) {
      for (int c = 0; c < nc; c++) {
        Object a = cols[c];
        if (a instanceof double[]) {
          sb.append(((double[]) a)[r]);
        } else if (a instanceof int[]) {
          sb.append(((int[]) a)[r]);
        } else {
          sb.append(((long[]) a)[r]);
        }
        sb.append(c + 1 < nc ? "," : "\n");
      }
    }
    System.out.print(sb);
  }

  // ----------------- native methods (cylon_jni.c) -----------------

  private static native void nativeLoadCSV(String path, String id);

  private static native void nativePutColumns(String id, String[] names,
                                              Object[] columns);

  private static native int nativeColumnCount(String id);

  private static native long nativeRowCount(String id);

  private static native String nativeColumnName(String id, int col);

  private static native int nativeColumnType(String id, int col);

  private static native long[] nativeReadI64(String id, int col);

  private static native double[] nativeReadF64(String id, int col);

  private static native int[] nativeReadCodes(String id, int col);

  private static native byte[] nativeReadValidity(String id, int col);

  private static native String[] nativeReadDictValues(String id, int col);

  private static native int nativeJoin(String left, String right,
                                       String dest, int leftCol,
                                       int rightCol, int joinType);

  private static native void nativeClear(String id);
}
