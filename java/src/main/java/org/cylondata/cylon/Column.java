package org.cylondata.cylon;

import java.util.List;

/**
 * A typed host-side column, returned by {@link Table#mapColumn}.
 *
 * <p>Parity: the reference's {@code Column<T>} (java/.../Column.java —
 * a typed holder the Java ops produce).
 */
public final class Column<T> {

  private final String name;
  private final List<T> values;

  Column(String name, List<T> values) {
    this.name = name;
    this.values = values;
  }

  public String getName() {
    return name;
  }

  public int size() {
    return values.size();
  }

  public T get(int i) {
    return values.get(i);
  }

  public List<T> values() {
    return values;
  }
}
