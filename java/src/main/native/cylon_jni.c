/* JNI bridge: org.cylondata.cylon.Table -> the host runtime C ABI.
 *
 * Parity: the reference's JNI layer
 * (java/src/main/native/src/Table.cpp, driven by the native method
 * declarations of Table.java:289-307) which forwards every call to the
 * string-id table_api catalog. Here the catalog is
 * cylon_tpu/native/cylon_host.h (cylon_catalog_*), shared with the
 * Python ctypes binding and the pure-C client
 * (examples/native/catalog_client.c) — three consumers, one ABI.
 *
 * Build (see java/build.sh):
 *   gcc -O2 -shared -fPIC -I$JAVA_HOME/include -I$JAVA_HOME/include/linux \
 *       cylon_jni.c -o libcylon_jni.so -L$LIBDIR -lcylon_host \
 *       -Wl,-rpath,$LIBDIR
 */
#include <jni.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "../../../../cylon_tpu/native/cylon_host.h"

static int col_info(JNIEnv *env, jstring jid, jint col, char *name_out,
                    int32_t name_cap, int32_t *dtype, int64_t *nbytes,
                    int32_t *has_valid);

static void throw_runtime(JNIEnv *env, const char *msg) {
  jclass cls = (*env)->FindClass(env, "java/lang/RuntimeException");
  if (cls) (*env)->ThrowNew(env, cls, msg);
}

/* ------------------------------------------------------------- CSV */

JNIEXPORT void JNICALL
Java_org_cylondata_cylon_Table_nativeLoadCSV(JNIEnv *env, jclass cls,
                                             jstring jpath, jstring jid) {
  const char *path = (*env)->GetStringUTFChars(env, jpath, NULL);
  const char *id = (*env)->GetStringUTFChars(env, jid, NULL);
  void *r = cylon_csv_read(path, ',', 1, 0);
  const char *err = cylon_csv_error(r);
  if (err) {
    throw_runtime(env, err);
    goto done;
  }
  {
    int64_t n = cylon_csv_num_rows(r);
    int32_t nc = cylon_csv_num_cols(r);
    /* string columns ship their dictionaries as the catalog's sidecar
     * convention ("<col>\x01blob" utf8 bytes + "<col>\x01offs" int64
     * offsets, shared with the Python binding) — without them, joins
     * on string keys would compare per-file codes */
    int32_t cap = nc * 3;
    const char **names = malloc(sizeof(char *) * cap);
    char **owned_names = calloc(cap, sizeof(char *));
    int32_t *dtypes = malloc(sizeof(int32_t) * cap);
    const void **bufs = malloc(sizeof(void *) * cap);
    int64_t *lens = malloc(sizeof(int64_t) * cap);
    const uint8_t **valids = malloc(sizeof(uint8_t *) * cap);
    void **owned = calloc(cap, sizeof(void *));
    uint8_t **ovalid = calloc(cap, sizeof(uint8_t *));
    /* pass 1: the real columns occupy slots 0..nc-1 (sidecars append
     * AFTER, so Java column indices == catalog indices) */
    int32_t slot = 0;
    for (int32_t c = 0; c < nc; c++) {
      int32_t s = slot++;
      names[s] = cylon_csv_col_name(r, c);
      dtypes[s] = cylon_csv_col_type(r, c);
      ovalid[s] = malloc((size_t) n);
      cylon_csv_col_validity(r, c, ovalid[s]);
      int all_valid = 1;
      for (int64_t i = 0; i < n; i++)
        if (!ovalid[s][i]) {
          all_valid = 0;
          break;
        }
      valids[s] = all_valid ? NULL : ovalid[s];
      if (dtypes[s] == 0) {
        owned[s] = malloc(sizeof(int64_t) * (size_t) n);
        cylon_csv_col_i64(r, c, (int64_t *) owned[s]);
        lens[s] = n * (int64_t) sizeof(int64_t);
      } else if (dtypes[s] == 1) {
        owned[s] = malloc(sizeof(double) * (size_t) n);
        cylon_csv_col_f64(r, c, (double *) owned[s]);
        lens[s] = n * (int64_t) sizeof(double);
      } else {
        owned[s] = malloc(sizeof(int32_t) * (size_t) n);
        cylon_csv_col_codes(r, c, (int32_t *) owned[s]);
        lens[s] = n * (int64_t) sizeof(int32_t);
      }
      bufs[s] = owned[s];
    }
    /* pass 2: dictionary sidecars for string columns */
    for (int32_t c = 0; c < nc; c++) {
      if (cylon_csv_col_type(r, c) != 2) continue;
      const char *base = cylon_csv_col_name(r, c);
      int32_t k = cylon_csv_dict_size(r, c);
      int64_t *offs = malloc(sizeof(int64_t) * ((size_t) k + 1));
      int64_t total = 0;
      offs[0] = 0;
      for (int32_t v = 0; v < k; v++) {
        total += (int64_t) strlen(cylon_csv_dict_value(r, c, v));
        offs[v + 1] = total;
      }
      char *blob = malloc(total ? (size_t) total : 1);
      for (int32_t v = 0; v < k; v++) {
        const char *val = cylon_csv_dict_value(r, c, v);
        memcpy(blob + offs[v], val, (size_t) (offs[v + 1] - offs[v]));
      }
      size_t base_len = strlen(base);
      int32_t bs = slot++;
      owned_names[bs] = malloc(base_len + 7);
      /* "\x01" kept as a separate literal: in C, "\x01b..." would
       * munch following hex digits into the escape */
      sprintf(owned_names[bs], "%s\x01" "blob", base);
      names[bs] = owned_names[bs];
      dtypes[bs] = 1;  /* Kind.UINT8 tag, Python-compatible */
      owned[bs] = blob;
      bufs[bs] = blob;
      lens[bs] = total;
      valids[bs] = NULL;
      ovalid[bs] = NULL;
      int32_t os = slot++;
      owned_names[os] = malloc(base_len + 7);
      sprintf(owned_names[os], "%s\x01" "offs", base);
      names[os] = owned_names[os];
      dtypes[os] = 8;  /* Kind.INT64 tag */
      owned[os] = offs;
      bufs[os] = offs;
      lens[os] = ((int64_t) k + 1) * 8;
      valids[os] = NULL;
      ovalid[os] = NULL;
    }
    if (cylon_catalog_put(id, slot, names, dtypes, n, bufs, lens, valids))
      throw_runtime(env, "catalog put failed");
    for (int32_t c = 0; c < cap; c++) {
      free(owned[c]);
      free(ovalid[c]);
      free(owned_names[c]);
    }
    free(names);
    free(owned_names);
    free(dtypes);
    free(bufs);
    free(lens);
    free(valids);
    free(owned);
    free(ovalid);
  }
done:
  cylon_csv_free(r);
  (*env)->ReleaseStringUTFChars(env, jpath, path);
  (*env)->ReleaseStringUTFChars(env, jid, id);
}

/* ------------------------------------------------- direct columns */

static int cmp_pstr(const void *a, const void *b) {
  return strcmp(*(const char *const *) a, *(const char *const *) b);
}

/* JNI strings cross the boundary as MODIFIED UTF-8 (CESU-8 surrogate
 * pairs for supplementary chars, C0 80 for U+0000), but the catalog
 * sidecar blobs are STRICT UTF-8 (the Python binding writes and
 * decodes them). Transcode both directions so cross-binding joins
 * compare identical bytes. U+0000 inside a value is unsupported by the
 * string layer (NUL-delimited plumbing) and becomes U+FFFD. */
static char *mutf8_to_utf8(const char *in) {
  size_t n = strlen(in);
  /* worst growth: a 2-byte C0 80 becomes a 3-byte U+FFFD (1.5x) */
  char *out = malloc(n * 3 / 2 + 4);
  size_t i = 0, w = 0;
  while (i < n) {
    unsigned char a = (unsigned char) in[i];
    if (a == 0xC0 && i + 1 < n && (unsigned char) in[i + 1] == 0x80) {
      out[w++] = (char) 0xEF;  /* U+FFFD: embedded NUL unsupported */
      out[w++] = (char) 0xBF;
      out[w++] = (char) 0xBD;
      i += 2;
      continue;
    }
    if (a == 0xED && i + 2 < n) {
      unsigned b = (unsigned char) in[i + 1], c = (unsigned char) in[i + 2];
      if (b >= 0xA0 && b <= 0xAF && i + 5 < n) {
        unsigned d = (unsigned char) in[i + 3];
        unsigned e = (unsigned char) in[i + 4];
        unsigned f = (unsigned char) in[i + 5];
        if (d == 0xED && e >= 0xB0 && e <= 0xBF) {
          unsigned hi = 0xD800u | ((b & 0x0Fu) << 6) | (c & 0x3Fu);
          unsigned lo = 0xDC00u | ((e & 0x0Fu) << 6) | (f & 0x3Fu);
          unsigned cp = 0x10000u + ((hi - 0xD800u) << 10)
              + (lo - 0xDC00u);
          out[w++] = (char) (0xF0 | (cp >> 18));
          out[w++] = (char) (0x80 | ((cp >> 12) & 0x3F));
          out[w++] = (char) (0x80 | ((cp >> 6) & 0x3F));
          out[w++] = (char) (0x80 | (cp & 0x3F));
          i += 6;
          continue;
        }
      }
      if (b >= 0xA0 && b <= 0xBF) {
        /* UNPAIRED surrogate (legal in a Java String): no valid UTF-8
         * form exists — U+FFFD keeps the blob strictly decodable */
        out[w++] = (char) 0xEF;
        out[w++] = (char) 0xBF;
        out[w++] = (char) 0xBD;
        i += 3;
        continue;
      }
    }
    out[w++] = in[i++];
  }
  out[w] = 0;
  return out;
}

static char *utf8_to_mutf8(const char *in, size_t n) {
  /* worst case: every 4-byte sequence becomes 6 bytes */
  char *out = malloc(n * 3 / 2 + 4);
  size_t i = 0, w = 0;
  while (i < n) {
    unsigned char a = (unsigned char) in[i];
    if (a >= 0xF0 && i + 3 < n) {
      unsigned cp = ((a & 0x07u) << 18)
          | (((unsigned char) in[i + 1] & 0x3Fu) << 12)
          | (((unsigned char) in[i + 2] & 0x3Fu) << 6)
          | ((unsigned char) in[i + 3] & 0x3Fu);
      unsigned hi = 0xD800u + ((cp - 0x10000u) >> 10);
      unsigned lo = 0xDC00u + ((cp - 0x10000u) & 0x3FFu);
      out[w++] = (char) 0xED;
      out[w++] = (char) (0xA0 | ((hi >> 6) & 0x0F));
      out[w++] = (char) (0x80 | (hi & 0x3F));
      out[w++] = (char) 0xED;
      out[w++] = (char) (0xB0 | ((lo >> 6) & 0x0F));
      out[w++] = (char) (0x80 | (lo & 0x3F));
      i += 4;
      continue;
    }
    out[w++] = in[i++];
  }
  out[w] = 0;
  return out;
}

JNIEXPORT void JNICALL
Java_org_cylondata_cylon_Table_nativePutColumns(JNIEnv *env, jclass cls,
                                                jstring jid,
                                                jobjectArray jnames,
                                                jobjectArray jcols) {
  if ((*env)->GetArrayLength(env, jnames)
      != (*env)->GetArrayLength(env, jcols)) {
    throw_runtime(env, "fromColumns: names and columns length mismatch");
    return;
  }
  const char *id = (*env)->GetStringUTFChars(env, jid, NULL);
  jsize nc = (*env)->GetArrayLength(env, jnames);
  /* String[] columns append two dictionary-sidecar slots each
   * ("<col>\x01blob" / "<col>\x01offs" — the Python binding's wire
   * convention, native/__init__.py), so joins on string keys compare
   * VALUES, not per-table codes */
  int32_t cap = (int32_t) nc * 3;
  const char **names = malloc(sizeof(char *) * cap);
  char **owned_names = calloc(cap, sizeof(char *));
  jstring *jname_refs = calloc(nc, sizeof(jstring));
  int32_t *dtypes = malloc(sizeof(int32_t) * cap);
  const void **bufs = malloc(sizeof(void *) * cap);
  int64_t *lens = malloc(sizeof(int64_t) * cap);
  const uint8_t **valids = malloc(sizeof(uint8_t *) * cap);
  void **owned = calloc(cap, sizeof(void *));
  uint8_t **ovalid = calloc(cap, sizeof(uint8_t *));
  int64_t n = -1;
  int bad = 0;
  int32_t slot = (int32_t) nc;  /* sidecars go after the user columns */

  jclass longArr = (*env)->FindClass(env, "[J");
  jclass dblArr = (*env)->FindClass(env, "[D");
  jclass strArr = (*env)->FindClass(env, "[Ljava/lang/String;");
  /* boxed Long[]/Double[]: null elements carry numeric NULLS through
   * (what Table.filter/select round-trip for nullable columns) */
  jclass boxLongArr = (*env)->FindClass(env, "[Ljava/lang/Long;");
  jclass boxDblArr = (*env)->FindClass(env, "[Ljava/lang/Double;");
  jclass longCls = (*env)->FindClass(env, "java/lang/Long");
  jclass dblCls = (*env)->FindClass(env, "java/lang/Double");
  jmethodID longVal = (*env)->GetMethodID(env, longCls, "longValue",
                                          "()J");
  jmethodID dblVal = (*env)->GetMethodID(env, dblCls, "doubleValue",
                                         "()D");
  for (jsize c = 0; c < nc; c++) {
    names[c] = "";
    dtypes[c] = 0;
    lens[c] = 0;
    valids[c] = NULL;
    jname_refs[c] = (jstring) (*env)->GetObjectArrayElement(env, jnames, c);
    if (jname_refs[c] == NULL) {
      /* GetStringUTFChars(NULL) would segfault the JVM */
      bad = 1;
    } else {
      names[c] = (*env)->GetStringUTFChars(env, jname_refs[c], NULL);
    }
    jobject col = (*env)->GetObjectArrayElement(env, jcols, c);
    jsize len = 0;
    if (col == NULL) {
      /* IsInstanceOf(NULL, cls) is JNI_TRUE per spec — a null column
       * would otherwise segfault in GetArrayLength */
      bad = 1;
    } else if ((*env)->IsInstanceOf(env, col, longArr)) {
      len = (*env)->GetArrayLength(env, (jarray) col);
      owned[c] = malloc(sizeof(int64_t) * (size_t) len);
      (*env)->GetLongArrayRegion(env, (jlongArray) col, 0, len,
                                 (jlong *) owned[c]);
      dtypes[c] = 0;
      lens[c] = (int64_t) len * 8;
    } else if ((*env)->IsInstanceOf(env, col, dblArr)) {
      len = (*env)->GetArrayLength(env, (jarray) col);
      owned[c] = malloc(sizeof(double) * (size_t) len);
      (*env)->GetDoubleArrayRegion(env, (jdoubleArray) col, 0, len,
                                   (jdouble *) owned[c]);
      dtypes[c] = 1;
      lens[c] = (int64_t) len * 8;
    } else if ((*env)->IsInstanceOf(env, col, boxLongArr)
               || (*env)->IsInstanceOf(env, col, boxDblArr)) {
      int is_long = (*env)->IsInstanceOf(env, col, boxLongArr);
      len = (*env)->GetArrayLength(env, (jarray) col);
      uint8_t *valid = malloc((size_t) len ? (size_t) len : 1);
      int any_null = 0;
      if (is_long) {
        int64_t *vals = malloc(sizeof(int64_t)
                               * ((size_t) len ? (size_t) len : 1));
        for (jsize i = 0; i < len; i++) {
          jobject e = (*env)->GetObjectArrayElement(
              env, (jobjectArray) col, i);
          if (e == NULL) {
            vals[i] = 0;
            valid[i] = 0;
            any_null = 1;
          } else {
            vals[i] = (int64_t) (*env)->CallLongMethod(env, e, longVal);
            valid[i] = 1;
            (*env)->DeleteLocalRef(env, e);
          }
        }
        dtypes[c] = 0;
        owned[c] = vals;
        lens[c] = (int64_t) len * 8;
      } else {
        double *vals = malloc(sizeof(double)
                              * ((size_t) len ? (size_t) len : 1));
        for (jsize i = 0; i < len; i++) {
          jobject e = (*env)->GetObjectArrayElement(
              env, (jobjectArray) col, i);
          if (e == NULL) {
            vals[i] = 0.0;
            valid[i] = 0;
            any_null = 1;
          } else {
            vals[i] = (double) (*env)->CallDoubleMethod(env, e, dblVal);
            valid[i] = 1;
            (*env)->DeleteLocalRef(env, e);
          }
        }
        dtypes[c] = 1;
        owned[c] = vals;
        lens[c] = (int64_t) len * 8;
      }
      if (any_null) {
        ovalid[c] = valid;
        valids[c] = valid;
      } else {
        free(valid);
      }
    } else if ((*env)->IsInstanceOf(env, col, strArr)) {
      /* dictionary-encode client-side: sorted-unique values (code
       * order == value order, matching the Python ingest), int32
       * codes, null elements -> validity 0 */
      len = (*env)->GetArrayLength(env, (jarray) col);
      char **svals = calloc((size_t) len ? (size_t) len : 1,
                            sizeof(char *));
      uint8_t *valid = malloc((size_t) len ? (size_t) len : 1);
      int any_null = 0;
      for (jsize i = 0; i < len; i++) {
        jstring js = (jstring) (*env)->GetObjectArrayElement(
            env, (jobjectArray) col, i);
        if (js == NULL) {
          valid[i] = 0;
          any_null = 1;
        } else {
          const char *u = (*env)->GetStringUTFChars(env, js, NULL);
          svals[i] = mutf8_to_utf8(u ? u : "");  /* strict UTF-8 blob */
          if (u) (*env)->ReleaseStringUTFChars(env, js, u);
          (*env)->DeleteLocalRef(env, js);
          valid[i] = 1;
        }
      }
      char **sorted = malloc(sizeof(char *) * ((size_t) len ? len : 1));
      int32_t m = 0;
      for (jsize i = 0; i < len; i++)
        if (svals[i]) sorted[m++] = svals[i];
      qsort(sorted, (size_t) m, sizeof(char *), cmp_pstr);
      int32_t u = 0;
      for (int32_t i = 0; i < m; i++)
        if (i == 0 || strcmp(sorted[i], sorted[u - 1]) != 0)
          sorted[u++] = sorted[i];
      int32_t *codes = malloc(sizeof(int32_t) * ((size_t) len ? len : 1));
      for (jsize i = 0; i < len; i++) {
        if (!svals[i]) {
          codes[i] = 0;
          continue;
        }
        char **hit = bsearch(&svals[i], sorted, (size_t) u,
                             sizeof(char *), cmp_pstr);
        codes[i] = hit ? (int32_t) (hit - sorted) : 0;
      }
      dtypes[c] = 2;
      owned[c] = codes;
      lens[c] = (int64_t) len * 4;
      if (any_null) {
        ovalid[c] = valid;
        valids[c] = valid;
      } else {
        free(valid);
      }
      /* dictionary sidecars over the unique values */
      int64_t *offs = malloc(sizeof(int64_t) * ((size_t) u + 1));
      int64_t total = 0;
      offs[0] = 0;
      for (int32_t v = 0; v < u; v++) {
        total += (int64_t) strlen(sorted[v]);
        offs[v + 1] = total;
      }
      char *blob = malloc(total ? (size_t) total : 1);
      for (int32_t v = 0; v < u; v++)
        memcpy(blob + offs[v], sorted[v],
               (size_t) (offs[v + 1] - offs[v]));
      size_t base_len = strlen(names[c]);
      int32_t bs = slot++;
      owned_names[bs] = malloc(base_len + 7);
      /* "\x01" kept separate: "\x01b..." would munch hex digits */
      sprintf(owned_names[bs], "%s\x01" "blob", names[c]);
      names[bs] = owned_names[bs];
      dtypes[bs] = 1;  /* Kind.UINT8 tag, Python-compatible */
      owned[bs] = blob;
      bufs[bs] = blob;
      lens[bs] = total;
      valids[bs] = NULL;
      int32_t os = slot++;
      owned_names[os] = malloc(base_len + 7);
      sprintf(owned_names[os], "%s\x01" "offs", names[c]);
      names[os] = owned_names[os];
      dtypes[os] = 8;  /* Kind.INT64 tag */
      owned[os] = offs;
      bufs[os] = offs;
      lens[os] = ((int64_t) u + 1) * 8;
      valids[os] = NULL;
      for (jsize i = 0; i < len; i++) free(svals[i]);
      free(svals);
      free(sorted);
    } else {
      bad = 1;
    }
    bufs[c] = owned[c];
    if (n < 0) n = len;
    if (len != n) bad = 1;
  }
  if (bad) {
    throw_runtime(env, "fromColumns: columns must be equal-length "
                       "long[], double[] or String[]");
  } else if (cylon_catalog_put(id, slot, names, dtypes, n, bufs,
                               lens, valids)) {
    throw_runtime(env, "catalog put failed");
  }
  for (int32_t c = 0; c < cap; c++) {
    free(owned[c]);
    free(ovalid[c]);
    free(owned_names[c]);
  }
  for (jsize c = 0; c < nc; c++) {
    if (jname_refs[c] != NULL)
      (*env)->ReleaseStringUTFChars(env, jname_refs[c], names[c]);
  }
  free(names);
  free(owned_names);
  free(jname_refs);
  free(dtypes);
  free(bufs);
  free(lens);
  free(valids);
  free(owned);
  free(ovalid);
  (*env)->ReleaseStringUTFChars(env, jid, id);
}

/* --------------------------------------------------- properties */

JNIEXPORT jint JNICALL
Java_org_cylondata_cylon_Table_nativeColumnCount(JNIEnv *env, jclass cls,
                                                 jstring jid) {
  const char *id = (*env)->GetStringUTFChars(env, jid, NULL);
  int32_t v = cylon_catalog_ncols(id);
  (*env)->ReleaseStringUTFChars(env, jid, id);
  return (jint) v;
}

JNIEXPORT jlong JNICALL
Java_org_cylondata_cylon_Table_nativeRowCount(JNIEnv *env, jclass cls,
                                              jstring jid) {
  /* jlong: the catalog's row count is int64 by design — truncating to
   * jint would silently wrap past 2^31 rows */
  const char *id = (*env)->GetStringUTFChars(env, jid, NULL);
  int64_t v = cylon_catalog_rows(id);
  (*env)->ReleaseStringUTFChars(env, jid, id);
  return (jlong) v;
}

JNIEXPORT jobjectArray JNICALL
Java_org_cylondata_cylon_Table_nativeReadDictValues(JNIEnv *env, jclass cls,
                                                    jstring jid, jint col) {
  /* decode the "<col>\x01blob"/"\x01offs" sidecar pair (see
   * nativeLoadCSV) into the column's dictionary values */
  char base[512];
  int32_t dt, hv;
  int64_t nb;
  if (col_info(env, jid, col, base, sizeof base, &dt, &nb, &hv)) {
    throw_runtime(env, "bad column");
    return NULL;
  }
  const char *id = (*env)->GetStringUTFChars(env, jid, NULL);
  int32_t nc = cylon_catalog_ncols(id);
  char want_blob[520], want_offs[520];
  sprintf(want_blob, "%s\x01" "blob", base);
  sprintf(want_offs, "%s\x01" "offs", base);
  int bi = -1, oi = -1;
  for (int32_t i = 0; i < nc; i++) {
    char nm[520];
    int32_t d2, h2;
    int64_t n2;
    if (cylon_catalog_col_info(id, i, nm, sizeof nm, &d2, &n2, &h2) < 0)
      continue;
    if (strcmp(nm, want_blob) == 0) bi = i;
    if (strcmp(nm, want_offs) == 0) oi = i;
  }
  jobjectArray out = NULL;
  if (bi >= 0 && oi >= 0) {
    char nm[520];
    int32_t d2, h2;
    int64_t blob_len, offs_len;
    cylon_catalog_col_info(id, bi, nm, sizeof nm, &d2, &blob_len, &h2);
    cylon_catalog_col_info(id, oi, nm, sizeof nm, &d2, &offs_len, &h2);
    char *blob = malloc(blob_len ? (size_t) blob_len : 1);
    int64_t *offs = malloc((size_t) offs_len);
    cylon_catalog_col_read(id, bi, blob, blob_len, NULL);
    cylon_catalog_col_read(id, oi, offs, offs_len, NULL);
    jsize k = (jsize) (offs_len / 8 - 1);
    jclass strcls = (*env)->FindClass(env, "java/lang/String");
    out = (*env)->NewObjectArray(env, k, strcls, NULL);
    for (jsize v = 0; v < k; v++) {
      int64_t a = offs[v], b = offs[v + 1];
      /* NewStringUTF expects MODIFIED UTF-8; the blob is strict */
      char *tmp = utf8_to_mutf8(blob + a, (size_t) (b - a));
      jstring s = (*env)->NewStringUTF(env, tmp);
      (*env)->SetObjectArrayElement(env, out, v, s);
      free(tmp);
    }
    free(blob);
    free(offs);
  }
  (*env)->ReleaseStringUTFChars(env, jid, id);
  return out;  /* NULL: no dictionary for this column */
}

/* name/dtype/length/validity of column i via cylon_catalog_col_info */
static int col_info(JNIEnv *env, jstring jid, jint col, char *name_out,
                    int32_t name_cap, int32_t *dtype, int64_t *nbytes,
                    int32_t *has_valid) {
  const char *id = (*env)->GetStringUTFChars(env, jid, NULL);
  int32_t rc = cylon_catalog_col_info(id, col, name_out, name_cap, dtype,
                                      nbytes, has_valid);
  (*env)->ReleaseStringUTFChars(env, jid, id);
  return rc < 0 ? -1 : 0;
}

JNIEXPORT jstring JNICALL
Java_org_cylondata_cylon_Table_nativeColumnName(JNIEnv *env, jclass cls,
                                                jstring jid, jint col) {
  char name[512];
  int32_t dt, hv;
  int64_t nb;
  if (col_info(env, jid, col, name, sizeof name, &dt, &nb, &hv)) {
    throw_runtime(env, "bad column");
    return NULL;
  }
  return (*env)->NewStringUTF(env, name);
}

JNIEXPORT jint JNICALL
Java_org_cylondata_cylon_Table_nativeColumnType(JNIEnv *env, jclass cls,
                                                jstring jid, jint col) {
  char name[512];
  int32_t dt = -1, hv;
  int64_t nb;
  if (col_info(env, jid, col, name, sizeof name, &dt, &nb, &hv)) {
    throw_runtime(env, "bad column");
  }
  return (jint) dt;
}

/* --------------------------------------------------- data readers */

static void *read_col(JNIEnv *env, jstring jid, jint col, int64_t *nbytes,
                      int32_t *dtype) {
  char name[512];
  int32_t hv;
  if (col_info(env, jid, col, name, sizeof name, dtype, nbytes, &hv)) {
    throw_runtime(env, "bad column");
    return NULL;
  }
  void *buf = malloc((size_t) *nbytes);
  const char *id = (*env)->GetStringUTFChars(env, jid, NULL);
  int32_t rc = cylon_catalog_col_read(id, col, buf, *nbytes, NULL);
  (*env)->ReleaseStringUTFChars(env, jid, id);
  if (rc != 0) {
    free(buf);
    throw_runtime(env, "column read failed");
    return NULL;
  }
  return buf;
}

JNIEXPORT jlongArray JNICALL
Java_org_cylondata_cylon_Table_nativeReadI64(JNIEnv *env, jclass cls,
                                             jstring jid, jint col) {
  int64_t nbytes;
  int32_t dt;
  void *buf = read_col(env, jid, col, &nbytes, &dt);
  if (!buf) return NULL;
  jsize n = (jsize) (nbytes / 8);
  jlongArray out = (*env)->NewLongArray(env, n);
  (*env)->SetLongArrayRegion(env, out, 0, n, (const jlong *) buf);
  free(buf);
  return out;
}

JNIEXPORT jdoubleArray JNICALL
Java_org_cylondata_cylon_Table_nativeReadF64(JNIEnv *env, jclass cls,
                                             jstring jid, jint col) {
  int64_t nbytes;
  int32_t dt;
  void *buf = read_col(env, jid, col, &nbytes, &dt);
  if (!buf) return NULL;
  jsize n = (jsize) (nbytes / 8);
  jdoubleArray out = (*env)->NewDoubleArray(env, n);
  (*env)->SetDoubleArrayRegion(env, out, 0, n, (const jdouble *) buf);
  free(buf);
  return out;
}

JNIEXPORT jintArray JNICALL
Java_org_cylondata_cylon_Table_nativeReadCodes(JNIEnv *env, jclass cls,
                                               jstring jid, jint col) {
  int64_t nbytes;
  int32_t dt;
  void *buf = read_col(env, jid, col, &nbytes, &dt);
  if (!buf) return NULL;
  jsize n = (jsize) (nbytes / 4);
  jintArray out = (*env)->NewIntArray(env, n);
  (*env)->SetIntArrayRegion(env, out, 0, n, (const jint *) buf);
  free(buf);
  return out;
}

JNIEXPORT jbyteArray JNICALL
Java_org_cylondata_cylon_Table_nativeReadValidity(JNIEnv *env, jclass cls,
                                                  jstring jid, jint col) {
  char name[512];
  int32_t dt, hv;
  int64_t nbytes;
  if (col_info(env, jid, col, name, sizeof name, &dt, &nbytes, &hv)) {
    throw_runtime(env, "bad column");
    return NULL;
  }
  if (!hv) return NULL;
  const char *id = (*env)->GetStringUTFChars(env, jid, NULL);
  int64_t n = cylon_catalog_rows(id);
  uint8_t *valid = malloc((size_t) n);
  void *data = malloc((size_t) nbytes);
  int32_t rc = cylon_catalog_col_read(id, col, data, nbytes, valid);
  (*env)->ReleaseStringUTFChars(env, jid, id);
  free(data);
  if (rc != 0) {
    free(valid);
    throw_runtime(env, "column read failed");
    return NULL;
  }
  jbyteArray out = (*env)->NewByteArray(env, (jsize) n);
  (*env)->SetByteArrayRegion(env, out, 0, (jsize) n, (const jbyte *) valid);
  free(valid);
  return out;
}

/* ------------------------------------------------------------ join */

JNIEXPORT jint JNICALL
Java_org_cylondata_cylon_Table_nativeJoin(JNIEnv *env, jclass cls,
                                          jstring jleft, jstring jright,
                                          jstring jdest, jint leftCol,
                                          jint rightCol, jint joinType) {
  const char *l = (*env)->GetStringUTFChars(env, jleft, NULL);
  const char *r = (*env)->GetStringUTFChars(env, jright, NULL);
  const char *d = (*env)->GetStringUTFChars(env, jdest, NULL);
  int32_t lk = (int32_t) leftCol, rk = (int32_t) rightCol;
  int32_t rc = cylon_catalog_join(l, r, d, 1, &lk, &rk, (int32_t) joinType);
  (*env)->ReleaseStringUTFChars(env, jleft, l);
  (*env)->ReleaseStringUTFChars(env, jright, r);
  (*env)->ReleaseStringUTFChars(env, jdest, d);
  return (jint) rc;
}

JNIEXPORT void JNICALL
Java_org_cylondata_cylon_Table_nativeClear(JNIEnv *env, jclass cls,
                                           jstring jid) {
  const char *id = (*env)->GetStringUTFChars(env, jid, NULL);
  cylon_catalog_remove(id);
  (*env)->ReleaseStringUTFChars(env, jid, id);
}
