#!/bin/sh
# Build + run the Java binding (parity: the reference's build.sh --java
# leg). Requires a JDK; the native host runtime (libcylon_host.so) is
# built automatically by the Python package, or directly with:
#   g++ -O3 -std=c++17 -shared -fPIC -pthread \
#       -o cylon_tpu/native/libcylon_host.so cylon_tpu/native/cylon_host.cpp
#
# Usage: java/build.sh [run]
set -e
cd "$(dirname "$0")"
REPO="$(cd .. && pwd)"
LIBDIR="$REPO/cylon_tpu/native"
OUT="$PWD/target"
mkdir -p "$OUT/classes"

: "${JAVA_HOME:=$(dirname "$(dirname "$(readlink -f "$(command -v javac)")")")}"

# 1. host runtime (skip if fresh; header changes rebuild too — a stale
#    .so against a new ABI would corrupt reads)
if [ ! -f "$LIBDIR/libcylon_host.so" ] || \
   [ "$LIBDIR/cylon_host.cpp" -nt "$LIBDIR/libcylon_host.so" ] || \
   [ "$LIBDIR/cylon_host.h" -nt "$LIBDIR/libcylon_host.so" ]; then
  g++ -O3 -std=c++17 -shared -fPIC -pthread \
      -o "$LIBDIR/libcylon_host.so" "$LIBDIR/cylon_host.cpp"
fi

# 2. JNI bridge
gcc -O2 -shared -fPIC \
    -I"$JAVA_HOME/include" -I"$JAVA_HOME/include/linux" \
    src/main/native/cylon_jni.c -o "$OUT/libcylon_jni.so" \
    -L"$LIBDIR" -lcylon_host -Wl,-rpath,"$LIBDIR"

# 3. Java classes
javac -d "$OUT/classes" \
    src/main/java/org/cylondata/cylon/*.java \
    src/main/java/org/cylondata/cylon/examples/*.java

# 4. optionally run the example
if [ "$1" = "run" ]; then
  CYLON_JNI_LIB="$OUT/libcylon_jni.so" \
      java -cp "$OUT/classes" org.cylondata.cylon.examples.JoinExample
fi
