"""Full benchmark suite: one JSON line per BASELINE.json config.

``bench.py`` stays the driver's single headline line (config 2); this
suite covers all five configs for broader tracking:

1. local inner merge (pycylon ``DataFrame.merge`` analog)
2. distributed hash inner-join (headline; same as bench.py)
3. distributed groupby-aggregate (sum/mean/count)
4. distributed sample-sort + set-union
5. TPC-H wall-clock, all 22 queries

Scale knobs: CYLON_BENCH_ROWS (default 1M), CYLON_BENCH_TPCH_SF
(default 0.1), CYLON_BENCH_REPS (default 3). Distributed configs run
over every visible device (1 real chip under axon; N with a mesh).
``--trace`` arms the flight recorder (``CYLON_TPU_TRACE``, inherited
by spawned children) and appends a ``trace_artifact`` record pointing
at the Chrome Trace JSON written next to the records.

The CHAOS section (``--chaos``) is the kill-level robustness proof: for
each out-of-core op it hard-kills a child mid-pass at a seeded fault
point (``FaultRule.kill`` → ``os._exit``), resumes from the durable
checkpoint in a fresh child, and asserts the resumed output is
byte-identical (sha256) to a fault-free oracle child's — one JSON
record per op. See docs/resilience.md "Checkpoint & recovery".

The EXCHANGE section (``--exchange``, also spawned automatically at the
end of a full run) times the multi-device shuffle/dist_join paths on an
8-device virtual CPU mesh — the one place the variable-size all-to-all
(`parallel.shuffle.exchange_arrays`) actually exchanges between shards
on this single-chip machine. Without it a shuffle regression would ship
invisibly behind the world==1 short-circuit (VERDICT r2 weak #2).
Parity: ``cpp/src/examples/bench/table_join_dist_test.cpp:38-56``.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np



def _artifacts_dir() -> str:
    """The bench-artifact directory — bench.py's ARTIFACTS_DIR is the
    single source of truth (one env knob, one default literal), so the
    suite's trace paths can never diverge from the headline bench's."""
    import bench as headline

    return headline.ARTIFACTS_DIR
def _timeit(fn, sync, reps):
    fn()  # compile
    float(np.asarray(sync()).ravel()[0])
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        float(np.asarray(sync()).ravel()[0])
        ts.append(time.perf_counter() - t0)
    return min(ts)


def _metrics_block():
    """Registry snapshot block embedded in EVERY bench record: byte /
    overflow / retry / padding context rides the perf trajectory, not
    just wall time (the required keys are pinned by
    ``tests/test_bench_guard.py`` so a future PR cannot silently drop
    them). Failure-proof: a bench must never die on telemetry."""
    try:
        from cylon_tpu import telemetry

        return telemetry.bench_metrics()
    except Exception as e:  # pragma: no cover - import-time breakage
        return {"telemetry_error": f"{type(e).__name__}: {e}"}


def _emit_record(line: dict):
    """The ONE stdout sink for bench JSON records — every record gets
    the telemetry ``metrics`` block attached here (the bench guard
    lints that no other call site prints ``json.dumps`` directly)."""
    line = dict(line)
    line["metrics"] = _metrics_block()
    print(json.dumps(line))


def _emit(metric, value, unit, baseline=None):
    line = {"metric": metric, "value": round(value, 1), "unit": unit}
    if baseline:
        line["vs_baseline"] = round(value / baseline, 3)
    _emit_record(line)


def _subproc_timeout():
    """Ceiling (seconds) on every child process the suite spawns —
    CYLON_BENCH_SUBPROC_TIMEOUT, default 3600, <= 0 disables. A child
    that hangs (wedged device, stuck collective) is killed at the
    ceiling and classified as a CRASH, so the respawn paths re-run its
    unattempted queries instead of the whole harness hanging forever
    with no diagnostics."""
    v = float(os.environ.get("CYLON_BENCH_SUBPROC_TIMEOUT", "3600"))
    return v if v > 0 else None


def main():
    import jax

    # persistent compile cache: the package points jax at
    # ~/.cache/cylon_tpu/xla on import (shared with every other run);
    # CYLON_COMPILE_CACHE reroutes it for an isolated cache — it must be
    # mapped onto the package knob BEFORE the import, which would
    # otherwise override it
    cache = os.environ.get("CYLON_COMPILE_CACHE")
    if cache:
        os.environ["CYLON_TPU_CACHE_DIR"] = cache

    import cylon_tpu as ct
    from cylon_tpu import Table
    from cylon_tpu.ops.groupby import groupby_aggregate
    from cylon_tpu.ops.join import join
    from cylon_tpu.ops.selection import sort_table
    from cylon_tpu.ops.setops import union

    n = int(os.environ.get("CYLON_BENCH_ROWS", 1_000_000))
    reps = int(os.environ.get("CYLON_BENCH_REPS", 3))
    sf = float(os.environ.get("CYLON_BENCH_TPCH_SF", 0.1))
    rng = np.random.default_rng(7)
    baseline_join = 1e9 / 4.0 / 64  # Cylon 64-rank rows/s/rank

    left = Table.from_pydict({"k": rng.integers(0, n, n).astype(np.int64),
                              "a": rng.normal(size=n)})
    right = Table.from_pydict({"k": rng.integers(0, n, n).astype(np.int64),
                               "b": rng.normal(size=n)})

    # 1. local inner merge ------------------------------------------------
    f1 = jax.jit(lambda l, r: join(l, r, on="k", how="inner",
                                   out_capacity=2 * n))
    out = {}
    t = _timeit(lambda: out.__setitem__("r", f1(left, right)),
                lambda: out["r"].nrows, reps)
    _emit("local_inner_merge_rows_per_sec", n / t, "rows/s", baseline_join)

    # 2. distributed join: bench.py is authoritative; rerun inline -------
    import bench as headline

    headline.main()

    # 3. distributed groupby ---------------------------------------------
    gt = Table.from_pydict({
        "k": rng.integers(0, 10_000, 10 * n).astype(np.int64),
        "v": rng.normal(size=10 * n)})
    f3 = jax.jit(lambda tt: groupby_aggregate(
        tt, ["k"], [("v", "sum"), ("v", "mean"), ("v", "count")],
        out_capacity=16_384))
    t = _timeit(lambda: out.__setitem__("g", f3(gt)),
                lambda: out["g"].nrows, reps)
    _emit("groupby_agg_rows_per_sec", 10 * n / t, "rows/s")

    # 3b. high-cardinality groupby: ~0.6 groups per row — the shape
    # where XLA's segment lowering collapses and the TPU segmented-scan
    # path (kernels.segmented_totals) carries the load
    hk = max(n * 6 // 10, 1)
    ht = Table.from_pydict({
        "k": rng.integers(0, hk, n).astype(np.int64),
        "v": rng.normal(size=n)})
    f3b = jax.jit(lambda tt: groupby_aggregate(
        tt, ["k"], [("v", "sum"), ("v", "mean"), ("v", "count")],
        out_capacity=hk + 1))
    t = _timeit(lambda: out.__setitem__("h", f3b(ht)),
                lambda: out["h"].nrows, reps)
    _emit("groupby_highcard_rows_per_sec", n / t, "rows/s")

    # 4. sort + union ------------------------------------------------------
    st = Table.from_pydict({"k": rng.integers(0, 2**40, n).astype(np.int64)})
    f4 = jax.jit(lambda tt: sort_table(tt, ["k"]))
    t = _timeit(lambda: out.__setitem__("s", f4(st)),
                lambda: out["s"].column("k").data[:1], reps)
    _emit("sort_rows_per_sec", n / t, "rows/s")
    ut = Table.from_pydict({"k": rng.integers(0, n, n).astype(np.int64)})
    f4b = jax.jit(lambda a, b: union(a, b, 2 * n))
    t = _timeit(lambda: out.__setitem__("u", f4b(st, ut)),
                lambda: out["u"].nrows, reps)
    _emit("union_rows_per_sec", 2 * n / t, "rows/s")

    # 5. TPC-H (the full 22-query suite), whole-query compiled -----------
    # each query is ONE XLA program (cylon_tpu.plan): one dispatch + one
    # result fetch, vs the eager chain's ~5-10 host syncs (~100 ms each
    # over the tunnel)
    from cylon_tpu import tpch
    from cylon_tpu.tpch import dbgen

    acct = _run_tpch(sf, reps)
    if acct["skipped"]:
        # a device crash truncated the suite and killed THIS process's
        # backend: finish the unattempted queries in fresh processes
        crash_log: list = []
        agg = {"tpch_attempted": acct["attempted"],
               "tpch_crashed": acct["crashed"],
               "tpch_ooc": acct["ooc_pending"]}
        _tpch_respawn("--tpch", acct["skipped"], agg, crash_log)
        if agg.get("tpch_skipped"):
            # recorded DNF with NAMES: queries no respawn ever reached
            # (each process already emitted its own ooc_dropped lines
            # for lost out-of-core completions — no re-report here)
            _emit_record({"metric": f"tpch_sf{sf}_never_attempted",
                          "value": len(agg["tpch_skipped"]),
                          "unit": "queries",
                          "queries": agg["tpch_skipped"]})
        for msg in crash_log:
            _emit_record({"metric": "tpch_respawn_failure",
                          "detail": msg})

    # 6. TPU ragged exchange: the flagship lax.ragged_all_to_all path,
    # runtime-proven on the real chip (W=1 mesh still compiles and
    # executes the ragged collective, the 64-bit split and
    # Pallas-under-shard_map on real Mosaic — VERDICT r3 missing #3).
    # A TPC-H device crash killed THIS process's backend — skip with a
    # recorded DNF instead of dying on the first dispatch (section 7
    # runs in its own child either way)
    if jax.devices()[0].platform in ("tpu", "axon"):
        if acct["crashed"]:
            _emit("tpu_exchange_skipped_dead_backend", 1,
                  "device crash earlier in suite")
        else:
            tpu_exchange_main()

    # 7. exchange path (separate process: the CPU mesh needs XLA_FLAGS
    # set before jax imports, and must not disturb this process's
    # backend)
    child_env = dict(os.environ)
    child_env["XLA_FLAGS"] = (child_env.get("XLA_FLAGS", "")
                              + " --xla_force_host_platform_device_count=8")
    # tracing parent: the child does the actual exchange dispatches, so
    # it gets --trace and its OWN artifact path (the epilogue runs in
    # the child); without the flag the inherited armed recorder would
    # buffer events nobody exports
    from cylon_tpu.telemetry import trace as _tr

    tracing_child = _tr.enabled()
    if tracing_child:
        # a DISTINCT path: sharing the parent's would let the parent's
        # end-of-suite artifact overwrite the child's
        base = os.environ.get("CYLON_BENCH_TRACE_PATH",
                              os.path.join(_artifacts_dir(),
                                       "bench_suite.trace.json"))
        root = base[:-5] if base.endswith(".json") else base
        child_env["CYLON_BENCH_TRACE_PATH"] = root + ".exchange.json"
    else:
        child_env.pop("CYLON_TPU_TRACE", None)
    try:
        subprocess.run([sys.executable, os.path.abspath(__file__),
                        "--exchange"]
                       + (["--trace"] if tracing_child else []),
                       env=child_env, check=False,
                       timeout=_subproc_timeout())
    except subprocess.TimeoutExpired:
        # recorded DNF for the leg; the rest of the suite already ran
        _emit("exchange_leg_timeout", 1,
              "child killed at CYLON_BENCH_SUBPROC_TIMEOUT")


def _fallback_parts() -> int:
    """Partition count for the suite's spill completions:
    CYLON_BENCH_FALLBACK_PARTS (clamped >= 1, typos degrade to the
    library default) — unset defers to
    ``cylon_tpu.fallback.default_partitions``."""
    from cylon_tpu.fallback import default_partitions

    v = os.environ.get("CYLON_BENCH_FALLBACK_PARTS")
    if not v:
        return default_partitions()
    try:
        return max(int(v), 1)
    except ValueError:  # a typo'd knob must not DNF the completions
        return default_partitions()


def _fallback_resume_dir(name: str) -> "str | None":
    """``CYLON_BENCH_FALLBACK_DIR/<name>`` when the checkpoint-root
    knob is set (a killed at-scale completion resumes instead of
    restarting); None — no checkpointing — otherwise. The ONE place
    the suite derives fallback resume locations."""
    root = os.environ.get("CYLON_BENCH_FALLBACK_DIR")
    return os.path.join(root, name) if root else None


def _fallback_ok(qname: str) -> bool:
    """Can this query complete out-of-core after an OOM? The two
    hand-written streaming paths (q1/q5) plus every query with a
    usable generic spill plan in ``tpch.manifest.FALLBACK``
    (``cylon_tpu.fallback.supports``)."""
    if qname in ("q1", "q5"):
        return True
    from cylon_tpu.fallback import supports

    return supports(qname)


def _is_oom(e: Exception) -> bool:
    """Device-memory exhaustion at a shape is a RESULT (the single-chip
    ceiling); anything else is a regression and must fail the bench."""
    return (isinstance(e, MemoryError)
            or "RESOURCE_EXHAUSTED" in str(e)
            or "ResourceExhausted" in str(e))


def _is_crash(e: Exception) -> bool:
    """Did the DEVICE WORKER die (vs a clean in-process OOM)? Observed
    at SF10: over-allocation comes back as UNAVAILABLE "worker process
    crashed" — the backend is unusable in this process afterwards, so
    the caller must respawn to continue. Also matches the resilience
    layer's Code.Unavailable (injected preemptions)."""
    s = str(e)
    if "UNAVAILABLE" in s or "worker process crashed" in s:
        return True
    code = getattr(e, "code", None)
    return getattr(code, "name", None) == "Unavailable"


def _hbm_stats(tag: str):
    """Emit device memory headroom (HBM on TPU) — the scale runs track
    how close each config sits to the 16 GB ceiling."""
    import jax

    try:
        st = jax.local_devices()[0].memory_stats() or {}
    except Exception:
        st = {}
    used = st.get("bytes_in_use")
    limit = st.get("bytes_limit")
    if used is not None:
        _emit(f"hbm_in_use_{tag}", used / 2**30, "GiB")
    if used is not None and limit:
        _emit(f"hbm_headroom_{tag}", (limit - used) / 2**30, "GiB")


def _run_tpch(sf, reps, tag_hbm: bool = False, ooc_report=None):
    """Time the (whole-query-compiled) TPC-H suite at scale factor
    ``sf``. CYLON_BENCH_TPCH_QUERIES="q1,q3,q5,q6" restricts the set
    (the SF10 runs time the numeric-heavy subset; full suite at
    SF<=1). Emits regrow events: any query whose capacity ladder
    settled above 1x reports its final scale.

    ``ooc_report``: a list to APPEND OOM'd-query names to instead of
    running their out-of-core fallbacks here — the at-scale driver runs
    them in a separate process, because an execution-time OOM leaves
    the failed run's device buffers unreclaimable in-process on this
    backend (the fallback would start with HBM already full).

    Returns ``{"attempted", "crashed", "skipped", "ooc_pending"}``
    (cross-process aggregation rides the sentinel JSON) — ``skipped``
    is the selected queries a device crash left untried, the exact set a
    respawned process should re-run via CYLON_BENCH_TPCH_QUERIES;
    ``ooc_pending`` is the out-of-core completions still owed (crash
    path with no ``ooc_report`` cannot run them in this process — the
    backend is dead — so they are RETURNED and emitted as
    ``ooc_dropped`` rather than silently lost). A crash also emits
    attempted/crashed/skipped count metrics, so a truncated suite is
    visible in the metrics JSON instead of silently DNF."""
    import numpy as np

    from cylon_tpu import tpch
    from cylon_tpu.tpch import dbgen

    only = os.environ.get("CYLON_BENCH_TPCH_QUERIES")
    valid = {f"q{i}" for i in range(1, 23)}
    only = ({q.strip() for q in only.split(",")} & valid) if only else None
    keep_by_table = None
    if only and os.environ.get("CYLON_BENCH_TPCH_PRUNE_INGEST",
                               "1") != "0":
        # query-subset runs generate AND ingest only the columns those
        # queries reference (the storage-scan projection any engine
        # does) — at SF10 a full lineitem load alone is ~10 GB of HBM,
        # and at SF100 full generation alone would dwarf host RAM.
        # Keep-sets AND predicate are the SAME explicit manifest +
        # queries.manifest_keep that queries._tables prunes by, so the
        # two layers cannot diverge
        from cylon_tpu.tpch.manifest import MANIFEST
        from cylon_tpu.tpch.queries import manifest_keep

        keep_by_table = {}
        for qn in sorted(only):
            for t, ks in MANIFEST[qn].items():
                keep_by_table.setdefault(t, set()).update(ks)
    data = dbgen.generate(sf=sf, seed=0, keep=keep_by_table)
    if keep_by_table is not None:
        from cylon_tpu.tpch.manifest import MANIFEST
        from cylon_tpu.tpch.queries import manifest_keep

        # a table NO selected query reads keeps zero columns (ingest
        # builds an empty frame for it; nothing is device_put)
        data = {t: {c: cols[c] for c in manifest_keep(
                        t, cols, keep_by_table.get(t, frozenset()))}
                for t, cols in data.items()}
    names = [f"q{i}" for i in range(1, 23)]
    selected = [q for q in names if only is None or q in only]
    # EXPLAIN-style pre-flight (the single-chip ceiling, same contract
    # as fallback.run_query's): with a device budget in force
    # (CYLON_TPU_HBM_BUDGET_BYTES or real allocator limits), a query
    # whose manifest-projected input bytes × the transient-expansion
    # knob exceed free device memory routes STRAIGHT to the out-of-
    # core completion — no doomed ingest+dispatch. At SF100 this is
    # load-bearing: the in-core attempt would die on ingest before any
    # recordable OOM. Plain CPU (no budget) stands down as ever.
    from cylon_tpu import fallback as _fb

    free = _fb.free_hbm_bytes()
    preflight: dict = {}
    if free is not None:
        from cylon_tpu.tpch.manifest import MANIFEST

        exp = _fb.expansion_factor()
        for qname in selected:
            est = 0
            for t, ks in MANIFEST[qname].items():
                for c in ks:
                    arr = data.get(t, {}).get(c)
                    if arr is None:
                        continue
                    a = np.asarray(arr)
                    # object strings ride as padded device bytes:
                    # ~64 B/row is the manifest columns' envelope
                    est += (len(a) * 64 if a.dtype == object
                            else a.nbytes)
            est = int(est * exp)
            if est > free:
                preflight[qname] = est
    # tables pre-ingested once (the reference's TPC-H timing also runs
    # on loaded tables); tpch.ingest applies the storage policy
    # (comment columns as device bytes — at SF>=1 a host dictionary
    # for them would be the dataset). When EVERY selected query was
    # preflight-routed there is nothing to ingest — skip the load
    # entirely (at SF100 even the pruned ingest is tens of GB)
    dfs = (tpch.ingest(data)
           if len(preflight) < len(selected) else None)
    if tag_hbm:
        _hbm_stats(f"tpch_sf{sf}_ingest")
    # eager mode: one compiled program PER OPERATOR instead of per
    # query — at very large scale factors the whole-query programs can
    # take minutes each to compile, and the per-op executables are
    # shared across queries
    eager = os.environ.get("CYLON_BENCH_TPCH_MODE") == "eager"
    ooc_pending: list = []
    attempted: list = []
    crashed: list = []
    scalar_q = ("q6", "q14", "q17", "q19")

    def _accounting(pending=()):
        skipped = [q for q in selected if q not in attempted]
        _emit(f"tpch_sf{sf}_attempted", len(attempted), "queries")
        _emit(f"tpch_sf{sf}_crashed", len(crashed), "queries")
        _emit(f"tpch_sf{sf}_skipped", len(skipped), "queries")
        return {"attempted": list(attempted), "crashed": list(crashed),
                "skipped": skipped, "ooc_pending": list(pending)}

    def _checkpoint():
        # per-query progress snapshot to the sentinel: if this process
        # is KILLED mid-query (parent timeout on a hang, OOM-killer),
        # the parent still learns exactly what was attempted and
        # charges the in-flight query as the crash (_classify_timeout)
        sentinel = os.environ.get("CYLON_SCALE_SENTINEL")
        if not sentinel:
            return
        try:
            # tmp + fsync + rename (resilience.atomic_write_json): the
            # parent may KILL this process at any instant (that is the
            # point), and a torn half-written JSON would read as "no
            # report" — losing the whole checkpoint history
            from cylon_tpu.resilience import atomic_write_json

            atomic_write_json(sentinel, {
                "tpch_attempted": list(attempted),
                "tpch_crashed": list(crashed),
                "tpch_skipped": [q for q in selected
                                 if q not in attempted],
                "tpch_ooc": list(ooc_pending)})
        except OSError:
            pass  # checkpointing must never fail the run

    for qname in selected:
        if qname in preflight:
            _emit_record({
                "metric": f"tpch_{qname}_sf{sf}_preflight_spill",
                "value": 1, "unit": "routed to ooc fallback",
                "predicted_bytes": preflight[qname],
                "free_hbm_bytes": free, "path": "ooc_fallback"})
            if _fallback_ok(qname):
                ooc_pending.append(qname)
            else:  # pragma: no cover - all 22 queries carry a plan
                _emit(f"tpch_{qname}_sf{sf}_fallback_unsupported", 1,
                      "no spill decomposition")
            attempted.append(qname)
            _checkpoint()
            continue
        qfn = getattr(tpch, qname) if eager else tpch.compiled(qname)
        res = {}
        try:
            if qname in scalar_q:
                t = _timeit(lambda: res.__setitem__(
                    "r", np.float64(qfn(dfs))), lambda: res["r"], reps)
            else:
                t = _timeit(lambda: res.__setitem__("r", qfn(dfs)),
                            lambda: res["r"].table.nrows, reps)
            # path column: the suite's per-query walls are auditable —
            # in_core here, ooc_fallback on the completion records
            _emit_record({"metric": f"tpch_{qname}_sf{sf}_wall",
                          "value": round(t * 1e3, 1), "unit": "ms",
                          "path": "in_core"})
        except Exception as e:
            if _is_crash(e):
                # the TPU WORKER died (observed at SF10: q1's over-
                # allocation comes back as UNAVAILABLE "worker process
                # crashed", not a clean RESOURCE_EXHAUSTED). The
                # backend is unusable in this process from here on —
                # record it, queue the query's out-of-core completion,
                # and abandon the remaining queries (the driver
                # respawns a fresh process for exactly the skipped
                # set — see _tpch_respawn / scale_main)
                _emit(f"tpch_{qname}_sf{sf}_device_crash", 1,
                      type(e).__name__)
                attempted.append(qname)
                crashed.append(qname)
                if _fallback_ok(qname):
                    ooc_pending.append(qname)
                if ooc_report is not None:
                    ooc_report.extend(ooc_pending)
                else:
                    # no collector and a dead backend: the OOC
                    # completions cannot run in this process — record
                    # the drop (and return it) instead of losing it
                    for q in ooc_pending:
                        _emit(f"tpch_{q}_sf{sf}_ooc_dropped", 1,
                              "device crash; complete via --scale or "
                              "a fresh --tpch run")
                return _accounting(ooc_pending)
            if not _is_oom(e):
                raise
            _emit(f"tpch_{qname}_sf{sf}_oom", 1, type(e).__name__)
            res.clear()
            if _fallback_ok(qname):
                ooc_pending.append(qname)
            else:  # pragma: no cover - all 22 queries carry a plan
                # recorded DNF, never a silent one (since ISSUE 16's
                # two-phase plans this arm is unreachable for TPC-H
                # names; it guards future non-TPC-H query sets)
                _emit(f"tpch_{qname}_sf{sf}_fallback_unsupported", 1,
                      "no spill decomposition")
        attempted.append(qname)
        _checkpoint()
    # regrow events: CompiledQuery memoizes the scale each (query,
    # shape) settled at — >1 means the capacity ladder re-dispatched
    for fn, cq in tpch._COMPILED.items():
        memo = getattr(cq, "_scale_memo", {})
        worst = max(memo.values(), default=1)
        if worst > 1:
            _emit(f"tpch_{fn.__name__}_sf{sf}_regrow_scale", worst, "x")
    if tag_hbm:
        _hbm_stats(f"tpch_sf{sf}_end")
    if ooc_report is not None:
        ooc_report.extend(ooc_pending)
        return _accounting()
    # out-of-core completion for the OOM'd queries (VERDICT r4 missing
    # #2) — AFTER dropping the device-resident ingest (dfs holds e.g.
    # SF10's ~10 GB lineitem; the streaming runs need that HBM back).
    # Slow is fine, DNF is not; its own OOM is a recorded result, not
    # a suite abort.
    if ooc_pending:
        import gc

        from cylon_tpu.tpch import streaming

        dfs = None
        gc.collect()
        _tpch_ooc(data, ooc_pending, sf)
    return _accounting()


def _tpch_ooc(data, qnames, sf):
    """Out-of-core completion for ``qnames``: the hand-written
    streaming variants for q1/q5, the generic manifest-driven
    partition fallback (:mod:`cylon_tpu.fallback`) for every other
    supported query. One wall record per query, ``path=ooc_fallback``
    — with a checkpoint dir (CYLON_BENCH_FALLBACK_DIR) a killed
    at-scale completion resumes instead of restarting."""
    from cylon_tpu import fallback, telemetry
    from cylon_tpu.tpch import streaming

    nparts = _fallback_parts()
    for qname in qnames:
        try:
            # every query here was routed to the spill path by the
            # bench harness after an in-core failure (a clean OOM or a
            # device crash — the sentinel merge loses the distinction,
            # so the label claims neither) — count it on the pinned
            # trajectory counter (run_with_fallback is bypassed here)
            telemetry.counter("ooc.fallbacks", op=qname,
                              reason="bench").inc()
            t0 = time.perf_counter()
            if qname in ("q1", "q5"):
                ofn = (streaming.q1_ooc if qname == "q1"
                       else streaming.q5_ooc)
                out = ofn(data, resume_dir=_fallback_resume_dir(qname))
                out.table.num_rows
            else:
                out = fallback.tpch_fallback(
                    qname, data, n_partitions=nparts,
                    resume_dir=_fallback_resume_dir(qname))
            t = time.perf_counter() - t0
            _emit_record({"metric": f"tpch_{qname}_sf{sf}_ooc_wall",
                          "value": round(t * 1e3, 1), "unit": "ms",
                          "path": "ooc_fallback"})
            del out
        except Exception as e:
            if not _is_oom(e):
                raise
            _emit(f"tpch_{qname}_sf{sf}_ooc_oom", 1, type(e).__name__)


def _spawn_sentinel(flag, extra_env=None):
    """Run this file in a child process with ``flag``, collecting its
    sentinel-JSON report (the process-boundary contract scale_main's
    docstring explains). Returns ``(returncode, report | None,
    timed_out)`` — a None report means the child died without
    reporting (a crash, not a recorded result); ``timed_out`` means it
    was KILLED at the :func:`_subproc_timeout` ceiling (a hang — the
    report, if any, is the child's last per-query checkpoint, and the
    caller classifies the in-flight query as crashed)."""
    import tempfile

    with tempfile.NamedTemporaryFile("r", suffix=".json",
                                     delete=False) as f:
        sentinel = f.name
    child_env = dict(os.environ)
    child_env.update(extra_env or {})
    child_env["CYLON_SCALE_SENTINEL"] = sentinel
    # sentinel children have no trace exporter wired (their argv has no
    # --trace, so no artifact epilogue runs): an inherited armed
    # recorder would buffer 64k events for nothing — strip it; per-leg
    # tracing is a direct `bench_suite.py --tpch --trace`-style run
    child_env.pop("CYLON_TPU_TRACE", None)
    timed_out = False
    try:
        rc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), flag],
            env=child_env, timeout=_subproc_timeout()).returncode
    except subprocess.TimeoutExpired:
        rc, timed_out = -9, True  # run() killed the child on expiry
    try:
        with open(sentinel) as f:
            part = json.load(f)
    except (OSError, ValueError):
        part = None
    finally:
        import glob

        # atomic_write_json tmps are '<name>.tmp<pid>_<tid>_<seq>' —
        # a child killed mid-sentinel-write (the chaos legs do exactly
        # that) strands one; sweep the whole family
        for p in [sentinel] + glob.glob(sentinel + ".tmp*"):
            try:
                os.unlink(p)
            except OSError:
                pass
    return rc, part, timed_out


def _classify_timeout(part, queried):
    """A timed-out TPC-H child was killed mid-query: its sentinel (the
    per-query checkpoint ``_run_tpch`` maintains, possibly absent if it
    hung before the first query completed) lists what it finished, so
    the hang victim is the first selected query not yet attempted.
    Classify that query as attempted+crashed — exactly how an in-child
    device crash reports — so ``_tpch_respawn`` strictly shrinks the
    skipped set and re-runs the remainder in a fresh process."""
    part = dict(part or {})
    names = [f"q{i}" for i in range(1, 23)]
    selected = [q for q in names if q in queried]
    attempted = list(part.get("tpch_attempted", []))
    hung = next((q for q in selected if q not in attempted), None)
    if hung is not None:
        attempted.append(hung)
        part["tpch_crashed"] = part.get("tpch_crashed", []) + [hung]
    part["tpch_attempted"] = attempted
    part["tpch_skipped"] = [q for q in selected if q not in attempted]
    part.setdefault("tpch_ooc", [])
    return part


def _tpch_respawn(flag, skipped, agg, crash_log):
    """Crash-respawn driver: a device crash abandons every query after
    it AND leaves the crashed process's backend unusable, so the only
    way to finish the suite is a FRESH process restricted (via
    CYLON_BENCH_TPCH_QUERIES) to exactly the unattempted set. Loops
    until the suite completes, a child dies without reporting, or a
    respawn makes no progress (every child attempts >= 1 query — a
    crashed query counts as attempted — so the skipped set strictly
    shrinks on any healthy child). Children's attempted/crashed/
    ooc-pending lists accumulate into ``agg``; the surviving skipped
    set lands in ``agg["tpch_skipped"]`` — non-empty means recorded
    DNF, never a silent one."""
    prev = None
    while skipped and skipped != prev:
        prev = skipped
        _emit("tpch_respawn_queries", len(skipped), "queries")
        rc, part, timed_out = _spawn_sentinel(flag, {
            "CYLON_BENCH_TPCH_QUERIES": ",".join(sorted(skipped))})
        if timed_out:
            # a HUNG child (killed at the timeout ceiling) is a crash:
            # charge the in-flight query and re-run the remainder
            part = _classify_timeout(part, set(skipped))
            crash_log.append(
                f"tpch respawn ({flag}) timed out; "
                f"{part['tpch_crashed'][-1:]} classified as crashed")
        elif part is None:
            crash_log.append(
                f"tpch respawn ({flag}) exited rc={rc} with no "
                "sentinel")
            break
        for k in ("tpch_attempted", "tpch_crashed", "tpch_ooc"):
            agg[k] = agg.get(k, []) + part.get(k, [])
        skipped = part.get("tpch_skipped", [])
    agg["tpch_skipped"] = skipped
    return agg


def scale_main():
    """--scale: the at-scale proof runs (VERDICT r3 missing #2) on the
    real chip — TPC-H at CYLON_BENCH_TPCH_SF (1 / 10) and the
    BASELINE.json larger join/sort configs at CYLON_BENCH_ROWS
    (10M / 100M), with HBM headroom tracked per stage.

    PROCESS STRUCTURE: each in-core attempt that may exceed HBM runs in
    its OWN child process (``--scale-incore=<join|sort|tpch>``), and the
    out-of-core completions run here in the parent afterwards. An
    execution-time OOM on this backend leaves the failed run's device
    buffers unreclaimable in-process (observed: after the 100M join's
    OOM, a 128 MB device_put still reports RESOURCE_EXHAUSTED after
    releasing every reference + gc), so "record the OOM, then complete
    out-of-core" is only reliable across a process boundary. The child
    reports which configs OOM'd via a sentinel JSON file; metrics print
    straight through to this process's stdout. The chip is leased one
    process at a time — children run sequentially and exit cleanly
    before the parent touches the device."""
    n = int(os.environ.get("CYLON_BENCH_ROWS", 0))
    sf = float(os.environ.get("CYLON_BENCH_TPCH_SF", 0))
    report = {}
    crashed = []
    legs = (["join", "sort"] if n else []) + (["tpch"] if sf else [])
    for leg in legs:
        rc, part, timed_out = _spawn_sentinel(f"--scale-incore={leg}")
        if timed_out and leg == "tpch":
            # hung child killed at the ceiling: classify the in-flight
            # query as crashed (from its per-query checkpoint) and let
            # the respawn path below finish the remainder
            only = os.environ.get("CYLON_BENCH_TPCH_QUERIES")
            queried = ({q.strip() for q in only.split(",")} if only
                       else {f"q{i}" for i in range(1, 23)})
            part = _classify_timeout(part, queried)
            crashed.append(f"--scale-incore={leg} timed out; "
                           "in-flight query classified as crashed")
        elif timed_out:
            crashed.append(f"--scale-incore={leg} killed at "
                           "CYLON_BENCH_SUBPROC_TIMEOUT (hang)")
            continue
        elif part is None:
            # the child died without reporting (not a recorded OOM — a
            # crash). Record it, but DON'T abort yet: earlier legs'
            # out-of-core completions must still run ("slow is fine,
            # DNF is not"), and they cannot run interleaved here — the
            # chip is leased one process at a time, so the parent must
            # not touch the device until every child has exited
            crashed.append(f"--scale-incore={leg} exited rc={rc} "
                           "with no sentinel")
            continue
        report.update(part)
        if leg == "tpch" and part.get("tpch_skipped"):
            # a device crash truncated the suite mid-leg: respawn fresh
            # processes for the unattempted queries (accumulating their
            # attempted/crashed/ooc reports into this parent's view)
            _tpch_respawn(f"--scale-incore={leg}",
                          part["tpch_skipped"], report, crashed)
    if "tpch_attempted" in report:
        _emit(f"tpch_sf{sf}_total_attempted",
              len(report["tpch_attempted"]), "queries")
        _emit(f"tpch_sf{sf}_total_crashed",
              len(report.get("tpch_crashed", [])), "queries")
        _emit(f"tpch_sf{sf}_total_skipped",
              len(report.get("tpch_skipped", [])), "queries")
        if report.get("tpch_skipped"):
            crashed.append("tpch queries never attempted after "
                           f"respawns: {report['tpch_skipped']}")

    if report.get("join_oom"):
        # out-of-core completion (VERDICT r4 missing #2): host-
        # partitioned spill join over the same device kernels, in this
        # so-far-device-idle parent (fresh HBM). Fresh rng(7) per leg:
        # each child seeds its own, so the leading draws reproduce
        # exactly the inputs that child OOM'd on
        from cylon_tpu.outofcore import ooc_join

        rng = np.random.default_rng(7)

        nparts = max(8, n // 12_500_000)
        lsrc = {"k": rng.integers(0, n, n).astype(np.int64),
                "a": rng.normal(size=n)}
        rsrc = {"k": rng.integers(0, n, n).astype(np.int64),
                "b": rng.normal(size=n)}
        # the sink pays the full device->host spill per partition
        # (honest wall) but retains only byte counts — keeping the
        # frames would re-create the memory pressure this path exists
        # to avoid
        spilled_bytes = [0]

        def _spill(df):
            spilled_bytes[0] += int(df.memory_usage(index=False).sum())

        t0 = time.perf_counter()
        total = ooc_join(lsrc, rsrc, on="k", n_partitions=nparts,
                         sink=_spill,
                         resume_dir=_fallback_resume_dir("join"))
        t = time.perf_counter() - t0
        assert total > 0
        _emit(f"local_inner_merge_{n}_ooc_rows_per_sec", n / t,
              "rows/s", 1e9 / 4.0 / 64)
        _emit(f"local_inner_merge_{n}_ooc_out_rows", float(total), "rows")
        _emit(f"local_inner_merge_{n}_ooc_spilled",
              spilled_bytes[0] / 2**30, "GiB")
        lsrc = rsrc = None

    if report.get("sort_oom"):
        # sample-sort completion: range-ordered spills ARE the sorted
        # table (the sink only counts bytes here, like the join's)
        from cylon_tpu.outofcore import ooc_sort

        src = {"k": np.random.default_rng(7)
               .integers(0, 2**40, n).astype(np.int64)}
        sorted_bytes = [0]

        def _ssink(df):
            sorted_bytes[0] += int(df.memory_usage(index=False).sum())

        t0 = time.perf_counter()
        total = ooc_sort(src, "k",
                         n_partitions=max(8, n // 12_500_000),
                         sink=_ssink,
                         resume_dir=_fallback_resume_dir("sort"))
        t = time.perf_counter() - t0
        assert total == n
        _emit(f"sort_{n}_ooc_rows_per_sec", n / t, "rows/s")
        _emit(f"sort_{n}_ooc_spilled", sorted_bytes[0] / 2**30, "GiB")
        src = None

    if report.get("tpch_ooc"):
        from cylon_tpu.tpch import dbgen
        from cylon_tpu.tpch.manifest import MANIFEST
        from cylon_tpu.tpch.queries import manifest_keep

        pending = report["tpch_ooc"]
        # generate AND prune to the pending queries' manifests, like
        # the child's ingest — regenerating SF10 unpruned would hold
        # ~10+ GB of comment strings in host RAM for streaming runs
        # that read only lineitem's numeric columns + the small build
        # tables (at SF100 unpruned generation would not fit at all)
        keep_by_table: dict = {}
        for qn in sorted(set(pending)):
            for t, ks in MANIFEST[qn].items():
                keep_by_table.setdefault(t, set()).update(ks)
        data = dbgen.generate(sf=sf, seed=0, keep=keep_by_table)
        data = {t: {c: cols[c] for c in manifest_keep(
                        t, cols, keep_by_table.get(t, frozenset()))}
                for t, cols in data.items()}
        _tpch_ooc(data, pending, sf)

    if crashed:
        raise RuntimeError("; ".join(crashed))


#: the at-scale race configs (ISSUE 16 / ROADMAP item 1) — the runs
#: the paper's claim is about, as named legs so the guard tests can
#: pin them and a driver can re-run any one by name. Each leg is one
#: ``--scale`` invocation (inheriting scale_main's sentinel +
#: crash-respawn machinery) with this env overlaid. The HBM budget
#: pins the v5e single-chip ceiling so in_core-vs-ooc_fallback routing
#: matches the real chip even on a CPU dev host.
SCALE_LEGS = (
    # the full 22-query suite at SF10: per-query wall + path column
    ("tpch_sf10_full", {"CYLON_BENCH_TPCH_SF": "10",
                        "CYLON_BENCH_ROWS": "0",
                        "CYLON_BENCH_TPCH_QUERIES": "",
                        "CYLON_TPU_HBM_BUDGET_BYTES": "17179869184"}),
    # the 1B-row inner-join config (BASELINE.json's headline scale)
    ("join_1b", {"CYLON_BENCH_ROWS": "1000000000",
                 "CYLON_BENCH_TPCH_SF": "0",
                 "CYLON_TPU_HBM_BUDGET_BYTES": "17179869184"}),
    # SF100 Q3/Q5: manifest-pruned generation (full SF100 dbgen would
    # dwarf host RAM), preflight-routed to the out-of-core paths
    ("tpch_sf100_q3q5", {"CYLON_BENCH_TPCH_SF": "100",
                         "CYLON_BENCH_ROWS": "0",
                         "CYLON_BENCH_TPCH_QUERIES": "q3,q5",
                         "CYLON_TPU_HBM_BUDGET_BYTES": "17179869184"}),
)


def race_main():
    """--race: run the :data:`SCALE_LEGS` at-scale configs end to end,
    one ``--scale`` child per leg (each child gets scale_main's full
    sentinel / timeout-classification / crash-respawn coverage), with
    a wall + rc record per leg. CYLON_BENCH_RACE_LEGS="name1,name2"
    restricts the set. A failed leg is a recorded failure line and the
    remaining legs still run — the race never silently truncates."""
    only = os.environ.get("CYLON_BENCH_RACE_LEGS")
    only = {s.strip() for s in only.split(",")} if only else None
    failures = []
    for name, leg_env in SCALE_LEGS:
        if only is not None and name not in only:
            continue
        child_env = dict(os.environ)
        child_env.update(leg_env)
        t0 = time.perf_counter()
        rc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--scale"],
            env=child_env, check=False).returncode
        _emit_record({"metric": f"race_{name}_wall",
                      "value": round(time.perf_counter() - t0, 1),
                      "unit": "s", "leg": name, "rc": rc})
        if rc != 0:
            failures.append(f"{name}: rc={rc}")
    if failures:
        raise RuntimeError("race legs failed: " + "; ".join(failures))


def scale_incore_main(leg: str):
    """One in-core at-scale attempt (see :func:`scale_main`): emits its
    metrics (or its OOM line) and writes the sentinel JSON telling the
    parent which out-of-core completions to run."""
    import jax

    import cylon_tpu as ct  # noqa: F401  (enables x64 + cache)
    from cylon_tpu import Table
    from cylon_tpu.ops.join import join
    from cylon_tpu.ops.selection import sort_table

    reps = int(os.environ.get("CYLON_BENCH_REPS", 2))
    n = int(os.environ.get("CYLON_BENCH_ROWS", 0))
    sf = float(os.environ.get("CYLON_BENCH_TPCH_SF", 0))
    rng = np.random.default_rng(7)
    out = {}
    report = {}

    if leg == "join":
        # pre-flight (ROADMAP item 1 / the 1B-row config): a join whose
        # predicted working set cannot fit free HBM routes STRAIGHT to
        # the parent's out-of-core completion — no doomed multi-minute
        # ingest+dispatch, no allocator churn. 16 bytes/row/table
        # (int64 key + float64 payload) × the transient-expansion knob.
        from cylon_tpu import fallback as _fb
        from cylon_tpu import telemetry as _tm

        est = int(2 * 16 * n * _fb.expansion_factor())
        free = _fb.free_hbm_bytes()
        if free is not None and est > free:
            _tm.counter("ooc.fallbacks", op="join",
                        reason="preflight").inc()
            _emit_record({
                "metric": f"local_inner_merge_{n}_preflight_spill",
                "value": 1, "unit": "routed to ooc_join",
                "predicted_bytes": est, "free_hbm_bytes": free,
                "path": "ooc_fallback"})
            report["join_oom"] = True
        if not report.get("join_oom"):
            try:
                left = Table.from_pydict(
                    {"k": rng.integers(0, n, n).astype(np.int64),
                     "a": rng.normal(size=n)})
                right = Table.from_pydict(
                    {"k": rng.integers(0, n, n).astype(np.int64),
                     "b": rng.normal(size=n)})
                _hbm_stats(f"join_{n}_ingest")
                f1 = jax.jit(lambda l, r: join(l, r, on="k",
                                               how="inner",
                                               out_capacity=2 * n))
                t = _timeit(lambda: out.__setitem__("r",
                                                    f1(left, right)),
                            lambda: out["r"].nrows, reps)
                _emit(f"local_inner_merge_{n}_rows_per_sec", n / t,
                      "rows/s", 1e9 / 4.0 / 64)
                _hbm_stats(f"join_{n}_end")
                report["join_oom"] = False
            except Exception as e:
                if not _is_oom(e):  # only allocation failures are
                    raise           # results
                _emit(f"local_inner_merge_{n}_oom", 1,
                      type(e).__name__)
                report["join_oom"] = True
    elif leg == "sort":
        try:
            st = Table.from_pydict(
                {"k": rng.integers(0, 2**40, n).astype(np.int64)})
            f2 = jax.jit(lambda tt: sort_table(tt, ["k"]))
            t = _timeit(lambda: out.__setitem__("s", f2(st)),
                        lambda: out["s"].column("k").data[:1], reps)
            _emit(f"sort_{n}_rows_per_sec", n / t, "rows/s")
            _hbm_stats(f"sort_{n}_end")
            report["sort_oom"] = False
        except Exception as e:
            if not _is_oom(e):
                raise
            _emit(f"sort_{n}_oom", 1, type(e).__name__)
            report["sort_oom"] = True
    elif leg == "tpch":
        pending: list = []
        acct = _run_tpch(sf, reps, tag_hbm=True, ooc_report=pending)
        report["tpch_ooc"] = pending
        report["tpch_attempted"] = acct["attempted"]
        report["tpch_crashed"] = acct["crashed"]
        report["tpch_skipped"] = acct["skipped"]
    else:
        raise ValueError(f"unknown --scale-incore leg {leg!r}")

    sentinel = os.environ.get("CYLON_SCALE_SENTINEL")
    if sentinel:
        from cylon_tpu.resilience import atomic_write_json

        atomic_write_json(sentinel, report)


def tpch_main():
    """--tpch: the TPC-H leg alone, in its own process — the respawn
    target main() uses after a device crash (a fresh process is the
    only way to a working backend). CYLON_BENCH_TPCH_QUERIES restricts
    the set; accounting reports through CYLON_SCALE_SENTINEL when the
    parent set one."""
    import cylon_tpu as ct  # noqa: F401  (enables x64 + cache)

    reps = int(os.environ.get("CYLON_BENCH_REPS", 3))
    sf = float(os.environ.get("CYLON_BENCH_TPCH_SF", 0.1))
    acct = _run_tpch(sf, reps)
    sentinel = os.environ.get("CYLON_SCALE_SENTINEL")
    if sentinel:
        from cylon_tpu.resilience import atomic_write_json

        atomic_write_json(sentinel, {
            "tpch_attempted": acct["attempted"],
            "tpch_crashed": acct["crashed"],
            "tpch_skipped": acct["skipped"],
            "tpch_ooc": acct["ooc_pending"]})


def chaos_child_main(op: str):
    """One chaos run of an out-of-core op (see :func:`chaos_main`):
    deterministic inputs, an optional seeded hard-kill plan
    (CYLON_BENCH_CHAOS_KILL="point:nth"), a resume checkpoint dir
    (CYLON_BENCH_CHAOS_DIR; unset = fault-free oracle run), and a
    sentinel report carrying the sha256 of the exact byte stream the
    sink saw — the "byte-identical resumed output" proof is a hash
    equality across child processes."""
    import hashlib

    import cylon_tpu  # noqa: F401  (enables x64 + cache)
    from cylon_tpu import resilience, telemetry
    from cylon_tpu.outofcore import ooc_groupby, ooc_join, ooc_sort

    n = int(os.environ.get("CYLON_BENCH_CHAOS_ROWS", "40000"))
    rdir = os.environ.get("CYLON_BENCH_CHAOS_DIR")
    kill = os.environ.get("CYLON_BENCH_CHAOS_KILL")
    if kill:
        point, nth = kill.rsplit(":", 1)
        resilience.install(resilience.FaultPlan(
            [resilience.FaultRule.kill(point, nth=int(nth))]))
    rng = np.random.default_rng(29)
    h = hashlib.sha256()

    def sink(df):
        # %.17g round-trips float64 exactly: identical frames hash
        # identically, and ANY divergence (dtype, order, value) shows
        h.update(df.to_csv(index=False, float_format="%.17g").encode())

    chunk = n // 7 + 1
    if op == "sort":
        src = {"k": rng.integers(0, 1000, n).astype(np.int64),
               "v": rng.normal(size=n)}
        total = ooc_sort(src, ["k", "v"], n_partitions=6,
                         chunk_rows=chunk, sink=sink, resume_dir=rdir)
    elif op == "join":
        left = {"k": rng.integers(0, n, n).astype(np.int64),
                "a": rng.normal(size=n)}
        right = {"k": rng.integers(0, n, n).astype(np.int64),
                 "b": rng.normal(size=n)}
        total = ooc_join(left, right, on="k", n_partitions=6,
                         chunk_rows=chunk, sink=sink, resume_dir=rdir)
    elif op == "groupby":
        src = {"g": rng.integers(0, 64, n).astype(np.int64),
               "v": rng.normal(size=n)}
        out = ooc_groupby(src, ["g"],
                          [("v", "sum", "s"), ("v", "count", "c")],
                          chunk_rows=chunk, resume_dir=rdir)
        pdf = out.to_pandas().sort_values("g").reset_index(drop=True)
        sink(pdf)
        total = len(pdf)
    else:
        raise ValueError(f"unknown chaos op {op!r}")
    sentinel = os.environ.get("CYLON_SCALE_SENTINEL")
    if sentinel:
        from cylon_tpu.resilience import atomic_write_json

        atomic_write_json(sentinel, {
            "sha256": h.hexdigest(), "rows": int(total),
            "units_resumed": telemetry.total("ooc.units_resumed")})


def chaos_main():
    """--chaos: the kill-level robustness proof (ISSUE 8). For each
    out-of-core op (sort/join/groupby), three child processes:

    1. an ORACLE child computes the fault-free output hash;
    2. a KILLED child runs the same workload with a resume_dir and a
       seeded ``FaultRule.kill`` plan — it must die HARD
       (``os._exit``, status ``KILL_EXIT_CODE``) mid-pass, leaving a
       partial durable checkpoint;
    3. a RESUME child re-invokes with identical args + resume_dir —
       it must actually resume (``units_resumed >= 1``) and its output
       hash must equal the oracle's byte for byte.

    Any deviation (child survived the kill, resumed hash differs,
    nothing resumed) fails the leg; one JSON record per op pins the
    artifact."""
    import shutil
    import tempfile

    from cylon_tpu.resilience import KILL_EXIT_CODE

    kills = {"sort": "spill_write:2", "join": "spill_write:2",
             "groupby": "spill_write:2"}
    failures = []
    for op, kill in kills.items():
        tmp = tempfile.mkdtemp(prefix=f"cylon-chaos-{op}-")
        try:
            rc0, oracle, _ = _spawn_sentinel(f"--chaos-child={op}")
            if oracle is None:
                failures.append(f"{op}: oracle child rc={rc0} with "
                                "no report")
                continue
            rc1, rep1, _ = _spawn_sentinel(
                f"--chaos-child={op}",
                {"CYLON_BENCH_CHAOS_DIR": tmp,
                 "CYLON_BENCH_CHAOS_KILL": kill})
            killed = rc1 == KILL_EXIT_CODE and rep1 is None
            if not killed:
                failures.append(
                    f"{op}: kill child exited rc={rc1} "
                    f"(want {KILL_EXIT_CODE}, no sentinel)")
            rc2, rep2, _ = _spawn_sentinel(
                f"--chaos-child={op}", {"CYLON_BENCH_CHAOS_DIR": tmp})
            identical = (rep2 is not None
                         and rep2["sha256"] == oracle["sha256"]
                         and rep2["rows"] == oracle["rows"])
            resumed = bool(rep2) and rep2.get("units_resumed", 0) >= 1
            if not identical:
                failures.append(f"{op}: resumed output != fault-free "
                                f"oracle ({rep2} vs {oracle})")
            elif not resumed:
                failures.append(f"{op}: resume child recomputed from "
                                "scratch (units_resumed=0) — the "
                                "checkpoint was not used")
            _emit_record({
                "metric": f"chaos_{op}_resume",
                "value": 1.0 if (killed and identical and resumed)
                else 0.0,
                "unit": "byte-identical resume",
                "kill": kill,
                "killed_rc": rc1,
                "rows": oracle["rows"],
                "oracle_sha256": oracle["sha256"],
                "resumed_sha256": rep2["sha256"] if rep2 else None,
                "units_resumed": rep2.get("units_resumed") if rep2
                else None,
            })
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    if failures:
        raise RuntimeError("chaos harness failures: "
                           + "; ".join(failures))


def tpu_exchange_main():
    """Force the ragged exchange on a 1-device TPU mesh. Every CPU test
    runs the padded path (XLA:CPU has no ragged-all-to-all thunk) and
    every real-chip op short-circuits at world==1, so without this the
    single most load-bearing TPU component (SURVEY §3.2) would only
    ever be compile-checked. Parity role: the reference's exchange runs
    under every mpirun test (cpp/test/CMakeLists.txt:44-50)."""
    import jax

    import cylon_tpu as ct
    from cylon_tpu import Table
    from cylon_tpu.parallel import dist_join, dtable, shuffle

    n = int(os.environ.get("CYLON_BENCH_EXCHANGE_ROWS", 500_000))
    reps = int(os.environ.get("CYLON_BENCH_REPS", 3))
    rng = np.random.default_rng(13)
    saved = {k: os.environ.get(k)
             for k in ("CYLON_TPU_SHUFFLE", "CYLON_TPU_FORCE_DIST")}
    os.environ["CYLON_TPU_SHUFFLE"] = "ragged"
    os.environ["CYLON_TPU_FORCE_DIST"] = "1"
    try:
        env = ct.CylonEnv(ct.TPUConfig(n_devices=1))
        comments = np.array([f"comment text number {i % 97} row {i}"
                             for i in range(n)], object)
        t_in = Table.from_pydict({
            "k": rng.integers(0, n, n).astype(np.int64),
            "v": rng.normal(size=n),
            "s": comments}, string_storage="bytes")
        out = {}

        def sync():
            return dtable.host_counts(out["r"]).sum()

        t = _timeit(lambda: out.__setitem__(
            "r", shuffle(env, t_in, ["k"])), sync, reps)
        _emit("shuffle_ragged_w1_tpu_rows_per_sec", n / t, "rows/s")

        lt = Table.from_pydict({
            "k": rng.integers(0, n, n).astype(np.int64),
            "a": rng.normal(size=n)})
        rt = Table.from_pydict({
            "k": rng.integers(0, n, n).astype(np.int64),
            "b": rng.normal(size=n)})
        t = _timeit(lambda: out.__setitem__(
            "r", dist_join(env, lt, rt, on="k", how="inner")), sync, reps)
        _emit("dist_join_ragged_w1_tpu_rows_per_sec", n / t, "rows/s")
    finally:
        for k, v in saved.items():  # restore any user-set override
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def weak_scaling_main():
    """--weak-scaling: the headline distributed inner join at
    W=1/2/4/8 on the virtual CPU mesh (rows scale WITH W — n per
    worker held constant), plus the 2x4 hierarchical (slice x worker)
    mesh (VERDICT r4 next #5). Emits one line per world size with
    wall, rows/s, and parallel efficiency vs W=1 — the harness that
    produces the multi-chip scaling claim the moment hardware exists.
    Parity: ``cpp/src/experiments/run_dist_scaling.py:35-36`` (the
    reference's weak-scaling driver). CPU-mesh numbers track SCALING
    SHAPE (collective/kernel overhead growth), not chip throughput."""
    import jax

    jax.config.update("jax_platforms", "cpu")  # env var loses to axon
    import cylon_tpu as ct
    from cylon_tpu import Table
    from cylon_tpu.parallel import dist_join, dtable, scatter_table

    from cylon_tpu import telemetry

    n_per = int(os.environ.get("CYLON_BENCH_WEAK_ROWS", 250_000))
    reps = int(os.environ.get("CYLON_BENCH_REPS", 3))
    rng = np.random.default_rng(23)
    out = {}

    def sync():
        return dtable.host_counts(out["r"]).sum()

    def one(env, tag, w):
        n = n_per * w
        lt = scatter_table(env, Table.from_pydict({
            "k": rng.integers(0, n, n).astype(np.int64),
            "a": rng.normal(size=n)}))
        rt = scatter_table(env, Table.from_pydict({
            "k": rng.integers(0, n, n).astype(np.int64),
            "b": rng.normal(size=n)}))
        bytes0 = telemetry.total("exchange.bytes_true")
        calls0 = telemetry.total("exchange.calls")
        t = _timeit(lambda: out.__setitem__(
            "r", dist_join(env, lt, rt, on="k", how="inner")), sync, reps)
        _emit(f"weak_scaling_{tag}_wall_ms", t * 1e3, "ms")
        _emit(f"weak_scaling_{tag}_rows_per_sec", n / t, "rows/s")
        # roofline-honest exchange pricing (VERDICT r5): true payload
        # bytes per dispatch (from the exchange.bytes_true counter the
        # eager dist ops maintain) over the best wall. On the virtual
        # CPU mesh the fraction-of-peak is a SHAPE metric (this host
        # is not a v5e); on real chips the same fields are the
        # roofline position. W=1 short-circuits the exchange entirely
        # (local join path) so no exchange fields are emitted there.
        calls = telemetry.total("exchange.calls") - calls0
        xbytes = telemetry.total("exchange.bytes_true") - bytes0
        if calls:
            bps = (xbytes / calls) / t
            _emit(f"weak_scaling_{tag}_exchange_bytes_per_sec", bps,
                  "bytes/s")
            _emit_record({
                "metric": f"weak_scaling_{tag}_fraction_of_hbm_peak",
                "value": round(telemetry.fraction_of_peak(bps), 8),
                "unit": "of v5e HBM peak (819e9 B/s; CPU mesh: "
                        "shape metric only)"})
            hr = telemetry.metric("exchange.headroom_ratio",
                                  op="dist_join")
            if hr is not None:
                _emit(f"weak_scaling_{tag}_headroom_ratio",
                      float(hr.value), "x (alloc/true rows)")
        out.clear()
        return (n / t) / w          # per-worker throughput

    # On real hardware each worker is a chip and per-worker throughput
    # is the efficiency claim. On the virtual CPU mesh all W "devices"
    # timeshare this host's cores, so the per-worker ratio is bounded
    # by cores/W — the core-normalized number (x W/cores when W>cores)
    # is the scaling-SHAPE metric there (collective+kernel overhead
    # growth with W, what a real mesh would add on top of its chips).
    ncores = os.cpu_count() or 1
    per_worker = {}
    for w in (1, 2, 4, 8):
        if w > len(jax.devices()):
            break
        env = ct.CylonEnv(ct.TPUConfig(n_devices=w))
        per_worker[w] = one(env, f"w{w}", w)
    for w, pw in per_worker.items():
        _emit(f"weak_scaling_w{w}_efficiency_pct",
              100.0 * pw / per_worker[1], "%")
        _emit(f"weak_scaling_w{w}_core_norm_efficiency_pct",
              100.0 * pw * max(1.0, w / max(ncores, 1)) / per_worker[1],
              "%")
    if len(jax.devices()) >= 8:
        # the DCN-analog two-stage exchange on a 2x4 hierarchy
        env = ct.CylonEnv(ct.TPUConfig(devices_per_slice=4))
        pw = one(env, "hier2x4", 8)
        _emit("weak_scaling_hier2x4_efficiency_pct",
              100.0 * pw / per_worker[1], "%")
        _emit("weak_scaling_hier2x4_core_norm_efficiency_pct",
              100.0 * pw * max(1.0, 8 / max(ncores, 1)) / per_worker[1],
              "%")


def exchange_main():
    """Shuffle/dist_join at world 8 on the virtual CPU mesh (see module
    docstring). Numbers are CPU-mesh regression trackers, not TPU
    throughput — compare across commits, not against the chip."""
    import jax

    jax.config.update("jax_platforms", "cpu")  # env var alone loses to
    #                                            the axon plugin
    import cylon_tpu as ct
    from cylon_tpu import Table
    from cylon_tpu.parallel import (dist_join, dist_to_pandas, dtable,
                                    scatter_table, shuffle)

    n = int(os.environ.get("CYLON_BENCH_EXCHANGE_ROWS", 500_000))
    reps = int(os.environ.get("CYLON_BENCH_REPS", 3))
    rng = np.random.default_rng(11)
    env = ct.CylonEnv()
    w = env.world_size

    t_in = scatter_table(env, Table.from_pydict({
        "k": rng.integers(0, n, n).astype(np.int64),
        "v": rng.normal(size=n)}))
    out = {}

    def sync():
        return dtable.host_counts(out["r"]).sum()

    t = _timeit(lambda: out.__setitem__("r", shuffle(env, t_in, ["k"])),
                sync, reps)
    _emit(f"shuffle_w{w}_cpu_rows_per_sec", n / t, "rows/s")

    lt = scatter_table(env, Table.from_pydict({
        "k": rng.integers(0, n, n).astype(np.int64),
        "a": rng.normal(size=n)}))
    rt = scatter_table(env, Table.from_pydict({
        "k": rng.integers(0, n, n).astype(np.int64),
        "b": rng.normal(size=n)}))
    t = _timeit(lambda: out.__setitem__(
        "r", dist_join(env, lt, rt, on="k", how="inner")), sync, reps)
    _emit(f"dist_join_w{w}_cpu_rows_per_sec", n / t, "rows/s")

    # bytes string-key join: the device-bytes column exchange + word-wise
    # key compare path (no host dictionary anywhere)
    sn = n // 5
    skeys = np.array([f"key_{i:08d}" for i in
                      rng.integers(0, sn, sn)], object)
    slt = scatter_table(env, Table.from_pydict(
        {"k": skeys, "a": rng.normal(size=sn)}, string_storage="bytes"))
    srt = scatter_table(env, Table.from_pydict(
        {"k": skeys[rng.integers(0, sn, sn)], "b": rng.normal(size=sn)},
        string_storage="bytes"))
    t = _timeit(lambda: out.__setitem__(
        "r", dist_join(env, slt, srt, on="k", how="inner")), sync, reps)
    _emit(f"dist_join_strkey_w{w}_cpu_rows_per_sec", sn / t, "rows/s")

    # distributed TPC-H regression walls (VERDICT r3 weak #3): q3/q5 at
    # SF0.01 on the 8-device mesh — the flagship distributed workload
    # gets a tracked wall, not just a parity test. Parity:
    # cpp/src/examples/bench/table_join_dist_test.cpp:38-56.
    from cylon_tpu import tpch

    data = tpch.generate(sf=0.01, seed=0)
    dfs = tpch.ingest(data)
    for qname in ("q3", "q5"):
        qfn = getattr(tpch, qname)
        res = {}
        t = _timeit(
            lambda: res.__setitem__("r", qfn(dfs, env=env)),
            lambda: dtable.host_counts(res["r"].table).sum(), reps)
        _emit(f"tpch_{qname}_dist_w{w}_sf0.01_wall", t * 1e3, "ms")


def _trace_artifact_record():
    """--trace epilogue: flush the armed flight recorder into a Chrome
    Trace artifact next to the records and pin its path + event count
    in one JSON record (the suite analog of ``bench.py --trace``).
    This artifact is the PARENT process's timeline; the exchange leg
    and the weak-scaling respawn get ``--trace`` forwarded and write
    their own artifacts (distinct paths), while the TPC-H sentinel
    children run with the recorder stripped — recording without an
    exporter would be pure overhead."""
    from cylon_tpu import telemetry
    from cylon_tpu.telemetry import trace

    evts = trace.events()
    path = os.environ.get("CYLON_BENCH_TRACE_PATH",
                          os.path.join(_artifacts_dir(),
                                       "bench_suite.trace.json"))
    telemetry.write_chrome_trace(path, trace.rank_buffers())
    _emit_record({"metric": "trace_artifact", "value": len(evts),
                  "unit": "events",
                  "trace_path": os.path.abspath(path),
                  "trace_events": len(evts),
                  "trace_dropped": trace.dropped()})


if __name__ == "__main__":
    _tracing = "--trace" in sys.argv
    if _tracing and os.environ.get("CYLON_TPU_TRACE", "") in (
            "", "0", "off"):
        # force-arm: an inherited =0/off must not defeat the flag
        os.environ["CYLON_TPU_TRACE"] = "1"
    if "--exchange" in sys.argv:
        exchange_main()
    elif any(a.startswith("--chaos-child=") for a in sys.argv):
        _op = next(a for a in sys.argv
                   if a.startswith("--chaos-child=")).split("=", 1)[1]
        chaos_child_main(_op)
    elif "--chaos" in sys.argv:
        chaos_main()
    elif any(a.startswith("--scale-incore=") for a in sys.argv):
        leg = next(a for a in sys.argv
                   if a.startswith("--scale-incore=")).split("=", 1)[1]
        scale_incore_main(leg)
    elif "--race" in sys.argv:
        race_main()
    elif "--scale" in sys.argv:
        scale_main()
    elif "--tpch" in sys.argv:
        tpch_main()
    elif "--weak-scaling" in sys.argv:
        if "--xla_force_host_platform_device_count" not in \
                os.environ.get("XLA_FLAGS", ""):
            # the virtual mesh must exist BEFORE jax initialises; a
            # direct invocation respawns itself with the flag (same
            # pattern as main()'s --exchange leg)
            child_env = dict(os.environ)
            child_env["XLA_FLAGS"] = (
                child_env.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8")
            try:
                # forward --trace so the child (which does the actual
                # work and then runs the artifact epilogue itself)
                # records; the parent exits via sys.exit right here
                sys.exit(subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--weak-scaling"]
                    + (["--trace"] if _tracing else []),
                    env=child_env,
                    timeout=_subproc_timeout()).returncode)
            except subprocess.TimeoutExpired:
                _emit("weak_scaling_timeout", 1,
                      "child killed at CYLON_BENCH_SUBPROC_TIMEOUT")
                sys.exit(124)
        weak_scaling_main()
    else:
        main()
    if _tracing:
        _trace_artifact_record()
