"""Headline benchmark: distributed inner-join rows/sec/chip.

Reproduces the reference's flagship experiment (distributed inner join,
``cpp/src/examples/bench/table_join_dist_test.cpp`` driven by
``cpp/src/experiments/run_dist_scaling.py``; published numbers in
``docs/docs/arch.md:148-162``). Baseline comparator: Cylon's 64-rank
MPI result — 1B rows in 4.0 s over 64 ranks = 3.906 M rows/s/rank
(BASELINE.md); ``vs_baseline`` is our single-chip rows/s over that
per-rank rate.

Config: BASELINE.json config 2 — two int64-keyed tables with float64
values, hash inner join, measured steady-state on the real chip.
Steady state means a pipeline of ``CYLON_BENCH_PIPELINE`` (default 12)
back-to-back joins inside one XLA program — distinct key AND value
columns per stage so nothing CSEs — timed over ``CYLON_BENCH_REPS``
dispatches; this amortises per-dispatch RPC/host overhead exactly as a
streaming workload would (the reference's 4.0 s / 64-rank number
likewise spans many overlapped exchanges, not one cold call). Depth 12
is where the measurement saturates on the tunneled v5e (per-dispatch
RPC is ~110 ms against ~12 ms of device time per join; beyond 12 the
number stops moving, i.e. it is the DEVICE being measured, not the
tunnel).

Emits ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from cylon_tpu import Table
    from cylon_tpu.ops.join import join

    n = int(os.environ.get("CYLON_BENCH_ROWS", 1_000_000))
    reps = int(os.environ.get("CYLON_BENCH_REPS", 5))
    depth = int(os.environ.get("CYLON_BENCH_PIPELINE", 12))
    # E[output rows] == n for uniform keys; 2x headroom stays safe while
    # keeping the capacity-bounded buffers (and their gathers) tight
    out_cap = 2 * n

    rng = np.random.default_rng(7)
    left = Table.from_pydict({
        "k": rng.integers(0, n, n).astype(np.int64),
        "a": rng.normal(size=n),
    })
    right = Table.from_pydict({
        "k": rng.integers(0, n, n).astype(np.int64),
        "b": rng.normal(size=n),
    })
    # per-stage right tables with INDEPENDENT keys and values: every
    # stage is a full join — nothing (sorts, group ids, gathers) is
    # shareable between stages, so XLA cannot CSE stage work away
    kstack = jnp.asarray(rng.integers(0, n, (depth, n)).astype(np.int64))
    bstack = jnp.asarray(rng.normal(size=(depth, n)))

    @jax.jit
    def step(lt, rt, ks, bs):
        col = rt.column("b").__class__
        total = jnp.int32(0)
        for i in range(depth):
            r = rt.add_column("k", col(ks[i], None, rt.column("k").dtype))
            r = r.add_column("b", col(bs[i], None, rt.column("b").dtype))
            # ordered=False matches the reference's semantics (its sort
            # join emits key order, not left-frame order) and is what
            # the distributed shards run
            res = join(lt, r, on="k", how="inner", out_capacity=out_cap,
                       ordered=False)
            total = total + res.nrows
        return total

    # compile + correctness guard
    nrows_total = int(step(left, right, kstack, bstack))
    assert 0 < nrows_total <= depth * out_cap, f"bad join {nrows_total}"
    single = join(left, right, on="k", how="inner", out_capacity=out_cap)
    assert 0 < int(single.nrows) <= out_cap

    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = step(left, right, kstack, bstack)
        float(np.asarray(out))  # host sync
        times.append(time.perf_counter() - t0)
    best = min(times)

    rows_per_sec = depth * n / best
    baseline_per_rank = 1e9 / 4.0 / 64  # Cylon 64-rank MPI (BASELINE.md)
    print(json.dumps({
        "metric": "dist_inner_join_rows_per_sec_per_chip",
        "value": round(rows_per_sec, 1),
        "unit": "rows/s/chip",
        "vs_baseline": round(rows_per_sec / baseline_per_rank, 3),
    }))


if __name__ == "__main__":
    main()
