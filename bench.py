"""Headline benchmark: distributed inner-join rows/sec/chip.

Reproduces the reference's flagship experiment (distributed inner join,
``cpp/src/examples/bench/table_join_dist_test.cpp`` driven by
``cpp/src/experiments/run_dist_scaling.py``; published numbers in
``docs/docs/arch.md:148-162``). Baseline comparator: Cylon's 64-rank
MPI result — 1B rows in 4.0 s over 64 ranks = 3.906 M rows/s/rank
(BASELINE.md); ``vs_baseline`` is our single-chip rows/s over that
per-rank rate.

Config: BASELINE.json config 2 — two int64-keyed tables with float64
values, hash inner join, measured steady-state on the real chip.
Steady state means a pipeline of ``CYLON_BENCH_PIPELINE`` (default 12)
back-to-back joins inside one XLA program — distinct key AND value
columns per stage so nothing CSEs — timed over ``CYLON_BENCH_REPS``
dispatches; this amortises per-dispatch RPC/host overhead exactly as a
streaming workload would (the reference's 4.0 s / 64-rank number
likewise spans many overlapped exchanges, not one cold call). Depth 12
is where the measurement saturates on the tunneled v5e (per-dispatch
RPC is ~110 ms against ~12 ms of device time per join; beyond 12 the
number stops moving, i.e. it is the DEVICE being measured, not the
tunnel).

The headline is EXCHANGE-INCLUSIVE (VERDICT r4 missing #1): every
pipeline stage hashes fresh keys (``partition_ids``), moves BOTH tables
through the real exchange path (``shuffle_local`` — ragged all-to-all
on TPU, the same code every multi-chip shuffle runs), then joins — the
measured wall covers partition + exchange + join exactly like the
reference's bench wall covers its MPI all-to-all + local join
(``table_join_dist_test.cpp:38-56``). The no-communication local-join
pipeline (the previous headline) is reported alongside as
``local_path_rows_per_sec``.

Emits ONE json line: {"metric", "value", "unit", "vs_baseline", ...}.

``--trace`` additionally runs the headline join ONCE through the
instrumented eager ``dist_join`` path with the flight recorder armed
(``CYLON_TPU_TRACE`` — the pipelined headline hand-rolls its shard_map
and bypasses the recorder by construction), writes the Chrome Trace
Event artifact next to the bench record
(``CYLON_BENCH_TRACE_PATH``, default
``bench_artifacts/bench.trace.json`` — open in
Perfetto / ``chrome://tracing``) and pins its path + event count +
rank-track count + per-stage wall coverage into the JSON record
(:data:`REQUIRED_TRACE_FIELDS`, schema enforced by
``tests/test_bench_guard.py``).

``--join-ab`` races the sort join against the bucketed O(n) hash join
(``ops/hash_join.py``) at ``CYLON_BENCH_JOIN_AB_ROWS`` sizes x
``CYLON_BENCH_JOIN_AB_DISTS`` key distributions, with staged
build/probe walls under ``join.build``/``join.probe`` spans; one
:data:`REQUIRED_JOIN_AB_FIELDS` record per config (the A/B verdict
artifact ``docs/joins.md`` cites).

``--ooc-overlap`` races the pipelined OOC executor
(:mod:`cylon_tpu.pipeline`: bounded prefetch + async checkpointed
spill) against the ``CYLON_TPU_OOC_PREFETCH_DEPTH=0`` sequential
control, per op (``CYLON_BENCH_OOC_OPS``) x chunk-source model
(``disk`` | ``tunneled_model`` — see :func:`_bench_ooc_overlap`); one
:data:`REQUIRED_OOC_OVERLAP_FIELDS` record per config with per-stage
idle fractions and a Chrome-trace artifact showing ``ooc.prefetch``
overlapping ``ooc.compute`` (``docs/outofcore.md`` "Pipelined
execution" cites the verdict).
"""

import json
import os
import sys
import time

import numpy as np


def _bench_local_pipeline(n, depth, reps, out_cap, rng):
    """The no-comm pipelined local join (previous headline)."""
    import jax
    import jax.numpy as jnp

    from cylon_tpu import Table
    from cylon_tpu.ops.join import join

    left = Table.from_pydict({
        "k": rng.integers(0, n, n).astype(np.int64),
        "a": rng.normal(size=n),
    })
    right = Table.from_pydict({
        "k": rng.integers(0, n, n).astype(np.int64),
        "b": rng.normal(size=n),
    })
    # per-stage right tables with INDEPENDENT keys and values: every
    # stage is a full join — nothing (sorts, group ids, gathers) is
    # shareable between stages, so XLA cannot CSE stage work away
    kstack = jnp.asarray(rng.integers(0, n, (depth, n)).astype(np.int64))
    bstack = jnp.asarray(rng.normal(size=(depth, n)))

    @jax.jit
    def step(lt, rt, ks, bs):
        col = rt.column("b").__class__
        total = jnp.int32(0)
        for i in range(depth):
            r = rt.add_column("k", col(ks[i], None, rt.column("k").dtype))
            r = r.add_column("b", col(bs[i], None, rt.column("b").dtype))
            # ordered=False matches the reference's semantics (its sort
            # join emits key order, not left-frame order) and is what
            # the distributed shards run
            res = join(lt, r, on="k", how="inner", out_capacity=out_cap,
                       ordered=False)
            total = total + res.nrows
        return total

    # compile + correctness guard
    nrows_total = int(step(left, right, kstack, bstack))
    assert 0 < nrows_total <= depth * out_cap, f"bad join {nrows_total}"
    single = join(left, right, on="k", how="inner", out_capacity=out_cap)
    assert 0 < int(single.nrows) <= out_cap

    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = step(left, right, kstack, bstack)
        float(np.asarray(out))  # host sync
        times.append(time.perf_counter() - t0)
    return depth * n / min(times)


def _bench_exchange_pipeline(n, depth, reps, out_cap, rng):
    """The exchange-inclusive pipelined join: per stage, BOTH sides get
    fresh independent keys/values (nothing CSEs), are hash-partitioned
    (``partition_ids``) and moved through the REAL exchange
    (``shuffle_local`` -> ragged all-to-all on TPU / padded on CPU),
    then joined — all ``depth`` stages inside ONE shard_map-under-jit
    program on a 1-device mesh, like every multi-chip dist_join shard
    runs. W=1 keeps the measurement per-chip (the reference's baseline
    is per-rank) while executing the full collective path."""
    import jax
    import jax.numpy as jnp

    import cylon_tpu as ct
    from cylon_tpu import Table
    from cylon_tpu.column import Column
    from cylon_tpu.ops.hash import partition_ids
    from cylon_tpu.ops.join import join
    from cylon_tpu.parallel import scatter_table
    from cylon_tpu.parallel.shuffle import checked_recv, shuffle_local

    env = ct.CylonEnv(ct.TPUConfig(n_devices=1))
    w = env.world_size
    ax = env.world_axes
    shuf_cap = 2 * n      # uniform keys: 2x expected receive is safe
    join_cap = out_cap

    proto = Table.from_pydict({
        "k": np.zeros(n, np.int64), "v": np.zeros(n)})
    lt0 = scatter_table(env, proto)
    rt0 = scatter_table(env, proto)
    kdt = lt0.column("k").dtype
    vdt = lt0.column("v").dtype

    # per-stage independent keys AND values for BOTH sides: every stage
    # re-hashes, re-exchanges and re-joins fresh data — no stage work is
    # shareable, exactly like the reference's repeated full joins
    kl = jnp.asarray(rng.integers(0, n, (depth, n)).astype(np.int64))
    av = jnp.asarray(rng.normal(size=(depth, n)))
    kr = jnp.asarray(rng.integers(0, n, (depth, n)).astype(np.int64))
    bv = jnp.asarray(rng.normal(size=(depth, n)))

    from jax.sharding import PartitionSpec as P

    def body(lt, rt, kls, avs, krs, bvs):
        total = jnp.int32(0)
        for i in range(depth):
            l = lt.with_nrows(lt.nrows[0])
            l = l.add_column("k", Column(kls[i], None, kdt))
            l = l.add_column("v", Column(avs[i], None, vdt))
            r = rt.with_nrows(rt.nrows[0])
            r = r.add_column("k", Column(krs[i], None, kdt))
            r = r.add_column("v", Column(bvs[i], None, vdt))
            lpid = partition_ids([l.column("k").data], w, [None])
            rpid = partition_ids([r.column("k").data], w, [None])
            lsh, _ = checked_recv(
                shuffle_local(l, lpid, shuf_cap, axis_name=ax), shuf_cap)
            rsh, _ = checked_recv(
                shuffle_local(r, rpid, shuf_cap, axis_name=ax), shuf_cap)
            res = join(lsh, rsh, on="k", how="inner",
                       suffixes=("_l", "_r"), out_capacity=join_cap,
                       ordered=False)
            total = total + res.nrows
        return total.reshape((1,))

    fn = jax.jit(jax.shard_map(
        body, mesh=env.mesh,
        in_specs=(P(ax), P(ax), P(None, ax), P(None, ax), P(None, ax),
                  P(None, ax)),
        out_specs=P(ax)))

    total = int(np.asarray(fn(lt0, rt0, kl, av, kr, bv))[0])
    assert 0 < total <= depth * join_cap, f"bad exchange join {total}"

    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(lt0, rt0, kl, av, kr, bv)
        int(np.asarray(out)[0])  # host sync
        times.append(time.perf_counter() - t0)
    # true exchange payload per dispatch, priced exactly like the
    # exchange.bytes_true counter (valid rows x packed u32 word width,
    # both tables, every stage) — the numerator of the roofline fields
    from cylon_tpu import telemetry
    from cylon_tpu.parallel.shuffle import transport_words

    words = transport_words(lt0) + transport_words(rt0)
    bytes_per_dispatch = depth * n * words * 4
    telemetry.counter("exchange.bytes_true",
                      op="bench_exchange").inc(bytes_per_dispatch * reps)
    return depth * n / min(times), bytes_per_dispatch / min(times)


#: headline-record fields the roofline trajectory depends on — main()
#: asserts them before emitting and ``tests/test_bench_guard.py`` pins
#: the set, so a refactor cannot silently drop the bytes/s or
#: fraction-of-peak columns from the BENCH_* history.
REQUIRED_HEADLINE_FIELDS = frozenset({
    "metric", "value", "unit", "vs_baseline",
    "exchange_bytes_per_sec", "fraction_of_hbm_peak", "exchange_note",
})

#: where bench artifacts (Chrome traces and friends) land by default:
#: a dedicated directory, NOT the repo root — committed artifacts stay
#: out of the tree's top level and every record pins the actual path
#: (ISSUE 14 satellite; override per artifact via
#: ``CYLON_BENCH_TRACE_PATH``)
ARTIFACTS_DIR = os.environ.get("CYLON_BENCH_ARTIFACTS_DIR",
                               "bench_artifacts")

#: fields a ``--trace`` run must pin into the headline record — the
#: artifact is only auditable if the record says where it is and how
#: much it holds (``tests/test_bench_guard.py`` pins this set).
REQUIRED_TRACE_FIELDS = frozenset({
    "trace_path", "trace_events", "trace_rank_tracks",
    "trace_stage_coverage", "trace_dropped",
})

#: fields every ``--join-ab`` record must pin (ISSUE 12) — the A/B
#: verdict is only reproducible if each record names the config, both
#: walls, the winner, and whether the bucketed path's overflow
#: fallback fired (``tests/test_bench_guard.py`` pins this set).
REQUIRED_JOIN_AB_FIELDS = frozenset({
    "rows", "distribution", "sort_wall", "hash_wall", "winner",
    "overflow_fallbacks",
})

#: fields every ``--ooc-overlap`` record must pin (ISSUE 13) — the
#: overlap verdict is only auditable if each record names the op, the
#: source model, BOTH walls (overlap on vs the
#: ``CYLON_TPU_OOC_PREFETCH_DEPTH=0`` sequential control), the
#: prefetch hit/miss counters, the hidden-IO seconds, the per-stage
#: idle fractions from the trace, and the trace artifact path
#: (``tests/test_bench_guard.py`` pins this set).
REQUIRED_OOC_OVERLAP_FIELDS = frozenset({
    "op", "rows", "source", "sequential_wall", "overlap_wall",
    "overlap_speedup", "rows_per_sec_sequential",
    "rows_per_sec_overlap", "prefetch_hits", "prefetch_misses",
    "overlap_seconds", "prefetch_compute_overlap_s",
    "idle_fractions_sequential", "idle_fractions_overlap",
    "platform", "trace_path",
})

#: pipeline stages the --ooc-overlap idle-fraction audit reads from
#: the trace (idle fraction = 1 - stage busy seconds / wall)
_OOC_STAGES = ("ooc.prefetch", "ooc.compute", "spill.write_async",
               "spill.write")


def _ooc_stage_stats(evts, wall):
    """Per-stage busy seconds + idle fractions from one run's trace,
    plus the cross-thread seconds where an ``ooc.prefetch`` span
    overlapped an ``ooc.compute`` span — the timeline proof that the
    ingest actually ran DURING compute (0 in the sequential arm by
    construction: both stages share one thread there)."""
    spans, open_spans = [], {}
    for e in evts:
        if e["kind"] == "begin":
            open_spans[e["id"]] = e
        elif e["kind"] == "end":
            b = open_spans.pop(e.get("id"), None)
            if b is not None:
                spans.append((b["name"], b.get("tid"), b["ts"],
                              e["ts"]))
        elif e["kind"] == "complete":
            spans.append((e["name"], e.get("tid"), e["ts"],
                          e["ts"] + e["dur"]))
    busy: dict = {}
    for name, _, t0, t1 in spans:
        busy[name] = busy.get(name, 0.0) + max(t1 - t0, 0.0)
    idle = {s: round(max(1.0 - busy.get(s, 0.0) / wall, 0.0), 4)
            for s in _OOC_STAGES if s in busy}
    pre = [(t0, t1, tid) for n, tid, t0, t1 in spans
           if n == "ooc.prefetch"]
    cmp_ = [(t0, t1, tid) for n, tid, t0, t1 in spans
            if n == "ooc.compute"]
    ov = 0.0
    for p0, p1, ptid in pre:
        for c0, c1, ctid in cmp_:
            if ctid == ptid:
                continue
            lo, hi = max(p0, c0), min(p1, c1)
            if hi > lo:
                ov += hi - lo
    return busy, idle, ov


def _bench_ooc_overlap():
    """ISSUE 13 A/B: pipelined OOC execution (bounded prefetch + async
    checkpointed spill) vs the ``CYLON_TPU_OOC_PREFETCH_DEPTH=0``
    sequential control, per op x chunk-source model.

    Sources: ``disk`` — a real uncompressed-parquet file, page cache
    evicted (``posix_fadvise DONTNEED``) before every pass so reads
    hit the device; ``tunneled_model`` — the same file with each chunk
    pull additionally paying ``CYLON_BENCH_OOC_RPC_MS`` (default 110
    ms: the MEASURED per-dispatch RPC of the tunneled v5e this repo's
    headline runs on — see the module docstring; a tunneled/remote
    chunk source pays exactly that class of round trip per pull, and
    this container has no tunnel to measure live). Each record labels
    its source; CPU-host walls throughout — on this 1-core container
    host "device" compute and host ingest share the core, so the
    ``disk`` legs bound what local-NVMe fsync/read waits alone can
    hide, while ``tunneled_model`` shows the gap the overlap exists to
    close in the recorded deployment."""
    import shutil
    import tempfile

    import pyarrow as pa
    import pyarrow.parquet as pq

    if os.environ.get("CYLON_TPU_TRACE", "") in ("", "0", "off"):
        os.environ["CYLON_TPU_TRACE"] = "1"

    import jax

    from cylon_tpu import telemetry
    from cylon_tpu.outofcore import ooc_groupby, ooc_sort
    from cylon_tpu.telemetry import trace

    ops = os.environ.get("CYLON_BENCH_OOC_OPS", "sort,groupby").split(",")
    sources = os.environ.get("CYLON_BENCH_OOC_SOURCES",
                             "disk,tunneled_model").split(",")
    n = int(os.environ.get("CYLON_BENCH_OOC_ROWS", 1_000_000))
    chunk = int(os.environ.get("CYLON_BENCH_OOC_CHUNK", 1 << 16))
    ncols = int(os.environ.get("CYLON_BENCH_OOC_VALUE_COLS", 6))
    reps = int(os.environ.get("CYLON_BENCH_OOC_REPS", 2))
    depth = os.environ.get("CYLON_BENCH_OOC_DEPTH", "2")
    rpc_ms = float(os.environ.get("CYLON_BENCH_OOC_RPC_MS", "110"))
    nparts = 8

    tmp = tempfile.mkdtemp(prefix="cylon_ooc_overlap_")
    rng = np.random.default_rng(7)
    cols = {"k": rng.integers(0, n, n).astype(np.int64),
            "g": rng.integers(0, 64, n).astype(np.int64)}
    for i in range(ncols):
        cols[f"v{i}"] = rng.normal(size=n)
    path = os.path.join(tmp, "src.parquet")
    pq.write_table(pa.table(cols), path, compression="none")
    del cols

    def _evict():
        # cold-ish reads both arms: evict the source from page cache so
        # every pass reads the device, like an SF100 source would
        fd = os.open(path, os.O_RDONLY)
        try:
            os.posix_fadvise(fd, 0, 0, os.POSIX_FADV_DONTNEED)
        except (AttributeError, OSError):
            pass
        finally:
            os.close(fd)

    def _chunks(source):
        _evict()
        pf = pq.ParquetFile(path)
        for b in pf.iter_batches(batch_size=chunk):
            if source == "tunneled_model":
                time.sleep(rpc_ms / 1000.0)
            yield {c: b.column(c).to_numpy(zero_copy_only=False)
                   for c in b.schema.names}

    def _run(op, source, depth_env, seq):
        os.environ["CYLON_TPU_OOC_PREFETCH_DEPTH"] = depth_env
        rdir = os.path.join(tmp, f"ck_{op}_{source}_{depth_env}_{seq}")
        odir = os.path.join(tmp, f"out_{op}_{source}_{depth_env}_{seq}")
        os.makedirs(odir)
        nsink = [0]

        def sink(pdf):
            # durable output: the sorted table is PERSISTED (what an
            # at-scale OOC sort is for) — rides the async writer
            p = os.path.join(odir, f"part{nsink[0]:05d}.npz")
            nsink[0] += 1
            with open(p, "wb") as f:
                np.savez(f, **{c: pdf[c].to_numpy()
                               for c in pdf.columns})
                f.flush()
                os.fsync(f.fileno())

        trace.clear()
        c0 = {k: telemetry.total(k) for k in
              ("ooc.prefetch_hits", "ooc.prefetch_misses",
               "ooc.overlap_seconds")}
        t0 = time.perf_counter()
        if op == "sort":
            ooc_sort(lambda: _chunks(source), ["k"],
                     n_partitions=nparts, chunk_rows=chunk,
                     resume_dir=rdir, sink=sink)
        elif op == "groupby":
            # Q1-shaped pre-combine: sum+min+max per value column plus
            # a count — the chunked streaming-aggregation workload
            # (tpch q1_ooc) whose per-chunk device compute the
            # prefetcher hides chunk pulls behind
            aggs = [("v0", "count", "cnt")]
            for i in range(ncols):
                aggs += [(f"v{i}", "sum", f"s{i}"),
                         (f"v{i}", "min", f"mn{i}"),
                         (f"v{i}", "max", f"mx{i}")]
            ooc_groupby(lambda: _chunks(source), ["g"], aggs,
                        chunk_rows=chunk, resume_dir=rdir)
        else:
            raise ValueError(f"unknown --ooc-overlap op {op!r}")
        wall = time.perf_counter() - t0
        evts = trace.events()
        deltas = {k: telemetry.total(k) - v for k, v in c0.items()}
        shutil.rmtree(rdir, ignore_errors=True)
        shutil.rmtree(odir, ignore_errors=True)
        return wall, evts, deltas

    records = []
    try:
        for op in ops:
            for source in sources:
                arms = {}
                for label, d in (("sequential", "0"),
                                 ("overlap", depth)):
                    best = None
                    for rep in range(max(reps, 1)):
                        wall, evts, deltas = _run(op, source, d,
                                                  f"{label}{rep}")
                        if best is None or wall < best[0]:
                            best = (wall, evts, deltas)
                    arms[label] = best
                seq_wall, seq_evts, _ = arms["sequential"]
                ov_wall, ov_evts, ov_deltas = arms["overlap"]
                _, seq_idle, _ = _ooc_stage_stats(seq_evts, seq_wall)
                _, ov_idle, xov = _ooc_stage_stats(ov_evts, ov_wall)
                tpath = os.path.abspath(
                    os.path.join(ARTIFACTS_DIR,
                    f"ooc_overlap.{op}.{source}.trace.json"))
                telemetry.write_chrome_trace(
                    tpath, telemetry.to_chrome_trace(
                        [{"rank": 0, "clock_offset": 0.0,
                          "events": ov_evts}]))
                record = {
                    "metric": "ooc_overlap_ab",
                    "op": op,
                    "rows": n,
                    "source": source,
                    "rpc_ms": (rpc_ms if source == "tunneled_model"
                               else 0.0),
                    "value_cols": ncols,
                    "chunk_rows": chunk,
                    "n_partitions": nparts,
                    "prefetch_depth": int(depth),
                    "sequential_wall": round(seq_wall, 4),
                    "overlap_wall": round(ov_wall, 4),
                    "overlap_speedup": round(seq_wall / ov_wall, 4),
                    "rows_per_sec_sequential": round(n / seq_wall, 1),
                    "rows_per_sec_overlap": round(n / ov_wall, 1),
                    "prefetch_hits": int(
                        ov_deltas["ooc.prefetch_hits"]),
                    "prefetch_misses": int(
                        ov_deltas["ooc.prefetch_misses"]),
                    "overlap_seconds": round(
                        float(ov_deltas["ooc.overlap_seconds"]), 4),
                    "prefetch_compute_overlap_s": round(xov, 4),
                    "idle_fractions_sequential": seq_idle,
                    "idle_fractions_overlap": ov_idle,
                    "reps": reps,
                    "platform": jax.default_backend(),
                    "host_note": ("1-core CPU host: device compute "
                                  "and host ingest share the core, so "
                                  "only true IO waits overlap; "
                                  "tunneled_model replays the "
                                  "recorded ~110 ms/RPC tunnel "
                                  "latency per chunk pull"),
                    "trace_path": tpath,
                }
                missing = REQUIRED_OOC_OVERLAP_FIELDS - record.keys()
                assert not missing, \
                    f"ooc-overlap record dropped {missing}"
                _emit_record(record)
                records.append(record)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return records


def _join_ab_keys(n, dist, rng):
    """Left-side key distribution per config; the right side is always
    ~unique (uniform over [0, n)) so the OUTPUT stays ~n rows while the
    left side's duplication drives the bucket-chain pressure (bucket
    load depends on key multiplicity, not value skew — the murmur hash
    randomises values)."""
    if dist == "uniform":
        lk = rng.integers(0, n, n)          # ~Poisson(1) duplication
    elif dist == "zipf":
        # heavy-head key frequencies: a few keys carry huge chains
        # (straddling the bucket width), the tail is near-unique
        lk = np.minimum(rng.zipf(1.5, n), n) - 1
    elif dist == "dup64":
        # every key ~64x duplicated: every chain exceeds the default
        # width-16 budget, so the bucketed hash path MUST take its
        # overflow fallback — this config measures that path's cost
        lk = rng.integers(0, max(n // 64, 1), n)
    else:
        raise ValueError(f"unknown distribution {dist!r}")
    rk = rng.integers(0, n, n)
    return lk.astype(np.int64), rk.astype(np.int64)


def _bench_join_ab(rows_list, dists, reps):
    """A/B race: the sort join vs the shipped ``algorithm="hash"``
    bucketed build/probe, per size x key distribution, plus staged
    build/probe walls (``join.build`` / ``join.probe`` spans — the
    same series ``RequestProfiler`` attributes serve-request stages
    from). Emits one :data:`REQUIRED_JOIN_AB_FIELDS` record per config
    and returns the list."""
    import time as _time

    import jax

    from cylon_tpu import Table, telemetry
    from cylon_tpu.ops import hash_join
    from cylon_tpu.ops.join import join
    from cylon_tpu.utils import tracing

    # the race is sort vs the BUCKETED kernel: pin the impl so the
    # record is reproducible from its own command line regardless of
    # the shipped DEFAULT_HASH_IMPL verdict (override to taste)
    os.environ.setdefault("CYLON_TPU_JOIN_HASH_IMPL", "bucketed")

    rng = np.random.default_rng(11)
    records = []
    for n in rows_list:
        n_reps = max(1, reps if n < 50_000_000 else 1)
        for dist in dists:
            lk, rk = _join_ab_keys(n, dist, rng)
            lt = Table.from_pydict({"k": lk, "a": rng.normal(size=n)})
            rt = Table.from_pydict({"k": rk, "b": rng.normal(size=n)})
            out_cap = 4 * n
            walls, out_rows = {}, {}
            ovf0 = telemetry.counter("join.overflow_fallbacks").value
            for alg in ("sort", "hash"):
                times = []
                for rep in range(n_reps + 1):  # rep 0 = compile
                    t0 = _time.perf_counter()
                    res = join(lt, rt, on="k", how="inner",
                               algorithm=alg, out_capacity=out_cap,
                               ordered=False)
                    nr = int(res.nrows)  # full program sync
                    if rep:
                        times.append(_time.perf_counter() - t0)
                assert 0 < nr <= out_cap, f"bad A/B join {nr}"
                walls[alg] = min(times)
                out_rows[alg] = nr
            overflowed = (telemetry.counter(
                "join.overflow_fallbacks").value - ovf0)
            assert out_rows["sort"] == out_rows["hash"], \
                f"A/B row-set mismatch {out_rows}"
            # staged walls: build and probe as separate dispatches
            # under the join.build/join.probe spans (stage attribution)
            bj = jax.jit(lambda kd, nr_: hash_join.build_phase(
                [kd], [None], nr_))
            pj = jax.jit(lambda kd, nr_, tbl, bw: hash_join.probe_phase(
                [kd], [None], nr_, tbl, bw))
            kd_b, kd_p = lt.column("k").data, rt.column("k").data
            table = bwords = None
            build_s = probe_s = None
            for rep in range(2):  # rep 0 = compile
                t0 = _time.perf_counter()
                with tracing.span("join.build"):
                    table, ovf, _, bwords = jax.block_until_ready(
                        bj(kd_b, lt.nrows))
                if rep:
                    build_s = _time.perf_counter() - t0
                t0 = _time.perf_counter()
                with tracing.span("join.probe"):
                    mask, _ = jax.block_until_ready(
                        pj(kd_p, rt.nrows, table, bwords))
                if rep:
                    probe_s = _time.perf_counter() - t0
            record = {
                "metric": "join_ab",
                "rows": n,
                "distribution": dist,
                "sort_wall": round(walls["sort"], 4),
                "hash_wall": round(walls["hash"], 4),
                "winner": ("hash" if walls["hash"] < walls["sort"]
                           else "sort"),
                "overflow_fallbacks": int(overflowed),
                "out_rows": out_rows["sort"],
                "build_s": round(build_s, 4),
                "probe_s": round(probe_s, 4),
                "build_overflow_rows": int(ovf),
                "reps": n_reps,
                "hash_impl": hash_join.hash_impl(),
                "bucket_width": hash_join.bucket_width(),
                "platform": jax.default_backend(),
            }
            missing = REQUIRED_JOIN_AB_FIELDS - record.keys()
            assert not missing, f"join-ab record dropped {missing}"
            _emit_record(record)
            records.append(record)
            del lt, rt
    return records


def _traced_headline_join(n: int, rng) -> dict:
    """One eager ``dist_join`` over every visible device with the
    flight recorder armed; writes the Chrome-trace artifact and returns
    the :data:`REQUIRED_TRACE_FIELDS` block for the headline record.

    Runs the INSTRUMENTED ``parallel.dist_ops`` path (stage spans,
    exchange instants with true/padded bytes, per-shard row counter
    tracks), unlike the pipelined headline which fuses its own
    shard_map. ``CYLON_TPU_FORCE_DIST`` keeps the exchange path live on
    a W=1 mesh (the real-chip default), so the artifact always carries
    exchange slices."""
    # force-arm: an inherited CYLON_TPU_TRACE=0/off must not make the
    # explicit --trace flag silently record nothing
    if os.environ.get("CYLON_TPU_TRACE", "") in ("", "0", "off"):
        os.environ["CYLON_TPU_TRACE"] = "1"

    import cylon_tpu as ct
    from cylon_tpu import Table, telemetry
    from cylon_tpu.parallel import dist_join, scatter_table
    from cylon_tpu.telemetry import trace

    env = ct.CylonEnv(ct.TPUConfig())
    lt = scatter_table(env, Table.from_pydict({
        "k": rng.integers(0, n, n).astype(np.int64),
        "a": rng.normal(size=n)}))
    rt = scatter_table(env, Table.from_pydict({
        "k": rng.integers(0, n, n).astype(np.int64),
        "b": rng.normal(size=n)}))
    trace.clear()
    # FORCE_DIST only for THIS join (restored after): the exchange path
    # must run even on a W=1 real chip, but later suite legs must keep
    # their configured world==1 short-circuit semantics
    prev_force = os.environ.get("CYLON_TPU_FORCE_DIST")
    os.environ["CYLON_TPU_FORCE_DIST"] = "1"
    try:
        dist_join(env, lt, rt, on="k", how="inner")
    finally:
        if prev_force is None:
            os.environ.pop("CYLON_TPU_FORCE_DIST", None)
        else:
            os.environ["CYLON_TPU_FORCE_DIST"] = prev_force
    evts = trace.events()
    coverage = trace.stage_coverage(evts, "dist_join")
    path = os.environ.get("CYLON_BENCH_TRACE_PATH",
                          os.path.join(ARTIFACTS_DIR,
                                       "bench.trace.json"))
    doc = telemetry.to_chrome_trace(trace.rank_buffers(env),
                                    world=env.world_size)
    telemetry.write_chrome_trace(path, doc)
    pids = {e.get("pid") for e in doc["traceEvents"]}
    return {
        "trace_path": os.path.abspath(path),
        "trace_events": len(evts),
        "trace_rank_tracks": len(pids),
        "trace_stage_coverage": (round(coverage, 4)
                                 if coverage is not None else None),
        # silent-loss audit: events the ring bound evicted before the
        # export — a non-zero value means the artifact is a WINDOW,
        # not the whole run (raise CYLON_TPU_TRACE_EVENTS)
        "trace_dropped": trace.dropped(),
    }


def _emit_record(line: dict):
    """Single stdout sink for the headline JSON record: attaches the
    telemetry ``metrics`` block (byte / overflow / retry context from
    ``cylon_tpu.telemetry.bench_metrics``) so the BENCH_* trajectory
    carries more than wall time — schema pinned by
    ``tests/test_bench_guard.py``. Telemetry must never fail a bench."""
    line = dict(line)
    try:
        from cylon_tpu import telemetry

        line["metrics"] = telemetry.bench_metrics()
    except Exception as e:  # pragma: no cover - import-time breakage
        line["metrics"] = {"telemetry_error": f"{type(e).__name__}: {e}"}
    print(json.dumps(line))


def main():
    if "--ooc-overlap" in sys.argv[1:]:
        _bench_ooc_overlap()
        return
    if "--join-ab" in sys.argv[1:]:
        rows_list = [int(x) for x in os.environ.get(
            "CYLON_BENCH_JOIN_AB_ROWS",
            "1000000,10000000,100000000").split(",")]
        dists = os.environ.get("CYLON_BENCH_JOIN_AB_DISTS",
                               "uniform,zipf,dup64").split(",")
        reps = int(os.environ.get("CYLON_BENCH_JOIN_AB_REPS", 3))
        _bench_join_ab(rows_list, dists, reps)
        return
    do_trace = "--trace" in sys.argv[1:] or os.environ.get(
        "CYLON_BENCH_TRACE", "") not in ("", "0", "off")
    n = int(os.environ.get("CYLON_BENCH_ROWS", 1_000_000))
    reps = int(os.environ.get("CYLON_BENCH_REPS", 5))
    depth = int(os.environ.get("CYLON_BENCH_PIPELINE", 12))
    # E[output rows] == n for uniform keys; 2x headroom stays safe while
    # keeping the capacity-bounded buffers (and their gathers) tight
    out_cap = 2 * n

    rng = np.random.default_rng(7)
    xchg_rows_per_sec, xchg_bytes_per_sec = _bench_exchange_pipeline(
        n, depth, reps, out_cap, rng)
    local_rows_per_sec = _bench_local_pipeline(n, depth, reps, out_cap,
                                               rng)

    from cylon_tpu import telemetry

    baseline_per_rank = 1e9 / 4.0 / 64  # Cylon 64-rank MPI (BASELINE.md)
    record = {
        "metric": "dist_inner_join_exchange_rows_per_sec_per_chip",
        "value": round(xchg_rows_per_sec, 1),
        "unit": "rows/s/chip",
        "vs_baseline": round(xchg_rows_per_sec / baseline_per_rank, 3),
        "local_path_rows_per_sec": round(local_rows_per_sec, 1),
        "local_path_vs_baseline": round(
            local_rows_per_sec / baseline_per_rank, 3),
        # roofline position (VERDICT r5): true exchange payload per
        # wall second against the v5e HBM peak. The headline runs on a
        # W=1 mesh where the all-to-all is a SELF-DMA through HBM —
        # there is no ICI traffic to price, hence the HBM denominator
        # and the explicit label
        "exchange_bytes_per_sec": round(xchg_bytes_per_sec, 1),
        "fraction_of_hbm_peak": round(telemetry.fraction_of_peak(
            xchg_bytes_per_sec), 6),
        "exchange_note": ("W=1 mesh: the all-to-all is a self-DMA, so "
                          "bytes/s is against the HBM roofline "
                          "(819 GB/s/chip), not ICI"),
    }
    if do_trace:
        record.update(_traced_headline_join(n, rng))
        missing_t = REQUIRED_TRACE_FIELDS - record.keys()
        assert not missing_t, f"trace record dropped fields {missing_t}"
    missing = REQUIRED_HEADLINE_FIELDS - record.keys()
    assert not missing, f"headline record dropped fields {missing}"
    _emit_record(record)


if __name__ == "__main__":
    main()
