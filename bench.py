"""Headline benchmark: distributed inner-join rows/sec/chip.

Reproduces the reference's flagship experiment (distributed inner join,
``cpp/src/examples/bench/table_join_dist_test.cpp`` driven by
``cpp/src/experiments/run_dist_scaling.py``; published numbers in
``docs/docs/arch.md:148-162``). Baseline comparator: Cylon's 64-rank
MPI result — 1B rows in 4.0 s over 64 ranks = 3.906 M rows/s/rank
(BASELINE.md); ``vs_baseline`` is our single-chip rows/s over that
per-rank rate.

Config: BASELINE.json config 2 — two int64-keyed tables, hash inner
join, measured steady-state (post-compile) on the real chip.

Emits ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import time

import numpy as np


def main():
    import jax

    from cylon_tpu import Table
    from cylon_tpu.ops.join import join

    n = int(os.environ.get("CYLON_BENCH_ROWS", 1_000_000))
    reps = int(os.environ.get("CYLON_BENCH_REPS", 5))
    out_cap = 3 * n

    rng = np.random.default_rng(7)
    left = Table.from_pydict({
        "k": rng.integers(0, n, n).astype(np.int64),
        "a": rng.normal(size=n),
    })
    right = Table.from_pydict({
        "k": rng.integers(0, n, n).astype(np.int64),
        "b": rng.normal(size=n),
    })

    @jax.jit
    def step(lt, rt):
        return join(lt, rt, on="k", how="inner", out_capacity=out_cap)

    # compile + correctness guard
    res = step(left, right)
    nrows = int(res.nrows)
    assert 0 < nrows <= out_cap, f"bad join result {nrows}"

    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        res = step(left, right)
        jax.block_until_ready(res.nrows)
        times.append(time.perf_counter() - t0)
    best = min(times)

    rows_per_sec = n / best
    baseline_per_rank = 1e9 / 4.0 / 64  # Cylon 64-rank MPI (BASELINE.md)
    print(json.dumps({
        "metric": "dist_inner_join_rows_per_sec_per_chip",
        "value": round(rows_per_sec, 1),
        "unit": "rows/s/chip",
        "vs_baseline": round(rows_per_sec / baseline_per_rank, 3),
    }))


if __name__ == "__main__":
    main()
