"""Resilience layer: fault injection, retry/backoff, loss accounting.

The reference threads ``cylon::Status{code, msg}`` through every call
(``cpp/src/cylon/status.hpp``) but has no recovery story: a failed rank
fails the mpirun job. A TPU deployment is different — workers are
preempted, tunneled IO flakes, and an out-of-core pass can die hours in
— so the rebuild grows the three mechanisms a production stack needs
before any scale claim is honest:

1. **Deterministic fault injection** (:class:`FaultPlan`): named
   injection points threaded through the spill store, chunk sources,
   IO readers, the mesh exchange and the multihost bootstrap. A plan
   fires configured :class:`~cylon_tpu.errors.CylonError`\\ s on the Nth
   hit of a point (or probabilistically from a seeded RNG), and
   ``reset()`` replays the exact same failure sequence — tests assert
   recovery against byte-identical fault schedules.

2. **Retry/backoff** (:func:`retrying`): exponential backoff with
   deterministic jitter (:class:`cylon_tpu.config.RetryPolicy`),
   driven by :func:`is_retryable` over ``errors.Code`` —
   ``Code.Unavailable`` / :class:`~cylon_tpu.errors.TransientError`
   retry, everything else raises immediately.

3. **Loss accounting** (:class:`RowAccount`): multi-pass pipelines
   (``outofcore.ooc_sort``, ``host_partition_chunks``, the eager
   shuffle drivers) count rows-in vs rows-out and raise
   :class:`~cylon_tpu.errors.DataLossError` on mismatch — silent
   truncation becomes a loud failure.

:class:`SpillStore` and :class:`CheckpointedRun` round the layer out:
a directory-backed unit spill with a fingerprinted, atomically-updated
(tmp + fsync + rename) completion manifest, so a pass killed at ANY
instant — including a hard ``os._exit`` preemption, injectable via
:meth:`FaultRule.kill` — resumes at the first incomplete unit and
produces output byte-identical to a fault-free run (every
``outofcore`` pass takes ``resume_dir=``; the serve engine builds its
write-ahead journal and catalog snapshot on the same primitives —
:mod:`cylon_tpu.serve.durability`).
"""

import contextlib
import contextvars
import dataclasses
import hashlib
import itertools
import json
import os
import threading
import time

import numpy as np

from cylon_tpu import telemetry
from cylon_tpu.config import RetryPolicy
from cylon_tpu.telemetry import trace as _trace
from cylon_tpu.errors import (Code, CylonError, DataLossError,
                              DeadlineExceeded, InvalidArgument,
                              TransientError)

__all__ = [
    "INJECTION_POINTS", "KILL_EXIT_CODE", "FaultRule", "FaultPlan",
    "install", "active", "scoped", "active_plan", "inject",
    "is_retryable", "default_policy", "backoff_delays", "retrying",
    "RowAccount", "accounting_enabled", "atomic_write_json",
    "SpillStore", "CheckpointedRun",
]

#: exit status of a hard-kill FaultRule firing (``FaultRule.kill``) —
#: distinct from every status the interpreter or pytest uses, so a
#: chaos driver can assert "the child died AT the seeded fault point"
#: rather than "the child died".
KILL_EXIT_CODE = 43

#: Named places the engine agrees to fail on demand. Each maps to a real
#: failure domain: ``spill_write``/``spill_read`` — the out-of-core
#: spill store; ``chunk_source`` — every chunk an out-of-core pass pulls
#: (``outofcore._as_chunks``); ``io_read`` — the CSV/Parquet readers;
#: ``exchange`` — the mesh shuffle dispatch; ``worker`` — worker
#: preemption (exercised by the multihost bootstrap); ``plan`` — the
#: compiled-query dispatch (``plan.CompiledQuery.__call__`` and the
#: fallback executor's in-core attempt), where a seeded
#: ``MemoryError`` is the deterministic twin of a device
#: RESOURCE_EXHAUSTED — the injection the OOM→spill fallback tests
#: drive; ``global_merge`` — the two-phase fallback executor's global
#: merge step (``fallback._two_phase``), the blocking scalar
#: computation between the partial pass and the apply pass, so chaos
#: harnesses can kill a run exactly at the phase boundary.
INJECTION_POINTS = ("spill_write", "spill_read", "chunk_source",
                    "io_read", "exchange", "worker", "plan",
                    "global_merge")


# ------------------------------------------------------------ fault plans
@dataclasses.dataclass
class FaultRule:
    """One configured failure. Counting rules (the default) fire on hits
    ``nth .. nth + times - 1`` of ``point`` (``times <= 0`` = every hit
    from ``nth`` on — a permanently-dead resource). ``prob > 0`` fires
    probabilistically instead, drawing from the plan's seeded RNG so a
    ``reset()`` replays the identical schedule. ``error`` is the
    exception instance (or class) to raise; default is a
    :class:`~cylon_tpu.errors.TransientError` describing the hit —
    i.e. a simulated preemption the retry engine may absorb.

    ``delay > 0`` is **delay mode**: a firing hit SLEEPS ``delay``
    seconds at the fault point instead of raising (pass ``error`` too
    for a slow *failing* call) — the deterministic way to inject a
    hang, since a hang never raises and only the watchdog layer
    (:mod:`cylon_tpu.watchdog`) can see it. Which hits fire follows
    the same counting/seeded-prob schedule as raising rules, so delay
    schedules replay exactly too. :meth:`hang` is the documented
    alias for an effectively-unbounded delay.

    ``exit_code`` (non-None) is **kill mode**: a firing hit
    ``os._exit``\\ s the whole process at the fault point — no
    exception, no ``finally`` blocks, no atexit flushes. This is the
    injectable twin of a TPU preemption/OOM-kill, the failure class
    retries cannot absorb and only a checkpoint/resume layer
    (:class:`CheckpointedRun`, the serve journal) survives.
    :meth:`kill` is the documented constructor (fixed
    :data:`KILL_EXIT_CODE` so chaos drivers can assert the death was
    the seeded one)."""

    point: str
    nth: int = 1
    times: int = 1
    error: "Exception | type | None" = None
    prob: float = 0.0
    delay: float = 0.0
    exit_code: "int | None" = None

    @classmethod
    def hang(cls, point: str, seconds: float = 3600.0,
             **kw) -> "FaultRule":
        """A rule that HANGS at ``point`` (sleeps ``seconds``, default
        an hour — far past any sane deadline) instead of raising: the
        injectable twin of a wedged peer or dead mount, detectable
        only by ``watchdog.deadline`` bounds."""
        return cls(point, delay=float(seconds), **kw)

    @classmethod
    def kill(cls, point: str, nth: int = 1, **kw) -> "FaultRule":
        """A rule that HARD-KILLS the process (``os._exit``, status
        :data:`KILL_EXIT_CODE`) on hit ``nth`` of ``point`` — the
        chaos-harness preemption. Nothing downstream of the fault
        point runs: no cleanup, no manifest flush beyond what is
        already durable, which is exactly the window checkpoint/resume
        must survive."""
        return cls(point, nth=nth, exit_code=KILL_EXIT_CODE, **kw)


class FaultPlan:
    """A deterministic, replayable failure schedule.

    Register process-wide with :func:`install` / :func:`active`, or on a
    :class:`~cylon_tpu.context.CylonEnv` via ``env.set_fault_plan`` for
    the mesh-op points. ``fired`` records every (point, hit#, detail)
    that raised; ``reset()`` rewinds counters AND the RNG, so driving
    the same workload twice produces the same ``fired`` log — the
    replay-determinism contract the tests pin down.
    """

    def __init__(self, rules=(), seed: int = 0):
        self.rules = [r if isinstance(r, FaultRule) else FaultRule(**r)
                      for r in rules]
        for r in self.rules:
            if r.point not in INJECTION_POINTS:
                raise InvalidArgument(
                    f"unknown injection point {r.point!r}; valid: "
                    f"{INJECTION_POINTS}")
            if r.prob == 0.0 and r.nth < 1:
                raise InvalidArgument(f"nth must be >= 1, got {r.nth}")
            if not 0.0 <= r.prob <= 1.0:
                raise InvalidArgument(f"prob {r.prob} not in [0, 1]")
            if r.delay < 0:
                raise InvalidArgument(
                    f"delay must be >= 0, got {r.delay}")
            if r.exit_code is not None and not 0 <= r.exit_code <= 255:
                raise InvalidArgument(
                    f"exit_code must be in [0, 255], got {r.exit_code}")
        self.seed = seed
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> "FaultPlan":
        """Rewind hit counters and the RNG: the next run replays the
        exact same failure schedule."""
        with self._lock:
            self._hits = {p: 0 for p in INJECTION_POINTS}
            self._rng = np.random.default_rng(self.seed)
            self._fired: list[tuple] = []
        return self

    @property
    def fired(self) -> list:
        """Log of every firing: (point, hit number, detail)."""
        return list(self._fired)

    def hits(self, point: str) -> int:
        return self._hits[point]

    def check(self, point: str, detail: str = "") -> None:
        """Record one hit of ``point``; raise — or, for delay-mode
        rules, sleep — if any rule fires (first matching rule wins)."""
        with self._lock:
            self._hits[point] += 1
            k = self._hits[point]
            hit = None
            for r in self.rules:
                if r.point != point:
                    continue
                if r.prob > 0.0:
                    # draw EVERY hit (not only when firing) so the
                    # stream position — and therefore the schedule —
                    # depends only on the hit sequence
                    fire = bool(self._rng.random() < r.prob)
                else:
                    hi = None if r.times <= 0 else r.nth + r.times - 1
                    fire = k >= r.nth and (hi is None or k <= hi)
                if fire and hit is None:
                    self._fired.append((point, k, detail))
                    hit = r
        if hit is None:
            return
        # tenant label: under the serve layer's ambient tenant scope
        # the firing is attributed to the tenant whose query stream hit
        # it — the "unpolluted metrics" half of fault isolation
        telemetry.counter("resilience.faults_injected", point=point,
                          **telemetry.tenant_labels()).inc()
        _trace.instant("resilience.fault", cat="resilience",
                       point=point, hit=k, detail=detail,
                       delay=hit.delay)
        if hit.delay > 0:
            # injected hang: sleep OUTSIDE the plan lock so other
            # threads' injection points stay live while this one stalls
            time.sleep(hit.delay)
        if hit.exit_code is not None:
            # kill mode: die RIGHT HERE, like a preemption would — no
            # exception propagation, no finally blocks. One stderr
            # line first so a chaos run's death site is diagnosable.
            import sys

            print(f"cylon_tpu.resilience: injected HARD KILL at "
                  f"{point!r} (hit {k}, exit {hit.exit_code})",
                  file=sys.stderr, flush=True)
            os._exit(hit.exit_code)
        err = hit.error() if isinstance(hit.error, type) else hit.error
        if err is None and hit.delay == 0:
            err = TransientError(
                f"injected fault at {point!r} (hit {k}"
                + (f": {detail}" if detail else "") + ")")
        if err is not None:
            raise err


_LOCK = threading.Lock()
_ACTIVE: "FaultPlan | None" = None

#: monotonic suffix for per-attempt spill tmp files (see
#: SpillStore.write_bucket — concurrent attempts must never share one)
_TMP_SEQ = itertools.count()


def install(plan: "FaultPlan | None") -> "FaultPlan | None":
    """Set the process-wide active plan (None clears). Returns the
    previous plan so callers can restore it."""
    global _ACTIVE
    with _LOCK:
        prev, _ACTIVE = _ACTIVE, plan
    return prev


def active_plan() -> "FaultPlan | None":
    return _ACTIVE


@contextlib.contextmanager
def active(plan: FaultPlan):
    """``with resilience.active(plan): ...`` — scoped installation of
    the PROCESS-WIDE plan (every thread sees it; the chaos-drill
    shape). For a plan that must only apply to the current execution
    context — one serve request among concurrent workloads — use
    :func:`scoped`."""
    prev = install(plan)
    try:
        yield plan
    finally:
        install(prev)


#: context-local fault-plan overlay: visible only to the installing
#: context (and workers spawned with ``copy_context`` — the request's
#: own bounded calls), NEVER to unrelated threads. The serving layer
#: installs per-request plans here so one tenant's injected faults
#: cannot leak into another workload running concurrently in the
#: process.
_SCOPED_PLAN: contextvars.ContextVar = contextvars.ContextVar(
    "cylon_fault_plan", default=None)


@contextlib.contextmanager
def scoped(plan: "FaultPlan | None"):
    """``with resilience.scoped(plan): ...`` — context-local
    installation (contextvar, not the process global): injection
    points consult it only from this context, after any env-registered
    plan and before the process-wide one."""
    tok = _SCOPED_PLAN.set(plan)
    try:
        yield plan
    finally:
        _SCOPED_PLAN.reset(tok)


def inject(point: str, detail: str = "", env=None) -> None:
    """Instrumentation hook: a no-op unless a plan is active.
    Precedence: a plan registered on the op's CylonEnv, then the
    context-local :func:`scoped` plan, then the process-wide
    :func:`install`/:func:`active` plan."""
    if point not in _POINT_SET:
        raise InvalidArgument(f"unknown injection point {point!r}")
    plan = getattr(env, "_fault_plan", None) if env is not None else None
    if plan is None:
        plan = _SCOPED_PLAN.get()
    plan = plan if plan is not None else _ACTIVE
    if plan is not None:
        plan.check(point, detail)


_POINT_SET = frozenset(INJECTION_POINTS)


# ---------------------------------------------------------- retry engine
#: codes whose failures are worth re-attempting; everything else is
#: deterministic (bad input, capacity, real data loss) and re-raises
_RETRYABLE_CODES = frozenset({Code.Unavailable})
#: transient OS-level failures (tunneled/remote IO); NOT FileNotFoundError
#: etc. — a missing file does not appear on retry
_RETRYABLE_OS = (ConnectionError, TimeoutError, InterruptedError)


def is_retryable(exc: BaseException) -> bool:
    """Classification over ``errors.Code``: TransientError and any
    CylonError carrying ``Code.Unavailable`` retry; other CylonErrors
    never do; transient OS errors (connection/timeout/EINTR) retry.
    DeadlineExceeded defers to its per-section flag
    (``watchdog.SECTIONS``): bootstrap/spill-IO deadlines retry, a
    deadline mid-collective never does — the mesh state is
    unrecoverable."""
    if isinstance(exc, DeadlineExceeded):
        return bool(getattr(exc, "retryable", False))
    if isinstance(exc, TransientError):
        return True
    if isinstance(exc, CylonError):
        return exc.code in _RETRYABLE_CODES
    return isinstance(exc, _RETRYABLE_OS)


def default_policy() -> RetryPolicy:
    """The process default :class:`~cylon_tpu.config.RetryPolicy`, with
    env overrides (read per call so tests can flip them)."""
    e = os.environ
    return RetryPolicy(
        max_attempts=int(e.get("CYLON_TPU_RETRY_ATTEMPTS", "3")),
        base_delay=float(e.get("CYLON_TPU_RETRY_BASE_DELAY", "0.05")),
        max_delay=float(e.get("CYLON_TPU_RETRY_MAX_DELAY", "2.0")),
        multiplier=float(e.get("CYLON_TPU_RETRY_MULTIPLIER", "2.0")),
        jitter=float(e.get("CYLON_TPU_RETRY_JITTER", "0.1")),
    )


def backoff_delays(policy: RetryPolicy):
    """Infinite generator of backoff delays for ``policy``:
    ``min(base * multiplier**k, max_delay)`` with deterministic +-jitter
    drawn from ``policy.seed`` — the same policy always yields the same
    sequence (exposed for tests and for reasoning about worst cases)."""
    rng = np.random.default_rng(policy.seed)
    d = float(policy.base_delay)
    while True:
        j = 1.0 + policy.jitter * (2.0 * rng.random() - 1.0)
        yield min(d, policy.max_delay) * j
        d = min(d * policy.multiplier, policy.max_delay)


def retrying(fn, policy: "RetryPolicy | None" = None, *,
             retry_on=None, sleep_fn=None, label: str | None = None):
    """Call ``fn()`` with retry/backoff; return its result.

    Retries only failures ``retry_on`` (default :func:`is_retryable`)
    classifies as transient, up to ``policy.max_attempts`` total
    attempts, sleeping a :func:`backoff_delays` step between attempts
    (``sleep_fn`` overrides ``time.sleep`` — tests pass a recorder).
    The final failure re-raises the original exception unchanged."""
    policy = policy or default_policy()
    classify = retry_on or is_retryable
    sleep = time.sleep if sleep_fn is None else sleep_fn
    delays = backoff_delays(policy)
    attempts = max(int(policy.max_attempts), 1)
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except Exception as e:
            if attempt >= attempts or not classify(e):
                raise
            d = next(delays)
            code = getattr(getattr(e, "code", None), "name", None) \
                or type(e).__name__
            telemetry.counter("resilience.retries", code=code,
                              **telemetry.tenant_labels()).inc()
            _trace.instant("resilience.retry", cat="resilience",
                           code=code, attempt=attempt,
                           label=label or "", backoff_s=d)
            from cylon_tpu.utils.logging import get_logger

            get_logger().warning(
                "%sattempt %d/%d failed (%s: %s); retrying in %.3fs",
                f"{label}: " if label else "", attempt, attempts,
                type(e).__name__, e, d)
            sleep(d)


# ------------------------------------------------------- loss accounting
def accounting_enabled() -> bool:
    """Row accounting defaults ON; ``CYLON_TPU_ROW_ACCOUNTING=0`` turns
    the eager shuffle-driver checks off (they cost one extra [W]-count
    fetch per eager exchange — ~100 ms on a tunneled chip)."""
    return os.environ.get("CYLON_TPU_ROW_ACCOUNTING", "1") \
        not in ("0", "off")


class RowAccount:
    """Rows-in vs rows-out invariant for a multi-pass pipeline."""

    def __init__(self, label: str):
        self.label = label
        self.rows_in = 0
        self.rows_out = 0

    def add_in(self, n) -> "RowAccount":
        self.rows_in += int(n)
        return self

    def add_out(self, n) -> "RowAccount":
        self.rows_out += int(n)
        return self

    def verify(self, what: str = "rows") -> None:
        if self.rows_in != self.rows_out:
            raise DataLossError(
                f"{self.label}: {self.rows_in} {what} in vs "
                f"{self.rows_out} out — data was silently dropped or "
                "duplicated")


def check_conservation(label: str, rows_in, rows_out,
                       what: str = "rows") -> None:
    """One-shot :class:`RowAccount`."""
    RowAccount(label).add_in(rows_in).add_out(rows_out).verify(what)


# ----------------------------------------------------------- spill store
def atomic_write_json(path: str, obj) -> None:
    """Crash-safe JSON write: unique tmp + ``flush`` + ``fsync`` +
    ``os.replace``. At EVERY instant the target path holds either the
    previous complete document or the new complete document — a hard
    kill (``os._exit``, SIGKILL, power loss) mid-write can only strand
    a tmp file, never a torn target. This is the ONE write primitive
    every manifest/journal/sentinel site uses (the atomicity audit in
    ``tests/test_checkpoint.py`` pins the fsync-before-replace order)."""
    tmp = (f"{path}.tmp{os.getpid()}_"
           f"{threading.get_ident()}_{next(_TMP_SEQ)}")
    try:
        with open(tmp, "w") as f:
            json.dump(obj, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class SpillStore:
    """Directory-backed bucket spill with a completion manifest.

    One ``bucket<p>.npz`` per completed range/partition plus
    ``manifest.json`` recording ``{bucket: rows}`` — updated atomically
    (tmp + rename) AFTER the bucket's data is durably written, so a kill
    at any instant leaves either a complete, recorded bucket or nothing.
    A ``fingerprint`` (hash of the pass's keys/splitters) guards reuse:
    a store opened with a different fingerprint discards stale state
    instead of resuming against the wrong plan.

    Writes and reads run under :func:`retrying` and hit the
    ``spill_write`` / ``spill_read`` injection points — this is the
    "out-of-core spill store" the retry engine wraps. Each attempt is
    additionally bounded by the ``spill_io`` watchdog section
    (:func:`cylon_tpu.watchdog.bounded`): under a deadline, a hung
    mount raises a *retryable* DeadlineExceeded, so the retry engine
    absorbs IO hangs exactly like raised IO errors.
    """

    MANIFEST = "manifest.json"

    def __init__(self, root: str, fingerprint: str = "",
                 policy: "RetryPolicy | None" = None):
        self.root = str(root)
        self._policy = policy or default_policy()
        os.makedirs(self.root, exist_ok=True)
        self._mpath = os.path.join(self.root, self.MANIFEST)
        m = self._load_manifest()
        if m is None or m.get("fingerprint") != fingerprint:
            # discard stale state — but ONLY files this store's naming
            # scheme owns (bucketNNNNN.npz + manifest); a resume_dir
            # accidentally pointed at a directory of unrelated .npz
            # data must never be wiped
            import re

            own = re.compile(r"^bucket\d{5}\.npz(\.tmp\S*)?$")
            for f in os.listdir(self.root):
                if own.match(f) or f == self.MANIFEST \
                        or f.startswith(self.MANIFEST + ".tmp"):
                    os.unlink(os.path.join(self.root, f))
            m = {"fingerprint": fingerprint, "completed": {}}
            self._write_manifest(m)
        self._m = m

    def _load_manifest(self):
        try:
            with open(self._mpath) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _write_manifest(self, m) -> None:
        atomic_write_json(self._mpath, m)

    def _bucket_path(self, p: int) -> str:
        return os.path.join(self.root, f"bucket{int(p):05d}.npz")

    @property
    def completed(self) -> dict:
        """{bucket index: rows} for every durably completed bucket."""
        return {int(k): int(v) for k, v in self._m["completed"].items()}

    def completed_rows(self, p: int) -> "int | None":
        v = self._m["completed"].get(str(int(p)))
        return None if v is None else int(v)

    def bucket_meta(self, p: int) -> "dict | None":
        """Per-unit metadata recorded at completion (e.g. the input
        sizes a resumed ``ooc_join`` partition must re-verify)."""
        return self._m.get("meta", {}).get(str(int(p)))

    def write_bucket(self, p: int, cols: dict, rows: int,
                     meta: "dict | None" = None) -> None:
        """Durably spill one bucket's columns, then record completion
        (plus optional ``meta``, kept in the manifest next to the row
        count). Empty buckets record 0 rows with no file."""
        path = self._bucket_path(p)

        def _write():
            inject("spill_write", f"bucket {p}")
            # per-attempt unique tmp: a deadline-abandoned worker may
            # still be writing ITS tmp when the retry starts — a shared
            # name would interleave two writers in one inode and
            # os.replace could install the torn file as a "completed"
            # bucket. Distinct inodes + atomic replace keep whichever
            # rename lands last a complete, valid write; the fsync
            # means the bytes are durable BEFORE the rename can make
            # the manifest point at them.
            tmp = (f"{path}.tmp{os.getpid()}_"
                   f"{threading.get_ident()}_{next(_TMP_SEQ)}")
            try:
                with open(tmp, "wb") as f:
                    np.savez(f, **cols)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise

        if rows:
            from cylon_tpu import watchdog

            with telemetry.timer("spill.write_seconds").time(), \
                    _trace.span("spill.write", cat="spill", bucket=p):
                retrying(lambda: watchdog.bounded(
                    _write, "spill_io", detail=f"write bucket {p}"),
                    self._policy, label=f"spill_write[{p}]")
            nb = int(sum(np.asarray(v).nbytes for v in cols.values()))
            telemetry.counter("spill.write_bytes").inc(nb)
            telemetry.counter("spill.write_buckets").inc()
            _trace.instant("spill.write", cat="spill", bucket=p,
                           bytes=nb, rows=int(rows))
        self._m["completed"][str(int(p))] = int(rows)
        if meta is not None:
            self._m.setdefault("meta", {})[str(int(p))] = dict(meta)
        self._write_manifest(self._m)

    def read_bucket(self, p: int) -> dict:
        """Reload a completed bucket's columns (insertion order kept)."""
        path = self._bucket_path(p)

        def _read():
            inject("spill_read", f"bucket {p}")
            with np.load(path, allow_pickle=True) as z:
                return {k: z[k] for k in z.files}

        from cylon_tpu import watchdog

        with telemetry.timer("spill.read_seconds").time(), \
                _trace.span("spill.read", cat="spill", bucket=p):
            out = retrying(lambda: watchdog.bounded(
                _read, "spill_io", detail=f"read bucket {p}"),
                self._policy, label=f"spill_read[{p}]")
        nb = int(sum(a.nbytes for a in out.values()))
        telemetry.counter("spill.read_bytes").inc(nb)
        telemetry.counter("spill.read_buckets").inc()
        _trace.instant("spill.read", cat="spill", bucket=p, bytes=nb)
        return out


class CheckpointedRun:
    """Generic checkpoint/resume for a multi-unit pass.

    Factors the resumable-manifest machinery ``ooc_sort`` pioneered
    into the reusable shape every long pass threads through
    (``ooc_join``/``ooc_groupby`` partitions and chunks, the serve
    catalog snapshot): a run is identified by a **fingerprint** —
    ``op`` plus the partitioning *plan* (keys, splitters, partition
    counts, transform identity…) — and made of numbered **units**,
    each completed atomically (data durable + fsynced BEFORE the
    manifest records it, via :class:`SpillStore`). The guarantees:

    * a process hard-killed at ANY instant leaves every recorded unit
      complete and valid — a re-invocation with the same arguments
      replays recorded units byte-identically and recomputes only the
      rest, so the final output equals a fault-free run's;
    * a directory whose fingerprint does not match (different op,
      keys, plan, data-derived splitters) is DISCARDED, never resumed
      against the wrong plan;
    * per-unit ``meta`` recorded at completion lets the resuming run
      re-verify source stability (e.g. partition input sizes) and
      raise :class:`~cylon_tpu.errors.DataLossError` instead of
      silently mixing two generations of the source.

    Every resumed unit counts ``ooc.units_resumed{op=}``.
    """

    def __init__(self, root: str, op: str, plan=(),
                 policy: "RetryPolicy | None" = None):
        self.op = str(op)
        self.fingerprint = fingerprint_arrays(self.op, *plan)
        self.store = SpillStore(root, fingerprint=self.fingerprint,
                                policy=policy)

    @property
    def completed(self) -> dict:
        """{unit: rows} for every durably completed unit."""
        return self.store.completed

    def completed_rows(self, unit: int) -> "int | None":
        """Recorded row count of ``unit`` (None = not completed)."""
        return self.store.completed_rows(unit)

    def unit_meta(self, unit: int) -> "dict | None":
        return self.store.bucket_meta(unit)

    def complete(self, unit: int, cols: dict, rows: int,
                 meta: "dict | None" = None) -> None:
        """Durably record ``unit`` done: columns spilled + fsynced,
        then the manifest updated atomically — a kill between the two
        just recomputes the unit."""
        self.store.write_bucket(unit, cols, int(rows), meta=meta)

    def note_resumed(self, unit: int) -> None:
        """Count a completed unit as resumed (no IO) — the metrics
        half of :meth:`resume_unit`, for callers that skip the data
        (count-only runs with no sink)."""
        telemetry.counter("ooc.units_resumed", op=self.op).inc()
        _trace.instant("ckpt.resume", cat="resilience", op=self.op,
                       unit=int(unit))
        telemetry.events.emit("checkpoint_resume", op=self.op,
                              unit=int(unit))

    def load_unit(self, unit: int) -> dict:
        """A completed unit's columns from the durable spill ({} for
        0-row units) — no counting; pair with :meth:`note_resumed`."""
        rows = self.store.completed_rows(unit)
        return self.store.read_bucket(unit) if rows else {}

    def resume_unit(self, unit: int) -> dict:
        """Replay a completed unit's columns from the durable spill
        ({} for 0-row units) and count it as resumed."""
        self.note_resumed(unit)
        return self.load_unit(unit)

    def verify_meta(self, unit: int, label: str, **expect) -> None:
        """Raise :class:`~cylon_tpu.errors.DataLossError` if ``unit``'s
        recorded meta disagrees with the re-derived values — the
        source changed since the manifest was written."""
        meta = self.unit_meta(unit) or {}
        bad = {k: (meta.get(k), v) for k, v in expect.items()
               if meta.get(k) != v}
        if bad:
            raise DataLossError(
                f"{label}: resume manifest for unit {unit} recorded "
                f"{ {k: got for k, (got, _) in bad.items()} } but the "
                f"re-derived source has "
                f"{ {k: want for k, (_, want) in bad.items()} } — the "
                "source changed since the checkpoint was written; "
                "clear the resume_dir")


def fingerprint_arrays(*parts) -> str:
    """Stable hex digest of heterogeneous plan state (key names, ints,
    numpy scalars/arrays) — the spill-store reuse guard."""
    h = hashlib.sha256()
    for part in parts:
        if isinstance(part, (list, tuple)):
            h.update(fingerprint_arrays(*part).encode())
        elif isinstance(part, np.ndarray) or isinstance(part, np.generic):
            a = np.asarray(part)
            h.update(str(a.dtype).encode())
            h.update(a.tobytes())
        else:
            h.update(repr(part).encode())
        h.update(b"|")
    return h.hexdigest()
