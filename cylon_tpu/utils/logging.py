"""Logging: glog-style levels driven by ``CYLON_LOG_LEVEL``.

Parity: the reference logs through glog everywhere (``table.hpp:18``)
with ``util/logging.{hpp,cpp}`` wrapping init, and PyCylon maps the
``CYLON_LOG_LEVEL`` env var to ``log_level()``/``disable_logging()``
(``python/pycylon/__init__.py:30-43``). Same contract here on the
stdlib ``logging`` module: glog severities 0..3 = INFO, WARNING, ERROR,
FATAL; anything above disables.
"""

import logging
import os

_LOGGER_NAME = "cylon_tpu"

#: glog severity -> stdlib level (``python/pycylon/util/logging.pyx``).
_GLOG_LEVELS = {0: logging.INFO, 1: logging.WARNING,
                2: logging.ERROR, 3: logging.CRITICAL}

_initialized = False

#: (rank, world) of the live process, set by ``CylonEnv.__init__`` —
#: None until an env exists, so library users who never construct one
#: keep the bare format.
_WORLD: "tuple[int, int] | None" = None


def set_world(rank: int, world: int) -> None:
    """Record the process's (rank, world); every subsequent log record
    is prefixed ``rank/world`` — on a multihost fleet the interleaved
    stderr streams are unreadable without it (the reference's glog
    lines carry the MPI rank the same way)."""
    global _WORLD
    _WORLD = (int(rank), int(world))


class _RankFilter(logging.Filter):
    """Injects ``record.rankprefix`` (``"[r/w] "`` once a CylonEnv is
    live, ``""`` before) for the handler's format string. A filter
    (not str concat at call sites) so EVERY record through the handler
    gets it, including records from third-party code routed here."""

    def filter(self, record):
        record.rankprefix = (f"[{_WORLD[0]}/{_WORLD[1]}] "
                             if _WORLD is not None else "")
        return True


def get_logger() -> logging.Logger:
    return logging.getLogger(_LOGGER_NAME)


def init_logging() -> None:
    """Idempotent init, called at package import (mirrors
    ``pycylon.__init__``): reads ``CYLON_LOG_LEVEL`` and attaches one
    stderr handler with a glog-flavoured format."""
    global _initialized
    if _initialized:
        return
    _initialized = True
    logger = get_logger()
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(levelname).1s %(asctime)s %(name)s] "
            "%(rankprefix)s%(message)s",
            datefmt="%H:%M:%S"))
        h.addFilter(_RankFilter())
        logger.addHandler(h)
    logger.propagate = False
    env = os.environ.get("CYLON_LOG_LEVEL")
    if env is None:
        logger.setLevel(logging.WARNING)
        return
    try:
        log_level(int(env))
    except ValueError:
        logger.setLevel(logging.WARNING)
        logger.warning("bad CYLON_LOG_LEVEL=%r (want 0..4)", env)


def log_level(glog_severity: int) -> None:
    """Set the minimum severity, glog numbering (0=INFO .. 3=FATAL)."""
    if glog_severity in _GLOG_LEVELS:
        get_logger().setLevel(_GLOG_LEVELS[glog_severity])
    else:
        disable_logging()


def disable_logging() -> None:
    get_logger().setLevel(logging.CRITICAL + 1)
