"""Runtime utilities: logging and op tracing/profiling.

Reference analog: ``cpp/src/cylon/util/`` (logging.{hpp,cpp} glog wrap,
macros) plus the inline ``std::chrono`` op timing at table boundaries
(``table.cpp:167-177``).
"""

from cylon_tpu.utils.logging import (disable_logging, get_logger,
                                     init_logging, log_level)
from cylon_tpu.utils.tracing import (profile_to, report, reset_timings,
                                     span, timings, traced)


def pow2_bucket(n: int, minimum: int = 1) -> int:
    """Smallest power of two >= max(n, minimum) — THE capacity bucket
    policy (power-of-2 buckets bound the distinct shape count and hence
    compiles; see plan.capacity_scale)."""
    return max(int(minimum), 1 << max(int(n) - 1, 0).bit_length())


__all__ = [
    "disable_logging", "get_logger", "init_logging", "log_level",
    "pow2_bucket",
    "profile_to", "report", "reset_timings", "span", "timings", "traced",
]
