"""Runtime utilities: logging and op tracing/profiling.

Reference analog: ``cpp/src/cylon/util/`` (logging.{hpp,cpp} glog wrap,
macros) plus the inline ``std::chrono`` op timing at table boundaries
(``table.cpp:167-177``).
"""

from cylon_tpu.utils.logging import (disable_logging, get_logger,
                                     init_logging, log_level)
from cylon_tpu.utils.tracing import (profile_to, report, reset_timings,
                                     span, timings, traced)

__all__ = [
    "disable_logging", "get_logger", "init_logging", "log_level",
    "profile_to", "report", "reset_timings", "span", "timings", "traced",
]
