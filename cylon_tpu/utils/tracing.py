"""Op tracing: wall-clock spans + JAX profiler hooks.

The reference has no tracer — it inlines ``std::chrono`` timing and
glog INFO lines at op boundaries (shuffle timings ``table.cpp:167-177``;
bench binaries log ``j_t``/``w_t`` per rank,
``cpp/src/examples/bench/table_join_dist_test.cpp:38-56``). The rebuild
formalises that: every public op runs under a :func:`span`, spans
accumulate into the process telemetry registry
(:mod:`cylon_tpu.telemetry` — one registry for spans, watchdog section
timings and engine counters, exportable as JSONL/Prometheus), and the
same spans emit ``jax.profiler.TraceAnnotation`` so they line up with
XLA device traces in xprof/tensorboard (:func:`profile_to`).

:func:`span`/:func:`profile_to`/:func:`timings` are kept as thin
wrappers over the registry so existing callers (and their tests) are
untouched; :class:`SpanStat` remains the aggregate view type. When the
flight recorder is armed (``CYLON_TPU_TRACE`` —
:mod:`cylon_tpu.telemetry.trace`), every span additionally emits
begin/end events with parent nesting into the trace buffer, so the
same instrumentation feeds the histogram aggregates AND the
Chrome-trace timelines; with the recorder off, the only addition over
the pre-recorder span is one env read.

Caveat that doesn't exist in the reference: JAX dispatch is async, so a
span around a jitted call measures *host orchestration* unless
``sync=`` is given a value to ``block_until_ready`` on.
"""

import contextlib
import functools
from dataclasses import dataclass, field

from cylon_tpu import telemetry
from cylon_tpu.telemetry import trace as _trace
from cylon_tpu.utils.logging import get_logger

#: the telemetry series spans record into (label ``name`` = span name)
SPAN_METRIC = "tracing.span_seconds"


@dataclass
class SpanStat:
    count: int = 0
    total_s: float = 0.0
    min_s: float = field(default=float("inf"))
    max_s: float = 0.0

    def add(self, dt: float) -> None:
        self.count += 1
        self.total_s += dt
        self.min_s = min(self.min_s, dt)
        self.max_s = max(self.max_s, dt)

    def to_json(self) -> dict:
        """Strict-JSON-safe dict: an empty stat's ``min_s`` default of
        ``float("inf")`` would serialise as invalid-JSON ``Infinity``
        (``json.dumps`` emits it happily), so fields normalise through
        the one canonical coercion, :func:`telemetry.json_safe`."""
        return telemetry.json_safe(
            {"count": self.count, "total_s": self.total_s,
             "min_s": self.min_s, "max_s": self.max_s})


@contextlib.contextmanager
def span(name: str, sync=None, cat: "str | None" = None, **targs):
    """Time a named region; optionally block on ``sync`` (any pytree of
    jax arrays) so device work is included in the measurement.

    ``cat``/``**targs`` annotate the flight-recorder event when tracing
    is armed (``cat="stage"`` marks the span as a stage for
    :func:`cylon_tpu.telemetry.trace.critical_path` attribution);
    they cost nothing when it is off. The per-span completion line logs
    at DEBUG — at millions of spans an INFO line per span is pure noise
    on hot paths; aggregate visibility is :func:`report`'s job."""
    import time

    import jax

    t0 = time.perf_counter()
    tok = _trace.begin(name, cat=cat, **targs) if _trace.enabled() \
        else None
    try:
        with jax.profiler.TraceAnnotation(name):
            try:
                yield
            finally:
                if sync is not None:
                    jax.block_until_ready(sync)
                dt = time.perf_counter() - t0
                # the ambient tenant (serve layer) splits the series so
                # per-tenant latency is reportable; outside a tenant
                # scope the labels are {} — the historical series key
                telemetry.timer(SPAN_METRIC, name=name,
                                **telemetry.tenant_labels()).observe(dt)
                get_logger().debug("%s: %.3f ms", name, dt * 1e3)
    finally:
        _trace.end(tok)


def traced(name: str | None = None):
    """Decorator: run the function under a :func:`span` (host timing)."""

    def deco(fn):
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with span(label):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def timings(tenant: "str | None" = None) -> dict[str, SpanStat]:
    """Snapshot of accumulated span statistics — a view over the
    telemetry registry's :data:`SPAN_METRIC` series. Series that differ
    only by ``tenant`` label merge per span name; ``tenant=`` restricts
    the view to one tenant's series (the serve layer's per-tenant
    latency slice)."""
    out = {}
    for _, labels, inst in telemetry.instruments(SPAN_METRIC):
        if tenant is not None and labels.get("tenant") != str(tenant):
            continue
        d = inst.dump()  # locked read: count/min/max move together
        if d["count"] and d["min"] is not None:
            s = out.get(labels["name"])
            if s is None:
                out[labels["name"]] = SpanStat(
                    d["count"], float(d["sum"]), float(d["min"]),
                    float(d["max"]))
            else:
                s.count += d["count"]
                s.total_s += float(d["sum"])
                s.min_s = min(s.min_s, float(d["min"]))
                s.max_s = max(s.max_s, float(d["max"]))
    return out


def reset_timings() -> None:
    telemetry.reset("tracing.")


def report(tenant: "str | None" = None) -> str:
    """Human-readable table of span stats, slowest total first. The
    p50/p99 columns come from the shared pow2 histogram buckets
    (:meth:`cylon_tpu.telemetry.registry.Histogram.quantile`) — mean/
    min/max alone hide tail latency, and the tail is where stragglers
    live. ``tenant=`` isolates one tenant's spans from a mixed
    multi-tenant recording (series labeled by the serve layer's
    ambient :func:`cylon_tpu.telemetry.tenant_scope`); the default
    merges every tenant's series per span name."""
    insts: dict[str, list] = {}
    for _, labels, inst in telemetry.instruments(SPAN_METRIC):
        if tenant is not None and labels.get("tenant") != str(tenant):
            continue
        insts.setdefault(labels.get("name", "?"), []).append(inst)
    snap = timings(tenant=tenant)
    if not snap:
        return "(no spans recorded)"
    rows = sorted(snap.items(), key=lambda kv: -kv[1].total_s)
    w = max(len(k) for k, _ in rows)
    lines = [f"{'span':<{w}}  {'count':>6}  {'total ms':>10}  "
             f"{'mean ms':>9}  {'min ms':>8}  {'p50 ms':>8}  "
             f"{'p99 ms':>8}  {'max ms':>8}"]
    for k, s in rows:
        # quantiles over the MERGED bucket ladder when a name has
        # several tenant series (associative by construction)
        inst = telemetry.merge_histograms(insts.get(k, []))
        p50 = inst.quantile(0.5) if inst is not None else None
        p99 = inst.quantile(0.99) if inst is not None else None
        lines.append(
            f"{k:<{w}}  {s.count:>6}  {s.total_s * 1e3:>10.3f}  "
            f"{s.total_s / s.count * 1e3:>9.3f}  {s.min_s * 1e3:>8.3f}  "
            f"{(p50 or 0.0) * 1e3:>8.3f}  {(p99 or 0.0) * 1e3:>8.3f}  "
            f"{s.max_s * 1e3:>8.3f}")
    return "\n".join(lines)


@contextlib.contextmanager
def profile_to(logdir: str):
    """Capture a JAX/XLA device profile (xprof format) for the enclosed
    region — the deep-dive tool the reference lacks; view with
    tensorboard or xprof."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
