"""String-id table catalog + id-keyed operation mirror.

Parity target: ``cpp/src/cylon/table_api.{hpp,cpp}`` — a process-global
registry mapping string ids to tables (``PutTable/GetTable/RemoveTable``,
``table_api.hpp:38-90``) with every relational op mirrored on ids
(``JoinTables(ctx, "left", "right", ...)``). In the reference this layer
exists to give the Java JNI binding a stable C surface; here it is the
FFI/embedding surface for non-Python hosts of the TPU runtime — and,
since the serving layer (:mod:`cylon_tpu.serve`), the **resident-table
store** of the always-on engine: tables register once, concurrent
queries :func:`pin` them for their lifetime (refcounted per holder),
:func:`drop` refuses pinned tables with a
:class:`~cylon_tpu.errors.FailedPrecondition` naming the holders, and
:func:`stats` reports per-table rows/bytes/pins.
"""

import collections
import contextlib
import threading
from typing import Mapping, Sequence

from cylon_tpu.config import JoinConfig
from cylon_tpu.errors import FailedPrecondition, InvalidArgument, KeyError_
from cylon_tpu.table import Table

_lock = threading.Lock()
_catalog: dict[str, Table] = {}
#: table id -> Counter of holder labels (pin refcounts). A pinned table
#: cannot be dropped: the serving layer pins every resident table a
#: request reads for the request's lifetime, so a concurrent ``drop``
#: fails loudly at the drop site (naming the holders) instead of as a
#: confusing late KeyError inside whichever query lost the race.
_pins: "dict[str, collections.Counter]" = {}


def put_table(table_id: str, table: Table) -> None:
    """Parity: ``PutTable`` (table_api.hpp:38). Re-registering an id is
    an overwrite — but not while the old table is pinned (an in-flight
    reader must never see its input swapped underneath it)."""
    if not isinstance(table, Table):
        raise InvalidArgument(f"not a Table: {type(table)}")
    with _lock:
        _require_unpinned(table_id, "overwrite")
        _catalog[table_id] = table


def get_table(table_id: str, pin_for: "str | None" = None) -> Table:
    """Parity: ``GetTable``. ``pin_for=holder`` additionally pins the
    table under ``holder`` in the same lock hold — the atomic
    lookup-and-pin a concurrent reader needs (a separate get + pin
    could lose a drop race in between)."""
    with _lock:
        if table_id not in _catalog:
            raise KeyError_(f"no table registered under {table_id!r}")
        if pin_for is not None:
            _pins.setdefault(table_id,
                             collections.Counter())[str(pin_for)] += 1
        return _catalog[table_id]


def _require_unpinned(table_id: str, verb: str) -> None:
    holders = _pins.get(table_id)
    if holders:
        names = sorted(holders)
        raise FailedPrecondition(
            f"cannot {verb} table {table_id!r}: pinned by "
            f"{sum(holders.values())} holder(s) {names}; drop waits "
            "until every holder unpins")


def pin(table_id: str, holder: str = "anonymous") -> None:
    """Refcount ``table_id`` under ``holder`` so :func:`drop` refuses
    it. Pins nest (one count per call); unpin with the same holder."""
    with _lock:
        if table_id not in _catalog:
            raise KeyError_(f"no table registered under {table_id!r}")
        _pins.setdefault(table_id, collections.Counter())[str(holder)] += 1


def unpin(table_id: str, holder: str = "anonymous") -> None:
    """Release one pin held by ``holder`` (unknown pins raise — an
    unbalanced unpin is a refcount bug, not a no-op)."""
    with _lock:
        holders = _pins.get(table_id)
        if not holders or holders[str(holder)] <= 0:
            raise InvalidArgument(
                f"table {table_id!r} holds no pin for {holder!r}")
        holders[str(holder)] -= 1
        if holders[str(holder)] <= 0:
            del holders[str(holder)]
        if not holders:
            _pins.pop(table_id, None)


@contextlib.contextmanager
def pinned(table_id: str, holder: str = "anonymous"):
    """``with catalog.pinned("lineitem", holder=req_id) as t:`` — the
    table, pinned for the scope (the per-request discipline
    :mod:`cylon_tpu.serve` applies around every query)."""
    t = get_table(table_id, pin_for=holder)
    try:
        yield t
    finally:
        unpin(table_id, holder)


def pins(table_id: str) -> "dict[str, int]":
    """Live pin counts per holder (empty when unpinned/unknown)."""
    with _lock:
        return dict(_pins.get(table_id, ()))


def drop(table_id: str, *, if_exists: bool = True) -> None:
    """Remove ``table_id`` — unless pinned, in which case a
    :class:`~cylon_tpu.errors.FailedPrecondition` NAMES the holders
    (the serve-layer contract: a resident table a query is reading
    cannot vanish mid-flight)."""
    with _lock:
        if table_id not in _catalog:
            if if_exists:
                return
            raise KeyError_(f"no table registered under {table_id!r}")
        _require_unpinned(table_id, "drop")
        del _catalog[table_id]


def remove_table(table_id: str) -> None:
    """Parity: ``RemoveTable`` — now pin-respecting (see :func:`drop`)."""
    drop(table_id, if_exists=True)


def list_tables() -> list[str]:
    with _lock:
        return sorted(_catalog)


def table_nbytes(table: Table) -> int:
    """Device bytes held by ``table``'s buffers (data + validity),
    summed over columns — no host sync (buffer shapes are static)."""
    total = 0
    for c in table.columns.values():
        total += c.data.size * c.data.dtype.itemsize
        if c.validity is not None:
            total += c.validity.size * c.validity.dtype.itemsize
    return total


def table_device_nbytes(table: Table) -> "dict[str, int]":
    """Per-device byte split of ``table``'s buffers —
    ``{"tpu:0": n, ...}`` from each array's addressable shard layout
    (shard metadata only: no host sync, no transfer; the key scheme
    and host fallback are
    :func:`cylon_tpu.telemetry.memory.accumulate_array_bytes`, shared
    with the live-bytes walk so the two accountings cross-check).
    This is the split the serve ``/tables`` endpoint reports: on a
    distributed table it shows exactly how evenly the resident bytes
    spread over the mesh."""
    from cylon_tpu.telemetry.memory import accumulate_array_bytes

    out: "dict[str, int]" = {}
    for c in table.columns.values():
        accumulate_array_bytes(c.data, out)
        if c.validity is not None:
            accumulate_array_bytes(c.validity, out)
    return out


def stats() -> "dict[str, dict]":
    """Per-table catalog statistics: ``{id: {rows, bytes,
    bytes_by_device, capacity, columns, distributed, pins,
    holders}}`` — the resident-table
    inventory ``cylon_tpu.serve`` reports. ``rows`` is the true row
    count (summed across shards for distributed tables; one small host
    fetch per table); tables whose count is not host-reachable (e.g.
    under trace) report ``rows=None``."""
    import numpy as np

    from cylon_tpu.parallel import dtable

    with _lock:
        items = list(_catalog.items())
        pin_view = {k: dict(v) for k, v in _pins.items()}
    out = {}
    for tid, t in items:
        try:
            rows = int(np.asarray(t.nrows).sum())
        except Exception:
            rows = None
        holders = pin_view.get(tid, {})
        out[tid] = {
            "rows": rows,
            "bytes": table_nbytes(t),
            "bytes_by_device": table_device_nbytes(t),
            "capacity": int(t.capacity),
            "columns": t.num_columns,
            "distributed": bool(dtable.is_distributed(t)),
            "pins": sum(holders.values()),
            "holders": sorted(holders),
        }
    return out


def clear() -> None:
    """Drop everything, pins included (test/teardown hatch — the
    pin-respecting path is :func:`drop`)."""
    with _lock:
        _catalog.clear()
        _pins.clear()


# ---------------------------------------------------------------- id ops
def read_csv(table_id: str, path, **kw) -> None:
    """Parity: ``ReadCSV(ctx, path, id)`` (table_api.hpp)."""
    from cylon_tpu.io import read_csv as _read

    put_table(table_id, _read(path, **kw).to_table())


def join_tables(left_id: str, right_id: str, out_id: str,
                config: JoinConfig | None = None, *, on=None,
                how: str = "inner", env=None, **kw) -> None:
    """Parity: ``JoinTables(ctx, "left", "right", ...)``
    (table_api.hpp:46)."""
    from cylon_tpu.ops.join import join
    from cylon_tpu.parallel import dist_join

    lt, rt = get_table(left_id), get_table(right_id)
    if config is not None:
        on = None
        kw.setdefault("left_on", list(config.left_on))
        kw.setdefault("right_on", list(config.right_on))
        how = config.join_type.value
    if env is not None:
        out = dist_join(env, lt, rt, on=on, how=how, **kw)
    else:
        out = join(lt, rt, on=on, how=how, **kw)
    put_table(out_id, out)


def _binary(op_name: str):
    def run(left_id: str, right_id: str, out_id: str, env=None, **kw):
        from cylon_tpu.ops import setops
        from cylon_tpu.parallel import dist_ops

        lt, rt = get_table(left_id), get_table(right_id)
        if env is not None:
            fn = getattr(dist_ops, f"dist_{op_name}")
            put_table(out_id, fn(env, lt, rt, **kw))
        else:
            fn = getattr(setops, op_name)
            put_table(out_id, fn(lt, rt, **kw))
    run.__name__ = f"{op_name}_tables"
    run.__doc__ = f"Parity: table_api {op_name.capitalize()}Tables."
    return run


union_tables = _binary("union")
intersect_tables = _binary("intersect")
subtract_tables = _binary("subtract")


def sort_table(table_id: str, out_id: str, by, env=None, **kw) -> None:
    """Parity: table_api Sort/DistributedSort."""
    from cylon_tpu.ops.selection import sort_table as _sort
    from cylon_tpu.parallel import dist_sort

    t = get_table(table_id)
    by = [by] if isinstance(by, str) else list(by)
    if env is not None:
        put_table(out_id, dist_sort(env, t, by, **kw))
    else:
        put_table(out_id, _sort(t, by, **kw))


def unique_table(table_id: str, out_id: str, cols=None, env=None, **kw
                 ) -> None:
    """Parity: table_api Unique/DistributedUnique."""
    from cylon_tpu.ops import setops
    from cylon_tpu.parallel import dist_unique

    t = get_table(table_id)
    if env is not None:
        put_table(out_id, dist_unique(env, t, cols, **kw))
    else:
        put_table(out_id, setops.unique(t, cols, **kw))


def select_columns(table_id: str, out_id: str, names: Sequence[str]) -> None:
    """Parity: table_api Project."""
    put_table(out_id, get_table(table_id).select(list(names)))


def table_to_pydict(table_id: str) -> Mapping[str, list]:
    return get_table(table_id).to_pydict()


# --------------------------------------------------------- native bridge
def to_native(table_id: str) -> None:
    """Copy a catalog entry into the native C-ABI registry
    (``cylon_catalog_*`` in ``native/cylon_host.cpp``) where any FFI
    host — the JNI-style binding surface — can read it."""
    from cylon_tpu import native

    native.catalog_put(table_id, get_table(table_id))


def from_native(table_id: str) -> None:
    """Import a table published in the native registry into this
    catalog (reverse direction of :func:`to_native`)."""
    from cylon_tpu import native

    put_table(table_id, native.catalog_get(table_id))
