"""String-id table catalog + id-keyed operation mirror.

Parity target: ``cpp/src/cylon/table_api.{hpp,cpp}`` — a process-global
registry mapping string ids to tables (``PutTable/GetTable/RemoveTable``,
``table_api.hpp:38-90``) with every relational op mirrored on ids
(``JoinTables(ctx, "left", "right", ...)``). In the reference this layer
exists to give the Java JNI binding a stable C surface; here it is the
FFI/embedding surface for non-Python hosts of the TPU runtime — and,
since the serving layer (:mod:`cylon_tpu.serve`), the **resident-table
store** of the always-on engine: tables register once, concurrent
queries :func:`pin` them for their lifetime (refcounted per holder),
:func:`drop` refuses pinned tables with a
:class:`~cylon_tpu.errors.FailedPrecondition` naming the holders, and
:func:`stats` reports per-table rows/bytes/pins.

Since the views subsystem (:mod:`cylon_tpu.views`), resident tables
are also **appendable and versioned**: :func:`append` folds a host
delta frame into a registered table under an ATOMIC swap (a concurrent
reader holds the old :class:`~cylon_tpu.table.Table` object and never
observes a half-applied delta), every mutation bumps a **monotone
generation number**, and :func:`table_version` exposes
``{generation, digest}`` where the digest is the content fingerprint
the fallback layer already uses to guard broadcast inputs
(:func:`cylon_tpu.fallback._cols_fingerprint`). Appended deltas are
retained in a bounded per-table log (:func:`deltas_since`) so a
materialized view can refresh from exactly the rows it has not applied
yet — and a watermark older than the retention window answers ``None``
(full recompute), never a silently truncated delta.
"""

import collections
import contextlib
import threading
from typing import Mapping, Sequence

from cylon_tpu.config import JoinConfig
from cylon_tpu.errors import FailedPrecondition, InvalidArgument, KeyError_
from cylon_tpu.table import Table

_lock = threading.Lock()
_catalog: dict[str, Table] = {}
#: table id -> Counter of holder labels (pin refcounts). A pinned table
#: cannot be dropped: the serving layer pins every resident table a
#: request reads for the request's lifetime, so a concurrent ``drop``
#: fails loudly at the drop site (naming the holders) instead of as a
#: confusing late KeyError inside whichever query lost the race.
_pins: "dict[str, collections.Counter]" = {}
#: table id -> {"generation": int, "digest": str | None}. Every
#: registration/append bumps the monotone generation; the content
#: digest is computed LAZILY (first :func:`table_version` call per
#: generation) because it hashes the table's host bytes.
_versions: "dict[str, dict]" = {}
#: table id -> [(generation, host pandas delta frame)] — the bounded
#: delta log :func:`deltas_since` serves incremental view refreshes
#: from (newest ``CYLON_TPU_CATALOG_DELTA_KEEP`` appends retained).
_deltas: "dict[str, list]" = {}
#: append listeners: ``cb(table_id, generation)`` after every
#: successful append — how the views layer invalidates result memos
#: keyed on the now-stale version without catalog importing views.
_append_listeners: list = []
#: serializes whole append operations (host gather + concat + swap);
#: the swap itself still happens under ``_lock``.
_append_mu = threading.Lock()

DEFAULT_DELTA_KEEP = 64


def put_table(table_id: str, table: Table) -> None:
    """Parity: ``PutTable`` (table_api.hpp:38). Re-registering an id is
    an overwrite — but not while the old table is pinned (an in-flight
    reader must never see its input swapped underneath it)."""
    if not isinstance(table, Table):
        raise InvalidArgument(f"not a Table: {type(table)}")
    with _lock:
        _require_unpinned(table_id, "overwrite")
        _catalog[table_id] = table
        _bump_version_locked(table_id)
        # a full overwrite restarts delta history: nothing in the old
        # log describes the new content, so views must full-recompute
        _deltas.pop(table_id, None)


def _bump_version_locked(table_id: str) -> int:
    """Advance ``table_id``'s monotone generation (digest recomputes
    lazily). Caller holds ``_lock``. Returns the new generation."""
    ent = _versions.get(table_id)
    gen = (int(ent["generation"]) + 1) if ent else 1
    _versions[table_id] = {"generation": gen, "digest": None}
    return gen


def get_table(table_id: str, pin_for: "str | None" = None) -> Table:
    """Parity: ``GetTable``. ``pin_for=holder`` additionally pins the
    table under ``holder`` in the same lock hold — the atomic
    lookup-and-pin a concurrent reader needs (a separate get + pin
    could lose a drop race in between)."""
    with _lock:
        if table_id not in _catalog:
            raise KeyError_(f"no table registered under {table_id!r}")
        if pin_for is not None:
            _pins.setdefault(table_id,
                             collections.Counter())[str(pin_for)] += 1
        return _catalog[table_id]


def _require_unpinned(table_id: str, verb: str) -> None:
    holders = _pins.get(table_id)
    if holders:
        names = sorted(holders)
        raise FailedPrecondition(
            f"cannot {verb} table {table_id!r}: pinned by "
            f"{sum(holders.values())} holder(s) {names}; drop waits "
            "until every holder unpins")


def pin(table_id: str, holder: str = "anonymous") -> None:
    """Refcount ``table_id`` under ``holder`` so :func:`drop` refuses
    it. Pins nest (one count per call); unpin with the same holder."""
    with _lock:
        if table_id not in _catalog:
            raise KeyError_(f"no table registered under {table_id!r}")
        _pins.setdefault(table_id, collections.Counter())[str(holder)] += 1


def unpin(table_id: str, holder: str = "anonymous") -> None:
    """Release one pin held by ``holder`` (unknown pins raise — an
    unbalanced unpin is a refcount bug, not a no-op)."""
    with _lock:
        holders = _pins.get(table_id)
        if not holders or holders[str(holder)] <= 0:
            raise InvalidArgument(
                f"table {table_id!r} holds no pin for {holder!r}")
        holders[str(holder)] -= 1
        if holders[str(holder)] <= 0:
            del holders[str(holder)]
        if not holders:
            _pins.pop(table_id, None)


@contextlib.contextmanager
def pinned(table_id: str, holder: str = "anonymous"):
    """``with catalog.pinned("lineitem", holder=req_id) as t:`` — the
    table, pinned for the scope (the per-request discipline
    :mod:`cylon_tpu.serve` applies around every query)."""
    t = get_table(table_id, pin_for=holder)
    try:
        yield t
    finally:
        unpin(table_id, holder)


def pins(table_id: str) -> "dict[str, int]":
    """Live pin counts per holder (empty when unpinned/unknown)."""
    with _lock:
        return dict(_pins.get(table_id, ()))


def drop(table_id: str, *, if_exists: bool = True) -> None:
    """Remove ``table_id`` — unless pinned, in which case a
    :class:`~cylon_tpu.errors.FailedPrecondition` NAMES the holders
    (the serve-layer contract: a resident table a query is reading
    cannot vanish mid-flight)."""
    with _lock:
        if table_id not in _catalog:
            if if_exists:
                return
            raise KeyError_(f"no table registered under {table_id!r}")
        _require_unpinned(table_id, "drop")
        del _catalog[table_id]
        _versions.pop(table_id, None)
        _deltas.pop(table_id, None)


def remove_table(table_id: str) -> None:
    """Parity: ``RemoveTable`` — now pin-respecting (see :func:`drop`)."""
    drop(table_id, if_exists=True)


def list_tables() -> list[str]:
    with _lock:
        return sorted(_catalog)


def table_nbytes(table: Table) -> int:
    """Device bytes held by ``table``'s buffers (data + validity),
    summed over columns — no host sync (buffer shapes are static)."""
    total = 0
    for c in table.columns.values():
        total += c.data.size * c.data.dtype.itemsize
        if c.validity is not None:
            total += c.validity.size * c.validity.dtype.itemsize
    return total


def table_device_nbytes(table: Table) -> "dict[str, int]":
    """Per-device byte split of ``table``'s buffers —
    ``{"tpu:0": n, ...}`` from each array's addressable shard layout
    (shard metadata only: no host sync, no transfer; the key scheme
    and host fallback are
    :func:`cylon_tpu.telemetry.memory.accumulate_array_bytes`, shared
    with the live-bytes walk so the two accountings cross-check).
    This is the split the serve ``/tables`` endpoint reports: on a
    distributed table it shows exactly how evenly the resident bytes
    spread over the mesh."""
    from cylon_tpu.telemetry.memory import accumulate_array_bytes

    out: "dict[str, int]" = {}
    for c in table.columns.values():
        accumulate_array_bytes(c.data, out)
        if c.validity is not None:
            accumulate_array_bytes(c.validity, out)
    return out


def stats() -> "dict[str, dict]":
    """Per-table catalog statistics: ``{id: {rows, bytes,
    bytes_by_device, capacity, columns, distributed, pins,
    holders}}`` — the resident-table
    inventory ``cylon_tpu.serve`` reports. ``rows`` is the true row
    count (summed across shards for distributed tables; one small host
    fetch per table); tables whose count is not host-reachable (e.g.
    under trace) report ``rows=None``."""
    import numpy as np

    from cylon_tpu.parallel import dtable

    with _lock:
        items = list(_catalog.items())
        pin_view = {k: dict(v) for k, v in _pins.items()}
    out = {}
    for tid, t in items:
        try:
            rows = int(np.asarray(t.nrows).sum())
        except Exception:
            rows = None
        try:
            version = table_version(tid)
        except Exception:
            # racing drop, or a table whose bytes are not
            # host-reachable (e.g. under trace) — report the
            # generation without a digest rather than failing stats
            with _lock:
                ent = _versions.get(tid) or {"generation": 1,
                                             "digest": None}
            version = {"generation": int(ent["generation"]),
                       "digest": ent["digest"]}
        holders = pin_view.get(tid, {})
        out[tid] = {
            "rows": rows,
            "bytes": table_nbytes(t),
            "bytes_by_device": table_device_nbytes(t),
            "capacity": int(t.capacity),
            "columns": t.num_columns,
            "distributed": bool(dtable.is_distributed(t)),
            "pins": sum(holders.values()),
            "holders": sorted(holders),
            # the version column (views subsystem): monotone
            # generation + content digest — what /tables shows and the
            # result-cache layers key invalidation on
            "version": version,
        }
    return out


def clear() -> None:
    """Drop everything, pins included (test/teardown hatch — the
    pin-respecting path is :func:`drop`)."""
    with _lock:
        _catalog.clear()
        _pins.clear()
        _versions.clear()
        _deltas.clear()


# -------------------------------------------------- versioned appends
def _table_digest(table: Table) -> str:
    """Content digest of a resident table — the SAME fingerprint the
    resumable fallback uses to guard changed broadcast inputs
    (:func:`cylon_tpu.fallback._cols_fingerprint`). Local tables hash
    their trimmed host content (two tables with identical logical rows
    digest identically regardless of capacity padding); distributed
    tables hash the raw shard buffers plus the per-shard row counts
    (no env is available here to gather, and any append changes the
    buffers, which is what versioning needs)."""
    import numpy as np

    from cylon_tpu.fallback import _cols_fingerprint
    from cylon_tpu.parallel import dtable

    if dtable.is_distributed(table):
        cols = {name: np.asarray(c.data)
                for name, c in table.columns.items()}
        cols["__nrows__"] = np.asarray(table.nrows)
        return _cols_fingerprint(cols)
    pdf = table.to_pandas()
    return _cols_fingerprint({c: pdf[c].to_numpy() for c in pdf.columns})


def generation(table_id: str) -> int:
    """The table's monotone generation number — one cheap dict read,
    no digest computation (the hot accessor view refreshes and
    generation-consistent serve reads poll)."""
    with _lock:
        if table_id not in _catalog:
            raise KeyError_(f"no table registered under {table_id!r}")
        ent = _versions.get(table_id)
        return int(ent["generation"]) if ent else 1


def table_version(table_id: str) -> dict:
    """``{"generation": int, "digest": str}`` for a resident table.
    The digest is computed lazily (it hashes the table's host bytes)
    and cached per generation — repeated calls between mutations are
    one dict read."""
    with _lock:
        if table_id not in _catalog:
            raise KeyError_(f"no table registered under {table_id!r}")
        t = _catalog[table_id]
        ent = _versions.setdefault(
            table_id, {"generation": 1, "digest": None})
        gen, digest = int(ent["generation"]), ent["digest"]
    if digest is None:
        digest = _table_digest(t)
        with _lock:
            cur = _versions.get(table_id)
            # only cache onto the generation we hashed — a racing
            # append's newer generation must not inherit a stale digest
            if cur is not None and int(cur["generation"]) == gen:
                cur["digest"] = digest
    return {"generation": gen, "digest": digest}


def restore_version(table_id: str, gen: int) -> None:
    """Reinstate a table's generation after a snapshot restore
    (:meth:`cylon_tpu.serve.ServeEngine.recover`): the recovered
    process must serve the POST-append generation the snapshot was
    taken at, not restart at 1 and silently alias the pre-append
    version."""
    with _lock:
        if table_id not in _catalog:
            raise KeyError_(f"no table registered under {table_id!r}")
        _versions[table_id] = {"generation": max(int(gen), 1),
                               "digest": None}


def _as_host_frame(delta):
    """Normalize an append delta (pandas frame, cylon DataFrame/Table,
    or a {col: array} mapping) to a host pandas frame."""
    import numpy as np
    import pandas as pd

    if isinstance(delta, pd.DataFrame):
        return delta.reset_index(drop=True)
    t = getattr(delta, "table", delta)
    if isinstance(t, Table):
        return t.to_pandas().reset_index(drop=True)
    if isinstance(delta, Mapping):
        return pd.DataFrame({k: np.asarray(v) for k, v in delta.items()})
    raise InvalidArgument(
        f"cannot append a {type(delta).__name__}: pass a pandas frame, "
        "a DataFrame/Table, or a column mapping")


def _delta_keep() -> int:
    import os

    try:
        return int(os.environ.get("CYLON_TPU_CATALOG_DELTA_KEEP",
                                  str(DEFAULT_DELTA_KEEP)))
    except ValueError:
        return DEFAULT_DELTA_KEEP


def on_append(cb) -> None:
    """Register ``cb(table_id, generation)`` to run after every
    successful :func:`append` — the invalidation hook the views layer
    uses to evict memos keyed on the now-stale version, and (ISSUE 19)
    how the serve plane's versioned result caches drop exactly the
    cached results whose version vector names the appended table
    (:func:`cylon_tpu.serve.result_cache.hook_on_append`). Callbacks
    run outside the catalog locks; exceptions are swallowed (an
    observer must never fail a mutation)."""
    _append_listeners.append(cb)


def append(table_id: str, delta, *, env=None) -> dict:
    """Fold ``delta`` rows into resident table ``table_id`` under an
    atomic swap, bumping its generation.

    Unlike :func:`put_table`'s overwrite, append is legal while the
    table is PINNED: an in-flight reader holds the old
    :class:`~cylon_tpu.table.Table` object, which is immutable — it
    finishes against the generation it started on and never observes a
    half-applied delta. The swap publishes the merged table and the
    new generation in one ``_lock`` hold.

    ``delta`` may be a pandas frame, a DataFrame/Table, or a
    ``{col: array}`` mapping; its columns must match the resident
    schema. Distributed targets need ``env=`` (gather → concat →
    re-scatter). The host delta is retained in the bounded per-table
    log (:func:`deltas_since`) for incremental view refresh. Returns
    ``{"generation", "delta_rows", "rows"}``.
    """
    import pandas as pd

    from cylon_tpu import telemetry
    from cylon_tpu.parallel import dtable
    from cylon_tpu.telemetry import events as _events

    pdf = _as_host_frame(delta)
    with _append_mu:
        with _lock:
            if table_id not in _catalog:
                raise KeyError_(
                    f"no table registered under {table_id!r}")
            cur = _catalog[table_id]
        distributed = bool(dtable.is_distributed(cur))
        if distributed:
            if env is None:
                raise InvalidArgument(
                    f"append to distributed table {table_id!r} needs "
                    "env= (gather + re-scatter run on the mesh)")
            from cylon_tpu.parallel import dist_to_pandas

            base = dist_to_pandas(env, cur)
        else:
            base = cur.to_pandas()
        if set(pdf.columns) != set(base.columns):
            raise InvalidArgument(
                f"append({table_id!r}): delta columns "
                f"{sorted(pdf.columns)} != resident schema "
                f"{sorted(base.columns)}")
        pdf = pdf[list(base.columns)]
        merged = (pd.concat([base, pdf], ignore_index=True)
                  if len(pdf) else base)
        new = Table.from_pydict(
            {c: merged[c].to_numpy() for c in merged.columns},
            capacity=None if len(merged) else 1)
        if distributed:
            from cylon_tpu.parallel import scatter_table

            new = scatter_table(env, new)
        # the build above happened OUTSIDE _lock (readers kept going);
        # the swap itself is one lock hold: table, generation and the
        # delta-log entry publish together
        with _lock:
            if table_id not in _catalog:
                raise KeyError_(
                    f"table {table_id!r} dropped during append")
            _catalog[table_id] = new
            gen = _bump_version_locked(table_id)
            log = _deltas.setdefault(table_id, [])
            log.append((gen, pdf.reset_index(drop=True)))
            keep = _delta_keep()
            if keep >= 0 and len(log) > keep:
                del log[:len(log) - keep]
    telemetry.counter("catalog.appends", table=table_id).inc()
    _events.emit("append", table=table_id, generation=gen,
                 delta_rows=int(len(pdf)))
    for cb in list(_append_listeners):
        try:
            cb(table_id, gen)
        except Exception:  # pragma: no cover - observer must not fail
            pass
    return {"generation": gen, "delta_rows": int(len(pdf)),
            "rows": int(len(merged))}


def deltas_since(table_id: str, gen: int) -> "list | None":
    """Host delta frames appended after generation ``gen``, oldest
    first — the exact rows a view at watermark ``gen`` has not applied
    yet. Returns ``[]`` when the watermark is current, and ``None``
    when the retention window (or an intervening full
    :func:`put_table` overwrite) no longer covers the span — the
    caller must full-recompute, never silently under-apply."""
    with _lock:
        if table_id not in _catalog:
            raise KeyError_(f"no table registered under {table_id!r}")
        ent = _versions.get(table_id)
        cur = int(ent["generation"]) if ent else 1
        log = list(_deltas.get(table_id, ()))
    gen = int(gen)
    if gen >= cur:
        return []
    got = {g: f for g, f in log}
    want = range(gen + 1, cur + 1)
    if any(g not in got for g in want):
        return None
    return [got[g] for g in want]


# ---------------------------------------------------------------- id ops
def read_csv(table_id: str, path, **kw) -> None:
    """Parity: ``ReadCSV(ctx, path, id)`` (table_api.hpp)."""
    from cylon_tpu.io import read_csv as _read

    put_table(table_id, _read(path, **kw).to_table())


def join_tables(left_id: str, right_id: str, out_id: str,
                config: JoinConfig | None = None, *, on=None,
                how: str = "inner", env=None, **kw) -> None:
    """Parity: ``JoinTables(ctx, "left", "right", ...)``
    (table_api.hpp:46)."""
    from cylon_tpu.ops.join import join
    from cylon_tpu.parallel import dist_join

    lt, rt = get_table(left_id), get_table(right_id)
    if config is not None:
        on = None
        kw.setdefault("left_on", list(config.left_on))
        kw.setdefault("right_on", list(config.right_on))
        how = config.join_type.value
    if env is not None:
        out = dist_join(env, lt, rt, on=on, how=how, **kw)
    else:
        out = join(lt, rt, on=on, how=how, **kw)
    put_table(out_id, out)


def _binary(op_name: str):
    def run(left_id: str, right_id: str, out_id: str, env=None, **kw):
        from cylon_tpu.ops import setops
        from cylon_tpu.parallel import dist_ops

        lt, rt = get_table(left_id), get_table(right_id)
        if env is not None:
            fn = getattr(dist_ops, f"dist_{op_name}")
            put_table(out_id, fn(env, lt, rt, **kw))
        else:
            fn = getattr(setops, op_name)
            put_table(out_id, fn(lt, rt, **kw))
    run.__name__ = f"{op_name}_tables"
    run.__doc__ = f"Parity: table_api {op_name.capitalize()}Tables."
    return run


union_tables = _binary("union")
intersect_tables = _binary("intersect")
subtract_tables = _binary("subtract")


def sort_table(table_id: str, out_id: str, by, env=None, **kw) -> None:
    """Parity: table_api Sort/DistributedSort."""
    from cylon_tpu.ops.selection import sort_table as _sort
    from cylon_tpu.parallel import dist_sort

    t = get_table(table_id)
    by = [by] if isinstance(by, str) else list(by)
    if env is not None:
        put_table(out_id, dist_sort(env, t, by, **kw))
    else:
        put_table(out_id, _sort(t, by, **kw))


def unique_table(table_id: str, out_id: str, cols=None, env=None, **kw
                 ) -> None:
    """Parity: table_api Unique/DistributedUnique."""
    from cylon_tpu.ops import setops
    from cylon_tpu.parallel import dist_unique

    t = get_table(table_id)
    if env is not None:
        put_table(out_id, dist_unique(env, t, cols, **kw))
    else:
        put_table(out_id, setops.unique(t, cols, **kw))


def select_columns(table_id: str, out_id: str, names: Sequence[str]) -> None:
    """Parity: table_api Project."""
    put_table(out_id, get_table(table_id).select(list(names)))


def table_to_pydict(table_id: str) -> Mapping[str, list]:
    return get_table(table_id).to_pydict()


# --------------------------------------------------------- native bridge
def to_native(table_id: str) -> None:
    """Copy a catalog entry into the native C-ABI registry
    (``cylon_catalog_*`` in ``native/cylon_host.cpp``) where any FFI
    host — the JNI-style binding surface — can read it."""
    from cylon_tpu import native

    native.catalog_put(table_id, get_table(table_id))


def from_native(table_id: str) -> None:
    """Import a table published in the native registry into this
    catalog (reverse direction of :func:`to_native`)."""
    from cylon_tpu import native

    put_table(table_id, native.catalog_get(table_id))
