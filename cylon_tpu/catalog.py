"""String-id table catalog + id-keyed operation mirror.

Parity target: ``cpp/src/cylon/table_api.{hpp,cpp}`` — a process-global
registry mapping string ids to tables (``PutTable/GetTable/RemoveTable``,
``table_api.hpp:38-90``) with every relational op mirrored on ids
(``JoinTables(ctx, "left", "right", ...)``). In the reference this layer
exists to give the Java JNI binding a stable C surface; here it is the
FFI/embedding surface for non-Python hosts of the TPU runtime.
"""

import threading
from typing import Mapping, Sequence

from cylon_tpu.config import JoinConfig
from cylon_tpu.errors import InvalidArgument, KeyError_
from cylon_tpu.table import Table

_lock = threading.Lock()
_catalog: dict[str, Table] = {}


def put_table(table_id: str, table: Table) -> None:
    """Parity: ``PutTable`` (table_api.hpp:38)."""
    if not isinstance(table, Table):
        raise InvalidArgument(f"not a Table: {type(table)}")
    with _lock:
        _catalog[table_id] = table


def get_table(table_id: str) -> Table:
    """Parity: ``GetTable``."""
    with _lock:
        if table_id not in _catalog:
            raise KeyError_(f"no table registered under {table_id!r}")
        return _catalog[table_id]


def remove_table(table_id: str) -> None:
    """Parity: ``RemoveTable``."""
    with _lock:
        _catalog.pop(table_id, None)


def list_tables() -> list[str]:
    with _lock:
        return sorted(_catalog)


def clear() -> None:
    with _lock:
        _catalog.clear()


# ---------------------------------------------------------------- id ops
def read_csv(table_id: str, path, **kw) -> None:
    """Parity: ``ReadCSV(ctx, path, id)`` (table_api.hpp)."""
    from cylon_tpu.io import read_csv as _read

    put_table(table_id, _read(path, **kw).to_table())


def join_tables(left_id: str, right_id: str, out_id: str,
                config: JoinConfig | None = None, *, on=None,
                how: str = "inner", env=None, **kw) -> None:
    """Parity: ``JoinTables(ctx, "left", "right", ...)``
    (table_api.hpp:46)."""
    from cylon_tpu.ops.join import join
    from cylon_tpu.parallel import dist_join

    lt, rt = get_table(left_id), get_table(right_id)
    if config is not None:
        on = None
        kw.setdefault("left_on", list(config.left_on))
        kw.setdefault("right_on", list(config.right_on))
        how = config.join_type.value
    if env is not None:
        out = dist_join(env, lt, rt, on=on, how=how, **kw)
    else:
        out = join(lt, rt, on=on, how=how, **kw)
    put_table(out_id, out)


def _binary(op_name: str):
    def run(left_id: str, right_id: str, out_id: str, env=None, **kw):
        from cylon_tpu.ops import setops
        from cylon_tpu.parallel import dist_ops

        lt, rt = get_table(left_id), get_table(right_id)
        if env is not None:
            fn = getattr(dist_ops, f"dist_{op_name}")
            put_table(out_id, fn(env, lt, rt, **kw))
        else:
            fn = getattr(setops, op_name)
            put_table(out_id, fn(lt, rt, **kw))
    run.__name__ = f"{op_name}_tables"
    run.__doc__ = f"Parity: table_api {op_name.capitalize()}Tables."
    return run


union_tables = _binary("union")
intersect_tables = _binary("intersect")
subtract_tables = _binary("subtract")


def sort_table(table_id: str, out_id: str, by, env=None, **kw) -> None:
    """Parity: table_api Sort/DistributedSort."""
    from cylon_tpu.ops.selection import sort_table as _sort
    from cylon_tpu.parallel import dist_sort

    t = get_table(table_id)
    by = [by] if isinstance(by, str) else list(by)
    if env is not None:
        put_table(out_id, dist_sort(env, t, by, **kw))
    else:
        put_table(out_id, _sort(t, by, **kw))


def unique_table(table_id: str, out_id: str, cols=None, env=None, **kw
                 ) -> None:
    """Parity: table_api Unique/DistributedUnique."""
    from cylon_tpu.ops import setops
    from cylon_tpu.parallel import dist_unique

    t = get_table(table_id)
    if env is not None:
        put_table(out_id, dist_unique(env, t, cols, **kw))
    else:
        put_table(out_id, setops.unique(t, cols, **kw))


def select_columns(table_id: str, out_id: str, names: Sequence[str]) -> None:
    """Parity: table_api Project."""
    put_table(out_id, get_table(table_id).select(list(names)))


def table_to_pydict(table_id: str) -> Mapping[str, list]:
    return get_table(table_id).to_pydict()


# --------------------------------------------------------- native bridge
def to_native(table_id: str) -> None:
    """Copy a catalog entry into the native C-ABI registry
    (``cylon_catalog_*`` in ``native/cylon_host.cpp``) where any FFI
    host — the JNI-style binding surface — can read it."""
    from cylon_tpu import native

    native.catalog_put(table_id, get_table(table_id))


def from_native(table_id: str) -> None:
    """Import a table published in the native registry into this
    catalog (reverse direction of :func:`to_native`)."""
    from cylon_tpu import native

    put_table(table_id, native.catalog_get(table_id))
