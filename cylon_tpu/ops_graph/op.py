"""Op base class: push-based dataflow node.

Parity: ``ops/api/parallel_op.hpp:32-183`` — ``Op`` holds per-tag input
queues, ``InsertTable(tag, table)`` enqueues, ``Progress()`` dequeues one
chunk and calls the subclass ``Execute``, results push to children;
``Finalize`` propagates once all parents finished. ``RootOp`` (the graph
sink) collects final tables and drives ``WaitForCompletion``
(``parallel_op.hpp:176``).
"""

import collections
from typing import Callable, Iterable, Optional

from cylon_tpu import telemetry
from cylon_tpu.errors import InvalidArgument
from cylon_tpu.table import Table


class TableChunk:
    """One unit of streamed work: a table plus its routing tag (the
    reference passes ``(tag, arrow::Table)`` pairs; tag = logical
    partition / relation id)."""

    __slots__ = ("tag", "table")

    def __init__(self, tag: int, table: Table):
        self.tag = tag
        self.table = table

    def __repr__(self):
        return f"TableChunk(tag={self.tag}, {self.table!r})"


class Op:
    """Dataflow node (parity: ``cylon::Op``, parallel_op.hpp:32).

    Subclasses override :meth:`execute` (one chunk in, zero or more
    chunks out) and optionally :meth:`on_finalize` (flush accumulated
    state). ``execute`` may also be given as a callable.
    """

    def __init__(self, op_id: int, execute: Optional[Callable] = None,
                 name: str | None = None):
        self.id = op_id
        self.name = name or type(self).__name__
        self._children: list[Op] = []
        self._parents: list[Op] = []
        self._queue: collections.deque[TableChunk] = collections.deque()
        self._finalized_parents = 0
        self._did_finalize = False
        self._execute_fn = execute
        #: chunks this op has processed (progress-loop visibility; the
        #: per-op twin of the ``ops_graph.chunks`` counter)
        self.processed = 0

    # -- graph wiring ----------------------------------------------------
    def add_child(self, child: "Op") -> "Op":
        """Parity: ``Op::AddChild`` (parallel_op.hpp:101)."""
        self._children.append(child)
        child._parents.append(self)
        return child

    @property
    def children(self) -> list["Op"]:
        return list(self._children)

    # -- data path -------------------------------------------------------
    def insert(self, tag: int, table: Table) -> None:
        """Parity: ``Op::InsertTable`` (parallel_op.hpp:120)."""
        if self._did_finalize:
            raise InvalidArgument(f"{self.name}: insert after finalize")
        self._queue.append(TableChunk(tag, table))

    def execute(self, tag: int, table: Table) -> Iterable[TableChunk]:
        """Process one chunk; yield output chunks. Parity:
        ``Op::Execute`` (parallel_op.hpp:128)."""
        if self._execute_fn is not None:
            out = self._execute_fn(tag, table)
            if out is None:
                return ()
            if isinstance(out, Table):
                return (TableChunk(tag, out),)
            return out
        return (TableChunk(tag, table),)  # identity

    def on_finalize(self) -> Iterable[TableChunk]:
        """Flush accumulated state when all inputs are done."""
        return ()

    # -- progress loop ---------------------------------------------------
    def progress(self) -> bool:
        """Process at most one queued chunk (parity: ``Op::Progress``,
        parallel_op.hpp:128-144). Returns True if work was done.
        Each processed chunk counts into ``ops_graph.chunks{op=}``
        (tenant-labeled under an ambient
        :func:`cylon_tpu.telemetry.tenant_scope`), so a mixed serving
        workload's streaming progress is attributable per tenant."""
        if not self._queue:
            return False
        chunk = self._queue.popleft()
        for out in self.execute(chunk.tag, chunk.table):
            self._emit(out)
        self.processed += 1
        telemetry.counter("ops_graph.chunks", op=self.name,
                          **telemetry.tenant_labels()).inc()
        return True

    def _emit(self, chunk: TableChunk) -> None:
        for child in self._children:
            child.insert(chunk.tag, chunk.table)

    @property
    def has_work(self) -> bool:
        return bool(self._queue)

    def done(self) -> bool:
        """Parity: ``Op::IsComplete`` — finalized and drained."""
        return self._did_finalize and not self._queue

    # -- finalize protocol ----------------------------------------------
    def finish(self) -> None:
        """Signal end-of-stream from one parent (or the driver, for
        sources). Parity: the reference's finalize propagation
        (parallel_op.hpp:146-162)."""
        self._finalized_parents += 1
        needed = max(len(self._parents), 1)
        if self._finalized_parents >= needed and not self._did_finalize:
            # drain remaining queue first
            while self.progress():
                pass
            for out in self.on_finalize():
                self._emit(out)
            self._did_finalize = True
            for child in self._children:
                child.finish()

    def __repr__(self):
        return (f"{self.name}(id={self.id}, queued={len(self._queue)}, "
                f"final={self._did_finalize})")


class RootOp(Op):
    """Graph sink collecting result chunks (parity: ``RootOp``,
    parallel_op.hpp:166-183)."""

    def __init__(self, op_id: int = 0, callback: Optional[Callable] = None):
        super().__init__(op_id, name="RootOp")
        self.results: list[TableChunk] = []
        self._callback = callback

    def execute(self, tag: int, table: Table):
        self.results.append(TableChunk(tag, table))
        if self._callback is not None:
            self._callback(tag, table)
        return ()

    def wait_for_completion(self, execution) -> list[TableChunk]:
        """Drive ``execution`` until the whole graph drains (parity:
        ``RootOp::WaitForCompletion`` → ``Execution::IsComplete`` loop,
        execution.hpp:33-37)."""
        while not execution.is_complete():
            pass
        while self.progress():
            pass
        return self.results

    def tables(self) -> list[Table]:
        return [c.table for c in self.results]
