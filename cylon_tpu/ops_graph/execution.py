"""Execution strategies: the order in which op nodes are progressed.

Parity: ``ops/execution/execution.hpp:28-110`` — ``RoundRobinExecution``
(:43), ``PriorityExecution`` (weighted repeats, :57), ``JoinExecution``
(drain two subtrees, then the join tail, :83), ``SequentialExecution``
(:103). The reference spins these on the main thread between MPI
progress calls; here a progress step dispatches one chunk's (async) XLA
work, so the schedule controls how host→device transfer and device
compute interleave.
"""

from typing import Sequence

from cylon_tpu.ops_graph.op import Op


class Execution:
    """Parity: ``Execution`` (execution.hpp:28-37)."""

    def progress(self) -> bool:  # pragma: no cover - abstract
        raise NotImplementedError

    def is_complete(self) -> bool:
        """One scheduling sweep; True when every op is drained+finalized."""
        raise NotImplementedError


class RoundRobinExecution(Execution):
    """Each op progresses once per sweep (execution.hpp:43-55)."""

    def __init__(self, ops: Sequence[Op] = ()):
        self._ops = list(ops)

    def add_op(self, op: Op) -> None:
        self._ops.append(op)

    def progress(self) -> bool:
        did = False
        for op in self._ops:
            did |= op.progress()
        return did

    def is_complete(self) -> bool:
        self.progress()
        return all(op.done() for op in self._ops)


class PriorityExecution(Execution):
    """Ops progress proportionally to integer priorities
    (execution.hpp:57-81 — the reference expands priorities into a
    round-robin multiset)."""

    def __init__(self, ops_with_priority: Sequence[tuple[Op, int]]):
        self._ops = [op for op, _ in ops_with_priority]
        self._schedule: list[Op] = []
        for op, prio in ops_with_priority:
            self._schedule.extend([op] * max(int(prio), 1))

    def progress(self) -> bool:
        did = False
        for op in self._schedule:
            did |= op.progress()
        return did

    def is_complete(self) -> bool:
        self.progress()
        return all(op.done() for op in self._ops)


class SequentialExecution(Execution):
    """Fully drain each op before moving to the next
    (execution.hpp:103-110)."""

    def __init__(self, ops: Sequence[Op] = ()):
        self._ops = list(ops)

    def add_op(self, op: Op) -> None:
        self._ops.append(op)

    def progress(self) -> bool:
        for op in self._ops:
            if op.progress():
                return True
        return False

    def is_complete(self) -> bool:
        for op in self._ops:
            while op.progress():
                pass
        return all(op.done() for op in self._ops)


class JoinExecution(Execution):
    """Alternate between the two relation subtrees, then drain the join
    tail (execution.hpp:83-101)."""

    def __init__(self, left_ops: Sequence[Op], right_ops: Sequence[Op],
                 tail_ops: Sequence[Op]):
        self._left = list(left_ops)
        self._right = list(right_ops)
        self._tail = list(tail_ops)

    def progress(self) -> bool:
        did = False
        for l, r in zip(self._left, self._right):
            did |= l.progress()
            did |= r.progress()
        for extra in (self._left[len(self._right):],
                      self._right[len(self._left):]):
            for op in extra:
                did |= op.progress()
        for op in self._tail:
            did |= op.progress()
        return did

    def is_complete(self) -> bool:
        self.progress()
        return all(op.done()
                   for op in self._left + self._right + self._tail)
