"""Execution strategies: the order in which op nodes are progressed.

Parity: ``ops/execution/execution.hpp:28-110`` — ``RoundRobinExecution``
(:43), ``PriorityExecution`` (weighted repeats, :57), ``JoinExecution``
(drain two subtrees, then the join tail, :83), ``SequentialExecution``
(:103). The reference spins these on the main thread between MPI
progress calls; here a progress step dispatches one chunk's (async) XLA
work, so the schedule controls how host→device transfer and device
compute interleave.
"""

from typing import Sequence

from cylon_tpu.ops_graph.op import Op


class Execution:
    """Parity: ``Execution`` (execution.hpp:28-37).

    The reference constructs one Execution per query graph and drops it
    at completion. The serving layer (:mod:`cylon_tpu.serve`) instead
    keeps ONE long-lived Execution whose op set churns as requests are
    admitted and retired — hence :meth:`add_op` / :meth:`remove_op` on
    the mutable schedules (RoundRobin/Priority), which the reference
    never needed."""

    def progress(self) -> bool:  # pragma: no cover - abstract
        raise NotImplementedError

    def is_complete(self) -> bool:
        """One scheduling sweep; True when every op is drained+finalized."""
        raise NotImplementedError


class RoundRobinExecution(Execution):
    """Each op progresses once per sweep (execution.hpp:43-55) — the
    serve layer's fair-share default: every live query advances one
    step per sweep regardless of how many steps it still holds."""

    def __init__(self, ops: Sequence[Op] = ()):
        self._ops = list(ops)

    def add_op(self, op: Op) -> None:
        self._ops.append(op)

    def remove_op(self, op: Op) -> None:
        """Retire a completed op from the schedule (no-op if absent) —
        the long-lived serving loop retires finished queries instead of
        rebuilding the execution each sweep."""
        try:
            self._ops.remove(op)
        except ValueError:
            pass

    @property
    def ops(self) -> list[Op]:
        return list(self._ops)

    def progress(self) -> bool:
        did = False
        for op in list(self._ops):
            did |= op.progress()
        return did

    def is_complete(self) -> bool:
        self.progress()
        return all(op.done() for op in self._ops)


class PriorityExecution(Execution):
    """Ops progress proportionally to integer priorities
    (execution.hpp:57-81 — the reference expands priorities into a
    round-robin multiset). The serve layer maps tenant weight onto the
    priority: a weight-3 tenant's query takes three steps per sweep to
    a weight-1 tenant's one."""

    def __init__(self, ops_with_priority: Sequence[tuple[Op, int]] = ()):
        self._ops: list[Op] = []
        self._schedule: list[Op] = []
        for op, prio in ops_with_priority:
            self.add_op(op, prio)

    def add_op(self, op: Op, priority: int = 1) -> None:
        self._ops.append(op)
        self._schedule.extend([op] * max(int(priority), 1))

    def remove_op(self, op: Op) -> None:
        try:
            self._ops.remove(op)
        except ValueError:
            return
        self._schedule = [o for o in self._schedule if o is not op]

    @property
    def ops(self) -> list[Op]:
        return list(self._ops)

    def progress(self) -> bool:
        did = False
        for op in list(self._schedule):
            did |= op.progress()
        return did

    def is_complete(self) -> bool:
        self.progress()
        return all(op.done() for op in self._ops)


class SequentialExecution(Execution):
    """Fully drain each op before moving to the next
    (execution.hpp:103-110)."""

    def __init__(self, ops: Sequence[Op] = ()):
        self._ops = list(ops)

    def add_op(self, op: Op) -> None:
        self._ops.append(op)

    def progress(self) -> bool:
        for op in self._ops:
            if op.progress():
                return True
        return False

    def is_complete(self) -> bool:
        for op in self._ops:
            while op.progress():
                pass
        return all(op.done() for op in self._ops)


class JoinExecution(Execution):
    """Alternate between the two relation subtrees, then drain the join
    tail (execution.hpp:83-101)."""

    def __init__(self, left_ops: Sequence[Op], right_ops: Sequence[Op],
                 tail_ops: Sequence[Op]):
        self._left = list(left_ops)
        self._right = list(right_ops)
        self._tail = list(tail_ops)

    def progress(self) -> bool:
        did = False
        for l, r in zip(self._left, self._right):
            did |= l.progress()
            did |= r.progress()
        for extra in (self._left[len(self._right):],
                      self._right[len(self._left):]):
            for op in extra:
                did |= op.progress()
        for op in self._tail:
            did |= op.progress()
        return did

    def is_complete(self) -> bool:
        self.progress()
        return all(op.done()
                   for op in self._left + self._right + self._tail)
