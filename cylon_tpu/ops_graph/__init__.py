"""Op-graph streaming execution engine (the reference's L7).

Parity target: ``cpp/src/cylon/ops/`` — push-based dataflow of ``Op``
nodes with per-child input queues and finalize propagation
(``ops/api/parallel_op.hpp:32-183``), pluggable execution strategies
(``ops/execution/execution.hpp:28-110``), and the prebuilt distributed
graphs ``DisJoinOP``/``DisUnionOp`` (``ops/dis_join_op.cpp:21-72``).

TPU redesign: the reference streams Arrow table chunks between threads
to overlap partition/shuffle/local-join. Here a chunk is a
capacity-bounded device table; streaming overlaps **host→device ingest
with device compute** (XLA dispatch is async — enqueueing chunk k+1's
kernels while chunk k executes keeps both DMA and compute busy), and
the per-chunk ops are the same fused jit programs used by the eager
path, so the op graph adds pipelining without a second kernel library.
"""

from cylon_tpu.ops_graph.op import Op, RootOp, TableChunk
from cylon_tpu.ops_graph.execution import (
    Execution,
    JoinExecution,
    PriorityExecution,
    RoundRobinExecution,
    SequentialExecution,
)
from cylon_tpu.ops_graph.graph import (
    DisJoinOp,
    chunk_stream,
    DisUnionOp,
    GroupByOp,
    JoinOp,
    PartitionOp,
    ShuffleOp,
    UnionOp,
)

__all__ = [
    "DisJoinOp",
    "chunk_stream",
    "DisUnionOp",
    "Execution",
    "GroupByOp",
    "JoinExecution",
    "JoinOp",
    "Op",
    "PartitionOp",
    "ShuffleOp",
    "PriorityExecution",
    "RootOp",
    "RoundRobinExecution",
    "SequentialExecution",
    "TableChunk",
    "UnionOp",
]
