"""Concrete streaming ops + prebuilt distributed graphs.

Parity: ``cpp/src/cylon/ops/`` kernels and builders — ``PartitionOp``
(``ops/partition_op.cpp``), ``JoinOp``/``UnionOp`` (``ops/join_op.cpp``,
``ops/union_op.cpp``), and the graph builders ``DisJoinOP``/``DisUnionOp``
(``ops/dis_join_op.cpp:21-72``: per-relation chain partition → shuffle →
split → shared join).

Two execution modes:

* local (``env=None``): the shuffle/split stages collapse into tag
  routing (a chunk's tag IS its logical partition) — data movement
  between logical partitions inside one host is free;
* distributed (``env=CylonEnv``): :class:`ShuffleOp` runs the REAL mesh
  all-to-all per chunk as it arrives, and the terminal op finishes with
  shard-local compute on the key-co-located accumulation
  (``parallel.dist_ops.colocated_join/unique``) — the reference's
  incremental exchange with its comm/compute overlap, on ICI.
"""

from typing import Callable, Iterable, Sequence

import jax.numpy as jnp
import numpy as np

from cylon_tpu.ops import setops as _setops
from cylon_tpu.ops.groupby import groupby_aggregate
from cylon_tpu.ops.hash import partition_ids
from cylon_tpu.ops.join import join as _join
from cylon_tpu.ops.selection import concat_tables, filter_table
from cylon_tpu.ops_graph.op import Op, RootOp, TableChunk
from cylon_tpu.table import Table


def chunk_stream(table: Table, chunk_rows: int) -> Iterable[Table]:
    """Slice a host-backed table into capacity-``chunk_rows`` chunks (the
    ingest side of the streaming pipeline; parity: the reference streams
    arrow record batches). Each chunk is a cooperative deadline
    checkpoint: a streamed ingest running inside an ambient
    :func:`cylon_tpu.watchdog.deadline` scope (a serve request's SLO,
    an OOC pass's budget) raises promptly between chunks instead of
    streaming past an expired deadline — attributed to the enclosing
    watched section, whichever layer that is."""
    from cylon_tpu import watchdog

    n = table.num_rows
    for lo in range(0, max(n, 1), chunk_rows):
        watchdog.check(detail="chunk_stream")
        hi = min(lo + chunk_rows, n)
        idx = jnp.arange(lo, lo + chunk_rows, dtype=jnp.int32)
        from cylon_tpu.ops.selection import take_columns

        yield take_columns(table, jnp.clip(idx, 0, max(n - 1, 0)), hi - lo)


class PartitionOp(Op):
    """Hash-partition each chunk into ``n_partitions`` sub-chunks, tagged
    by partition id (parity: ``ops/partition_op.cpp`` +
    ``ops/kernels/partition.cpp``)."""

    def __init__(self, op_id: int, key_cols: Sequence[str],
                 n_partitions: int):
        super().__init__(op_id, name="PartitionOp")
        self._keys = list(key_cols)
        self._n = n_partitions

    def execute(self, tag: int, table: Table):
        names = self._keys or table.column_names
        keys = [table.column(c).data for c in names]
        vals = [table.column(c).validity for c in names]
        pid = partition_ids(keys, self._n, vals)
        for p in range(self._n):
            yield TableChunk(p, filter_table(table, pid == p))


class ShuffleOp(Op):
    """The mesh exchange stage of the streaming graph: every incoming
    chunk immediately hash-shuffles over the device mesh
    (``parallel.dist_ops.shuffle`` — count exchange + ragged/padded
    all-to-all on ICI), emerging as a key-co-located DISTRIBUTED chunk.

    This is the true analog of the reference's AllToAllOp inside
    ``DisJoinOP`` (``ops/dis_join_op.cpp:34-71``): communication runs
    per chunk while the host slices and ingests the next one — the
    comm/compute overlap the reference's progress loop provides by
    hand, supplied here by XLA's async dispatch (each chunk's shuffle
    program is in flight on the mesh while Python prepares its
    successor; the explicit lossless capacity below keeps the path
    sync-free).
    """

    def __init__(self, op_id: int, key_cols: Sequence[str], env):
        super().__init__(op_id, name="ShuffleOp")
        self._keys = list(key_cols)
        self._env = env

    def execute(self, tag: int, table: Table):
        from cylon_tpu.parallel.dist_ops import shuffle

        keys = self._keys or table.column_names
        # lossless bound — a chunk can at worst land on one shard, so
        # out_l == chunk capacity always fits; explicit capacity means
        # no adaptive host sync and fully asynchronous dispatch
        out_cap = table.capacity * self._env.world_size
        yield TableChunk(tag, shuffle(self._env, table, keys,
                                      out_capacity=out_cap))


class _SidePort(Op):
    """Adapter routing chunks into one side of a binary op (the
    reference distinguishes relations by tag ranges in
    ``dis_join_op.cpp:34-71``; explicit ports are clearer)."""

    def __init__(self, op_id: int, target: "JoinOp", side: int):
        super().__init__(op_id, name=f"Port{side}")
        self._target = target
        self._side = side
        self.add_child(target)

    def execute(self, tag: int, table: Table):
        self._target.accept(self._side, tag, table)
        return ()


class JoinOp(Op):
    """Per-partition accumulate-then-join (parity: ``ops/join_op.cpp`` +
    ``ops/kernels/join_kernel.cpp`` — the reference also concatenates a
    relation's queued chunks before the local join)."""

    def __init__(self, op_id: int, env=None, **join_kw):
        super().__init__(op_id, name="JoinOp")
        self._kw = join_kw
        self._env = env
        self._buf: dict[int, tuple[list, list]] = {}

    def left_port(self, op_id: int) -> Op:
        return _SidePort(op_id, self, 0)

    def right_port(self, op_id: int) -> Op:
        return _SidePort(op_id, self, 1)

    def accept(self, side: int, tag: int, table: Table) -> None:
        self._buf.setdefault(tag, ([], []))[side].append(table)

    def on_finalize(self):
        for tag in sorted(self._buf):
            lefts, rights = self._buf[tag]
            if not lefts or not rights:
                # hash partitioning emits every partition (possibly empty)
                # per chunk, so a truly absent side means the relation got
                # no input at all
                continue
            if self._env is not None:
                # chunks are mesh-distributed and key-co-located
                # (ShuffleOp): concatenate shard-locally, join per shard
                from cylon_tpu.parallel import colocated_join, dist_concat

                lt = (dist_concat(self._env, lefts)
                      if len(lefts) > 1 else lefts[0])
                rt = (dist_concat(self._env, rights)
                      if len(rights) > 1 else rights[0])
                # defaulted capacities already regrow + verify inside
                # colocated_join; explicit overflow poison surfaces at
                # materialisation like every other dist op
                res = colocated_join(self._env, lt, rt, **self._kw)
                yield TableChunk(tag, res)
                continue
            lt = concat_tables(lefts) if len(lefts) > 1 else lefts[0]
            rt = concat_tables(rights) if len(rights) > 1 else rights[0]
            res = _join(lt, rt, **self._kw)
            res.num_rows  # raises OutOfCapacity on overflow (host-side)
            yield TableChunk(tag, res)


class UnionOp(Op):
    """Per-partition set union (parity: ``ops/union_op.cpp`` +
    ``ops/kernels/union_kernel``)."""

    def __init__(self, op_id: int, out_capacity: int | None = None,
                 env=None):
        super().__init__(op_id, name="UnionOp")
        self._buf: dict[int, list] = {}
        self._out_capacity = out_capacity
        self._env = env

    def execute(self, tag: int, table: Table):
        self._buf.setdefault(tag, []).append(table)
        return ()

    def on_finalize(self):
        for tag in sorted(self._buf):
            chunks = self._buf[tag]
            if self._env is not None:
                from cylon_tpu.parallel import (colocated_unique,
                                                dist_concat)

                t = (dist_concat(self._env, chunks)
                     if len(chunks) > 1 else chunks[0])
                yield TableChunk(tag, colocated_unique(
                    self._env, t, out_capacity=self._out_capacity))
                continue
            t = concat_tables(chunks) if len(chunks) > 1 else chunks[0]
            yield TableChunk(tag, _setops.unique(
                t, out_capacity=self._out_capacity))


class GroupByOp(Op):
    """Streaming groupby: each chunk is pre-combined on arrival (the
    partials are tiny), finalize re-aggregates — parity with the
    pre-combine → final combine structure of ``DistributedHashGroupBy``
    (``groupby/groupby.cpp:62-78``) applied to the chunk dimension."""

    _MERGE = {"sum": "sum", "count": "sum", "size": "sum",
              "min": "min", "max": "max"}

    def __init__(self, op_id: int, by: Sequence[str], aggs,
                 out_capacity: int | None = None, env=None):
        super().__init__(op_id, name="GroupByOp")
        self._by = list(by)
        self._aggs = [(a[0], a[1], a[2] if len(a) > 2 else f"{a[0]}_{a[1]}")
                      for a in (tuple(x) for x in aggs)]
        self._out_capacity = out_capacity
        self._decomposable = all(op in self._MERGE
                                 for _, op, _ in self._aggs)
        self._env = env
        self._buf: dict[int, list] = {}

    def execute(self, tag: int, table: Table):
        if self._decomposable:
            part = groupby_aggregate(
                table, self._by,
                [(src, op, out) for src, op, out in self._aggs])
        else:
            part = table
        if self._env is not None:
            # mesh mode: shuffle the (tiny) partials / raw rows so equal
            # keys co-locate; the per-chunk collective is in flight
            # while the next chunk pre-combines (the reference's
            # comm/compute overlap)
            from cylon_tpu.parallel.dist_ops import shuffle

            part = shuffle(self._env, part, self._by,
                           out_capacity=part.capacity
                           * self._env.world_size)
        self._buf.setdefault(tag, []).append(part)
        return ()

    def on_finalize(self):
        for tag in sorted(self._buf):
            chunks = self._buf[tag]
            if self._decomposable:
                final = [(out, self._MERGE[op], out)
                         for _, op, out in self._aggs]
            else:
                final = self._aggs
            if self._env is not None:
                from cylon_tpu.parallel import (colocated_groupby,
                                                dist_concat)

                t = (dist_concat(self._env, chunks)
                     if len(chunks) > 1 else chunks[0])
                yield TableChunk(tag, colocated_groupby(
                    self._env, t, self._by, final,
                    out_capacity=self._out_capacity))
                continue
            t = concat_tables(chunks) if len(chunks) > 1 else chunks[0]
            yield TableChunk(tag, groupby_aggregate(
                t, self._by, final, out_capacity=self._out_capacity))


class DisJoinOp:
    """Prebuilt join graph (parity: ``DisJoinOP``, dis_join_op.cpp:21-72:
    per relation partition → [shuffle] → shared join → callback).

    ``n_partitions`` logical partitions bound per-partition working-set
    size (the reference's parallelism knob); chunks stream through
    ``insert_left/right`` and results arrive at the root after
    ``finish()``.
    """

    def __init__(self, key_cols: Sequence[str] | str, n_partitions: int = 4,
                 callback: Callable | None = None, env=None, **join_kw):
        keys = [key_cols] if isinstance(key_cols, str) else list(key_cols)
        join_kw.setdefault("on", keys if len(keys) > 1 else keys[0])
        self.root = RootOp(0, callback)
        self.join = JoinOp(1, env=env, **join_kw)
        self.join.add_child(self.root)
        lport = self.join.left_port(2)
        rport = self.join.right_port(3)
        if env is not None:
            # distributed graph: the exchange stage is a real mesh
            # all-to-all per chunk; the mesh IS the partitioning, so
            # logical sub-partitioning is unnecessary
            self.left_partition = ShuffleOp(4, keys, env)
            self.right_partition = ShuffleOp(5, keys, env)
        else:
            self.left_partition = PartitionOp(4, keys, n_partitions)
            self.right_partition = PartitionOp(5, keys, n_partitions)
        self.left_partition.add_child(lport)
        self.right_partition.add_child(rport)
        self.ops = [self.left_partition, self.right_partition, lport, rport,
                    self.join, self.root]
        self._env = env

    def insert_left(self, table: Table, tag: int = 0):
        self.left_partition.insert(tag, table)

    def insert_right(self, table: Table, tag: int = 0):
        self.right_partition.insert(tag, table)

    def finish(self):
        self.left_partition.finish()
        self.right_partition.finish()

    def result(self, execution=None) -> Table:
        """Drive to completion and concatenate per-partition results."""
        from cylon_tpu.ops_graph.execution import JoinExecution

        if execution is None:
            execution = JoinExecution(
                [self.left_partition], [self.right_partition],
                [self.join, self.root])
        self.finish()
        chunks = self.root.wait_for_completion(execution)
        tables = [c.table for c in chunks]
        if not tables:
            raise ValueError("join produced no partitions")
        if len(tables) == 1:
            return tables[0]
        if self._env is not None:
            from cylon_tpu.parallel import dist_concat

            return dist_concat(self._env, tables)
        return concat_tables(tables)


class DisUnionOp:
    """Prebuilt union graph (parity: ``DisUnionOp``,
    ``ops/dis_union_op.cpp``)."""

    def __init__(self, n_partitions: int = 4,
                 callback: Callable | None = None,
                 out_capacity: int | None = None,
                 key_cols: Sequence[str] | None = None, env=None):
        self.root = RootOp(0, callback)
        self.union = UnionOp(1, out_capacity, env=env)
        self.union.add_child(self.root)
        self._keys = key_cols
        self._n = n_partitions
        self._env = env
        self._partitions: list[Op] = []

    def add_input(self, key_cols: Sequence[str] | None = None) -> Op:
        keys = list(key_cols or self._keys or ())
        if self._env is not None:
            p = ShuffleOp(10 + len(self._partitions), keys, self._env)
        else:
            p = PartitionOp(10 + len(self._partitions), keys, self._n)
        p.add_child(self.union)
        self._partitions.append(p)
        return p

    def finish(self):
        for p in self._partitions:
            p.finish()

    def result(self, execution=None) -> Table:
        from cylon_tpu.ops_graph.execution import RoundRobinExecution

        if execution is None:
            execution = RoundRobinExecution(
                self._partitions + [self.union, self.root])
        self.finish()
        chunks = self.root.wait_for_completion(execution)
        tables = [c.table for c in chunks]
        if not tables:
            raise ValueError("union produced no partitions")
        if len(tables) == 1:
            return tables[0]
        if self._env is not None:
            from cylon_tpu.parallel import dist_concat

            return dist_concat(self._env, tables)
        return concat_tables(tables)
