"""Delta-merge algebra for incremental view maintenance.

The observation this subsystem is built on (ROADMAP item 3): the
fallback layer's partial-merge combiners
(:func:`cylon_tpu.fallback._merge_partials` and the two-phase plans in
:mod:`cylon_tpu.tpch.twophase`) are *delta-apply operators*. A merge
spec that recombines per-partition partials of a hash-partitioned run
recombines, for exactly the same algebraic reason, a resident result
with the result of the SAME query run on an appended delta:

* ``merge == "sum"`` — a scalar aggregate: state' = state + delta.
* ``merge == "groupby"`` — associative re-aggregation (sum/min/max)
  plus count-weighted means (``("wmean", weight_col)``): groups
  present in both sides re-aggregate, new groups appear.
* ``merge == "concat"`` — order-refining plans whose output rows each
  derive from one partition-closed key group (e.g. q3's per-order
  revenue, partitioned by orderkey): concat + stable resort. The view
  keeps its state UNTRUNCATED (the query's row limit re-applies at
  read time via :func:`present`), so the merge is exact even for
  top-k queries.
* ``merge == "twophase"`` — global-scalar plans: the view's state is
  the *associative phase-1 partial* (sum/count pairs, per-group sums),
  combined by :func:`combine_partials` and only finalized into the
  blocking scalar at read time by :func:`finalize_twophase`.

Exactness contract (documented per-query in ``docs/views.md``): a
delta must be **partition-closed** for the spec's partition keys —
every key group lands entirely in the base or entirely in one delta.
TPC-H's RF1 refresh stream satisfies this by construction (new orders
arrive with all their lineitems).
"""

import numpy as np
import pandas as pd

from cylon_tpu.errors import InvalidArgument

__all__ = ["merge_delta", "present", "combine_partials",
           "finalize_twophase", "TWOPHASE_COMBINE_BY"]


def merge_delta(state, delta_partial, spec: dict):
    """Fold one delta partial into a view's resident state per the
    manifest merge spec — :func:`fallback._merge_partials` run over
    ``[state, delta_partial]`` with NO row limit (state stays
    untruncated; :func:`present` re-applies the query's limit), or the
    scalar/two-phase combine for those kinds. Either side may be
    ``None`` (an empty base or an all-filtered delta)."""
    from cylon_tpu.fallback import _merge_partials

    kind = spec["merge"]
    if kind == "twophase":
        return combine_partials(spec["query"],
                                [state, delta_partial])
    return _merge_partials([state, delta_partial], spec, None)


def present(state, spec: dict, limit=None):
    """The client-visible result of a view state: the spec's stable
    sort plus the query's row limit. Scalar states pass through. The
    state itself is never truncated — only its presentation."""
    if state is None or isinstance(state, float):
        return state
    if spec["merge"] == "twophase":
        return finalize_twophase(spec["query"], state)
    df = state
    sort = spec.get("sort")
    if sort:
        df = df.sort_values(
            sort, ascending=spec.get("ascending", [True] * len(sort)),
            kind="stable", ignore_index=True)
    if limit is not None:
        df = df.head(int(limit))
    return df.reset_index(drop=True)


#: two-phase queries whose phase-1 partial is view-maintainable, and
#: the group keys their partials re-combine under (``None`` = a
#: single-row frame of associative sums). Plans with a phase-2 apply
#: pass (q11/q15/q22) need their base tables at finalize time and are
#: NOT maintainable from the partial alone — absent here on purpose.
#: q16's partial is exact only for supplier-closed deltas (its
#: COUNT(DISTINCT) dedups inside one partial) — see docs/views.md.
TWOPHASE_COMBINE_BY: "dict[str, list | None]" = {
    "q8": ["o_year"],
    "q14": None,
    "q16": ["p_brand", "p_type", "p_size"],
}


def combine_partials(query: str, partials: list):
    """Associatively combine two-phase phase-1 partials: column-wise
    sums for single-row scalar partials (q14's promo/total revenue),
    per-group re-aggregation for per-group partials (q8's per-year
    totals, q16's per-brand distinct counts).
    ``None``/empty entries (an empty base or delta) contribute
    nothing."""
    by = TWOPHASE_COMBINE_BY.get(query)
    if query not in TWOPHASE_COMBINE_BY:
        raise InvalidArgument(
            f"{query!r} is not view-maintainable: its two-phase plan "
            "needs an apply pass over the base tables (maintainable: "
            f"{sorted(TWOPHASE_COMBINE_BY)})")
    fs = [f for f in partials if f is not None and len(f)]
    if not fs:
        return next((f for f in partials if f is not None), None)
    df = pd.concat(fs, ignore_index=True)
    if by is None:
        return pd.DataFrame([df.sum(axis=0)])
    return df.groupby(by, sort=False, as_index=False).sum()


def finalize_twophase(query: str, state, **params):
    """The blocking answer of a two-phase view from its combined
    associative state: the plan's global merge runs over the single
    combined partial, then its reduce unwraps the final scalar/frame
    (exactly the math the fallback executor journals as its merge
    unit)."""
    from cylon_tpu.tpch.twophase import PLANS

    plan = PLANS[query]
    if plan.phase2 is not None:
        raise InvalidArgument(
            f"{query!r}: plans with a phase-2 apply pass are not "
            "incrementally maintainable as views")
    if state is None:
        state = pd.DataFrame()
    merged = plan.merge([state if len(state) else None], **params)
    return plan.reduce(merged, None, **params)
