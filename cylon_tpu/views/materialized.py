"""Materialized views: named incremental queries over appendable,
version-digested catalog tables.

A registered view holds a RESIDENT host result (its *state*) plus a
per-source generation watermark. When :func:`cylon_tpu.catalog.append`
lands a delta on the view's delta source, :func:`refresh` runs the
view's query over **the delta rows only** (dimension sources ride
along in full, so join closure — RF1-style "new orders arrive with
their lineitems" — keeps the delta result exact) and folds the delta
partial into the state through the fallback merge combiners
(:mod:`cylon_tpu.views.combiners`). Cost per refresh is therefore
o(resident data): proportional to the delta, not the table.

**Consistency.** The state, its watermark and its content digest swap
under one view mutex hold — :func:`read` captures
``(result, generations, digest)`` atomically, so a serve read is
generation-consistent by construction: the returned result is exactly
the from-scratch answer at the returned generations, never a blend.
Appends invalidate the presented-result memo (and any
``query_fn.invalidate()`` plan memo — see
:meth:`cylon_tpu.plan.CompiledQuery.invalidate`) through the
catalog's on-append hook.

**Durability.** ``refresh(resume_dir=...)`` checkpoints through
:class:`cylon_tpu.resilience.CheckpointedRun` — unit 0 is the delta
partial, unit 1 the merged state, fingerprinted by (view, spec, base
and target generations, base-state digest). A hard kill mid-refresh
(the ``plan`` / ``global_merge`` injection points fire inside it)
resumes to a byte-identical state; the resident view is only swapped
AFTER the merge completes, so a killed refresh never corrupts it.

**Watermark semantics.** ``applied[delta_source]`` advances by exactly
the deltas applied; a watermark older than the catalog's delta
retention window (or an intervening full ``put_table`` overwrite)
triggers a full recompute — never a silent under-application.
"""

import threading
import time

import numpy as np
import pandas as pd

from cylon_tpu.errors import InvalidArgument, KeyError_
from cylon_tpu.views import combiners

__all__ = ["MaterializedView", "register_view", "refresh", "read",
           "view_version", "drop_view", "list_views", "stats",
           "clear"]

_reg_mu = threading.Lock()
_views: "dict[str, MaterializedView]" = {}

#: (table_id, generation) -> host pandas frame; bounded. Dimension
#: sources re-read every refresh would otherwise re-gather the full
#: table; the on-append hook evicts superseded generations.
_HOST_CACHE: "dict[tuple, object]" = {}
_HOST_CACHE_CAP = 16


class MaterializedView:
    """One registered view: query + merge spec + resident state."""

    __slots__ = ("name", "query_fn", "spec", "sources", "delta_source",
                 "limit", "env", "state", "applied", "state_digest",
                 "refreshes", "last_refresh_s", "_mu", "_present_memo")

    def __init__(self, name, query_fn, spec, sources, delta_source,
                 limit, env):
        self.name = str(name)
        self.query_fn = query_fn
        self.spec = spec
        self.sources = dict(sources)
        self.delta_source = delta_source
        self.limit = limit
        self.env = env
        self.state = None
        self.applied: "dict[str, int]" = {}
        self.state_digest = None
        self.refreshes = 0
        self.last_refresh_s = None
        self._mu = threading.Lock()
        self._present_memo = None


def _state_digest(state) -> str:
    """Content digest of a view state — the same fingerprint scheme
    the catalog versions tables with, so "byte-identical view" is a
    string comparison."""
    from cylon_tpu.fallback import _cols_fingerprint

    if state is None:
        return "empty"
    if isinstance(state, float):
        return _cols_fingerprint(
            {"__scalar__": np.asarray([state], np.float64)})
    return _cols_fingerprint(
        {c: state[c].to_numpy() for c in state.columns})


def _host_state(out):
    """Materialize a query_fn result to host state: frames to pandas,
    scalars to float."""
    if out is None or isinstance(out, float):
        return out
    if isinstance(out, pd.DataFrame):
        return out.reset_index(drop=True)
    if hasattr(out, "to_pandas"):
        return out.to_pandas().reset_index(drop=True)
    arr = np.asarray(out)
    if arr.ndim == 0:
        return float(arr)
    raise InvalidArgument(
        f"view query returned un-materializable {type(out).__name__}")


def _host_frame(table_id: str, env=None):
    """``(generation, host frame)`` of a catalog table, read
    consistently (generation re-checked after the fetch; retries a
    racing append) and cached per generation."""
    from cylon_tpu import catalog
    from cylon_tpu.serve.durability import CatalogSnapshot

    while True:
        gen = catalog.generation(table_id)
        key = (table_id, gen)
        hit = _HOST_CACHE.get(key)
        if hit is not None:
            return gen, hit
        t = catalog.get_table(table_id)
        pdf = CatalogSnapshot._host_frame(t, env)
        if catalog.generation(table_id) != gen:
            continue  # an append swapped the table under the fetch
        if len(_HOST_CACHE) >= _HOST_CACHE_CAP:
            _HOST_CACHE.pop(next(iter(_HOST_CACHE)), None)
        _HOST_CACHE[key] = pdf
        return gen, pdf


def _on_append(table_id: str, gen: int) -> None:
    """Catalog on-append hook: evict superseded host-frame cache
    entries and every dependent view's presented-result memo (the
    result memos keyed on the now-stale version), plus the view
    query's own plan memos when it exposes ``invalidate()``."""
    for key in [k for k in list(_HOST_CACHE)
                if k[0] == table_id and k[1] != gen]:
        _HOST_CACHE.pop(key, None)
    with _reg_mu:
        dependents = [v for v in _views.values()
                      if table_id in v.sources.values()]
    for v in dependents:
        with v._mu:
            v._present_memo = None
        inv = getattr(v.query_fn, "invalidate", None)
        if callable(inv):
            try:
                inv()
            except Exception:  # pragma: no cover - hook must not fail
                pass


def _install_hook() -> None:
    from cylon_tpu import catalog

    catalog.on_append(_on_append)


_install_hook()


def _view(name: str) -> MaterializedView:
    with _reg_mu:
        v = _views.get(str(name))
    if v is None:
        raise KeyError_(f"no view registered under {name!r} "
                        f"(known: {sorted(_views)})")
    return v


def _spec_fp(spec: dict) -> tuple:
    return tuple(sorted((k, repr(v)) for k, v in spec.items()))


def register_view(name: str, query_fn, refresh_plan: dict, *,
                  sources, delta_source: "str | None" = None,
                  limit=None, env=None) -> MaterializedView:
    """Register a materialized view and compute its initial state.

    ``query_fn(tables)`` takes ``{alias: host pandas frame}`` and
    returns the view's UNTRUNCATED merge-state partial (lift any row
    limit — reads re-apply it via ``limit=``); for two-phase plans it
    returns the associative phase-1 partial. ``refresh_plan`` is a
    fallback merge spec (:data:`cylon_tpu.tpch.manifest.FALLBACK`
    entry or hand-built: ``merge`` in sum/concat/groupby/twophase plus
    by/aggs/sort/distinct; twophase specs carry a ``query`` key naming
    the :data:`~cylon_tpu.tpch.twophase.PLANS` entry). ``sources``
    maps query aliases to catalog table ids; ``delta_source`` names
    the ONE appendable alias whose deltas drive incremental refresh
    (defaults to the spec's sole partitioned table) — other sources
    are join-closed dimensions. ``env`` gathers distributed sources.
    """
    name = str(name)
    spec = dict(refresh_plan)
    if spec.get("merge") not in ("sum", "concat", "groupby",
                                 "twophase"):
        raise InvalidArgument(
            f"refresh_plan merge {spec.get('merge')!r} not one of "
            "sum/concat/groupby/twophase")
    if spec["merge"] == "twophase":
        q = spec.get("query")
        if q not in combiners.TWOPHASE_COMBINE_BY:
            raise InvalidArgument(
                "twophase refresh_plan needs query= naming a "
                f"maintainable plan "
                f"{sorted(combiners.TWOPHASE_COMBINE_BY)}; got {q!r}")
    sources = dict(sources)
    if not sources:
        raise InvalidArgument("a view needs at least one source table")
    if delta_source is None:
        part = [a for a in spec.get("partition", {}) if a in sources]
        if len(part) == 1:
            delta_source = part[0]
        elif len(sources) == 1:
            delta_source = next(iter(sources))
        else:
            raise InvalidArgument(
                f"ambiguous delta_source among {sorted(sources)}; "
                "pass delta_source=")
    if delta_source not in sources:
        raise InvalidArgument(
            f"delta_source {delta_source!r} not in sources "
            f"{sorted(sources)}")
    v = MaterializedView(name, query_fn, spec, sources, delta_source,
                         limit, env)
    with _reg_mu:
        if name in _views:
            raise InvalidArgument(
                f"view {name!r} already registered; drop_view() first")
        _views[name] = v
    try:
        with v._mu:
            _recompute_locked(v)
    except BaseException:
        with _reg_mu:
            _views.pop(name, None)
        raise
    return v


def _recompute_locked(v: MaterializedView) -> dict:
    """Full from-scratch state compute (initial registration, or a
    watermark the delta log no longer covers). Caller holds ``v._mu``.
    The generation capture re-checks after the read so a racing append
    is either fully in the state or fully pending — never half."""
    from cylon_tpu import resilience

    while True:
        inputs, target = {}, {}
        for alias, tid in v.sources.items():
            target[alias], inputs[alias] = _host_frame(tid, v.env)
        from cylon_tpu import catalog

        if all(catalog.generation(tid) == target[a]
               for a, tid in v.sources.items()):
            break
    resilience.inject("plan", f"view.{v.name}.recompute")
    v.state = _host_state(v.query_fn(inputs))
    v.applied = target
    v.state_digest = _state_digest(v.state)
    v._present_memo = None
    return target


def _copartition_prune(v: MaterializedView, inputs: dict) -> None:
    """Semi-join pushdown over the spec's co-partition keys: the merge
    spec declares which sources hash-co-partition on a shared key
    domain (``spec["partition"]``, e.g. orders on ``o_orderkey`` with
    lineitem on ``l_orderkey``) — the exactness contract already
    requires every key group to land wholly in the base or wholly in
    one delta, so a co-partitioned dimension row whose key is absent
    from the delta CANNOT contribute to the delta-only result. Pruning
    those rows turns the refresh from O(dimension) into O(delta) — on
    an RF1 round the full orders table shrinks to just the new orders.
    Broadcast sources (no partition key) stay whole. In place, on
    fresh frames (the host cache is never mutated)."""
    part = v.spec.get("partition") or {}
    dkey = part.get(v.delta_source)
    dframe = inputs.get(v.delta_source)
    if (dkey is None or dframe is None
            or dkey not in getattr(dframe, "columns", ())):
        return
    dvals = dframe[dkey].unique()
    for alias, frame in list(inputs.items()):
        if alias == v.delta_source:
            continue
        akey = part.get(alias)
        if akey and akey in getattr(frame, "columns", ()):
            inputs[alias] = (frame[frame[akey].isin(dvals)]
                             .reset_index(drop=True))


def refresh(name: str, *, resume_dir: "str | None" = None,
            full: bool = False) -> dict:
    """Bring view ``name`` up to date with its sources' current
    generations. Incremental when the catalog's delta log covers the
    span (query over the delta only + combiner merge); full recompute
    when it does not (or ``full=True``). Returns ``{"view",
    "refreshed", "full_recompute", "delta_rows", "generations",
    "digest", "wall_s"}``."""
    from cylon_tpu import catalog, resilience, telemetry, watchdog
    from cylon_tpu.fallback import (_encode_partial,
                                    _partial_schema_meta,
                                    _resume_partial)
    from cylon_tpu.telemetry import events as _events

    v = _view(name)
    t0 = time.perf_counter()
    with v._mu:
        delta_tid = v.sources[v.delta_source]
        base_wm = int(v.applied.get(v.delta_source, 0))
        deltas = (None if full
                  else catalog.deltas_since(delta_tid, base_wm))
        full_recompute = deltas is None
        if full_recompute:
            target = _recompute_locked(v)
            delta_rows = None
        else:
            delta_rows = int(sum(len(f) for f in deltas))
            target = {a: catalog.generation(tid)
                      for a, tid in v.sources.items()
                      if a != v.delta_source}
            # the watermark advances by exactly the deltas applied —
            # an append racing this refresh stays pending
            target[v.delta_source] = base_wm + len(deltas)
            if target == v.applied:
                return {"view": v.name, "refreshed": False,
                        "full_recompute": False, "delta_rows": 0,
                        "generations": dict(v.applied),
                        "digest": v.state_digest, "wall_s": 0.0}
            if delta_rows:
                inputs = {a: _host_frame(tid, v.env)[1]
                          for a, tid in v.sources.items()
                          if a != v.delta_source}
                inputs[v.delta_source] = pd.concat(
                    deltas, ignore_index=True)
                _copartition_prune(v, inputs)
                ckpt = None
                if resume_dir is not None:
                    ckpt = resilience.CheckpointedRun(
                        resume_dir, f"view_{v.name}",
                        (_spec_fp(v.spec),
                         tuple(sorted(v.applied.items())),
                         tuple(sorted(target.items())),
                         v.state_digest))
                meta = {"delta_rows": delta_rows}
                if ckpt is not None and 0 in ckpt.completed:
                    ckpt.verify_meta(0, f"view[{v.name}] delta",
                                     **meta)
                    partial = _resume_partial(ckpt, 0,
                                              op=f"view_{v.name}")
                else:
                    resilience.inject("plan", f"view.{v.name}.delta")
                    partial = _host_state(v.query_fn(inputs))
                    if ckpt is not None:
                        cols, rows = _encode_partial(partial)
                        ckpt.complete(0, cols, rows,
                                      meta=_partial_schema_meta(
                                          partial, meta))
                if ckpt is not None and 1 in ckpt.completed:
                    merged = _resume_partial(ckpt, 1,
                                             op=f"view_{v.name}")
                else:
                    def _merge():
                        resilience.inject("global_merge",
                                          f"view.{v.name}")
                        return combiners.merge_delta(v.state, partial,
                                                     v.spec)

                    # the merge runs bounded like the fallback's own
                    # global merge — a hang dumps stacks, not wedges
                    merged = watchdog.bounded(
                        _merge, "fallback_merge",
                        detail=f"view.{v.name}")
                    if ckpt is not None:
                        cols, rows = _encode_partial(merged)
                        ckpt.complete(1, cols, rows,
                                      meta=_partial_schema_meta(
                                          merged, meta))
                # the swap: state + watermark + digest publish
                # together under v._mu — a reader sees the old view or
                # the new view, never a blend
                v.state = merged
                v.state_digest = _state_digest(merged)
            v.applied = target
            v._present_memo = None
        v.refreshes += 1
        wall = time.perf_counter() - t0
        v.last_refresh_s = wall
        gens = dict(v.applied)
        digest = v.state_digest
    telemetry.histogram("view.refresh_seconds",
                        view=v.name).observe(wall)
    if delta_rows:
        telemetry.counter("view.delta_rows",
                          view=v.name).inc(delta_rows)
    _events.emit("view_refresh", view=v.name,
                 generation=int(gens.get(v.delta_source, 0)),
                 delta_rows=(-1 if delta_rows is None else delta_rows),
                 wall_s=round(wall, 6), full_recompute=full_recompute)
    return {"view": v.name, "refreshed": True,
            "full_recompute": full_recompute,
            "delta_rows": delta_rows, "generations": gens,
            "digest": digest, "wall_s": wall}


def read(name: str) -> dict:
    """Generation-consistent read: ``{"result", "generations",
    "digest", "lag"}`` captured under one view-mutex hold — the result
    IS the view at exactly those generations. ``lag`` is how many
    generations the freshest source has advanced past the state
    (0 = fully current). The presented result (sort + row limit, or a
    two-phase finalize) memoizes per watermark; appends evict the
    memo."""
    from cylon_tpu import catalog

    v = _view(name)
    with v._mu:
        applied = dict(v.applied)
        memo = v._present_memo
        if memo is not None and memo[0] == applied:
            result = memo[1]
        else:
            result = combiners.present(v.state, v.spec, v.limit)
            v._present_memo = (applied, result)
        digest = v.state_digest
    lag = 0
    for alias, tid in v.sources.items():
        try:
            lag = max(lag,
                      catalog.generation(tid) - applied.get(alias, 0))
        except KeyError_:
            pass  # source dropped: lag is undefined, not an error
    return {"view": v.name, "result": result, "generations": applied,
            "digest": digest, "lag": int(lag)}


def view_version(name: str) -> dict:
    """``{"generations", "digest"}`` without materializing the
    presented result."""
    v = _view(name)
    with v._mu:
        return {"generations": dict(v.applied),
                "digest": v.state_digest}


def list_views() -> "list[str]":
    with _reg_mu:
        return sorted(_views)


def stats() -> "dict[str, dict]":
    """Per-view inventory (the serve ``/views`` payload): sources,
    watermarks, digest, refresh count, state size."""
    with _reg_mu:
        items = list(_views.items())
    out = {}
    for name, v in items:
        with v._mu:
            state = v.state
            out[name] = {
                "sources": dict(v.sources),
                "delta_source": v.delta_source,
                "merge": v.spec["merge"],
                "generations": dict(v.applied),
                "digest": v.state_digest,
                "refreshes": int(v.refreshes),
                "last_refresh_s": v.last_refresh_s,
                "state_rows": (None if state is None else
                               1 if isinstance(state, float)
                               else int(len(state))),
            }
    return out


def drop_view(name: str, *, if_exists: bool = True) -> None:
    with _reg_mu:
        if str(name) not in _views:
            if if_exists:
                return
            raise KeyError_(f"no view registered under {name!r}")
        del _views[str(name)]


def clear() -> None:
    """Drop every view + the host-frame cache (test/teardown hatch)."""
    with _reg_mu:
        _views.clear()
    _HOST_CACHE.clear()
