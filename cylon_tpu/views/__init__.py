"""Incremental materialized views over appendable catalog tables.

The package has two layers: :mod:`cylon_tpu.views.combiners` (the
pure delta-merge algebra lifted from the fallback layer's partial
combiners) and :mod:`cylon_tpu.views.materialized` (the registry:
named views with resident state, generation watermarks, checkpointable
incremental refresh, and generation-consistent reads). See
``docs/views.md`` for the refresh semantics and exactness contract.
"""

from cylon_tpu.views.combiners import (  # noqa: F401
    TWOPHASE_COMBINE_BY, combine_partials, finalize_twophase,
    merge_delta, present,
)
from cylon_tpu.views.materialized import (  # noqa: F401
    MaterializedView, clear, drop_view, list_views, read,
    refresh, register_view, stats, view_version,
)

__all__ = [
    "TWOPHASE_COMBINE_BY", "combine_partials", "finalize_twophase",
    "merge_delta", "present",
    "MaterializedView", "clear", "drop_view", "list_views", "read",
    "refresh", "register_view", "stats", "view_version",
]
