// cylon_host: native host runtime for the TPU-native framework.
//
// Parity targets in the reference (all C++ there, so C++ here):
//   - memory pool:      cpp/src/cylon/ctx/memory_pool.hpp +
//                       ctx/arrow_memory_pool_utils.cpp (pluggable
//                       allocator with stats, bridged to Arrow)
//   - murmur3:          cpp/src/cylon/util/murmur3.{hpp,cpp}
//                       (MurmurHash3_x86_32, the row-hash primitive of
//                       arrow_partition_kernels.cpp:140)
//   - data loader:      cpp/src/cylon/io/ + the per-file reader threads
//                       of table.cpp:788-795 — here a chunk-parallel
//                       CSV parser producing columnar host buffers that
//                       feed jax.device_put directly
//   - thread pool:      the execution loop of ops/execution/execution.hpp
//                       reimagined as a work-stealing-free fixed pool
//
// Exposed as a C ABI consumed via ctypes (no pybind11 in the image).

#include <atomic>
#include <cctype>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <fstream>
#include <unordered_map>
#include <unordered_set>
#include <functional>
#include <algorithm>
#include <limits>
#include <map>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <vector>

extern "C" {

// ------------------------------------------------------------------
// Memory pool: aligned allocations with stats + size-bucketed free lists.
// Parity: cylon::MemoryPool interface {Allocate, Reallocate, Free,
// bytes_allocated, max_memory} (ctx/memory_pool.hpp:24-60).
// ------------------------------------------------------------------

struct CylonPool {
  std::mutex mu;
  std::map<size_t, std::vector<void*>> free_lists;  // size -> buffers
  std::atomic<int64_t> bytes_allocated{0};
  std::atomic<int64_t> max_memory{0};
  std::atomic<int64_t> num_allocations{0};
  std::atomic<int64_t> pooled_bytes{0};
  int64_t pool_limit;  // max bytes kept in free lists
};

static const size_t kAlign = 64;  // cache line; also XLA's row alignment

void* cylon_pool_create(int64_t pool_limit_bytes) {
  auto* p = new CylonPool();
  p->pool_limit = pool_limit_bytes > 0 ? pool_limit_bytes : (256ll << 20);
  return p;
}

void cylon_pool_destroy(void* pool) {
  auto* p = static_cast<CylonPool*>(pool);
  for (auto& kv : p->free_lists)
    for (void* buf : kv.second) std::free(buf);
  delete p;
}

void* cylon_pool_alloc(void* pool, int64_t size) {
  auto* p = static_cast<CylonPool*>(pool);
  size_t sz = ((static_cast<size_t>(size) + kAlign - 1) / kAlign) * kAlign;
  {
    std::lock_guard<std::mutex> lk(p->mu);
    auto it = p->free_lists.find(sz);
    if (it != p->free_lists.end() && !it->second.empty()) {
      void* buf = it->second.back();
      it->second.pop_back();
      p->pooled_bytes -= static_cast<int64_t>(sz);
      p->bytes_allocated += static_cast<int64_t>(sz);
      p->num_allocations++;
      if (p->bytes_allocated > p->max_memory)
        p->max_memory.store(p->bytes_allocated.load());
      return buf;
    }
  }
  void* buf = nullptr;
  if (posix_memalign(&buf, kAlign, sz) != 0) return nullptr;
  p->bytes_allocated += static_cast<int64_t>(sz);
  p->num_allocations++;
  if (p->bytes_allocated > p->max_memory)
    p->max_memory.store(p->bytes_allocated.load());
  return buf;
}

void cylon_pool_free(void* pool, void* buf, int64_t size) {
  if (buf == nullptr) return;
  auto* p = static_cast<CylonPool*>(pool);
  size_t sz = ((static_cast<size_t>(size) + kAlign - 1) / kAlign) * kAlign;
  p->bytes_allocated -= static_cast<int64_t>(sz);
  std::lock_guard<std::mutex> lk(p->mu);
  if (p->pooled_bytes + static_cast<int64_t>(sz) <= p->pool_limit) {
    p->free_lists[sz].push_back(buf);
    p->pooled_bytes += static_cast<int64_t>(sz);
  } else {
    std::free(buf);
  }
}

void cylon_pool_stats(void* pool, int64_t* bytes_allocated,
                      int64_t* max_memory, int64_t* num_allocations,
                      int64_t* pooled_bytes) {
  auto* p = static_cast<CylonPool*>(pool);
  *bytes_allocated = p->bytes_allocated.load();
  *max_memory = p->max_memory.load();
  *num_allocations = p->num_allocations.load();
  *pooled_bytes = p->pooled_bytes.load();
}

// ------------------------------------------------------------------
// MurmurHash3_x86_32 (parity: util/murmur3.cpp MurmurHash3_x86_32).
// ------------------------------------------------------------------

static inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

static inline uint32_t fmix32(uint32_t h) {
  h ^= h >> 16;
  h *= 0x85ebca6b;
  h ^= h >> 13;
  h *= 0xc2b2ae35;
  h ^= h >> 16;
  return h;
}

uint32_t cylon_murmur3_x86_32(const void* key, int len, uint32_t seed) {
  const uint8_t* data = static_cast<const uint8_t*>(key);
  const int nblocks = len / 4;
  uint32_t h1 = seed;
  const uint32_t c1 = 0xcc9e2d51;
  const uint32_t c2 = 0x1b873593;

  for (int i = 0; i < nblocks; i++) {
    uint32_t k1;
    std::memcpy(&k1, data + i * 4, 4);
    k1 *= c1;
    k1 = rotl32(k1, 15);
    k1 *= c2;
    h1 ^= k1;
    h1 = rotl32(h1, 13);
    h1 = h1 * 5 + 0xe6546b64;
  }

  const uint8_t* tail = data + nblocks * 4;
  uint32_t k1 = 0;
  switch (len & 3) {
    case 3: k1 ^= tail[2] << 16; [[fallthrough]];
    case 2: k1 ^= tail[1] << 8; [[fallthrough]];
    case 1:
      k1 ^= tail[0];
      k1 *= c1;
      k1 = rotl32(k1, 15);
      k1 *= c2;
      h1 ^= k1;
  }

  h1 ^= static_cast<uint32_t>(len);
  return fmix32(h1);
}

// Bulk row hashing: int64 keys -> uint32 hashes (the hot loop of
// MapToHashPartitions, partition/partition.cpp:93, done natively for
// host-resident data).
void cylon_murmur3_int64_array(const int64_t* keys, int64_t n, uint32_t seed,
                               uint32_t* out) {
  for (int64_t i = 0; i < n; i++)
    out[i] = cylon_murmur3_x86_32(&keys[i], 8, seed);
}

// ------------------------------------------------------------------
// Thread pool (fixed workers, FIFO queue).
// ------------------------------------------------------------------

struct CylonThreadPool {
  std::vector<std::thread> workers;
  std::queue<std::function<void()>> tasks;
  std::mutex mu;
  std::condition_variable cv;
  std::condition_variable done_cv;
  std::atomic<int64_t> pending{0};
  bool stop = false;

  explicit CylonThreadPool(int n) {
    for (int i = 0; i < n; i++) {
      workers.emplace_back([this] {
        for (;;) {
          std::function<void()> task;
          {
            std::unique_lock<std::mutex> lk(mu);
            cv.wait(lk, [this] { return stop || !tasks.empty(); });
            if (stop && tasks.empty()) return;
            task = std::move(tasks.front());
            tasks.pop();
          }
          task();
          if (--pending == 0) {
            std::lock_guard<std::mutex> lk(mu);
            done_cv.notify_all();
          }
        }
      });
    }
  }

  void submit(std::function<void()> f) {
    pending++;
    {
      std::lock_guard<std::mutex> lk(mu);
      tasks.push(std::move(f));
    }
    cv.notify_one();
  }

  void wait_all() {
    std::unique_lock<std::mutex> lk(mu);
    done_cv.wait(lk, [this] { return pending.load() == 0; });
  }

  ~CylonThreadPool() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stop = true;
    }
    cv.notify_all();
    for (auto& w : workers) w.join();
  }
};

void* cylon_threadpool_create(int n_threads) {
  return new CylonThreadPool(n_threads > 0 ? n_threads
                                           : (int)std::thread::hardware_concurrency());
}

void cylon_threadpool_destroy(void* tp) {
  delete static_cast<CylonThreadPool*>(tp);
}

typedef void (*cylon_task_fn)(void* arg);

void cylon_threadpool_submit(void* tp, cylon_task_fn fn, void* arg) {
  static_cast<CylonThreadPool*>(tp)->submit([fn, arg] { fn(arg); });
}

void cylon_threadpool_wait(void* tp) {
  static_cast<CylonThreadPool*>(tp)->wait_all();
}

// ------------------------------------------------------------------
// CSV loader: chunk-parallel parse into columnar buffers.
//
// Model (parity): arrow::csv's parallel block parser as configured by
// io/csv_read_config.hpp, plus the per-file reader threads of
// table.cpp:788. The file is split at newline boundaries into one byte
// range per worker; each worker parses its rows into per-chunk column
// vectors which are stitched in order.
//
// Column types: inferred from the first data row — INT64 (all digits),
// FLOAT64, else STRING. Strings are dictionary-encoded host-side
// (sorted dictionary; codes int32), matching the device table format.
// ------------------------------------------------------------------

enum ColType : int32_t { COL_INT64 = 0, COL_FLOAT64 = 1, COL_STRING = 2 };

struct CsvResult {
  int64_t n_rows = 0;
  int32_t n_cols = 0;
  std::vector<std::string> names;
  std::vector<int32_t> types;
  // per column: fixed buffers
  std::vector<std::vector<int64_t>> i64;
  std::vector<std::vector<double>> f64;
  std::vector<std::vector<int32_t>> codes;     // string columns
  std::vector<std::vector<uint8_t>> validity;  // 1 = non-null
  std::vector<std::vector<std::string>> dict;  // sorted unique values
  std::string error;
};

struct ChunkOut {
  std::vector<std::vector<int64_t>> i64;
  std::vector<std::vector<double>> f64;
  std::vector<std::vector<std::string>> str;
  std::vector<std::vector<uint8_t>> valid;
  int64_t rows = 0;
};

static void split_fields(const char* line, size_t len, char delim,
                         std::vector<std::pair<const char*, size_t>>* out) {
  out->clear();
  size_t start = 0;
  for (size_t i = 0; i <= len; i++) {
    if (i == len || line[i] == delim) {
      size_t flen = i - start;
      // trim \r
      while (flen > 0 && (line[start + flen - 1] == '\r')) flen--;
      out->push_back({line + start, flen});
      start = i + 1;
    }
  }
}

// Quote-aware variant (parity: csv_read_config UseQuoting/WithQuoteChar/
// DoubleQuote): a field starting with `quote` runs to the closing quote,
// may contain the delimiter, and encodes a literal quote as a doubled
// one. Unescaped bytes are materialised into `arena` (cleared per line
// by the caller); embedded newlines are NOT supported on this path —
// the chunker splits at raw newlines (callers with
// has_newlines_in_values use the arrow engine).
static void split_fields_q(const char* line, size_t len, char delim,
                           char quote, std::deque<std::string>* arena,
                           std::vector<std::pair<const char*, size_t>>* out,
                           std::vector<uint8_t>* quoted,
                           bool* unterminated) {
  out->clear();
  if (quoted) quoted->clear();
  size_t i = 0;
  while (i <= len) {
    if (i < len && line[i] == quote) {
      // quoted field, arrow-exact: doubled quotes inside are literals;
      // the FIRST lone closing quote ends quoted mode for good, and
      // everything after it up to the delimiter — including further
      // quote chars — is literal ('"x"yz' -> xyz, '"x"y"z"' -> xy"z").
      std::string buf;
      size_t j = i + 1;
      bool in_q = true;
      size_t close_pos = 0;  // buf length at the closing quote
      while (j < len) {
        char ch = line[j];
        if (in_q) {
          if (ch == quote) {
            if (j + 1 < len && line[j + 1] == quote) {
              buf.push_back(quote);
              j += 2;
              continue;
            }
            in_q = false;
            close_pos = buf.size();
            j++;
            continue;
          }
          buf.push_back(ch);
          j++;
        } else {
          if (ch == delim) break;
          buf.push_back(ch);
          j++;
        }
      }
      // a quoted field running past end-of-line means the value
      // contains a raw newline — the chunker split inside it; callers
      // must fail (arrow with has_newlines_in_values handles those)
      if (in_q) {
        if (unterminated) *unterminated = true;
        close_pos = buf.size();
      }
      // line-ending \r trim: only bytes appended OUTSIDE the quotes
      // (a \r inside the quotes is data)
      while (buf.size() > close_pos && buf.back() == '\r') buf.pop_back();
      arena->push_back(std::move(buf));
      out->push_back({arena->back().data(), arena->back().size()});
      if (quoted) quoted->push_back(1);
      if (j >= len) return;
      i = j + 1;
    } else {
      size_t j = i;
      while (j < len && line[j] != delim) j++;
      size_t flen = j - i;
      while (flen > 0 && line[i + flen - 1] == '\r') flen--;
      out->push_back({line + i, flen});
      if (quoted) quoted->push_back(0);
      if (j >= len) return;
      i = j + 1;
    }
  }
}

struct CsvOpts {
  char quote = 0;  // 0 = quoting off
  bool strings_null = false;  // NullValues apply to string columns too
  std::vector<std::string> na;  // tiny: linear memcmp beats hashing
  std::unordered_map<std::string, int32_t> type_overrides;  // name -> ColType
};

static void csv_split(const char* line, size_t len, char delim,
                      const CsvOpts& o, std::deque<std::string>* arena,
                      std::vector<std::pair<const char*, size_t>>* out,
                      std::vector<uint8_t>* quoted = nullptr,
                      bool* unterminated = nullptr) {
  if (o.quote) {
    arena->clear();
    split_fields_q(line, len, delim, o.quote, arena, out, quoted,
                   unterminated);
  } else {
    split_fields(line, len, delim, out);
    if (quoted) quoted->assign(out->size(), 0);
  }
}

static bool is_na(const CsvOpts& o, const char* s, size_t len) {
  // hot per-cell path: no allocations (the na list is a handful of
  // short spellings)
  for (const auto& v : o.na)
    if (v.size() == len && std::memcmp(v.data(), s, len) == 0) return true;
  return false;
}

static bool parse_i64(const char* s, size_t len, int64_t* out) {
  if (len == 0) return false;
  char buf[32];
  if (len >= sizeof(buf)) return false;
  std::memcpy(buf, s, len);
  buf[len] = 0;
  char* end = nullptr;
  errno = 0;
  long long v = strtoll(buf, &end, 10);
  if (errno != 0 || end != buf + len) return false;
  *out = v;
  return true;
}

static bool parse_f64(const char* s, size_t len, double* out) {
  if (len == 0) return false;
  char buf[64];
  if (len >= sizeof(buf)) return false;
  std::memcpy(buf, s, len);
  buf[len] = 0;
  char* end = nullptr;
  errno = 0;
  double v = strtod(buf, &end);
  if (end != buf + len) return false;
  *out = v;
  return true;
}

static void* csv_read_impl(const char* path, char delim, int has_header,
                           int n_threads, const CsvOpts& opt) {
  auto* res = new CsvResult();
  std::ifstream f(path, std::ios::binary | std::ios::ate);
  if (!f) {
    res->error = std::string("cannot open ") + path;
    return res;
  }
  std::streamsize size = f.tellg();
  f.seekg(0);
  std::string content(static_cast<size_t>(size), 0);
  if (!f.read(&content[0], size)) {
    res->error = "read failed";
    return res;
  }

  // header
  size_t pos = 0;
  std::vector<std::pair<const char*, size_t>> fields;
  std::deque<std::string> arena;
  size_t first_nl = content.find('\n');
  if (first_nl == std::string::npos) first_nl = content.size();
  csv_split(content.data(), first_nl, delim, opt, &arena, &fields);
  res->n_cols = static_cast<int32_t>(fields.size());
  if (has_header) {
    for (auto& fd : fields) res->names.emplace_back(fd.first, fd.second);
    pos = first_nl + 1;
  } else {
    for (size_t i = 0; i < fields.size(); i++)
      res->names.push_back("f" + std::to_string(i));
  }

  // type inference: the first non-NA value per column decides (a
  // single-row probe would stringify numeric columns whose first
  // values are null spellings). The scan stops as soon as every
  // column is resolved — row 1 for typical files; an all-null column
  // costs one extra pass, the price of agreeing with arrow.
  res->types.assign(res->n_cols, -1);
  {
    size_t p = pos;
    int32_t resolved = 0;
    // explicit overrides resolve up front (parity: WithColumnTypes,
    // csv_read_config.hpp:113) — they must not force the scan on
    for (size_t i = 0; i < res->names.size(); i++) {
      auto it = opt.type_overrides.find(res->names[i]);
      if (it != opt.type_overrides.end()) {
        res->types[i] = it->second;
        resolved++;
      }
    }
    while (p < content.size() && resolved < res->n_cols) {
      size_t nl = content.find('\n', p);
      if (nl == std::string::npos) nl = content.size();
      csv_split(content.data() + p, nl - p, delim, opt, &arena, &fields);
      for (size_t i = 0; i < static_cast<size_t>(res->n_cols); i++) {
        if (res->types[i] != -1 || i >= fields.size()) continue;
        const char* s = fields[i].first;
        size_t sl = fields[i].second;
        if (sl == 0 || is_na(opt, s, sl)) continue;  // undecided
        int64_t iv;
        double dv;
        if (parse_i64(s, sl, &iv)) res->types[i] = COL_INT64;
        else if (parse_f64(s, sl, &dv)) res->types[i] = COL_FLOAT64;
        else res->types[i] = COL_STRING;
        resolved++;
      }
      p = nl + 1;
    }
    for (auto& t : res->types)
      if (t == -1) t = COL_STRING;  // all-null/empty columns
  }

  // chunk boundaries at newlines
  int nt = n_threads > 0 ? n_threads
                         : (int)std::thread::hardware_concurrency();
  if (nt < 1) nt = 1;
  size_t body = content.size() - pos;
  size_t chunk = body / static_cast<size_t>(nt) + 1;
  std::vector<std::pair<size_t, size_t>> ranges;
  size_t start = pos;
  while (start < content.size()) {
    size_t end = start + chunk;
    if (end >= content.size()) {
      end = content.size();
    } else {
      size_t nl = content.find('\n', end);
      end = (nl == std::string::npos) ? content.size() : nl + 1;
    }
    ranges.push_back({start, end});
    start = end;
  }

  std::vector<ChunkOut> outs(ranges.size());
  std::atomic<bool> failed{false};
  {
    CylonThreadPool tp(nt);
    for (size_t c = 0; c < ranges.size(); c++) {
      tp.submit([&, c] {
        auto& out = outs[c];
        int ncols = res->n_cols;
        out.i64.resize(ncols);
        out.f64.resize(ncols);
        out.str.resize(ncols);
        out.valid.resize(ncols);
        std::vector<std::pair<const char*, size_t>> fds;
        std::vector<uint8_t> fquoted;
        std::deque<std::string> chunk_arena;
        size_t p = ranges[c].first;
        const size_t end = ranges[c].second;
        while (p < end) {
          size_t nl = content.find('\n', p);
          if (nl == std::string::npos || nl > end) nl = end;
          size_t linelen = nl - p;
          if (linelen > 0 || (p < end && content[p] != '\n')) {
            // skip fully empty lines
            bool empty = true;
            for (size_t i = p; i < p + linelen; i++)
              if (!std::isspace(static_cast<unsigned char>(content[i]))) {
                empty = false;
                break;
              }
            if (!empty) {
              bool unterm = false;
              csv_split(content.data() + p, linelen, delim, opt,
                        &chunk_arena, &fds, &fquoted, &unterm);
              if (unterm) {
                failed.store(true);
                break;
              }
              out.rows++;
              for (int col = 0; col < ncols; col++) {
                const char* s = col < (int)fds.size() ? fds[col].first : "";
                size_t sl = col < (int)fds.size() ? fds[col].second : 0;
                bool was_q = col < (int)fquoted.size() && fquoted[col];
                uint8_t ok = is_na(opt, s, sl) ? 0 : 1;
                switch (res->types[col]) {
                  case COL_INT64: {
                    int64_t v = 0;
                    if (!ok || !parse_i64(s, sl, &v)) ok = 0, v = 0;
                    out.i64[col].push_back(v);
                    break;
                  }
                  case COL_FLOAT64: {
                    double v = 0;
                    if (!ok || !parse_f64(s, sl, &v)) ok = 0, v = 0;
                    out.f64[col].push_back(v);
                    break;
                  }
                  default: {
                    // arrow semantics: NullValues hit string columns
                    // only under StringsCanBeNull, and an explicitly
                    // QUOTED empty field is the empty string, not null
                    if (!ok && !opt.strings_null) ok = 1;
                    if (sl == 0 && !was_q) ok = 0;
                    out.str[col].emplace_back(ok ? s : "", ok ? sl : 0);
                    break;
                  }
                }
                out.valid[col].push_back(ok);
              }
            }
          }
          p = nl + 1;
        }
      });
    }
    tp.wait_all();
  }
  if (failed.load()) {
    res->error = "quoted field contains a raw newline; read with "
                 "has_newlines_in_values (arrow engine)";
    return res;
  }

  // stitch chunks in order
  int ncols = res->n_cols;
  res->i64.resize(ncols);
  res->f64.resize(ncols);
  res->codes.resize(ncols);
  res->validity.resize(ncols);
  res->dict.resize(ncols);
  for (auto& out : outs) res->n_rows += out.rows;
  for (int col = 0; col < ncols; col++) {
    res->validity[col].reserve(res->n_rows);
    if (res->types[col] == COL_INT64) {
      res->i64[col].reserve(res->n_rows);
      for (auto& out : outs) {
        res->i64[col].insert(res->i64[col].end(), out.i64[col].begin(),
                             out.i64[col].end());
        res->validity[col].insert(res->validity[col].end(),
                                  out.valid[col].begin(),
                                  out.valid[col].end());
      }
    } else if (res->types[col] == COL_FLOAT64) {
      res->f64[col].reserve(res->n_rows);
      for (auto& out : outs) {
        res->f64[col].insert(res->f64[col].end(), out.f64[col].begin(),
                             out.f64[col].end());
        res->validity[col].insert(res->validity[col].end(),
                                  out.valid[col].begin(),
                                  out.valid[col].end());
      }
    } else {
      // dictionary-encode: sorted unique values -> int32 codes
      std::map<std::string, int32_t> lut;
      for (auto& out : outs)
        for (auto& s : out.str[col]) lut.emplace(s, 0);
      int32_t code = 0;
      for (auto& kv : lut) kv.second = code++;
      res->dict[col].reserve(lut.size());
      for (auto& kv : lut) res->dict[col].push_back(kv.first);
      res->codes[col].reserve(res->n_rows);
      for (auto& out : outs) {
        for (auto& s : out.str[col])
          res->codes[col].push_back(lut[s]);
        res->validity[col].insert(res->validity[col].end(),
                                  out.valid[col].begin(),
                                  out.valid[col].end());
      }
    }
  }
  return res;
}

void* cylon_csv_read(const char* path, char delim, int has_header,
                     int n_threads) {
  return csv_read_impl(path, delim, has_header, n_threads, CsvOpts());
}

// Extended reader (parity: csv_read_config.hpp UseQuoting/WithQuoteChar/
// NullValues/WithColumnTypes).
//   quote_char:  0 disables quoting.
//   na_values:   '\x1f'-joined null spellings, or NULL.
//   col_types:   "name\x1ftype;..." with type = ColType int, or NULL.
void* cylon_csv_read_opts(const char* path, char delim, int has_header,
                          int n_threads, char quote_char,
                          const char* na_values, const char* col_types,
                          int strings_can_be_null) {
  CsvOpts opt;
  opt.quote = quote_char;
  opt.strings_null = strings_can_be_null != 0;
  if (na_values && *na_values) {
    const char* s = na_values;
    while (true) {
      const char* sep = strchr(s, '\x1f');
      if (!sep) {
        opt.na.emplace_back(s);
        break;
      }
      opt.na.emplace_back(s, sep - s);
      s = sep + 1;
    }
  }
  if (col_types && *col_types) {
    const char* s = col_types;
    while (*s) {
      const char* sep = strchr(s, '\x1f');
      if (!sep) break;  // malformed: ignore rest
      const char* end = strchr(sep + 1, ';');
      std::string name(s, sep - s);
      int32_t t = static_cast<int32_t>(
          strtol(sep + 1, nullptr, 10));
      if (t >= COL_INT64 && t <= COL_STRING) opt.type_overrides[name] = t;
      if (!end) break;
      s = end + 1;
    }
  }
  return csv_read_impl(path, delim, has_header, n_threads, opt);
}

const char* cylon_csv_error(void* r) {
  auto* res = static_cast<CsvResult*>(r);
  return res->error.empty() ? nullptr : res->error.c_str();
}

int64_t cylon_csv_num_rows(void* r) {
  return static_cast<CsvResult*>(r)->n_rows;
}

int32_t cylon_csv_num_cols(void* r) {
  return static_cast<CsvResult*>(r)->n_cols;
}

const char* cylon_csv_col_name(void* r, int32_t col) {
  return static_cast<CsvResult*>(r)->names[col].c_str();
}

int32_t cylon_csv_col_type(void* r, int32_t col) {
  return static_cast<CsvResult*>(r)->types[col];
}

// Copy column data into caller-provided buffers (numpy-owned).
void cylon_csv_col_i64(void* r, int32_t col, int64_t* out) {
  auto* res = static_cast<CsvResult*>(r);
  std::memcpy(out, res->i64[col].data(), res->n_rows * sizeof(int64_t));
}

void cylon_csv_col_f64(void* r, int32_t col, double* out) {
  auto* res = static_cast<CsvResult*>(r);
  std::memcpy(out, res->f64[col].data(), res->n_rows * sizeof(double));
}

void cylon_csv_col_codes(void* r, int32_t col, int32_t* out) {
  auto* res = static_cast<CsvResult*>(r);
  std::memcpy(out, res->codes[col].data(), res->n_rows * sizeof(int32_t));
}

void cylon_csv_col_validity(void* r, int32_t col, uint8_t* out) {
  auto* res = static_cast<CsvResult*>(r);
  std::memcpy(out, res->validity[col].data(), res->n_rows);
}

int32_t cylon_csv_dict_size(void* r, int32_t col) {
  return static_cast<int32_t>(static_cast<CsvResult*>(r)->dict[col].size());
}

const char* cylon_csv_dict_value(void* r, int32_t col, int32_t code) {
  return static_cast<CsvResult*>(r)->dict[col][code].c_str();
}

void cylon_csv_free(void* r) { delete static_cast<CsvResult*>(r); }

// ------------------------------------------------------------------
// Catalog: string-id keyed columnar table registry, C ABI.
//
// Parity: table_api.{hpp,cpp} PutTable/GetTable/RemoveTable (:38-90) —
// the exact surface the reference's Java binding drives over JNI
// (Table.java:289-307 -> java/src/main/native/src/Table.cpp). Any FFI
// runtime (JNI, ctypes, cffi, .NET) binds these symbols; the Python
// bridge in native/__init__.py is one such client and round-trips full
// cylon_tpu Tables (dictionary columns ride as a codes column plus two
// companion blob/offset columns, documented there).
//
// Columns are opaque byte buffers tagged with a caller-defined dtype
// code; the catalog copies in on put and out on read, so callers never
// share ownership across the ABI. All entry points are mutex-guarded
// (the JNI bridge in the reference serialises through the same kind of
// global registry).
// ------------------------------------------------------------------

namespace {

struct CatColumn {
  std::string name;
  int32_t dtype = 0;
  std::vector<uint8_t> data;
  std::vector<uint8_t> validity;  // empty = no nulls
};

struct CatTable {
  int64_t n_rows = 0;
  std::vector<CatColumn> cols;
};

std::mutex g_catalog_mu;
std::unordered_map<std::string, CatTable>& catalog() {
  static std::unordered_map<std::string, CatTable> c;
  return c;
}

}  // namespace

int32_t cylon_catalog_put(const char* id, int32_t ncols,
                          const char** names, const int32_t* dtypes,
                          int64_t n_rows, const void** data_bufs,
                          const int64_t* data_lens,
                          const uint8_t** validity_bufs) {
  if (!id || ncols < 0 || n_rows < 0) return -1;
  CatTable t;
  t.n_rows = n_rows;
  t.cols.reserve(ncols);
  for (int32_t i = 0; i < ncols; ++i) {
    CatColumn col;
    col.name = names[i];
    col.dtype = dtypes[i];
    const auto* p = static_cast<const uint8_t*>(data_bufs[i]);
    col.data.assign(p, p + data_lens[i]);
    if (validity_bufs && validity_bufs[i]) {
      col.validity.assign(validity_bufs[i], validity_bufs[i] + n_rows);
    }
    t.cols.push_back(std::move(col));
  }
  std::lock_guard<std::mutex> lk(g_catalog_mu);
  catalog()[id] = std::move(t);  // overwrite, like PutTable
  return 0;
}

int64_t cylon_catalog_rows(const char* id) {
  std::lock_guard<std::mutex> lk(g_catalog_mu);
  auto it = catalog().find(id);
  return it == catalog().end() ? -1 : it->second.n_rows;
}

int32_t cylon_catalog_ncols(const char* id) {
  std::lock_guard<std::mutex> lk(g_catalog_mu);
  auto it = catalog().find(id);
  return it == catalog().end() ? -1
                               : static_cast<int32_t>(it->second.cols.size());
}

// returns the column name's byte length on success (callers retry with
// a bigger buffer when it is >= name_cap — snprintf truncated), or a
// negative error code.
int32_t cylon_catalog_col_info(const char* id, int32_t i, char* name_out,
                               int32_t name_cap, int32_t* dtype_out,
                               int64_t* nbytes_out, int32_t* has_validity) {
  std::lock_guard<std::mutex> lk(g_catalog_mu);
  auto it = catalog().find(id);
  if (it == catalog().end()) return -1;
  if (i < 0 || i >= static_cast<int32_t>(it->second.cols.size())) return -2;
  const CatColumn& c = it->second.cols[i];
  std::snprintf(name_out, name_cap, "%s", c.name.c_str());
  *dtype_out = c.dtype;
  *nbytes_out = static_cast<int64_t>(c.data.size());
  *has_validity = c.validity.empty() ? 0 : 1;
  return static_cast<int32_t>(c.name.size());
}

// data_cap bounds the write into data_out (-3 if too small).
int32_t cylon_catalog_col_read(const char* id, int32_t i, void* data_out,
                               int64_t data_cap, uint8_t* validity_out) {
  std::lock_guard<std::mutex> lk(g_catalog_mu);
  auto it = catalog().find(id);
  if (it == catalog().end()) return -1;
  if (i < 0 || i >= static_cast<int32_t>(it->second.cols.size())) return -2;
  const CatColumn& c = it->second.cols[i];
  if (data_cap < static_cast<int64_t>(c.data.size())) return -3;
  std::memcpy(data_out, c.data.data(), c.data.size());
  if (validity_out && !c.validity.empty()) {
    std::memcpy(validity_out, c.validity.data(), c.validity.size());
  }
  return 0;
}

int32_t cylon_catalog_remove(const char* id) {
  std::lock_guard<std::mutex> lk(g_catalog_mu);
  return catalog().erase(id) ? 0 : -1;
}

int32_t cylon_catalog_size() {
  std::lock_guard<std::mutex> lk(g_catalog_mu);
  return static_cast<int32_t>(catalog().size());
}

// newline-joined ids; returns bytes written (excluding NUL), or the
// required size if cap is too small (call twice).
int64_t cylon_catalog_ids(char* buf, int64_t cap) {
  std::lock_guard<std::mutex> lk(g_catalog_mu);
  std::string all;
  for (const auto& kv : catalog()) {
    if (!all.empty()) all += '\n';
    all += kv.first;
  }
  int64_t need = static_cast<int64_t>(all.size());
  if (buf && cap > need) {
    std::memcpy(buf, all.data(), all.size());
    buf[all.size()] = '\0';
    return need;
  }
  return need + 1;
}

void cylon_catalog_clear() {
  std::lock_guard<std::mutex> lk(g_catalog_mu);
  catalog().clear();
}

}  // extern "C"

// ------------------------------------------------------------------
// Native host join over catalog tables.
//
// Parity: the reference's string-id join surface used by the Java
// binding — `table_api` JoinTables (`table_api.hpp:38-90`) behind
// `Table.java:289-307` nativeJoin. This is the HOST runtime's join
// (hash build + probe, like `join/hash_join.cpp:22-31`): a foreign
// runtime (C/JNI/Go) can put tables, join, and read results with no
// Python in the process. The TPU path (`cylon_tpu.ops.join`) remains
// the compute engine for device-resident tables; this covers the
// catalog/FFI surface with the same null==null, pandas-suffix
// semantics so results agree with the device join.
// ------------------------------------------------------------------

namespace {

// canonical 64-bit cell image: int64/f64 as 8 bytes (f64 canonicalises
// -0.0 and NaN so bit-equality == value-equality, matching
// kernels.order_key), int32 codes sign-extended.
inline int64_t cell_bits(const CatColumn& c, int64_t i) {
  if (c.dtype == 2) {
    int32_t v;
    std::memcpy(&v, c.data.data() + i * 4, 4);
    return v;
  }
  if (c.dtype == 1) {
    double d;
    std::memcpy(&d, c.data.data() + i * 8, 8);
    if (d == 0.0) d = 0.0;                      // -0.0 -> +0.0
    if (d != d) d = std::numeric_limits<double>::quiet_NaN();
    int64_t v;
    std::memcpy(&v, &d, 8);
    return v;
  }
  int64_t v;
  std::memcpy(&v, c.data.data() + i * 8, 8);
  return v;
}

inline bool cell_valid(const CatColumn& c, int64_t i) {
  return c.validity.empty() || c.validity[i] != 0;
}

inline int64_t cell_width(const CatColumn& c) {
  return c.dtype == 2 ? 4 : 8;
}

// ---- dictionary sidecars (the Python binding's wire convention,
// native/__init__.py: "<col>\x01blob" utf8 bytes + "<col>\x01offs"
// int64 offsets carry a string column's dictionary through the
// catalog; the device program only ever sees the int32 codes) ----

constexpr char kSidecarSep = '\x01';

inline bool is_sidecar(const std::string& n) {
  return n.find(kSidecarSep) != std::string::npos;
}

inline int find_col(const CatTable& t, const std::string& name) {
  for (size_t i = 0; i < t.cols.size(); ++i)
    if (t.cols[i].name == name) return (int)i;
  return -1;
}

bool extract_dict(const CatTable& t, const std::string& base,
                  std::vector<std::string>* out) {
  int bi = find_col(t, base + kSidecarSep + std::string("blob"));
  int oi = find_col(t, base + kSidecarSep + std::string("offs"));
  if (bi < 0 || oi < 0) return false;
  const auto& blob = t.cols[bi].data;
  const auto& offs = t.cols[oi].data;
  if (offs.size() < 8 || offs.size() % 8) return false;
  size_t n = offs.size() / 8 - 1;
  out->clear();
  for (size_t i = 0; i < n; ++i) {
    int64_t a, b;
    std::memcpy(&a, offs.data() + i * 8, 8);
    std::memcpy(&b, offs.data() + (i + 1) * 8, 8);
    if (a < 0 || b < a || (size_t)b > blob.size()) return false;
    out->emplace_back(blob.begin() + a, blob.begin() + b);
  }
  return true;
}

void append_dict_sidecars(CatTable* out, const std::string& base,
                          const std::vector<std::string>& values) {
  CatColumn blob, offs;
  blob.name = base + kSidecarSep + std::string("blob");
  blob.dtype = 1;  // Kind.UINT8 tag, matching the Python binding
  offs.name = base + kSidecarSep + std::string("offs");
  offs.dtype = 8;  // Kind.INT64 tag
  offs.data.resize((values.size() + 1) * 8, 0);
  int64_t pos = 0;
  for (size_t i = 0; i < values.size(); ++i) {
    blob.data.insert(blob.data.end(), values[i].begin(), values[i].end());
    pos += (int64_t)values[i].size();
    std::memcpy(offs.data.data() + (i + 1) * 8, &pos, 8);
  }
  out->cols.push_back(std::move(blob));
  out->cols.push_back(std::move(offs));
}

// ---- join key views: the physical interpretation of a key column.
// Accepts both the raw C-client tags (0 int64 / 1 f64 / 2 codes,
// cylon_host.h) and the Python binding's Kind tags (8=INT64,
// 11=DOUBLE, 12/13=STRING/BINARY codes). ----

struct KeyCol {
  const CatColumn* col;
  int cls;    // 0 = int image, 1 = f64, 2 = int32 codes, 3 = f32
  int width;  // bytes per element (class 0; others fixed 4/8)
};

// Resolve a key column's physical interpretation from its tag AND its
// measured element width. The raw C-client tags (0 int64 / 1 f64 /
// 2 codes) collide with Kind values (BOOL=0 / UINT8=1 / INT8=2); the
// width disambiguates: a Kind-tagged narrow column is 1 byte/row, the
// C-client meanings are 8/8/4. Every class validates the buffer size
// against n_rows so an under-sized or mis-tagged buffer is rejected
// (-1 -> join status -4) instead of read out of bounds.
struct KeyClass {
  int cls;    // -1 = unsupported/mis-sized
  int width;
};

inline KeyClass key_class(const CatColumn& c, int64_t n_rows) {
  int tag = c.dtype & 0xFF;
  if (n_rows <= 0) return {0, 0};  // no reads ever issued
  if ((int64_t)c.data.size() % n_rows != 0) return {-1, 0};
  int64_t w = (int64_t)c.data.size() / n_rows;
  if (tag == 12 || tag == 13) return w == 4 ? KeyClass{2, 4} : KeyClass{-1, 0};
  if (tag == 11) return w == 8 ? KeyClass{1, 8} : KeyClass{-1, 0};
  if (tag == 10) return w == 4 ? KeyClass{3, 4} : KeyClass{-1, 0};
  if (tag == 9) return {-1, 0};  // f16 keys: raw-bit compare would get
                                 // -0.0/NaN wrong; unsupported (as before)
  if (tag == 2) {   // C-client codes (4) vs Kind.INT8 (1)
    if (w == 4) return {2, 4};
    if (w == 1) return {0, 1};
    return {-1, 0};
  }
  if (tag == 1) {   // C-client f64 (8) vs Kind.UINT8 (1)
    if (w == 8) return {1, 8};
    if (w == 1) return {0, 1};
    return {-1, 0};
  }
  if (tag == 0) {   // C-client int64 (8) vs Kind.BOOL (1)
    if (w == 8) return {0, 8};
    if (w == 1) return {0, 1};
    return {-1, 0};
  }
  // remaining int/temporal kinds: raw little-endian image of their width
  if (w == 1 || w == 2 || w == 4 || w == 8) return {0, (int)w};
  return {-1, 0};
}

inline int64_t key_bits(const KeyCol& k, int64_t i) {
  const CatColumn& c = *k.col;
  if (k.cls == 2) {
    int32_t v;
    std::memcpy(&v, c.data.data() + i * 4, 4);
    return v;
  }
  if (k.cls == 1) {
    double d;
    std::memcpy(&d, c.data.data() + i * 8, 8);
    if (d == 0.0) d = 0.0;                      // -0.0 -> +0.0
    if (d != d) d = std::numeric_limits<double>::quiet_NaN();
    int64_t v;
    std::memcpy(&v, &d, 8);
    return v;
  }
  if (k.cls == 3) {
    float f;
    std::memcpy(&f, c.data.data() + i * 4, 4);
    if (f == 0.0f) f = 0.0f;                    // -0.0 -> +0.0
    if (f != f) f = std::numeric_limits<float>::quiet_NaN();
    int32_t v;
    std::memcpy(&v, &f, 4);
    return v;
  }
  // int image, zero-extended: both sides share the exact dtype tag
  // (enforced before key setup), so equal bits <=> equal values
  uint64_t v = 0;
  std::memcpy(&v, c.data.data() + i * k.width, (size_t)k.width);
  return (int64_t)v;
}

// composite row-key hash over the key views (null == null: validity
// folds in as its own word, like ops/hash._row_words)
inline uint64_t row_key_hash(const std::vector<KeyCol>& keys, int64_t i) {
  uint64_t h = 0x9E3779B97F4A7C15ull;
  for (const KeyCol& k : keys) {
    bool valid = cell_valid(*k.col, i);
    uint64_t w = valid ? static_cast<uint64_t>(key_bits(k, i)) : 0ull;
    h ^= w + 0x9E3779B97F4A7C15ull + (h << 6) + (h >> 2);
    h ^= (valid ? 0x517CC1B727220A95ull : 0x2545F4914F6CDD1Dull)
         + (h << 6) + (h >> 2);
  }
  return h;
}

inline bool rows_key_equal(const std::vector<KeyCol>& ka, int64_t i,
                           const std::vector<KeyCol>& kb, int64_t j) {
  for (size_t f = 0; f < ka.size(); ++f) {
    bool va = cell_valid(*ka[f].col, i), vb = cell_valid(*kb[f].col, j);
    if (va != vb) return false;
    if (va && key_bits(ka[f], i) != key_bits(kb[f], j)) return false;
  }
  return true;
}

// gather `rows` (with -1 = null slot) from `src` into a fresh column;
// `w` is the per-row byte width (from data length / n_rows — dtype
// tags alone are ambiguous across the two tag conventions)
CatColumn gather_col_w(const CatColumn& src, int64_t w,
                       const std::vector<int64_t>& rows) {
  CatColumn out;
  out.name = src.name;
  out.dtype = src.dtype;
  out.data.assign(rows.size() * w, 0);
  bool any_null = false;
  out.validity.assign(rows.size(), 1);
  for (size_t r = 0; r < rows.size(); ++r) {
    int64_t i = rows[r];
    if (i < 0 || !cell_valid(src, i)) {
      out.validity[r] = 0;
      any_null = true;
      continue;
    }
    std::memcpy(out.data.data() + r * w, src.data.data() + i * w, w);
  }
  if (!any_null) out.validity.clear();
  return out;
}

CatColumn gather_col(const CatColumn& src, const std::vector<int64_t>& rows) {
  return gather_col_w(src, cell_width(src), rows);
}

}  // namespace

extern "C" {

int32_t cylon_catalog_join(const char* left_id, const char* right_id,
                           const char* out_id, int32_t n_keys,
                           const int32_t* left_keys,
                           const int32_t* right_keys,
                           int32_t join_type) {
  if (!left_id || !right_id || !out_id || n_keys <= 0 || !left_keys ||
      !right_keys || join_type < 0 || join_type > 3)
    return -1;
  std::lock_guard<std::mutex> lk(g_catalog_mu);
  auto lit = catalog().find(left_id);
  auto rit = catalog().find(right_id);
  if (lit == catalog().end() || rit == catalog().end()) return -2;
  const CatTable& L = lit->second;
  const CatTable& R = rit->second;
  std::vector<int32_t> lk_(left_keys, left_keys + n_keys);
  std::vector<int32_t> rk_(right_keys, right_keys + n_keys);
  for (int32_t i = 0; i < n_keys; ++i) {
    if (lk_[i] < 0 || lk_[i] >= (int32_t)L.cols.size() || rk_[i] < 0 ||
        rk_[i] >= (int32_t)R.cols.size())
      return -3;
    // exact tag equality (incl. temporal-unit bits): equal raw images
    // of DIFFERENT logical types (timestamp[s] vs [ms]) must not join
    // on bit coincidence. The stringish tags {2 raw codes, 12 STRING,
    // 13 LARGE_STRING} are one logical class across the two tag
    // conventions (the JNI writes 2, the Python binding 12): they
    // compare by resolved KeyClass below, and sidecar dictionaries
    // make the codes comparable by VALUE — so a Java-vs-Python
    // string-key join is legal, not a -4.
    auto stringish = [](int32_t d) {
      int t = d & 0xFF;
      return t == 2 || t == 12 || t == 13;
    };
    if (L.cols[lk_[i]].dtype != R.cols[rk_[i]].dtype) {
      if (!(stringish(L.cols[lk_[i]].dtype) &&
            stringish(R.cols[rk_[i]].dtype)))
        return -4;
      // cross-convention string keys are only meaningful when BOTH
      // sides carry sidecar dictionaries (the unification below then
      // compares by VALUE); a sidecar-less raw-code side would fall
      // through to the legacy bit compare of TABLE-LOCAL codes —
      // exactly the bit-coincidence join the strict gate existed to
      // reject. Presence check only (cheap); a present-but-malformed
      // sidecar is re-rejected when the unification loop extracts it.
      auto has_sidecars = [](const CatTable& t, const std::string& base) {
        return find_col(t, base + kSidecarSep + std::string("blob")) >= 0 &&
               find_col(t, base + kSidecarSep + std::string("offs")) >= 0;
      };
      if (!has_sidecars(L, L.cols[lk_[i]].name) ||
          !has_sidecars(R, R.cols[rk_[i]].name))
        return -4;
    }
    KeyClass lkc = key_class(L.cols[lk_[i]], L.n_rows);
    KeyClass rkc = key_class(R.cols[rk_[i]], R.n_rows);
    if (lkc.cls < 0 || rkc.cls < 0) return -4;
    // equal AMBIGUOUS tags can still resolve to different physical
    // interpretations (raw C-client codes vs Kind.INT8, f64 vs uint8):
    // matching on bit coincidence across classes/widths is meaningless.
    // Empty sides (n_rows == 0, width 0) match anything: no reads occur
    // and the join degenerates per join type.
    if (L.n_rows > 0 && R.n_rows > 0 &&
        (lkc.cls != rkc.cls || lkc.width != rkc.width))
      return -4;
  }

  // dictionary-aware keys: codes are TABLE-LOCAL (each ingest assigns
  // its own), so when both sides carry their dictionaries (sidecar
  // columns) the codes are remapped onto one merged sorted dictionary
  // before hashing — otherwise equal strings with different codes
  // would not join (and different strings with equal codes would).
  // Raw-code tables without sidecars keep the legacy bit compare.
  std::deque<CatColumn> shadows;
  std::vector<KeyCol> lkv, rkv;
  std::vector<int8_t> unified(n_keys, 0);
  std::vector<std::vector<std::string>> merged_vals(n_keys);
  for (int32_t f = 0; f < n_keys; ++f) {
    const CatColumn& lc = L.cols[lk_[f]];
    const CatColumn& rc = R.cols[rk_[f]];
    KeyClass lkc = key_class(lc, L.n_rows);
    KeyClass rkc = key_class(rc, R.n_rows);
    int cls = lkc.cls;
    if (cls == 2 && rkc.cls == 2) {
      bool mixed_tags = lc.dtype != rc.dtype;
      std::vector<std::string> lv, rv;
      bool unified_ok =
          extract_dict(L, lc.name, &lv) && extract_dict(R, rc.name, &rv);
      // mixed-tag keys passed the gate on sidecar PRESENCE; if the
      // sidecars turn out malformed the bit-compare fallback would be
      // meaningless across conventions — reject instead
      if (mixed_tags && !unified_ok) return -4;
      if (unified_ok) {
        std::vector<std::string> merged = lv;
        merged.insert(merged.end(), rv.begin(), rv.end());
        std::sort(merged.begin(), merged.end());
        merged.erase(std::unique(merged.begin(), merged.end()),
                     merged.end());
        auto remap = [&merged](const std::vector<std::string>& vals) {
          std::vector<int32_t> m(vals.size());
          for (size_t c = 0; c < vals.size(); ++c)
            m[c] = (int32_t)(std::lower_bound(merged.begin(), merged.end(),
                                              vals[c]) - merged.begin());
          return m;
        };
        std::vector<int32_t> lm = remap(lv), rm = remap(rv);
        auto shadow = [&shadows](const CatColumn& src, int64_t n,
                                 const std::vector<int32_t>& m) {
          CatColumn s;
          s.dtype = 2;
          s.validity = src.validity;
          s.data.assign((size_t)n * 4, 0);
          for (int64_t i = 0; i < n; ++i) {
            int32_t code;
            std::memcpy(&code, src.data.data() + i * 4, 4);
            int32_t u = (code >= 0 && (size_t)code < m.size())
                            ? m[code] : -1;
            std::memcpy(s.data.data() + i * 4, &u, 4);
          }
          shadows.push_back(std::move(s));
          return &shadows.back();
        };
        lkv.push_back({shadow(lc, L.n_rows, lm), 2, 4});
        rkv.push_back({shadow(rc, R.n_rows, rm), 2, 4});
        unified[f] = 1;
        merged_vals[f] = std::move(merged);
        continue;
      }
    }
    lkv.push_back({&lc, cls, lkc.width});
    rkv.push_back({&rc, rkc.cls, rkc.width});
  }

  // build on the right, probe from the left (hash_join.cpp builds on
  // the smaller side; catalog joins are host-sized, simplicity wins)
  std::unordered_map<uint64_t, std::vector<int64_t>> buckets;
  buckets.reserve(R.n_rows * 2);
  for (int64_t j = 0; j < R.n_rows; ++j)
    buckets[row_key_hash(rkv, j)].push_back(j);

  std::vector<int64_t> li_out, ri_out;
  std::vector<uint8_t> r_matched(R.n_rows, 0);
  const bool emit_left = join_type == 1 || join_type == 3;   // left/full
  const bool emit_right = join_type == 2 || join_type == 3;  // right/full
  for (int64_t i = 0; i < L.n_rows; ++i) {
    auto it = buckets.find(row_key_hash(lkv, i));
    bool any = false;
    if (it != buckets.end()) {
      for (int64_t j : it->second) {
        if (rows_key_equal(lkv, i, rkv, j)) {
          li_out.push_back(i);
          ri_out.push_back(j);
          r_matched[j] = 1;
          any = true;
        }
      }
    }
    if (!any && emit_left) {
      li_out.push_back(i);
      ri_out.push_back(-1);
    }
  }
  if (emit_right) {
    for (int64_t j = 0; j < R.n_rows; ++j) {
      if (!r_matched[j]) {
        li_out.push_back(-1);
        ri_out.push_back(j);
      }
    }
  }

  // assemble, matching the device join's naming (_assemble in
  // ops/join.py, itself pandas-merge semantics): a key pair is SHARED
  // only when the two columns have the same name — shared keys emit one
  // coalesced column and the right copy is dropped; differently-named
  // keys stay separate columns (left side null for right-only rows).
  // Remaining name collisions get the pandas _x/_y suffixes.
  CatTable out;
  out.n_rows = static_cast<int64_t>(li_out.size());
  std::unordered_map<std::string, int> name_count;
  std::vector<uint8_t> drop_r(R.cols.size(), 0);   // shared (same-name) keys
  std::vector<int32_t> coalesce_r(L.cols.size(), -1);
  std::vector<int32_t> key_of_l(L.cols.size(), -1);
  for (int32_t f = 0; f < n_keys; ++f) {
    key_of_l[lk_[f]] = f;
    if (L.cols[lk_[f]].name == R.cols[rk_[f]].name) {
      drop_r[rk_[f]] = 1;
      coalesce_r[lk_[f]] = rk_[f];
    }
  }
  // dictionary sidecars never enter the row loops: they are carried
  // table-level metadata (dict length != row count), re-emitted under
  // each surviving dict column's FINAL name at the end
  for (const auto& c : L.cols)
    if (!is_sidecar(c.name)) name_count[c.name]++;
  for (size_t j = 0; j < R.cols.size(); ++j)
    if (!drop_r[j] && !is_sidecar(R.cols[j].name))
      name_count[R.cols[j].name]++;

  auto width_of = [](const CatTable& t, const CatColumn& c) {
    if (t.n_rows > 0) return (int64_t)c.data.size() / t.n_rows;
    int tag = c.dtype & 0xFF;
    return (int64_t)((tag == 2 || tag == 12 || tag == 13) ? 4 : 8);
  };

  // final name -> dictionary values to re-emit
  std::vector<std::pair<std::string, std::vector<std::string>>> out_dicts;

  for (size_t ci = 0; ci < L.cols.size(); ++ci) {
    if (is_sidecar(L.cols[ci].name)) continue;
    int32_t f = key_of_l[ci];
    bool uni = f >= 0 && unified[f];
    // unified dict keys join (and emit) in merged-code space: the
    // shadow columns already hold merged ids for both sides
    const CatColumn& lsrc = uni ? *lkv[f].col : L.cols[ci];
    const int64_t w = uni ? 4 : width_of(L, L.cols[ci]);
    CatColumn col = gather_col_w(lsrc, w, li_out);
    col.name = L.cols[ci].name;
    col.dtype = L.cols[ci].dtype;
    if (coalesce_r[ci] >= 0 && !col.validity.empty()) {
      // shared key: fill right-only rows from the right key column
      const CatColumn& rc = uni ? *rkv[f].col : R.cols[coalesce_r[ci]];
      for (size_t r = 0; r < li_out.size(); ++r) {
        if (li_out[r] >= 0 || ri_out[r] < 0) continue;
        if (!cell_valid(rc, ri_out[r])) continue;
        std::memcpy(col.data.data() + r * w,
                    rc.data.data() + ri_out[r] * w, w);
        col.validity[r] = 1;
      }
      if (std::find(col.validity.begin(), col.validity.end(), 0) ==
          col.validity.end())
        col.validity.clear();
    }
    bool shared_key = coalesce_r[ci] >= 0;
    if (!shared_key && name_count[col.name] > 1) col.name += "_x";
    if (uni) {
      out_dicts.emplace_back(col.name, merged_vals[f]);
    } else {
      std::vector<std::string> dv;
      if (key_class(L.cols[ci], L.n_rows).cls == 2
          && extract_dict(L, L.cols[ci].name, &dv))
        out_dicts.emplace_back(col.name, std::move(dv));
    }
    out.cols.push_back(std::move(col));
  }
  for (size_t cj = 0; cj < R.cols.size(); ++cj) {
    if (drop_r[cj] || is_sidecar(R.cols[cj].name)) continue;
    CatColumn col = gather_col_w(R.cols[cj], width_of(R, R.cols[cj]),
                                 ri_out);
    if (name_count[col.name] > 1) col.name += "_y";
    std::vector<std::string> dv;
    if (key_class(R.cols[cj], R.n_rows).cls == 2
        && extract_dict(R, R.cols[cj].name, &dv))
      out_dicts.emplace_back(col.name, std::move(dv));
    out.cols.push_back(std::move(col));
  }
  for (auto& kv : out_dicts)
    append_dict_sidecars(&out, kv.first, kv.second);
  catalog()[out_id] = std::move(out);
  return 0;
}

}  // extern "C"
