"""ctypes bindings for the native host runtime (``cylon_host.cpp``).

The reference's runtime layers (memory pool ``ctx/memory_pool.hpp``,
murmur3 ``util/murmur3.cpp``, threaded CSV ingest ``table.cpp:788`` /
``io/``) are C++; so are ours. The shared library is built on first use
with the in-image g++ (no pip deps, no pybind11 — plain C ABI + ctypes)
and cached next to this file. Everything degrades gracefully: callers
check :func:`available` and fall back to the pyarrow/numpy paths.
"""

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "cylon_host.cpp")
_SO = os.path.join(_HERE, "libcylon_host.so")

_lib = None
_lock = threading.Lock()
_build_error: str | None = None


def _build() -> str | None:
    """Compile the shared library; returns an error string or None."""
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           "-o", _SO, _SRC]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=300)
    except (OSError, subprocess.TimeoutExpired) as e:
        return f"g++ unavailable: {e}"
    if proc.returncode != 0:
        return f"native build failed: {proc.stderr[-2000:]}"
    return None


def _load():
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        if not os.path.exists(_SO) or (
                os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
            err = _build()
            if err is not None:
                _build_error = err
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            _build_error = str(e)
            return None
        _declare(lib)
        _lib = lib
        return _lib


def _declare(lib):
    c = ctypes
    lib.cylon_pool_create.restype = c.c_void_p
    lib.cylon_pool_create.argtypes = [c.c_int64]
    lib.cylon_pool_destroy.argtypes = [c.c_void_p]
    lib.cylon_pool_alloc.restype = c.c_void_p
    lib.cylon_pool_alloc.argtypes = [c.c_void_p, c.c_int64]
    lib.cylon_pool_free.argtypes = [c.c_void_p, c.c_void_p, c.c_int64]
    lib.cylon_pool_stats.argtypes = [c.c_void_p] + [c.POINTER(c.c_int64)] * 4

    lib.cylon_murmur3_x86_32.restype = c.c_uint32
    lib.cylon_murmur3_x86_32.argtypes = [c.c_void_p, c.c_int, c.c_uint32]
    lib.cylon_murmur3_int64_array.argtypes = [
        c.c_void_p, c.c_int64, c.c_uint32, c.c_void_p]

    lib.cylon_threadpool_create.restype = c.c_void_p
    lib.cylon_threadpool_create.argtypes = [c.c_int]
    lib.cylon_threadpool_destroy.argtypes = [c.c_void_p]
    lib.cylon_threadpool_wait.argtypes = [c.c_void_p]

    lib.cylon_csv_read.restype = c.c_void_p
    lib.cylon_csv_read.argtypes = [c.c_char_p, c.c_char, c.c_int, c.c_int]
    lib.cylon_csv_read_opts.restype = c.c_void_p
    lib.cylon_csv_read_opts.argtypes = [
        c.c_char_p, c.c_char, c.c_int, c.c_int, c.c_char, c.c_char_p,
        c.c_char_p, c.c_int]
    lib.cylon_csv_error.restype = c.c_char_p
    lib.cylon_csv_error.argtypes = [c.c_void_p]
    lib.cylon_csv_num_rows.restype = c.c_int64
    lib.cylon_csv_num_rows.argtypes = [c.c_void_p]
    lib.cylon_csv_num_cols.restype = c.c_int32
    lib.cylon_csv_num_cols.argtypes = [c.c_void_p]
    lib.cylon_csv_col_name.restype = c.c_char_p
    lib.cylon_csv_col_name.argtypes = [c.c_void_p, c.c_int32]
    lib.cylon_csv_col_type.restype = c.c_int32
    lib.cylon_csv_col_type.argtypes = [c.c_void_p, c.c_int32]
    for fn in (lib.cylon_csv_col_i64, lib.cylon_csv_col_f64,
               lib.cylon_csv_col_codes, lib.cylon_csv_col_validity):
        fn.argtypes = [c.c_void_p, c.c_int32, c.c_void_p]
    lib.cylon_csv_dict_size.restype = c.c_int32
    lib.cylon_csv_dict_size.argtypes = [c.c_void_p, c.c_int32]
    lib.cylon_csv_dict_value.restype = c.c_char_p
    lib.cylon_csv_dict_value.argtypes = [c.c_void_p, c.c_int32, c.c_int32]
    lib.cylon_csv_free.argtypes = [c.c_void_p]

    lib.cylon_catalog_put.restype = c.c_int32
    lib.cylon_catalog_put.argtypes = [
        c.c_char_p, c.c_int32, c.POINTER(c.c_char_p),
        c.POINTER(c.c_int32), c.c_int64, c.POINTER(c.c_void_p),
        c.POINTER(c.c_int64), c.POINTER(c.c_void_p)]
    lib.cylon_catalog_rows.restype = c.c_int64
    lib.cylon_catalog_rows.argtypes = [c.c_char_p]
    lib.cylon_catalog_ncols.restype = c.c_int32
    lib.cylon_catalog_ncols.argtypes = [c.c_char_p]
    lib.cylon_catalog_col_info.restype = c.c_int32
    lib.cylon_catalog_col_info.argtypes = [
        c.c_char_p, c.c_int32, c.c_char_p, c.c_int32,
        c.POINTER(c.c_int32), c.POINTER(c.c_int64), c.POINTER(c.c_int32)]
    lib.cylon_catalog_col_read.restype = c.c_int32
    lib.cylon_catalog_col_read.argtypes = [
        c.c_char_p, c.c_int32, c.c_void_p, c.c_int64, c.c_void_p]
    lib.cylon_catalog_join.restype = c.c_int32
    lib.cylon_catalog_join.argtypes = [
        c.c_char_p, c.c_char_p, c.c_char_p, c.c_int32,
        c.POINTER(c.c_int32), c.POINTER(c.c_int32), c.c_int32]
    lib.cylon_catalog_remove.restype = c.c_int32
    lib.cylon_catalog_remove.argtypes = [c.c_char_p]
    lib.cylon_catalog_size.restype = c.c_int32
    lib.cylon_catalog_size.argtypes = []
    lib.cylon_catalog_ids.restype = c.c_int64
    lib.cylon_catalog_ids.argtypes = [c.c_char_p, c.c_int64]
    lib.cylon_catalog_clear.argtypes = []


def available() -> bool:
    return _load() is not None


def build_error() -> str | None:
    _load()
    return _build_error


# ---------------------------------------------------------------- pool
class MemoryPool:
    """Aligned host allocator with stats (parity:
    ``ctx/memory_pool.hpp:24-60``)."""

    def __init__(self, pool_limit_bytes: int = 0):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native runtime unavailable: {_build_error}")
        self._lib = lib
        self._h = lib.cylon_pool_create(pool_limit_bytes)

    def alloc(self, size: int) -> int:
        return self._lib.cylon_pool_alloc(self._h, size)

    def free(self, ptr: int, size: int) -> None:
        self._lib.cylon_pool_free(self._h, ptr, size)

    def stats(self) -> dict:
        vals = [ctypes.c_int64() for _ in range(4)]
        self._lib.cylon_pool_stats(self._h, *[ctypes.byref(v) for v in vals])
        return {"bytes_allocated": vals[0].value,
                "max_memory": vals[1].value,
                "num_allocations": vals[2].value,
                "pooled_bytes": vals[3].value}

    def close(self):
        if self._h:
            self._lib.cylon_pool_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


# -------------------------------------------------------------- murmur3
def murmur3_32(data: bytes, seed: int = 0) -> int:
    """Parity: ``util::MurmurHash3_x86_32``."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native runtime unavailable: {_build_error}")
    return int(lib.cylon_murmur3_x86_32(data, len(data), seed))


def murmur3_int64(keys: np.ndarray, seed: int = 0) -> np.ndarray:
    """Bulk int64 row hash (parity: the per-row murmur loop of
    ``arrow_partition_kernels.cpp:140``)."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native runtime unavailable: {_build_error}")
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    out = np.empty(len(keys), dtype=np.uint32)
    lib.cylon_murmur3_int64_array(
        keys.ctypes.data_as(ctypes.c_void_p), len(keys), seed,
        out.ctypes.data_as(ctypes.c_void_p))
    return out


# ------------------------------------------------------------ csv loader
_COL_INT64, _COL_FLOAT64, _COL_STRING = 0, 1, 2


#: ColType ints of the native parser (cylon_host.h)
_NATIVE_TYPES = {"int64": 0, "float64": 1, "str": 2, "string": 2}


def csv_dtype_ok(t) -> bool:
    """Can the native csv engine represent dtype override ``t``?
    (int64 / float64 / str only — THE acceptance rule, shared by the
    io routing gate and the spec encoder below.)"""
    if t in ("str", "string", str):
        return True
    try:
        return str(np.dtype(t)) in ("int64", "float64")
    except TypeError:
        return False


def _native_type_spec(column_types) -> bytes | None:
    if not column_types:
        return None
    parts = []
    for name, t in column_types.items():
        if not csv_dtype_ok(t):
            raise NotImplementedError(
                f"native csv engine cannot represent dtype {t!r} for "
                f"column {name!r} (int64/float64/str only); use "
                f"engine='arrow'")
        if t in ("str", "string", str):
            code = 2
        else:
            code = _NATIVE_TYPES[str(np.dtype(t))]
        parts.append(f"{name}\x1f{code}")
    return (";".join(parts)).encode()


def read_csv_native(path: str, delimiter: str = ",", header: bool = True,
                    n_threads: int = 0, quote_char: str | None = None,
                    na_values=None, column_types=None,
                    strings_can_be_null: bool = False) -> dict:
    """Chunk-parallel CSV parse → dict of numpy columns (+ dictionaries).

    Returns ``{name: ndarray}`` where string columns come back as
    ``(codes int32, values ndarray[object], validity)`` triples ready for
    :class:`cylon_tpu.column.Column`; numeric columns are int64/float64
    arrays (with a validity array when nulls were seen).

    ``quote_char``/``na_values``/``column_types``/``strings_can_be_null``
    mirror the reference's UseQuoting/NullValues/WithColumnTypes/
    StringsCanBeNull (csv_read_config.hpp:80-141).
    """
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native runtime unavailable: {_build_error}")
    if quote_char or na_values or column_types:
        na = ("\x1f".join(na_values).encode() if na_values else None)
        h = lib.cylon_csv_read_opts(
            path.encode(), delimiter.encode(), 1 if header else 0,
            n_threads, (quote_char or "\x00").encode(), na,
            _native_type_spec(column_types),
            1 if strings_can_be_null else 0)
    else:
        h = lib.cylon_csv_read(path.encode(), delimiter.encode(),
                               1 if header else 0, n_threads)
    try:
        err = lib.cylon_csv_error(h)
        if err:
            raise IOError(err.decode())
        n = lib.cylon_csv_num_rows(h)
        ncols = lib.cylon_csv_num_cols(h)
        out = {}
        for col in range(ncols):
            name = lib.cylon_csv_col_name(h, col).decode()
            typ = lib.cylon_csv_col_type(h, col)
            validity = np.empty(n, dtype=np.uint8)
            lib.cylon_csv_col_validity(
                h, col, validity.ctypes.data_as(ctypes.c_void_p))
            vmask = validity.astype(bool)
            if typ == _COL_INT64:
                data = np.empty(n, dtype=np.int64)
                lib.cylon_csv_col_i64(
                    h, col, data.ctypes.data_as(ctypes.c_void_p))
                out[name] = ("i64", data, vmask)
            elif typ == _COL_FLOAT64:
                data = np.empty(n, dtype=np.float64)
                lib.cylon_csv_col_f64(
                    h, col, data.ctypes.data_as(ctypes.c_void_p))
                out[name] = ("f64", data, vmask)
            else:
                codes = np.empty(n, dtype=np.int32)
                lib.cylon_csv_col_codes(
                    h, col, codes.ctypes.data_as(ctypes.c_void_p))
                k = lib.cylon_csv_dict_size(h, col)
                values = np.array(
                    [lib.cylon_csv_dict_value(h, col, i).decode()
                     for i in range(k)], dtype=object)
                out[name] = ("str", codes, vmask, values)
        return out
    finally:
        lib.cylon_csv_free(h)


def csv_to_table(path: str, delimiter: str = ",", header: bool = True,
                 n_threads: int = 0, capacity: int | None = None,
                 quote_char: str | None = None, na_values=None,
                 column_types=None, strings_can_be_null: bool = False):
    """Native CSV → device :class:`cylon_tpu.table.Table`."""
    import jax.numpy as jnp

    from cylon_tpu import dtypes
    from cylon_tpu.column import Column, Dictionary
    from cylon_tpu.table import Table

    raw = read_csv_native(path, delimiter, header, n_threads,
                          quote_char=quote_char, na_values=na_values,
                          column_types=column_types,
                          strings_can_be_null=strings_can_be_null)
    cols = {}
    n = 0
    for name, payload in raw.items():
        kind = payload[0]
        if kind == "str":
            _, codes, vmask, values = payload
            n = len(codes)
            col = Column.from_numpy(codes.astype(np.int32), capacity)
            validity = None
            if not vmask.all():
                validity = np.concatenate(
                    [vmask, np.zeros(col.capacity - n, bool)])
            cols[name] = Column(col.data,
                                None if validity is None else jnp.asarray(validity),
                                dtypes.string, Dictionary(values))
        else:
            _, data, vmask = payload
            n = len(data)
            col = Column.from_numpy(data, capacity)
            if not vmask.all():
                validity = np.concatenate(
                    [vmask, np.zeros(col.capacity - n, bool)])
                col = Column(col.data, jnp.asarray(validity), col.dtype)
            cols[name] = col
    return Table(cols, n)


# ------------------------------------------------------------- catalog
# Parity: table_api.{hpp,cpp} PutTable/GetTable/RemoveTable (:38-90),
# the registry the reference's Java JNI binding drives
# (Table.java:289-307). The same C symbols are bindable from JNI/cffi/
# .NET; this is the ctypes client. Wire format per column: a raw byte
# buffer + dtype code + optional uint8 validity; dictionary columns ship
# their codes plus two companion pseudo-columns (utf8 blob, int64
# offsets) named "<col>\x01blob" / "<col>\x01offs".

#: dtype tag = Kind enum value | (temporal-unit index << 8); opaque to C.
_UNITS = [None, "s", "ms", "us", "ns", "D", "h", "m", "W"]
_DICT_BLOB = "\x01blob"
_DICT_OFFS = "\x01offs"


def _dtype_tag(dt) -> int:
    if dt.unit not in _UNITS:
        raise ValueError(f"temporal unit {dt.unit!r} not representable "
                         f"in the catalog tag (known: {_UNITS[1:]})")
    return int(dt.kind.value) | (_UNITS.index(dt.unit) << 8)


def _tag_dtype(tag: int):
    from cylon_tpu import dtypes as _dt

    kind = _dt.Kind(tag & 0xFF)
    unit = _UNITS[(tag >> 8) & 0xFF]
    return _dt.DType(kind, unit)


def _require():
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native runtime unavailable: {_build_error}")
    return lib


def catalog_put(table_id: str, table) -> None:
    """Copy a (host-materialised) Table into the native catalog
    (parity: ``PutTable``, table_api.hpp:38)."""
    lib = _require()
    n = table.num_rows
    names, dtags, bufs, lens, vals = [], [], [], [], []

    def add(name, arr, tag, validity=None):
        arr = np.ascontiguousarray(arr)
        names.append(name.encode())
        dtags.append(tag)
        bufs.append(arr)
        lens.append(arr.nbytes)
        vals.append(validity)

    from cylon_tpu import dtypes as _dt

    for name, c in table.columns.items():
        data = np.asarray(c.data[:n])
        validity = None
        if c.validity is not None:
            validity = np.ascontiguousarray(
                np.asarray(c.validity[:n]), dtype=np.uint8)
        add(name, data, _dtype_tag(c.dtype), validity)
        if c.dtype.is_dictionary and c.dictionary is not None:
            blobs = [str(v).encode() for v in c.dictionary.values]
            offs = np.zeros(len(blobs) + 1, np.int64)
            for i, b in enumerate(blobs):
                offs[i + 1] = offs[i] + len(b)
            blob = (np.frombuffer(b"".join(blobs), np.uint8).copy()
                    if blobs else np.zeros(0, np.uint8))
            add(name + _DICT_BLOB, blob, _dtype_tag(_dt.uint8))
            add(name + _DICT_OFFS, offs, _dtype_tag(_dt.int64))

    nc = len(names)
    c_names = (ctypes.c_char_p * nc)(*names)
    c_dtypes = (ctypes.c_int32 * nc)(*dtags)
    c_bufs = (ctypes.c_void_p * nc)(
        *[b.ctypes.data_as(ctypes.c_void_p).value for b in bufs])
    c_lens = (ctypes.c_int64 * nc)(*lens)
    c_vals = (ctypes.c_void_p * nc)(
        *[(v.ctypes.data_as(ctypes.c_void_p).value if v is not None else None)
          for v in vals])
    rc = lib.cylon_catalog_put(table_id.encode(), nc, c_names, c_dtypes,
                               n, c_bufs, c_lens, c_vals)
    if rc != 0:
        raise RuntimeError(f"catalog put failed rc={rc}")


def catalog_get(table_id: str):
    """Rebuild a cylon_tpu Table from a native catalog entry
    (parity: ``GetTable``, table_api.hpp:44)."""
    import jax.numpy as jnp

    from cylon_tpu.column import Column, Dictionary
    from cylon_tpu.table import Table

    lib = _require()
    n = lib.cylon_catalog_rows(table_id.encode())
    if n < 0:
        raise KeyError(table_id)
    nc = lib.cylon_catalog_ncols(table_id.encode())
    raw = {}
    for i in range(nc):
        cap = 512
        while True:
            name_buf = ctypes.create_string_buffer(cap)
            tag = ctypes.c_int32()
            nbytes = ctypes.c_int64()
            hasv = ctypes.c_int32()
            rc = lib.cylon_catalog_col_info(table_id.encode(), i, name_buf,
                                            cap, ctypes.byref(tag),
                                            ctypes.byref(nbytes),
                                            ctypes.byref(hasv))
            if rc < 0:
                raise RuntimeError(f"catalog col_info failed rc={rc}")
            if rc < cap:  # full name fit
                break
            cap = rc + 1
        dt = _tag_dtype(tag.value)
        npdt = np.dtype(dt.physical)
        if nbytes.value % npdt.itemsize:
            raise RuntimeError(
                f"column {i} of {table_id!r}: byte length {nbytes.value} "
                f"not a multiple of {npdt} itemsize (foreign writer bug?)")
        data = np.empty(nbytes.value // npdt.itemsize, npdt)
        validity = np.empty(n, np.uint8) if hasv.value else None
        rc = lib.cylon_catalog_col_read(
            table_id.encode(), i, data.ctypes.data_as(ctypes.c_void_p),
            data.nbytes,
            validity.ctypes.data_as(ctypes.c_void_p)
            if validity is not None else None)
        if rc != 0:
            raise RuntimeError(f"catalog col_read failed rc={rc}")
        raw[name_buf.value.decode()] = (dt, data, validity)

    cols = {}
    for name, (dt, data, validity) in raw.items():
        if _DICT_BLOB in name or _DICT_OFFS in name:
            continue
        vmask = (None if validity is None
                 else jnp.asarray(validity.astype(bool)))
        dictionary = None
        if name + _DICT_BLOB in raw:
            _, blob, _ = raw[name + _DICT_BLOB]
            _, offs, _ = raw[name + _DICT_OFFS]
            b = blob.tobytes()
            dictionary = Dictionary(np.array(
                [b[offs[j]:offs[j + 1]].decode()
                 for j in range(len(offs) - 1)], object))
        cols[name] = Column(jnp.asarray(data), vmask, dt, dictionary)
    return Table(cols, n)


def catalog_ids() -> list:
    lib = _require()
    need = lib.cylon_catalog_ids(None, 0)
    while True:
        buf = ctypes.create_string_buffer(int(need) + 1)
        got = lib.cylon_catalog_ids(buf, need + 1)
        if got <= need:  # fit (a concurrent put may have grown the set)
            break
        need = got
    s = buf.value.decode()
    return sorted(s.split("\n")) if s else []


def catalog_remove(table_id: str) -> None:
    if _require().cylon_catalog_remove(table_id.encode()) != 0:
        raise KeyError(table_id)


def catalog_clear() -> None:
    _require().cylon_catalog_clear()
