/* cylon_host.h — public C ABI of the cylon_tpu native host runtime.
 *
 * This is the surface a foreign-language binding links against — the
 * same role the reference's JNI bridge plays over its string-id table
 * catalog (`cpp/src/cylon/table_api.hpp:38-90`,
 * `java/src/main/native/src/Table.cpp`). A Java/Go/Rust host calls
 * these with plain buffers; the Python side binds them via ctypes
 * (`cylon_tpu/native/__init__.py`).
 *
 * Build: g++ -O2 -shared -fPIC -std=c++17 cylon_host.cpp -o
 *        libcylon_host.so   (done automatically on first import)
 *
 * Thread safety: every function is safe to call from any thread; the
 * catalog and pool are internally locked.
 */

#ifndef CYLON_HOST_H_
#define CYLON_HOST_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* ---- memory pool (parity: ctx/memory_pool.hpp) --------------------- */
/* 64-byte-aligned allocations with size-bucketed free lists. */
void*    cylon_pool_create(int64_t pool_limit_bytes);
void     cylon_pool_destroy(void* pool);
void*    cylon_pool_alloc(void* pool, int64_t size);
void     cylon_pool_free(void* pool, void* buf, int64_t size);
void     cylon_pool_stats(void* pool, int64_t* bytes_allocated,
                          int64_t* max_memory, int64_t* num_allocations,
                          int64_t* pooled_bytes);

/* ---- murmur3 (parity: util/murmur3.cpp) ---------------------------- */
uint32_t cylon_murmur3_x86_32(const void* key, int len, uint32_t seed);
/* Vectorised per-element hash of an int64 array (the row-hash the
 * hash-partitioner uses); out must hold n uint32. */
void     cylon_murmur3_int64_array(const int64_t* keys, int64_t n,
                                   uint32_t seed, uint32_t* out);

/* ---- thread pool (parity: table.cpp:788 per-file reader threads) --- */
typedef void (*cylon_task_fn)(void* arg);
void*    cylon_threadpool_create(int n_threads);
void     cylon_threadpool_destroy(void* tp);
void     cylon_threadpool_submit(void* tp, cylon_task_fn fn, void* arg);
void     cylon_threadpool_wait(void* tp);

/* ---- chunk-parallel CSV reader (parity: io/csv_read_config) -------- */
/* Column dtypes in results: 0 = int64, 1 = float64, 2 = dictionary-
 * encoded string (int32 codes + per-column dictionary). */
void*       cylon_csv_read(const char* path, char delim, int has_header,
                           int n_threads);
/* Extended options (parity: UseQuoting/WithQuoteChar/NullValues/
 * WithColumnTypes of csv_read_config.hpp):
 *   quote_char  0 disables quoting; else RFC-4180 quoting with doubled
 *               quotes for literals (no embedded newlines).
 *   na_values   '\x1f'-joined null spellings, or NULL.
 *   col_types   "name\x1f<type int>;..." per-column overrides, or NULL. */
void*       cylon_csv_read_opts(const char* path, char delim,
                                int has_header, int n_threads,
                                char quote_char, const char* na_values,
                                const char* col_types,
                                int strings_can_be_null);
const char* cylon_csv_error(void* r);          /* NULL when ok */
int64_t     cylon_csv_num_rows(void* r);
int32_t     cylon_csv_num_cols(void* r);
const char* cylon_csv_col_name(void* r, int32_t col);
int32_t     cylon_csv_col_type(void* r, int32_t col);
void        cylon_csv_col_i64(void* r, int32_t col, int64_t* out);
void        cylon_csv_col_f64(void* r, int32_t col, double* out);
void        cylon_csv_col_codes(void* r, int32_t col, int32_t* out);
void        cylon_csv_col_validity(void* r, int32_t col, uint8_t* out);
int32_t     cylon_csv_dict_size(void* r, int32_t col);
const char* cylon_csv_dict_value(void* r, int32_t col, int32_t code);
void        cylon_csv_free(void* r);

/* ---- string-id table catalog (parity: table_api.hpp) --------------- */
/* dtypes: 0 = int64, 1 = float64, 2 = int32 codes (dictionary handled
 * by the binding layer). Returns 0 on success, negative on error. */
int32_t  cylon_catalog_put(const char* id, int32_t ncols,
                           const char** names, const int32_t* dtypes,
                           int64_t n_rows, const void** data_bufs,
                           const int64_t* data_lens,
                           const uint8_t** validity_bufs);
int64_t  cylon_catalog_rows(const char* id);      /* -1 if missing */
int32_t  cylon_catalog_ncols(const char* id);     /* -1 if missing */
int32_t  cylon_catalog_col_info(const char* id, int32_t i,
                                char* name_out, int32_t name_cap,
                                int32_t* dtype_out,
                                int64_t* data_len_out,
                                int32_t* has_validity_out);
int32_t  cylon_catalog_col_read(const char* id, int32_t i,
                                void* data_out, int64_t data_cap,
                                uint8_t* validity_out);
/* Native host hash join (parity: table_api JoinTables behind the JNI
 * nativeJoin surface, Table.java:289-307; build/probe like
 * join/hash_join.cpp:22-31). Joins catalog tables left_id and right_id
 * on n_keys column-index pairs and stores the result under out_id.
 * join_type: 0 inner, 1 left, 2 right, 3 full outer. Null keys match
 * null keys (pandas merge semantics). Output columns follow the device
 * join (ops/join.py _assemble): same-NAME key pairs emit one coalesced
 * column (right copy dropped); differently-named keys stay separate;
 * remaining name collisions get the _x/_y suffixes.
 * Returns 0, or negative on error (-2 missing id, -3 bad key index,
 * -4 key dtype mismatch). */
int32_t  cylon_catalog_join(const char* left_id, const char* right_id,
                            const char* out_id, int32_t n_keys,
                            const int32_t* left_keys,
                            const int32_t* right_keys,
                            int32_t join_type);
int32_t  cylon_catalog_remove(const char* id);
int32_t  cylon_catalog_size(void);
void     cylon_catalog_clear(void);
/* Write newline-separated ids into buf (cap bytes); returns the number
 * of bytes that would be needed. */
int64_t  cylon_catalog_ids(char* buf, int64_t cap);

#ifdef __cplusplus
}  /* extern "C" */
#endif

#endif  /* CYLON_HOST_H_ */
