"""Row view: typed access to one table row.

Parity: ``cpp/src/cylon/row.{hpp,cpp}`` — ``Row`` with per-type getters
(``row.hpp:23``: GetInt8..GetInt64, GetFloat/GetDouble, GetBool,
GetString) addressed by column index. Here rows are host-side views
fetched from the device table in ONE batched ``jax.device_get`` per
row (``Table.row`` slices every column's element on device, transfers
them together under a ``table.row_fetch`` span, and decodes host-side
— a per-field fetch would pay the fixed ~100 ms tunnel RPC once per
column). The getters below are pure host accessors over the already-
fetched values; columnar access remains the fast path in both systems.
"""

from typing import Any, Iterator

import numpy as np


class Row:
    """One row of a :class:`cylon_tpu.table.Table` (host view)."""

    __slots__ = ("_names", "_values")

    def __init__(self, names, values):
        self._names = names
        self._values = values

    # -- generic access --------------------------------------------------
    def __getitem__(self, key) -> Any:
        if isinstance(key, int):
            return self._values[key]
        return self._values[self._names.index(key)]

    def __len__(self) -> int:
        return len(self._values)

    def __iter__(self) -> Iterator[Any]:
        return iter(self._values)

    def keys(self):
        return list(self._names)

    def to_dict(self) -> dict:
        return dict(zip(self._names, self._values))

    def __repr__(self):
        inner = ", ".join(f"{n}={v!r}"
                          for n, v in zip(self._names, self._values))
        return f"Row({inner})"

    def __eq__(self, other):
        if isinstance(other, Row):
            return (self._names == other._names
                    and self._values == other._values)
        return NotImplemented

    def __hash__(self):
        # value-based, like __eq__ (hash(1) == hash(1.0) in Python, so
        # the hash/eq contract holds across int/float typed columns)
        return hash((tuple(self._names), tuple(self._values)))

    # -- typed getters (row.hpp:23 surface) ------------------------------
    def _typed(self, i: int, kinds, exclude=()) -> Any:
        v = self._values[i if isinstance(i, int) else self._names.index(i)]
        bad = not isinstance(v, kinds) or isinstance(v, exclude)
        if bad and v is not None:
            raise TypeError(f"column {i}: {type(v).__name__} is not "
                            f"{'/'.join(k.__name__ for k in kinds)}")
        return v

    def get_int64(self, i) -> int | None:
        # bool is an int subclass in Python; the typed surface keeps
        # them distinct like the reference's per-type getters
        return self._typed(i, (int, np.integer), exclude=(bool, np.bool_))

    get_int8 = get_int16 = get_int32 = get_int64
    get_uint8 = get_uint16 = get_uint32 = get_uint64 = get_int64

    def get_double(self, i) -> float | None:
        return self._typed(i, (float, np.floating))

    get_float = get_half_float = get_double

    def get_bool(self, i) -> bool | None:
        return self._typed(i, (bool, np.bool_))

    def get_string(self, i) -> str | None:
        return self._typed(i, (str,))
