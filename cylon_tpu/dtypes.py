"""Logical type system mapped onto TPU-friendly physical dtypes.

Parity target: the reference type system in ``cpp/src/cylon/data_types.hpp``
(``Type::type`` enum lines 25-90, ``Layout`` fixed/variable-width, factory
functions lines 141-166) and the Arrow bridge ``cpp/src/cylon/arrow/arrow_types.hpp``.

TPU-first deviations:

- Every device column is a fixed-width ``jnp`` array. Variable-width data
  (STRING/BINARY) is **dictionary-encoded at ingest** on the host: the device
  sees ``int32`` codes, the dictionary (unique values) stays host-side as a
  numpy object array. Relational ops (join/groupby/sort on hash order/unique)
  operate on codes; order-sensitive string ops re-encode with a sorted
  dictionary so code order == lexicographic order.
- Temporal types are int64 on device with unit metadata here.
- float64/int64 are fully supported (jax x64 is enabled by the package);
  bf16/f32 are preferred for compute-heavy aggregation paths.
"""

import dataclasses
import enum

import jax.numpy as jnp
import numpy as np


class Kind(enum.IntEnum):
    """Logical kind. Parity: ``data_types.hpp:25-90`` ``Type::type``."""

    BOOL = 0
    UINT8 = 1
    INT8 = 2
    UINT16 = 3
    INT16 = 4
    UINT32 = 5
    INT32 = 6
    UINT64 = 7
    INT64 = 8
    HALF_FLOAT = 9
    FLOAT = 10
    DOUBLE = 11
    STRING = 12
    BINARY = 13
    FIXED_SIZE_BINARY = 14
    DATE32 = 15
    DATE64 = 16
    TIMESTAMP = 17
    TIME32 = 18
    TIME64 = 19
    DURATION = 21


class Layout(enum.IntEnum):
    """Parity: ``data_types.hpp`` Layout (fixed vs variable width)."""

    FIXED_WIDTH = 1
    VARIABLE_WIDTH = 2  # dictionary-encoded on device


_PHYSICAL = {
    Kind.BOOL: jnp.bool_,
    Kind.UINT8: jnp.uint8,
    Kind.INT8: jnp.int8,
    Kind.UINT16: jnp.uint16,
    Kind.INT16: jnp.int16,
    Kind.UINT32: jnp.uint32,
    Kind.INT32: jnp.int32,
    Kind.UINT64: jnp.uint64,
    Kind.INT64: jnp.int64,
    Kind.HALF_FLOAT: jnp.float16,
    Kind.FLOAT: jnp.float32,
    Kind.DOUBLE: jnp.float64,
    Kind.STRING: jnp.int32,  # dictionary codes
    Kind.BINARY: jnp.int32,  # dictionary codes
    Kind.FIXED_SIZE_BINARY: jnp.int32,
    Kind.DATE32: jnp.int32,
    Kind.DATE64: jnp.int64,
    Kind.TIMESTAMP: jnp.int64,
    Kind.TIME32: jnp.int32,
    Kind.TIME64: jnp.int64,
    Kind.DURATION: jnp.int64,
}


@dataclasses.dataclass(frozen=True)
class DType:
    """Logical dtype. Parity: ``cylon::DataType`` (``data_types.hpp:94-139``).

    STRING/BINARY columns have two device layouts (the rebuild of the
    reference's variable-width ``Layout``, ``data_types.hpp:141``):
    dictionary codes (``bytes_width is None`` — int32 codes + host
    dictionary) or device bytes (``bytes_width`` set — [cap, nwords]
    big-endian uint32 words, :mod:`cylon_tpu.ops.bytescol`).
    """

    kind: Kind
    unit: str | None = None  # temporal unit ("s"/"ms"/"us"/"ns") when applicable
    bytes_width: int | None = None  # device-bytes string: padded byte width

    @property
    def physical(self) -> jnp.dtype:
        """Device representation dtype."""
        if self.bytes_width is not None:
            return jnp.dtype(jnp.uint32)
        return jnp.dtype(_PHYSICAL[self.kind])

    @property
    def layout(self) -> Layout:
        if self.kind in (Kind.STRING, Kind.BINARY):
            return Layout.VARIABLE_WIDTH
        return Layout.FIXED_WIDTH

    @property
    def is_dictionary(self) -> bool:
        """True if the device array holds dictionary codes."""
        return (self.kind in (Kind.STRING, Kind.BINARY)
                and self.bytes_width is None)

    @property
    def is_bytes(self) -> bool:
        """True if the device array holds packed big-endian byte words."""
        return self.bytes_width is not None

    @property
    def is_numeric(self) -> bool:
        return self.kind in (
            Kind.UINT8, Kind.INT8, Kind.UINT16, Kind.INT16, Kind.UINT32,
            Kind.INT32, Kind.UINT64, Kind.INT64, Kind.HALF_FLOAT, Kind.FLOAT,
            Kind.DOUBLE,
        )

    @property
    def is_floating(self) -> bool:
        return self.kind in (Kind.HALF_FLOAT, Kind.FLOAT, Kind.DOUBLE)

    def __repr__(self):
        if self.bytes_width is not None:
            return f"{self.kind.name.lower()}[bytes:{self.bytes_width}]"
        u = f"[{self.unit}]" if self.unit else ""
        return f"{self.kind.name.lower()}{u}"


# Factory singletons, mirroring data_types.hpp:141-166 factory functions.
bool_ = DType(Kind.BOOL)
uint8 = DType(Kind.UINT8)
int8 = DType(Kind.INT8)
uint16 = DType(Kind.UINT16)
int16 = DType(Kind.INT16)
uint32 = DType(Kind.UINT32)
int32 = DType(Kind.INT32)
uint64 = DType(Kind.UINT64)
int64 = DType(Kind.INT64)
float16 = DType(Kind.HALF_FLOAT)
float32 = DType(Kind.FLOAT)
float64 = DType(Kind.DOUBLE)
string = DType(Kind.STRING)
binary = DType(Kind.BINARY)
date32 = DType(Kind.DATE32)
date64 = DType(Kind.DATE64)


def string_bytes(width: int) -> DType:
    """Device-bytes string dtype (``width`` padded bytes per row; the
    device array is [cap, width/4] big-endian uint32 words)."""
    if width % 4:
        width += 4 - width % 4
    return DType(Kind.STRING, None, int(width))


def timestamp(unit: str = "ns") -> DType:
    return DType(Kind.TIMESTAMP, unit)


def duration(unit: str = "ns") -> DType:
    return DType(Kind.DURATION, unit)


_NUMPY_TO_KIND = {
    np.dtype(np.bool_): Kind.BOOL,
    np.dtype(np.uint8): Kind.UINT8,
    np.dtype(np.int8): Kind.INT8,
    np.dtype(np.uint16): Kind.UINT16,
    np.dtype(np.int16): Kind.INT16,
    np.dtype(np.uint32): Kind.UINT32,
    np.dtype(np.int32): Kind.INT32,
    np.dtype(np.uint64): Kind.UINT64,
    np.dtype(np.int64): Kind.INT64,
    np.dtype(np.float16): Kind.HALF_FLOAT,
    np.dtype(np.float32): Kind.FLOAT,
    np.dtype(np.float64): Kind.DOUBLE,
}


def from_numpy_dtype(dt) -> DType:
    """numpy dtype -> logical DType (parity: ``arrow_types.cpp`` bridge)."""
    dt = np.dtype(dt)
    if dt.kind in ("U", "S", "O"):
        return string
    if dt.kind == "M":  # datetime64
        unit = np.datetime_data(dt)[0]
        return timestamp(unit)
    if dt.kind == "m":
        unit = np.datetime_data(dt)[0]
        return duration(unit)
    kind = _NUMPY_TO_KIND.get(dt)
    if kind is None:
        raise TypeError(f"unsupported numpy dtype {dt}")
    return DType(kind)


def sentinel_high(phys: jnp.dtype):
    """Largest value of a physical dtype — used to pad invalid rows so they
    sort to the end (replaces the reference's exact-length buffers; XLA needs
    static shapes so padded rows must be order-inert)."""
    phys = jnp.dtype(phys)
    if phys == jnp.bool_:
        return True
    if jnp.issubdtype(phys, jnp.floating):
        return jnp.inf
    return jnp.iinfo(phys).max


def sentinel_low(phys: jnp.dtype):
    phys = jnp.dtype(phys)
    if phys == jnp.bool_:
        return False
    if jnp.issubdtype(phys, jnp.floating):
        return -jnp.inf
    return jnp.iinfo(phys).min
