"""Typed option structs.

Parity targets: ``cpp/src/cylon/join/join_config.hpp:25-197`` (JoinType,
JoinAlgorithm, JoinConfig), ``cpp/src/cylon/table.hpp:378-394`` (SortOptions),
``cpp/src/cylon/io/csv_read_config.hpp:28-152`` / ``csv_write_config.hpp``.
The reference uses builder-style C++ structs; here they are frozen dataclasses.
"""

import dataclasses
import enum
from typing import Sequence


class JoinType(enum.Enum):
    """Parity: ``join_config.hpp`` JoinType {INNER, LEFT, RIGHT, FULL_OUTER}."""

    INNER = "inner"
    LEFT = "left"
    RIGHT = "right"
    FULL_OUTER = "fullouter"


class JoinAlgorithm(enum.Enum):
    """Parity: ``join_config.hpp`` JoinAlgorithm {SORT, HASH}.

    SORT groups rows by lexicographic key rank; HASH by murmur bucket
    with the key words as collision tiebreakers
    (``kernels.group_sort(hash_first=True)``) — the TPU rendition of the
    reference's flat_hash_map build/probe (``join/hash_join.cpp:22-31``).
    Both are exact and produce identical row sets.
    """

    SORT = "sort"
    HASH = "hash"


@dataclasses.dataclass(frozen=True)
class JoinConfig:
    """Parity: ``join_config.hpp:42-197``."""

    join_type: JoinType = JoinType.INNER
    algorithm: JoinAlgorithm = JoinAlgorithm.SORT
    left_on: Sequence[str] = ()
    right_on: Sequence[str] = ()
    left_suffix: str = "_x"
    right_suffix: str = "_y"

    @staticmethod
    def make(join_type="inner", algorithm="sort", left_on=(), right_on=(),
             suffixes=("_x", "_y")) -> "JoinConfig":
        jt = JoinType(join_type) if not isinstance(join_type, JoinType) else join_type
        alg = (JoinAlgorithm(algorithm)
               if not isinstance(algorithm, JoinAlgorithm) else algorithm)
        return JoinConfig(jt, alg, tuple(left_on), tuple(right_on),
                          suffixes[0], suffixes[1])


@dataclasses.dataclass(frozen=True)
class SortOptions:
    """Parity: ``table.hpp:378-383`` SortOptions{num_bins, num_samples}.

    Controls distributed range partitioning (``dist_sort``):
    ``num_bins == 0`` (default) uses strided-sample splitters (each
    shard contributes ``num_samples`` sorted samples, one all_gather);
    ``num_bins > 0`` uses the reference's histogram scheme instead —
    distributed min/max, a ``num_bins``-bucket fixed-width histogram
    psum-reduced across shards, split points at count quantiles
    (``arrow_partition_kernels.cpp:334-421``).
    """

    num_bins: int = 0        # 0 -> sample splitters; >0 -> histogram
    num_samples: int = 0     # 0 -> 1024
    ascending: bool = True


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Knobs for the retry/backoff engine (:mod:`cylon_tpu.resilience`).

    No reference analog: ``cylon::Status`` threads error codes but never
    retries. Delays follow ``min(base_delay * multiplier**k, max_delay)``
    with a DETERMINISTIC jitter drawn from ``seed`` — two processes with
    the same policy back off identically, so failure traces replay
    exactly (the property the fault-injection harness tests against).

    The process-wide default policy reads env overrides:
    ``CYLON_TPU_RETRY_ATTEMPTS`` / ``_BASE_DELAY`` / ``_MAX_DELAY`` /
    ``_MULTIPLIER`` / ``_JITTER`` (see
    :func:`cylon_tpu.resilience.default_policy`).
    """

    max_attempts: int = 3      # total attempts, including the first
    base_delay: float = 0.05   # seconds before the first retry
    max_delay: float = 2.0     # backoff ceiling (pre-jitter)
    multiplier: float = 2.0    # exponential growth per retry
    jitter: float = 0.1        # +- fraction, deterministic from seed
    seed: int = 0


#: Default per-section deadline budgets (seconds) for the watchdog
#: layer (:mod:`cylon_tpu.watchdog`); ``None`` = unbounded, preserving
#: the historical wait-forever semantics unless an ambient
#: ``watchdog.deadline(...)`` scope is active. Each section is
#: env-overridable per call via ``CYLON_TPU_DEADLINE_<SECTION>``
#: (uppercased section name; ``0`` or negative clears it back to
#: unbounded), so a deployment can bound e.g. every barrier at 300 s
#: without touching code.
DEADLINE_SECTIONS: "dict[str, float | None]" = {
    "barrier": None,         # CylonEnv.barrier device drain
    "bootstrap": None,       # jax.distributed.initialize (multihost)
    "overflow_fetch": None,  # plan._check_overflow batched device_get
    "spill_io": None,        # SpillStore bucket write/read
    "ooc_pass": None,        # out-of-core join/groupby/sort passes
    "ooc_prefetch": None,    # one pipelined-ingest unit (cylon_tpu.pipeline)
    "exchange": None,        # shuffle/repartition/dist_join dispatch
    "serve_request": None,   # one serve-layer query step (cylon_tpu.serve)
    "router_poll": None,     # one fleet-router health/events poll
    "fallback_merge": None,  # two-phase fallback global merge (fallback.py)
}


@dataclasses.dataclass(frozen=True)
class DeadlinePolicy:
    """Knobs for the deadline/watchdog layer (:mod:`cylon_tpu.watchdog`).

    No reference analog: the reference's async all-to-all surfaces
    progress via its ``isComplete()`` loop but every host-side wait
    still blocks forever. Here a monitor thread (started lazily — never
    unless some section runs under a deadline) watches registered
    blocking sections and, when one stalls past its budget, dumps
    all-thread stack traces to stderr with the section label and
    elapsed time, then either lets the section raise
    :class:`~cylon_tpu.errors.DeadlineExceeded` (``action="raise"``,
    the default) or kills the process (``action="abort"`` — the honest
    policy for a wedged collective no raise can unwind; exit code 70).

    The process default reads env overrides per call (see
    :func:`cylon_tpu.watchdog.default_deadline_policy`):
    ``CYLON_TPU_WATCHDOG_POLL`` / ``CYLON_TPU_DEADLINE_ACTION`` /
    ``CYLON_TPU_DEADLINE_DUMP``.
    """

    #: monitor re-scan cadence while an already-dumped section is still
    #: stalled (waits for undumped expiries are exact/event-driven)
    poll_interval: float = 0.05
    action: str = "raise"        # "raise" | "abort" (os._exit(70))
    dump_stacks: bool = True     # all-thread stacks to stderr on stall


@dataclasses.dataclass(frozen=True)
class CSVReadOptions:
    """Parity: ``io/csv_read_config.hpp:28-152`` — every builder method
    becomes a field (UseThreads, WithDelimiter, IgnoreEmptyLines,
    BlockSize, IncludeColumns, SkipRows, ColumnNames,
    AutoGenerateColumnNames, UseQuoting/WithQuoteChar/DoubleQuote,
    UseEscaping/EscapingCharacter, HasNewLinesInValues, NullValues,
    TrueValues/FalseValues, StringsCanBeNull, WithColumnTypes,
    ConcurrentFileReads, Slice, IncludeMissingColumns).

    The native engine handles quoting, ``na_values`` and
    ``column_types``; escaping, true/false values, embedded newlines and
    skip_rows route to the arrow engine automatically."""

    use_threads: bool = True
    delimiter: str = ","
    ignore_emptylines: bool = True
    block_size: int = 1 << 22
    use_cols: Sequence[str] | None = None
    skip_rows: int = 0
    column_names: Sequence[str] | None = None
    slice: bool = False  # distributed read: shard rows across the mesh
    concurrent_file_reads: bool = True
    auto_generate_column_names: bool = False
    # quoting (UseQuoting/WithQuoteChar/DoubleQuote)
    use_quoting: bool = True
    quote_char: str = '"'
    double_quote: bool = True
    # escaping (UseEscaping/EscapingCharacter)
    use_escaping: bool = False
    escaping_character: str = "\\"
    has_newlines_in_values: bool = False
    # null/bool spellings (NullValues/TrueValues/FalseValues/
    # StringsCanBeNull)
    na_values: Sequence[str] | None = None
    true_values: Sequence[str] | None = None
    false_values: Sequence[str] | None = None
    strings_can_be_null: bool = False
    # explicit per-column dtypes (WithColumnTypes): {name: "int64" |
    # "float64" | "str" | np.dtype-like}
    column_types: "dict | None" = None
    include_missing_columns: bool = False

    def __hash__(self):  # dict/sequence fields -> canonical tuples
        def h(v):
            if isinstance(v, dict):
                return tuple(sorted((k, str(x)) for k, x in v.items()))
            if isinstance(v, (list, tuple)):
                return tuple(v)
            return v

        return hash(tuple(h(getattr(self, f.name))
                          for f in dataclasses.fields(self)))


@dataclasses.dataclass(frozen=True)
class CSVWriteOptions:
    """Parity: ``io/csv_write_config.hpp``."""

    delimiter: str = ","
    include_header: bool = True


@dataclasses.dataclass(frozen=True)
class ParquetOptions:
    """Parity: ``io/parquet_config.hpp`` ParquetOptions — ChunkSize /
    ConcurrentFileReads, with the WriterProperties indirection flattened
    into the properties users actually set through it (compression,
    row-group size, dictionary encoding, column subset on write).

    Read side: ``concurrent_file_reads`` toggles the per-file thread
    pool (reference spawns a std::thread per file, table.cpp:1121-1127);
    ``use_cols`` restricts the columns read. Write side maps onto
    pyarrow's writer.
    """

    # read
    concurrent_file_reads: bool = True
    use_cols: Sequence[str] | None = None
    # write (WriterProperties flattened)
    compression: str = "snappy"      # "none"|"snappy"|"gzip"|"zstd"|...
    row_group_size: int | None = None  # rows per row group (ChunkSize)
    use_dictionary: bool = True
    write_cols: Sequence[str] | None = None  # column subset on write

    def __hash__(self):
        def h(v):
            return tuple(v) if isinstance(v, (list, tuple)) else v

        return hash(tuple(h(getattr(self, f.name))
                          for f in dataclasses.fields(self)))
