"""Series: a single named device column with elementwise compute.

Parity target: ``python/pycylon/series.py`` (Series over a single
Cylon column) plus the single-column slice of the compute engine
(``python/pycylon/data/compute.pyx``: comparison/math ops :455-700,
``is_in`` :702, ``drop_na`` :728). All elementwise math lowers to one
fused XLA program on the padded device array; validity (null) masks
propagate through operations the way Arrow's validity bitmaps do.
"""

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from cylon_tpu import dtypes
from cylon_tpu.column import Column
from cylon_tpu.errors import InvalidArgument, TypeError_


class Series:
    """One named column + valid-row count (parity: pycylon ``Series``)."""

    def __init__(self, data=None, name: str = "", capacity: int | None = None,
                 nrows=None):
        if isinstance(data, Series):
            self._col, self._nrows, self.name = data._col, data._nrows, name or data.name
            return
        if isinstance(data, Column):
            # a bare Column carries no row count; pass nrows when the
            # column's capacity exceeds its logical length (padding)
            self._col = data
            self._nrows = jnp.asarray(
                data.capacity if nrows is None else nrows, jnp.int32)
        else:
            arr = np.asarray(data)
            self._col = Column.from_numpy(arr, capacity)
            self._nrows = jnp.asarray(len(arr), jnp.int32)
        self.name = name

    @staticmethod
    def _wrap(col: Column, nrows, name: str = "") -> "Series":
        s = object.__new__(Series)
        s._col, s._nrows, s.name = col, nrows, name
        return s

    # -- accessors -------------------------------------------------------
    @property
    def column(self) -> Column:
        return self._col

    @property
    def dtype(self) -> dtypes.DType:
        return self._col.dtype

    @property
    def nrows(self):
        return self._nrows

    def _require_local(self, what: str):
        # a [W]-vector nrows means the column is mesh-distributed
        # (frame.DataFrame.series keeps the layout); only elementwise
        # ops are defined there
        if getattr(self._nrows, "ndim", 0) == 1:
            raise InvalidArgument(
                f"{what} on a distributed Series; use the DataFrame "
                "reductions with env= (dist_aggregate) or materialise "
                "the frame first")

    def __len__(self):
        self._require_local("len()")
        return int(self._nrows)

    @property
    def shape(self):
        return (len(self),)

    @property
    def values(self) -> np.ndarray:
        return self.to_numpy()

    def to_numpy(self) -> np.ndarray:
        return self._col.to_numpy(len(self))

    def to_pandas(self):
        import pandas as pd

        return pd.Series(self.to_numpy(), name=self.name or None)

    def __repr__(self):
        return f"Series(name={self.name!r}, {self.to_numpy()!r})"

    # -- elementwise engine ---------------------------------------------
    def _valid(self) -> jax.Array | None:
        return self._col.validity

    def _binop(self, other, fn: Callable, out_kind=None) -> "Series":
        c = self._col
        if c.dtype.is_dictionary or c.dtype.is_bytes:
            raise TypeError_("math on string series requires codes/decode")
        if isinstance(other, Series):
            o, ov = other._col.data, other._col.validity
        elif isinstance(other, Column):
            o, ov = other.data, other.validity
        else:
            o, ov = other, None
        data = fn(c.data, o)
        validity = c.validity
        if ov is not None:
            validity = ov if validity is None else (validity & ov)
        dt = (dtypes.from_numpy_dtype(np.dtype(data.dtype))
              if out_kind is None else out_kind)
        return Series._wrap(Column(data, validity, dt), self._nrows, self.name)

    def _rbinop(self, other, fn):
        return self._binop(other, lambda a, b: fn(b, a))

    def __add__(self, o): return self._binop(o, jnp.add)
    def __radd__(self, o): return self._rbinop(o, jnp.add)
    def __sub__(self, o): return self._binop(o, jnp.subtract)
    def __rsub__(self, o): return self._rbinop(o, jnp.subtract)
    def __mul__(self, o): return self._binop(o, jnp.multiply)
    def __rmul__(self, o): return self._rbinop(o, jnp.multiply)
    def __truediv__(self, o): return self._binop(o, jnp.true_divide)
    def __rtruediv__(self, o): return self._rbinop(o, jnp.true_divide)
    def __floordiv__(self, o): return self._binop(o, jnp.floor_divide)
    def __rfloordiv__(self, o): return self._rbinop(o, jnp.floor_divide)
    def __mod__(self, o): return self._binop(o, jnp.mod)
    def __pow__(self, o): return self._binop(o, jnp.power)
    def __neg__(self): return self._binop(0, lambda a, _: jnp.negative(a))
    def __abs__(self): return self._binop(0, lambda a, _: jnp.abs(a))

    def _cmp_op(self, o, name: str, fn: Callable) -> "Series":
        """Comparison dispatch: device-bytes string columns compare by
        big-endian word order on device (bytewise string order — the
        binary-comparator role of ``arrow_comparator.cpp``); everything
        else goes through the elementwise engine."""
        c = self._col
        if c.dtype.is_bytes and isinstance(o, str):
            from cylon_tpu.ops import bytescol

            lt, eq = bytescol.cmp_scalar(c, o)
            m = {"eq": eq, "ne": ~eq, "lt": lt, "le": lt | eq,
                 "gt": ~(lt | eq), "ge": ~lt}[name]
            if c.validity is not None:
                # pandas null semantics: null != x is True, every other
                # comparison with null is False
                m = (m | ~c.validity) if name == "ne" else (m & c.validity)
            return Series._wrap(Column(m, None, dtypes.bool_),
                                self._nrows, self.name)
        if c.dtype.is_bytes and isinstance(o, (Series, Column)) \
                and name in ("eq", "ne"):
            from cylon_tpu.ops import bytescol

            oc = o._col if isinstance(o, Series) else o
            if oc.dtype.is_bytes or oc.dtype.is_dictionary:
                ca, cb = bytescol.align_storages([c, oc])
                m = (ca.data == cb.data).all(axis=1)
                bothv = None
                for v in (ca.validity, cb.validity):
                    if v is not None:
                        bothv = v if bothv is None else (bothv & v)
                if bothv is not None:
                    m = m & bothv
                if name == "ne":
                    m = ~m  # null != anything -> True (pandas parity,
                    #         same rule as the scalar path above)
                return Series._wrap(Column(m, None, dtypes.bool_),
                                    self._nrows, self.name)
        return self._binop(o, fn, dtypes.bool_)

    def __eq__(self, o): return self._cmp_op(o, "eq", jnp.equal)
    def __ne__(self, o): return self._cmp_op(o, "ne", jnp.not_equal)
    def __lt__(self, o): return self._cmp_op(o, "lt", jnp.less)
    def __le__(self, o): return self._cmp_op(o, "le", jnp.less_equal)
    def __gt__(self, o): return self._cmp_op(o, "gt", jnp.greater)
    def __ge__(self, o): return self._cmp_op(o, "ge", jnp.greater_equal)

    def __and__(self, o): return self._binop(o, jnp.logical_and, dtypes.bool_)
    def __or__(self, o): return self._binop(o, jnp.logical_or, dtypes.bool_)
    def __xor__(self, o): return self._binop(o, jnp.logical_xor, dtypes.bool_)

    def __invert__(self):
        return self._binop(0, lambda a, _: jnp.logical_not(a), dtypes.bool_)

    def __hash__(self):  # __eq__ is elementwise; keep identity hashing
        return id(self)

    # -- null handling ---------------------------------------------------
    def null_flags(self) -> jax.Array:
        """[capacity] bool, True where missing (validity or float NaN)."""
        from cylon_tpu.ops.selection import _null_flags

        f = _null_flags(self._col)
        return (jnp.zeros(self._col.capacity, bool) if f is None
                else f.astype(bool))

    def isnull(self) -> "Series":
        return Series._wrap(Column(self.null_flags(), None, dtypes.bool_),
                            self._nrows, self.name)

    isna = isnull

    def notnull(self) -> "Series":
        return Series._wrap(Column(~self.null_flags(), None, dtypes.bool_),
                            self._nrows, self.name)

    notna = notnull

    def fillna(self, value) -> "Series":
        c = self._col
        if c.dtype.is_bytes:
            from cylon_tpu.ops import bytescol

            return Series._wrap(bytescol.fill_value(c, value), self._nrows,
                                self.name)
        if c.dtype.is_dictionary:
            from cylon_tpu.ops.dictenc import encode_fill_value

            if c.validity is None:
                return self
            c2, code = encode_fill_value(c, value)
            data = jnp.where(c2.validity, c2.data, jnp.int32(code))
            return Series._wrap(Column(data, None, c2.dtype, c2.dictionary),
                                self._nrows, self.name)
        data = c.data
        if jnp.issubdtype(data.dtype, jnp.floating):
            data = jnp.where(jnp.isnan(data), value, data)
        if c.validity is not None:
            data = jnp.where(c.validity, data, jnp.asarray(value, data.dtype))
        return Series._wrap(Column(data, None, c.dtype, c.dictionary),
                            self._nrows, self.name)

    def dropna(self) -> "Series":
        from cylon_tpu.ops import kernels

        self._require_local("dropna()")
        mask = ~self.null_flags()
        perm, count = kernels.compact_mask(mask, self._nrows)
        c = self._col
        safe = jnp.clip(perm, 0, max(c.capacity - 1, 0))
        col = Column(c.data[safe],
                     None if c.validity is None else c.validity[safe],
                     c.dtype, c.dictionary)
        return Series._wrap(col, count, self.name)

    # -- membership / map ------------------------------------------------
    def isin(self, values) -> "Series":
        """Parity: ``compute.pyx`` is_in (:702). A null-ish probe value
        (None / NaN) matches null rows, like pandas isin([None]); a
        type-incompatible probe value never matches but does not poison
        the rest of the list (pandas: isin([1, 'a']) still matches 1)."""
        from cylon_tpu.ops.bytescol import is_nullish

        c = self._col
        vset = list(values)
        if c.dtype.is_bytes:
            from cylon_tpu.ops import bytescol

            mask = bytescol.isin(c, vset)
            return Series._wrap(Column(mask, None, dtypes.bool_),
                                self._nrows, self.name)
        has_null = any(is_nullish(v) for v in vset)
        vals = [v for v in vset if not is_nullish(v)]
        if c.dtype.is_dictionary:
            dvals = [] if c.dictionary is None else c.dictionary.values
            lut = {v: i for i, v in enumerate(dvals)}
            probe = [lut[v] for v in vals if v in lut]
            pdt = np.int32
        elif c.dtype.kind in (dtypes.Kind.TIMESTAMP, dtypes.Kind.DURATION,
                              dtypes.Kind.DATE32, dtypes.Kind.DATE64):
            # temporal columns store unit-scaled ints; coerce probes
            # through numpy temporal space at the column's unit (a raw
            # int compare against a datetime64 probe would never match)
            unit = c.dtype.unit or (
                "D" if c.dtype.kind == dtypes.Kind.DATE32 else "ms")
            cast = (np.timedelta64 if c.dtype.kind == dtypes.Kind.DURATION
                    else np.datetime64)
            pdt = np.dtype(c.data.dtype)
            probe = []
            for v in vals:
                if isinstance(v, (int, float, bool)):
                    continue  # pandas: a bare number never matches a date
                try:
                    probe.append(np.asarray(
                        cast(v, unit).astype(np.int64), pdt)[()])
                except (TypeError, ValueError):
                    continue
        else:
            pdt = np.dtype(c.data.dtype)
            probe = []
            for v in vals:
                try:
                    cv = np.asarray(v, pdt)[()]
                except (TypeError, ValueError, OverflowError):
                    continue
                if cv == v:  # 1.5 must not match int 1 via truncation
                    probe.append(cv)
        if probe:
            p = jnp.asarray(np.asarray(probe, pdt))
            mask = (c.data[:, None] == p[None, :]).any(axis=1)
        else:
            mask = jnp.zeros(c.capacity, bool)
        if c.validity is not None:
            mask = mask & c.validity
            if has_null:
                mask = mask | ~c.validity
        elif has_null and jnp.issubdtype(c.data.dtype, jnp.floating):
            # floats without a validity buffer carry nulls as NaN
            mask = mask | jnp.isnan(c.data)
        return Series._wrap(Column(mask, None, dtypes.bool_), self._nrows,
                            self.name)

    def _dict_pred(self, pred: Callable) -> "Series":
        """Boolean mask from a host predicate over the dictionary values
        of a string column. The predicate runs once per DISTINCT value
        (host-side, tiny); the row mask is an ``isin`` over matching
        codes — the device never sees bytes. This is how LIKE-style
        predicates (``p_type LIKE 'PROMO%'``) map onto dictionary
        encoding."""
        c = self._col
        if not c.dtype.is_dictionary:
            raise TypeError_("string predicate on non-string column")
        vals = [] if c.dictionary is None else list(c.dictionary.values)
        return self.isin([v for v in vals if pred(v)])

    def _bytes_pred(self, mask) -> "Series":
        return Series._wrap(Column(mask, None, dtypes.bool_), self._nrows,
                            self.name)

    @property
    def str(self) -> "_StrAccessor":
        """pandas-style string accessor (``s.str.startswith(...)``),
        covering both device layouts: device-bytes columns run windowed
        byte kernels on device, dictionary columns evaluate once per
        distinct value on host."""
        return _StrAccessor(self)

    def str_startswith(self, prefix: str) -> "Series":
        """Rows whose value starts with ``prefix`` (pandas
        ``Series.str.startswith``; always literal). Device-bytes
        columns run the windowed byte compare on device
        (:func:`bytescol.startswith`) — no host dictionary scan."""
        if self._col.dtype.is_bytes:
            from cylon_tpu.ops import bytescol

            return self._bytes_pred(bytescol.startswith(self._col, prefix))
        return self._dict_pred(lambda v: v is not None
                               and str(v).startswith(prefix))

    def str_endswith(self, suffix: str) -> "Series":
        """Rows whose value ends with ``suffix`` (pandas
        ``Series.str.endswith``; always literal)."""
        if self._col.dtype.is_bytes:
            from cylon_tpu.ops import bytescol

            return self._bytes_pred(bytescol.endswith(self._col, suffix))
        return self._dict_pred(lambda v: v is not None
                               and str(v).endswith(suffix))

    def str_contains(self, pat: str, regex: bool = True) -> "Series":
        """Rows whose value contains ``pat`` — a regex by default, same
        as pandas ``Series.str.contains``; pass ``regex=False`` for
        literal substring matching. Device-bytes columns: literal
        patterns (and regexes with no metacharacters) run the shifted
        window compare on device; a true regex decodes to host (the one
        string op with no device form)."""
        import re

        if self._col.dtype.is_bytes:
            from cylon_tpu.ops import bytescol

            if not regex or not re.search(r"[.^$*+?{}\[\]\\|()]", pat):
                return self._bytes_pred(bytescol.contains(self._col, pat))
            rx = re.compile(pat)
            self._require_local("str_contains(regex) on device bytes")
            host = self.to_numpy()
            hits = np.array([v is not None and rx.search(str(v)) is not None
                             for v in host], bool)
            mask = jnp.zeros(self._col.capacity, bool
                             ).at[:len(hits)].set(jnp.asarray(hits))
            return self._bytes_pred(mask)
        if regex:
            rx = re.compile(pat)
            return self._dict_pred(lambda v: v is not None
                                   and rx.search(str(v)) is not None)
        return self._dict_pred(lambda v: v is not None and pat in str(v))

    def map(self, fn: Callable) -> "Series":
        """Elementwise map (parity: ``compute.pyx`` infer_map :805). A
        jnp-traceable ``fn`` compiles into the XLA graph; anything else
        falls back to a host round-trip like the reference's inferred
        python loop."""
        c = self._col
        if c.dtype.is_bytes:
            # arbitrary python fn over variable-length values: host
            # round trip (decode, map, re-ingest as bytes)
            self._require_local("map() on device bytes")
            host = np.array([fn(v) for v in self.to_numpy()], object)
            col = Column.from_numpy(host, c.capacity,
                                    string_storage="bytes") \
                if all(isinstance(v, str) or v is None for v in host) \
                else Column.from_numpy(host, c.capacity)
            return Series._wrap(col, self._nrows, self.name)
        if c.dtype.is_dictionary:
            from cylon_tpu.ops.dictenc import reencode_values

            vals = [fn(v) for v in c.dictionary.values]
            return Series._wrap(reencode_values(c, vals), self._nrows,
                                self.name)
        try:
            data = jax.vmap(fn)(c.data)
            dt = dtypes.from_numpy_dtype(np.dtype(data.dtype))
            return Series._wrap(Column(data, c.validity, dt), self._nrows,
                                self.name)
        except Exception:
            host = np.array([fn(v) for v in self.to_numpy()])
            out = Series(host, self.name)
            return out

    applymap = map

    # -- reductions ------------------------------------------------------
    def _reduce(self, op: str):
        import jax

        from cylon_tpu.ops import aggregates
        from cylon_tpu.table import Table

        self._require_local(f"{op}()")
        t = Table({self.name or "x": self._col}, self._nrows)
        res = aggregates.table_aggregate(t, self.name or "x", op)
        if isinstance(res, jax.core.Tracer):
            return res  # under whole-query trace: stay on device
        return np.asarray(res)[()]

    def sum(self): return self._reduce("sum")
    def count(self): return self._reduce("count")
    def min(self): return self._reduce("min")
    def max(self): return self._reduce("max")
    def mean(self): return self._reduce("mean")
    def var(self): return self._reduce("var")
    def std(self): return self._reduce("std")
    def nunique(self): return self._reduce("nunique")

    def unique(self) -> np.ndarray:
        """Distinct values, host-side (parity: ``table.pyx`` unique on a
        single column)."""
        vals = self.to_numpy()
        seen, out = set(), []
        for v in vals:
            k = v if v == v else None  # NaN folds
            if k not in seen:
                seen.add(k)
                out.append(v)
        return np.asarray(out, dtype=vals.dtype)


class _StrAccessor:
    """``Series.str`` — the pandas string-method namespace (parity:
    pandas ``Series.str``; the reference exposes string compute through
    pycylon's compute surface). Methods dispatch on the column's device
    layout; see the ``str_*`` methods on :class:`Series`."""

    def __init__(self, s: Series):
        self._s = s

    def startswith(self, prefix: str) -> Series:
        return self._s.str_startswith(prefix)

    def endswith(self, suffix: str) -> Series:
        return self._s.str_endswith(suffix)

    def contains(self, pat: str, regex: bool = True) -> Series:
        return self._s.str_contains(pat, regex=regex)

    def len(self) -> Series:
        """Value length in CHARACTERS for both layouts (pandas
        semantics): host map over distinct values for dictionary
        columns, a device UTF-8 start-byte count
        (:func:`bytescol.char_lengths`) for device-bytes columns — the
        two storages agree on non-ASCII data."""
        s = self._s
        c = s.column
        if c.dtype.is_bytes:
            from cylon_tpu.ops import bytescol

            data = bytescol.char_lengths(c.data)
            return Series._wrap(Column(data, c.validity, dtypes.int32),
                                s._nrows, s.name)
        if c.dtype.is_dictionary:
            import numpy as np

            vals = ([] if c.dictionary is None
                    else [len(str(v)) for v in c.dictionary.values])
            lut = jnp.asarray(np.asarray(vals or [0], np.int32))
            data = lut[jnp.clip(c.data, 0, max(len(vals) - 1, 0))]
            return Series._wrap(Column(data, c.validity, dtypes.int32),
                                s._nrows, s.name)
        raise TypeError_("str.len() on non-string column")

    def _ascii_case(self, upper: bool) -> Series:
        s = self._s
        c = s.column
        if c.dtype.is_bytes:
            # ASCII case transform fully on device: flip bit 5 of a-z /
            # A-Z bytes inside each big-endian word; non-ASCII (>=0x80)
            # bytes are multi-byte UTF-8 payload and pass through
            lo, hi = (0x61, 0x7A) if upper else (0x41, 0x5A)
            data = c.data
            out = jnp.zeros_like(data)
            for shift in (24, 16, 8, 0):
                b = (data >> jnp.uint32(shift)) & jnp.uint32(0xFF)
                flip = (b >= lo) & (b <= hi)
                b = jnp.where(flip, b ^ jnp.uint32(0x20), b)
                out = out | (b << jnp.uint32(shift))
            return Series._wrap(Column(out, c.validity, c.dtype),
                                s._nrows, s.name)
        if c.dtype.is_dictionary:
            from cylon_tpu.ops.dictenc import reencode_values

            fn = str.upper if upper else str.lower
            vals = [None if v is None else fn(str(v))
                    for v in (c.dictionary.values
                              if c.dictionary is not None else [])]
            return Series._wrap(reencode_values(c, vals), s._nrows,
                                s.name)
        raise TypeError_("str case transform on non-string column")

    def upper(self) -> Series:
        """ASCII upper-case (device-side for bytes columns; non-ASCII
        characters pass through unchanged)."""
        return self._ascii_case(True)

    def lower(self) -> Series:
        return self._ascii_case(False)
