"""Pandas-style indexing over device tables.

Parity target: ``cpp/src/cylon/indexing/`` — ``IndexingType`` and the
``BaseArrowIndex`` family (``indexing/index.hpp:36-42,108-425``), the
loc/iloc indexers (``indexing/indexer.hpp:76,123``), and the PyCylon
facade (``python/pycylon/indexing/index.pyx:71-371``).

TPU redesign: the reference's hash-map indices (flat_hash_map from value
to row positions) don't map to XLA; the equivalents here are

- :class:`RangeIndex` — positional, zero-storage (parity
  ``ArrowRangeIndex``),
- :class:`LinearIndex` — vectorized full-column comparison, O(n) per
  probe batch but embarrassingly parallel on the VPU (parity
  ``ArrowLinearIndex``),
- :class:`HashIndex` — a *sorted* permutation of the key column probed
  with ``searchsorted`` (O(log n) per probe). It answers exactly the
  queries the reference's ``ArrowNumericHashIndex``/``ArrowBinaryHashIndex``
  answer, with a sort in place of a hash table — the standing TPU
  substitution used across this codebase.
"""

from cylon_tpu.indexing.index import (
    BaseIndex,
    HashIndex,
    IndexingType,
    LinearIndex,
    RangeIndex,
    build_index,
)
from cylon_tpu.indexing.indexer import ILocIndexer, LocIndexer

__all__ = [
    "BaseIndex",
    "HashIndex",
    "ILocIndexer",
    "IndexingType",
    "LinearIndex",
    "LocIndexer",
    "RangeIndex",
    "build_index",
]
