"""loc / iloc indexers over DataFrame.

Parity: ``ArrowLocIndexer``/``ArrowILocIndexer``
(``indexing/indexer.hpp:76,123``, impl ``indexing/indexer.cpp``) and the
``PyLocIndexer`` facade (``python/pycylon/indexing/index.pyx:71-371``).
Supported key shapes mirror the reference: scalar value, list of values,
closed value range (slice), each optionally with a column or list of
columns as the second tuple element.
"""

from typing import Sequence

import jax.numpy as jnp
import numpy as np

from cylon_tpu.errors import IndexError_, KeyError_
from cylon_tpu.indexing.index import BaseIndex, RangeIndex
from cylon_tpu.ops import kernels
from cylon_tpu.ops.selection import take_columns


def _split_key(key):
    if isinstance(key, tuple) and len(key) == 2:
        return key[0], key[1]
    return key, None


def _col_subset(df, cols):
    if cols is None:
        return df.columns
    if isinstance(cols, str):
        return [cols]
    if isinstance(cols, slice):
        names = df.columns
        lo = 0 if cols.start is None else names.index(cols.start)
        hi = len(names) - 1 if cols.stop is None else names.index(cols.stop)
        return names[lo:hi + 1]
    return list(cols)


def _take_with_index(df, idx, nrows, cols):
    from cylon_tpu.frame import DataFrame

    t = take_columns(df.table, jnp.asarray(idx, jnp.int32), nrows,
                     names=cols)
    # labels ride along the gather — an implicit RangeIndex degrades to a
    # LinearIndex of the original positions (pandas keeps old labels)
    new_index = df.index.take(jnp.asarray(idx, jnp.int32), nrows)
    return DataFrame._wrap(t, index=new_index)


class LocIndexer:
    """Value-based row selection (parity: ``ArrowLocIndexer``,
    indexing/indexer.hpp:76)."""

    def __init__(self, df):
        self._df = df

    def __getitem__(self, key):
        rows, cols = _split_key(key)
        df = self._df._materialized()
        names = _col_subset(df, cols)
        index: BaseIndex = df.index

        if isinstance(rows, slice):
            if rows.step is not None:
                raise IndexError_("loc slices do not support a step")
            cap = df.table.capacity
            if rows.start is None and rows.stop is None:
                mask = df.table.row_mask()
            else:
                vals = index.to_numpy()
                start = rows.start
                stop = rows.stop
                if start is None:
                    start = vals.min() if len(vals) else 0
                if stop is None:
                    stop = vals.max() if len(vals) else 0
                mask = index.mask_range(cap, start, stop)
            perm, count = kernels.compact_mask(mask, df.table.nrows)
            return _take_with_index(df, perm, count, names)

        single = np.isscalar(rows) or isinstance(rows, (str, bytes))
        probe = [rows] if single else list(rows)
        # boolean mask passthrough (pandas-compatible convenience)
        arr = np.asarray(probe)
        if arr.dtype == bool:
            mask = jnp.asarray(arr)
            if mask.shape[0] != df.table.capacity:
                pad = jnp.zeros(df.table.capacity - mask.shape[0], bool)
                mask = jnp.concatenate([mask, pad])
            mask = mask & df.table.row_mask()
            perm, count = kernels.compact_mask(mask, df.table.nrows)
            return _take_with_index(df, perm, count, names)

        pos, found = index.locate(probe)
        ok = np.asarray(found)
        if not ok.all():
            missing = [p for p, f in zip(probe, ok) if not f]
            raise KeyError_(f"labels not found in index: {missing}")
        return _take_with_index(df, pos, len(probe), names)


class ILocIndexer:
    """Position-based row selection (parity: ``ArrowILocIndexer``,
    indexing/indexer.hpp:123)."""

    def __init__(self, df):
        self._df = df

    def __getitem__(self, key):
        rows, cols = _split_key(key)
        df = self._df._materialized()
        names = _col_subset(df, cols)
        n = df.table.num_rows

        if isinstance(rows, (bool, np.bool_)):
            raise IndexError_("iloc position cannot be a bool")
        if isinstance(rows, slice):
            idx = np.arange(n)[rows]
        elif np.isscalar(rows):
            r = int(rows)
            if r < 0:
                r += n
            if not 0 <= r < n:
                raise IndexError_(f"position {rows} out of range [0, {n})")
            idx = np.array([r])
        else:
            idx = np.asarray(rows)
            if idx.dtype == bool:
                idx = np.nonzero(idx[:n])[0]
            else:
                idx = np.where(idx < 0, idx + n, idx)
                if ((idx < 0) | (idx >= n)).any():
                    raise IndexError_(f"positions out of range [0, {n})")
        return _take_with_index(df, idx, len(idx), names)
