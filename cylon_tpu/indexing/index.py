"""Index structures: value -> row-position resolution on device.

Parity: ``indexing/index.hpp`` (``IndexingType`` :36-42; ``BaseArrowIndex``
:108; ``ArrowNumericHashIndex``/``ArrowBinaryHashIndex`` :246;
``ArrowRangeIndex`` :393; ``ArrowLinearIndex`` :425; builder kernels
:455-521).
"""

import enum
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from cylon_tpu import dtypes
from cylon_tpu.column import Column
from cylon_tpu.errors import InvalidArgument, KeyError_


class IndexingType(enum.Enum):
    """Parity: ``indexing/index.hpp:36-42``. BINARY_TREE/BTREE are accepted
    and resolve to the sorted (HASH) implementation — on TPU a sorted
    permutation IS the search tree."""

    RANGE = 0
    LINEAR = 1
    HASH = 2
    BINARY_TREE = 3
    BTREE = 4


class BaseIndex:
    """Parity: ``BaseArrowIndex`` (indexing/index.hpp:108). Resolves index
    values to row positions; all probes are vectorized device programs."""

    indexing_type: IndexingType
    name: str | None

    def __len__(self):  # pragma: no cover - abstract
        raise NotImplementedError

    def locate(self, values) -> tuple[jax.Array, jax.Array]:
        """values -> (positions[int32], found[bool]) — first matching row
        per probe (parity: LocationByValue)."""
        raise NotImplementedError

    def mask_range(self, capacity: int, start, stop) -> jax.Array:
        """Boolean row mask for index values in [start, stop] (closed on
        both ends — pandas .loc slice semantics)."""
        raise NotImplementedError

    def to_numpy(self) -> np.ndarray:
        raise NotImplementedError

    def values_column(self) -> Column | None:
        """The backing column (None for RangeIndex)."""
        return None

    def take(self, idx: jax.Array, nrows) -> "BaseIndex":
        """Gather index entries by row position (keeps the index aligned
        through filters/sorts)."""
        raise NotImplementedError


class RangeIndex(BaseIndex):
    """Positional index 0..n-1 (parity: ``ArrowRangeIndex``,
    indexing/index.hpp:393)."""

    indexing_type = IndexingType.RANGE

    def __init__(self, nrows, name: str | None = None):
        self._nrows = nrows
        self.name = name

    def __len__(self):
        return int(self._nrows)

    def locate(self, values):
        vals = jnp.atleast_1d(jnp.asarray(values, jnp.int32))
        found = (vals >= 0) & (vals < jnp.asarray(self._nrows, jnp.int32))
        return vals, found

    def mask_range(self, capacity: int, start, stop):
        pos = jnp.arange(capacity, dtype=jnp.int32)
        return (pos >= start) & (pos <= stop) & (pos < self._nrows)

    def to_numpy(self):
        return np.arange(int(self._nrows))

    def take(self, idx, nrows):
        # positions are regenerated; a taken range index degrades to the
        # gathered positions as a linear index (pandas keeps old labels)
        col = Column(jnp.asarray(idx, jnp.int64), None, dtypes.int64)
        return LinearIndex(col, nrows, self.name)


class LinearIndex(BaseIndex):
    """Full-scan index (parity: ``ArrowLinearIndex``, indexing/index.hpp:425).
    Probe cost O(n) per batch but fully vectorized."""

    indexing_type = IndexingType.LINEAR

    def __init__(self, column: Column, nrows, name: str | None = None):
        self.column = column
        self._nrows = nrows
        self.name = name

    def __len__(self):
        return int(self._nrows)

    def _encode_probe(self, values):
        vals = np.atleast_1d(np.asarray(values, dtype=object))
        if self.column.dtype.is_dictionary:
            lut = {v: i for i, v in enumerate(self.column.dictionary.values)}
            codes = np.array([lut.get(v, -1) for v in vals], np.int32)
            return jnp.asarray(codes)
        return jnp.asarray(vals.astype(np.dtype(self.column.data.dtype)))

    def locate(self, values):
        probe = self._encode_probe(values)
        data = self.column.data
        cap = data.shape[0]
        valid = jnp.arange(cap, dtype=jnp.int32) < self._nrows
        if self.column.validity is not None:
            valid = valid & self.column.validity
        eq = (data[None, :] == probe[:, None]) & valid[None, :]
        found = eq.any(axis=1)
        pos = jnp.argmax(eq, axis=1).astype(jnp.int32)
        return pos, found

    def mask_range(self, capacity: int, start, stop):
        if self.column.dtype.is_dictionary:
            # a bound need not be an existing value: map to the code range
            # via the sorted dictionary (codes are value-ordered)
            vals = self.column.dictionary.values
            lo = jnp.int32(np.searchsorted(vals, start, side="left"))
            hi = jnp.int32(np.searchsorted(vals, stop, side="right") - 1)
        else:
            lo = self._encode_probe([start])[0]
            hi = self._encode_probe([stop])[0]
        data = self.column.data
        valid = jnp.arange(capacity, dtype=jnp.int32) < self._nrows
        if self.column.validity is not None:
            valid = valid & self.column.validity
        return (data >= lo) & (data <= hi) & valid

    def mask_isin(self, capacity: int, values):
        probe = self._encode_probe(values)
        data = self.column.data
        valid = jnp.arange(capacity, dtype=jnp.int32) < self._nrows
        if self.column.validity is not None:
            valid = valid & self.column.validity
        return (data[:, None] == probe[None, :]).any(axis=1) & valid

    def to_numpy(self):
        return self.column.to_numpy(int(self._nrows))

    def values_column(self):
        return self.column

    def take(self, idx, nrows):
        safe = jnp.clip(idx, 0, max(self.column.capacity - 1, 0))
        c = self.column
        col = Column(c.data[safe],
                     None if c.validity is None else c.validity[safe],
                     c.dtype, c.dictionary)
        return type(self)(col, nrows, self.name)


class HashIndex(LinearIndex):
    """Sorted-permutation index probed by ``searchsorted`` (parity:
    ``ArrowNumericHashIndex``/``ArrowBinaryHashIndex``,
    indexing/index.hpp:246 — same query surface, sort instead of
    flat_hash_map; see module docstring)."""

    indexing_type = IndexingType.HASH

    def __init__(self, column: Column, nrows, name: str | None = None):
        super().__init__(column, nrows, name)
        cap = column.capacity
        key = column.data
        # pad & nulls get a high sentinel; a real row carrying the sentinel
        # value itself is disambiguated by sorting the invalid flag as a
        # secondary key (valid rows first among equal keys) and checking it
        # at probe time
        sent = dtypes.sentinel_high(key.dtype)
        valid = jnp.arange(cap, dtype=jnp.int32) < jnp.asarray(nrows, jnp.int32)
        if column.validity is not None:
            valid = valid & column.validity
        masked = jnp.where(valid, key, jnp.asarray(sent, key.dtype))
        invalid = (~valid).astype(jnp.uint8)
        iota = jnp.arange(cap, dtype=jnp.int32)
        self._sorted, sv, self._perm = jax.lax.sort(
            (masked, invalid, iota), num_keys=2, is_stable=True)
        self._sorted_valid = sv == 0

    def locate(self, values):
        probe = self._encode_probe(values)
        slot = jnp.searchsorted(self._sorted, probe.astype(self._sorted.dtype))
        slot = jnp.clip(slot, 0, self._sorted.shape[0] - 1)
        found = (self._sorted[slot] == probe.astype(self._sorted.dtype)) \
            & self._sorted_valid[slot]
        return self._perm[slot], found


def build_index(column: Column, nrows,
                indexing_type: IndexingType = IndexingType.HASH,
                name: str | None = None) -> BaseIndex:
    """Parity: the index-builder kernels of ``indexing/index.hpp:455-521``
    + ``IndexUtil``. BINARY_TREE/BTREE collapse to HASH (sorted)."""
    if indexing_type == IndexingType.RANGE:
        return RangeIndex(nrows, name)
    if indexing_type == IndexingType.LINEAR:
        return LinearIndex(column, nrows, name)
    if indexing_type in (IndexingType.HASH, IndexingType.BINARY_TREE,
                         IndexingType.BTREE):
        return HashIndex(column, nrows, name)
    raise InvalidArgument(f"unknown indexing type {indexing_type}")
