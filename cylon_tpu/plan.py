"""Whole-query compilation: one XLA program per query.

The reference's answer to multi-operator queries is the L7 streaming
op-graph — ``DisJoinOP`` builds partition→shuffle→split→join chains and
overlaps their progress on table chunks (``ops/dis_join_op.cpp:21-72``,
schedulers ``ops/execution/execution.hpp:43-110``). That machinery
exists because eager C++ operators would otherwise serialise on the
network.

The TPU-first reimagining: **the query is a traced function**. Every
operator in this framework is jit-safe (static capacities, no
data-dependent host control flow), so an entire query —
filter→join→join→groupby→sort→head — compiles into ONE XLA executable
in which the compiler overlaps compute and ICI collectives at the
instruction level (what the reference's RoundRobin/Priority schedulers
approximate by hand). Host involvement drops to one dispatch plus one
result fetch — on a tunneled chip (~100 ms/sync) this collapses the
5-10 per-operator syncs an eager chain pays.

Two pieces:

* :func:`capacity_scale` / :func:`current_scale` — an ambient multiplier
  applied to every *defaulted* capacity bound chosen while tracing.
  Powers of two keep the shape space (and hence compile count) bounded.
* :func:`compile_query` — wrap a query function (Tables/DataFrames in,
  Table/DataFrame out) into a compiled, capacity-adaptive callable:
  run at scale 1; if any result shard overflowed its buffer
  (``OutOfCapacity``), double the scale and re-dispatch. The XLA
  compilation cache (persistent, see ``cylon_tpu/__init__``) makes the
  retry cheap; steady-state reruns hit the right scale's executable
  directly via :class:`CompiledQuery`'s scale memo.
"""

import collections
import contextlib
import contextvars
import functools
import threading

import jax

from cylon_tpu import telemetry
from cylon_tpu.errors import OutOfCapacity
from cylon_tpu.telemetry import trace as _trace

__all__ = ["capacity_scale", "current_scale", "compile_query",
           "CompiledQuery", "MAX_SCALE", "note_overflow",
           "tight_enabled", "current_row_hint", "row_hint",
           "shared_compiled", "plan_cache_stats",
           "query_fingerprint"]

#: regrow ceiling: 2^10 = 1024x the default budget. Buffers grow only as
#: far as the retry that fits (geometric, ~10 re-dispatches worst case);
#: past this the workload is a near-cross-join and the caller should set
#: an explicit capacity or rethink the keys. Device memory, not this
#: constant, is the practical bound — the reference behaves the same way
#: (its dynamically allocated receives simply OOM on a true cross join).
MAX_SCALE = 1024

_SCALE: contextvars.ContextVar = contextvars.ContextVar(
    "cylon_capacity_scale", default=1)

#: trace-time overflow-flag registry. Tables carry overflow as a
#: poisoned ``nrows > capacity`` the host check reads off the result —
#: but a compiled query that returns only a *scalar* (q6/q14/q17 shape)
#: has no table in its result pytree, so an internal join/groupby
#: truncation would otherwise come back as plausible-looking on-device
#: poison (NaN / iinfo.min). Ops therefore also register their 0-d bool
#: overflow indicators here while tracing; :class:`CompiledQuery`
#: returns the OR of them alongside the result and checks it on host.
_FLAGS: contextvars.ContextVar = contextvars.ContextVar(
    "cylon_overflow_flags", default=None)


def note_overflow(flag) -> None:
    """Register a 0-d bool overflow indicator with the enclosing
    :class:`CompiledQuery` trace (no-op outside one). Ops whose result
    cannot carry table-poison (scalar aggregates) MUST call this; ops
    that do poison ``nrows`` may also call it — the flag check subsumes
    the result-table scan when intermediate poison could be masked by a
    downstream op."""
    lst = _FLAGS.get()
    if lst is not None:
        import jax.numpy as jnp

        lst.append(jnp.asarray(flag).reshape(()))


@contextlib.contextmanager
def _collect_flags(into: list):
    tok = _FLAGS.set(into)
    try:
        yield
    finally:
        _FLAGS.reset(tok)


@contextlib.contextmanager
def capacity_scale(scale: int):
    """Ambient multiplier for defaulted capacity bounds (trace-time)."""
    tok = _SCALE.set(int(scale))
    try:
        yield
    finally:
        _SCALE.reset(tok)


def current_scale() -> int:
    return _SCALE.get()


def adaptive_enabled() -> bool:
    """The ONE parse of ``CYLON_TPU_ADAPTIVE`` (default on) — every
    regrow ladder (``dist_ops._adaptive``, ``groupby``, the nunique
    ladder) consults this, so the accepted spellings live here."""
    import os

    return os.environ.get("CYLON_TPU_ADAPTIVE", "1") not in (
        "0", "off", "false")


def tight_enabled() -> bool:
    """The ONE parse of ``CYLON_TPU_TIGHT`` (default on): count-driven
    tight-capacity sizing of defaulted exchange bounds
    (``dist_ops._tight_rows_local``). ``CYLON_TPU_TIGHT=0`` restores
    the unconditional ``DEFAULT_SKEW``×capacity headroom everywhere."""
    import os

    return os.environ.get("CYLON_TPU_TIGHT", "1") not in (
        "0", "off", "false")


#: ambient trace-time hint: a power-of-2 bucket of the compiled
#: query's concrete TOTAL input rows. Inside the trace every row count
#: is a tracer, so exchanges cannot size from true counts the way
#: eager dispatches do — instead :class:`CompiledQuery` records this
#: bucket (static, so the program retraces only when the bucket
#: changes) and ``dist_ops._tight_rows_local`` derives a
#: skew-buffered per-shard bound from it. Inexact for intermediates
#: (a join can outgrow its inputs) — overflow falls back to this
#: class's whole-program regrow ladder, exactly like any other
#: defaulted bound.
_ROW_HINT: contextvars.ContextVar = contextvars.ContextVar(
    "cylon_row_hint", default=None)


def current_row_hint() -> "int | None":
    return _ROW_HINT.get()


@contextlib.contextmanager
def row_hint(rows: "int | None"):
    """Ambient input-row bucket for defaulted exchange bounds chosen
    while tracing (see :data:`_ROW_HINT`)."""
    tok = _ROW_HINT.set(rows)
    try:
        yield
    finally:
        _ROW_HINT.reset(tok)


def _result_tables(out):
    """Tables reachable in a query result (pytree of Tables/DataFrames)."""
    from cylon_tpu.table import Table

    found = []

    def visit(x):
        if isinstance(x, Table):
            found.append(x)
            return
        # DataFrame and containers
        t = getattr(x, "table", None)
        if isinstance(t, Table):
            found.append(t)
            return
        if isinstance(x, (list, tuple)):
            for v in x:
                visit(v)
        elif isinstance(x, dict):
            for v in x.values():
                visit(v)

    visit(out)
    return found


#: arrays this small ride the overflow check's batched fetch (below a
#: page of f64s — scalars and tiny vectors, never column buffers)
_PREFETCH_ELEMS = 512


def _result_scalars(out):
    """Small bare jax arrays in a query result (scalar aggregates, tiny
    vectors) — NOT table columns. Prefetched with the overflow check so
    the caller's own ``float(np.asarray(x))`` hits the host cache
    instead of paying a second tunnel round trip (q6/q14/q17-shaped
    queries return only scalars)."""
    found = []

    def visit(x):
        if isinstance(x, jax.Array):
            if x.size <= _PREFETCH_ELEMS and \
                    getattr(x, "is_fully_addressable", True):
                found.append(x)
            return
        if hasattr(x, "table") or hasattr(x, "columns"):
            return  # tables fetch via nrows; columns via to_pandas
        if isinstance(x, (list, tuple)):
            for v in x:
                visit(v)
        elif isinstance(x, dict):
            for v in x.values():
                visit(v)

    visit(out)
    return found


#: result tables whose buffers total at most this many bytes ride the
#: overflow check's batched transfer too — a later ``to_pandas`` then
#: reads host caches instead of paying its own tunnel round trip
_PREFETCH_TABLE_BYTES = 4 << 20


def _input_row_bucket(dyn_pos, dyn_kw) -> "int | None":
    """Power-of-2 bucket of the largest concrete input table's TRUE
    total rows — the per-call static row hint tight exchange sizing
    reads under the trace (see :data:`_ROW_HINT`). The count memo
    plumbing (batched fill, poison rules) is
    ``dist_ops.batched_true_rows`` — ONE home for the convention.
    Returns None — default sizing — when there are no input tables,
    any input is poisoned, or a count is not host-reachable."""
    from cylon_tpu.parallel.dist_ops import batched_true_rows
    from cylon_tpu.utils import pow2_bucket

    tables = _result_tables((list(dyn_pos), dyn_kw))
    if not tables:
        return None
    rows = batched_true_rows(tables)
    if rows is None:
        return None
    return pow2_bucket(max(rows))


def _check_overflow(out, bad=None) -> None:
    """Host-side: raise OutOfCapacity if any result shard overflowed
    (poisoned nrows > local capacity — see ``parallel.shuffle.poison``)
    or the registered poison flag ``bad`` fired.

    ONE batched device->host transfer covers the flag, every result
    table's row counts, small result scalars, and the column buffers of
    small (bucket-sliced) result tables (async copies issued together,
    then gathered — the ``Table.to_pandas`` pattern): on a tunneled
    device each separate ``np.asarray`` is a ~100-120 ms round trip,
    and this check + result fetch used to pay three of them per
    compiled-query call."""
    import numpy as np

    from cylon_tpu.parallel import dtable

    tables = _result_tables(out)
    leaves = [t.nrows for t in tables
              if getattr(t.nrows, "is_fully_addressable", True)]
    leaves.extend(_result_scalars(out))
    for t in tables:
        if dtable.is_distributed(t):
            continue
        nbytes = sum(c.data.size * c.data.dtype.itemsize
                     + (c.validity.size if c.validity is not None else 0)
                     for c in t.columns.values())
        if nbytes <= _PREFETCH_TABLE_BYTES:
            for c in t.columns.values():
                leaves.append(c.data)
                if c.validity is not None:
                    leaves.append(c.validity)
    if bad is not None:
        leaves.append(bad)
    telemetry.counter("plan.prefetch_bytes").inc(sum(
        int(getattr(x, "size", 0)) * x.dtype.itemsize
        for x in leaves if hasattr(x, "dtype")))
    from cylon_tpu import watchdog

    # batch; host values now cached per array. The one synchronous
    # device->host wait of a compiled-query call — a wedged chip hangs
    # exactly here, so it is a bounded watchdog section (never
    # retryable: re-fetching from a wedged device re-hangs)
    watchdog.bounded(lambda: jax.device_get(leaves), "overflow_fetch",
                     detail=f"{len(leaves)} leaves")
    if bad is not None and bool(np.asarray(bad)):
        raise OutOfCapacity(
            "an op inside the compiled query overflowed its "
            "capacity bound")
    for t in tables:
        if dtable.is_distributed(t):
            dtable.dist_num_rows(t)
        else:
            n = int(np.asarray(t.nrows))
            if n > t.capacity:
                raise OutOfCapacity(
                    f"result rows {n} exceed capacity {t.capacity}")


def _map_result_tables(out, fn):
    """Rebuild a query-result pytree with ``fn`` applied to every Table
    (DataFrames re-wrapped). Visits tables in the same order as
    :func:`_result_tables`."""
    from cylon_tpu.table import Table

    def walk(x):
        if isinstance(x, Table):
            return fn(x)
        t = getattr(x, "table", None)
        if isinstance(t, Table) and hasattr(type(x), "_wrap"):
            return type(x)._wrap(fn(t), getattr(x, "_index", None))
        if isinstance(x, list):
            return [walk(v) for v in x]
        if isinstance(x, tuple):
            return tuple(walk(v) for v in x)
        if isinstance(x, dict):
            return {k: walk(v) for k, v in x.items()}
        return x

    return walk(out)


def _shrink_results(out):
    """Trim result buffers to power-of-2 buckets of their true row
    counts. Compiled queries keep intermediate static capacities all
    the way to the output (no per-op host shrink), so a 4-row groupby
    result can sit in a 600k-row buffer — materialising that over a
    tunneled device transfers the whole buffer (~seconds) for a
    screenful of rows. The row counts were just fetched by the overflow
    check, so this costs no extra sync; distributed tables keep their
    shard layout (the mesh contract)."""
    from cylon_tpu.parallel import dtable

    import os

    if os.environ.get("CYLON_TPU_NO_SHRINK"):
        return out

    def shrink(t):
        if dtable.is_distributed(t):
            return t
        # the row count is host-cached from the overflow check, so
        # shrink_to_fit's num_rows read costs no extra device sync
        return t.shrink_to_fit(only_above=0)

    return _map_result_tables(out, shrink)


def _apply_buckets(out, buckets):
    """Device-side: slice each local result table to its memoized
    power-of-2 bucket capacity (nrows kept — a result that outgrew its
    bucket reads nrows > capacity on the host, which retries with the
    observed size). Distributed tables keep their shard layout."""
    from cylon_tpu.parallel import dtable

    it = iter(buckets)

    def cut(t):
        b = next(it)
        if b is None or dtable.is_distributed(t) or b >= t.capacity:
            return t
        # with_capacity clamps nrows to the new capacity — restore the
        # TRUE count so an outgrown bucket reads nrows > capacity on
        # the host instead of silently truncating the result
        return t.with_capacity(b).with_nrows(t.nrows)

    return _map_result_tables(out, cut)


class CompiledQuery:
    """A query function compiled to one XLA program per capacity scale.

    Call it like the original function. Table/DataFrame/array arguments
    (positional or keyword, possibly nested in dicts/lists) are traced;
    every other argument must be hashable and becomes part of the
    compile key.
    """

    def __init__(self, fn, *, check=True):
        self._fn = fn
        self._check = check
        #: ONE lock for the three memo structures below. A CompiledQuery
        #: is shared across serving threads (``shared_compiled``) — the
        #: memos must obey a lock discipline: every read-modify-write
        #: (LRU reorder, widen-only merge, first-sight counting) holds
        #: ``_mu``; the expensive part (the jitted dispatch itself)
        #: never does. jax.jit's own executable cache is thread-safe,
        #: so concurrent first calls at worst trace twice — the memo
        #: bookkeeping here must never corrupt, double-count, or lose
        #: a widen under that race.
        self._mu = threading.Lock()
        self._scale_memo: dict = {}  # static key -> known-good scale
        #: (static key, scale, row hint, dyn-arg shape signature)
        #: 4-tuples already dispatched, LRU-ordered — first sight of a
        #: tuple is (at most) one fresh XLA program build, counted as
        #: ``plan.compile_count`` (the persistent on-disk cache may make
        #: some of these cheap; the counter tracks program-shape churn,
        #: which is what the capacity ladder is sized to bound). The
        #: shape signature matters: the same static key re-traces when a
        #: dynamic argument's buffer shapes change (pow2 capacities of
        #: bigger inputs), and those recompiles are exactly the churn.
        #: Re-sight of a tuple is a ``plan.cache_hits``; first sight a
        #: ``plan.cache_misses``; the store is bounded
        #: (``CYLON_TPU_PLAN_CACHE_ENTRIES``) with oldest-first
        #: eviction counted as ``plan.cache_evictions`` — eviction
        #: forgets only the seen-shape bookkeeping (a later identical
        #: call re-counts a miss; jax's executable cache still holds
        #: the program).
        self._compiled: collections.OrderedDict = collections.OrderedDict()
        #: static key -> per-result-table pow2 capacity buckets. After
        #: the first call observes the result sizes, later calls
        #: compile a variant that emits bucket-sized output buffers —
        #: so the overflow check's ONE batched transfer also carries
        #: the (small) result columns and a following to_pandas reads
        #: host caches: one tunnel round trip per call instead of three
        self._size_memo: dict = {}

        def traced(scale, hint, static_pos, static_kw, dyn_pos,
                   **dyn_kw):
            import jax.numpy as jnp

            n = len(static_pos) + len(dyn_pos)
            slots = dict(static_pos)
            dyn_idx = (i for i in range(n) if i not in slots)
            slots.update(zip(dyn_idx, dyn_pos))
            flags: list = []
            with capacity_scale(scale), row_hint(hint), \
                    _collect_flags(flags):
                out = fn(*(slots[i] for i in range(n)),
                         **dict(static_kw), **dyn_kw)
            bad = functools.reduce(jax.numpy.logical_or, flags,
                                   jnp.zeros((), bool))
            return out, bad

        self._jitted = jax.jit(traced, static_argnums=(0, 1, 2, 3))
        # the bucket slice is a SEPARATE tiny program composed after
        # the main one (an extra async dispatch, ~free): folding it
        # into `traced` would recompile the whole query — minutes of
        # XLA time for a big TPC-H program — the first time its result
        # sizes are known
        self._slicer = jax.jit(
            lambda buckets, out: _apply_buckets(out, buckets),
            static_argnums=0)

    def invalidate(self) -> None:
        """Drop the scale/shape/size memos (one lock hold). The views
        layer calls this through ``query_fn.invalidate()`` when a
        source table's generation advances: the memos key on buffer
        shapes, and an append that grows a table past its pow2
        capacity bucket would otherwise replay a stale size memo.
        jax's executable cache is untouched — identical shapes recompile
        for free; only the bookkeeping resets."""
        with self._mu:
            self._scale_memo.clear()
            self._compiled.clear()
            self._size_memo.clear()

    def __call__(self, *args, **kwargs):
        import numpy as np

        from cylon_tpu.parallel import dtable
        from cylon_tpu.telemetry import memory as _memory
        from cylon_tpu.utils import pow2_bucket
        from cylon_tpu.utils.tracing import span as _span

        dyn_pos, static_pos, static_kw, dyn_kw = _split_args(args, kwargs)
        key = (static_pos, static_kw)
        with self._mu:
            scale = self._scale_memo.get(key, 1)
            buckets = self._size_memo.get(key) if self._check else None
        # the count-driven row bucket rides the compile key: pow2
        # bucketing means it changes (and retraces) only when the
        # input's true row count crosses a power of two, exactly like
        # the capacity-scale ladder bounds its shape space. check=False
        # queries skip the probe entirely: they promise no host sync,
        # and with no overflow check there is no regrow ladder to
        # repair a hint-shrunk bound that real data outgrows
        hint = (_input_row_bucket(dyn_pos, dyn_kw)
                if self._check and tight_enabled()
                and adaptive_enabled() else None)
        shape_sig = tuple(
            (getattr(x, "shape", None), str(getattr(x, "dtype", "")))
            for x in jax.tree_util.tree_leaves((tuple(dyn_pos),
                                                dyn_kw)))
        while True:
            entry = (key, scale, hint, shape_sig)
            with self._mu:
                hit = entry in self._compiled
                if hit:
                    self._compiled.move_to_end(entry)
                else:
                    self._compiled[entry] = True
                    evicted = 0
                    while len(self._compiled) > _cache_entries():
                        self._compiled.popitem(last=False)
                        evicted += 1
            telemetry.counter("plan.cache_hits" if hit
                              else "plan.cache_misses").inc()
            if not hit:
                if evicted:
                    telemetry.counter("plan.cache_evictions").inc(evicted)
                telemetry.counter("plan.compile_count").inc()
                _trace.instant("plan.compile", cat="plan", scale=scale,
                               row_hint=hint,
                               fn=getattr(self._fn, "__name__", "?"))
            # the compile-vs-execute split the ANALYZE profile reads:
            # on a cache miss this span is dominated by trace+compile
            # (dispatch is async), on a hit it is pure host dispatch;
            # the plan.fetch span below is the wait on real execution.
            # An allocation failure here gets the resident-consumer
            # forensics dump (telemetry.memory) before it propagates.
            with _span("plan.dispatch", cat="stage", cache_hit=hit), \
                    _memory.forensics("plan.dispatch"):
                # seeded-fault hook (the "plan" injection point): the
                # OOM→spill fallback layer injects deterministic
                # allocation failures exactly where a real
                # RESOURCE_EXHAUSTED would surface
                from cylon_tpu import resilience

                resilience.inject(
                    "plan", getattr(self._fn, "__name__", "?"))
                raw, bad = self._jitted(scale, hint, static_pos,
                                        static_kw, tuple(dyn_pos),
                                        **dyn_kw)
            if not self._check:
                return raw
            out = self._slicer(buckets, raw) if buckets is not None \
                else raw
            try:
                # registered flags (covers scalar-only results and
                # intermediate poison masked by downstream ops) + the
                # result-table nrows scan + small result buffers, all
                # fetched in ONE transfer
                with _span("plan.fetch", cat="stage"), \
                        _memory.forensics("plan.fetch"):
                    _check_overflow(out, bad)
            except OutOfCapacity as err:
                if buckets is not None and not bool(np.asarray(bad)):
                    # maybe only the memoized result buckets were
                    # outgrown — but an UNFLAGGED genuine overflow
                    # (nrows-poison from a local op, a distributed
                    # shard bound) reads exactly the same here. The
                    # UNBUCKETED ground truth is already in hand (the
                    # slicer is post-hoc): check it directly, no
                    # re-dispatch
                    buckets = None
                    try:
                        _check_overflow(raw, bad)
                        out = raw
                    except OutOfCapacity as err2:
                        err = err2
                        out = None
                else:
                    out = None
                if out is None:
                    # genuine op overflow: regrow the capacity budget
                    telemetry.counter("plan.overflow_events",
                                      site="compiled").inc()
                    _trace.instant("capacity.overflow", cat="capacity",
                                   site="compiled", scale=scale)
                    if scale >= MAX_SCALE:
                        raise err
                    scale *= 2
                    telemetry.counter("plan.capacity_rescales",
                                      site="compiled").inc()
                    _trace.instant("capacity.regrow", cat="capacity",
                                   site="compiled", scale=scale)
                    continue
            observed = tuple(
                None if dtable.is_distributed(t)
                else pow2_bucket(int(np.asarray(t.nrows)))
                for t in _result_tables(out))
            with self._mu:
                # scale memo is widen-only too: a concurrent call that
                # regrew further must not be clobbered back down by a
                # call that succeeded at a smaller scale
                if scale > self._scale_memo.get(key, 0):
                    self._scale_memo[key] = scale
                old = self._size_memo.get(key)
                if old is not None:
                    # widen-only: shrinking the memo would make every
                    # later larger-result call pay a wasted bucketed
                    # dispatch + overflow round trip before widening
                    # back (and, under concurrency, lose a racing
                    # call's wider observation)
                    observed = tuple(
                        None if n is None
                        else (n if o is None else max(o, n))
                        for o, n in zip(old, observed))
                if observed != old and any(b is not None
                                           for b in observed):
                    # all-None/empty buckets (scalar-only or
                    # distributed results) would recompile an identical
                    # program for a no-op _apply_buckets — leave the
                    # memo unset
                    self._size_memo[key] = observed
            return _shrink_results(out)


def _cache_entries() -> int:
    """Bound on the per-query seen-shape LRU (``CYLON_TPU_PLAN_CACHE_ENTRIES``,
    default 4096 — far above any sane shape churn; the knob exists so a
    pathological workload can't grow the bookkeeping without bound)."""
    import os

    try:
        return max(int(os.environ.get("CYLON_TPU_PLAN_CACHE_ENTRIES",
                                      "4096")), 1)
    except ValueError:
        return 4096


#: process-wide compiled-query cache: (fn, check) -> CompiledQuery.
#: THE cross-request plan cache of the serving layer — N clients
#: submitting the same query function share ONE CompiledQuery, so the
#: pow2 input-row bucket + shape signature becomes the cross-request
#: cache key and the N-1 later clients' calls are ``plan.cache_hits``
#: (one trace paid for the fleet).
_SHARED_MU = threading.Lock()
_SHARED: "dict[tuple, CompiledQuery]" = {}


def shared_compiled(fn, *, check: bool = True) -> CompiledQuery:
    """Get-or-create the process-wide :class:`CompiledQuery` for ``fn``
    (keyed on the function object + ``check``). Unlike
    :func:`compile_query` — which builds a fresh program cache per call
    site — every caller of ``shared_compiled(q3)`` shares one scale/
    size/shape memo, which is what makes a multi-tenant serving layer
    pay one trace per query shape instead of one per client."""
    key = (fn, bool(check))
    cq = _SHARED.get(key)
    if cq is None:
        with _SHARED_MU:
            cq = _SHARED.get(key)
            if cq is None:
                cq = functools.wraps(fn)(CompiledQuery(fn, check=check))
                _SHARED[key] = cq
    return cq


def plan_cache_stats() -> dict:
    """Hit/miss/eviction totals of the compiled-plan cache plus the
    derived hit rate — the block the serve bench record embeds."""
    hits = telemetry.total("plan.cache_hits")
    misses = telemetry.total("plan.cache_misses")
    looked = hits + misses
    return {
        "hits": hits,
        "misses": misses,
        "evictions": telemetry.total("plan.cache_evictions"),
        "hit_rate": (hits / looked) if looked else 0.0,
        "shared_queries": len(_SHARED),
    }


def query_fingerprint(name: str, args=(), kwargs=None) -> "str | None":
    """Stable fingerprint of a REGISTERED query invocation — the first
    half of the serve layer's result-cache key ``(fingerprint,
    table-version vector)``.

    Keyed on the query NAME (the durable, cross-process identity the
    journal already records) plus the canonical-JSON form of its
    arguments, hashed with sha256 — so two processes (an engine and a
    fleet router, or two engines behind one router) derive the SAME
    fingerprint for the same logical request without sharing any
    in-memory state. Returns None when the arguments are not
    JSON-canonicalizable (closures, arrays, ...): such an invocation
    has no stable identity and must never be coalesced or cached."""
    import hashlib
    import json

    try:
        blob = json.dumps(
            {"name": str(name), "args": list(args),
             "kwargs": dict(kwargs or {})},
            sort_keys=True, separators=(",", ":"), allow_nan=False)
    except (TypeError, ValueError):
        return None
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


def _is_dynamic(x) -> bool:
    import numpy as np

    from cylon_tpu.table import Table

    if isinstance(x, Table) or hasattr(x, "table"):
        return True
    if isinstance(x, (list, tuple)):
        return any(_is_dynamic(v) for v in x)
    if isinstance(x, dict):
        return any(_is_dynamic(v) for v in x.values())
    return isinstance(x, (jax.Array, np.ndarray))


def _split_args(args, kwargs):
    """Partition the call's arguments into traced (Tables/DataFrames/
    arrays, nested ok) and static (everything else, made hashable).
    Positional statics are carried as (index, value) pairs so the traced
    wrapper can reassemble the original argument order."""
    dyn_pos, static_pos = [], []
    for i, v in enumerate(args):
        if _is_dynamic(v):
            dyn_pos.append(v)
        else:
            static_pos.append((i, _hashable(v)))
    static_kw, dyn_kw = [], {}
    for k, v in kwargs.items():
        if _is_dynamic(v):
            dyn_kw[k] = v
        else:
            static_kw.append((k, _hashable(v)))
    return dyn_pos, tuple(static_pos), tuple(sorted(static_kw)), dyn_kw


def _hashable(v):
    if isinstance(v, list):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    if isinstance(v, set):
        return frozenset(_hashable(x) for x in v)
    return v


def regrow_eager(run, *, bounded: bool):
    """Host-side regrow ladder for ONE eager local dispatch.

    ``run()`` must build and execute the op reading the ambient
    :func:`capacity_scale` for its defaulted bounds and return a local
    Table. ``bounded=True`` (caller passed an explicit capacity) keeps
    the raise-on-overflow contract. Under an outer trace the row count
    is a tracer — the check is skipped and the enclosing
    :class:`CompiledQuery` ladder regrows the whole program instead
    (seeding from ``current_scale()`` keeps the two ladders composable).
    The distributed analog with per-shard count checks is
    ``parallel.dist_ops._adaptive``.
    """
    scale = current_scale()
    while True:
        with capacity_scale(scale):
            t = run()
        if bounded or isinstance(t.nrows, jax.core.Tracer):
            return t
        try:
            t.num_rows  # host sync; raises on overflow
            return t
        except OutOfCapacity:
            telemetry.counter("plan.overflow_events",
                              site="eager").inc()
            _trace.instant("capacity.overflow", cat="capacity",
                           site="eager", scale=scale)
            if scale >= MAX_SCALE:
                raise
            scale *= 2
            telemetry.counter("plan.capacity_rescales",
                              site="eager").inc()
            _trace.instant("capacity.regrow", cat="capacity",
                           site="eager", scale=scale)


def compile_query(fn=None, *, check: bool = True):
    """Decorator/wrapper: compile a whole query into one XLA program
    with automatic capacity regrow (see module docstring).

    ``check=False`` skips the host-side overflow check (and its one
    device sync) — for callers that inspect ``num_rows`` themselves.
    """
    if fn is None:
        return functools.partial(compile_query, check=check)
    return functools.wraps(fn)(CompiledQuery(fn, check=check))
