"""HBM-resident columnar Table.

Parity target: ``cpp/src/cylon/table.hpp:46-200`` (Table wraps
``shared_ptr<arrow::Table>`` + ctx) and the conversion surface of
``python/pycylon/data/table.pyx:767-1004`` (from/to arrow, pandas, numpy,
pydict).

TPU-first redesign — the load-bearing difference from the reference:
XLA compiles static shapes, but relational ops produce data-dependent row
counts. A Table therefore carries

- ``capacity``: the static padded row count (the arrays' leading dim), and
- ``nrows``:    a traced int32 scalar — how many leading rows are real.

Rows in ``[nrows, capacity)`` are padding; every kernel either masks them
with order-inert sentinels or filters them on output. This replaces the
reference's exact-length Arrow buffers and is what lets an entire
multi-op pipeline (partition -> shuffle -> join -> groupby) stay inside one
``jit`` without host round-trips.
"""

import collections
from typing import Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from cylon_tpu import dtypes
from cylon_tpu.column import Column, Dictionary
from cylon_tpu.errors import InvalidArgument, KeyError_


@jax.tree_util.register_pytree_node_class
class Table:
    """Named device columns + a traced valid-row count."""

    def __init__(self, columns: Mapping[str, Column], nrows):
        self._columns = collections.OrderedDict(columns)
        caps = {c.capacity for c in self._columns.values()}
        if len(caps) > 1:
            raise InvalidArgument(f"column capacities differ: {caps}")
        if isinstance(nrows, (int, np.integer)):
            nrows = jnp.asarray(nrows, dtype=jnp.int32)
        self.nrows = nrows

    # -- pytree ----------------------------------------------------------
    def tree_flatten(self):
        return (tuple(self._columns.values()), self.nrows), tuple(self._columns)

    @classmethod
    def tree_unflatten(cls, names, children):
        cols, nrows = children
        t = object.__new__(cls)
        t._columns = collections.OrderedDict(zip(names, cols))
        t.nrows = nrows
        return t

    # -- shape / schema --------------------------------------------------
    @property
    def capacity(self) -> int:
        if not self._columns:
            return 0
        return next(iter(self._columns.values())).capacity

    def _check_overflow(self, n: int) -> int:
        if n > self.capacity:
            from cylon_tpu.errors import OutOfCapacity

            raise OutOfCapacity(
                f"result has {n} rows but static capacity is "
                f"{self.capacity}; re-run with a larger out_capacity")
        return n

    @property
    def num_rows(self) -> int:
        """Concrete row count (syncs device->host; not usable under trace).
        Parity: ``table.hpp`` Rows(). Raises OutOfCapacity if a
        capacity-bounded kernel overflowed its static result bound."""
        return self._check_overflow(int(self.nrows))

    @property
    def num_columns(self) -> int:
        return len(self._columns)

    @property
    def column_names(self) -> list[str]:
        return list(self._columns)

    @property
    def columns(self) -> "collections.OrderedDict[str, Column]":
        return self._columns

    def column(self, name: str) -> Column:
        if name not in self._columns:
            raise KeyError_(f"no column {name!r}; have {self.column_names}")
        return self._columns[name]

    def __getitem__(self, key):
        if isinstance(key, str):
            return self.column(key)
        if isinstance(key, (list, tuple)):
            return self.select(key)
        raise KeyError_(f"bad key {key!r}")

    def __contains__(self, name):
        return name in self._columns

    def row_mask(self) -> jax.Array:
        """[capacity] bool — True for real rows."""
        return jnp.arange(self.capacity, dtype=jnp.int32) < self.nrows

    # -- schema ops (parity: table.pyx project/rename/drop) --------------
    def select(self, names: Sequence[str]) -> "Table":
        """Project columns (parity: ``table.hpp`` Project / table.pyx ``__getitem__``)."""
        return Table({n: self.column(n) for n in names}, self.nrows)

    def rename(self, mapping: Mapping[str, str]) -> "Table":
        return Table({mapping.get(n, n): c for n, c in self._columns.items()},
                     self.nrows)

    def drop(self, names: Sequence[str]) -> "Table":
        names = set(names)
        return Table({n: c for n, c in self._columns.items() if n not in names},
                     self.nrows)

    def add_column(self, name: str, col: Column) -> "Table":
        out = collections.OrderedDict(self._columns)
        out[name] = col
        return Table(out, self.nrows)

    def with_nrows(self, nrows) -> "Table":
        return Table(self._columns, nrows)

    def with_capacity(self, capacity: int) -> "Table":
        """Pad (zeros) or trim the static capacity. Trimming below nrows is
        caller's responsibility to avoid (checked on host when concrete)."""
        cur = self.capacity
        if capacity == cur:
            return self
        cols = {}
        for n, c in self._columns.items():
            if capacity > cur:
                data = jnp.concatenate(
                    [c.data, jnp.zeros((capacity - cur,) + c.data.shape[1:],
                                       dtype=c.data.dtype)])
                validity = (None if c.validity is None else
                            jnp.concatenate([c.validity,
                                             jnp.zeros(capacity - cur, bool)]))
            else:
                data = c.data[:capacity]
                validity = None if c.validity is None else c.validity[:capacity]
            cols[n] = Column(data, validity, c.dtype, c.dictionary)
        return Table(cols, jnp.minimum(self.nrows, capacity))

    def shrink_to_fit(self, min_capacity: int = 1024,
                      only_above: int = 1 << 16) -> "Table":
        """Trim static capacity to a power-of-2 bucket of the concrete
        row count (local-eager optimisation: selective filters/joins
        leave the buffer mostly padding, and downstream sort-based
        kernels cost O(capacity log capacity) regardless of real rows).
        Power-of-2 buckets bound the number of distinct compiled shapes.

        Reading the row count is a host sync — a fixed ~100 ms round
        trip on a tunneled device — so tables at or below ``only_above``
        capacity are left alone: the sync would cost more than any
        downstream sort saves.

        No-op when the row count is abstract (under jit trace), when the
        table overflowed its bound (the OutOfCapacity poison must keep
        propagating to the host materialisation that reports it), or
        when the bucket wouldn't shrink anything.
        """
        if self.capacity <= only_above:
            return self
        if getattr(self.nrows, "ndim", 0):  # distributed [W] counts
            return self
        from cylon_tpu.errors import OutOfCapacity

        try:
            n = self.num_rows
        except OutOfCapacity:  # poison must propagate, not be trimmed
            return self
        except (jax.errors.TracerIntegerConversionError,
                jax.errors.ConcretizationTypeError):  # under jit trace
            return self
        from cylon_tpu.utils import pow2_bucket

        bucket = pow2_bucket(n, min_capacity)
        if bucket < self.capacity:
            return self.with_capacity(bucket)
        return self

    # -- host bridges ----------------------------------------------------
    @staticmethod
    def _storage_of(string_storage, name: str) -> str:
        """Resolve a per-column storage request: a plain string applies
        to every string column; a dict maps column name -> storage with
        ``"dict"`` as the default."""
        if isinstance(string_storage, Mapping):
            return string_storage.get(name, "dict")
        return string_storage

    @staticmethod
    def from_pydict(data: Mapping[str, object], capacity: int | None = None,
                    string_storage="dict") -> "Table":
        """Parity: ``table.pyx`` from_pydict. ``string_storage``:
        "dict"/"bytes"/"auto" or a per-column-name mapping (see
        :meth:`Column.from_numpy`)."""
        arrays = {n: np.asarray(v) for n, v in data.items()}
        n = len(next(iter(arrays.values()))) if arrays else 0
        for name, a in arrays.items():
            if len(a) != n:
                raise InvalidArgument(f"column {name} length {len(a)} != {n}")
        cols = {name: Column.from_numpy(
            a, capacity, Table._storage_of(string_storage, name))
            for name, a in arrays.items()}
        return Table(cols, n)

    @staticmethod
    def from_pandas(df, capacity: int | None = None,
                    string_storage="dict") -> "Table":
        """Parity: ``table.pyx`` from_pandas."""
        data = {}
        for name in df.columns:
            s = df[name]
            if str(s.dtype).startswith(("Int", "UInt", "Float", "boolean")):
                # pandas nullable extension arrays
                mask = s.isna().to_numpy()
                fill = False if str(s.dtype) == "boolean" else 0
                vals = s.fillna(fill).to_numpy()
                col = Column.from_numpy(vals, capacity)
                if mask.any():
                    v = np.concatenate([~mask, np.zeros(col.capacity - len(mask), bool)])
                    col = Column(col.data, jnp.asarray(v), col.dtype, col.dictionary)
                data[str(name)] = col
                continue
            data[str(name)] = Column.from_numpy(
                s.to_numpy(), capacity,
                Table._storage_of(string_storage, str(name)))
        return Table(data, len(df))

    @staticmethod
    def from_arrow(atable, capacity: int | None = None,
                   string_storage="dict") -> "Table":
        """Parity: ``table.pyx`` from_arrow."""
        import pyarrow as pa
        import pyarrow.compute as pc

        cols = {}
        for name in atable.column_names:
            arr = atable.column(name).combine_chunks()
            if pa.types.is_string(arr.type) or pa.types.is_large_string(
                    arr.type):
                cols[str(name)] = Column.from_numpy(
                    arr.to_numpy(zero_copy_only=False), capacity,
                    Table._storage_of(string_storage, str(name)))
                continue
            # Nullable int/bool: keep the logical type, carry Arrow's null
            # mask as validity (to_numpy alone would coerce to float64+NaN).
            if arr.null_count and (pa.types.is_integer(arr.type)
                                   or pa.types.is_boolean(arr.type)):
                isnull = arr.is_null().to_numpy(zero_copy_only=False)
                fill = False if pa.types.is_boolean(arr.type) else 0
                filled = pc.fill_null(arr, fill).to_numpy(zero_copy_only=False)
                col = Column.from_numpy(filled, capacity)
                validity = np.concatenate(
                    [~isnull, np.zeros(col.capacity - len(isnull), bool)])
                col = Column(col.data, jnp.asarray(validity), col.dtype,
                             col.dictionary)
            else:
                col = Column.from_numpy(
                    arr.to_numpy(zero_copy_only=False), capacity)
            cols[str(name)] = col
        return Table(cols, atable.num_rows)

    @staticmethod
    def from_numpy(names: Sequence[str], arrays: Sequence[np.ndarray],
                   capacity: int | None = None) -> "Table":
        return Table.from_pydict(dict(zip(names, arrays)), capacity)

    # -- thin op/convenience surface (parity: table.pyx methods) ----------
    @property
    def row_count(self) -> int:
        """Alias of :attr:`num_rows` (table.pyx ``row_count``)."""
        return self.num_rows

    @property
    def column_count(self) -> int:
        return self.num_columns

    @property
    def schema(self) -> dict:
        """name -> logical dtype (parity: table.pyx ``schema``)."""
        return {n: c.dtype for n, c in self._columns.items()}

    def project(self, cols: Sequence) -> "Table":
        """Select columns by index or name (parity: ``Project``,
        table.hpp / table.pyx ``project``)."""
        names = [self.column_names[c] if isinstance(c, int) else c
                 for c in cols]
        return self.select(names)

    def add_prefix(self, prefix: str) -> "Table":
        return self.rename({n: prefix + n for n in self.column_names})

    def add_suffix(self, suffix: str) -> "Table":
        return self.rename({n: n + suffix for n in self.column_names})

    def filter(self, mask) -> "Table":
        """Keep rows where ``mask`` is True (compacted)."""
        from cylon_tpu.ops.selection import filter_table

        return filter_table(self, mask)

    def sort(self, by, ascending=True) -> "Table":
        from cylon_tpu.ops.selection import sort_table

        by = [by] if isinstance(by, str) else list(by)
        return sort_table(self, by, ascending=ascending)

    def join(self, right: "Table", **kw) -> "Table":
        from cylon_tpu.ops.join import join as _join

        return _join(self, right, **kw)

    def union(self, other: "Table", out_capacity=None) -> "Table":
        from cylon_tpu.ops import setops

        if out_capacity is None:
            out_capacity = self.capacity + other.capacity
        return setops.union(self, other, out_capacity)

    def intersect(self, other: "Table", out_capacity=None) -> "Table":
        from cylon_tpu.ops import setops

        if out_capacity is None:
            out_capacity = self.capacity
        return setops.intersect(self, other, out_capacity)

    def subtract(self, other: "Table", out_capacity=None) -> "Table":
        from cylon_tpu.ops import setops

        if out_capacity is None:
            out_capacity = self.capacity
        return setops.subtract(self, other, out_capacity)

    def unique(self, cols=None, keep: str = "first") -> "Table":
        from cylon_tpu.ops import setops

        return setops.unique(self, cols, keep=keep)

    def show(self, n: int = 10) -> None:
        """Print the first ``n`` rows (parity: table.pyx ``show``)."""
        print(self.to_string(n))

    def to_string(self, n: int | None = None) -> str:
        from cylon_tpu.ops.selection import head

        t = self if n is None else head(self, n)
        return t.to_pandas().to_string()

    def to_csv(self, path, **kw) -> None:
        from cylon_tpu.io import write_csv

        write_csv(self, path, **kw)

    @staticmethod
    def from_list(col_names: Sequence[str], cols: Sequence) -> "Table":
        """Build from a COLUMN-major list of lists (parity: table.pyx
        ``from_list`` semantics)."""
        return Table.from_numpy(col_names, cols)

    def row(self, i: int) -> "Row":
        """Typed host view of row ``i`` (parity: ``cylon::Row``,
        row.hpp:23). Columnar access is the fast path; this syncs —
        but exactly ONCE: every column's one-element slice (data +
        validity) rides a single batched ``jax.device_get``, not one
        round trip per field. On a tunneled chip each fetch is a fixed
        ~100 ms RPC, so the per-column loop made one ``row()`` cost
        ~100 ms x n_columns (VERDICT r5 weak #5). The fetch runs under
        a ``table.row_fetch`` span so the host-sync cost is visible in
        trace timelines."""
        from cylon_tpu.parallel import dtable
        from cylon_tpu.row import Row
        from cylon_tpu.utils.tracing import span

        if dtable.is_distributed(self):
            # pre-existing limitation surfaced clearly: a [W]-count
            # table has no single local row i (the old code died in
            # int([W]-array) deep inside jax instead)
            raise InvalidArgument(
                "row() needs a local table; gather the distributed "
                "result first (parallel.dtable.gather_table)")
        n = self.num_rows
        if not -n <= i < n:
            raise IndexError(f"row {i} out of range [0, {n})")
        if i < 0:
            i += n
        names = list(self._columns)
        # slice ONE element on device before the host transfer — a
        # full-column copy per cell would make row loops O(n^2)
        payload = []
        for c in self._columns.values():
            payload.append(c.data[i:i + 1])
            if c.validity is not None:
                payload.append(c.validity[i:i + 1])
        with span("table.row_fetch", row=int(i)):
            fetched = jax.device_get(payload)
        it = iter(fetched)
        values = []
        for c in self._columns.values():
            data = np.asarray(next(it))
            validity = (np.asarray(next(it))
                        if c.validity is not None else None)
            v = c.decode_host(data, validity)[0]
            values.append(v.item() if hasattr(v, "item") else v)
        return Row(names, values)

    def iterrows(self):
        """Iterate host Rows (one device sync total, not per row)."""
        from cylon_tpu.row import Row

        n = self.num_rows
        names = list(self._columns)
        mats = [c.to_numpy(n) for c in self._columns.values()]
        for i in range(n):
            vals = [m[i].item() if hasattr(m[i], "item") else m[i]
                    for m in mats]
            yield Row(names, vals)

    def _host_columns(self) -> "collections.OrderedDict[str, np.ndarray]":
        """All columns as decoded host arrays via ONE device->host
        transfer (row count + every data/validity buffer batched into a
        single ``jax.device_get``). Per-column fetches each pay a fixed
        ~100 ms round trip on a tunneled device; batching pays it once.
        Raises OutOfCapacity like :attr:`num_rows`."""
        payload = [self.nrows]
        for c in self._columns.values():
            payload.append(c.data)
            if c.validity is not None:
                payload.append(c.validity)
        fetched = jax.device_get(payload)
        n = self._check_overflow(int(fetched[0]))
        out = collections.OrderedDict()
        it = iter(fetched[1:])
        for name, c in self._columns.items():
            data = next(it)[:n]
            validity = next(it)[:n] if c.validity is not None else None
            out[name] = c.decode_host(data, validity)
        return out

    def to_pydict(self) -> dict:
        return {name: a.tolist() for name, a in self._host_columns().items()}

    def to_pandas(self):
        import pandas as pd

        return pd.DataFrame(self._host_columns())

    def to_arrow(self):
        import pyarrow as pa

        return pa.table(dict(self._host_columns()))

    def to_numpy(self) -> np.ndarray:
        """[nrows, ncols] host matrix (parity: table.pyx to_numpy)."""
        return np.stack(list(self._host_columns().values()), axis=1)

    def __repr__(self):
        from cylon_tpu.errors import OutOfCapacity

        try:
            n = str(self.num_rows)
        except OutOfCapacity:
            n = f"OVERFLOW({int(self.nrows)})"
        except Exception:
            n = "<traced>"
        schema = ", ".join(f"{name}: {c.dtype!r}" for name, c in self._columns.items())
        return f"Table[{n}/{self.capacity} rows]({schema})"
