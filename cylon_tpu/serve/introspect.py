"""Read-only live introspection endpoint for the serve engine.

Until this module the only way to see inside a running
:class:`~cylon_tpu.serve.ServeEngine` was to kill it and read the
atexit telemetry flush. This is the ops plane: a stdlib
``http.server`` thread serving the engine's live state as JSON (and
Prometheus text), armed ONLY by ``CYLON_TPU_SERVE_HTTP_PORT`` — the
same no-threads-unless-armed contract as every other telemetry
surface: with the env unset, :func:`maybe_start` is one env read and
returns None; no socket is bound, no thread starts (pinned by
``tests/test_introspect.py``).

Endpoints (all GET, all read-only — the bench guard lints statically
that no handler can reach ``submit``/``register_*``/``drop_*``/
``close``):

=========================  ============================================
path                       payload
=========================  ============================================
``/healthz``               liveness: state, live request count,
                           uptime — PLUS the breaker's observable
                           state (open/half-open, cooldown remaining)
                           and shed counts, so the cheap probe can
                           never silently disagree with ``/health``
``/health``                the ROUTER-GRADE composite verdict
                           (:func:`health_verdict`): ``{"status":
                           ok|degraded|unhealthy, "score",
                           "reasons": [...], "components": {...}}``
                           from queue depth vs cap, breaker state,
                           SLO burn rates, free-HBM headroom,
                           recent watchdog expiries and scheduler
                           last-step age
``/metrics``               live Prometheus text (the PR 3 exposition
                           formatter over a fresh registry snapshot)
``/metrics/window``        the sliding-window JSON view
                           (:func:`cylon_tpu.telemetry.timeseries.
                           window_view`): merged counter/histogram
                           deltas over ``?window=<s>`` (default: the
                           full history window)
``/events``                the structured event journal replayed in
                           order from ``?since=<cursor>``
                           (:func:`cylon_tpu.telemetry.events.since`)
``/trace``                 the flight recorder's trace segment from
                           ``?since=<cursor>``
                           (:func:`cylon_tpu.telemetry.trace.since` —
                           same cursor/gap discipline as ``/events``;
                           ``armed: false`` when ``CYLON_TPU_TRACE``
                           never armed the recorder)
``/queries``               in-flight tickets — tenant, state, elapsed,
                           remaining SLO budget, step count — plus the
                           process's active watchdog sections (what
                           the engine is blocked on RIGHT NOW)
``/tenants``               ``ServeEngine.tenant_stats()``
``/tables``                resident catalog: rows/bytes/pins/holders +
                           the per-device byte split and the
                           generation/digest version column
``/views``                 materialized views: sources, generation
                           watermarks, state digests, refresh counts
``/profiles/<rid>``        one retired-or-live request's ANALYZE
                           profile (``QueryTicket.profile()``)
=========================  ============================================

Binding is loopback-only (``127.0.0.1``) — this is an operator
diagnostic port, not a public API; port ``0`` binds an ephemeral port
(tests), the bound address is ``IntrospectServer.address``.
"""

import json
import os
import threading
import time
import urllib.parse

__all__ = ["maybe_start", "IntrospectServer", "ENDPOINTS",
           "health_verdict"]

#: the read-only surface (for docs and the landing page)
ENDPOINTS = ("/healthz", "/health", "/metrics", "/metrics/window",
             "/events", "/trace", "/queries", "/tenants", "/tables",
             "/views", "/profiles/<rid>")

#: /health status thresholds over the composite score (1.0 = pristine)
_OK_SCORE = 0.8
_DEGRADED_SCORE = 0.5


def health_verdict(engine) -> dict:
    """The composite health verdict a router polls (ISSUE 14).

    Pure read: every component is an existing observable — queue depth
    vs the admission cap, the circuit breaker's
    :meth:`~cylon_tpu.serve.admission.CircuitBreaker.snapshot`,
    per-tenant SLO burn rates (:meth:`ServeEngine.slo_report`),
    free-HBM headroom (the PR 8/9 allocator accounting), watchdog
    sections expired inside the metric-history window, and the
    scheduler's last-step age. Each finding subtracts a fixed penalty
    from a score starting at 1.0 and appends a human-readable reason;
    ``status`` is ``ok`` (>= 0.8), ``degraded`` (>= 0.5) or
    ``unhealthy`` — the contract being: a router should prefer ``ok``
    engines, deprioritise ``degraded`` ones, and stop routing to
    ``unhealthy`` ones entirely (an open breaker or a wedged scheduler
    alone is enough to get there)."""
    from cylon_tpu import fallback as _fallback
    from cylon_tpu.telemetry import timeseries

    reasons: "list[str]" = []
    components: dict = {}
    score = 1.0
    policy = engine._policy
    adm = engine._admission

    # 1. queue depth vs cap — the front door's remaining capacity
    live, cap = adm.live, policy.max_queue
    ratio = live / cap if cap else 0.0
    components["queue"] = {"live": live, "cap": cap,
                           "ratio": round(ratio, 3)}
    if ratio >= 1.0:
        score -= 0.3
        reasons.append(f"queue_full: {live}/{cap} live requests")
    elif ratio >= 0.8:
        score -= 0.1
        reasons.append(f"queue_pressure: {live}/{cap} live requests")

    # 2. circuit breaker — open means every new submit sheds
    br = adm.breaker.snapshot()
    components["breaker"] = br
    if br["state"] == "open":
        score -= 0.6
        reasons.append(
            f"breaker_open: {br['window_failures']} failure(s) in "
            f"{br['window_s']:.0f}s window, cooldown "
            f"{br['cooldown_remaining_s']:.1f}s remaining")
    elif br["state"] == "half_open":
        score -= 0.15
        reasons.append("breaker_half_open: probing after cooldown")

    # 3. SLO burn — the worst tenant/window pair, read fresh
    slo = engine.slo_report()
    components["slo"] = slo
    worst = slo.get("worst")
    if worst is not None:
        b = worst["burn"]
        if b >= policy.burn_critical:
            score -= 0.5
            reasons.append(
                f"slo_burn: tenant {worst['tenant']!r} burning "
                f"{b:.1f}x its error budget over {worst['window']}")
        elif b >= 1.0:
            score -= 0.15
            reasons.append(
                f"slo_burn_warning: tenant {worst['tenant']!r} at "
                f"{b:.1f}x budget over {worst['window']}")

    # 4. free-HBM headroom (PR 8/9 allocator accounting; skipped on a
    # limit-less backend rather than inventing a denominator)
    free = _fallback.free_hbm_bytes()
    limit = _fallback.hbm_limit_bytes()
    mem = {"free_hbm_bytes": free, "hbm_limit_bytes": limit}
    if free is not None and limit:
        headroom = free / limit
        mem["headroom"] = round(headroom, 4)
        if headroom < 0.02:
            score -= 0.4
            reasons.append(
                f"hbm_exhausted: {headroom:.1%} of {limit} bytes free")
        elif headroom < 0.10:
            score -= 0.15
            reasons.append(
                f"hbm_pressure: {headroom:.1%} of {limit} bytes free")
    components["memory"] = mem

    # 5. watchdog expiries inside the history window (arms/refreshes
    # the sliding-window ring — the /health poll IS the cadence)
    view = timeseries.window_view()
    expired = 0
    for e in view["series"].values():
        if e.get("name") == "watchdog.sections_expired" \
                and e.get("type") == "counter":
            expired += e.get("value", 0)
    components["watchdog"] = {
        "expired_in_window": expired,
        "window_s": round(view["window_s"], 1)}
    if expired:
        score -= 0.2
        reasons.append(
            f"watchdog_expired: {expired} section(s) blew their "
            f"deadline in the last {view['window_s']:.0f}s")

    # 6. scheduler progress — live work + a stale sweep = wedged
    age = engine.last_step_age()
    try:
        stall_after = float(os.environ.get(
            "CYLON_TPU_SERVE_STALL_AGE", "10"))
    except ValueError:
        stall_after = 10.0
    components["scheduler"] = {
        "last_step_age_s": (None if age is None else round(age, 3)),
        "stall_after_s": stall_after}
    if live > 0 and age is not None and age > stall_after:
        score -= 0.6
        reasons.append(
            f"scheduler_stalled: {live} live request(s) but no "
            f"scheduler step for {age:.1f}s")

    if getattr(engine, "_closed", False):
        score = 0.0
        reasons.append("engine_closed")

    score = max(round(score, 3), 0.0)
    status = ("ok" if score >= _OK_SCORE else
              "degraded" if score >= _DEGRADED_SCORE else "unhealthy")
    return {"status": status, "score": score, "reasons": reasons,
            "components": components, "live": live,
            "uptime_s": engine.uptime_s}


def maybe_start(engine) -> "IntrospectServer | None":
    """Start the introspection server for ``engine`` IFF
    ``CYLON_TPU_SERVE_HTTP_PORT`` is set — otherwise one env read,
    None returned, no socket/thread exists.

    Startup failures (malformed port value, address already in use)
    are logged LOUDLY and degrade to None instead of raising: the
    endpoint is a diagnostic, and a stale listener on the configured
    port must never take down engine construction — least of all
    ``ServeEngine.recover()``, where failing here would abandon a
    durable engine's journaled requests."""
    port = os.environ.get("CYLON_TPU_SERVE_HTTP_PORT")
    if not port:
        return None
    from cylon_tpu.utils.logging import get_logger

    try:
        return IntrospectServer(engine, int(port))
    except (ValueError, OSError) as e:
        get_logger().warning(
            "introspection endpoint NOT started "
            "(CYLON_TPU_SERVE_HTTP_PORT=%r): %s: %s — the engine "
            "runs without its ops plane", port, type(e).__name__, e)
        return None


class IntrospectServer:
    """One daemon HTTP thread serving an engine's live state."""

    def __init__(self, engine, port: int):
        import http.server

        self._engine = engine
        self._started = time.monotonic()
        outer = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            server_version = "cylon-tpu-introspect"
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet by default
                from cylon_tpu.utils.logging import get_logger

                get_logger().debug("introspect: " + fmt, *args)

            def do_GET(self):  # noqa: N802 - stdlib handler name
                try:
                    outer._route(self)
                except BrokenPipeError:  # client went away mid-write
                    pass
                except Exception as e:  # never kill the server thread
                    try:
                        outer._send(self, 500, {
                            "error": f"{type(e).__name__}: {e}"})
                    except Exception:
                        pass

        self._httpd = http.server.ThreadingHTTPServer(
            ("127.0.0.1", port), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="cylon-serve-introspect", daemon=True)
        self._thread.start()

    @property
    def address(self) -> "tuple[str, int]":
        """(host, port) actually bound (port 0 resolves here)."""
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    # ---------------------------------------------------------- routes
    def _send(self, h, code: int, payload, content_type=None) -> None:
        from cylon_tpu import telemetry

        if isinstance(payload, (dict, list)):
            body = json.dumps(telemetry.json_safe(payload),
                              allow_nan=False).encode()
            content_type = content_type or "application/json"
        else:
            body = str(payload).encode()
            content_type = content_type or "text/plain; charset=utf-8"
        h.send_response(code)
        h.send_header("Content-Type", content_type)
        h.send_header("Content-Length", str(len(body)))
        h.end_headers()
        h.wfile.write(body)

    def _route(self, h) -> None:
        from cylon_tpu import telemetry, watchdog
        from cylon_tpu.telemetry import events as _events
        from cylon_tpu.telemetry import timeseries as _ts
        from cylon_tpu.telemetry import trace as _trace

        path, _, query = h.path.partition("?")
        path = path.rstrip("/") or "/"
        qs = urllib.parse.parse_qs(query)
        eng = self._engine
        if path in ("/healthz", "/health") and eng.closing:
            # a drain in progress: close() is joining the scheduler /
            # flushing the journal, and the engine's internals are
            # mid-teardown. Answer the probe CLEANLY (503 = stop
            # routing here) instead of racing the teardown into a 500
            # — the router treats "closing" like "unhealthy", which is
            # the correct drain signal (ISSUE 15 satellite).
            self._send(h, 503, {"status": "closing",
                                "live": eng.live,
                                "uptime_s": eng.uptime_s})
            return
        if path == "/healthz":
            # the cheap liveness probe carries the breaker's
            # observable state + shed counts, so it can never
            # silently disagree with the /health verdict (ISSUE 14
            # satellite): a prober seeing "ok" while every submit
            # sheds was exactly the bug class this closes
            self._send(h, 200, {
                "status": "closed" if eng._closed else "ok",
                "live": eng.live,
                "uptime_s": time.monotonic() - self._started,
                "breaker": eng._admission.breaker.snapshot(),
                "shed": telemetry.total("serve.shed"),
                "rejected": telemetry.total("serve.rejected"),
            })
        elif path == "/health":
            self._send(h, 200, health_verdict(eng))
        elif path == "/metrics/window":
            window = None
            if qs.get("window"):
                try:
                    window = float(qs["window"][0])
                except ValueError:
                    self._send(h, 400, {
                        "error": f"malformed window "
                                 f"{qs['window'][0]!r}"})
                    return
            self._send(h, 200, _ts.window_view(window))
        elif path == "/events":
            try:
                cursor = int(qs.get("since", ["0"])[0])
            except ValueError:
                self._send(h, 400, {
                    "error": f"malformed since cursor "
                             f"{qs['since'][0]!r}"})
                return
            self._send(h, 200, _events.since(cursor))
        elif path == "/trace":
            try:
                cursor = int(qs.get("since", ["0"])[0])
            except ValueError:
                self._send(h, 400, {
                    "error": f"malformed since cursor "
                             f"{qs['since'][0]!r}"})
                return
            self._send(h, 200, _trace.since(cursor))
        elif path == "/metrics":
            self._send(h, 200, telemetry.to_prometheus(),
                       content_type="text/plain; version=0.0.4; "
                                    "charset=utf-8")
        elif path == "/queries":
            self._send(h, 200, {
                "queries": eng.queries(),
                "active_sections": [
                    {"section": s, "detail": d, "elapsed_s": e}
                    for s, d, e in watchdog.active_sections()],
            })
        elif path == "/tenants":
            self._send(h, 200, eng.tenant_stats())
        elif path == "/tables":
            self._send(h, 200, eng.table_stats())
        elif path == "/views":
            self._send(h, 200, eng.view_stats())
        elif path.startswith("/profiles/"):
            rid = path.rsplit("/", 1)[1]
            ticket = eng.ticket(int(rid)) if rid.isdigit() else None
            if ticket is None:
                self._send(h, 404, {"error": f"unknown rid {rid!r}"})
                return
            prof = ticket.profile()
            if prof is None:
                self._send(h, 404, {
                    "error": f"request {rid} has no profile "
                             "(CYLON_TPU_SERVE_PROFILE=0?)"})
                return
            self._send(h, 200, prof)
        elif path == "/":
            self._send(h, 200, {"endpoints": list(ENDPOINTS)})
        else:
            self._send(h, 404, {"error": f"unknown path {path!r}",
                                "endpoints": list(ENDPOINTS)})
