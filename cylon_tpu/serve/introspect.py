"""Read-only live introspection endpoint for the serve engine.

Until this module the only way to see inside a running
:class:`~cylon_tpu.serve.ServeEngine` was to kill it and read the
atexit telemetry flush. This is the ops plane: a stdlib
``http.server`` thread serving the engine's live state as JSON (and
Prometheus text), armed ONLY by ``CYLON_TPU_SERVE_HTTP_PORT`` — the
same no-threads-unless-armed contract as every other telemetry
surface: with the env unset, :func:`maybe_start` is one env read and
returns None; no socket is bound, no thread starts (pinned by
``tests/test_introspect.py``).

Endpoints (all GET, all read-only — the bench guard lints statically
that no handler can reach ``submit``/``register_*``/``drop_*``/
``close``):

=======================  ==============================================
path                     payload
=======================  ==============================================
``/healthz``             liveness: state, live request count, uptime
``/metrics``             live Prometheus text (the PR 3 exposition
                         formatter over a fresh registry snapshot)
``/queries``             in-flight tickets — tenant, state, elapsed,
                         remaining SLO budget, step count — plus the
                         process's active watchdog sections (what the
                         engine is blocked on RIGHT NOW)
``/tenants``             ``ServeEngine.tenant_stats()``
``/tables``              resident catalog: rows/bytes/pins/holders +
                         the per-device byte split
``/profiles/<rid>``      one retired-or-live request's ANALYZE
                         profile (``QueryTicket.profile()``)
=======================  ==============================================

Binding is loopback-only (``127.0.0.1``) — this is an operator
diagnostic port, not a public API; port ``0`` binds an ephemeral port
(tests), the bound address is ``IntrospectServer.address``.
"""

import json
import os
import threading
import time

__all__ = ["maybe_start", "IntrospectServer", "ENDPOINTS"]

#: the read-only surface (for docs and the landing page)
ENDPOINTS = ("/healthz", "/metrics", "/queries", "/tenants",
             "/tables", "/profiles/<rid>")


def maybe_start(engine) -> "IntrospectServer | None":
    """Start the introspection server for ``engine`` IFF
    ``CYLON_TPU_SERVE_HTTP_PORT`` is set — otherwise one env read,
    None returned, no socket/thread exists.

    Startup failures (malformed port value, address already in use)
    are logged LOUDLY and degrade to None instead of raising: the
    endpoint is a diagnostic, and a stale listener on the configured
    port must never take down engine construction — least of all
    ``ServeEngine.recover()``, where failing here would abandon a
    durable engine's journaled requests."""
    port = os.environ.get("CYLON_TPU_SERVE_HTTP_PORT")
    if not port:
        return None
    from cylon_tpu.utils.logging import get_logger

    try:
        return IntrospectServer(engine, int(port))
    except (ValueError, OSError) as e:
        get_logger().warning(
            "introspection endpoint NOT started "
            "(CYLON_TPU_SERVE_HTTP_PORT=%r): %s: %s — the engine "
            "runs without its ops plane", port, type(e).__name__, e)
        return None


class IntrospectServer:
    """One daemon HTTP thread serving an engine's live state."""

    def __init__(self, engine, port: int):
        import http.server

        self._engine = engine
        self._started = time.monotonic()
        outer = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            server_version = "cylon-tpu-introspect"
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet by default
                from cylon_tpu.utils.logging import get_logger

                get_logger().debug("introspect: " + fmt, *args)

            def do_GET(self):  # noqa: N802 - stdlib handler name
                try:
                    outer._route(self)
                except BrokenPipeError:  # client went away mid-write
                    pass
                except Exception as e:  # never kill the server thread
                    try:
                        outer._send(self, 500, {
                            "error": f"{type(e).__name__}: {e}"})
                    except Exception:
                        pass

        self._httpd = http.server.ThreadingHTTPServer(
            ("127.0.0.1", port), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="cylon-serve-introspect", daemon=True)
        self._thread.start()

    @property
    def address(self) -> "tuple[str, int]":
        """(host, port) actually bound (port 0 resolves here)."""
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    # ---------------------------------------------------------- routes
    def _send(self, h, code: int, payload, content_type=None) -> None:
        from cylon_tpu import telemetry

        if isinstance(payload, (dict, list)):
            body = json.dumps(telemetry.json_safe(payload),
                              allow_nan=False).encode()
            content_type = content_type or "application/json"
        else:
            body = str(payload).encode()
            content_type = content_type or "text/plain; charset=utf-8"
        h.send_response(code)
        h.send_header("Content-Type", content_type)
        h.send_header("Content-Length", str(len(body)))
        h.end_headers()
        h.wfile.write(body)

    def _route(self, h) -> None:
        from cylon_tpu import telemetry, watchdog

        path = h.path.split("?", 1)[0].rstrip("/") or "/"
        eng = self._engine
        if path == "/healthz":
            self._send(h, 200, {
                "status": "closed" if eng._closed else "ok",
                "live": eng.live,
                "uptime_s": time.monotonic() - self._started,
            })
        elif path == "/metrics":
            self._send(h, 200, telemetry.to_prometheus(),
                       content_type="text/plain; version=0.0.4; "
                                    "charset=utf-8")
        elif path == "/queries":
            self._send(h, 200, {
                "queries": eng.queries(),
                "active_sections": [
                    {"section": s, "detail": d, "elapsed_s": e}
                    for s, d, e in watchdog.active_sections()],
            })
        elif path == "/tenants":
            self._send(h, 200, eng.tenant_stats())
        elif path == "/tables":
            self._send(h, 200, eng.table_stats())
        elif path.startswith("/profiles/"):
            rid = path.rsplit("/", 1)[1]
            ticket = eng.ticket(int(rid)) if rid.isdigit() else None
            if ticket is None:
                self._send(h, 404, {"error": f"unknown rid {rid!r}"})
                return
            prof = ticket.profile()
            if prof is None:
                self._send(h, 404, {
                    "error": f"request {rid} has no profile "
                             "(CYLON_TPU_SERVE_PROFILE=0?)"})
                return
            self._send(h, 200, prof)
        elif path == "/":
            self._send(h, 200, {"endpoints": list(ENDPOINTS)})
        else:
            self._send(h, 404, {"error": f"unknown path {path!r}",
                                "endpoints": list(ENDPOINTS)})
