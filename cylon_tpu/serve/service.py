"""The always-on query engine: one resident mesh, many tenants.

Everything below :mod:`cylon_tpu.serve` is one-script-one-query; this
module is the front door the ROADMAP's "millions of users" item calls
for — a long-lived :class:`ServeEngine` that admits concurrent queries
against shared resident tables and drives them to completion over ONE
resident :class:`~cylon_tpu.context.CylonEnv`.

Design — an assembly of the subsystems the previous PRs built:

* **Admission** (:mod:`cylon_tpu.serve.admission`): a queue-depth cap
  rejects over-cap submits with a fast
  :class:`~cylon_tpu.errors.ResourceExhausted`; every admitted request
  is stamped with an absolute SLO deadline (queue wait counts — the
  client-visible contract).

* **Scheduling**: each admitted request becomes a :class:`_QueryOp` —
  an :class:`cylon_tpu.ops_graph.op.Op` whose ``progress()`` advances
  the query one *step* — and ONE long-lived
  :class:`~cylon_tpu.ops_graph.execution.RoundRobinExecution` (fair
  share, the default) or
  :class:`~cylon_tpu.ops_graph.execution.PriorityExecution` (tenant
  weights) sweeps the live set exactly the way the reference's
  parallel-op engine progresses concurrent op streams
  (``ops/execution/execution.hpp``). Query functions may be plain
  callables (one step) or **generator functions** (each ``yield`` is a
  step boundary) — a staged query yields after its dispatch phase, so
  while its XLA work is in flight on the mesh the scheduler is already
  driving the next request's host-side phase: host→device transfer and
  device compute interleave *across requests*.

* **Per-request SLO** (:mod:`cylon_tpu.watchdog`): every step runs
  under ``watchdog.deadline(remaining)`` inside a named
  ``serve_request`` :func:`~cylon_tpu.watchdog.watched_section`, so a
  wedged step dumps stacks and the request fails with
  :class:`~cylon_tpu.errors.DeadlineExceeded` instead of stalling the
  schedule; expired requests are refused *before* their next step runs.

* **Shared compiled-plan cache** (:func:`cylon_tpu.plan.shared_compiled`):
  submit compiled queries (e.g. ``tpch.compiled("q3")``) and N clients
  with the same query shape pay ONE trace — later calls are
  ``plan.cache_hits``.

* **Per-tenant observability**: every step executes under
  :func:`cylon_tpu.telemetry.tenant_scope`, so span timers, watchdog
  sections, fault/retry counters and flight-recorder events all carry
  the tenant label; request latency lands in
  ``serve.request_seconds{tenant=}`` whose
  :meth:`~cylon_tpu.telemetry.Histogram.quantile` supplies per-tenant
  p50/p99 (:meth:`ServeEngine.tenant_stats`).

* **Fault isolation**: a per-request
  :class:`~cylon_tpu.resilience.FaultPlan` is installed only around
  that request's steps (the scheduler runs steps one at a time, so the
  scope can never leak into another tenant's step), and resident-table
  pins (:func:`cylon_tpu.catalog.pin`) keep a concurrent ``drop`` from
  yanking a table out from under an in-flight query.

* **Durability** (:mod:`cylon_tpu.serve.durability`): with a
  ``durable_dir``, every admitted request is journaled (fsynced
  write-ahead, BEFORE dispatch — the invariant the bench guard lints
  statically) and every registered table snapshots through the
  checkpoint spill machinery, so a hard-killed engine process recovers
  via :meth:`ServeEngine.recover`: mesh restarted, resident tables
  restored, journaled-but-incomplete **named** requests re-run exactly
  once (client-supplied idempotency keys dedup a client's own retries
  against the replay). A sustained failure storm trips the admission
  circuit breaker (:class:`~cylon_tpu.serve.admission.CircuitBreaker`)
  instead of wedging the engine: new work sheds fast, in-flight work
  drains.

* **Coalescing + the versioned result cache**
  (:mod:`cylon_tpu.serve.result_cache`): same-``(query fingerprint,
  table-version vector)`` requests dedup at admission — a completed
  result is served straight from the byte-budgeted cache
  (``serve.admitted{path="cache_hit"}``, invalidated precisely by
  :func:`cylon_tpu.catalog.append`), and requests identical to one
  already in flight attach to it as followers of ONE scheduler op
  (``path="coalesced"``) fanned back to N tickets at retirement. Each
  ticket keeps its own tenant label, SLO deadline, journal entry and
  profile (``coalesced: leader|follower``); dedup'd paths never feed
  the circuit breaker and never count queue wait — they never ran.

* **Graceful degradation** (:mod:`cylon_tpu.fallback`): a request
  submitted with a ``fallback=`` spill path whose step dies with an
  allocation failure re-runs ONCE through that path instead of
  erroring — it retires DONE with ``degraded=true`` (+ the OOM
  forensics report) in its ANALYZE profile, counts
  ``serve.degraded{tenant}``, and NEVER feeds the circuit breaker
  (only a fallback that *also* fails retires as an error). Memory-
  aware admission (``ServePolicy.memory_budget``) sheds requests whose
  ``predicted_bytes`` cannot fit, counted
  ``serve.shed{reason="memory"}``.
"""

import collections
import contextlib
import functools
import itertools
import os
import threading
import time

from cylon_tpu import catalog, plan, resilience, telemetry, watchdog
from cylon_tpu.errors import (DeadlineExceeded, FailedPrecondition,
                              InvalidArgument)
from cylon_tpu.ops_graph.execution import (PriorityExecution,
                                           RoundRobinExecution)
from cylon_tpu.ops_graph.op import Op
from cylon_tpu.serve.admission import AdmissionController, ServePolicy
from cylon_tpu.serve import introspect
from cylon_tpu.serve.result_cache import (ResultCache,
                                          cache_bytes_from_env,
                                          hook_on_append,
                                          version_vector)
from cylon_tpu.serve.slo import SloTracker
from cylon_tpu.telemetry import events as _events
from cylon_tpu.telemetry import memory as _memory
from cylon_tpu.telemetry import profile as _profile
from cylon_tpu.telemetry import trace as _trace
from cylon_tpu.utils import tracing

__all__ = ["QueryTicket", "ServeEngine"]

#: request lifecycle states (QueryTicket.state)
QUEUED, RUNNING, DONE, FAILED = "queued", "running", "done", "failed"


class QueryTicket:
    """Handle for one admitted request (the client's future)."""

    def __init__(self, rid: int, tenant: str, priority: int,
                 slo: "float | None"):
        self.rid = rid
        self.tenant = tenant
        self.priority = priority
        self.slo = slo
        self.submitted = time.monotonic()
        #: absolute SLO expiry (monotonic); queue wait counts against
        #: the budget — the latency the CLIENT sees is the contract
        self.deadline_at = (None if slo is None
                            else self.submitted + float(slo))
        self.started: "float | None" = None
        self.finished: "float | None" = None
        self.state = QUEUED
        self.value = None
        self.error: "BaseException | None" = None
        #: did this request complete through the OOM→spill fallback?
        #: (set by the scheduler's degrade path; rides ``profile()``)
        self.degraded = False
        #: dedup attribution: ``leader``/``follower`` when this request
        #: coalesced with identical in-flight work (rides ``profile()``
        #: as the ``coalesced`` marker), True when it was served
        #: straight from the versioned result cache
        self.coalesced_role: "str | None" = None
        self.cache_hit = False
        #: ``{"fingerprint", "versions"}`` the result is cacheable
        #: under (set at retirement IFF the version vector was still
        #: current) — the fleet router's cross-engine cache key
        self.cache_key: "dict | None" = None
        #: fleet trace identity (ISSUE 20): the one id naming this
        #: request's whole causal chain — inherited from the router's
        #: HTTP headers, minted at direct submit when tracing is
        #: armed, None (zero cost) otherwise. A failover REPLAY keeps
        #: the original id, so the stitched timeline spans engines.
        self.trace_id: "str | None" = None
        self.parent_span = None
        self._event = threading.Event()
        #: ANALYZE profiler (telemetry.profile.RequestProfiler), set
        #: at admission unless CYLON_TPU_SERVE_PROFILE=0
        self._profiler = None
        self._retired = False

    def remaining(self) -> "float | None":
        """Seconds of SLO budget left (None = unbounded)."""
        if self.deadline_at is None:
            return None
        return self.deadline_at - time.monotonic()

    @property
    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: "float | None" = None) -> bool:
        return self._event.wait(timeout)

    def profile(self) -> "dict | None":
        """The request's EXPLAIN ANALYZE profile
        (:data:`cylon_tpu.telemetry.profile.REQUIRED_PROFILE_FIELDS`):
        per-stage walls, rows/bytes per operator, compile-vs-execute
        split, spill bytes, retries/faults and the HBM peak watermark
        — live (partial) while running, final once retired. None when
        profiling is disabled (``CYLON_TPU_SERVE_PROFILE=0``)."""
        if self._profiler is None:
            return None
        prof = self._profiler.render(self)
        if isinstance(prof, dict):
            if self.coalesced_role is not None:
                prof["coalesced"] = self.coalesced_role
            if self.cache_hit:
                prof["cache_hit"] = True
        return prof

    def result(self, timeout: "float | None" = None):
        """Block for the result; re-raise the request's failure."""
        if not self._event.wait(timeout):
            raise DeadlineExceeded(
                f"result({timeout=}) timed out waiting on request "
                f"{self.rid} (tenant {self.tenant!r}, state "
                f"{self.state})", section="serve_request")
        if self.error is not None:
            raise self.error
        return self.value

    def __repr__(self):
        return (f"QueryTicket(rid={self.rid}, tenant={self.tenant!r}, "
                f"state={self.state})")


class _QueryOp(Op):
    """One admitted request as a schedulable op node.

    ``progress()`` advances the query by one step: for a generator
    function each ``yield`` delimits a step (``StopIteration.value`` is
    the result); a plain callable is a single step. Steps run under the
    request's tenant scope + remaining-SLO deadline + per-request fault
    plan + the ``serve_request`` watchdog section — all scoped to the
    step, so nothing leaks into the next op the schedule sweeps."""

    def __init__(self, op_id: int, engine: "ServeEngine",
                 ticket: QueryTicket, fn, args, kwargs,
                 fault_plan, pins: "list[str]", fallback=None):
        super().__init__(op_id, name=f"QueryOp[{ticket.tenant}]")
        self._engine = engine
        self.ticket = ticket
        self._fn = fn
        self._args = args
        self._kwargs = kwargs
        self._fault_plan = fault_plan
        self._pins = pins
        self._gen = None
        self._step = 0
        #: the request's spill path (zero-arg callable or generator
        #: fn): armed by submit(fallback=); consumed at most once
        self._fallback = fallback
        self._degraded = False

    def done(self) -> bool:
        return self.ticket.done

    def progress(self) -> bool:  # one scheduled step
        t = self.ticket
        if t.done:
            return False
        # liveness stamp at STEP granularity (not just sweep ends):
        # /health's scheduler-age probe must not read a long single
        # step mid-sweep as a wedged scheduler
        self._engine._last_sweep = time.monotonic()
        # followers never run steps, so the per-step SLO check above
        # cannot expire them — sweep the attached tickets here
        self._engine._expire_followers(self)
        try:
            rem = t.remaining()
            if rem is not None and rem <= 0:
                telemetry.counter("serve.expired", tenant=t.tenant).inc()
                raise DeadlineExceeded(
                    f"request {t.rid} (tenant {t.tenant!r}) missed its "
                    f"{t.slo:.3f}s SLO after {self._step} step(s)",
                    section="serve_request",
                    elapsed=time.monotonic() - t.submitted)
            self._run_step(rem)
        except BaseException as e:  # noqa: BLE001 - isolate per request
            if not self._maybe_degrade(e):
                self._engine._retire(self, error=e)
        finally:
            # the client-visible completion signal fires only AFTER
            # the step's profiler/forensics scopes have fully unwound:
            # a result() that returned implies the ANALYZE profile is
            # complete, not racing the scheduler's bookkeeping
            if t.state in (DONE, FAILED):
                t._event.set()
        return True

    def _maybe_degrade(self, e: BaseException) -> bool:
        """An OOM'd step with an armed ``fallback=`` degrades instead
        of erroring: the op swaps its query fn for the spill callable
        and stays LIVE — the next schedule sweep re-runs it through
        the degraded path under the same tenant scope, remaining SLO
        and profiler. Consumed at most once: a fallback that ALSO
        fails retires as a normal error (and only then can feed the
        circuit breaker — an OOM that ends in a successful degraded
        completion never does)."""
        t = self.ticket
        if (self._fallback is None or self._degraded
                or not _memory.is_oom(e)):
            return False
        self._degraded = True
        # NOTE: ticket.degraded + serve.degraded{tenant} are recorded
        # at SUCCESSFUL retirement (_retire), not here — "degraded"
        # means COMPLETED through the spill path; a fallback that also
        # fails retires as a plain error. The routing counter fires
        # now: the query IS being routed to the spill path, whether or
        # not its fallback callable goes through run_with_fallback.
        telemetry.counter("ooc.fallbacks", op="serve",
                          reason="oom").inc()
        with _trace.trace_context(t.trace_id, t.parent_span):
            # the degrade re-run keeps the SAME trace_id: one id names
            # admission, the OOM'd attempt AND the spill-path rerun
            _trace.instant("serve.degrade", cat="serve",
                           tenant=t.tenant, rid=t.rid,
                           error=type(e).__name__)
        _events.emit("degraded", tenant=t.tenant, rid=t.rid,
                     error=type(e).__name__)
        from cylon_tpu.utils.logging import get_logger

        get_logger().warning(
            "request %d (tenant %r) exhausted memory (%s) — "
            "degrading through its spill fallback", t.rid, t.tenant,
            type(e).__name__)
        self._gen = None
        self._fn, self._args, self._kwargs = self._fallback, (), {}
        return True

    def _run_step(self, rem: "float | None") -> None:
        t = self.ticket
        if t.started is None:
            t.started = time.monotonic()
            t.state = RUNNING
            telemetry.timer("serve.queue_wait_seconds",
                            tenant=t.tenant).observe(
                                t.started - t.submitted)
        with contextlib.ExitStack() as stack:
            # ORDER matters: the tenant scope first (so every nested
            # metric/trace/section carries the label), then the SLO
            # budget, then the request's fault plan — scoped to this
            # step only, which is the whole isolation argument
            if t.trace_id is not None:
                # every span/instant the step records carries the
                # request's fleet trace id (None = unarmed: this
                # branch costs one attribute read, nothing else)
                stack.enter_context(_trace.trace_context(
                    t.trace_id, t.parent_span))
            stack.enter_context(telemetry.tenant_scope(t.tenant))
            if rem is not None:
                stack.enter_context(watchdog.deadline(
                    rem, label=f"serve:{t.rid}"))
            if self._fault_plan is not None:
                # context-LOCAL install (contextvar, not the process
                # global): a noisy tenant's plan is invisible to any
                # other thread reaching an injection point, and it
                # propagates into watchdog workers via copy_context
                stack.enter_context(resilience.scoped(self._fault_plan))
            stack.enter_context(tracing.span(
                "serve.step", cat="serve", rid=t.rid, step=self._step))
            stack.enter_context(watchdog.watched_section(
                "serve_request", detail=f"{t.tenant}/{t.rid}"
                f"#{self._step}"))
            if t._profiler is not None:
                # registry-delta + memory-sample bracket: the one-step-
                # at-a-time scheduler makes the delta THIS request's
                stack.enter_context(t._profiler.step())
            # allocation failures inside the step get the resident-
            # consumer forensics dump before the request fails
            stack.enter_context(_memory.forensics("serve_request"))
            self._step += 1
            if self._gen is None:
                first = self._fn(*self._args, **self._kwargs)
                if hasattr(first, "__next__"):  # generator query
                    self._gen = first
                else:  # plain callable: one step, done
                    self._engine._retire(self, value=first)
                    return
            try:
                next(self._gen)
            except StopIteration as fin:
                self._engine._retire(self, value=fin.value)


class ServeEngine:
    """The long-lived multi-tenant query service (module docstring).

    One engine per process/mesh is the intended shape; the env is
    resident for the engine's lifetime. Thread-safe: many client
    threads submit; ONE scheduler thread executes steps (the same
    single-threaded progress model the reference's parallel-op engine
    runs between MPI calls — concurrency comes from interleaving steps
    and from XLA's async dispatch, not from racing host threads into
    the mesh)."""

    _ids = itertools.count(1)

    def __init__(self, env=None, policy: "ServePolicy | None" = None,
                 durable_dir: "str | None" = None,
                 snapshot_dir: "str | None" = None):
        self._env = env
        self._admission = AdmissionController(policy)
        self._policy = self._admission.policy
        #: per-tenant SLO burn accounting (ISSUE 14) — a no-op unless
        #: the policy sets slo_target (no windows allocated)
        self._slo = SloTracker(self._policy)
        self._started = time.monotonic()
        #: monotonic ts of the scheduler's last liveness stamp —
        #: refreshed at admission, loop wake-up, every op step, and
        #: sweep completion, so /health's age probe only grows while
        #: the scheduler is genuinely wedged inside one step (an idle
        #: engine or a freshly-admitted cold query never reads stalled)
        self._last_sweep: "float | None" = None
        if self._policy.schedule == "priority":
            self._exec = PriorityExecution()
        else:
            self._exec = RoundRobinExecution()
        self._cond = threading.Condition()
        self._thread: "threading.Thread | None" = None
        self._closed = False
        self._op_ids = itertools.count(1)
        #: named-query registry: the replayable submission surface
        #: (recovery can only re-run what it can name)
        self._queries: "dict[str, object]" = {}
        #: idempotency-key -> ticket (live AND retired): a retried key
        #: returns the existing ticket instead of double-executing
        self._idem: "dict[str, QueryTicket]" = {}
        #: versioned result cache (byte-budgeted LRU, precise
        #: catalog.on_append invalidation) — the admission-time fast
        #: path that keeps hot queries off the mesh entirely
        self._result_cache = hook_on_append(ResultCache(
            cache_bytes_from_env("CYLON_TPU_SERVE_RESULT_CACHE_BYTES"),
            metric_prefix="serve"))
        #: (fingerprint, version-vector) -> live leader op: identical
        #: in-queue requests attach here as followers of ONE scheduler
        #: op instead of executing N times (under ``_cond``)
        self._coalesce: "dict[tuple, _QueryOp]" = {}
        self._journal = self._snapshot = None
        if durable_dir is not None:
            from cylon_tpu.serve.durability import (CatalogSnapshot,
                                                    RequestJournal)

            # the journal acquires this engine's exclusive owner lock
            # (fleet fencing — a second live engine on the same dir
            # fails loudly); snapshot_dir lets a fleet share ONE
            # snapshot store while journals stay per-engine
            self._journal = RequestJournal(durable_dir)
            self._snapshot = CatalogSnapshot(snapshot_dir or durable_dir)
        #: measured query-cost history (ISSUE 20): executed walls
        #: keyed by (fingerprint, row bucket), persisted under the
        #: durable tree so explain()'s predicted_wall_s survives a
        #: restart and merges fleet-wide. Durable engines only — the
        #: same class of hot-path cost as the write-ahead journal.
        self._profile_history = None
        if durable_dir is not None:
            self._profile_history = _profile.ProfileHistory(
                os.path.join(durable_dir, _profile.HISTORY_FILE))
        self.durable_dir = durable_dir
        #: bounded rid -> ticket history (live AND retired): the
        #: lookup surface behind /profiles/<rid> and QueryTicket
        #: retrieval after the fact
        self._recent: "collections.OrderedDict[int, QueryTicket]" = \
            collections.OrderedDict()
        #: the ops-plane HTTP thread — armed ONLY by
        #: CYLON_TPU_SERVE_HTTP_PORT (None otherwise: no socket, no
        #: thread — the telemetry fast-path contract, pinned by test)
        self._http = introspect.maybe_start(self)

    # ------------------------------------------------- resident tables
    @property
    def env(self):
        return self._env

    def register_table(self, table_id: str, table) -> None:
        """Register a resident table (Table or DataFrame) in the
        process catalog under ``table_id`` — the shared store every
        request reads through (pin-protected; see
        :func:`cylon_tpu.catalog.drop`). On a durable engine the
        table's host content also snapshots to ``durable_dir`` so
        :meth:`recover` can restore it after a kill."""
        t = getattr(table, "table", table)
        catalog.put_table(table_id, t)
        if self._snapshot is not None:
            self._snapshot.save(table_id, t, env=self._env,
                                generation=catalog.generation(table_id))

    def append_table(self, table_id: str, delta) -> dict:
        """Fold delta rows into a resident table under the catalog's
        atomic swap (:func:`cylon_tpu.catalog.append`) — legal while
        the table is pinned (in-flight readers finish against the
        generation they started on). On a durable engine the merged
        table re-snapshots WITH its new generation stamped into the
        snapshot map, so :meth:`recover` after the append restores the
        post-append generation instead of silently serving the stale
        one. Returns ``{"generation", "delta_rows", "rows"}``."""
        res = catalog.append(table_id, delta, env=self._env)
        if self._snapshot is not None:
            self._snapshot.save(table_id, catalog.get_table(table_id),
                                env=self._env,
                                generation=res["generation"])
        return res

    def drop_table(self, table_id: str) -> None:
        """Pin-respecting drop: raises
        :class:`~cylon_tpu.errors.FailedPrecondition` naming the
        holders while any session/request still pins the table."""
        catalog.drop(table_id, if_exists=False)
        if self._snapshot is not None:
            self._snapshot.drop(table_id)

    def register_query(self, name: str, fn, fallback=None,
                       tables=()) -> None:
        """Name a query function for :meth:`submit_named` — the
        REPLAYABLE submission surface: only named queries (with
        JSON-able args) can be re-run by :meth:`recover`, because the
        journal can name them where it cannot serialize a closure.

        ``fallback`` (same signature as ``fn``) registers the query's
        spill path alongside it: every :meth:`submit_named` —
        INCLUDING a journal replay after :meth:`recover` — arms it
        automatically, so graceful degradation survives a crash (the
        journal can name the query but could never serialize a
        per-submit fallback closure).

        ``tables`` declares the query's READ SET (resident catalog
        ids): it is what makes the query coalescible and cacheable —
        the version vector half of the ``(fingerprint, versions)``
        dedup key is computed over exactly these tables (plus any
        per-submit pins), so an append to any of them invalidates the
        cached result precisely. A query registered WITHOUT tables has
        no versionable read set and is never deduped."""
        self._queries[str(name)] = (fn, fallback,
                                    tuple(str(t) for t in tables))

    def table_stats(self) -> dict:
        """Per-table rows/bytes/pins/version of the resident catalog
        (the ``version`` column carries the monotone generation +
        content digest the views subsystem keys on)."""
        return catalog.stats()

    # --------------------------------------------- materialized views
    def register_view(self, name: str, query_fn, refresh_plan: dict,
                      *, sources, delta_source: "str | None" = None,
                      limit=None):
        """Register an incremental materialized view over this
        engine's resident tables
        (:func:`cylon_tpu.views.register_view`, bound to the engine's
        env so distributed sources gather correctly)."""
        from cylon_tpu import views

        return views.register_view(
            name, query_fn, refresh_plan, sources=sources,
            delta_source=delta_source, limit=limit, env=self._env)

    def refresh_view(self, name: str, *,
                     resume_dir: "str | None" = None,
                     full: bool = False) -> dict:
        """Bring a view up to date with its sources
        (:func:`cylon_tpu.views.refresh`); ``resume_dir`` makes the
        refresh checkpointable across a kill."""
        from cylon_tpu import views

        return views.refresh(name, resume_dir=resume_dir, full=full)

    def read_view(self, name: str) -> dict:
        """Generation-consistent view read
        (:func:`cylon_tpu.views.read`): the returned ``result`` is
        exactly the view at the returned ``generations`` — an append
        racing the read lands entirely before or entirely after it,
        never inside."""
        from cylon_tpu import views

        return views.read(name)

    def view_stats(self) -> dict:
        """Per-view watermarks/digests/refresh counts
        (:func:`cylon_tpu.views.stats`)."""
        from cylon_tpu import views

        return views.stats()

    def session(self, tenant: str, priority: int = 1, tables=()):
        """Open a :class:`cylon_tpu.serve.session.Session` bound to
        this engine (pins ``tables`` for the session's lifetime)."""
        from cylon_tpu.serve.session import Session

        return Session(self, tenant, priority=priority, tables=tables)

    # ------------------------------------------------------ submission
    def submit(self, fn, *args, tenant: str = "default",
               priority: int = 1, slo: "float | None" = None,
               tables=(), fault_plan=None,
               idempotency_key: "str | None" = None,
               fallback=None, predicted_bytes: "int | None" = None,
               _journal_name: "str | None" = None,
               _fingerprint: "str | None" = None,
               _read_tables=None,
               _trace_id: "str | None" = None,
               _parent_span=None,
               **kwargs) -> QueryTicket:
        """Admit one query for scheduled execution.

        ``fn(*args, **kwargs)`` runs on the scheduler thread — a plain
        callable is one step; a generator function advances one step
        per schedule sweep (its ``return`` value is the result).
        ``slo=None`` takes the engine default
        (``CYLON_TPU_SERVE_SLO``); ``slo <= 0`` explicitly unbounds the
        request. ``tables`` are catalog ids pinned for the request's
        lifetime. ``fault_plan`` (tests/chaos drills) is installed only
        around this request's steps. ``idempotency_key`` dedups: a key
        the engine has already seen (live or retired) returns the
        EXISTING ticket — the same request is never executed twice, so
        a client retrying after a lost answer (or a recovery replaying
        the journal) is safe. ``fallback`` (a zero-arg callable or
        generator fn — e.g. ``lambda:
        cylon_tpu.fallback.tpch_fallback("q3", data)``) arms the
        degrade path: a step that dies with an allocation failure
        re-runs ONCE through it instead of erroring (``degraded=true``
        in the profile, ``serve.degraded{tenant}``, breaker untouched).
        ``predicted_bytes`` feeds memory-aware admission: when it
        exceeds the policy's ``memory_budget`` the submit sheds
        immediately (``serve.shed{reason="memory"}``). Raises
        :class:`~cylon_tpu.errors.ResourceExhausted` immediately when
        the live-request cap is hit, the memory budget is exceeded, or
        the circuit breaker is open."""
        if self._closed:
            raise InvalidArgument("engine is closed")
        key = idempotency_key
        if key is not None:
            with self._cond:
                existing = self._idem.get(key)
            if existing is not None:
                telemetry.counter("serve.idempotent_hits",
                                  tenant=tenant).inc()
                return existing
        # fleet trace identity (ISSUE 20): adopt the propagated id
        # (router → gateway headers → here), else the ambient context,
        # else mint at this outermost entry — ONLY when tracing is
        # armed. Unarmed: one env read, trace_id stays None and every
        # downstream trace hook short-circuits on that None.
        if _trace_id is None and _trace.enabled():
            _trace_id = _trace.current_trace_id() or _trace.new_trace_id()
            if _parent_span is None:
                _parent_span = _trace.current_parent_span()
        # journal the PRE-normalization slo: an explicit slo<=0
        # ("unbounded") must replay unbounded, not pick up the engine
        # default the way a None would
        slo_raw = slo
        if slo is None:
            slo = self._policy.default_slo
        elif slo <= 0:
            slo = None
        # the two-level dedup (fingerprinted submits only — bare
        # callables have no stable identity): a completed result under
        # this exact (fingerprint, table-version vector) is served
        # straight from the cache; failing that, identical in-flight
        # work adopts this request as a follower. Both paths bypass
        # the scheduler entirely.
        fp, vv = _fingerprint, None
        if fp is not None and (self._result_cache.enabled
                               or self._coalesce_on()):
            vv = version_vector(_read_tables)
        if vv is not None and self._result_cache.enabled:
            hit, cached = self._result_cache.lookup(fp, vv)
            if hit:
                return self._admit_cache_hit(
                    cached, fp, vv, tenant=tenant, priority=priority,
                    slo=slo, slo_raw=slo_raw, key=key,
                    journal_name=_journal_name, args=args,
                    kwargs=kwargs, tables=tables,
                    trace_id=_trace_id, parent_span=_parent_span)
        if vv is not None and self._coalesce_on():
            follower = self._maybe_attach_follower(
                fp, vv, fn=fn, args=args, kwargs=kwargs,
                tenant=tenant, priority=priority, slo=slo,
                slo_raw=slo_raw, key=key, tables=tables,
                fault_plan=fault_plan, fallback=fallback,
                journal_name=_journal_name, trace_id=_trace_id,
                parent_span=_parent_span)
            if follower is not None:
                return follower
        # may raise ResourceExhausted (queue cap, breaker, or the
        # memory-aware predicted-bytes shed)
        self._admission.admit(tenant, predicted_bytes=predicted_bytes)
        ticket = QueryTicket(next(self._ids), str(tenant),
                             int(priority), slo)
        ticket.trace_id, ticket.parent_span = _trace_id, _parent_span
        if _profile.profiling_enabled():
            ticket._profiler = _profile.RequestProfiler()
        holder = f"{tenant}/req{ticket.rid}"
        pinned: list[str] = []
        try:
            for tid in tables:
                catalog.pin(tid, holder=holder)
                pinned.append(tid)
        except Exception:
            for tid in pinned:
                catalog.unpin(tid, holder=holder)
            self._admission.release()
            raise
        op = _QueryOp(next(self._op_ids), self, ticket, fn, args,
                      kwargs, fault_plan, pinned, fallback=fallback)
        op._holder = holder
        op._idem_key = key
        # dedup bookkeeping: a fingerprinted op is the (potential)
        # leader of its (fp, vv) coalesce group and publishes its
        # result to the cache at retirement; followers re-run through
        # _requeue_follower if it fails, which needs the journal name
        op._fp, op._vv = fp, vv
        op._followers = []
        op._coalesce_closed = False
        op._admitted = True
        if key is not None:
            with self._cond:
                existing = self._idem.get(key)
                if existing is not None:  # lost a submit race: undo
                    self._undo_admission(op)
                    telemetry.counter("serve.idempotent_hits",
                                      tenant=tenant).inc()
                    return existing
                self._idem[key] = ticket
                self._evict_idem_locked()
        telemetry.counter("serve.requests", tenant=ticket.tenant).inc()
        telemetry.counter("serve.admitted", path="executed",
                          tenant=ticket.tenant).inc()
        with _trace.trace_context(_trace_id, _parent_span):
            _trace.instant("serve.admit", cat="serve",
                           tenant=ticket.tenant, rid=ticket.rid,
                           slo=slo)
        _events.emit("admit", tenant=ticket.tenant, rid=ticket.rid,
                     slo=slo, path="executed")
        # WRITE-AHEAD: the journal records the admission durably BEFORE
        # the scheduler can touch it — a kill at any later instant
        # leaves the request recoverable (bench-guard lints this order).
        # A journal that cannot be written fails the submit CLEANLY
        # (slot/pins/key released): accepting an unjournalable request
        # would silently void the recovery contract.
        try:
            self._journal_admit(ticket, _journal_name, args, kwargs,
                                key, slo_raw, tables)
        except BaseException:
            with self._cond:
                self._undo_admission(op)
            raise
        with self._cond:
            # bounded rid->ticket history: the /profiles + ticket()
            # lookup surface (oldest-first eviction; generous cap,
            # env-tunable like the idempotency map). Defensive parse:
            # a malformed env value must not fail a submit AFTER the
            # journal write (the slot/pins would leak — the exact
            # window the journal-failure rollback exists to close)
            self._recent[ticket.rid] = ticket
            try:
                cap = int(os.environ.get(
                    "CYLON_TPU_SERVE_RECENT_ENTRIES", "1024"))
            except ValueError:
                cap = 1024
            while cap > 0 and len(self._recent) > cap:
                self._recent.popitem(last=False)
        self._dispatch(op, ticket)
        return ticket

    def _undo_admission(self, op: "_QueryOp") -> None:
        """Roll back an admission that never reached the scheduler:
        release pins + the admission slot + the idempotency entry.
        Caller holds ``self._cond``."""
        for tid in op._pins:
            try:
                catalog.unpin(tid, holder=op._holder)
            except Exception:  # pragma: no cover - unpin best-effort
                pass
        if getattr(op, "_admitted", True):
            # a requeued follower never took an admission slot; undoing
            # it must not release one it doesn't hold
            self._admission.release()
        if op._idem_key is not None and \
                self._idem.get(op._idem_key) is op.ticket:
            self._idem.pop(op._idem_key, None)

    def _evict_idem_locked(self) -> None:
        """Bound the idempotency map (always-on engines would otherwise
        grow it — and every retained result — forever): past the cap,
        drop retired entries OLDEST-RETIRED-FIRST (by finish time), so
        a recently completed ticket's result survives the bound
        instead of being dropped in arbitrary dict-insertion order;
        live tickets are never evicted. Caller holds ``self._cond``.
        An evicted key loses its dedup guarantee, which is why the cap
        is generous and env-tunable
        (``CYLON_TPU_SERVE_IDEM_ENTRIES``)."""
        try:
            cap = int(os.environ.get("CYLON_TPU_SERVE_IDEM_ENTRIES",
                                     "65536"))
        except ValueError:
            cap = 65536
        if cap <= 0 or len(self._idem) <= cap:
            return
        retired = sorted(
            ((t.finished if t.finished is not None else 0.0, k)
             for k, t in self._idem.items() if t.done))
        for _finished, k in retired:
            if len(self._idem) <= cap:
                break
            del self._idem[k]

    # ------------------------------------------------ dedup fast paths
    @staticmethod
    def _coalesce_on() -> bool:
        """Micro-batched dispatch knob (``CYLON_TPU_SERVE_COALESCE``;
        on by default, ``0``/``off`` disables)."""
        return os.environ.get("CYLON_TPU_SERVE_COALESCE",
                              "1") not in ("0", "off")

    def _record_recent_locked(self, ticket: QueryTicket) -> None:
        """Bounded rid->ticket history insert (caller holds
        ``_cond``): the /profiles + ticket() lookup surface."""
        self._recent[ticket.rid] = ticket
        try:
            cap = int(os.environ.get(
                "CYLON_TPU_SERVE_RECENT_ENTRIES", "1024"))
        except ValueError:
            cap = 1024
        while cap > 0 and len(self._recent) > cap:
            self._recent.popitem(last=False)

    def _admit_cache_hit(self, value, fp, vv, *, tenant, priority,
                         slo, slo_raw, key, journal_name, args,
                         kwargs, tables, trace_id=None,
                         parent_span=None) -> QueryTicket:
        """Serve one admission straight from the versioned result
        cache: the ticket retires DONE before submit() returns — no
        admission slot, no scheduler op, no mesh work. The request is
        still journaled (admit line THEN an immediate done line) so a
        :meth:`recover` after a kill never replays an answer the
        client already has. Cache hits never feed the circuit breaker
        and never observe ``serve.queue_wait_seconds`` — they never
        queued (the satellite-2 contract); they count
        ``serve.admitted{path="cache_hit"}``."""
        ticket = QueryTicket(next(self._ids), str(tenant),
                             int(priority), slo)
        ticket.cache_hit = True
        ticket.trace_id, ticket.parent_span = trace_id, parent_span
        ticket.cache_key = {"fingerprint": fp,
                            "versions": [list(v) for v in vv]}
        if _profile.profiling_enabled():
            ticket._profiler = _profile.RequestProfiler()
        if key is not None:
            with self._cond:
                existing = self._idem.get(key)
                if existing is not None:  # lost a submit race
                    telemetry.counter("serve.idempotent_hits",
                                      tenant=tenant).inc()
                    return existing
                self._idem[key] = ticket
                self._evict_idem_locked()
        telemetry.counter("serve.requests", tenant=ticket.tenant).inc()
        telemetry.counter("serve.admitted", path="cache_hit",
                          tenant=ticket.tenant).inc()
        with _trace.trace_context(trace_id, parent_span):
            # the short-circuit is part of the request's causal chain:
            # its admit/done instants carry the propagated trace_id
            _trace.instant("serve.admit", cat="serve",
                           tenant=ticket.tenant, rid=ticket.rid,
                           slo=slo)
        _events.emit("admit", tenant=ticket.tenant, rid=ticket.rid,
                     slo=slo, path="cache_hit")
        _events.emit("cache_hit", tenant=ticket.tenant,
                     rid=ticket.rid, fingerprint=fp)
        try:
            self._journal_admit(ticket, journal_name, args, kwargs,
                                key, slo_raw, tables)
        except BaseException:
            with self._cond:
                if key is not None and \
                        self._idem.get(key) is ticket:
                    self._idem.pop(key, None)
            raise
        with self._cond:
            self._record_recent_locked(ticket)
        self._finish_ticket(ticket, value=value, idem_key=key)
        return ticket

    def _maybe_attach_follower(self, fp, vv, *, fn, args, kwargs,
                               tenant, priority, slo, slo_raw, key,
                               tables, fault_plan, fallback,
                               journal_name, trace_id=None,
                               parent_span=None
                               ) -> "QueryTicket | None":
        """Micro-batched dispatch: if an identical ``(fp, vv)`` op is
        already in the queue, attach this request to it as a FOLLOWER
        — its own ticket (tenant label, SLO deadline, journal entry,
        profile marked ``coalesced: follower``) but no scheduler op
        and no admission slot: the leader's one execution fans back to
        every attached ticket at retirement. Returns None when there
        is no open leader (the caller proceeds down the normal
        admission path and becomes one)."""
        with self._cond:
            leader = self._coalesce.get((fp, vv))
            if (leader is None or leader._coalesce_closed
                    or leader.ticket.done):
                return None
            ticket = QueryTicket(next(self._ids), str(tenant),
                                 int(priority), slo)
            ticket.coalesced_role = "follower"
            ticket.trace_id, ticket.parent_span = trace_id, parent_span
            if _profile.profiling_enabled():
                ticket._profiler = _profile.RequestProfiler()
            holder = f"{tenant}/req{ticket.rid}"
            pinned: list = []
            try:
                for tid in tables:
                    catalog.pin(tid, holder=holder)
                    pinned.append(tid)
            except Exception:
                for tid in pinned:
                    catalog.unpin(tid, holder=holder)
                raise
            if key is not None:
                existing = self._idem.get(key)
                if existing is not None:  # lost a submit race
                    for tid in pinned:
                        catalog.unpin(tid, holder=holder)
                    telemetry.counter("serve.idempotent_hits",
                                      tenant=tenant).inc()
                    return existing
                self._idem[key] = ticket
                self._evict_idem_locked()
            leader.ticket.coalesced_role = "leader"
            telemetry.counter("serve.requests",
                              tenant=ticket.tenant).inc()
            telemetry.counter("serve.admitted", path="coalesced",
                              tenant=ticket.tenant).inc()
            telemetry.counter("serve.coalesced",
                              tenant=ticket.tenant).inc()
            with _trace.trace_context(trace_id, parent_span):
                _trace.instant("serve.admit", cat="serve",
                               tenant=ticket.tenant, rid=ticket.rid,
                               slo=slo)
            _events.emit("admit", tenant=ticket.tenant,
                         rid=ticket.rid, slo=slo, path="coalesced")
            _events.emit("coalesced", tenant=ticket.tenant,
                         rid=ticket.rid,
                         leader_rid=leader.ticket.rid)
            # WRITE-AHEAD: the follower journals its OWN admit line
            # before it can be answered — recover() after a kill
            # replays it independently of the leader's fate
            try:
                self._journal_admit(ticket, journal_name, args,
                                    kwargs, key, slo_raw, tables)
            except BaseException:
                for tid in pinned:
                    catalog.unpin(tid, holder=holder)
                if key is not None and self._idem.get(key) is ticket:
                    self._idem.pop(key, None)
                raise
            leader._followers.append({
                "ticket": ticket, "key": key, "fn": fn, "args": args,
                "kwargs": kwargs, "fault_plan": fault_plan,
                "fallback": fallback, "pins": pinned,
                "holder": holder, "name": journal_name,
                "slo_raw": slo_raw, "tables": tables, "fp": fp,
                "vv": vv})
            self._record_recent_locked(ticket)
            return ticket

    def _expire_followers(self, op: "_QueryOp") -> None:
        """Retire attached followers whose SLO budget ran out
        mid-flight (the scheduler's per-step expiry check cannot see
        them — they have no op). Counted ``serve.expired`` like any
        expiry, but NEVER fed to the circuit breaker: a coalesced
        ticket did no work that could indicate engine distress."""
        if not getattr(op, "_followers", None):
            return
        expired: list = []
        with self._cond:
            keep = []
            for rec in op._followers:
                rem = rec["ticket"].remaining()
                (expired if rem is not None and rem <= 0
                 else keep).append(rec)
            op._followers = keep
        for rec in expired:
            t = rec["ticket"]
            telemetry.counter("serve.expired", tenant=t.tenant).inc()
            self._finish_ticket(
                t, error=DeadlineExceeded(
                    f"coalesced request {t.rid} (tenant {t.tenant!r}) "
                    f"missed its {t.slo:.3f}s SLO while attached to "
                    f"leader {op.ticket.rid}", section="serve_request"),
                idem_key=rec["key"], pins=rec["pins"],
                holder=rec["holder"])

    def _fanout_follower(self, rec: dict, value) -> None:
        """Deliver the leader's result to one attached follower (or
        expire it, if its deadline passed between the last step and
        retirement — a stale answer is still a missed SLO)."""
        t = rec["ticket"]
        rem = t.remaining()
        if rem is not None and rem <= 0:
            telemetry.counter("serve.expired", tenant=t.tenant).inc()
            self._finish_ticket(
                t, error=DeadlineExceeded(
                    f"coalesced request {t.rid} (tenant {t.tenant!r}) "
                    f"missed its {t.slo:.3f}s SLO awaiting its "
                    "leader's result", section="serve_request"),
                idem_key=rec["key"], pins=rec["pins"],
                holder=rec["holder"])
            return
        self._finish_ticket(t, value=value, idem_key=rec["key"],
                            pins=rec["pins"], holder=rec["holder"])

    def _requeue_follower(self, rec: dict) -> None:
        """The leader FAILED but this follower still has SLO budget:
        re-run it as its own scheduler op (a leader failure fails only
        the tickets that cannot re-run within SLO). The write-ahead
        invariant holds here like every submission path: the re-run
        journals a fresh admit line BEFORE ``_dispatch`` (the journal
        dedups by key/rid, so the replay stays exactly-once)."""
        t = rec["ticket"]
        op = _QueryOp(next(self._op_ids), self, t, rec["fn"],
                      rec["args"], rec["kwargs"], rec["fault_plan"],
                      rec["pins"], fallback=rec["fallback"])
        op._holder = rec["holder"]
        op._idem_key = rec["key"]
        op._fp, op._vv = rec["fp"], rec["vv"]
        op._followers = []
        op._coalesce_closed = False
        #: no admission slot was ever taken for a follower — its
        #: retirement must not release one
        op._admitted = False
        try:
            self._journal_admit(t, rec["name"], rec["args"],
                                rec["kwargs"], rec["key"],
                                rec["slo_raw"], rec["tables"])
            self._dispatch(op, t)
        except BaseException as e:  # noqa: BLE001 - fail THIS ticket
            self._finish_ticket(t, error=e, idem_key=rec["key"],
                                pins=rec["pins"],
                                holder=rec["holder"])

    def _finish_ticket(self, ticket: QueryTicket, value=None,
                       error: "BaseException | None" = None, *,
                       idem_key: "str | None" = None, pins=(),
                       holder: "str | None" = None,
                       release_slot: bool = False,
                       feed_breaker: bool = False,
                       set_event: bool = True) -> None:
        """Shared retirement bookkeeping: outcome + latency + SLO
        accounting, journal done line, pin/slot release, waiter
        wake-up. Cache hits and coalesced followers retire through
        this directly (no slot, no breaker feed — they never ran);
        :meth:`_retire` routes executed ops through it with
        ``release_slot``/``feed_breaker`` armed."""
        t = ticket
        if getattr(t, "_retired", False):
            return
        t._retired = True
        t.finished = time.monotonic()
        wall = t.finished - t.submitted
        if error is None:
            t.state, t.value = DONE, value
            telemetry.counter("serve.completed", tenant=t.tenant).inc()
            if feed_breaker:
                self._admission.breaker.record_success()
        else:
            t.state, t.error = FAILED, error
            telemetry.counter("serve.errors", tenant=t.tenant,
                              kind=type(error).__name__).inc()
            if feed_breaker:
                # dedup'd retirements never reach here with
                # feed_breaker: a cache/coalesce failure says nothing
                # about engine health (satellite-2 contract)
                self._admission.breaker.record_failure(
                    type(error).__name__)
        self._slo.record(t.tenant, ok=error is None, latency_s=wall)
        _events.emit("retire", tenant=t.tenant, rid=t.rid,
                     state=t.state, wall_s=round(wall, 6),
                     error=type(error).__name__ if error else None)
        if self._journal is not None:
            try:
                self._journal.done(rid=t.rid, key=idem_key,
                                   state=t.state)
            except OSError:  # pragma: no cover - journal best-effort
                pass  # a full disk must not wedge retirement
            except FailedPrecondition as e:
                # journal FENCED mid-flight: a router declared this
                # engine dead and is replaying its journal on a peer.
                # The retirement still completes locally (the client
                # holding this ticket gets its answer) but the done
                # line must NOT race the replay — log loudly instead.
                from cylon_tpu.utils.logging import get_logger

                get_logger().error(
                    "request %d retired but its journal is fenced "
                    "(%s); a fleet router has failed this engine over",
                    t.rid, e)
        telemetry.timer("serve.request_seconds",
                        tenant=t.tenant).observe(wall)
        with _trace.trace_context(t.trace_id, t.parent_span):
            _trace.instant(
                "serve.done" if error is None else "serve.error",
                cat="serve", tenant=t.tenant, rid=t.rid, wall=wall,
                error=type(error).__name__ if error else None)
        for tid in pins:
            try:
                catalog.unpin(tid, holder=holder)
            except Exception:  # pragma: no cover - unpin best-effort
                pass
        if release_slot:
            self._admission.release()
        if set_event:
            t._event.set()

    #: submit()'s control keywords — everything else in a
    #: submit_named(**kwargs) belongs to the query function itself
    #: (and therefore to its registered fallback's signature too)
    _CONTROL_KW = frozenset({
        "tenant", "priority", "slo", "tables", "fault_plan",
        "idempotency_key", "fallback", "predicted_bytes",
        # propagated fleet trace context (gateway → submit_named →
        # submit): underscore-prefixed so no query kwarg can collide,
        # excluded here so the fingerprint stays trace-independent
        "_trace_id", "_parent_span"})

    def submit_named(self, name: str, *args,
                     idempotency_key: "str | None" = None,
                     **kwargs) -> QueryTicket:
        """Submit a query registered via :meth:`register_query` — the
        durable submission surface: the journal records the NAME plus
        JSON-able args, so :meth:`recover` can re-run the request in a
        fresh process. Accepts every :meth:`submit` keyword
        (tenant/priority/slo/tables/fault_plan/fallback/
        predicted_bytes); when the registry carries a fallback for
        ``name`` and the caller passes none, it is armed with this
        request's query arguments — so a journal REPLAY keeps the
        degrade path its original submit had."""
        entry = self._queries.get(str(name))
        if entry is None:
            raise InvalidArgument(
                f"no query registered under {name!r}; "
                f"register_query() it first (known: "
                f"{sorted(self._queries)})")
        fn, reg_fb, reg_tables = entry
        qkw = {k: v for k, v in kwargs.items()
               if k not in self._CONTROL_KW}
        # "fallback" ABSENT arms the registry's; an explicit
        # fallback=None is a per-request opt-out of degradation
        if reg_fb is not None and "fallback" not in kwargs:
            kwargs["fallback"] = functools.partial(reg_fb, *args, **qkw)
        # the dedup identity: the stable fingerprint over name + query
        # args (None for non-JSON-able args — no stable identity, no
        # dedup) plus the read set the version vector is computed over
        read = set(reg_tables) | {str(t) for t in kwargs.get("tables",
                                                             ())}
        fp = (plan.query_fingerprint(name, args, qkw)
              if read else None)
        return self.submit(fn, *args, idempotency_key=idempotency_key,
                           _journal_name=str(name), _fingerprint=fp,
                           _read_tables=tuple(sorted(read)), **kwargs)

    def _journal_admit(self, ticket: QueryTicket,
                       name: "str | None", args, kwargs,
                       key: "str | None", slo_raw, tables) -> None:
        """No-op unless durable (see :class:`RequestJournal`).
        ``slo_raw`` is the caller's pre-normalization slo argument, so
        an explicit 0 ("unbounded") survives a replay as 0."""
        if self._journal is None:
            return
        self._journal.admit(
            rid=ticket.rid, key=key, name=name, args=args,
            kwargs=kwargs, tenant=ticket.tenant,
            priority=ticket.priority, slo=slo_raw,
            tables=list(tables), trace_id=ticket.trace_id)

    def _dispatch(self, op: "_QueryOp", ticket: QueryTicket) -> None:
        """Hand one admitted (and, if durable, journaled) request to
        the scheduler. The ONLY place ops enter the execution set —
        the bench guard pins that statically, so no future submission
        path can skip the write-ahead journal."""
        with self._cond:
            if self._closed:  # lost a race with close(): undo and refuse
                self._undo_admission(op)
                raise InvalidArgument("engine is closed")
            # reset the scheduler-age clock at admission: after an
            # idle gap _last_sweep is stale by construction (the loop
            # was parked in cond.wait), and /health polled before the
            # first post-idle sweep must not read that as a stall
            self._last_sweep = time.monotonic()
            if (self._coalesce_on()
                    and getattr(op, "_fp", None) is not None
                    and getattr(op, "_vv", None) is not None):
                # open the coalesce window: identical (fingerprint,
                # version-vector) submissions attach to this op as
                # followers until it retires. setdefault — an already
                # open leader for the key keeps the window
                self._coalesce.setdefault((op._fp, op._vv), op)
            if self._policy.schedule == "priority":
                self._exec.add_op(op, ticket.priority)
            else:
                self._exec.add_op(op)
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, name="cylon-serve-scheduler",
                    daemon=True)
                self._thread.start()
            self._cond.notify_all()

    # ------------------------------------------------- scheduler loop
    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._exec.ops and not self._closed:
                    self._cond.wait()
                if self._closed and not self._exec.ops:
                    return
                self._last_sweep = time.monotonic()  # awake, sweeping
            # one fair-share / weighted sweep over every live query:
            # each op advances one step (or `priority` steps), so
            # requests interleave at step granularity
            self._exec.progress()
            self._last_sweep = time.monotonic()
            with self._cond:
                for op in [o for o in self._exec.ops if o.done()]:
                    self._exec.remove_op(op)

    def _retire(self, op: _QueryOp, value=None,
                error: "BaseException | None" = None) -> None:
        """Finish one request: record outcome + latency, release pins
        and the admission slot, wake waiters; then settle the op's
        coalesced followers and (on success) publish the result into
        the versioned cache. Runs on the scheduler thread (once per
        request — ops retire exactly once)."""
        t = op.ticket
        if getattr(t, "_retired", False):
            # a request that retired successfully can still raise on
            # scope exit (a deadline verdict from watched_section);
            # the first retirement's outcome stands
            return
        fp = getattr(op, "_fp", None)
        vv = getattr(op, "_vv", None)
        followers: "list[dict]" = []
        if fp is not None and vv is not None:
            with self._cond:
                # close the coalesce window FIRST: a submit racing
                # this retirement must become a fresh leader (or a
                # cache hit), never attach to an op that will no
                # longer sweep
                op._coalesce_closed = True
                if self._coalesce.get((fp, vv)) is op:
                    self._coalesce.pop((fp, vv), None)
                followers = list(getattr(op, "_followers", ()))
                op._followers = []
        if error is None and getattr(op, "_degraded", False):
            # the degrade COMPLETED: this is the moment the
            # request earns degraded=true and the tenant counter
            t.degraded = True
            telemetry.counter("serve.degraded", tenant=t.tenant).inc()
        # executed retirements feed the breaker and release the slot
        # they took at admission (re-queued followers took none); the
        # waiter event is set by _QueryOp.progress() after the step
        # scopes unwind (see there) — not here, which runs inside them
        self._finish_ticket(
            t, value=value, error=error,
            idem_key=getattr(op, "_idem_key", None), pins=op._pins,
            holder=getattr(op, "_holder", None),
            release_slot=getattr(op, "_admitted", True),
            feed_breaker=True, set_event=False)
        if error is None:
            ck = None
            if fp is not None and vv is not None \
                    and self._result_cache.enabled:
                # store-at-retirement staleness guard: only publish
                # if the read set is STILL at the admitted versions —
                # an append that landed mid-flight makes this result
                # answer data that no longer exists
                cur = version_vector([tid for tid, _g, _d in vv])
                if cur == vv:
                    self._result_cache.store(fp, vv, value)
                    ck = {"fingerprint": fp,
                          "versions": [list(v) for v in vv]}
                    t.cache_key = ck
            for rec in followers:
                if ck is not None:
                    # the router learns (fp, vv) from whichever
                    # ticket it polled — followers advertise the
                    # SAME publishable key as their leader
                    rec["ticket"].cache_key = ck
                self._fanout_follower(rec, value)
            if followers:
                # one leader execution just answered N+1 tickets —
                # the micro-batch itself, journaled (satellite 1)
                _events.emit(
                    "batch_retire", tenant=t.tenant, rid=t.rid,
                    followers=len(followers),
                    wall_s=round(t.finished - t.submitted, 6))
            self._record_profile_history(op, t)
        else:
            # leader failed: followers with SLO budget left re-run as
            # their own ops; the rest fail cleanly (never silently)
            for rec in followers:
                rem = rec["ticket"].remaining()
                if rem is None or rem > 0:
                    self._requeue_follower(rec)
                else:
                    t2 = rec["ticket"]
                    telemetry.counter("serve.expired",
                                      tenant=t2.tenant).inc()
                    self._finish_ticket(
                        t2, error=error, idem_key=rec["key"],
                        pins=rec["pins"], holder=rec["holder"])

    def _record_profile_history(self, op: "_QueryOp",
                                t: QueryTicket) -> None:
        """Persist one executed retirement into the measured cost
        history: (fingerprint, pow2 row bucket) -> execution wall.
        Runs on the scheduler thread after the request completed (the
        row read is a host-side scalar fetch, never racing the mesh).
        Unfingerprinted or non-durable: no-op."""
        fp = getattr(op, "_fp", None)
        hist = self._profile_history
        if hist is None or fp is None:
            return
        bucket = None
        try:
            # the SAME derivation explain() uses for its lookup key:
            # pow2 bucket of the largest input table's true rows
            from cylon_tpu.parallel.dist_ops import batched_true_rows
            from cylon_tpu.plan import _result_tables
            from cylon_tpu.utils import pow2_bucket

            tbls = _result_tables((list(op._args),
                                   dict(op._kwargs)))
            if tbls:
                bucket = pow2_bucket(max(batched_true_rows(tbls)))
        except Exception:  # pragma: no cover - bucket best-effort
            bucket = None
        started = t.started if t.started is not None else t.submitted
        wall = max((t.finished or started) - started, 0.0)
        hist.record(fp, bucket, wall, path="executed",
                    degraded=t.degraded)

    def explain_named(self, name: str, *args, **kwargs) -> dict:
        """EXPLAIN a registered query with this engine's measured
        profile history attached: the :func:`explain` plan plus
        ``cost_estimate.predicted_wall_s`` — the median wall previous
        executions of the same (fingerprint, row bucket) actually
        took (None until the history has samples, or on a
        non-durable engine)."""
        entry = self._queries.get(str(name))
        if entry is None:
            raise InvalidArgument(
                f"no query registered under {name!r}; "
                f"register_query() it first (known: "
                f"{sorted(self._queries)})")
        fn, _fb, reg_tables = entry
        qkw = {k: v for k, v in kwargs.items()
               if k not in self._CONTROL_KW}
        read = set(reg_tables) | {str(t) for t in
                                  kwargs.get("tables", ())}
        fp = (plan.query_fingerprint(name, args, qkw)
              if read else None)
        return _profile.explain(fn, *args,
                                _history=self._profile_history,
                                _fingerprint=fp, **qkw)

    @property
    def profile_history(self) -> "_profile.ProfileHistory | None":
        """The engine's measured cost history (None when not
        durable) — :func:`cylon_tpu.telemetry.profile.merged_history`
        folds every fleet member's into one estimator."""
        return self._profile_history

    # ------------------------------------------------------- reporting
    @property
    def live(self) -> int:
        """Live (queued + running) request count."""
        return self._admission.live

    @property
    def closing(self) -> bool:
        """True once :meth:`close` has committed to shutting down
        (``_closed`` published — admission refused, drain under way or
        done): the public flag the introspection endpoints turn into a
        clean 503 ``{"status": "closing"}`` instead of racing the
        teardown (ISSUE 15 satellite)."""
        return self._closed

    @property
    def http_address(self) -> "tuple[str, int] | None":
        """(host, port) of the introspection endpoint, or None when
        ``CYLON_TPU_SERVE_HTTP_PORT`` is unarmed."""
        return None if self._http is None else self._http.address

    def ticket(self, rid: int) -> "QueryTicket | None":
        """Look up a recent (live or retired) request by rid — the
        ``/profiles/<rid>`` surface. None once evicted from the
        bounded history (``CYLON_TPU_SERVE_RECENT_ENTRIES``)."""
        with self._cond:
            return self._recent.get(int(rid))

    def queries(self) -> "list[dict]":
        """In-flight request inventory (the ``/queries`` payload):
        rid, tenant, state, priority, elapsed, queue wait, remaining
        SLO budget and step count per live request."""
        with self._cond:
            ops = list(self._exec.ops)
        now = time.monotonic()
        out = []
        for op in ops:
            t = op.ticket
            out.append({
                "rid": t.rid,
                "tenant": t.tenant,
                "state": t.state,
                "priority": t.priority,
                "elapsed_s": now - t.submitted,
                "queue_wait_s": (t.started or now) - t.submitted,
                "remaining_slo_s": t.remaining(),
                "steps": op._step,
            })
        return out

    @property
    def uptime_s(self) -> float:
        return time.monotonic() - self._started

    def last_step_age(self) -> "float | None":
        """Seconds since the scheduler last showed liveness (admission,
        loop wake, op step, or sweep completion) — None before any.
        With live requests pending, a large age means the scheduler is
        wedged inside one step, the signal ``/health`` turns into a
        ``scheduler_stalled`` verdict."""
        last = self._last_sweep
        return None if last is None else time.monotonic() - last

    def slo_report(self) -> dict:
        """Fresh per-tenant burn rates plus the worst offender —
        ``{"enabled", "objective", "latency_s", "tenants":
        {tenant: {"60s": burn, ...}}, "worst": {...} | None}``.
        Windows key by the ``serve.slo_burn`` gauge's window label."""
        from cylon_tpu.serve.slo import _wlabel

        rates = {
            t: {_wlabel(w): (round(b, 4) if b is not None else None)
                for w, b in burns.items()}
            for t, burns in self._slo.burn_rates().items()}
        worst = self._slo.worst()
        return {
            "enabled": self._slo.enabled,
            "objective": self._slo.objective,
            "latency_s": self._slo.latency_s,
            "tenants": rates,
            "worst": (None if worst is None else {
                "tenant": worst[0], "window": _wlabel(worst[1]),
                "burn": round(worst[2], 4)}),
        }

    def health(self) -> dict:
        """The router-grade composite verdict (``/health``):
        ``{"status": ok|degraded|unhealthy, "score", "reasons": [...],
        "components": {...}}`` — see
        :func:`cylon_tpu.serve.introspect.health_verdict`."""
        return introspect.health_verdict(self)

    def tenant_stats(self) -> "dict[str, dict]":
        """Per-tenant serving report: requests/completed/errors/
        rejected/expired counts plus p50/p99/max request latency from
        the ``serve.request_seconds{tenant=}`` histogram quantiles."""
        out: dict = {}

        def _count(metric_name):
            for _, labels, inst in telemetry.instruments(metric_name):
                ten = labels.get("tenant")
                if ten is None:
                    continue
                d = out.setdefault(ten, {})
                key = metric_name.split(".", 1)[1]
                d[key] = d.get(key, 0) + inst.value

        for m in ("serve.requests", "serve.completed", "serve.errors",
                  "serve.rejected", "serve.expired"):
            _count(m)
        for _, labels, inst in telemetry.instruments(
                "serve.request_seconds"):
            ten = labels.get("tenant")
            if ten is None or not inst.count:
                continue
            d = out.setdefault(ten, {})
            d.update(p50_s=inst.quantile(0.5),
                     p99_s=inst.quantile(0.99),
                     mean_s=inst.sum / inst.count,
                     max_s=inst.max)
        return out

    def plan_cache_stats(self) -> dict:
        """Hit/miss/eviction totals of the shared compiled-plan cache
        (:func:`cylon_tpu.plan.plan_cache_stats`)."""
        return plan.plan_cache_stats()

    # -------------------------------------------------------- recovery
    @classmethod
    def recover(cls, durable_dir: str, env=None,
                policy: "ServePolicy | None" = None,
                queries: "dict | None" = None,
                replay: bool = True,
                snapshot_dir: "str | None" = None) -> "ServeEngine":
        """Rebuild a killed durable engine from ``durable_dir``.

        1. **Mesh**: ``env=None`` starts a fresh resident
           :class:`~cylon_tpu.context.CylonEnv` in this process (the
           old one died with the old process).
        2. **Resident tables**: every
           :class:`~cylon_tpu.serve.durability.CatalogSnapshot` table
           restores into the process catalog (and re-registers in the
           new engine's snapshot, so the recovered engine is itself
           recoverable).
        3. **Requests**: journaled-but-incomplete NAMED requests re-run
           via :meth:`submit_named` with their original idempotency
           keys — exactly once (``serve.journal_replayed`` counts
           them); incomplete requests the journal cannot name (bare
           callables, non-JSON args) are reported, not silently lost.

        ``queries`` maps names to query functions (the registry does
        not survive the process — code is re-supplied, state is
        restored). The report lands on ``engine.recovery_report``::

            {"replayed": {key_or_rid: QueryTicket}, "restored_tables":
             [...], "unreplayable": [journal entries]}

        Counts one ``serve.recoveries``.
        """
        from cylon_tpu.serve.durability import RequestJournal

        if env is None:
            import cylon_tpu as ct

            env = ct.CylonEnv(ct.TPUConfig())
        engine = cls(env, policy, durable_dir=durable_dir,
                     snapshot_dir=snapshot_dir)
        for name, fn in (queries or {}).items():
            # a (fn, fallback) pair re-registers the degrade path too,
            # so replayed requests keep their graceful degradation
            if isinstance(fn, tuple):
                engine.register_query(name, *fn)
            else:
                engine.register_query(name, fn)
        telemetry.counter("serve.recoveries").inc()
        _trace.instant("serve.recover", cat="serve", dir=durable_dir)
        restored = engine._snapshot.restore()
        gens = engine._snapshot.generations()
        for tid, table in restored.items():
            catalog.put_table(tid, table)
            # reinstate the generation the snapshot was taken at: a
            # recovered engine must serve post-append content under the
            # post-append generation, not restart the counter at 1 and
            # alias every version-keyed memo (ISSUE 18 fix)
            if tid in gens:
                catalog.restore_version(tid, gens[tid])
        replayable, unreplayable = RequestJournal.incomplete(durable_dir)
        tickets: dict = {}
        if replay:
            for e in list(replayable):
                if e["name"] not in engine._queries:
                    # journaled under a name this recovery cannot
                    # resolve: report it lost, don't die mid-recovery
                    unreplayable.append(e)
                    continue
                tickets[e.get("key") or e["rid"]] = engine.submit_named(
                    e["name"], *e.get("args", ()),
                    idempotency_key=e.get("key"),
                    tenant=e.get("tenant", "default"),
                    priority=e.get("priority", 1),
                    slo=e.get("slo"), tables=e.get("tables", ()),
                    **e.get("kwargs", {}))
                # retire the ORIGINAL journal entry of a KEYLESS
                # request: the replay's own admit line (just written,
                # ahead of its dispatch) now carries it — without this
                # the entry reads incomplete forever and re-executes on
                # EVERY subsequent recovery. Keyed entries must NOT get
                # this line (a done'd key would hide the replay if THIS
                # process is killed mid-replay); their exactly-once
                # comes from first-admit-per-key dedup instead.
                if e.get("key") is None:
                    engine._journal.done(rid=e["rid"], key=None,
                                         state="replayed")
                telemetry.counter("serve.journal_replayed",
                                  tenant=e.get("tenant",
                                               "default")).inc()
        for e in unreplayable:
            telemetry.counter("serve.journal_unreplayable",
                              tenant=e.get("tenant", "default")).inc()
        engine.recovery_report = {
            "replayed": tickets,
            "restored_tables": sorted(restored),
            "unreplayable": unreplayable,
        }
        return engine

    # -------------------------------------------------------- lifecycle
    def close(self, wait: bool = True,
              timeout: "float | None" = None) -> None:
        """Stop admitting; optionally drain live requests. With
        ``wait=False`` a close under live requests raises
        :class:`~cylon_tpu.errors.FailedPrecondition` (the engine never
        silently abandons admitted work)."""
        with self._cond:
            live = len(self._exec.ops)
            if live and not wait:
                # decide the refusal BEFORE publishing _closed, so a
                # concurrent submit never sees a closed engine that
                # then stays open
                raise FailedPrecondition(
                    f"close(wait=False) with {live} live request(s); "
                    "drain or pass wait=True")
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout)
        if self._profile_history is not None:
            self._profile_history.save()
        if self._journal is not None:
            self._journal.close()
        if self._http is not None:
            self._http.close()
            self._http = None

    def __enter__(self) -> "ServeEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close(wait=True)
