"""The always-on query engine: one resident mesh, many tenants.

Everything below :mod:`cylon_tpu.serve` is one-script-one-query; this
module is the front door the ROADMAP's "millions of users" item calls
for — a long-lived :class:`ServeEngine` that admits concurrent queries
against shared resident tables and drives them to completion over ONE
resident :class:`~cylon_tpu.context.CylonEnv`.

Design — an assembly of the subsystems the previous PRs built:

* **Admission** (:mod:`cylon_tpu.serve.admission`): a queue-depth cap
  rejects over-cap submits with a fast
  :class:`~cylon_tpu.errors.ResourceExhausted`; every admitted request
  is stamped with an absolute SLO deadline (queue wait counts — the
  client-visible contract).

* **Scheduling**: each admitted request becomes a :class:`_QueryOp` —
  an :class:`cylon_tpu.ops_graph.op.Op` whose ``progress()`` advances
  the query one *step* — and ONE long-lived
  :class:`~cylon_tpu.ops_graph.execution.RoundRobinExecution` (fair
  share, the default) or
  :class:`~cylon_tpu.ops_graph.execution.PriorityExecution` (tenant
  weights) sweeps the live set exactly the way the reference's
  parallel-op engine progresses concurrent op streams
  (``ops/execution/execution.hpp``). Query functions may be plain
  callables (one step) or **generator functions** (each ``yield`` is a
  step boundary) — a staged query yields after its dispatch phase, so
  while its XLA work is in flight on the mesh the scheduler is already
  driving the next request's host-side phase: host→device transfer and
  device compute interleave *across requests*.

* **Per-request SLO** (:mod:`cylon_tpu.watchdog`): every step runs
  under ``watchdog.deadline(remaining)`` inside a named
  ``serve_request`` :func:`~cylon_tpu.watchdog.watched_section`, so a
  wedged step dumps stacks and the request fails with
  :class:`~cylon_tpu.errors.DeadlineExceeded` instead of stalling the
  schedule; expired requests are refused *before* their next step runs.

* **Shared compiled-plan cache** (:func:`cylon_tpu.plan.shared_compiled`):
  submit compiled queries (e.g. ``tpch.compiled("q3")``) and N clients
  with the same query shape pay ONE trace — later calls are
  ``plan.cache_hits``.

* **Per-tenant observability**: every step executes under
  :func:`cylon_tpu.telemetry.tenant_scope`, so span timers, watchdog
  sections, fault/retry counters and flight-recorder events all carry
  the tenant label; request latency lands in
  ``serve.request_seconds{tenant=}`` whose
  :meth:`~cylon_tpu.telemetry.Histogram.quantile` supplies per-tenant
  p50/p99 (:meth:`ServeEngine.tenant_stats`).

* **Fault isolation**: a per-request
  :class:`~cylon_tpu.resilience.FaultPlan` is installed only around
  that request's steps (the scheduler runs steps one at a time, so the
  scope can never leak into another tenant's step), and resident-table
  pins (:func:`cylon_tpu.catalog.pin`) keep a concurrent ``drop`` from
  yanking a table out from under an in-flight query.
"""

import contextlib
import itertools
import threading
import time

from cylon_tpu import catalog, plan, resilience, telemetry, watchdog
from cylon_tpu.errors import (DeadlineExceeded, FailedPrecondition,
                              InvalidArgument)
from cylon_tpu.ops_graph.execution import (PriorityExecution,
                                           RoundRobinExecution)
from cylon_tpu.ops_graph.op import Op
from cylon_tpu.serve.admission import AdmissionController, ServePolicy
from cylon_tpu.telemetry import trace as _trace
from cylon_tpu.utils import tracing

__all__ = ["QueryTicket", "ServeEngine"]

#: request lifecycle states (QueryTicket.state)
QUEUED, RUNNING, DONE, FAILED = "queued", "running", "done", "failed"


class QueryTicket:
    """Handle for one admitted request (the client's future)."""

    def __init__(self, rid: int, tenant: str, priority: int,
                 slo: "float | None"):
        self.rid = rid
        self.tenant = tenant
        self.priority = priority
        self.slo = slo
        self.submitted = time.monotonic()
        #: absolute SLO expiry (monotonic); queue wait counts against
        #: the budget — the latency the CLIENT sees is the contract
        self.deadline_at = (None if slo is None
                            else self.submitted + float(slo))
        self.started: "float | None" = None
        self.finished: "float | None" = None
        self.state = QUEUED
        self.value = None
        self.error: "BaseException | None" = None
        self._event = threading.Event()

    def remaining(self) -> "float | None":
        """Seconds of SLO budget left (None = unbounded)."""
        if self.deadline_at is None:
            return None
        return self.deadline_at - time.monotonic()

    @property
    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: "float | None" = None) -> bool:
        return self._event.wait(timeout)

    def result(self, timeout: "float | None" = None):
        """Block for the result; re-raise the request's failure."""
        if not self._event.wait(timeout):
            raise DeadlineExceeded(
                f"result({timeout=}) timed out waiting on request "
                f"{self.rid} (tenant {self.tenant!r}, state "
                f"{self.state})", section="serve_request")
        if self.error is not None:
            raise self.error
        return self.value

    def __repr__(self):
        return (f"QueryTicket(rid={self.rid}, tenant={self.tenant!r}, "
                f"state={self.state})")


class _QueryOp(Op):
    """One admitted request as a schedulable op node.

    ``progress()`` advances the query by one step: for a generator
    function each ``yield`` delimits a step (``StopIteration.value`` is
    the result); a plain callable is a single step. Steps run under the
    request's tenant scope + remaining-SLO deadline + per-request fault
    plan + the ``serve_request`` watchdog section — all scoped to the
    step, so nothing leaks into the next op the schedule sweeps."""

    def __init__(self, op_id: int, engine: "ServeEngine",
                 ticket: QueryTicket, fn, args, kwargs,
                 fault_plan, pins: "list[str]"):
        super().__init__(op_id, name=f"QueryOp[{ticket.tenant}]")
        self._engine = engine
        self.ticket = ticket
        self._fn = fn
        self._args = args
        self._kwargs = kwargs
        self._fault_plan = fault_plan
        self._pins = pins
        self._gen = None
        self._step = 0

    def done(self) -> bool:
        return self.ticket.done

    def progress(self) -> bool:  # one scheduled step
        t = self.ticket
        if t.done:
            return False
        try:
            rem = t.remaining()
            if rem is not None and rem <= 0:
                telemetry.counter("serve.expired", tenant=t.tenant).inc()
                raise DeadlineExceeded(
                    f"request {t.rid} (tenant {t.tenant!r}) missed its "
                    f"{t.slo:.3f}s SLO after {self._step} step(s)",
                    section="serve_request",
                    elapsed=time.monotonic() - t.submitted)
            self._run_step(rem)
        except BaseException as e:  # noqa: BLE001 - isolate per request
            self._engine._retire(self, error=e)
        return True

    def _run_step(self, rem: "float | None") -> None:
        t = self.ticket
        if t.started is None:
            t.started = time.monotonic()
            t.state = RUNNING
            telemetry.timer("serve.queue_wait_seconds",
                            tenant=t.tenant).observe(
                                t.started - t.submitted)
        with contextlib.ExitStack() as stack:
            # ORDER matters: the tenant scope first (so every nested
            # metric/trace/section carries the label), then the SLO
            # budget, then the request's fault plan — scoped to this
            # step only, which is the whole isolation argument
            stack.enter_context(telemetry.tenant_scope(t.tenant))
            if rem is not None:
                stack.enter_context(watchdog.deadline(
                    rem, label=f"serve:{t.rid}"))
            if self._fault_plan is not None:
                # context-LOCAL install (contextvar, not the process
                # global): a noisy tenant's plan is invisible to any
                # other thread reaching an injection point, and it
                # propagates into watchdog workers via copy_context
                stack.enter_context(resilience.scoped(self._fault_plan))
            stack.enter_context(tracing.span(
                "serve.step", cat="serve", rid=t.rid, step=self._step))
            stack.enter_context(watchdog.watched_section(
                "serve_request", detail=f"{t.tenant}/{t.rid}"
                f"#{self._step}"))
            self._step += 1
            if self._gen is None:
                first = self._fn(*self._args, **self._kwargs)
                if hasattr(first, "__next__"):  # generator query
                    self._gen = first
                else:  # plain callable: one step, done
                    self._engine._retire(self, value=first)
                    return
            try:
                next(self._gen)
            except StopIteration as fin:
                self._engine._retire(self, value=fin.value)


class ServeEngine:
    """The long-lived multi-tenant query service (module docstring).

    One engine per process/mesh is the intended shape; the env is
    resident for the engine's lifetime. Thread-safe: many client
    threads submit; ONE scheduler thread executes steps (the same
    single-threaded progress model the reference's parallel-op engine
    runs between MPI calls — concurrency comes from interleaving steps
    and from XLA's async dispatch, not from racing host threads into
    the mesh)."""

    _ids = itertools.count(1)

    def __init__(self, env=None, policy: "ServePolicy | None" = None):
        self._env = env
        self._admission = AdmissionController(policy)
        self._policy = self._admission.policy
        if self._policy.schedule == "priority":
            self._exec = PriorityExecution()
        else:
            self._exec = RoundRobinExecution()
        self._cond = threading.Condition()
        self._thread: "threading.Thread | None" = None
        self._closed = False
        self._op_ids = itertools.count(1)

    # ------------------------------------------------- resident tables
    @property
    def env(self):
        return self._env

    def register_table(self, table_id: str, table) -> None:
        """Register a resident table (Table or DataFrame) in the
        process catalog under ``table_id`` — the shared store every
        request reads through (pin-protected; see
        :func:`cylon_tpu.catalog.drop`)."""
        t = getattr(table, "table", table)
        catalog.put_table(table_id, t)

    def drop_table(self, table_id: str) -> None:
        """Pin-respecting drop: raises
        :class:`~cylon_tpu.errors.FailedPrecondition` naming the
        holders while any session/request still pins the table."""
        catalog.drop(table_id, if_exists=False)

    def table_stats(self) -> dict:
        """Per-table rows/bytes/pins of the resident catalog."""
        return catalog.stats()

    def session(self, tenant: str, priority: int = 1, tables=()):
        """Open a :class:`cylon_tpu.serve.session.Session` bound to
        this engine (pins ``tables`` for the session's lifetime)."""
        from cylon_tpu.serve.session import Session

        return Session(self, tenant, priority=priority, tables=tables)

    # ------------------------------------------------------ submission
    def submit(self, fn, *args, tenant: str = "default",
               priority: int = 1, slo: "float | None" = None,
               tables=(), fault_plan=None, **kwargs) -> QueryTicket:
        """Admit one query for scheduled execution.

        ``fn(*args, **kwargs)`` runs on the scheduler thread — a plain
        callable is one step; a generator function advances one step
        per schedule sweep (its ``return`` value is the result).
        ``slo=None`` takes the engine default
        (``CYLON_TPU_SERVE_SLO``); ``slo <= 0`` explicitly unbounds the
        request. ``tables`` are catalog ids pinned for the request's
        lifetime. ``fault_plan`` (tests/chaos drills) is installed only
        around this request's steps. Raises
        :class:`~cylon_tpu.errors.ResourceExhausted` immediately when
        the live-request cap is hit."""
        if self._closed:
            raise InvalidArgument("engine is closed")
        if slo is None:
            slo = self._policy.default_slo
        elif slo <= 0:
            slo = None
        self._admission.admit(tenant)  # may raise ResourceExhausted
        ticket = QueryTicket(next(self._ids), str(tenant),
                             int(priority), slo)
        holder = f"{tenant}/req{ticket.rid}"
        pinned: list[str] = []
        try:
            for tid in tables:
                catalog.pin(tid, holder=holder)
                pinned.append(tid)
        except Exception:
            for tid in pinned:
                catalog.unpin(tid, holder=holder)
            self._admission.release()
            raise
        op = _QueryOp(next(self._op_ids), self, ticket, fn, args,
                      kwargs, fault_plan, pinned)
        op._holder = holder
        telemetry.counter("serve.requests", tenant=ticket.tenant).inc()
        _trace.instant("serve.admit", cat="serve", tenant=ticket.tenant,
                       rid=ticket.rid, slo=slo)
        with self._cond:
            if self._closed:  # lost a race with close(): undo and refuse
                for tid in pinned:
                    catalog.unpin(tid, holder=holder)
                self._admission.release()
                raise InvalidArgument("engine is closed")
            if self._policy.schedule == "priority":
                self._exec.add_op(op, ticket.priority)
            else:
                self._exec.add_op(op)
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, name="cylon-serve-scheduler",
                    daemon=True)
                self._thread.start()
            self._cond.notify_all()
        return ticket

    # ------------------------------------------------- scheduler loop
    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._exec.ops and not self._closed:
                    self._cond.wait()
                if self._closed and not self._exec.ops:
                    return
            # one fair-share / weighted sweep over every live query:
            # each op advances one step (or `priority` steps), so
            # requests interleave at step granularity
            self._exec.progress()
            with self._cond:
                for op in [o for o in self._exec.ops if o.done()]:
                    self._exec.remove_op(op)

    def _retire(self, op: _QueryOp, value=None,
                error: "BaseException | None" = None) -> None:
        """Finish one request: record outcome + latency, release pins
        and the admission slot, wake waiters. Runs on the scheduler
        thread (once per request — ops retire exactly once)."""
        t = op.ticket
        if t.done:  # pragma: no cover - retire races are scheduler bugs
            return
        t.finished = time.monotonic()
        wall = t.finished - t.submitted
        if error is None:
            t.state, t.value = DONE, value
            telemetry.counter("serve.completed", tenant=t.tenant).inc()
        else:
            t.state, t.error = FAILED, error
            telemetry.counter("serve.errors", tenant=t.tenant,
                              kind=type(error).__name__).inc()
        telemetry.timer("serve.request_seconds",
                        tenant=t.tenant).observe(wall)
        _trace.instant("serve.done" if error is None else "serve.error",
                       cat="serve", tenant=t.tenant, rid=t.rid,
                       wall=wall,
                       error=type(error).__name__ if error else None)
        holder = getattr(op, "_holder", None)
        for tid in op._pins:
            try:
                catalog.unpin(tid, holder=holder)
            except Exception:  # pragma: no cover - unpin best-effort
                pass
        self._admission.release()
        t._event.set()

    # ------------------------------------------------------- reporting
    @property
    def live(self) -> int:
        """Live (queued + running) request count."""
        return self._admission.live

    def tenant_stats(self) -> "dict[str, dict]":
        """Per-tenant serving report: requests/completed/errors/
        rejected/expired counts plus p50/p99/max request latency from
        the ``serve.request_seconds{tenant=}`` histogram quantiles."""
        out: dict = {}

        def _count(metric_name):
            for _, labels, inst in telemetry.instruments(metric_name):
                ten = labels.get("tenant")
                if ten is None:
                    continue
                d = out.setdefault(ten, {})
                key = metric_name.split(".", 1)[1]
                d[key] = d.get(key, 0) + inst.value

        for m in ("serve.requests", "serve.completed", "serve.errors",
                  "serve.rejected", "serve.expired"):
            _count(m)
        for _, labels, inst in telemetry.instruments(
                "serve.request_seconds"):
            ten = labels.get("tenant")
            if ten is None or not inst.count:
                continue
            d = out.setdefault(ten, {})
            d.update(p50_s=inst.quantile(0.5),
                     p99_s=inst.quantile(0.99),
                     mean_s=inst.sum / inst.count,
                     max_s=inst.max)
        return out

    def plan_cache_stats(self) -> dict:
        """Hit/miss/eviction totals of the shared compiled-plan cache
        (:func:`cylon_tpu.plan.plan_cache_stats`)."""
        return plan.plan_cache_stats()

    # -------------------------------------------------------- lifecycle
    def close(self, wait: bool = True,
              timeout: "float | None" = None) -> None:
        """Stop admitting; optionally drain live requests. With
        ``wait=False`` a close under live requests raises
        :class:`~cylon_tpu.errors.FailedPrecondition` (the engine never
        silently abandons admitted work)."""
        with self._cond:
            live = len(self._exec.ops)
            if live and not wait:
                # decide the refusal BEFORE publishing _closed, so a
                # concurrent submit never sees a closed engine that
                # then stays open
                raise FailedPrecondition(
                    f"close(wait=False) with {live} live request(s); "
                    "drain or pass wait=True")
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout)

    def __enter__(self) -> "ServeEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close(wait=True)
