"""serve_bench: N concurrent clients replaying a mixed TPC-H workload
against one :class:`~cylon_tpu.serve.ServeEngine`.

The serving acceptance harness (ROADMAP item 4): ``--clients N``
(default 8) client threads, each its own tenant/session, fire a mixed
TPC-H query stream (default mix q1/q3/q5/q6 — groupby-heavy, 3-way
join, 6-way join, scalar aggregate) at a shared engine holding the
TPC-H tables RESIDENT on one mesh. Every result is compared against a
single-query oracle (the same query run once, alone, before serving
starts), so the run proves correctness under concurrency, not just
liveness. One JSON record lands on stdout with the schema pinned by
:data:`REQUIRED_SERVE_FIELDS` (and ``tests/test_bench_guard.py``):
p50/p99 request latency from the ``serve.request_seconds`` histogram
quantiles, throughput (qps), plan-cache hit rate (the shared
compiled-plan cache means N clients with one query shape pay one
trace), and rejected/expired/error counts.

Run (CPU-host mesh, the same 8-virtual-device topology tier-1 uses)::

    python -m cylon_tpu.serve.bench --clients 8

Knobs: ``--requests`` per client (default 2), ``--sf`` scale factor
(default 0.002), ``--schedule roundrobin|priority``, ``--slo`` seconds
(default unbounded), ``--max-queue``, ``--seed``, plus the
``CYLON_TPU_SERVE_*`` env family (``docs/serving.md``).
"""

import argparse
import json
import os
import sys
import threading
import time

# CPU-host mesh by default (like tests/conftest.py): harmless on a real
# TPU backend — the flag only shapes the *host* platform's device count
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")

import numpy as np

#: serve-record fields the serving trajectory depends on — emit asserts
#: them and ``tests/test_bench_guard.py`` pins the set, so a refactor
#: cannot silently drop the latency quantiles or the cache-hit column.
REQUIRED_SERVE_FIELDS = frozenset({
    "metric", "clients", "requests_total", "tenants", "schedule",
    "p50_s", "p99_s", "qps", "cache_hit_rate", "rejected", "errors",
    "expired", "oracle_mismatches", "shed", "journal_replayed",
    "recoveries", "degraded",
    # attribution columns (ISSUE 9): every serve artifact carries the
    # slowest request's ANALYZE profile and the run's HBM high-water
    # mark, not just p50/p99
    "slowest_profile", "peak_live_bytes",
})

#: default mixed workload: groupby-heavy scan, 3-way join + top-k,
#: 6-way join, and a scalar aggregate — four distinct shapes so the
#: schedule interleaves genuinely different pipelines
DEFAULT_MIX = ("q1", "q3", "q5", "q6")


def _emit_record(line: dict):
    """The ONE stdout sink for serve bench records: attaches the
    telemetry ``metrics`` block like every other bench driver (schema
    lint in tests/test_bench_guard.py). Telemetry must never fail a
    bench."""
    line = dict(line)
    try:
        from cylon_tpu import telemetry

        line["metrics"] = telemetry.bench_metrics()
    except Exception as e:  # pragma: no cover - import-time breakage
        line["metrics"] = {"telemetry_error": f"{type(e).__name__}: {e}"}
    print(json.dumps(line))


def _materialize(out):
    """Host-side result of a query call: DataFrames/Tables gather to
    pandas, scalars to float — the client-visible payload."""
    if hasattr(out, "to_pandas"):
        return out.to_pandas().reset_index(drop=True)
    arr = np.asarray(out)
    if arr.ndim == 0:
        return float(arr)
    return arr


def _results_match(got, want) -> bool:
    """Order-insensitive equality between a served result and its
    single-query oracle (float columns to 1e-9 rtol)."""
    import pandas as pd

    if isinstance(want, float):
        return bool(np.isclose(float(got), want, rtol=1e-9))
    if not isinstance(want, pd.DataFrame):
        return bool(np.allclose(np.asarray(got), np.asarray(want)))
    if list(got.columns) != list(want.columns) or len(got) != len(want):
        return False
    keys = [c for c in want.columns
            if not np.issubdtype(want[c].dtype, np.floating)]
    g = got.sort_values(keys or list(got.columns)).reset_index(drop=True)
    w = want.sort_values(keys or list(want.columns)).reset_index(drop=True)
    for c in want.columns:
        if np.issubdtype(want[c].dtype, np.floating):
            if not np.allclose(g[c].to_numpy(), w[c].to_numpy(),
                               rtol=1e-9):
                return False
        elif list(g[c]) != list(w[c]):
            return False
    return True


def _staged_query(cq, resident, env):
    """A two-step generator query for the scheduler: step 1 runs the
    compiled program (dispatch + overflow check), step 2 materialises
    the result to the host — so while one request's result fetch (or
    XLA in-flight work) drains, the schedule is already dispatching the
    next tenant's step."""

    def run():
        out = cq(resident, env=env)
        yield  # step boundary: result fetch happens on the next sweep
        return _materialize(out)

    return run


def _mk_resident(env, data):
    """Lay the TPC-H tables out on the mesh ONCE and register them in
    the catalog (``tpch/<name>``) — the shared resident store every
    request reads; returns the {name: DataFrame} mapping queries take."""
    from cylon_tpu import tpch
    from cylon_tpu.frame import DataFrame
    from cylon_tpu.parallel import scatter_table

    resident = {}
    for name, df in tpch.ingest(data).items():
        if env is not None and env.is_distributed:
            df = DataFrame._wrap(scatter_table(env, df.table))
        resident[name] = df
    return resident


def run_bench(clients: int = 8, requests: int = 2, sf: float = 0.002,
              schedule: str = "roundrobin", slo: "float | None" = None,
              max_queue: "int | None" = None, seed: int = 0,
              mix=DEFAULT_MIX) -> dict:
    import cylon_tpu as ct
    from cylon_tpu import catalog, telemetry, tpch, watchdog
    from cylon_tpu.errors import ResourceExhausted
    from cylon_tpu.serve import ServeEngine, ServePolicy
    from cylon_tpu.serve.admission import default_policy
    from cylon_tpu.tpch import dbgen

    env = ct.CylonEnv(ct.TPUConfig())
    data = dbgen.generate(sf, seed)
    resident = _mk_resident(env, data)
    for name, df in resident.items():
        catalog.put_table(f"tpch/{name}", df.table)

    base = default_policy()
    policy = ServePolicy(
        max_queue=max_queue if max_queue is not None else base.max_queue,
        default_slo=slo if slo and slo > 0 else base.default_slo,
        schedule=schedule)

    # single-query oracles: each mix query runs ONCE, alone, through
    # the same shared compiled plan — every concurrent result must
    # reproduce these exactly (and the serving run then hits the warm
    # cross-request plan cache, which is the point of sharing it)
    compiled = {q: tpch.compiled(q) for q in mix}
    oracles = {q: _materialize(compiled[q](resident, env=env))
               for q in mix}

    engine = ServeEngine(env, policy)
    mismatches = []
    rejected_local = [0]
    all_tickets = []  # (query, ticket) across every client thread
    lock = threading.Lock()

    def client(i: int):
        # under the priority schedule, odd clients are weight-2
        # tenants — they take two steps per sweep to the others' one
        prio = 2 if (schedule == "priority" and i % 2) else 1
        tenant = f"tenant{i}"
        with engine.session(tenant, priority=prio,
                            tables=[f"tpch/{n}" for n in resident]) as s:
            tickets = []
            for r in range(requests):
                q = mix[(i + r) % len(mix)]
                try:
                    tk = s.submit(_staged_query(compiled[q],
                                                resident, env))
                    tickets.append((q, tk))
                    with lock:
                        all_tickets.append((q, tk))
                except ResourceExhausted:
                    with lock:
                        rejected_local[0] += 1
            for q, tk in tickets:
                try:
                    got = tk.result()
                except Exception as e:
                    with lock:
                        mismatches.append((tenant, q,
                                           f"{type(e).__name__}: {e}"))
                    continue
                if not _results_match(got, oracles[q]):
                    with lock:
                        mismatches.append((tenant, q, "result mismatch"))

    # the whole replay runs inside the named serve_request watchdog
    # section: a hung engine dumps stacks + raises under an ambient
    # deadline instead of wedging the driver silently
    t0 = time.perf_counter()
    with watchdog.watched_section("serve_request",
                                  detail="serve_bench replay"):
        threads = [threading.Thread(target=client, args=(i,),
                                    name=f"serve-client-{i}")
                   for i in range(clients)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
    wall = time.perf_counter() - t0
    http_addr = engine.http_address  # captured before close unbinds
    engine.close(wait=True)

    hist = telemetry.merge_histograms(
        [inst for _, _, inst in
         telemetry.instruments("serve.request_seconds")])
    completed = telemetry.total("serve.completed")
    cache = engine.plan_cache_stats()
    record = {
        "metric": "serve_bench_tpch_mix",
        "clients": clients,
        "requests_total": clients * requests,
        "tenants": len(engine.tenant_stats()),
        "schedule": schedule,
        "sf": sf,
        "wall_s": round(wall, 3),
        "qps": round(completed / wall, 3) if wall > 0 else None,
        "p50_s": (round(hist.quantile(0.5), 4)
                  if hist is not None and hist.count else None),
        "p99_s": (round(hist.quantile(0.99), 4)
                  if hist is not None and hist.count else None),
        "completed": completed,
        "rejected": telemetry.total("serve.rejected"),
        "errors": telemetry.total("serve.errors"),
        "expired": telemetry.total("serve.expired"),
        # robustness columns (ISSUE 8): load shed by the admission
        # layer (queue_full / breaker), journal replays and recoveries
        # — 0 on a healthy fault-free replay, pinned so a chaos run's
        # sheds/replays ride the trajectory
        "shed": telemetry.total("serve.shed"),
        "journal_replayed": telemetry.total("serve.journal_replayed"),
        "recoveries": telemetry.total("serve.recoveries"),
        # graceful degradation (ISSUE 10): requests that completed
        # through the OOM→spill fallback — 0 on a healthy replay,
        # pinned so degraded completions ride the trajectory
        "degraded": telemetry.total("serve.degraded"),
        "cache_hit_rate": round(cache["hit_rate"], 4),
        "cache_hits": cache["hits"],
        "cache_misses": cache["misses"],
        "oracle_mismatches": len(mismatches),
        "mismatch_detail": mismatches[:8],
        "resident_tables": len(resident),
    }
    # attribution (ISSUE 9): the slowest completed request's ANALYZE
    # profile rides the artifact — a p99 regression in the trajectory
    # names its stages, operators and bytes instead of being a bare
    # number — plus the run's HBM high-water mark
    slowest = None
    for q, tk in all_tickets:
        if tk.finished is None or tk.state != "done":
            continue
        w = tk.finished - tk.submitted
        if slowest is None or w > slowest[0]:
            slowest = (w, q, tk)
    prof = slowest[2].profile() if slowest is not None else None
    if prof is not None:
        prof["query"] = slowest[1]
    record["slowest_profile"] = prof
    record["peak_live_bytes"] = telemetry.memory.peak_live_bytes()
    if http_addr is not None:
        record["http_url"] = "http://%s:%d" % http_addr
    return record


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--clients", type=int, default=8)
    p.add_argument("--requests", type=int, default=2,
                   help="queries per client")
    p.add_argument("--sf", type=float, default=0.002)
    p.add_argument("--schedule", default="roundrobin",
                   choices=("roundrobin", "priority"))
    p.add_argument("--slo", type=float, default=0.0,
                   help="per-request SLO seconds (0 = unbounded)")
    p.add_argument("--max-queue", type=int, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--mix", default=",".join(DEFAULT_MIX),
                   help="comma-separated TPC-H query names")
    args = p.parse_args(argv)

    record = run_bench(
        clients=args.clients, requests=args.requests, sf=args.sf,
        schedule=args.schedule, slo=args.slo,
        max_queue=args.max_queue, seed=args.seed,
        mix=tuple(q.strip() for q in args.mix.split(",") if q.strip()))
    missing = REQUIRED_SERVE_FIELDS - record.keys()
    assert not missing, f"serve record dropped fields {missing}"
    _emit_record(record)
    # a replay that corrupted results or failed requests is a FAILED
    # bench, not a slow one
    return 1 if (record["oracle_mismatches"] or record["errors"]) else 0


if __name__ == "__main__":
    sys.exit(main())
