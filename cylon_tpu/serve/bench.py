"""serve_bench: N concurrent clients replaying a mixed TPC-H workload
against one :class:`~cylon_tpu.serve.ServeEngine`.

The serving acceptance harness (ROADMAP item 4): ``--clients N``
(default 8) client threads, each its own tenant/session, fire a mixed
TPC-H query stream (default mix q1/q3/q5/q6 — groupby-heavy, 3-way
join, 6-way join, scalar aggregate) at a shared engine holding the
TPC-H tables RESIDENT on one mesh. Every result is compared against a
single-query oracle (the same query run once, alone, before serving
starts), so the run proves correctness under concurrency, not just
liveness. One JSON record lands on stdout with the schema pinned by
:data:`REQUIRED_SERVE_FIELDS` (and ``tests/test_bench_guard.py``):
p50/p99 request latency from the ``serve.request_seconds`` histogram
quantiles, throughput (qps), plan-cache hit rate (the shared
compiled-plan cache means N clients with one query shape pay one
trace), and rejected/expired/error counts.

Run (CPU-host mesh, the same 8-virtual-device topology tier-1 uses)::

    python -m cylon_tpu.serve.bench --clients 8

Knobs: ``--requests`` per client (default 2), ``--sf`` scale factor
(default 0.002), ``--schedule roundrobin|priority``, ``--slo`` seconds
(default unbounded), ``--max-queue``, ``--seed``, plus the
``CYLON_TPU_SERVE_*`` env family (``docs/serving.md``).

``--refresh`` runs the incremental-view leg instead (ISSUE 18,
``docs/views.md``): RF1-style append rounds (``--appends``,
``--delta-sf``) interleaved with concurrent ``read_view`` readers
against registered q1/q3/q5/q6 materialized views — every read audited
post-hoc against a pinned-generation oracle — emitting one record
pinned by :data:`REQUIRED_REFRESH_FIELDS` (incremental refresh wall vs
full-recompute wall, gated ``speedup >= 2``, ``oracle_mismatches``
gated 0).
"""

import argparse
import json
import os
import sys
import threading
import time

# CPU-host mesh by default (like tests/conftest.py): harmless on a real
# TPU backend — the flag only shapes the *host* platform's device count
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")

import numpy as np

#: serve-record fields the serving trajectory depends on — emit asserts
#: them and ``tests/test_bench_guard.py`` pins the set, so a refactor
#: cannot silently drop the latency quantiles or the cache-hit column.
REQUIRED_SERVE_FIELDS = frozenset({
    "metric", "clients", "requests_total", "tenants", "schedule",
    "p50_s", "p99_s", "qps", "cache_hit_rate", "rejected", "errors",
    "expired", "oracle_mismatches", "shed", "journal_replayed",
    "recoveries", "degraded",
    # attribution columns (ISSUE 9): every serve artifact carries the
    # slowest request's ANALYZE profile and the run's HBM high-water
    # mark, not just p50/p99
    "slowest_profile", "peak_live_bytes",
    # windowed-observability columns (ISSUE 14): the sliding-window
    # p99 (from the metric-history ring — within one pow2 bucket of
    # the exact per-request quantile, which rides as p99_exact_s) and
    # the worst SLO burn rate any tenant reached (0 when burn
    # accounting is unarmed)
    "windowed_p99_s", "slo_burn",
    # dedup-layer columns (ISSUE 19): the versioned result cache and
    # micro-batched dispatch counters — 0 on the bare-callable replay
    # (no fingerprints), live on the --hot-mix leg — pinned so a
    # refactor cannot silently drop the dedup plane's accounting
    "result_cache_hits", "result_cache_misses",
    "result_cache_invalidations", "coalesced",
})

#: hot-mix-record fields (ISSUE 19): the ``--hot-mix`` acceptance is
#: only auditable if every record pins the measured hot-path QPS
#: against the single-engine uncached baseline (their ratio is the
#: ``qps_multiplier`` the acceptance gates at >= 10x), the hot-phase
#: cache hit rate, the dedup counters, and the staleness audit (an
#: append between submissions MUST force a re-execution — 0 stale
#: results). ``tests/test_bench_guard.py`` pins the set; main()
#: asserts it before emitting.
REQUIRED_HOTMIX_FIELDS = frozenset({
    "metric", "engines", "clients", "requests_total", "completed",
    "baseline_qps", "hot_qps", "qps_multiplier", "p50_s", "p99_s",
    "cache_hit_rate", "shed", "coalesced", "result_cache_hits",
    "result_cache_misses", "result_cache_invalidations",
    "oracle_mismatches", "stale_results", "errors",
})

#: fleet-record fields (ISSUE 15): the ``--fleet`` acceptance is only
#: auditable if every record pins the engine count, the failover and
#: replay counters, the lost-ack and double-execution audits (both
#: MUST be 0) and the p99 before/during/after the mid-run kill.
#: ``tests/test_bench_guard.py`` pins the set; main() asserts it
#: before emitting.
REQUIRED_FLEET_FIELDS = frozenset({
    "metric", "engines", "clients", "requests_total", "completed",
    "failovers", "replayed", "lost_acks", "routed", "deduped",
    "retry_deduped", "double_executions", "oracle_mismatches",
    "errors", "p99_before_s", "p99_during_s", "p99_after_s",
})

#: extra fields a ``--fleet-trace`` record must carry (ISSUE 20): the
#: stitched-timeline artifact is only auditable if the record pins
#: where the Chrome trace landed, how many spans and engine tracks it
#: stitched, the clock-handshake jitter bound the alignment rests on,
#: and the failover replay hops the headline trace id crossed.
#: ``tests/test_bench_guard.py`` pins the set; main() asserts it.
REQUIRED_FLEET_TRACE_FIELDS = frozenset({
    "trace_path", "spans", "engines_stitched", "offset_jitter_s",
    "replay_hops",
})

#: refresh-record fields (ISSUE 18): the ``--refresh`` acceptance is
#: only auditable if every record pins the incremental-refresh wall
#: against the from-scratch recompute wall (their ratio is the
#: ``speedup`` the acceptance gates at >= 2x), the generation lag the
#: concurrent readers observed, and the oracle audit (MUST be 0
#: mismatches). ``tests/test_bench_guard.py`` pins the set; main()
#: asserts it before emitting.
REQUIRED_REFRESH_FIELDS = frozenset({
    "metric", "sf", "delta_sf", "views", "appends", "refreshes",
    "delta_rows_total", "refresh_wall_s", "recompute_wall_s",
    "speedup", "generation_lag", "oracle_mismatches", "reads_total",
    "errors",
})

#: default mixed workload: groupby-heavy scan, 3-way join + top-k,
#: 6-way join, a scalar aggregate, and a two-phase global aggregate
#: (q14's promo ratio needs a global merge scalar — its spill path is
#: the ISSUE 16 two-phase plan) — five distinct shapes so the schedule
#: interleaves genuinely different pipelines
DEFAULT_MIX = ("q1", "q3", "q5", "q6", "q14")

#: the ``--refresh`` workload (ISSUE 18): the four mix shapes whose
#: fallback merge is directly view-maintainable — groupby+wmean (q1),
#: concat+resort top-k (q3), associative groupby (q5), scalar sum
#: (q6). Two-phase views keep a phase-1 partial as state and need a
#: partial-returning query fn — they ride tests/test_views.py, not
#: this leg.
REFRESH_MIX = ("q1", "q3", "q5", "q6")


def _emit_record(line: dict):
    """The ONE stdout sink for serve bench records: attaches the
    telemetry ``metrics`` block like every other bench driver (schema
    lint in tests/test_bench_guard.py). Telemetry must never fail a
    bench."""
    line = dict(line)
    try:
        from cylon_tpu import telemetry

        line["metrics"] = telemetry.bench_metrics()
    except Exception as e:  # pragma: no cover - import-time breakage
        line["metrics"] = {"telemetry_error": f"{type(e).__name__}: {e}"}
    print(json.dumps(line))


def _materialize(out):
    """Host-side result of a query call: DataFrames/Tables gather to
    pandas, scalars to float — the client-visible payload."""
    if hasattr(out, "to_pandas"):
        return out.to_pandas().reset_index(drop=True)
    arr = np.asarray(out)
    if arr.ndim == 0:
        return float(arr)
    return arr


def _results_match(got, want) -> bool:
    """Order-insensitive equality between a served result and its
    single-query oracle (float columns to 1e-9 rtol)."""
    import pandas as pd

    if isinstance(want, float):
        return bool(np.isclose(float(got), want, rtol=1e-9))
    if not isinstance(want, pd.DataFrame):
        return bool(np.allclose(np.asarray(got), np.asarray(want)))
    if list(got.columns) != list(want.columns) or len(got) != len(want):
        return False
    keys = [c for c in want.columns
            if not np.issubdtype(want[c].dtype, np.floating)]
    g = got.sort_values(keys or list(got.columns)).reset_index(drop=True)
    w = want.sort_values(keys or list(want.columns)).reset_index(drop=True)
    for c in want.columns:
        if np.issubdtype(want[c].dtype, np.floating):
            if not np.allclose(g[c].to_numpy(), w[c].to_numpy(),
                               rtol=1e-9):
                return False
        elif list(g[c]) != list(w[c]):
            return False
    return True


def _staged_query(cq, resident, env):
    """A two-step generator query for the scheduler: step 1 runs the
    compiled program (dispatch + overflow check), step 2 materialises
    the result to the host — so while one request's result fetch (or
    XLA in-flight work) drains, the schedule is already dispatching the
    next tenant's step."""

    def run():
        out = cq(resident, env=env)
        yield  # step boundary: result fetch happens on the next sweep
        return _materialize(out)

    return run


def _mk_resident(env, data):
    """Lay the TPC-H tables out on the mesh ONCE and register them in
    the catalog (``tpch/<name>``) — the shared resident store every
    request reads; returns the {name: DataFrame} mapping queries take."""
    from cylon_tpu import tpch
    from cylon_tpu.frame import DataFrame
    from cylon_tpu.parallel import scatter_table

    resident = {}
    for name, df in tpch.ingest(data).items():
        if env is not None and env.is_distributed:
            df = DataFrame._wrap(scatter_table(env, df.table))
        resident[name] = df
    return resident


def _fault_storm(engine, http_addr, requests: int = 8,
                 tenant: str = "storm") -> dict:
    """The ISSUE 14 measured acceptance: drive ONE tenant into a
    deadline storm against a live engine and watch the observability
    plane tell the story — ``/health`` flips ok → unhealthy (reasons
    naming the breaker and the burning tenant's SLO), sheds and
    breaker transitions land in ``/events`` in order, and after the
    cooldown + the storm window aging out, ``/health`` recovers to ok.

    Polls the verdict over HTTP when the introspection endpoint is
    armed (the router's view), falling back to ``engine.health()``."""
    import urllib.request

    from cylon_tpu import telemetry
    from cylon_tpu.telemetry import events as _events

    def verdict():
        if http_addr is not None:
            url = "http://%s:%d/health" % http_addr
            with urllib.request.urlopen(url, timeout=10) as r:
                return json.loads(r.read())
        return engine.health()

    cursor = _events.since(0)["cursor"]
    transitions = [verdict()["status"]]
    unhealthy_reasons = None
    peak_burn = 0.0

    def note(v):
        nonlocal unhealthy_reasons, peak_burn
        if v["status"] != transitions[-1]:
            transitions.append(v["status"])
        if v["status"] == "unhealthy" and unhealthy_reasons is None:
            unhealthy_reasons = list(v["reasons"])
        worst = (v.get("components", {}).get("slo") or {}).get("worst")
        if worst and worst["burn"] > peak_burn:
            peak_burn = worst["burn"]

    def slow():
        time.sleep(0.3)
        return None

    t0 = time.perf_counter()
    tickets = []
    for _ in range(int(requests)):
        try:
            tickets.append(engine.submit(slow, tenant=tenant,
                                         slo=0.02))
        except Exception:
            pass  # breaker may already be shedding: that IS the storm
        note(verdict())
    for tk in tickets:
        try:
            tk.result(30)
        except Exception:
            pass
        note(verdict())
    # keep poking the front door while open so sheds land in /events
    shed_probe_errors = 0
    deadline = time.monotonic() + 60
    recovered = False
    while time.monotonic() < deadline:
        v = verdict()
        note(v)
        if v["status"] == "ok" and "unhealthy" in transitions:
            recovered = True
            break
        try:
            # good traffic probes the half-open breaker and re-earns
            # the SLO budget once the storm ages out of the window
            engine.submit(lambda: 1, tenant=tenant,
                          slo=30.0).result(30)
        except Exception:
            shed_probe_errors += 1
        time.sleep(0.25)
    replay = _events.since(cursor)
    kinds = [e["kind"] for e in replay["events"]]
    seqs = [e["seq"] for e in replay["events"]]
    return {
        "tenant": tenant,
        "requests": int(requests),
        "wall_s": round(time.perf_counter() - t0, 3),
        "health_transitions": transitions,
        "unhealthy_reasons": unhealthy_reasons,
        "recovered": recovered,
        "peak_burn": round(peak_burn, 4),
        # recovery probes the open/half-open breaker refused — how
        # hard the front door pushed back during the recovery loop
        "recovery_probes_shed": shed_probe_errors,
        "storm_errors": telemetry.total("serve.errors"),
        "storm_shed": telemetry.total("serve.shed"),
        "breaker_trips": telemetry.total("serve.breaker_trips"),
        "events_replayed": len(kinds),
        "event_kinds": sorted(set(kinds)),
        "events_in_order": seqs == sorted(seqs),
        "events_dropped": replay["dropped"],
    }


def run_bench(clients: int = 8, requests: int = 2, sf: float = 0.002,
              schedule: str = "roundrobin", slo: "float | None" = None,
              max_queue: "int | None" = None, seed: int = 0,
              mix=DEFAULT_MIX, slo_target: "float | None" = None,
              slo_latency: "float | None" = None,
              slo_windows: "tuple | None" = None,
              storm: int = 0) -> dict:
    import cylon_tpu as ct
    from cylon_tpu import catalog, telemetry, tpch, watchdog
    from cylon_tpu.errors import ResourceExhausted
    from cylon_tpu.serve import ServeEngine, ServePolicy
    from cylon_tpu.serve.admission import default_policy
    from cylon_tpu.telemetry import timeseries
    from cylon_tpu.tpch import dbgen

    env = ct.CylonEnv(ct.TPUConfig())
    data = dbgen.generate(sf, seed)
    resident = _mk_resident(env, data)
    for name, df in resident.items():
        catalog.put_table(f"tpch/{name}", df.table)

    base = default_policy()
    if storm and slo_target is None and base.slo_target is None:
        # the fault-storm acceptance needs burn accounting armed and
        # windows short enough to watch /health recover inside one
        # bench run
        slo_target = 0.99
        slo_windows = slo_windows or (10.0, 30.0)
    policy = ServePolicy(
        max_queue=max_queue if max_queue is not None else base.max_queue,
        default_slo=slo if slo and slo > 0 else base.default_slo,
        schedule=schedule,
        breaker_fails=base.breaker_fails,
        breaker_window=base.breaker_window,
        breaker_cooldown=base.breaker_cooldown,
        slo_target=(slo_target if slo_target is not None
                    else base.slo_target),
        slo_latency=(slo_latency if slo_latency is not None
                     else base.slo_latency),
        slo_windows=tuple(slo_windows or base.slo_windows),
        burn_critical=base.burn_critical)
    # baseline sample for the windowed-p99 column: the whole replay
    # lands in one history delta slot
    timeseries.sample(force=True)

    # single-query oracles: each mix query runs ONCE, alone, through
    # the same shared compiled plan — every concurrent result must
    # reproduce these exactly (and the serving run then hits the warm
    # cross-request plan cache, which is the point of sharing it)
    compiled = {q: tpch.compiled(q) for q in mix}
    oracles = {q: _materialize(compiled[q](resident, env=env))
               for q in mix}

    engine = ServeEngine(env, policy)
    mismatches = []
    rejected_local = [0]
    all_tickets = []  # (query, ticket) across every client thread
    lock = threading.Lock()

    def client(i: int):
        # under the priority schedule, odd clients are weight-2
        # tenants — they take two steps per sweep to the others' one
        prio = 2 if (schedule == "priority" and i % 2) else 1
        tenant = f"tenant{i}"
        with engine.session(tenant, priority=prio,
                            tables=[f"tpch/{n}" for n in resident]) as s:
            tickets = []
            for r in range(requests):
                q = mix[(i + r) % len(mix)]
                try:
                    tk = s.submit(_staged_query(compiled[q],
                                                resident, env))
                    tickets.append((q, tk))
                    with lock:
                        all_tickets.append((q, tk))
                except ResourceExhausted:
                    with lock:
                        rejected_local[0] += 1
            for q, tk in tickets:
                try:
                    got = tk.result()
                except Exception as e:
                    with lock:
                        mismatches.append((tenant, q,
                                           f"{type(e).__name__}: {e}"))
                    continue
                if not _results_match(got, oracles[q]):
                    with lock:
                        mismatches.append((tenant, q, "result mismatch"))

    # the whole replay runs inside the named serve_request watchdog
    # section: a hung engine dumps stacks + raises under an ambient
    # deadline instead of wedging the driver silently
    t0 = time.perf_counter()
    with watchdog.watched_section("serve_request",
                                  detail="serve_bench replay"):
        threads = [threading.Thread(target=client, args=(i,),
                                    name=f"serve-client-{i}")
                   for i in range(clients)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
    wall = time.perf_counter() - t0
    http_addr = engine.http_address  # captured before close unbinds
    # close the replay's windowed slot + read the healthy-phase gate
    # counters BEFORE any storm phase muddies them (storm errors are
    # INTENDED; they ride the storm block, not the pass/fail columns)
    timeseries.sample(force=True)
    windowed_p99 = timeseries.history().quantile(
        "serve.request_seconds", 0.99)
    exact_walls = sorted(
        tk.finished - tk.submitted for _, tk in all_tickets
        if tk.finished is not None and tk.state == "done")
    p99_exact = (float(np.quantile(np.asarray(exact_walls), 0.99))
                 if exact_walls else None)
    healthy_errors = telemetry.total("serve.errors")
    healthy_shed = telemetry.total("serve.shed")
    healthy_rejected = telemetry.total("serve.rejected")
    healthy_expired = telemetry.total("serve.expired")
    # ... and the latency/throughput columns: the cumulative request
    # histogram, completed count and tenant set are REPLAY-ONLY too —
    # read after the storm they would absorb the storm's expired
    # walls + recovery probes and overstate qps against the
    # replay-only wall
    hist = telemetry.merge_histograms(
        [inst for _, _, inst in
         telemetry.instruments("serve.request_seconds")])
    completed = telemetry.total("serve.completed")
    n_tenants = len(engine.tenant_stats())

    storm_block = (_fault_storm(engine, http_addr, requests=storm)
                   if storm else None)
    worst = engine.slo_report().get("worst")
    engine.close(wait=True)

    cache = engine.plan_cache_stats()
    record = {
        "metric": "serve_bench_tpch_mix",
        "clients": clients,
        "requests_total": clients * requests,
        "tenants": n_tenants,
        "schedule": schedule,
        "sf": sf,
        "wall_s": round(wall, 3),
        "qps": round(completed / wall, 3) if wall > 0 else None,
        "p50_s": (round(hist.quantile(0.5), 4)
                  if hist is not None and hist.count else None),
        "p99_s": (round(hist.quantile(0.99), 4)
                  if hist is not None and hist.count else None),
        "completed": completed,
        "rejected": healthy_rejected,
        "errors": healthy_errors,
        "expired": healthy_expired,
        # robustness columns (ISSUE 8): load shed by the admission
        # layer (queue_full / breaker), journal replays and recoveries
        # — 0 on a healthy fault-free replay, pinned so a chaos run's
        # sheds/replays ride the trajectory
        "shed": healthy_shed,
        # windowed-observability columns (ISSUE 14): sliding-window
        # p99 from the metric-history ring (bucket resolution — the
        # exact client-side quantile rides as p99_exact_s for the
        # within-one-bucket pin) and the worst tenant burn rate
        "windowed_p99_s": (round(windowed_p99, 4)
                           if windowed_p99 is not None else None),
        "p99_exact_s": (round(p99_exact, 4)
                        if p99_exact is not None else None),
        # the worst burn any tenant REACHED during the run (a storm's
        # peak survives the recovery that the live read decays with)
        "slo_burn": max(
            worst["burn"] if worst is not None else 0.0,
            storm_block["peak_burn"] if storm_block else 0.0),
        "journal_replayed": telemetry.total("serve.journal_replayed"),
        "recoveries": telemetry.total("serve.recoveries"),
        # graceful degradation (ISSUE 10): requests that completed
        # through the OOM→spill fallback — 0 on a healthy replay,
        # pinned so degraded completions ride the trajectory
        "degraded": telemetry.total("serve.degraded"),
        "cache_hit_rate": round(cache["hit_rate"], 4),
        "cache_hits": cache["hits"],
        "cache_misses": cache["misses"],
        # dedup-layer counters (ISSUE 19): result-cache traffic and
        # coalesced fan-outs — structurally 0 here (this replay
        # submits bare callables, which have no fingerprint), live on
        # the --hot-mix leg; pinned so the columns always ride
        "result_cache_hits": int(
            telemetry.total("serve.result_cache_hits")),
        "result_cache_misses": int(
            telemetry.total("serve.result_cache_misses")),
        "result_cache_invalidations": int(
            telemetry.total("serve.result_cache_invalidations")),
        "coalesced": int(telemetry.total("serve.coalesced")),
        "oracle_mismatches": len(mismatches),
        "mismatch_detail": mismatches[:8],
        "resident_tables": len(resident),
    }
    # attribution (ISSUE 9): the slowest completed request's ANALYZE
    # profile rides the artifact — a p99 regression in the trajectory
    # names its stages, operators and bytes instead of being a bare
    # number — plus the run's HBM high-water mark
    slowest = None
    for q, tk in all_tickets:
        if tk.finished is None or tk.state != "done":
            continue
        w = tk.finished - tk.submitted
        if slowest is None or w > slowest[0]:
            slowest = (w, q, tk)
    prof = slowest[2].profile() if slowest is not None else None
    if prof is not None:
        prof["query"] = slowest[1]
    record["slowest_profile"] = prof
    record["peak_live_bytes"] = telemetry.memory.peak_live_bytes()
    if storm_block is not None:
        record["storm"] = storm_block
    if http_addr is not None:
        record["http_url"] = "http://%s:%d" % http_addr
    return record


def run_hotmix_bench(clients: int = 64, requests: int = 4,
                     sf: float = 0.002, seed: int = 0,
                     mix=DEFAULT_MIX, engines: int = 2) -> dict:
    """The ISSUE 19 measured acceptance: N concurrent clients replay a
    HOT mix (identical fingerprints, stable tables) through the
    FleetRouter twice — once against a single uncached engine
    (coalescing and both result caches disabled: every request
    executes), once against the full dedup plane (engine + router
    caches on, coalescing on, warmed) — and the record gates the
    hot-over-baseline QPS multiplier at >= 10x. A mid-probe append
    then proves the staleness contract: the very next submission of an
    affected query must MISS and re-execute (0 stale results). Every
    result, both phases, is oracle-checked."""
    import cylon_tpu as ct
    from cylon_tpu import catalog, telemetry, tpch
    from cylon_tpu.errors import ResourceExhausted
    from cylon_tpu.serve import ServeEngine
    from cylon_tpu.serve.fleet import (QUERY_READ_SETS, EngineUnavailable,
                                       FleetRouter, LocalEngineClient,
                                       _mk_fleet_query)
    from cylon_tpu.tpch import dbgen

    env = ct.CylonEnv(ct.TPUConfig())
    data = dbgen.generate(sf, seed)
    resident = _mk_resident(env, data)
    for name, df in resident.items():
        catalog.put_table(f"tpch/{name}", df.table)
    mix = tuple(mix)
    # oracles warm the shared compiled-plan cache for BOTH phases
    # equally — the multiplier measures the dedup plane, not compile
    # amortisation
    compiled = {q: tpch.compiled(q) for q in mix}
    oracles = {q: _materialize(compiled[q](resident, env=env))
               for q in mix}

    def mk_fleet(n_engines: int):
        engs, clis = [], []
        for i in range(n_engines):
            e = ServeEngine(env)
            for q in mix:
                reads = QUERY_READ_SETS.get(q, tuple(resident))
                e.register_query(
                    q, _mk_fleet_query(compiled[q], resident, env),
                    tables=[f"tpch/{nm}" for nm in reads
                            if nm in resident])
            engs.append(e)
            clis.append(LocalEngineClient(e, f"hot{i}"))
        return engs, FleetRouter(clis, poll_interval=0.25)

    def drive(router, n_requests: int, label: str) -> dict:
        mismatches: list = []
        errors: list = []
        shed = [0]
        walls: "list[float]" = []
        lock = threading.Lock()

        def client(i: int):
            tenant = f"tenant{i}"
            for r in range(n_requests):
                q = mix[(i + r) % len(mix)]
                s0 = time.monotonic()
                try:
                    got = router.submit(q, tenant=tenant).result(600)
                except Exception as e:
                    with lock:
                        if isinstance(e, (ResourceExhausted,
                                          EngineUnavailable)):
                            shed[0] += 1
                        errors.append(
                            (tenant, q, f"{type(e).__name__}: {e}"))
                    continue
                w = time.monotonic() - s0
                with lock:
                    walls.append(w)
                if not _results_match(got, oracles[q]):
                    with lock:
                        mismatches.append((tenant, q, label))

        t0 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(i,),
                                    name=f"hotmix-{label}-{i}")
                   for i in range(clients)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        wall = time.perf_counter() - t0
        ws = sorted(walls)
        return {
            "wall_s": wall, "completed": len(walls),
            "qps": (len(walls) / wall) if wall > 0 else None,
            "p50_s": (float(np.quantile(np.asarray(ws), 0.5))
                      if ws else None),
            "p99_s": (float(np.quantile(np.asarray(ws), 0.99))
                      if ws else None),
            "shed": shed[0], "mismatches": mismatches,
            "errors": errors,
        }

    knobs = {"CYLON_TPU_SERVE_RESULT_CACHE_BYTES": "0",
             "CYLON_TPU_SERVE_COALESCE": "0",
             "CYLON_TPU_FLEET_RESULT_CACHE_BYTES": "0"}
    saved = {k: os.environ.get(k) for k in knobs}

    # ---- phase 1: the single-engine uncached baseline (dedup plane
    # OFF end to end — every submission executes). Fewer requests per
    # client than the hot phase: QPS is a rate, and the baseline only
    # needs a stable one
    base_requests = max(1, requests // 2)
    os.environ.update(knobs)
    try:
        engs, router = mk_fleet(1)
        try:
            base = drive(router, base_requests, "baseline")
        finally:
            router.close()
            for e in engs:
                e.close(wait=True)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    # ---- phase 2: the full dedup plane (engine + router caches,
    # coalescing), warmed with one execution per mix query so the
    # measured window is the HOT path
    engs, router = mk_fleet(engines)
    try:
        for q in mix:
            got = router.submit(q, tenant="warmup").result(600)
            if not _results_match(got, oracles[q]):
                base["mismatches"].append(("warmup", q, "warmup"))
        hits0 = telemetry.total("fleet.result_cache_hits") + \
            telemetry.total("serve.result_cache_hits")
        hot = drive(router, requests, "hot")
        hits1 = telemetry.total("fleet.result_cache_hits") + \
            telemetry.total("serve.result_cache_hits")
        hit_rate = ((hits1 - hits0) / hot["completed"]
                    if hot["completed"] else 0.0)

        # ---- the staleness probe: append one row to lineitem, then
        # re-submit a lineitem query — the dedup plane must MISS
        # (invalidation reached both caches) and the re-execution must
        # still match the oracle. A hit here would be a STALE RESULT.
        probe_q = next((q for q in mix if "lineitem"
                        in QUERY_READ_SETS.get(q, ("lineitem",))),
                       mix[0])
        cols = catalog.get_table("tpch/lineitem").column_names
        row = {c: np.asarray(data["lineitem"][c][:1]) for c in cols}
        misses0 = telemetry.total("fleet.result_cache_misses") + \
            telemetry.total("serve.result_cache_misses")
        catalog.append("tpch/lineitem", row, env=env)
        stale = 0
        try:
            got = router.submit(probe_q, tenant="probe").result(600)
        except Exception as e:
            hot["errors"].append(("probe", probe_q,
                                  f"{type(e).__name__}: {e}"))
        else:
            misses1 = telemetry.total("fleet.result_cache_misses") + \
                telemetry.total("serve.result_cache_misses")
            # resident inputs are engine-side frames (the catalog
            # entry only versions them), so the re-run still matches
            # the oracle; what MUST have changed is the miss count
            if misses1 <= misses0:
                stale += 1
            if not _results_match(got, oracles[probe_q]):
                hot["mismatches"].append(("probe", probe_q, "probe"))
    finally:
        router.close()
        for e in engs:
            e.close(wait=True)

    mismatches = base["mismatches"] + hot["mismatches"]
    errors = base["errors"] + hot["errors"]
    record = {
        "metric": "serve_hotmix_fleet",
        "engines": engines,
        "clients": clients,
        "requests_total": clients * requests,
        "completed": hot["completed"],
        "sf": sf,
        "mix": list(mix),
        "baseline_requests_total": clients * base_requests,
        "baseline_completed": base["completed"],
        "baseline_wall_s": round(base["wall_s"], 3),
        "baseline_qps": (round(base["qps"], 3)
                         if base["qps"] else None),
        "baseline_p50_s": (round(base["p50_s"], 4)
                           if base["p50_s"] is not None else None),
        "baseline_p99_s": (round(base["p99_s"], 4)
                           if base["p99_s"] is not None else None),
        "wall_s": round(hot["wall_s"], 3),
        "hot_qps": round(hot["qps"], 3) if hot["qps"] else None,
        "qps_multiplier": (round(hot["qps"] / base["qps"], 2)
                           if hot["qps"] and base["qps"] else None),
        "p50_s": (round(hot["p50_s"], 4)
                  if hot["p50_s"] is not None else None),
        "p99_s": (round(hot["p99_s"], 4)
                  if hot["p99_s"] is not None else None),
        "cache_hit_rate": round(hit_rate, 4),
        "shed": base["shed"] + hot["shed"],
        "coalesced": int(telemetry.total("serve.coalesced")),
        "result_cache_hits": int(
            telemetry.total("fleet.result_cache_hits")
            + telemetry.total("serve.result_cache_hits")),
        "result_cache_misses": int(
            telemetry.total("fleet.result_cache_misses")
            + telemetry.total("serve.result_cache_misses")),
        "result_cache_invalidations": int(
            telemetry.total("fleet.result_cache_invalidations")
            + telemetry.total("serve.result_cache_invalidations")),
        "stale_results": stale,
        "oracle_mismatches": len(mismatches),
        "mismatch_detail": mismatches[:8],
        "errors": len(errors),
        "error_detail": errors[:8],
    }
    return record


def _refresh_keep(mix) -> dict:
    """Per-table column keep-sets for the refresh workload: the union
    of the mix's manifests plus the order keys the RF1 append stream
    offsets — SF1 stays host-feasible because unreferenced columns
    (the wide comment strings above all) never generate."""
    from cylon_tpu.tpch.manifest import MANIFEST

    keep: dict = {}
    for q in mix:
        for t, cols in MANIFEST[q].items():
            keep.setdefault(t, set()).update(cols)
    keep.setdefault("orders", set()).add("o_orderkey")
    keep.setdefault("lineitem", set()).add("l_orderkey")
    return {t: frozenset(c) for t, c in keep.items()}


def _mk_view_query(q):
    """The view query fn for one mix query: the engine's partitioned
    EAGER fallback over whatever tables it is handed — the same
    execution path for the delta run, the initial materialization and
    the from-scratch oracle, so the refresh-vs-recompute walls compare
    like with like. Small inputs (a delta) skip the partition split."""
    from cylon_tpu import fallback

    def qf(tables):
        data = {name: {c: df[c].to_numpy() for c in df.columns}
                for name, df in tables.items()}
        li = data.get("lineitem")
        rows = len(next(iter(li.values()))) if li else 0
        return fallback.tpch_fallback(
            q, data, compiled=False,
            n_partitions=1 if rows < 100_000 else None)

    return qf


def run_refresh_bench(sf: float = 0.05, delta_sf: "float | None" = None,
                      rounds: int = 2, clients: int = 4, seed: int = 0,
                      mix=REFRESH_MIX) -> dict:
    """The ISSUE 18 acceptance harness: TPC-H RF1-style appends (new
    orders arriving WITH their lineitems — join-closed by
    construction) interleaved with the q1/q3/q5/q6 mix served as
    incremental materialized views.

    Per round: one key-offset dbgen delta appends to the resident
    ``orders`` and ``lineitem`` tables (generation bumps), every view
    refreshes INCREMENTALLY (query over the delta + combiner merge,
    timed), and a from-scratch recompute at the same pinned
    generations runs as the oracle (timed — the denominator of
    ``speedup``). ``clients`` reader threads hammer
    ``engine.read_view`` throughout; every read's
    ``(generations, result)`` pair is verified post-hoc against the
    from-scratch oracle at exactly those generations — the
    generation-consistency proof (``oracle_mismatches`` MUST be 0).
    """
    import pandas as pd

    import cylon_tpu as ct
    from cylon_tpu import tpch, views, watchdog
    from cylon_tpu.fallback import _resolve_limit
    from cylon_tpu.serve import ServeEngine
    from cylon_tpu.tpch import dbgen
    from cylon_tpu.tpch.manifest import FALLBACK, MANIFEST

    if delta_sf is None:
        delta_sf = max(sf / 100.0, 1e-4)
    keep = _refresh_keep(mix)
    env = ct.CylonEnv(ct.TPUConfig())
    base = dbgen.generate(sf, seed, keep=keep)
    # resident tables stay LOCAL (host-backed Tables): each RF1 append
    # rebuilds the table host-side, and the eager fallback gathers to
    # host anyway — a per-round mesh re-scatter would only add noise
    # to the walls being compared
    resident = tpch.ingest(base)
    engine = ServeEngine(env)
    for name, df in resident.items():
        engine.register_table(f"tpch/{name}", df)

    query_fns = {q: _mk_view_query(q) for q in mix}
    limits = {}
    for q in mix:
        spec = FALLBACK[q]
        if spec["merge"] == "twophase":
            from cylon_tpu.errors import InvalidArgument

            raise InvalidArgument(
                f"--refresh mix cannot include two-phase query {q!r}: "
                "its view state is a phase-1 partial, which needs a "
                "partial-returning query fn (see tests/test_views.py);"
                f" maintainable here: {REFRESH_MIX}")
        limits[q] = _resolve_limit(getattr(tpch, q), spec, {})
        engine.register_view(
            f"view/{q}", query_fns[q], spec,
            sources={t: f"tpch/{t}" for t in MANIFEST[q]},
            delta_source="lineitem", limit=limits[q])

    # the bench-side delta history: content at ANY generation rebuilds
    # as base + deltas[:gen-1] — what the oracle recomputes from
    host_frames = {t: df.to_pandas() for t, df in resident.items()}
    delta_hist: "dict[str, list]" = {"orders": [], "lineitem": []}
    n_base_ord = int(len(host_frames["orders"]))

    def content_at(tname: str, gen: int):
        hist = delta_hist.get(tname, ())
        parts = [host_frames[tname]] + list(hist[:max(gen - 1, 0)])
        return (parts[0] if len(parts) == 1
                else pd.concat(parts, ignore_index=True))

    oracle_cache: dict = {}
    oracle_mu = threading.Lock()

    def oracle_for(q: str, gens: dict):
        """(result, wall_s, fresh) of the from-scratch recompute at
        exactly ``gens`` — cached per pinned-generation combo."""
        combo = tuple(sorted(gens.items()))
        with oracle_mu:
            hit = oracle_cache.get((q, combo))
        if hit is not None:
            return hit[0], hit[1], False
        # view generations are keyed by query ALIAS (== the TPC-H
        # table name here; the catalog id is tpch/<alias>)
        tabs = {a: content_at(a, g) for a, g in gens.items()}
        t0 = time.perf_counter()
        out = query_fns[q](tabs)
        wall = time.perf_counter() - t0
        res = views.present(out, FALLBACK[q], limits[q])
        with oracle_mu:
            oracle_cache[(q, combo)] = (res, wall)
        return res, wall, True

    refresh_walls = {q: 0.0 for q in mix}
    recompute_walls = {q: 0.0 for q in mix}
    mismatches: list = []
    errors: list = []
    samples: list = []  # (q, generations, result, lag)
    lock = threading.Lock()
    stop_readers = threading.Event()
    refreshes = [0]
    full_recomputes = [0]
    delta_rows_total = [0]

    def reader(i: int):
        while not stop_readers.is_set():
            for q in mix:
                try:
                    r = engine.read_view(f"view/{q}")
                except Exception as e:
                    with lock:
                        errors.append((f"read view/{q}",
                                       f"{type(e).__name__}: {e}"))
                    continue
                with lock:
                    samples.append((q, dict(r["generations"]),
                                    r["result"], int(r["lag"])))
            time.sleep(0.01)

    t0 = time.perf_counter()
    with watchdog.watched_section("serve_request",
                                  detail="refresh_bench"):
        threads = [threading.Thread(target=reader, args=(i,),
                                    name=f"refresh-reader-{i}")
                   for i in range(clients)]
        for th in threads:
            th.start()
        try:
            for r in range(rounds):
                d = dbgen.generate(delta_sf, seed + 1 + r, keep=keep)
                # RF1 key offset: this round's new orders (and their
                # lineitems) land in a key range disjoint from the
                # base AND every other round; dimension keys
                # (custkey/suppkey/partkey) stay inside the base
                # ranges because delta_sf < sf
                n_d = int(len(d["orders"]["o_orderkey"]))
                off = n_base_ord + r * n_d
                d["orders"]["o_orderkey"] = (
                    d["orders"]["o_orderkey"] + off)
                d["lineitem"]["l_orderkey"] = (
                    d["lineitem"]["l_orderkey"] + off)
                for t in ("orders", "lineitem"):
                    engine.append_table(f"tpch/{t}", d[t])
                    delta_hist[t].append(pd.DataFrame(
                        {c: np.asarray(v) for c, v in d[t].items()}))
                delta_rows_total[0] += int(
                    len(d["lineitem"]["l_orderkey"]))
                for q in mix:
                    out = engine.refresh_view(f"view/{q}")
                    refreshes[0] += 1
                    refresh_walls[q] += out["wall_s"]
                    if out["full_recompute"]:
                        full_recomputes[0] += 1
                    want, wall, fresh = oracle_for(
                        q, out["generations"])
                    if fresh:
                        recompute_walls[q] += wall
                    got = engine.read_view(f"view/{q}")
                    if (got["generations"] == out["generations"]
                            and not _results_match(got["result"],
                                                   want)):
                        mismatches.append(
                            (q, dict(out["generations"]),
                             "post-refresh mismatch"))
        finally:
            stop_readers.set()
            for th in threads:
                th.join()
    wall = time.perf_counter() - t0

    # post-hoc audit: EVERY concurrent read must equal the
    # from-scratch recompute at its pinned generations (distinct
    # combos are few — state only changes under refresh — so the
    # oracle cache absorbs the volume)
    lag_max = 0
    for q, gens, result, lag in samples:
        lag_max = max(lag_max, lag)
        want, _, _ = oracle_for(q, gens)
        if not _results_match(result, want):
            mismatches.append((q, gens, "concurrent read mismatch"))
    engine.close(wait=True)

    refresh_wall = sum(refresh_walls.values())
    recompute_wall = sum(recompute_walls.values())
    record = {
        "metric": "refresh_bench_tpch_rf1",
        "sf": sf,
        "delta_sf": delta_sf,
        "rounds": rounds,
        "clients": clients,
        "views": [f"view/{q}" for q in mix],
        # one RF1 round appends to BOTH orders and lineitem
        "appends": rounds * 2,
        "refreshes": refreshes[0],
        "full_recomputes": full_recomputes[0],
        "delta_rows_total": delta_rows_total[0],
        "refresh_wall_s": round(refresh_wall, 4),
        "recompute_wall_s": round(recompute_wall, 4),
        "speedup": (round(recompute_wall / refresh_wall, 2)
                    if refresh_wall > 0 else None),
        "per_view": {q: {
            "refresh_wall_s": round(refresh_walls[q], 4),
            "recompute_wall_s": round(recompute_walls[q], 4),
            "speedup": (round(recompute_walls[q] / refresh_walls[q], 2)
                        if refresh_walls[q] > 0 else None),
        } for q in mix},
        "generation_lag": lag_max,
        "reads_total": len(samples),
        "oracle_mismatches": len(mismatches),
        "mismatch_detail": mismatches[:8],
        "errors": len(errors),
        "error_detail": errors[:8],
        "wall_s": round(wall, 3),
        "view_stats": engine.view_stats(),
    }
    return record


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--clients", type=int, default=8)
    p.add_argument("--requests", type=int, default=2,
                   help="queries per client")
    p.add_argument("--sf", type=float, default=0.002)
    p.add_argument("--schedule", default="roundrobin",
                   choices=("roundrobin", "priority"))
    p.add_argument("--slo", type=float, default=0.0,
                   help="per-request SLO seconds (0 = unbounded)")
    p.add_argument("--max-queue", type=int, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--mix", default=None,
                   help="comma-separated TPC-H query names (default: "
                        f"{','.join(DEFAULT_MIX)}; --refresh default: "
                        f"{','.join(REFRESH_MIX)})")
    p.add_argument("--slo-target", type=float, default=0.0,
                   help="per-tenant success objective for burn-rate "
                        "accounting (e.g. 0.99; 0 = policy/env default)")
    p.add_argument("--slo-latency", type=float, default=0.0,
                   help="latency objective seconds (0 = success-only)")
    p.add_argument("--storm", type=int, default=0,
                   help="after the replay, drive N fault-storm "
                        "requests on one tenant and record the "
                        "/health ok->unhealthy->ok transitions + "
                        "/events replay (the ISSUE 14 acceptance)")
    p.add_argument("--fleet", action="store_true",
                   help="replicated-fleet mode (ISSUE 15): spawn "
                        "--engines engine PROCESSES over one durable "
                        "tree, route the mix through a FleetRouter, "
                        "SIGKILL one engine mid-run and prove 0 lost "
                        "acks / 0 double-executions across the "
                        "failover")
    p.add_argument("--engines", type=int, default=2,
                   help="engine process count for --fleet (>= 2)")
    p.add_argument("--no-kill", action="store_true",
                   help="--fleet without the mid-run kill (baseline)")
    p.add_argument("--fleet-trace", action="store_true",
                   help="with --fleet (ISSUE 20): arm CYLON_TPU_TRACE "
                        "fleet-wide, stitch the router's and every "
                        "engine's trace segments onto one clock "
                        "(ping-handshake offsets) and write the "
                        "Chrome Trace artifact; the record gains the "
                        "stitched-request report and the query-profile "
                        "cost-model audit")
    p.add_argument("--hot-mix", action="store_true",
                   help="hot-mix dedup mode (ISSUE 19): replay a hot "
                        "mix (identical fingerprints) through the "
                        "FleetRouter against a single uncached "
                        "baseline engine, then against the warmed "
                        "coalescing + versioned-result-cache plane, "
                        "and gate the QPS multiplier at >= 10x with "
                        "0 oracle mismatches and 0 stale results "
                        "across a mid-probe append")
    p.add_argument("--refresh", action="store_true",
                   help="incremental-view mode (ISSUE 18): drive "
                        "TPC-H RF1-style appends interleaved with the "
                        "mix served as materialized views, and gate "
                        "on refresh wall <= 0.5x the from-scratch "
                        "recompute wall with 0 oracle mismatches on "
                        "concurrent generation-pinned reads")
    p.add_argument("--appends", type=int, default=2,
                   help="RF1 append rounds for --refresh")
    p.add_argument("--delta-sf", type=float, default=0.0,
                   help="scale factor of each RF1 delta (0 = sf/100)")
    args = p.parse_args(argv)
    mix_arg = (tuple(q.strip() for q in args.mix.split(",")
                     if q.strip()) if args.mix else None)

    if args.hot_mix:
        record = run_hotmix_bench(
            clients=args.clients, requests=max(args.requests, 2),
            sf=args.sf, seed=args.seed, mix=mix_arg or DEFAULT_MIX,
            engines=args.engines)
        missing = REQUIRED_HOTMIX_FIELDS - record.keys()
        assert not missing, f"hot-mix record dropped fields {missing}"
        _emit_record(record)
        # the acceptance gate: a stale result served across an append,
        # an oracle mismatch, or a dedup plane that is not at least
        # 10x the uncached baseline's QPS is a FAILED bench
        if record["oracle_mismatches"] or record["errors"] \
                or record["stale_results"]:
            return 1
        if record["qps_multiplier"] is None \
                or record["qps_multiplier"] < 10.0:
            return 1
        return 0

    if args.refresh:
        record = run_refresh_bench(
            sf=args.sf,
            delta_sf=args.delta_sf if args.delta_sf > 0 else None,
            rounds=args.appends, clients=args.clients,
            seed=args.seed, mix=mix_arg or REFRESH_MIX)
        missing = REQUIRED_REFRESH_FIELDS - record.keys()
        assert not missing, f"refresh record dropped fields {missing}"
        _emit_record(record)
        # the acceptance gate: a stale or wrong read (oracle mismatch)
        # or an incremental refresh that is not at least 2x cheaper
        # than recomputing from scratch is a FAILED bench
        if record["oracle_mismatches"] or record["errors"]:
            return 1
        if record["speedup"] is None or record["speedup"] < 2.0:
            return 1
        return 0

    if args.fleet:
        from cylon_tpu.serve.fleet import run_fleet_bench

        record = run_fleet_bench(
            clients=args.clients,
            requests=max(args.requests, 2), sf=args.sf,
            seed=args.seed, engines=args.engines,
            mix=mix_arg or DEFAULT_MIX,
            kill_mid_run=not args.no_kill,
            fleet_trace=args.fleet_trace)
        missing = REQUIRED_FLEET_FIELDS - record.keys()
        assert not missing, f"fleet record dropped fields {missing}"
        if args.fleet_trace:
            missing = REQUIRED_FLEET_TRACE_FIELDS - record.keys()
            assert not missing, \
                f"fleet-trace record dropped fields {missing}"
        _emit_record(record)
        # the acceptance gate: an acknowledged request lost, a double
        # execution, an oracle mismatch, or (with the kill armed) a
        # run that never failed over is a FAILED bench
        if record["lost_acks"] or record["double_executions"] \
                or record["oracle_mismatches"] or record["errors"]:
            return 1
        if not args.no_kill and record["failovers"] < 1:
            return 1
        return 0

    if args.storm:
        # the storm acceptance wants the full plane armed: the event
        # journal and the router's HTTP view of /health
        os.environ.setdefault("CYLON_TPU_EVENTS", "1")
        os.environ.setdefault("CYLON_TPU_SERVE_HTTP_PORT", "0")

    record = run_bench(
        clients=args.clients, requests=args.requests, sf=args.sf,
        schedule=args.schedule, slo=args.slo,
        max_queue=args.max_queue, seed=args.seed,
        mix=mix_arg or DEFAULT_MIX,
        slo_target=args.slo_target if args.slo_target > 0 else None,
        slo_latency=args.slo_latency if args.slo_latency > 0 else None,
        storm=args.storm)
    missing = REQUIRED_SERVE_FIELDS - record.keys()
    assert not missing, f"serve record dropped fields {missing}"
    _emit_record(record)
    # a replay that corrupted results or failed requests is a FAILED
    # bench, not a slow one; a storm leg that never drove /health to
    # unhealthy AND back to ok failed its acceptance
    if record["oracle_mismatches"] or record["errors"]:
        return 1
    if args.storm and not record.get("storm", {}).get("recovered"):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
