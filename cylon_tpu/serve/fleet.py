"""Replicated serve fleet: N engine processes, one router, zero lost acks.

ROADMAP item 5(c): "millions of users" needs more than one
:class:`~cylon_tpu.serve.ServeEngine` — it needs N engine *processes*
behind a router that keeps serving when one of them dies. Every
prerequisite already exists: PR 7 made a single engine crash-safe
(fsync'd write-ahead journal, snapshot tables, exactly-once replay with
idempotency keys) and PR 14 shipped the router contract (the
``/health`` composite verdict, ``/metrics/window``, the cursored
``/events?since=`` journal). This module is the missing assembly — the
fleet — and its chaos proof: kill one engine mid-run, lose nothing.

Topology (see ``docs/serving.md`` → "A replicated serve fleet")::

                         FleetRouter (this module)
                  poll: /health + /events?since=<cursor>
                  submit: POST /submit → GET /result/<rid>
                 ┌────────────┴────────────┐
           engine process e0         engine process e1
           ServeEngine + gateway     ServeEngine + gateway
                 │                          │
            <root>/engines/e0/        <root>/engines/e1/
              journal.jsonl             journal.jsonl
              journal.lock              journal.lock
                 └────────── <root>/catalog-store ───────┘
                          (shared snapshot store)

The moving parts:

* **One durable dir tree** (:class:`FleetLayout`): per-engine journal
  subdirs (each fenced by its own
  :class:`~cylon_tpu.serve.durability.JournalLock` — a second live
  engine can never append to an owned journal) over ONE shared
  snapshot store (every engine registers the same resident tables, so
  the snapshots are content-identical and either engine's store
  recovers them).

* **An engine gateway** (:class:`EngineGateway`): the *write* half of
  the per-engine HTTP surface — ``POST /submit`` admits a registered
  named query (the replayable submission surface), ``GET
  /result/<rid>`` long-polls its outcome. The read half stays the PR 14
  introspection endpoint (``/health``, ``/events``, ``/metrics/window``
  — still statically read-only-linted); the gateway is a separate
  port so the diagnostic plane never grows a control surface.

* **The router** (:class:`FleetRouter`): admits requests with
  fleet-scoped idempotency keys, routes by tenant affinity over each
  engine's latest ``/health`` verdict, and polls every engine on a
  cursor loop (``/health`` + ``/events?since=`` + ``/metrics/window``)
  under the ``router_poll`` watchdog section with
  :func:`~cylon_tpu.resilience.retrying` backoff — transport failures
  classify as ``Code.Unavailable`` (:class:`EngineUnavailable`), i.e.
  retryable, until they aren't.

* **Failover**: an engine that fails ``CYLON_TPU_FLEET_FAIL_THRESHOLD``
  consecutive polls (or answers unhealthy/closing past
  ``CYLON_TPU_FLEET_DWELL`` seconds) is declared dead. The router then
  (1) **fences** its journal
  (:func:`~cylon_tpu.serve.durability.fence_journal` — a zombie's next
  append raises instead of racing the replay), (2) reads the dead
  journal's admitted-but-unresolved entries and **replays** them on a
  surviving peer with their ORIGINAL idempotency keys — exactly once,
  because keys dedup through both the router's ack cache and the
  peer's journal — and (3) re-points every affected
  :class:`RouterTicket` at its replacement, so a client blocked in
  ``result()`` just... gets its result. An acknowledged request is
  never lost (``fleet.lost_acks`` MUST stay 0); a retried one never
  double-executes.

Telemetry: ``fleet.routed{engine,tenant}``, ``fleet.failovers``,
``fleet.replayed``, ``fleet.lost_acks``, ``fleet.deduped``,
``fleet.events_gap{engine}`` counters plus ``failover``/``fence``/
``events_gap`` entries in the structured event journal.

Fleet tracing (ISSUE 20, armed by ``CYLON_TPU_TRACE`` like the local
flight recorder — the unarmed router mints nothing and pulls nothing):
every admitted request gets a ``trace_id`` minted at
:meth:`FleetRouter.submit` (the outermost entry), carried to the
engine as ``X-Cylon-Trace-Id``/``X-Cylon-Parent-Span`` headers on
``POST /submit`` — headers, not body kwargs, so the journaled replay
entry keeps the query's own arguments — and kept by a failover replay
(the journal entry records the id; the survivor re-runs under it with
a ``fleet.replay_hop`` marker). The poll loop additionally drains each
engine's cursored ``/trace?since=`` segments and estimates per-engine
clock offsets from the ``/ping`` wall stamp (midpoint method), so
:meth:`FleetRouter.fleet_trace_buffers` hands
:func:`cylon_tpu.telemetry.trace.merge_timelines` one aligned
router+engines timeline per run (the ``--fleet-trace`` bench leg's
Chrome trace artifact).

Knobs (``docs/serving.md`` knob table):

================================  =================================  =======
env                               meaning                            default
================================  =================================  =======
``CYLON_TPU_FLEET_POLL``          router poll interval (s)           ``0.5``
``CYLON_TPU_FLEET_FAIL_THRESHOLD``consecutive failed polls → dead    ``3``
``CYLON_TPU_FLEET_DWELL``         unhealthy/closing dwell (s) → dead ``5``
``CYLON_TPU_FLEET_PROBE_TIMEOUT`` per-probe HTTP timeout (s; a busy
                                  engine is not a dead engine)       ``30``
``CYLON_TPU_FLEET_LOCK_TTL``      journal-lock heartbeat TTL (s;
                                  ``0`` = pid-liveness only)         ``0``
================================  =================================  =======

Run one engine process (the fleet bench / chaos harness spawns these)::

    python -m cylon_tpu.serve.fleet --root /tmp/fleet --name e0 \\
        --sf 0.002 --mix q1,q3,q5,q6

The measured acceptance is ``python -m cylon_tpu.serve.bench --fleet
--clients 16``: two engine processes, SIGKILL one mid-run, and the
record (``BENCH_r09.json``) pins failovers ≥ 1, lost_acks == 0,
double_executions == 0 and the windowed p99 before/during/after the
kill.
"""

import argparse
import base64
import hashlib
import http.client
import itertools
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

from cylon_tpu import plan, resilience, telemetry, watchdog
from cylon_tpu.errors import (Code, CylonError, DataLossError,
                              DeadlineExceeded, InvalidArgument,
                              ResourceExhausted)
from cylon_tpu.serve.durability import RequestJournal, fence_journal
from cylon_tpu.serve.result_cache import (ResultCache,
                                          cache_bytes_from_env,
                                          hook_on_append)
from cylon_tpu.telemetry import events as _events
from cylon_tpu.telemetry import trace as _trace
from cylon_tpu.utils.logging import get_logger

__all__ = [
    "EngineUnavailable", "RemoteRequestFailed", "FleetLayout",
    "EngineGateway", "HttpEngineClient", "LocalEngineClient",
    "RouterTicket", "FleetRouter", "spawn_engine", "EngineProc",
    "run_fleet_bench", "encode_value", "decode_value",
]

#: default mixed workload for fleet engine processes (mirrors
#: serve.bench.DEFAULT_MIX without importing the bench at module load)
DEFAULT_MIX = ("q1", "q3", "q5", "q6", "q14")

#: per-query TPC-H read sets — the version-vector half of the result-
#: cache key each fleet engine declares at register_query time (ISSUE
#: 19). Precise sets mean precise invalidation: an orders append must
#: not evict a cached q1 (lineitem-only). A query not listed here
#: falls back to the FULL resident set — over-invalidation is merely
#: slower, under-invalidation would serve stale bytes.
QUERY_READ_SETS = {
    "q1": ("lineitem",),
    "q3": ("customer", "orders", "lineitem"),
    "q4": ("orders", "lineitem"),
    "q5": ("customer", "orders", "lineitem", "supplier", "nation",
           "region"),
    "q6": ("lineitem",),
    "q7": ("supplier", "lineitem", "orders", "customer", "nation"),
    "q10": ("customer", "orders", "lineitem", "nation"),
    "q12": ("orders", "lineitem"),
    "q14": ("lineitem", "part"),
    "q18": ("customer", "orders", "lineitem"),
    "q19": ("lineitem", "part"),
}


def _poll_interval() -> float:
    try:
        return float(os.environ.get("CYLON_TPU_FLEET_POLL", "0.5"))
    except ValueError:
        return 0.5


def _fail_threshold() -> int:
    try:
        return max(int(os.environ.get(
            "CYLON_TPU_FLEET_FAIL_THRESHOLD", "3")), 1)
    except ValueError:
        return 3


def _dwell() -> float:
    try:
        return float(os.environ.get("CYLON_TPU_FLEET_DWELL", "5"))
    except ValueError:
        return 5.0


class EngineUnavailable(CylonError):
    """An engine's HTTP surface could not be reached (connection
    refused, reset, timeout, or a 5xx from a dying process). Carries
    ``Code.Unavailable`` so :func:`cylon_tpu.resilience.is_retryable`
    classifies it retryable — the router retries with backoff, and only
    a run of consecutive exhausted retries declares the engine dead.

    ``refused`` is True when the transport failure was a connection
    REFUSAL — no listener, so the request provably never reached an
    admission path. That is the one transport failure a submit may
    re-route on unconditionally (a timeout/reset is ambiguous: the
    engine may have admitted the request before the connection died,
    so re-routing is only safe once the engine is declared dead and
    the failover replay dedups the key)."""

    code = Code.Unavailable
    refused = False


class RemoteRequestFailed(CylonError):
    """A fleet-routed request FAILED on its engine (the error is the
    request's outcome — the answer was delivered, just not the happy
    one). ``kind`` preserves the engine-side error class name."""

    def __init__(self, msg: str = "", kind: "str | None" = None):
        super().__init__(msg)
        self.kind = kind


# --------------------------------------------------------- value codec
def encode_value(v) -> dict:
    """JSON-able envelope for a query result crossing the gateway:
    pandas DataFrames (column-wise, dtype-tagged, datetimes as int64
    ns, bytes base64), numpy arrays, numpy/python scalars. Floats ride
    native JSON (repr round-trips exactly); non-finite floats encode as
    None."""
    import numpy as np

    try:
        import pandas as pd
    except ImportError:  # pragma: no cover - pandas is a hard dep here
        pd = None

    def _enc_float(x):
        # strict JSON has no Infinity/NaN tokens: tag non-finite
        # floats so decode restores them EXACTLY (inf must not come
        # back as NaN — or worse, None)
        if x != x:
            return {"__f__": "nan"}
        if x == float("inf"):
            return {"__f__": "inf"}
        if x == float("-inf"):
            return {"__f__": "-inf"}
        return x

    def _enc_item(x):
        if x is None:
            return None
        if isinstance(x, bytes):
            return {"__b64__": base64.b64encode(x).decode("ascii")}
        if isinstance(x, (str, bool, int)):
            return x
        if isinstance(x, float):
            return _enc_float(x)
        if isinstance(x, np.generic):
            return _enc_item(x.item())
        raise InvalidArgument(
            f"fleet result codec cannot encode {type(x).__name__}")

    def _enc_col(arr):
        arr = np.asarray(arr)
        if np.issubdtype(arr.dtype, np.datetime64):
            return {"dtype": str(arr.dtype), "kind": "datetime",
                    "data": arr.astype("int64").tolist()}
        if arr.dtype != object and (
                np.issubdtype(arr.dtype, np.number)
                or arr.dtype == bool):
            data = arr.tolist()
            if np.issubdtype(arr.dtype, np.floating):
                data = [_enc_float(x) for x in data]
            return {"dtype": str(arr.dtype), "kind": "num",
                    "data": data}
        return {"dtype": "object", "kind": "obj",
                "data": [_enc_item(x) for x in arr.tolist()]}

    if pd is not None and isinstance(v, pd.DataFrame):
        return {"__fleet__": "frame", "columns": list(map(str, v.columns)),
                "cols": {str(c): _enc_col(v[c].to_numpy())
                         for c in v.columns}}
    if isinstance(v, np.ndarray):
        return {"__fleet__": "ndarray", "col": _enc_col(v)}
    return {"__fleet__": "scalar", "data": _enc_item(v)}


def decode_value(env: "dict | None"):
    """Inverse of :func:`encode_value`."""
    import numpy as np
    import pandas as pd

    if env is None:
        return None

    _SPECIALS = {"nan": float("nan"), "inf": float("inf"),
                 "-inf": float("-inf")}

    def _dec_item(x):
        if isinstance(x, dict):
            if "__b64__" in x:
                return base64.b64decode(x["__b64__"])
            if "__f__" in x:
                return _SPECIALS[x["__f__"]]
        return x

    def _dec_col(c):
        if c["kind"] == "datetime":
            return np.asarray(c["data"],
                              dtype="int64").astype(c["dtype"])
        if c["kind"] == "num":
            data = [_dec_item(x) for x in c["data"]]
            return np.asarray(data, dtype=np.dtype(c["dtype"]))
        return np.asarray([_dec_item(x) for x in c["data"]],
                          dtype=object)

    kind = env.get("__fleet__")
    if kind == "frame":
        return pd.DataFrame({c: _dec_col(env["cols"][c])
                             for c in env["columns"]},
                            columns=env["columns"])
    if kind == "ndarray":
        return _dec_col(env["col"])
    if kind == "scalar":
        return _dec_item(env.get("data"))
    raise InvalidArgument(f"unknown fleet value envelope {kind!r}")


# --------------------------------------------------------- layout
class FleetLayout:
    """The shared durable dir tree: per-engine journal subdirs under
    ``<root>/engines/<name>/`` (each with its own lockfile fence) plus
    ONE shared snapshot store at ``<root>/catalog-store`` — every
    engine registers the same resident tables, so the snapshots are
    content-identical and dedup on disk."""

    def __init__(self, root: str):
        self.root = str(root)

    @property
    def engines_root(self) -> str:
        return os.path.join(self.root, "engines")

    def engine_dir(self, name: str) -> str:
        return os.path.join(self.engines_root, str(name))

    @property
    def snapshot_dir(self) -> str:
        return os.path.join(self.root, "catalog-store")

    def engine_names(self) -> "list[str]":
        try:
            return sorted(os.listdir(self.engines_root))
        except OSError:
            return []


def snapshot_generations(root: str) -> "dict[str, int]":
    """Per-table generation stamps in the fleet's SHARED snapshot
    store (ISSUE 18 fix): every engine's ``register_table``/
    ``append_table`` writes its catalog generation into the snapshot
    map, so a failover target's :meth:`ServeEngine.recover` — and this
    router-side audit — sees the POST-append generation, not a
    silently stale one. Reads the store at ``<root>/catalog-store``;
    tables snapshotted before the versioning era are absent."""
    from cylon_tpu.serve.durability import CatalogSnapshot

    return CatalogSnapshot(FleetLayout(root).snapshot_dir).generations()


# --------------------------------------------------------- gateway
class EngineGateway:
    """The per-engine-process submission surface the router talks to.

    Deliberately separate from the read-only introspection endpoint
    (``serve/introspect.py`` stays statically linted as having no
    mutating calls): ``POST /submit`` admits one REGISTERED named query
    through the engine's public :meth:`~ServeEngine.submit_named` —
    which means every gateway admission is write-ahead journaled,
    idempotency-key deduped and SLO-stamped exactly like a local one —
    and ``GET /result/<rid>`` long-polls the ticket's outcome. Loopback
    only, like the introspection port."""

    def __init__(self, engine, port: int = 0):
        import http.server

        self._engine = engine
        outer = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            server_version = "cylon-tpu-fleet-gateway"
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                get_logger().debug("gateway: " + fmt, *args)

            def _reply(self, code: int, payload: dict) -> None:
                body = json.dumps(telemetry.json_safe(payload),
                                  allow_nan=False).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 - stdlib handler name
                try:
                    outer._get(self)
                except BrokenPipeError:
                    pass
                except Exception as e:  # never kill the server thread
                    try:
                        self._reply(500, {
                            "error": f"{type(e).__name__}: {e}",
                            "kind": type(e).__name__})
                    except Exception:
                        pass

            def do_POST(self):  # noqa: N802 - stdlib handler name
                try:
                    outer._post(self)
                except BrokenPipeError:
                    pass
                except Exception as e:
                    try:
                        self._reply(500, {
                            "error": f"{type(e).__name__}: {e}",
                            "kind": type(e).__name__})
                    except Exception:
                        pass

        self._httpd = http.server.ThreadingHTTPServer(
            ("127.0.0.1", int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="cylon-fleet-gateway", daemon=True)
        self._thread.start()

    @property
    def address(self) -> "tuple[str, int]":
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    # ------------------------------------------------------- handlers
    def _get(self, h) -> None:
        import urllib.parse

        path, _, query = h.path.partition("?")
        qs = urllib.parse.parse_qs(query)
        eng = self._engine
        if path == "/ping":
            h._reply(503 if eng.closing else 200,
                     {"ok": not eng.closing, "closing": eng.closing,
                      "live": eng.live,
                      # wall-clock stamp for the router's clock-offset
                      # handshake (midpoint method): offset =
                      # ts - (t0 + t1)/2 around the probe
                      "ts": time.time()})
            return
        if path.startswith("/result/"):
            rid = path.rsplit("/", 1)[1]
            ticket = eng.ticket(int(rid)) if rid.isdigit() else None
            if ticket is None:
                h._reply(404, {"error": f"unknown rid {rid!r}",
                               "kind": "NotFound"})
                return
            try:
                wait_s = min(float(qs.get("timeout", ["0"])[0]), 60.0)
            except ValueError:
                wait_s = 0.0
            if wait_s > 0:
                ticket.wait(wait_s)
            if not ticket.done:
                h._reply(200, {"state": "running",
                               "rid": ticket.rid})
                return
            if ticket.error is not None:
                h._reply(200, {
                    "state": "failed", "rid": ticket.rid,
                    "error": str(ticket.error),
                    "kind": type(ticket.error).__name__})
                return
            h._reply(200, {"state": "done", "rid": ticket.rid,
                           "value": encode_value(ticket.value),
                           # the (fingerprint, version-vector) the
                           # engine published this result under —
                           # None when uncacheable; the router's
                           # fleet-scoped cache keys on it verbatim
                           "cache_key": getattr(ticket, "cache_key",
                                                None)})
            return
        h._reply(404, {"error": f"unknown path {path!r}",
                       "kind": "NotFound"})

    def _post(self, h) -> None:
        eng = self._engine
        if h.path.partition("?")[0] != "/submit":
            h._reply(404, {"error": f"unknown path {h.path!r}",
                           "kind": "NotFound"})
            return
        if eng.closing:
            h._reply(503, {"error": "engine closing",
                           "kind": "Unavailable"})
            return
        try:
            n = int(h.headers.get("Content-Length", "0"))
            body = json.loads(h.rfile.read(n) or b"{}")
        except ValueError as e:
            h._reply(400, {"error": f"malformed submit body: {e}",
                           "kind": "InvalidArgument"})
            return
        # the fleet trace context crosses the process hop as HTTP
        # headers, never as body kwargs — the journal must record the
        # query's OWN kwargs so a replay's fingerprint still matches.
        # submit_named strips the _trace_* control keywords before
        # fingerprinting for the same reason.
        tid = h.headers.get("X-Cylon-Trace-Id")
        parent = h.headers.get("X-Cylon-Parent-Span")
        if parent is not None and parent.isdigit():
            parent = int(parent)
        try:
            ticket = eng.submit_named(
                str(body["name"]), *body.get("args", ()),
                idempotency_key=body.get("key"),
                tenant=body.get("tenant", "default"),
                priority=int(body.get("priority", 1)),
                slo=body.get("slo"),
                tables=body.get("tables", ()),
                _trace_id=tid, _parent_span=parent,
                **body.get("kwargs", {}))
        except ResourceExhausted as e:
            h._reply(429, {"error": str(e),
                           "kind": "ResourceExhausted"})
            return
        except (InvalidArgument, KeyError) as e:
            h._reply(400, {"error": str(e),
                           "kind": type(e).__name__})
            return
        h._reply(200, {"rid": ticket.rid, "state": ticket.state,
                       "tenant": ticket.tenant})


# --------------------------------------------------------- clients
class HttpEngineClient:
    """The router's handle on one engine PROCESS: the gateway port for
    submit/result, the introspection port for /health, /events and
    /metrics/window. Every transport failure maps to
    :class:`EngineUnavailable` (``Code.Unavailable`` — retryable)."""

    def __init__(self, name: str, gateway_url: str,
                 introspect_url: "str | None" = None,
                 durable_dir: "str | None" = None,
                 pid: "int | None" = None,
                 probe_timeout: "float | None" = None):
        self.name = str(name)
        self.gateway_url = gateway_url.rstrip("/")
        self.introspect_url = (introspect_url.rstrip("/")
                               if introspect_url else None)
        self.durable_dir = durable_dir
        self.pid = pid
        # a BUSY engine is not a dead engine: on a saturated host the
        # GIL can starve the HTTP threads for seconds, so probes get a
        # generous timeout — a real kill still detects instantly
        # (connection refused), and a wedged-but-listening engine is
        # the unhealthy-dwell / lock-TTL path's job, not this one's
        if probe_timeout is None:
            try:
                probe_timeout = float(os.environ.get(
                    "CYLON_TPU_FLEET_PROBE_TIMEOUT", "30"))
            except ValueError:
                probe_timeout = 30.0
        self.probe_timeout = probe_timeout

    def _request(self, url: str, data: "bytes | None" = None,
                 timeout: float = 10.0,
                 headers: "dict | None" = None) -> dict:
        hdrs = {"Content-Type": "application/json"} if data else {}
        if headers:
            hdrs.update(headers)
        req = urllib.request.Request(url, data=data, headers=hdrs)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read())
            except Exception:
                payload = {"error": str(e), "kind": "HTTPError"}
            if e.code == 503:
                # a clean "closing"/unavailable verdict, not a crash
                payload.setdefault("status", "closing")
                payload["http_status"] = 503
                return payload
            if e.code == 429:
                raise ResourceExhausted(payload.get("error", str(e)))
            if e.code in (400, 404, 409):
                raise InvalidArgument(payload.get("error", str(e)))
            if "kind" in payload:
                # the GATEWAY's error envelope: the engine is alive
                # and answered — an application-level failure (e.g. a
                # result the codec cannot encode) must not read as
                # engine death and trip a failover
                raise RemoteRequestFailed(
                    f"engine {self.name!r} request failed: "
                    f"{payload.get('error', '')}",
                    kind=payload.get("kind"))
            raise EngineUnavailable(
                f"engine {self.name!r} answered HTTP {e.code}: "
                f"{payload.get('error', '')}")
        except (urllib.error.URLError, ConnectionError, OSError,
                TimeoutError, http.client.HTTPException) as e:
            # includes IncompleteRead/RemoteDisconnected: the process
            # died (or was SIGKILLed) mid-response — Unavailable, the
            # retryable transport class
            reason = getattr(e, "reason", e)
            exc = EngineUnavailable(
                f"engine {self.name!r} unreachable at {url}: "
                f"{type(e).__name__}: {e}")
            exc.refused = isinstance(reason, ConnectionRefusedError)
            raise exc

    # ------------------------------------------------- router surface
    def submit(self, name: str, args=(), kwargs=None,
               tenant: str = "default", priority: int = 1,
               slo=None, key: "str | None" = None,
               tables=(), trace_id: "str | None" = None,
               parent_span=None) -> int:
        body = {"name": name, "args": list(args),
                "kwargs": dict(kwargs or {}), "tenant": tenant,
                "priority": priority, "slo": slo, "key": key,
                "tables": list(tables)}
        headers = {}
        if trace_id is not None:
            headers["X-Cylon-Trace-Id"] = str(trace_id)
            if parent_span is not None:
                headers["X-Cylon-Parent-Span"] = str(parent_span)
        out = self._request(self.gateway_url + "/submit",
                            data=json.dumps(body).encode(),
                            timeout=max(self.probe_timeout, 10.0),
                            headers=headers or None)
        if "rid" not in out:
            raise EngineUnavailable(
                f"engine {self.name!r} refused submit: {out}")
        return int(out["rid"])

    def result(self, rid: int, timeout: float = 5.0) -> dict:
        return self._request(
            f"{self.gateway_url}/result/{int(rid)}?timeout={timeout}",
            timeout=timeout + max(self.probe_timeout, 10.0))

    def health(self) -> dict:
        base = self.introspect_url or self.gateway_url
        path = "/health" if self.introspect_url else "/ping"
        return self._request(base + path, timeout=self.probe_timeout)

    def events_since(self, cursor: int = 0) -> dict:
        if self.introspect_url is None:
            return {"events": [], "cursor": int(cursor), "dropped": 0,
                    "armed": False}
        return self._request(
            f"{self.introspect_url}/events?since={int(cursor)}",
            timeout=self.probe_timeout)

    def trace_since(self, cursor: int = 0) -> dict:
        """The engine's cursored ``/trace?since=`` span segment (same
        payload discipline as :meth:`events_since`)."""
        if self.introspect_url is None:
            return {"events": [], "cursor": int(cursor), "dropped": 0,
                    "armed": False}
        return self._request(
            f"{self.introspect_url}/trace?since={int(cursor)}",
            timeout=self.probe_timeout)

    def ping(self) -> dict:
        """Raw gateway liveness reply — carries the engine's wall
        ``ts`` for the router's clock-offset handshake."""
        return self._request(self.gateway_url + "/ping",
                             timeout=self.probe_timeout)

    def metrics_window(self, window: "float | None" = None) -> dict:
        if self.introspect_url is None:
            return {}
        q = f"?window={window}" if window else ""
        return self._request(
            self.introspect_url + "/metrics/window" + q,
            timeout=self.probe_timeout)


class LocalEngineClient:
    """The same client interface over an IN-PROCESS engine — the fleet
    logic is identical whether the engine is a process or an object,
    which is what lets the router's routing/failover machinery unit-
    test without interpreter spawns. Talks only through the engine's
    public API (the bench-guard lint pins that for this whole
    module)."""

    def __init__(self, engine, name: str,
                 durable_dir: "str | None" = None):
        self.engine = engine
        self.name = str(name)
        self.durable_dir = durable_dir or engine.durable_dir
        self.pid = os.getpid()

    def submit(self, name: str, args=(), kwargs=None,
               tenant: str = "default", priority: int = 1,
               slo=None, key: "str | None" = None,
               tables=(), trace_id: "str | None" = None,
               parent_span=None) -> int:
        if self.engine.closing:
            e = EngineUnavailable(
                f"engine {self.name!r} is closing")
            e.refused = True  # nothing admitted: safe to re-route
            raise e
        t = self.engine.submit_named(
            name, *args, idempotency_key=key, tenant=tenant,
            priority=priority, slo=slo, tables=tables,
            _trace_id=trace_id, _parent_span=parent_span,
            **(kwargs or {}))
        return t.rid

    def result(self, rid: int, timeout: float = 5.0) -> dict:
        t = self.engine.ticket(rid)
        if t is None:
            raise EngineUnavailable(
                f"engine {self.name!r} lost rid {rid}")
        t.wait(timeout)
        if not t.done:
            return {"state": "running", "rid": rid}
        if t.error is not None:
            return {"state": "failed", "rid": rid,
                    "error": str(t.error),
                    "kind": type(t.error).__name__}
        return {"state": "done", "rid": rid,
                "value": encode_value(t.value),
                "cache_key": getattr(t, "cache_key", None)}

    def health(self) -> dict:
        if self.engine.closing:
            return {"status": "closing"}
        return self.engine.health()

    def events_since(self, cursor: int = 0) -> dict:
        return _events.since(cursor)

    def trace_since(self, cursor: int = 0) -> dict:
        return _trace.since(cursor)

    def ping(self) -> dict:
        # in-process: same clock as the router, so the handshake's
        # midpoint estimate converges on ~0 offset
        return {"ok": not self.engine.closing,
                "closing": self.engine.closing, "ts": time.time()}

    def metrics_window(self, window: "float | None" = None) -> dict:
        from cylon_tpu.telemetry import timeseries

        return timeseries.window_view(window)


# --------------------------------------------------------- router
def _affinity_order(tenant: str, names: "list[str]") -> "list[str]":
    """Deterministic tenant-affinity ring: the tenant's md5 picks a
    starting engine, failures walk the ring. Stable across processes
    (no PYTHONHASHSEED dependence) so a router restart keeps the same
    placement."""
    names = sorted(names)
    if not names:
        return []
    h = int.from_bytes(
        hashlib.md5(str(tenant).encode()).digest()[:4], "big")
    k = h % len(names)
    return names[k:] + names[:k]


class _EngineState:
    """Router-side view of one engine."""

    def __init__(self, client):
        self.client = client
        self.name = client.name
        self.verdict: "dict | None" = None
        self.status = "unknown"
        self.failures = 0          # consecutive failed polls
        self.unhealthy_since: "float | None" = None
        self.dead = False
        self.last_window: "dict | None" = None
        self.events_seen = 0
        # fleet tracing (ISSUE 20): the engine's pulled /trace segment
        # stream (cursored, bounded like the source ring) plus the
        # ping-handshake clock estimate — all idle until the router's
        # recorder is armed
        self.trace_cursor = 0
        self.trace_events: list = []
        self.trace_dropped = 0
        self.clock_offset: "float | None" = None
        self.offset_jitter: "float | None" = None

    def snapshot(self) -> dict:
        return {"name": self.name, "status": self.status,
                "dead": self.dead, "failures": self.failures,
                "events_seen": self.events_seen}


class RouterTicket:
    """The fleet-level future: survives the engine it was first routed
    to. ``result()`` long-polls the current assignment and, when a
    failover re-points the ticket at a peer, simply keeps polling
    there — the client never sees the swap."""

    def __init__(self, router: "FleetRouter", key: str, name: str,
                 tenant: str):
        self._router = router
        self.key = key
        self.name = name
        self.tenant = tenant
        self._cv = threading.Condition()
        self._client = None
        self.rid: "int | None" = None
        self._lost: "str | None" = None
        self.submitted = time.monotonic()
        #: the fleet trace id minted for this request (None unarmed) —
        #: one id names the whole causal chain, failover hops included
        self.trace_id: "str | None" = None

    @property
    def engine(self) -> "str | None":
        with self._cv:
            return None if self._client is None else self._client.name

    def _assign(self, client, rid: int) -> None:
        # dead-ness checked OUTSIDE _cv (router lock ordering: never
        # _cv → _mu): a failover replay may have already re-pointed
        # this ticket at a live peer while our submit thread was
        # descheduled — the stale assignment to the now-dead engine
        # must not overwrite it (result() would poll a corpse forever)
        new_dead = self._router._is_dead(getattr(client, "name", None))
        with self._cv:
            if self._client is not None and new_dead:
                return
            self._client, self.rid = client, int(rid)
            self._cv.notify_all()

    def _mark_lost(self, why: str) -> None:
        """Declare this acknowledged request LOST. The ONE place the
        per-ticket ``fleet.lost_acks`` count happens (once per ticket,
        however many threads observe the loss)."""
        with self._cv:
            if self._lost is not None:
                return
            self._lost = why
            self._cv.notify_all()
        telemetry.counter("fleet.lost_acks",
                          tenant=self.tenant).inc()

    def result(self, timeout: "float | None" = None):
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            done, value = self._router._acked(self.key)
            if done:
                return value
            failed = self._router._failure(self.key)
            if failed is not None:
                raise RemoteRequestFailed(
                    f"request {self.key!r} failed on engine "
                    f"{failed['engine']}: {failed['error']}",
                    kind=failed["kind"])
            with self._cv:
                if self._lost is not None:
                    # counted once at _mark_lost time, not per waiter
                    raise DataLossError(
                        f"acknowledged request {self.key!r} was LOST: "
                        f"{self._lost}")
                client, rid = self._client, self.rid
            remaining = (None if deadline is None
                         else deadline - time.monotonic())
            if remaining is not None and remaining <= 0:
                raise DeadlineExceeded(
                    f"result({timeout=}) timed out waiting on fleet "
                    f"request {self.key!r}", section="router_poll",
                    retryable=True)
            chunk = 5.0 if remaining is None else min(remaining, 5.0)
            if client is None:  # awaiting failover reassignment
                with self._cv:
                    if self._client is None and self._lost is None:
                        self._cv.wait(min(chunk, 0.25))
                continue
            try:
                res = client.result(rid, timeout=chunk)
            except EngineUnavailable:
                # the engine died under us: tell the router (counts
                # toward its failure threshold) and wait for either a
                # reassignment or a lost verdict
                self._router._note_failure(client.name,
                                           reason="result_poll")
                with self._cv:
                    if self._client is client and self._lost is None:
                        self._cv.wait(0.25)
                continue
            state = res.get("state")
            if state == "done":
                value = decode_value(res.get("value"))
                self._router._store_result(res.get("cache_key"),
                                           res.get("value"))
                self._router._record_ack(self.key, value)
                return value
            if state == "failed":
                self._router._record_failure(
                    self.key, engine=client.name,
                    error=res.get("error", ""),
                    kind=res.get("kind", "Error"))
                raise RemoteRequestFailed(
                    f"request {self.key!r} failed on engine "
                    f"{client.name}: {res.get('error', '')}",
                    kind=res.get("kind"))
            # running (or a 503 "closing" envelope): poll again


class FleetRouter:
    """Tenant-affinity + health-verdict routing over N engines, with
    journal-replay failover (module docstring). ``clients`` is any mix
    of :class:`HttpEngineClient` (engine processes) and
    :class:`LocalEngineClient` (in-process engines — tests)."""

    _ids = itertools.count(1)

    def __init__(self, clients, poll_interval: "float | None" = None,
                 fail_threshold: "int | None" = None,
                 unhealthy_dwell: "float | None" = None,
                 retry_policy=None, start: bool = True):
        clients = list(clients)
        if len({c.name for c in clients}) != len(clients):
            raise InvalidArgument("engine names must be unique")
        self._mu = threading.RLock()
        self._states = {c.name: _EngineState(c) for c in clients}
        self._cursors = {c.name: 0 for c in clients}
        # fleet tracing arms off the SAME env as the local recorder:
        # one check at construction — an unarmed router never mints
        # ids, opens spans, handshakes clocks or pulls /trace
        self._trace_armed = _trace.enabled()
        self.poll_interval = (poll_interval if poll_interval is not None
                              else _poll_interval())
        self.fail_threshold = (fail_threshold
                               if fail_threshold is not None
                               else _fail_threshold())
        self.unhealthy_dwell = (unhealthy_dwell
                                if unhealthy_dwell is not None
                                else _dwell())
        self._retry_policy = retry_policy
        # the FLEET-scoped versioned result cache (ISSUE 19): keyed
        # exactly like the engine-side cache — (query fingerprint,
        # table-version vector) — but holding the ENCODED value
        # envelopes the gateways reply with, so a hit on any engine
        # serves every engine, and the cache survives the engine the
        # result first ran on. The router only learns a key from a
        # done reply's ``cache_key`` (it cannot version remote
        # tables itself), so ``_vv_by_fp`` maps fingerprint -> the
        # last vector an engine answered with; a stale mapping can
        # only cause a MISS (the entry under the old vector was
        # already invalidated), never a stale hit.
        self._result_cache = hook_on_append(ResultCache(
            cache_bytes_from_env("CYLON_TPU_FLEET_RESULT_CACHE_BYTES"),
            metric_prefix="fleet"))
        self._vv_by_fp: "dict[str, tuple]" = {}
        self._tickets: "dict[str, RouterTicket]" = {}
        self._acks: "dict[str, object]" = {}
        self._failures: "dict[str, dict]" = {}
        self._replayed_keys: "list[str]" = []
        self._failovers: "list[dict]" = []
        self._kseq = itertools.count(1)
        self._stop = threading.Event()
        #: ONE poll thread per engine: a hung-but-listening engine
        #: (probe timeouts eat retries × probe_timeout per tick) must
        #: not head-of-line-block the detection of every OTHER
        #: engine's death
        self._pollers: "dict[str, threading.Thread]" = {}
        if start:
            self.start()

    # ------------------------------------------------------- lifecycle
    def start(self) -> None:
        with self._mu:
            for name in self._states:
                th = self._pollers.get(name)
                if th is not None and th.is_alive():
                    continue
                th = threading.Thread(
                    target=self._poll_loop, args=(name,),
                    name=f"cylon-fleet-poll-{name}", daemon=True)
                self._pollers[name] = th
                th.start()

    def close(self) -> None:
        """Stop the poll loops (the engines belong to their owner)."""
        self._stop.set()
        for th in list(self._pollers.values()):
            th.join(timeout=5)

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------- routing
    def engines(self) -> "list[dict]":
        with self._mu:
            return [s.snapshot() for s in self._states.values()]

    def _eligible_locked(self) -> "list[_EngineState]":
        """Routable engines, best verdict first: ``ok`` engines, then
        ``degraded``, then never-polled ``unknown`` (optimistic — a
        just-started fleet must route before the first poll lands).
        ``unhealthy``/``closing``/dead engines never route."""
        rank = {"ok": 0, "degraded": 1, "unknown": 2}
        out = [s for s in self._states.values()
               if not s.dead and s.status in rank]
        out.sort(key=lambda s: (rank[s.status], s.name))
        return out

    def _pick_locked(self, tenant: str,
                     exclude=frozenset()) -> "_EngineState":
        eligible = [s for s in self._eligible_locked()
                    if s.name not in exclude]
        if not eligible:
            raise EngineUnavailable(
                f"no routable engine in the fleet (states: "
                f"{[s.snapshot() for s in self._states.values()]})")
        # route within the best-status tier only (an ok engine always
        # beats a degraded one); the tenant's affinity ring breaks ties
        order = ("ok", "degraded", "unknown")
        best_rank = min(order.index(s.status) for s in eligible)
        tier = {s.name: s for s in eligible
                if order.index(s.status) == best_rank}
        name = _affinity_order(tenant, list(tier))[0]
        return tier[name]

    def submit(self, name: str, *args, tenant: str = "default",
               idempotency_key: "str | None" = None,
               priority: int = 1, slo=None, tables=(),
               **kwargs) -> RouterTicket:
        """Admit one named query into the fleet. ``idempotency_key``
        is FLEET-scoped: a key the router has already acked returns
        the cached result's ticket (no engine is touched — the dedup
        survives the engine the original ran on); an unknown key is
        stamped on the engine-side journal, so a failover replay and a
        client retry can never both execute. Keys are generated when
        the client brings none (the replay path needs one).

        When tracing is armed this is the request's OUTERMOST entry:
        it mints the ``trace_id``, opens the router-side
        ``fleet.submit`` span, and hands both across the HTTP hop —
        the engine's first span links back here via ``parent_span``."""
        if not self._trace_armed:
            return self._submit_routed(
                name, args, kwargs, tenant=tenant,
                idempotency_key=idempotency_key, priority=priority,
                slo=slo, tables=tables, trace_id=None,
                parent_span=None)
        trace_id = _trace.new_trace_id()
        with _trace.trace_context(trace_id):
            tok = _trace.begin("fleet.submit", cat="fleet",
                               query=str(name), tenant=str(tenant))
            try:
                return self._submit_routed(
                    name, args, kwargs, tenant=tenant,
                    idempotency_key=idempotency_key,
                    priority=priority, slo=slo, tables=tables,
                    trace_id=trace_id,
                    parent_span=tok[0] if tok else None)
            finally:
                _trace.end(tok)

    def _submit_routed(self, name, args, kwargs, *, tenant,
                       idempotency_key, priority, slo, tables,
                       trace_id, parent_span) -> RouterTicket:
        key = idempotency_key or \
            f"fleet-{os.getpid()}-{next(self._kseq)}"
        with self._mu:
            existing = self._tickets.get(key)
            if existing is not None:
                telemetry.counter("fleet.deduped",
                                  tenant=tenant).inc()
                return existing
            ticket = RouterTicket(self, key, name, tenant)
            ticket.trace_id = trace_id
            self._tickets[key] = ticket
        # fleet-scoped cache check BEFORE any engine is touched: the
        # fingerprint is computed router-side (same canonical JSON the
        # engines hash), the version vector is the one an engine last
        # answered this fingerprint with — an append anywhere in the
        # fleet invalidated the entry under it, so a hit is provably
        # current. The hit resolves through the ack ledger, exactly
        # like a delivered result (0 engine round-trips).
        if self._result_cache.enabled:
            fp = plan.query_fingerprint(name, args, kwargs)
            if fp is not None:
                with self._mu:
                    vv = self._vv_by_fp.get(fp)
                hit, env = self._result_cache.lookup(fp, vv)
                if hit:
                    self._record_ack(key, decode_value(env))
                    return ticket
        # a submit that lands in an engine's death window (killed but
        # not yet declared dead — _pick_locked can still select it)
        # walks the affinity ring to the next peer instead of erroring
        # the client. Re-routing with the SAME key is safe ONLY when
        # the first attempt provably did not execute: a connection
        # REFUSAL (no listener — nothing was admitted), or an engine
        # since declared DEAD (if it did journal the admit, the
        # failover replay dedups the key). An ambiguous failure
        # against a live engine (timeout while it grinds) must raise
        # instead — the engine may be executing the request, and a
        # same-key resubmission to a peer would genuinely run twice.
        tried: set = set()
        while True:
            try:
                with self._mu:
                    st = self._pick_locked(tenant, exclude=tried)
            except EngineUnavailable:
                with self._mu:
                    self._tickets.pop(key, None)
                raise
            try:
                rid = st.client.submit(
                    name, args=args, kwargs=kwargs, tenant=tenant,
                    priority=priority, slo=slo, key=key,
                    tables=tables, trace_id=trace_id,
                    parent_span=parent_span)
            except EngineUnavailable as e:
                self._note_failure(st.name, reason="submit")
                if not (getattr(e, "refused", False)
                        or self._is_dead(st.name)):
                    with self._mu:
                        self._tickets.pop(key, None)
                    raise
                tried.add(st.name)
                get_logger().warning(
                    "fleet: submit of %r to %r failed (%s); "
                    "re-routing", key, st.name, e)
                continue
            except BaseException:
                with self._mu:
                    self._tickets.pop(key, None)
                raise
            break
        ticket._assign(st.client, rid)
        telemetry.counter("fleet.routed", engine=st.name,
                          tenant=tenant).inc()
        return ticket

    # ------------------------------------------------------- acks
    def _record_ack(self, key: str, value) -> None:
        with self._mu:
            self._acks[key] = value

    def _store_result(self, cache_key: "dict | None", env) -> None:
        """Publish one delivered result envelope into the fleet cache
        under the ``(fingerprint, version-vector)`` the ENGINE stamped
        on it (an engine only stamps a key when its read set was still
        at the admitted versions at retirement — the staleness guard
        already ran there). Local-fleet belt-and-braces: when the
        router process itself holds a vector table (in-process
        engines share the catalog), a version that moved since the
        stamp drops the store instead of publishing a dead entry."""
        if not cache_key or not self._result_cache.enabled:
            return
        fp = cache_key.get("fingerprint")
        vv = tuple(tuple(v) for v in cache_key.get("versions", ()))
        if fp is None or not vv:
            return
        from cylon_tpu import catalog
        from cylon_tpu.errors import KeyError_

        for tid, gen, dig in vv:
            try:
                cur = catalog.table_version(str(tid))
            except (KeyError, KeyError_):
                continue  # remote table: /events invalidation governs
            if (int(cur["generation"]) != int(gen)
                    or str(cur["digest"]) != str(dig)):
                return
        self._result_cache.store(fp, vv, env)
        with self._mu:
            self._vv_by_fp[fp] = vv

    def _acked(self, key: str) -> "tuple[bool, object]":
        with self._mu:
            if key in self._acks:
                return True, self._acks[key]
        return False, None

    def _record_failure(self, key: str, engine: str, error: str,
                        kind: str) -> None:
        with self._mu:
            self._failures[key] = {"engine": engine, "error": error,
                                   "kind": kind}

    def _failure(self, key: str) -> "dict | None":
        with self._mu:
            return self._failures.get(key)

    # ------------------------------------------------------- polling
    def _poll_loop(self, name: str) -> None:
        st = self._states[name]
        while not self._stop.is_set():
            if st.dead:
                return  # DEAD is terminal; nothing left to watch
            self._poll_one(st)
            self._stop.wait(self.poll_interval)

    def _poll_one(self, st: "_EngineState") -> None:
        """One cursor-loop tick against one engine: the /health
        verdict (with retry/backoff — transport errors are
        ``Code.Unavailable``), the /events cursor advance, and the
        windowed metrics view, all inside the ``router_poll`` watchdog
        section."""
        with watchdog.watched_section("router_poll", detail=st.name):
            try:
                verdict = resilience.retrying(
                    st.client.health, self._retry_policy,
                    label=f"router_poll[{st.name}]")
            except Exception:
                self._note_failure(st.name, reason="health_poll")
                return
            try:
                ev = st.client.events_since(self._cursors[st.name])
                self._cursors[st.name] = ev.get(
                    "cursor", self._cursors[st.name])
                st.events_seen += len(ev.get("events", ()))
                gap = int(ev.get("dropped", 0) or 0)
                if gap:
                    # the engine's journal ring evicted entries before
                    # this poll read them — the router fell behind.
                    # Counted per engine and journaled, never silent:
                    # a gap can hide an append (stale fleet cache) or
                    # a replayed admit.
                    telemetry.counter("fleet.events_gap",
                                      engine=st.name).inc(gap)
                    _events.emit("events_gap", engine=st.name,
                                 dropped=gap)
                # fleet-cache invalidation rides the same cursor: an
                # append ANY engine journals evicts exactly the cached
                # results whose version vector read that table (for
                # in-process fleets the catalog hook already fired —
                # re-invalidating an evicted table is a no-op)
                for e in ev.get("events", ()):
                    if e.get("kind") == "append" and e.get("table"):
                        self._result_cache.invalidate_table(
                            e["table"])
                if self._trace_armed:
                    self._pull_trace(st)
                st.last_window = st.client.metrics_window()
            except Exception:
                # the health verdict landed; a flaky events/window read
                # alone is not a liveness failure
                pass
        now = time.monotonic()
        with self._mu:
            st.verdict = verdict
            st.status = verdict.get("status", "unknown")
            st.failures = 0
            if st.status in ("unhealthy", "closing"):
                if st.unhealthy_since is None:
                    st.unhealthy_since = now
                dwell = now - st.unhealthy_since
            else:
                st.unhealthy_since = None
                dwell = 0.0
        if dwell > self.unhealthy_dwell:
            self._fail_over(st.name,
                            reason=f"{st.status}_past_dwell")

    def _pull_trace(self, st: "_EngineState") -> None:
        """Advance one engine's ``/trace`` cursor: append its new span
        segment to the router-side buffer (bounded like the source
        ring, same eviction-means-gap accounting) and, once per
        engine, estimate the clock offset from a ping handshake."""
        if st.clock_offset is None:
            st.clock_offset, st.offset_jitter = \
                self._clock_handshake(st.client)
        tr = st.client.trace_since(st.trace_cursor)
        st.trace_cursor = tr.get("cursor", st.trace_cursor)
        st.trace_dropped += int(tr.get("dropped", 0) or 0)
        st.trace_events.extend(tr.get("events", ()))
        del st.trace_events[:-_trace.DEFAULT_CAPACITY]

    @staticmethod
    def _clock_handshake(client,
                         probes: int = 5) -> "tuple[float, float]":
        """Estimate ``engine_clock - router_clock`` by the midpoint
        method: each ping reads the engine's wall ``ts`` between local
        stamps t0/t1, giving ``offset = ts - (t0 + t1)/2``; the probe
        with the smallest round trip wins and its half-RTT bounds the
        asymmetry error (the recorded jitter). A reply with no ``ts``
        (an older gateway) contributes nothing; all-failed probes fall
        back to (0, 0) — same-host fleets, the bench topology, are
        near-0 anyway and the jitter says how much to trust it."""
        best = None
        for _ in range(max(int(probes), 1)):
            t0 = time.time()
            try:
                pong = client.ping()
            except Exception:
                continue
            t1 = time.time()
            ts = pong.get("ts")
            if not isinstance(ts, (int, float)):
                continue
            rtt = max(t1 - t0, 0.0)
            off = float(ts) - (t0 + t1) / 2.0
            if best is None or rtt < best[0]:
                best = (rtt, off)
        if best is None:
            return 0.0, 0.0
        return best[1], best[0] / 2.0

    def _is_dead(self, name: "str | None") -> bool:
        with self._mu:
            st = self._states.get(name)
            return st is not None and st.dead

    def _note_failure(self, name: str, reason: str) -> None:
        with self._mu:
            st = self._states.get(name)
            if st is None or st.dead:
                return
            st.failures += 1
            tripped = st.failures >= self.fail_threshold
        if tripped:
            self._fail_over(name, reason=f"unreachable ({reason})")

    # ------------------------------------------------------- failover
    def _fail_over(self, name: str, reason: str) -> None:
        """Declare ``name`` dead and move its work: fence the journal,
        replay admitted-but-unresolved entries on a surviving peer
        (original idempotency keys — exactly once), re-point affected
        tickets. Idempotent: the first caller wins."""
        with self._mu:
            st = self._states.get(name)
            if st is None or st.dead:
                return
            st.dead = True
            st.status = "dead"
        telemetry.counter("fleet.failovers").inc()
        log = get_logger()
        log.warning("fleet: engine %r declared DEAD (%s); failing "
                    "over", name, reason)
        durable = st.client.durable_dir
        if durable:
            try:
                fence_journal(durable, owner=f"router:{os.getpid()}")
                _events.emit("fence", engine=name,
                             owner=f"router:{os.getpid()}")
                # mark the barrier on the router's trace track too:
                # the stitched timeline shows the victim go quiet,
                # THE FENCE, then the survivor's replay hops
                _trace.instant("fleet.fence", cat="fleet",
                               engine=name, reason=reason)
            except OSError as e:  # pragma: no cover - fs failure
                log.error("fleet: could not fence %s: %s", durable, e)
        replayed, lost = self._replay_journal(st, durable)
        done_at = time.monotonic()
        with self._mu:
            self._failovers.append({
                "engine": name, "reason": reason,
                "replayed": replayed, "lost": lost,
                "completed_ts": done_at})
        _events.emit("failover", engine=name, reason=reason,
                     replayed=replayed, lost=lost)
        log.warning("fleet: failover of %r complete — %d request(s) "
                    "replayed, %d lost", name, replayed, lost)

    def _unresolved_entries(self, durable: "str | None") -> \
            "tuple[list[dict], list[dict]]":
        """(replayable, unreplayable) journal entries the fleet still
        owes an answer for. Beyond the journal's own incomplete set
        (no ``done`` line), an entry that journaled done but whose
        result the ROUTER never delivered is also unresolved — the
        value died with the engine's memory, so exactly-once yields to
        never-lost and the entry re-executes under its original key."""
        if not durable:
            return [], []
        replayable, unreplayable = RequestJournal.incomplete(durable)
        have = {e.get("key") for e in replayable}
        with self._mu:
            undelivered = {
                k for k, t in self._tickets.items()
                if k not in self._acks and k not in self._failures}
        for e in RequestJournal.read(durable):
            if e.get("kind") != "admit" or e.get("key") in have:
                continue
            if e.get("key") in undelivered:
                (replayable if e.get("replayable") and e.get("name")
                 else unreplayable).append(e)
                have.add(e.get("key"))
        return replayable, unreplayable

    def _replay_journal(self, dead: "_EngineState",
                        durable: "str | None") -> "tuple[int, int]":
        replayable, unreplayable = self._unresolved_entries(durable)
        replayed = lost = 0
        for e in unreplayable:
            # admitted (= acknowledged) but not expressible as a named
            # query: nothing can re-run it. This is the one genuinely
            # lossy shape — counted, never silent.
            lost += 1
            telemetry.counter("fleet.lost_acks",
                              tenant=e.get("tenant", "default")).inc()
            log = get_logger()
            log.error("fleet: journal entry rid=%s on dead engine %r "
                      "is unreplayable (bare callable / non-JSON "
                      "args) — the acknowledged request is lost",
                      e.get("rid"), dead.name)
        for e in replayable:
            key = e.get("key")
            with self._mu:
                if key is not None and (key in self._acks
                                        or key in self._failures):
                    continue  # outcome already delivered via router
            tenant = e.get("tenant", "default")
            # the replayed request keeps its ORIGINAL trace id (the
            # dead engine's journal recorded it at admission): one id
            # names router admission, the dead engine's partial run,
            # the fence, and the survivor's re-run — with this hop
            # marker stitching the two engine tracks together
            tid = e.get("trace_id")
            try:
                with self._mu:
                    peer = self._pick_locked(tenant)
                with _trace.trace_context(tid):
                    rid = peer.client.submit(
                        e["name"], args=e.get("args", ()),
                        kwargs=e.get("kwargs", {}), tenant=tenant,
                        priority=e.get("priority", 1),
                        slo=e.get("slo"), key=key,
                        tables=e.get("tables", ()), trace_id=tid)
                    if tid is not None:
                        _trace.instant("fleet.replay_hop",
                                       cat="fleet", engine=peer.name,
                                       key=key)
            except Exception as exc:
                lost += 1
                get_logger().error(
                    "fleet: replay of %r from dead engine %r failed: "
                    "%s", key or e.get("rid"), dead.name, exc)
                t = (self._tickets.get(key)
                     if key is not None else None)
                if t is not None:
                    # _mark_lost owns the lost_acks count (once)
                    t._mark_lost(
                        f"engine {dead.name!r} died and the "
                        f"replay on a peer failed: {exc}")
                else:  # journal-only entry: no ticket to carry it
                    telemetry.counter("fleet.lost_acks",
                                      tenant=tenant).inc()
                continue
            replayed += 1
            telemetry.counter("fleet.replayed", tenant=tenant).inc()
            with self._mu:
                self._replayed_keys.append(key)
                ticket = self._tickets.get(key)
            if ticket is not None:
                ticket._assign(peer.client, rid)
        # any router ticket still pointing at the dead engine with no
        # journal entry cannot exist (submit acks only after the
        # write-ahead line) — but belt-and-braces: mark them lost
        # rather than letting result() spin forever
        with self._mu:
            stranded = [
                t for k, t in self._tickets.items()
                if k not in self._acks and k not in self._failures
                and t.engine == dead.name]
        for t in stranded:
            lost += 1
            t._mark_lost(f"engine {dead.name!r} died with no "
                         "replayable journal entry for this key")
        return replayed, lost

    # ------------------------------------------------------- report
    def report(self) -> dict:
        with self._mu:
            return {
                "engines": [s.snapshot()
                            for s in self._states.values()],
                "tickets": len(self._tickets),
                "acked": len(self._acks),
                "failed": len(self._failures),
                "failovers": list(self._failovers),
                "replayed_keys": list(self._replayed_keys),
                "routed": telemetry.total("fleet.routed"),
                "deduped": telemetry.total("fleet.deduped"),
                "lost_acks": telemetry.total("fleet.lost_acks"),
            }

    def fleet_trace_buffers(self, drain: bool = True) -> "list[dict]":
        """Per-PROCESS trace buffers for
        :func:`cylon_tpu.telemetry.trace.merge_timelines`: the
        router's own recorder as the reference track (offset 0) plus
        every engine's pulled ``/trace`` segments on its
        handshake-estimated clock offset. ``drain`` pulls each
        engine's cursor once more first, so spans emitted after the
        last poll tick are included — call BEFORE :meth:`close` while
        survivors still answer (a dead engine's tail was pulled when
        it still lived, or is part of the gap accounting)."""
        with self._mu:
            states = list(self._states.values())
        if drain and self._trace_armed:
            for st in states:
                try:
                    self._pull_trace(st)
                except Exception:
                    pass  # dead engine: keep what the polls got
        bufs = [{"proc": "router", "pid": os.getpid(),
                 "clock_offset": 0.0, "offset_jitter": 0.0,
                 "dropped": _trace.dropped(),
                 "events": _trace.events()}]
        for st in states:
            bufs.append({
                "proc": st.name,
                "pid": getattr(st.client, "pid", None),
                "clock_offset": st.clock_offset or 0.0,
                "offset_jitter": st.offset_jitter,
                "dropped": st.trace_dropped,
                "events": list(st.trace_events)})
        return bufs


# ----------------------------------------------------- engine process
def _mk_fleet_query(cq, resident, env):
    """A registered named query for one fleet engine: step 1 dispatches
    the compiled program, step 2 materialises to the host (the same
    staged shape serve.bench uses, so requests interleave)."""
    from cylon_tpu.serve.bench import _materialize

    def run():
        out = cq(resident, env=env)
        yield
        return _materialize(out)

    return run


def _mk_fleet_fallback(query: str, data):
    """The registered spill path for one fleet query: the partitioned
    host fallback (the two-phase plan for global-aggregate queries
    like q14). Registered — not per-submit — so a journal REPLAY after
    a failover re-arms it automatically: a replayed request that OOMs
    on the survivor recomputes its merge scalar there instead of
    trusting anything from the dead engine's journal."""
    from cylon_tpu import fallback

    def run():
        # eager per-partition execution: the spill path must not
        # re-enter the compiled-dispatch layer that just exhausted
        # memory (it would OOM again under the same pressure). The
        # result is already HOST-shaped (pandas frame / float) — the
        # same client-visible shape _materialize gives the compiled
        # path.
        out = fallback.tpch_fallback(query, data, compiled=False)
        return out if hasattr(out, "columns") else float(out)

    return run


def _engine_main(args) -> int:
    """One fleet engine process: resident TPC-H tables on its own
    mesh, named queries registered for the gateway, durable dir at
    ``<root>/engines/<name>`` with the shared snapshot store. Prints
    one ``FLEET_ENGINE_READY {json}`` line, then serves until
    SIGTERM/SIGINT (clean close — journal lock released)."""
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    os.environ.setdefault("CYLON_TPU_SERVE_HTTP_PORT", "0")

    import cylon_tpu as ct
    from cylon_tpu import tpch
    from cylon_tpu.serve import ServeEngine
    from cylon_tpu.serve.bench import _mk_resident
    from cylon_tpu.tpch import dbgen

    # chaos harness hooks (same env contract as tests/test_chaos.py):
    # CHAOS_KILL=point:nth installs a process-wide FaultRule.kill so
    # the engine hard-dies (rc 43) at a seeded mid-query instant;
    # CHAOS_OOM=point:nth makes every hit from nth on raise
    # MemoryError — each dispatch exhausts memory, so every request
    # completes through its registered spill fallback (the degraded
    # path, including replayed requests after a failover)
    rules = []
    kill = os.environ.get("CHAOS_KILL")
    if kill:
        point, nth = kill.rsplit(":", 1)
        rules.append(resilience.FaultRule.kill(point, nth=int(nth)))
    oom = os.environ.get("CHAOS_OOM")
    if oom:
        point, nth = oom.rsplit(":", 1)
        rules.append(resilience.FaultRule(
            point, nth=int(nth), times=0,
            error=MemoryError("injected OOM (CHAOS_OOM)")))
    if rules:
        resilience.install(resilience.FaultPlan(rules))

    layout = FleetLayout(args.root)
    env = ct.CylonEnv(ct.TPUConfig())
    data = dbgen.generate(args.sf, args.seed)
    resident = _mk_resident(env, data)
    engine = ServeEngine(env,
                         durable_dir=layout.engine_dir(args.name),
                         snapshot_dir=layout.snapshot_dir)
    for nm, df in resident.items():
        engine.register_table(f"tpch/{nm}", df)
    mix = tuple(q.strip() for q in args.mix.split(",") if q.strip())
    for q in mix:
        reads = QUERY_READ_SETS.get(q, tuple(resident))
        engine.register_query(q, _mk_fleet_query(tpch.compiled(q),
                                                 resident, env),
                              fallback=_mk_fleet_fallback(q, data),
                              tables=[f"tpch/{nm}" for nm in reads
                                      if nm in resident])
    gateway = EngineGateway(engine, port=args.gateway_port)
    ready = {"name": args.name, "pid": os.getpid(),
             "gateway": list(gateway.address),
             "introspect": (list(engine.http_address)
                            if engine.http_address else None),
             "durable_dir": engine.durable_dir, "mix": list(mix)}
    print("FLEET_ENGINE_READY " + json.dumps(ready), flush=True)

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *a: stop.set())
    while not stop.is_set():
        stop.wait(0.5)
    engine.close(wait=True)
    gateway.close()
    return 0


class EngineProc:
    """A spawned fleet engine process + its router-side client."""

    def __init__(self, name: str, proc, client: HttpEngineClient,
                 log_path: str):
        self.name = name
        self.proc = proc
        self.client = client
        self.log_path = log_path

    @property
    def pid(self) -> int:
        return self.proc.pid

    def kill(self, sig=signal.SIGKILL) -> None:
        """The chaos hammer: SIGKILL by default — no cleanup, no lock
        release, exactly like a preemption."""
        os.kill(self.proc.pid, sig)

    def terminate(self, timeout: float = 60.0) -> "int | None":
        if self.proc.poll() is None:
            self.proc.terminate()
        try:
            return self.proc.wait(timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            return self.proc.wait(10)


def spawn_engine(root: str, name: str, sf: float = 0.002,
                 seed: int = 0, mix=DEFAULT_MIX,
                 env_extra: "dict | None" = None,
                 ready_timeout: float = 300.0) -> EngineProc:
    """Spawn ``python -m cylon_tpu.serve.fleet`` as one engine process
    under ``root`` and wait for its READY line. The child's stderr
    streams to ``<root>/<name>.log`` (post-mortem evidence); stdout is
    drained by a daemon thread after the handshake."""
    os.makedirs(root, exist_ok=True)
    log_path = os.path.join(root, f"{name}.log")
    cmd = [sys.executable, "-m", "cylon_tpu.serve.fleet",
           "--root", str(root), "--name", str(name),
           "--sf", str(sf), "--seed", str(seed),
           "--mix", ",".join(mix)]
    child_env = dict(os.environ)
    child_env.setdefault("CYLON_TPU_SERVE_HTTP_PORT", "0")
    child_env.setdefault("CYLON_TPU_EVENTS", "1")
    # a compiled query's FIRST dispatch traces + compiles for tens of
    # seconds on a small host, holding the single-step scheduler the
    # whole time — /health's stall probe must not read warm-up compile
    # as a wedged scheduler (the router would dwell it to death)
    child_env.setdefault("CYLON_TPU_SERVE_STALL_AGE", "120")
    child_env.pop("CHAOS_KILL", None)
    child_env.pop("CHAOS_OOM", None)
    child_env.update(env_extra or {})
    logf = open(log_path, "ab")
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, stderr=logf,
                            env=child_env, text=True)
    logf.close()  # the child holds its own descriptor now

    # the handshake read rides a daemon reader thread so ready_timeout
    # is ENFORCED — a child wedged before printing READY (stuck
    # compile, hung import) must not block the spawner forever; the
    # same thread keeps draining stdout afterwards so the pipe never
    # fills
    import queue as _queue

    lines: "_queue.Queue" = _queue.Queue(maxsize=1024)

    def _reader():
        for line in proc.stdout:
            try:
                lines.put_nowait(line)
            except _queue.Full:  # post-handshake chatter: discard,
                pass             # never let the pipe back up
        try:
            lines.put_nowait(None)  # EOF sentinel
        except _queue.Full:
            pass

    threading.Thread(target=_reader, daemon=True,
                     name=f"fleet-spawn-{name}").start()
    deadline = time.monotonic() + ready_timeout
    ready = None
    while ready is None:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            proc.kill()
            raise EngineUnavailable(
                f"fleet engine {name!r} never reported READY within "
                f"{ready_timeout}s; see {log_path}")
        try:
            line = lines.get(timeout=min(remaining, 1.0))
        except _queue.Empty:
            continue
        if line is None:
            raise EngineUnavailable(
                f"fleet engine {name!r} died before READY "
                f"(rc={proc.poll()}); see {log_path}")
        if line.startswith("FLEET_ENGINE_READY "):
            ready = json.loads(line.split(" ", 1)[1])
    client = HttpEngineClient(
        name, gateway_url="http://%s:%d" % tuple(ready["gateway"]),
        introspect_url=("http://%s:%d" % tuple(ready["introspect"])
                        if ready.get("introspect") else None),
        durable_dir=ready["durable_dir"], pid=ready["pid"])
    return EngineProc(name, proc, client, log_path)


# ----------------------------------------------------- fleet bench
def _phase_p99s(samples: "list[tuple[float, float, float]]",
                kill_ts: "float | None",
                recovered_ts: "float | None") -> dict:
    """p99 request walls by phase relative to the outage window
    ``[kill_ts, recovered_ts]``: *before* = completed before the kill,
    *during* = the request's lifetime OVERLAPPED the outage (it was in
    flight when the engine died, or started before the failover
    finished — the set the kill could actually hurt), *after* =
    submitted after the failover completed. ``samples`` are
    (start, end, wall) triples; phases with no population report
    None."""
    import numpy as np

    def p99(walls):
        if not walls:
            return None
        return float(np.quantile(np.asarray(walls), 0.99))

    if kill_ts is None:
        return {"before": p99([w for _, _, w in samples]),
                "during": None, "after": None}
    hi = recovered_ts if recovered_ts is not None else kill_ts
    return {
        "before": p99([w for s, e, w in samples if e < kill_ts]),
        "during": p99([w for s, e, w in samples
                       if e >= kill_ts and s <= hi]),
        "after": p99([w for s, e, w in samples if s > hi]),
    }


def audit_double_executions(layout: FleetLayout,
                            replayed_keys) -> "tuple[int, dict]":
    """Cross-journal exactly-once audit: a key with more than one
    ``done(state=done)`` line across the fleet's journals executed
    more than once. Keys the router knowingly re-executed (a completed
    result that died undelivered — never-lost beats exactly-once
    there) are excluded; everything else is a real double-execution."""
    done_counts: "dict[str, int]" = {}
    for name in layout.engine_names():
        for e in RequestJournal.read(layout.engine_dir(name)):
            if e.get("kind") == "done" and e.get("state") == "done" \
                    and e.get("key"):
                done_counts[e["key"]] = done_counts.get(e["key"],
                                                        0) + 1
    allowed = set(k for k in (replayed_keys or ()) if k)
    doubles = {k: n for k, n in done_counts.items()
               if n > 1 and k not in allowed}
    return len(doubles), doubles


def _fleet_trace_artifact(router: "FleetRouter", root: str) -> dict:
    """Collect and stitch the fleet's per-process trace buffers (call
    BEFORE the router closes — the final cursor drain wants live
    survivors) and write the Chrome Trace artifact under ``root``.
    Returns the ``--fleet-trace`` record fields
    (:data:`cylon_tpu.serve.bench.REQUIRED_FLEET_TRACE_FIELDS`) plus
    the stitched report of the headline request — when the chaos kill
    produced a failover replay, that request's SINGLE trace id spans
    router admission, the dead engine's partial run, and the
    survivor's replay hop."""
    from cylon_tpu.telemetry.export import write_chrome_trace

    bufs = router.fleet_trace_buffers()
    merged = _trace.merge_timelines(bufs)
    path = write_chrome_trace(
        os.path.join(root, "fleet_trace.trace.json"), bufs)
    jitters = [b.get("offset_jitter") for b in bufs[1:]
               if isinstance(b.get("offset_jitter"), (int, float))]
    hops = [e for e in merged if e.get("name") == "fleet.replay_hop"]
    hop_tids = sorted({e.get("trace_id") for e in hops
                       if e.get("trace_id")})
    stitched = None
    if hop_tids:
        # several requests may have replayed; headline the one whose
        # events survive on the MOST process tracks (a dead engine's
        # unpulled ring segments die with it — some replayed traces
        # keep the victim's partial run, some don't), ties broken by
        # event count then tid for determinism
        def _coverage(tid):
            evs = [e for e in merged if e.get("trace_id") == tid]
            return (len({e.get("proc") for e in evs}), len(evs))

        best = max(sorted(hop_tids), key=_coverage)
        stitched = _trace.fleet_request_report(merged, best)
    else:  # no failover this run: report the busiest trace instead
        by_tid: "dict[str, int]" = {}
        for e in merged:
            t = e.get("trace_id")
            if t:
                by_tid[t] = by_tid.get(t, 0) + 1
        if by_tid:
            top = max(sorted(by_tid), key=lambda t: by_tid[t])
            stitched = _trace.fleet_request_report(merged, top)
    return {
        "trace_path": path,
        "spans": sum(1 for e in merged if e.get("kind") == "begin"),
        "engines_stitched": sum(1 for b in bufs[1:]
                                if b.get("events")),
        "offset_jitter_s": (round(max(jitters), 6) if jitters
                            else None),
        "replay_hops": len(hops),
        "trace_dropped": sum(int(b.get("dropped", 0) or 0)
                             for b in bufs),
        "stitched_request": stitched,
    }


def _fleet_history_check(layout: FleetLayout, mix) -> dict:
    """Audit the query-profile cost model against the run it just
    learned from: merge the engines' persisted histories (each engine
    saved ``profile_history.json`` at clean close; a SIGKILLed one
    never did — the merge reads what survived) and compare each mix
    query's ``predicted_wall_s`` against the mean of its own executed
    walls. The ISSUE 20 acceptance gates the prediction within 2x of
    actual — measured against real executions, not against a probe
    request that would resolve from the result cache."""
    from cylon_tpu.telemetry import profile as _profile

    paths = [os.path.join(layout.engine_dir(n), _profile.HISTORY_FILE)
             for n in layout.engine_names()]
    paths = [p for p in paths if os.path.exists(p)]
    hist = _profile.merged_history(paths)
    checks: "dict[str, dict | None]" = {}
    for q in mix:
        # fleet queries take no arguments: the engine-side fingerprint
        # at record time is the same canonical hash over (name, (), {})
        fp = plan.query_fingerprint(q, (), {})
        est = hist.predict(fp) if fp is not None else None
        if est is None or not est.get("samples"):
            checks[q] = None
            continue
        mean = float(est["mean_wall_s"])
        pred = float(est["predicted_wall_s"])
        checks[q] = {
            "predicted_wall_s": round(pred, 4),
            "actual_mean_wall_s": round(mean, 4),
            "samples": est["samples"],
            "within_2x": bool(mean > 0
                              and 0.5 <= pred / mean <= 2.0),
        }
    return {"history_files": len(paths), "queries": checks}


def run_fleet_bench(clients: int = 16, requests: int = 3,
                    sf: float = 0.002, seed: int = 0,
                    mix=DEFAULT_MIX, engines: int = 2,
                    kill_mid_run: bool = True,
                    root: "str | None" = None,
                    result_timeout: float = 600.0,
                    fleet_trace: bool = False) -> dict:
    """The ISSUE 15 measured acceptance: ≥2 engine processes over one
    durable tree, N concurrent clients replaying the TPC-H mix through
    the router, one engine SIGKILLed mid-run. Every ticket the router
    acknowledged must complete oracle-exact (0 lost acks), nothing may
    double-execute, and the record carries the windowed p99 before /
    during / after the kill. Returns the record
    (:data:`cylon_tpu.serve.bench.REQUIRED_FLEET_FIELDS`)."""
    import tempfile

    import numpy as np  # noqa: F401  (quantiles in _phase_p99)

    import cylon_tpu as ct
    from cylon_tpu import tpch
    from cylon_tpu.serve.bench import (_materialize, _mk_resident,
                                       _results_match)
    from cylon_tpu.tpch import dbgen

    if engines < 2:
        raise InvalidArgument(
            f"a fleet needs >= 2 engines, got {engines}")
    if fleet_trace:
        # arm the flight recorder fleet-wide: this (router) process
        # plus — via env inheritance and the explicit extra below —
        # every spawned engine. The leg is opt-in, so mutating the
        # env here mirrors how the storm leg arms CYLON_TPU_EVENTS.
        os.environ["CYLON_TPU_TRACE"] = "1"
    root = root or os.environ.get("CYLON_BENCH_FLEET_DIR") \
        or tempfile.mkdtemp(prefix="cylon_fleet_")
    layout = FleetLayout(root)
    mix = tuple(mix)

    # oracles: each mix query once, alone, in THIS process — every
    # fleet-routed result must reproduce them exactly
    env = ct.CylonEnv(ct.TPUConfig())
    data = dbgen.generate(sf, seed)
    resident = _mk_resident(env, data)
    oracles = {q: _materialize(tpch.compiled(q)(resident, env=env))
               for q in mix}

    # every spawned engine is terminated on ANY exit path — a
    # mid-bench exception must not leak live engine processes (ports,
    # journal locks, resident meshes) onto the host
    procs: "list[EngineProc]" = []
    router = None
    try:
        for i in range(engines):
            procs.append(spawn_engine(
                root, f"e{i}", sf=sf, seed=seed, mix=mix,
                env_extra={"CYLON_TPU_TRACE": "1"} if fleet_trace
                else None))
        # SIGKILL detection rides connection-refused polls (threshold
        # 3 at 0.25s — ~1s to DEAD); the dwell only governs
        # verdict-based failover and is deliberately generous so a
        # host saturated by 16 concurrent compiles is not misread as
        # an outage
        router = FleetRouter([p.client for p in procs],
                             poll_interval=0.25, fail_threshold=3,
                             unhealthy_dwell=45.0)
        return _drive_fleet_bench(
            router, procs, layout, oracles, clients=clients,
            requests=requests, sf=sf, mix=mix,
            kill_mid_run=kill_mid_run, root=root,
            result_timeout=result_timeout, fleet_trace=fleet_trace)
    finally:
        if router is not None:
            router.close()
        for p in procs:
            try:
                p.terminate()
            except Exception:  # pragma: no cover - teardown best-effort
                pass


def _drive_fleet_bench(router, procs, layout, oracles, *, clients,
                       requests, sf, mix, kill_mid_run, root,
                       result_timeout, fleet_trace=False) -> dict:
    """The measured body of :func:`run_fleet_bench` (engines/router
    lifecycle owned by the caller's try/finally)."""
    import numpy as np  # noqa: F401  (quantiles in _phase_p99s)

    from cylon_tpu.serve.bench import _results_match

    t0 = time.perf_counter()
    samples: "list[tuple[float, float, float]]" = []  # (start, end, wall)
    mismatches: list = []
    errors: list = []
    completed = [0]
    shed = [0]
    lock = threading.Lock()
    kill_ts = [None]
    total = clients * requests
    kill_at = max(total // 3, 1)  # after ~1/3 of acks land

    def client_thread(i: int):
        # sequential submit→result per client (one outstanding request
        # each): submissions spread across the whole run, so the
        # before/during/after phase populations all exist
        tenant = f"tenant{i}"
        for r in range(requests):
            q = mix[(i + r) % len(mix)]
            key = f"c{i}-r{r}"
            try:
                tk = router.submit(q, tenant=tenant,
                                   idempotency_key=key)
                got = tk.result(result_timeout)
            except Exception as e:
                with lock:
                    if isinstance(e, (ResourceExhausted,
                                      EngineUnavailable)):
                        shed[0] += 1
                    errors.append((key,
                                   f"{type(e).__name__}: {e}"))
                continue
            end = time.monotonic()
            with lock:
                samples.append((tk.submitted, end,
                                end - tk.submitted))
                completed[0] += 1
            if not _results_match(got, oracles[q]):
                with lock:
                    mismatches.append((key, q))

    def killer():
        # wait until ~1/3 of the run completed, then SIGKILL e0
        while True:
            with lock:
                if completed[0] >= kill_at:
                    break
            if all(not th.is_alive() for th in threads):
                return  # run ended (e.g. everything shed) — no kill
            time.sleep(0.05)
        kill_ts[0] = time.monotonic()
        get_logger().warning("fleet bench: SIGKILL engine %r (pid "
                             "%d) mid-run", procs[0].name,
                             procs[0].pid)
        procs[0].kill()

    threads = [threading.Thread(target=client_thread, args=(i,),
                                name=f"fleet-client-{i}")
               for i in range(clients)]
    kt = (threading.Thread(target=killer, name="fleet-killer")
          if kill_mid_run else None)
    for th in threads:
        th.start()
    if kt is not None:
        kt.start()
    for th in threads:
        th.join()
    if kt is not None:
        kt.join()
    wall = time.perf_counter() - t0

    rep = router.report()
    recovered_ts = (rep["failovers"][0]["completed_ts"]
                    if rep["failovers"] else None)

    # the post-failover idempotent-retry probe: re-submit an already-
    # completed key through the router — it must come back from the
    # ack cache without executing anywhere (the ISSUE 15 "a retried
    # one never double-executes" half, measured)
    retry_deduped = None
    if samples:
        probe_key = "c0-r0"
        before = telemetry.total("fleet.deduped")
        try:
            router.submit(mix[0], tenant="tenant0",
                          idempotency_key=probe_key).result(30)
            retry_deduped = telemetry.total("fleet.deduped") > before
        except Exception as e:  # pragma: no cover - probe best-effort
            retry_deduped = False
            errors.append(("retry_probe",
                           f"{type(e).__name__}: {e}"))

    # the stitched trace must be collected while survivors still
    # answer /trace (the final cursor drain) and before the poll
    # loops stop
    trace_extra = (_fleet_trace_artifact(router, root)
                   if fleet_trace else None)
    # stop the poll loop BEFORE terminating survivors (a still-running
    # poll would read the graceful shutdown as one more "failover"),
    # then stop the engines so their journals are quiescent to audit
    router.close()
    for p in procs:
        p.terminate()
    doubles, double_detail = audit_double_executions(
        layout, rep["replayed_keys"])
    record = {
        "metric": "fleet_bench_tpch_mix",
        "engines": len(procs),
        "clients": clients,
        "requests_total": total,
        "completed": completed[0],
        "shed": shed[0],
        "wall_s": round(wall, 3),
        "sf": sf,
        "mix": list(mix),
        "kill": ("sigkill_mid_run" if kill_mid_run else None),
        "failovers": len(rep["failovers"]),
        "failover_detail": [
            {k: v for k, v in f.items() if k != "completed_ts"}
            for f in rep["failovers"]],
        "replayed": telemetry.total("fleet.replayed"),
        "lost_acks": rep["lost_acks"],
        "routed": rep["routed"],
        "deduped": rep["deduped"],
        "retry_deduped": retry_deduped,
        "double_executions": doubles,
        "double_execution_detail": double_detail,
        "oracle_mismatches": len(mismatches),
        "mismatch_detail": mismatches[:8],
        "errors": len(errors),
        "error_detail": errors[:8],
        "p99_before_s": None,
        "p99_during_s": None,
        "p99_after_s": None,
        "fleet_root": root,
        # the shared store's per-table generation stamps (quiescent —
        # engines are down): what a failover recover() would restore
        "table_generations": snapshot_generations(root),
    }
    phases = _phase_p99s(samples, kill_ts[0], recovered_ts)
    record.update(p99_before_s=phases["before"],
                  p99_during_s=phases["during"],
                  p99_after_s=phases["after"])
    for k in ("p99_before_s", "p99_during_s", "p99_after_s"):
        if record[k] is not None:
            record[k] = round(record[k], 4)
    if trace_extra is not None:
        record.update(trace_extra)
        # the engines just closed cleanly (terminate → SIGTERM →
        # engine.close saves profile_history.json), so the merged
        # query-profile history is on disk to audit the cost model
        record["cost_model"] = _fleet_history_check(layout, mix)
    return record


# ----------------------------------------------------------- __main__
def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="run ONE fleet engine process (the fleet bench / "
                    "chaos harness spawns these; humans usually want "
                    "`python -m cylon_tpu.serve.bench --fleet`)")
    p.add_argument("--root", required=True,
                   help="fleet durable root (FleetLayout)")
    p.add_argument("--name", required=True, help="engine name")
    p.add_argument("--sf", type=float, default=0.002)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--mix", default=",".join(DEFAULT_MIX))
    p.add_argument("--gateway-port", type=int, default=0)
    return _engine_main(p.parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
