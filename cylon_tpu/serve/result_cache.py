"""Versioned result cache: serve a hot query once per table version.

The serving layer's second dedup level (the first is
:func:`cylon_tpu.plan.shared_compiled`, which dedupes the *trace*):
completed results are stored under ``(query fingerprint, table-version
vector)`` and served straight from admission — a hot query never
touches the scheduler, the mesh, or the breaker again until one of its
tables mutates.

**Keying is the whole contract.** The fingerprint
(:func:`cylon_tpu.plan.query_fingerprint`) identifies *what* was asked;
the version vector — a sorted tuple of ``(table_id, generation,
content digest)`` from :func:`cylon_tpu.catalog.table_version` —
identifies *which data* answered it. Both halves are REQUIRED
positional arguments of :meth:`ResultCache.lookup` /
:meth:`ResultCache.store`, and a bench-guard AST lint walks every call
site in the tree asserting the vector is actually passed: a lookup
keyed on the fingerprint alone would happily serve pre-append bytes
after an append, which is exactly the staleness bug this keying
exists to make unrepresentable. A query with NO declared tables has no
version vector (``versions=None``) and is therefore uncacheable by
construction — lookups miss, stores are dropped.

**Invalidation is precise, not temporal.** Entries are indexed by the
table ids in their vector; :meth:`invalidate_table` — wired to
:func:`cylon_tpu.catalog.on_append` by the engine (and to the
``append`` event stream by the fleet router) — evicts exactly the
entries that read the mutated table. There is no TTL: an entry is
correct until its inputs change, and wrong immediately after.

**Bounded.** Byte-budgeted LRU (``CYLON_TPU_SERVE_RESULT_CACHE_BYTES``
engine-side, ``CYLON_TPU_FLEET_RESULT_CACHE_BYTES`` router-side;
``0`` disables). Counters ride telemetry as
``{prefix}.result_cache_{hits,misses,invalidations,evictions}``.
"""

import collections
import os
import sys
import threading

from cylon_tpu import telemetry

__all__ = ["ResultCache", "DEFAULT_CACHE_BYTES", "cache_bytes_from_env",
           "hook_on_append", "value_nbytes", "version_vector"]

#: default byte budget for a result cache (generous for scalar/frame
#: TPC-H answers; bound the hoard, not the hit rate)
DEFAULT_CACHE_BYTES = 256 * 2**20


def cache_bytes_from_env(var: str) -> int:
    """Read a cache byte budget from ``var`` (defensive parse — a
    malformed value falls back to the default rather than failing an
    engine construction). ``0``/negative disables the cache."""
    try:
        return int(os.environ.get(var, str(DEFAULT_CACHE_BYTES)))
    except ValueError:
        return DEFAULT_CACHE_BYTES


def version_vector(table_ids) -> "tuple | None":
    """The version half of the cache key: a SORTED tuple of
    ``(table_id, generation, digest)`` over ``table_ids``, from
    :func:`cylon_tpu.catalog.table_version`. None — uncacheable — when
    no tables are declared or any of them is not resident (a request
    whose read set the engine cannot version must never be deduped)."""
    from cylon_tpu import catalog

    ids = sorted(set(str(t) for t in table_ids or ()))
    if not ids:
        return None
    vec = []
    try:
        for tid in ids:
            v = catalog.table_version(tid)
            vec.append((tid, int(v["generation"]), str(v["digest"])))
    except KeyError:
        return None
    return tuple(vec)


def value_nbytes(value) -> int:
    """Byte-size estimate of a cached result: device buffer bytes for
    Tables/DataFrames, ``.nbytes`` for arrays, recursive for
    containers, ``sys.getsizeof`` otherwise. An estimate is enough —
    the budget bounds the hoard, it is not an allocator."""
    from cylon_tpu import catalog as _catalog
    from cylon_tpu.table import Table

    t = getattr(value, "table", value)
    if isinstance(t, Table):
        return _catalog.table_nbytes(t)
    if isinstance(value, (str, bytes, bytearray)):
        return len(value)
    if isinstance(value, dict):
        return sum(value_nbytes(k) + value_nbytes(v)
                   for k, v in value.items()) + 64
    if isinstance(value, (list, tuple, set, frozenset)):
        return sum(value_nbytes(v) for v in value) + 64
    nb = getattr(value, "nbytes", None)
    if isinstance(nb, int):
        return nb
    try:
        return sys.getsizeof(value)
    except TypeError:  # pragma: no cover - exotic __sizeof__
        return 64


class ResultCache:
    """Byte-budgeted, version-keyed LRU of completed query results.

    Thread-safe; shared by client threads (admission-time lookups) and
    the scheduler thread (stores at retirement) on the engine, and by
    submitter + poller threads on the fleet router."""

    def __init__(self, max_bytes: int, *, metric_prefix: str = "serve"):
        self.max_bytes = int(max_bytes)
        self._prefix = str(metric_prefix)
        self._mu = threading.Lock()
        #: (fingerprint, versions) -> (value, nbytes), LRU order
        self._entries: "collections.OrderedDict" = \
            collections.OrderedDict()
        #: table_id -> set of keys whose vector reads it (the precise
        #: invalidation index on_append drives)
        self._by_table: "dict[str, set]" = {}
        self._bytes = 0

    @property
    def enabled(self) -> bool:
        return self.max_bytes > 0

    # ------------------------------------------------------------ read
    def lookup(self, fingerprint, versions):
        """``(hit, value)`` for ``(fingerprint, versions)`` — BOTH key
        halves are required (the bench-guard AST lint pins that every
        call site passes the version vector; see module docstring). A
        None fingerprint or None vector is uncacheable: always a
        miss."""
        if (not self.enabled or fingerprint is None
                or versions is None):
            telemetry.counter(
                f"{self._prefix}.result_cache_misses").inc()
            return False, None
        key = (fingerprint, tuple(versions))
        with self._mu:
            ent = self._entries.get(key)
            if ent is not None:
                self._entries.move_to_end(key)
        if ent is None:
            telemetry.counter(
                f"{self._prefix}.result_cache_misses").inc()
            return False, None
        telemetry.counter(f"{self._prefix}.result_cache_hits").inc()
        return True, ent[0]

    # ----------------------------------------------------------- write
    def store(self, fingerprint, versions, value,
              nbytes: "int | None" = None) -> bool:
        """Insert a completed result under ``(fingerprint, versions)``
        — both halves required, same lint as :meth:`lookup`. Returns
        False (dropped) for uncacheable keys or a value larger than
        the whole budget."""
        if (not self.enabled or fingerprint is None
                or versions is None):
            return False
        if nbytes is None:
            nbytes = value_nbytes(value)
        nbytes = max(int(nbytes), 1)
        if nbytes > self.max_bytes:
            return False
        key = (fingerprint, tuple(versions))
        with self._mu:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (value, nbytes)
            self._bytes += nbytes
            for tid, _gen, _dig in key[1]:
                self._by_table.setdefault(str(tid), set()).add(key)
            while self._bytes > self.max_bytes and self._entries:
                self._evict_lru_locked()
        return True

    def _evict_lru_locked(self) -> None:
        key, (_, nb) = self._entries.popitem(last=False)
        self._bytes -= nb
        self._unindex_locked(key)
        telemetry.counter(
            f"{self._prefix}.result_cache_evictions").inc()

    def _unindex_locked(self, key) -> None:
        for tid, _gen, _dig in key[1]:
            keys = self._by_table.get(str(tid))
            if keys is not None:
                keys.discard(key)
                if not keys:
                    del self._by_table[str(tid)]

    # ---------------------------------------------------- invalidation
    def invalidate_table(self, table_id: str) -> int:
        """Evict every entry whose version vector reads ``table_id`` —
        the :func:`catalog.on_append` hook target. Precise: entries
        over other tables are untouched. Returns the eviction count."""
        table_id = str(table_id)
        with self._mu:
            keys = self._by_table.pop(table_id, None)
            if not keys:
                return 0
            n = 0
            for key in keys:
                ent = self._entries.pop(key, None)
                if ent is None:
                    continue
                self._bytes -= ent[1]
                self._unindex_locked(key)
                n += 1
        if n:
            telemetry.counter(
                f"{self._prefix}.result_cache_invalidations").inc(n)
        return n

    def clear(self) -> None:
        with self._mu:
            self._entries.clear()
            self._by_table.clear()
            self._bytes = 0

    # ------------------------------------------------------- reporting
    def stats(self) -> dict:
        with self._mu:
            return {"entries": len(self._entries),
                    "bytes": self._bytes,
                    "max_bytes": self.max_bytes}

    def __len__(self) -> int:
        with self._mu:
            return len(self._entries)


# ------------------------------------------------- append-hook wiring
#: live caches wired to the catalog append stream — weakly held, so a
#: closed engine's cache is collectable without an unhook protocol
_LIVE: "weakref.WeakSet" = None  # type: ignore[assignment]
_HOOK_MU = threading.Lock()
_HOOKED = False


def _on_append(table_id: str, generation: int) -> None:
    for cache in list(_LIVE or ()):
        cache.invalidate_table(table_id)


def hook_on_append(cache: ResultCache) -> ResultCache:
    """Wire ``cache`` to :func:`cylon_tpu.catalog.on_append` so every
    append invalidates exactly the entries that read the mutated table.
    One catalog listener is registered process-wide (listeners cannot
    be removed); caches are tracked weakly. Returns ``cache`` so the
    call composes inline at construction."""
    global _LIVE, _HOOKED
    import weakref

    from cylon_tpu import catalog

    with _HOOK_MU:
        if _LIVE is None:
            _LIVE = weakref.WeakSet()
        _LIVE.add(cache)
        if not _HOOKED:
            catalog.on_append(_on_append)
            _HOOKED = True
    return cache
