"""cylon_tpu.serve — the always-on multi-tenant query service.

One resident mesh, many concurrent queries: a long-lived
:class:`ServeEngine` admits requests against shared resident tables
(:mod:`cylon_tpu.catalog` pins), schedules them through the
:mod:`cylon_tpu.ops_graph` execution strategies (RoundRobin fair-share
/ Priority tenant weights), bounds each under a per-request SLO
(:func:`cylon_tpu.watchdog.deadline`), shares one compiled-plan cache
across clients (:func:`cylon_tpu.plan.shared_compiled`) and meters
everything per tenant (``serve.*`` + tenant-labeled instruments).
``python -m cylon_tpu.serve.bench --clients 8`` replays a mixed TPC-H
workload against it. See ``docs/serving.md``.
"""

from cylon_tpu.serve.admission import (AdmissionController, ServePolicy,
                                       default_policy)
from cylon_tpu.serve.service import QueryTicket, ServeEngine
from cylon_tpu.serve.session import Session

__all__ = ["ServeEngine", "QueryTicket", "Session", "ServePolicy",
           "AdmissionController", "default_policy"]
