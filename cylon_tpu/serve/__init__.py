"""cylon_tpu.serve — the always-on multi-tenant query service.

One resident mesh, many concurrent queries: a long-lived
:class:`ServeEngine` admits requests against shared resident tables
(:mod:`cylon_tpu.catalog` pins), schedules them through the
:mod:`cylon_tpu.ops_graph` execution strategies (RoundRobin fair-share
/ Priority tenant weights), bounds each under a per-request SLO
(:func:`cylon_tpu.watchdog.deadline`), shares one compiled-plan cache
across clients (:func:`cylon_tpu.plan.shared_compiled`) and meters
everything per tenant (``serve.*`` + tenant-labeled instruments).
With a ``durable_dir`` the engine is CRASH-SAFE: admitted requests
journal write-ahead (idempotency-key deduped), resident tables
snapshot, and ``ServeEngine.recover(dir)`` rebuilds mesh + tables +
in-flight work after a hard kill; a sustained failure storm trips the
admission circuit breaker instead of wedging the process.
``python -m cylon_tpu.serve.bench --clients 8`` replays a mixed TPC-H
workload against it. See ``docs/serving.md``.
"""

from cylon_tpu.serve.admission import (AdmissionController,
                                       CircuitBreaker, ServePolicy,
                                       default_policy)
from cylon_tpu.serve.durability import (CatalogSnapshot, JournalLock,
                                        RequestJournal, fence_journal)
from cylon_tpu.serve.fleet import (EngineGateway, FleetLayout,
                                   FleetRouter, HttpEngineClient,
                                   LocalEngineClient, RouterTicket)
from cylon_tpu.serve.introspect import IntrospectServer
from cylon_tpu.serve.service import QueryTicket, ServeEngine
from cylon_tpu.serve.session import Session

__all__ = ["ServeEngine", "QueryTicket", "Session", "ServePolicy",
           "AdmissionController", "CircuitBreaker", "RequestJournal",
           "CatalogSnapshot", "default_policy", "IntrospectServer",
           "JournalLock", "fence_journal", "FleetLayout",
           "FleetRouter", "RouterTicket", "EngineGateway",
           "HttpEngineClient", "LocalEngineClient"]
