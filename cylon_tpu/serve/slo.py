"""Per-tenant SLO accounting: good/bad events → multi-window burn rates.

The serve layer has enforced per-request SLOs since PR 6 (the watchdog
deadline kills a request that blows its budget) but never ACCOUNTED
for them: nothing could say "tenant alice is burning her error budget
4× too fast over the last minute" — the signal a router needs to stop
sending her traffic to this engine, and the signal an operator pages
on. This module is the SRE-workbook recipe over the shared windowed
machinery (:mod:`cylon_tpu.telemetry.timeseries`):

* every request retirement is classified **good** (completed without
  error AND — when a latency objective is set — within
  ``slo_latency`` seconds) or **bad** (error, expiry, or too slow);
* each tenant accumulates good/bad counts in one
  :class:`~cylon_tpu.telemetry.timeseries.BurnRate` — a pair of
  sliding :class:`~cylon_tpu.telemetry.timeseries.EventWindow` rings
  per configured window;
* after every retirement the current burn rate lands on the
  ``serve.slo_burn{tenant=,window=}`` gauge — scrapeable from
  ``/metrics``, windowed-viewable from ``/metrics/window``, and read
  directly by the ``/health`` verdict.

``burn = bad_fraction / (1 - objective)``: 1.0 means the tenant is
consuming its error budget exactly at the sustainable pace; the
``/health`` verdict flags ``burn >= 1`` as degraded and
``burn >= ServePolicy.burn_critical`` (default 10 — a budget gone 10×
too fast) as unhealthy, reading the SHORT window for fast detection
with the LONG window as the de-flapper.

Disabled (the default — ``slo_target`` unset) this module allocates
nothing and :meth:`SloTracker.record` returns after one attribute
read: the unarmed-process contract of the whole observability plane.
"""

import threading

from cylon_tpu import telemetry
from cylon_tpu.telemetry.timeseries import BurnRate

__all__ = ["SloTracker"]


class SloTracker:
    """Good/bad retirement accounting per tenant (module docstring).

    Built from a :class:`~cylon_tpu.serve.admission.ServePolicy`:
    ``slo_target`` (the success objective, e.g. ``0.99``) arms it;
    ``slo_latency`` (seconds) optionally tightens "good" to "fast
    enough"; ``slo_windows`` are the burn windows (short first)."""

    def __init__(self, policy):
        self.objective = policy.slo_target
        self.latency_s = policy.slo_latency
        self.windows = tuple(policy.slo_windows)
        self._mu = threading.Lock()
        self._tenants: "dict[str, BurnRate]" = {}

    @property
    def enabled(self) -> bool:
        return self.objective is not None

    def record(self, tenant: str, ok: bool,
               latency_s: "float | None") -> None:
        """Classify one retirement and refresh the tenant's burn
        gauges. No-op (one attribute read) when no objective is set."""
        if self.objective is None:
            return
        good = bool(ok)
        if (good and self.latency_s is not None
                and latency_s is not None
                and latency_s > self.latency_s):
            good = False  # completed, but too slow to count as good
        tenant = str(tenant)
        with self._mu:
            br = self._tenants.get(tenant)
            if br is None:
                br = self._tenants[tenant] = BurnRate(
                    self.objective, self.windows)
            br.record(good)
            burns = br.burns()
        for w, b in burns.items():
            if b is not None:
                telemetry.gauge("serve.slo_burn", tenant=tenant,
                                window=_wlabel(w)).set(round(b, 4))

    def burn_rates(self) -> "dict[str, dict]":
        """``{tenant: {window_s: burn | None}}`` recomputed from the
        live windows (an idle tenant's burn decays to None as its
        events age out — gauges keep the last written value, this is
        the fresh read ``/health`` uses). Reads INSIDE the tracker
        lock: EventWindow is caller-locked by contract, and a /health
        poll racing the scheduler's record() on the same deques would
        otherwise corrupt counts (or IndexError mid-evict)."""
        with self._mu:
            return {t: br.burns() for t, br in self._tenants.items()}

    def worst(self) -> "tuple[str, float, float] | None":
        """The worst (tenant, window_s, burn) right now, or None when
        no tenant has events in any window."""
        worst = None
        for tenant, burns in self.burn_rates().items():
            for w, b in burns.items():
                if b is None:
                    continue
                if worst is None or b > worst[2]:
                    worst = (tenant, w, b)
        return worst


def _wlabel(window_s: float) -> str:
    """Stable label for a window length (``60s``, ``300s`` — trailing
    zeros trimmed so 60.0 and 60 key the same series)."""
    w = float(window_s)
    return f"{int(w)}s" if w == int(w) else f"{w}s"
