"""Serve-engine durability: the write-ahead journal + catalog snapshot.

The always-on engine (:mod:`cylon_tpu.serve.service`) is exactly the
process a preemption hurts most: it holds resident tables other
processes registered and requests clients already got tickets for.
This module gives :class:`~cylon_tpu.serve.ServeEngine` a durable spine
so ``ServeEngine.recover(dir)`` can rebuild both after a hard kill:

* :class:`RequestJournal` — an append-only JSONL **write-ahead
  journal**. Every admitted request lands as an ``admit`` line (fsynced
  BEFORE the request is dispatched to the scheduler — the write-ahead
  invariant the bench guard enforces statically), and every retirement
  as a ``done`` line. Recovery replays admitted-but-not-done entries.
  Client-supplied **idempotency keys** make the replay exactly-once: a
  client retrying a request it never got an answer for reuses its key,
  and the engine dedups against both live and replayed requests instead
  of double-executing. Dedup'd admissions (ISSUE 19) journal the same
  way: a result-cache hit writes its admit line and then an immediate
  ``done`` line, and every coalesced follower writes its OWN admit
  line before it can be answered — so ``recover()`` never replays an
  answer a client already holds, and a killed leader's followers are
  each independently replayable.

* :class:`CatalogSnapshot` — the resident tables, spilled through the
  same fsync-then-rename :class:`~cylon_tpu.resilience.SpillStore`
  machinery the out-of-core checkpoints use. ``register_table`` on a
  durable engine snapshots the table's host content; ``recover``
  restores every snapshot into the process catalog (distributed tables
  restore as local tables — re-scatter against the recovered mesh if
  the deployment shards them).

* :class:`JournalLock` — the multi-engine fence (ISSUE 15). A fleet
  shares one durable dir tree, so a second live engine pointed at an
  OWNED journal must fail loudly instead of silently interleaving
  journal lines with the owner. Each journal carries an exclusive
  owner lockfile (``journal.lock``, created ``O_EXCL``) recording the
  owner's pid/host plus a random fencing token; every append
  re-verifies the token on disk, so :func:`fence_journal` (the
  router's "you are dead to me" write) makes a zombie owner's next
  append raise :class:`~cylon_tpu.errors.FailedPrecondition` instead
  of corrupting the stream. Stale locks — dead pid on this host, a
  fence marker, or a heartbeat mtime older than
  ``CYLON_TPU_FLEET_LOCK_TTL`` (0 disables the TTL rule) — are broken
  automatically on acquire, which is exactly what
  ``ServeEngine.recover`` needs to adopt a killed engine's journal.

Crash-window contract (shared with :class:`CheckpointedRun`): every
manifest write is tmp + fsync + ``os.replace``; journal lines are
flushed + fsynced per record, and a torn trailing line (the kill landed
mid-append) is skipped on replay, never fatal.
"""

import json
import os
import socket
import threading
import time
import uuid

from cylon_tpu.errors import FailedPrecondition
from cylon_tpu.resilience import SpillStore, atomic_write_json
from cylon_tpu.utils.logging import get_logger

__all__ = ["RequestJournal", "CatalogSnapshot", "JournalLock",
           "fence_journal"]


class JournalLock:
    """Exclusive owner lockfile for one request journal.

    The file holds ``{"pid", "host", "owner", "token", "acquired"}``;
    the in-memory ``token`` is the owner's proof of possession. Three
    operations matter:

    * :meth:`acquire` — ``O_EXCL`` create; an existing lock is broken
      IFF :meth:`_stale` says so (owner pid dead on this host, a
      ``fenced`` marker, or mtime heartbeat older than the TTL),
      otherwise :class:`~cylon_tpu.errors.FailedPrecondition` names the
      live owner. A broken-and-reacquired lock gets a FRESH token, so
      the previous owner is fenced as a side effect.
    * :meth:`verify` — called under the journal mutex before every
      append: the on-disk token must still be ours. A mismatch means
      somebody fenced us (or adopted the journal); the append raises
      instead of interleaving with the new owner.
    * :meth:`heartbeat` — ``os.utime`` after every append, the
      liveness signal the TTL rule reads (a wedged-but-alive engine
      eventually reads stale once the deployment sets the TTL).
    """

    FILE = "journal.lock"

    def __init__(self, root: str):
        self.root = str(root)
        self.path = os.path.join(self.root, self.FILE)
        self.token: "str | None" = None

    # ------------------------------------------------------- internals
    def _read(self) -> "dict | None":
        try:
            with open(self.path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    @staticmethod
    def _ttl() -> float:
        try:
            return float(os.environ.get("CYLON_TPU_FLEET_LOCK_TTL",
                                        "0") or 0)
        except ValueError:
            return 0.0

    def _stale(self, cur: "dict | None") -> bool:
        """May this lock be broken? Unreadable/torn locks and fence
        markers are always breakable (a fence only needs to stop the
        OLD token holder — any new owner may take over). On the
        owner's own host, pid liveness is AUTHORITATIVE: a dead pid is
        stale, a provably-alive pid is never stale (an idle engine
        appends nothing, so its heartbeat mtime ages — the TTL must
        not break a live owner; fencing a wedged-but-alive engine is
        :func:`fence_journal`'s job, a deliberate act). Only when the
        pid is uncheckable (different host — shared storage) does the
        armed-TTL heartbeat rule decide."""
        if cur is None or cur.get("fenced"):
            return True
        pid = cur.get("pid")
        if cur.get("host") == socket.gethostname() \
                and isinstance(pid, int):
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                return True
            except PermissionError:
                return False  # alive, different user
            return False  # alive: liveness beats any heartbeat age
        ttl = self._ttl()
        if ttl > 0:
            try:
                age = time.time() - os.stat(self.path).st_mtime
            except OSError:
                return True
            if age > ttl:
                return True
        return False

    # ------------------------------------------------------ operations
    def acquire(self, owner: str = "engine") -> "JournalLock":
        os.makedirs(self.root, exist_ok=True)
        payload = {"pid": os.getpid(), "host": socket.gethostname(),
                   "owner": str(owner),
                   "token": uuid.uuid4().hex,
                   "acquired": time.time()}
        for _ in range(8):  # bounded retry around break/acquire races
            try:
                fd = os.open(self.path,
                             os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                cur = self._read()
                if not self._stale(cur):
                    cur = cur or {}
                    raise FailedPrecondition(
                        f"journal {self.root!r} is owned by a live "
                        f"engine (pid {cur.get('pid')} on "
                        f"{cur.get('host')!r}, owner "
                        f"{cur.get('owner')!r}) — a second engine must "
                        "never append to an owned journal; point it at "
                        "its own durable dir, or fence/stop the owner "
                        "first. NOTE: pid liveness is only checkable "
                        "on the owner's host — for cross-host "
                        "deployments (shared storage) arm "
                        "CYLON_TPU_FLEET_LOCK_TTL so a crashed "
                        "remote owner's heartbeat expires, or "
                        "fence_journal()/unlink the lock once the "
                        "owner is provably gone")
                get_logger().warning(
                    "breaking stale journal lock %s (owner %r)",
                    self.path, (cur or {}).get("owner"))
                try:
                    os.unlink(self.path)
                except FileNotFoundError:
                    pass
                continue
            with os.fdopen(fd, "w") as f:
                json.dump(payload, f)
                f.flush()
                os.fsync(f.fileno())
            self.token = payload["token"]
            return self
        raise FailedPrecondition(
            f"could not acquire journal lock {self.path!r}: lost the "
            "break/acquire race repeatedly")

    def verify(self) -> None:
        """Raise :class:`~cylon_tpu.errors.FailedPrecondition` unless
        the on-disk lock still carries OUR token — i.e. we were fenced
        (or the lock was broken and re-acquired) since the last
        append."""
        cur = self._read()
        if cur is None or cur.get("token") != self.token:
            raise FailedPrecondition(
                f"journal {self.root!r} has been FENCED (lock token "
                f"changed; current owner: "
                f"{(cur or {}).get('owner')!r}) — this engine no "
                "longer owns its journal and must not append; a "
                "router declared it dead and failed its requests over")

    def heartbeat(self) -> None:
        try:
            os.utime(self.path, None)
        except OSError:  # pragma: no cover - heartbeat best-effort
            pass

    def release(self) -> None:
        """Unlink the lock IFF it is still ours (never steal a
        successor's lock — release after a fence is a no-op)."""
        if self.token is None:
            return
        cur = self._read()
        if cur is not None and cur.get("token") == self.token:
            try:
                os.unlink(self.path)
            except OSError:  # pragma: no cover - release best-effort
                pass
        self.token = None


def fence_journal(root: str, owner: str = "router") -> None:
    """FENCE a journal: atomically install a fresh lock token so the
    current owner's next :meth:`JournalLock.verify` fails. This is the
    router's failover barrier — written AFTER an engine is declared
    dead and BEFORE its journaled-but-incomplete requests replay on a
    peer, so a zombie engine (alive but unreachable) can never append
    an ``admit``/``done`` line that races the replay. The fence itself
    is marked breakable (``fenced: true``): a later
    ``ServeEngine.recover`` on the same dir adopts the journal
    normally."""
    payload = {"pid": os.getpid(), "host": socket.gethostname(),
               "owner": str(owner), "token": uuid.uuid4().hex,
               "acquired": time.time(), "fenced": True}
    os.makedirs(str(root), exist_ok=True)
    atomic_write_json(os.path.join(str(root), JournalLock.FILE),
                      payload)


class RequestJournal:
    """Append-only JSONL write-ahead journal of serve requests.

    One line per event::

        {"kind": "admit", "rid": 3, "key": "c1-q3-0", "name": "q3",
         "args": [...], "kwargs": {...}, "tenant": "t1", "priority": 1,
         "slo": null, "tables": ["tpch/lineitem"], "replayable": true}
        {"kind": "done", "rid": 3, "key": "c1-q3-0", "state": "done"}

    ``admit`` is written (flush + fsync) BEFORE the request reaches the
    scheduler, so a kill at any later instant leaves the request
    recoverable. A request whose args are not JSON-serializable (or
    that was submitted as a bare callable rather than a registered
    named query) is journaled with ``replayable: false`` — recovery
    reports it as lost instead of silently dropping it.
    """

    FILE = "journal.jsonl"

    def __init__(self, root: str, owner: str = "engine"):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        self.path = os.path.join(self.root, self.FILE)
        self._mu = threading.Lock()
        #: exclusive ownership BEFORE the append handle opens: a second
        #: live engine pointed at this journal fails here (ISSUE 15 —
        #: two writers would silently interleave admit/done lines);
        #: stale locks (dead pid, fence marker, expired heartbeat) are
        #: broken, which is how recover() adopts a killed engine's dir
        self.lock = JournalLock(self.root).acquire(owner=owner)
        self._f = open(self.path, "a")

    def _append(self, entry: dict) -> None:
        line = json.dumps(entry)
        with self._mu:
            # fencing check rides every append: once a router fenced
            # this journal (token replaced), appending would race the
            # failover replay — refuse instead
            self.lock.verify()
            self._f.write(line + "\n")
            self._f.flush()
            os.fsync(self._f.fileno())
            self.lock.heartbeat()

    def admit(self, *, rid: int, key: "str | None", name: "str | None",
              args=(), kwargs=None, tenant: str = "default",
              priority: int = 1, slo: "float | None" = None,
              tables=(), trace_id: "str | None" = None) -> None:
        """Write-ahead record of one admitted request. Falls back to
        ``replayable: false`` (with args dropped) when the payload is
        not JSON-serializable — the journal must never fail a submit
        that the engine would otherwise accept. ``trace_id`` (ISSUE
        20) rides the entry so a failover REPLAY of this request can
        keep the original fleet trace identity."""
        entry = {"kind": "admit", "rid": int(rid), "key": key,
                 "name": name, "args": list(args),
                 "kwargs": dict(kwargs or {}), "tenant": str(tenant),
                 "priority": int(priority), "slo": slo,
                 "tables": list(tables),
                 "trace_id": (None if trace_id is None
                              else str(trace_id)),
                 "replayable": name is not None}
        try:
            self._append(entry)
        except (TypeError, ValueError):
            entry.update(args=[], kwargs={}, replayable=False)
            self._append(entry)

    def done(self, *, rid: int, key: "str | None", state: str) -> None:
        """Retirement record (state ``done``/``failed``): the request
        needs no replay — even a FAILED one, whose error the client
        already observed (re-running it on recovery would surprise an
        idempotent client with a second side-effect attempt)."""
        self._append({"kind": "done", "rid": int(rid), "key": key,
                      "state": str(state)})

    # ---------------------------------------------------------- replay
    @staticmethod
    def read(root: str) -> "list[dict]":
        """All parseable journal entries under ``root`` (missing file =
        empty). A torn trailing line — the kill landed mid-append — is
        skipped; a torn line FOLLOWED by valid lines would mean
        fsync-ordering was violated and is logged loudly but still
        skipped (recovery must degrade, not die)."""
        path = os.path.join(str(root), RequestJournal.FILE)
        entries: list = []
        torn = 0
        try:
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError:
            return entries
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                entries.append(json.loads(line))
            except ValueError:
                torn += 1
                if i != len(lines) - 1:
                    get_logger().error(
                        "serve journal %s: torn NON-final line %d "
                        "(skipped) — fsync ordering violated?",
                        path, i + 1)
        if torn:
            get_logger().warning(
                "serve journal %s: skipped %d torn line(s)", path, torn)
        return entries

    @staticmethod
    def incomplete(root: str) -> "tuple[list[dict], list[dict]]":
        """(replayable, unreplayable) admitted-but-not-done entries, in
        admission order, deduped by idempotency key (a key journaled
        twice — e.g. admitted again by a previous recovery — replays
        once)."""
        done_keys, done_rids = set(), set()
        for e in RequestJournal.read(root):
            if e.get("kind") == "done":
                if e.get("key") is not None:
                    done_keys.add(e["key"])
                done_rids.add(e.get("rid"))
        replayable, unreplayable, seen = [], [], set()
        for e in RequestJournal.read(root):
            if e.get("kind") != "admit":
                continue
            key = e.get("key")
            if key is not None:
                if key in done_keys or key in seen:
                    continue
                seen.add(key)
            elif e.get("rid") in done_rids:
                continue
            (replayable if e.get("replayable") and e.get("name")
             else unreplayable).append(e)
        return replayable, unreplayable

    def close(self) -> None:
        with self._mu:
            try:
                self._f.close()
            except OSError:  # pragma: no cover - close best-effort
                pass
            self.lock.release()


class CatalogSnapshot:
    """Durable image of the resident-table catalog.

    Tables spill into a :class:`~cylon_tpu.resilience.SpillStore` under
    ``<root>/catalog/`` (one bucket per table, fsync-then-rename data +
    manifest), with a ``tables.json`` map from table id to bucket —
    itself written via :func:`~cylon_tpu.resilience.atomic_write_json`.
    The store's fingerprint is a fixed format tag, so reopening after a
    kill resumes the snapshot rather than discarding it."""

    FORMAT = "serve-catalog-v1"
    MAP = "tables.json"
    INIT_LOCK = ".init.lock"

    def __init__(self, root: str):
        self.root = os.path.join(str(root), "catalog")
        self.store = self._store_with_init_mutex()
        self._mpath = os.path.join(self.root, self.MAP)
        try:
            with open(self._mpath) as f:
                self._map = json.load(f)
        except (OSError, ValueError):
            self._map = {"tables": {}, "next": 0}

    def _store_with_init_mutex(self) -> SpillStore:
        """Open the spill store under a tiny cross-process init mutex.

        A FLEET shares one snapshot store (ISSUE 15): two engine
        processes constructing it concurrently on a FRESH dir would
        race SpillStore's first-manifest write against the other's
        stale-state sweep (which unlinks ``manifest.json.tmp*`` —
        deleting the peer's in-flight atomic write). The mutex only
        guards construction; steady-state saves stay lock-free
        (identical content, atomic per-file replace). A mutex file
        older than 60s is a crashed initializer and is broken."""
        os.makedirs(self.root, exist_ok=True)
        lockpath = os.path.join(self.root, self.INIT_LOCK)
        deadline = time.monotonic() + 120.0
        while True:
            try:
                fd = os.open(lockpath,
                             os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                if time.monotonic() > deadline:
                    raise FailedPrecondition(
                        f"snapshot store {self.root!r} init mutex "
                        "held past the deadline — wedged "
                        "initializer?")
                try:
                    age = time.time() - os.stat(lockpath).st_mtime
                except OSError:
                    age = None  # released/claimed under us: retry
                if age is not None and age > 60.0:
                    # crashed initializer: CLAIM the stale mutex by
                    # atomic rename — exactly one breaker wins the
                    # replace (the losers' replace raises and they
                    # just retry the O_EXCL create), so a freshly
                    # re-created lock can never be unlinked by a
                    # racing breaker that statted the OLD file
                    stale = (f"{lockpath}.stale{os.getpid()}_"
                             f"{threading.get_ident()}")
                    try:
                        os.replace(lockpath, stale)
                        os.unlink(stale)
                    except OSError:
                        pass
                time.sleep(0.05)
                continue
            os.close(fd)
            try:
                return SpillStore(self.root, fingerprint=self.FORMAT)
            finally:
                try:
                    os.unlink(lockpath)
                except OSError:  # pragma: no cover - best-effort
                    pass

    def _flush_map(self) -> None:
        atomic_write_json(self._mpath, self._map)

    @property
    def tables(self) -> "list[str]":
        return sorted(self._map["tables"])

    def save(self, table_id: str, table, env=None,
             generation: "int | None" = None) -> None:
        """Snapshot one table's host content (distributed tables
        gather to host first). Data lands durably BEFORE the map names
        it — a kill mid-save leaves the previous snapshot intact.

        ``generation`` stamps the catalog's monotone version into the
        map entry: a :meth:`restore` after an append must reinstate
        the POST-append generation, or the recovered process would
        serve generation-1 content under a generation-1 label and
        every version-keyed memo/view watermark would silently alias
        the stale version (ISSUE 18 fix)."""
        pdf = self._host_frame(table, env)
        if not len(pdf.columns):
            get_logger().warning(
                "catalog snapshot: table %r has no columns; skipped",
                table_id)
            return
        ent = self._map["tables"].get(table_id)
        if ent is None:
            bucket = int(self._map["next"])
            self._map["next"] = bucket + 1
        else:
            bucket = int(ent["bucket"])
        self.store.write_bucket(
            bucket, {c: pdf[c].to_numpy() for c in pdf.columns},
            max(len(pdf), 1), meta={"table_id": table_id,
                                    "rows": int(len(pdf))})
        entry = {"bucket": bucket, "rows": int(len(pdf))}
        if generation is not None:
            entry["generation"] = int(generation)
        self._map["tables"][table_id] = entry
        self._flush_map()

    def generations(self) -> "dict[str, int]":
        """Per-table generation stamps recorded at save time (tables
        snapshotted before the versioning era are absent — restore
        treats them as generation 1)."""
        return {tid: int(ent["generation"])
                for tid, ent in self._map["tables"].items()
                if "generation" in ent}

    @staticmethod
    def _host_frame(table, env=None):
        from cylon_tpu.parallel import dtable

        if dtable.is_distributed(table):
            from cylon_tpu.parallel import dist_to_pandas

            return dist_to_pandas(env, table)
        return table.to_pandas()

    def drop(self, table_id: str) -> None:
        """Forget a table's snapshot (the orphaned bucket is left on
        disk; the map is authoritative)."""
        if self._map["tables"].pop(table_id, None) is not None:
            self._flush_map()

    def restore(self) -> "dict[str, object]":
        """Rebuild every snapshot table: {table_id: Table}. Rows==0
        snapshots restore with their schema (the spill kept empty
        columns)."""
        from cylon_tpu.table import Table

        out: dict = {}
        for tid, ent in sorted(self._map["tables"].items()):
            cols = self.store.read_bucket(int(ent["bucket"]))
            rows = int(ent["rows"])
            out[tid] = Table.from_pydict(
                {k: v[:rows] for k, v in cols.items()},
                capacity=None if rows else 1)
        return out
