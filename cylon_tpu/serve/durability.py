"""Serve-engine durability: the write-ahead journal + catalog snapshot.

The always-on engine (:mod:`cylon_tpu.serve.service`) is exactly the
process a preemption hurts most: it holds resident tables other
processes registered and requests clients already got tickets for.
This module gives :class:`~cylon_tpu.serve.ServeEngine` a durable spine
so ``ServeEngine.recover(dir)`` can rebuild both after a hard kill:

* :class:`RequestJournal` — an append-only JSONL **write-ahead
  journal**. Every admitted request lands as an ``admit`` line (fsynced
  BEFORE the request is dispatched to the scheduler — the write-ahead
  invariant the bench guard enforces statically), and every retirement
  as a ``done`` line. Recovery replays admitted-but-not-done entries.
  Client-supplied **idempotency keys** make the replay exactly-once: a
  client retrying a request it never got an answer for reuses its key,
  and the engine dedups against both live and replayed requests instead
  of double-executing.

* :class:`CatalogSnapshot` — the resident tables, spilled through the
  same fsync-then-rename :class:`~cylon_tpu.resilience.SpillStore`
  machinery the out-of-core checkpoints use. ``register_table`` on a
  durable engine snapshots the table's host content; ``recover``
  restores every snapshot into the process catalog (distributed tables
  restore as local tables — re-scatter against the recovered mesh if
  the deployment shards them).

Crash-window contract (shared with :class:`CheckpointedRun`): every
manifest write is tmp + fsync + ``os.replace``; journal lines are
flushed + fsynced per record, and a torn trailing line (the kill landed
mid-append) is skipped on replay, never fatal.
"""

import json
import os
import threading

from cylon_tpu.resilience import SpillStore, atomic_write_json
from cylon_tpu.utils.logging import get_logger

__all__ = ["RequestJournal", "CatalogSnapshot"]


class RequestJournal:
    """Append-only JSONL write-ahead journal of serve requests.

    One line per event::

        {"kind": "admit", "rid": 3, "key": "c1-q3-0", "name": "q3",
         "args": [...], "kwargs": {...}, "tenant": "t1", "priority": 1,
         "slo": null, "tables": ["tpch/lineitem"], "replayable": true}
        {"kind": "done", "rid": 3, "key": "c1-q3-0", "state": "done"}

    ``admit`` is written (flush + fsync) BEFORE the request reaches the
    scheduler, so a kill at any later instant leaves the request
    recoverable. A request whose args are not JSON-serializable (or
    that was submitted as a bare callable rather than a registered
    named query) is journaled with ``replayable: false`` — recovery
    reports it as lost instead of silently dropping it.
    """

    FILE = "journal.jsonl"

    def __init__(self, root: str):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        self.path = os.path.join(self.root, self.FILE)
        self._mu = threading.Lock()
        self._f = open(self.path, "a")

    def _append(self, entry: dict) -> None:
        line = json.dumps(entry)
        with self._mu:
            self._f.write(line + "\n")
            self._f.flush()
            os.fsync(self._f.fileno())

    def admit(self, *, rid: int, key: "str | None", name: "str | None",
              args=(), kwargs=None, tenant: str = "default",
              priority: int = 1, slo: "float | None" = None,
              tables=()) -> None:
        """Write-ahead record of one admitted request. Falls back to
        ``replayable: false`` (with args dropped) when the payload is
        not JSON-serializable — the journal must never fail a submit
        that the engine would otherwise accept."""
        entry = {"kind": "admit", "rid": int(rid), "key": key,
                 "name": name, "args": list(args),
                 "kwargs": dict(kwargs or {}), "tenant": str(tenant),
                 "priority": int(priority), "slo": slo,
                 "tables": list(tables),
                 "replayable": name is not None}
        try:
            self._append(entry)
        except (TypeError, ValueError):
            entry.update(args=[], kwargs={}, replayable=False)
            self._append(entry)

    def done(self, *, rid: int, key: "str | None", state: str) -> None:
        """Retirement record (state ``done``/``failed``): the request
        needs no replay — even a FAILED one, whose error the client
        already observed (re-running it on recovery would surprise an
        idempotent client with a second side-effect attempt)."""
        self._append({"kind": "done", "rid": int(rid), "key": key,
                      "state": str(state)})

    # ---------------------------------------------------------- replay
    @staticmethod
    def read(root: str) -> "list[dict]":
        """All parseable journal entries under ``root`` (missing file =
        empty). A torn trailing line — the kill landed mid-append — is
        skipped; a torn line FOLLOWED by valid lines would mean
        fsync-ordering was violated and is logged loudly but still
        skipped (recovery must degrade, not die)."""
        path = os.path.join(str(root), RequestJournal.FILE)
        entries: list = []
        torn = 0
        try:
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError:
            return entries
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                entries.append(json.loads(line))
            except ValueError:
                torn += 1
                if i != len(lines) - 1:
                    get_logger().error(
                        "serve journal %s: torn NON-final line %d "
                        "(skipped) — fsync ordering violated?",
                        path, i + 1)
        if torn:
            get_logger().warning(
                "serve journal %s: skipped %d torn line(s)", path, torn)
        return entries

    @staticmethod
    def incomplete(root: str) -> "tuple[list[dict], list[dict]]":
        """(replayable, unreplayable) admitted-but-not-done entries, in
        admission order, deduped by idempotency key (a key journaled
        twice — e.g. admitted again by a previous recovery — replays
        once)."""
        done_keys, done_rids = set(), set()
        for e in RequestJournal.read(root):
            if e.get("kind") == "done":
                if e.get("key") is not None:
                    done_keys.add(e["key"])
                done_rids.add(e.get("rid"))
        replayable, unreplayable, seen = [], [], set()
        for e in RequestJournal.read(root):
            if e.get("kind") != "admit":
                continue
            key = e.get("key")
            if key is not None:
                if key in done_keys or key in seen:
                    continue
                seen.add(key)
            elif e.get("rid") in done_rids:
                continue
            (replayable if e.get("replayable") and e.get("name")
             else unreplayable).append(e)
        return replayable, unreplayable

    def close(self) -> None:
        with self._mu:
            try:
                self._f.close()
            except OSError:  # pragma: no cover - close best-effort
                pass


class CatalogSnapshot:
    """Durable image of the resident-table catalog.

    Tables spill into a :class:`~cylon_tpu.resilience.SpillStore` under
    ``<root>/catalog/`` (one bucket per table, fsync-then-rename data +
    manifest), with a ``tables.json`` map from table id to bucket —
    itself written via :func:`~cylon_tpu.resilience.atomic_write_json`.
    The store's fingerprint is a fixed format tag, so reopening after a
    kill resumes the snapshot rather than discarding it."""

    FORMAT = "serve-catalog-v1"
    MAP = "tables.json"

    def __init__(self, root: str):
        self.root = os.path.join(str(root), "catalog")
        self.store = SpillStore(self.root, fingerprint=self.FORMAT)
        self._mpath = os.path.join(self.root, self.MAP)
        try:
            with open(self._mpath) as f:
                self._map = json.load(f)
        except (OSError, ValueError):
            self._map = {"tables": {}, "next": 0}

    def _flush_map(self) -> None:
        atomic_write_json(self._mpath, self._map)

    @property
    def tables(self) -> "list[str]":
        return sorted(self._map["tables"])

    def save(self, table_id: str, table, env=None) -> None:
        """Snapshot one table's host content (distributed tables
        gather to host first). Data lands durably BEFORE the map names
        it — a kill mid-save leaves the previous snapshot intact."""
        pdf = self._host_frame(table, env)
        if not len(pdf.columns):
            get_logger().warning(
                "catalog snapshot: table %r has no columns; skipped",
                table_id)
            return
        ent = self._map["tables"].get(table_id)
        if ent is None:
            bucket = int(self._map["next"])
            self._map["next"] = bucket + 1
        else:
            bucket = int(ent["bucket"])
        self.store.write_bucket(
            bucket, {c: pdf[c].to_numpy() for c in pdf.columns},
            max(len(pdf), 1), meta={"table_id": table_id,
                                    "rows": int(len(pdf))})
        self._map["tables"][table_id] = {"bucket": bucket,
                                         "rows": int(len(pdf))}
        self._flush_map()

    @staticmethod
    def _host_frame(table, env=None):
        from cylon_tpu.parallel import dtable

        if dtable.is_distributed(table):
            from cylon_tpu.parallel import dist_to_pandas

            return dist_to_pandas(env, table)
        return table.to_pandas()

    def drop(self, table_id: str) -> None:
        """Forget a table's snapshot (the orphaned bucket is left on
        disk; the map is authoritative)."""
        if self._map["tables"].pop(table_id, None) is not None:
            self._flush_map()

    def restore(self) -> "dict[str, object]":
        """Rebuild every snapshot table: {table_id: Table}. Rows==0
        snapshots restore with their schema (the spill kept empty
        columns)."""
        from cylon_tpu.table import Table

        out: dict = {}
        for tid, ent in sorted(self._map["tables"].items()):
            cols = self.store.read_bucket(int(ent["bucket"]))
            rows = int(ent["rows"])
            out[tid] = Table.from_pydict(
                {k: v[:rows] for k, v in cols.items()},
                capacity=None if rows else 1)
        return out
