"""Tenant sessions: a named client of the engine with pinned residency.

A :class:`Session` is the unit of tenancy the serving layer hands a
client: it names the tenant (every request it submits is admitted,
scheduled, metered and traced under that label), carries the tenant's
scheduling weight (``priority`` — the
:class:`~cylon_tpu.ops_graph.execution.PriorityExecution` multiplier
under the ``priority`` schedule), and holds **session pins** on the
resident tables the tenant works against: for the session's lifetime
:func:`cylon_tpu.catalog.drop` on those tables fails with a
:class:`~cylon_tpu.errors.FailedPrecondition` naming this session as
the holder, instead of a concurrent query discovering the loss as a
late ``KeyError``.

    with engine.session("alice", priority=2,
                        tables=["tpch/lineitem"]) as s:
        t1 = s.submit(my_query, resident, env=engine.env)
        t2 = s.submit(other_query, resident, env=engine.env)
        r1, r2 = t1.result(), t2.result()

Per-request pins (``submit(tables=...)``) stack on top of session pins
— both are plain refcounts in the catalog.
"""

import itertools

from cylon_tpu import catalog
from cylon_tpu.errors import InvalidArgument

__all__ = ["Session"]


class Session:
    """One tenant's handle on a :class:`~cylon_tpu.serve.ServeEngine`
    (construct via :meth:`~cylon_tpu.serve.ServeEngine.session`)."""

    _ids = itertools.count(1)

    def __init__(self, engine, tenant: str, priority: int = 1,
                 tables=()):
        if priority < 1:
            raise InvalidArgument(
                f"priority must be >= 1, got {priority}")
        self._engine = engine
        self.tenant = str(tenant)
        self.priority = int(priority)
        self.holder = f"session:{self.tenant}#{next(self._ids)}"
        self._pins: list[str] = []
        self._closed = False
        try:
            for tid in tables:
                self.attach(tid)
        except Exception:
            self.close()
            raise

    # --------------------------------------------------- residency pins
    def attach(self, table_id: str) -> None:
        """Pin ``table_id`` for this session's lifetime."""
        if self._closed:
            raise InvalidArgument(f"session {self.holder} is closed")
        catalog.pin(table_id, holder=self.holder)
        self._pins.append(table_id)

    def detach(self, table_id: str) -> None:
        """Release one session pin on ``table_id``."""
        self._pins.remove(table_id)  # raises if never attached
        catalog.unpin(table_id, holder=self.holder)

    def table(self, table_id: str):
        """The resident table (must be attached — a session only reads
        tables it pinned, so nothing it touches can vanish mid-query)."""
        if table_id not in self._pins:
            raise InvalidArgument(
                f"table {table_id!r} is not attached to session "
                f"{self.holder}; attach() it first")
        return catalog.get_table(table_id)

    @property
    def tables(self) -> list:
        return list(self._pins)

    # ------------------------------------------------------- submission
    def submit(self, fn, *args, slo: "float | None" = None,
               tables=(), fault_plan=None, **kwargs):
        """Submit under this session's tenant + priority (see
        :meth:`cylon_tpu.serve.ServeEngine.submit`)."""
        if self._closed:
            raise InvalidArgument(f"session {self.holder} is closed")
        return self._engine.submit(
            fn, *args, tenant=self.tenant, priority=self.priority,
            slo=slo, tables=tables, fault_plan=fault_plan, **kwargs)

    # -------------------------------------------------------- lifecycle
    def close(self) -> None:
        """Release every session pin (idempotent)."""
        if self._closed:
            return
        self._closed = True
        for tid in self._pins:
            try:
                catalog.unpin(tid, holder=self.holder)
            except Exception:  # table force-cleared under us
                pass
        self._pins.clear()

    def __enter__(self) -> "Session":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __repr__(self):
        return (f"Session({self.tenant!r}, priority={self.priority}, "
                f"tables={self._pins})")
