"""Admission control for the always-on engine: load-shed fast, never
pile up.

The serving contract "millions of users" fails first at the front
door: an engine that accepts every request under overload turns one
slow query into unbounded queue growth, memory pressure and a p99 that
never recovers. The reference has no serving layer at all (one mpirun
= one query); the closest production analog is gRPC's
RESOURCE_EXHAUSTED discipline, which this module adopts:

* a **queue-depth cap** (``max_queue``) on live (queued + running)
  requests — a submit over the cap raises
  :class:`~cylon_tpu.errors.ResourceExhausted` *immediately* (a dict
  check under one lock, no device work, no blocking), so the client
  learns to back off in microseconds instead of timing out minutes
  later;
* a **default SLO** (``default_slo``) stamped on every admitted
  request that doesn't bring its own — the per-request
  :func:`cylon_tpu.watchdog.deadline` budget the scheduler enforces at
  every step;
* the **schedule policy** (``roundrobin`` fair-share default, or
  ``priority`` weighted by tenant priority) the scheduler drives
  through the :mod:`cylon_tpu.ops_graph.execution` strategies.

Knobs (all env-overridable — the ``CYLON_TPU_SERVE_*`` family, read at
engine construction; see ``docs/serving.md``):

=========================== ============================== =========
env                         meaning                        default
=========================== ============================== =========
``CYLON_TPU_SERVE_MAX_QUEUE``  live-request cap            ``64``
``CYLON_TPU_SERVE_SLO``        default per-request SLO (s; ``0`` =
                               unbounded)                  ``0``
``CYLON_TPU_SERVE_SCHEDULE``   ``roundrobin`` | ``priority``
                                                           roundrobin
=========================== ============================== =========
"""

import dataclasses
import os
import threading

from cylon_tpu import telemetry
from cylon_tpu.errors import InvalidArgument, ResourceExhausted

__all__ = ["ServePolicy", "default_policy", "AdmissionController"]

_SCHEDULES = ("roundrobin", "priority")


@dataclasses.dataclass(frozen=True)
class ServePolicy:
    """Engine-level admission/scheduling knobs (see module docstring)."""

    max_queue: int = 64
    default_slo: "float | None" = None
    schedule: str = "roundrobin"

    def __post_init__(self):
        if self.max_queue < 1:
            raise InvalidArgument(
                f"max_queue must be >= 1, got {self.max_queue}")
        if self.schedule not in _SCHEDULES:
            raise InvalidArgument(
                f"unknown schedule {self.schedule!r}; valid: "
                f"{_SCHEDULES}")
        if self.default_slo is not None and self.default_slo <= 0:
            raise InvalidArgument(
                f"default_slo must be > 0 seconds or None, got "
                f"{self.default_slo}")


def default_policy() -> ServePolicy:
    """The process :class:`ServePolicy` with ``CYLON_TPU_SERVE_*`` env
    overrides (read per call so tests can flip them)."""
    e = os.environ
    slo = float(e.get("CYLON_TPU_SERVE_SLO", "0"))
    return ServePolicy(
        max_queue=int(e.get("CYLON_TPU_SERVE_MAX_QUEUE", "64")),
        default_slo=slo if slo > 0 else None,
        schedule=e.get("CYLON_TPU_SERVE_SCHEDULE", "roundrobin"),
    )


class AdmissionController:
    """The queue-depth gate in front of the scheduler.

    ``admit(tenant)`` either takes one live slot or raises
    :class:`~cylon_tpu.errors.ResourceExhausted` naming the depth and
    cap (counted per tenant as ``serve.rejected{tenant=}``); every
    admit is balanced by exactly one ``release()`` when the request
    retires (done, failed, or expired). ``serve.queue_depth`` gauges
    the live count after every transition."""

    def __init__(self, policy: "ServePolicy | None" = None):
        self.policy = policy or default_policy()
        self._mu = threading.Lock()
        self._live = 0

    @property
    def live(self) -> int:
        with self._mu:
            return self._live

    def admit(self, tenant: str) -> None:
        with self._mu:
            if self._live >= self.policy.max_queue:
                depth = self._live
                admitted = False
            else:
                self._live += 1
                depth = self._live
                admitted = True
        telemetry.gauge("serve.queue_depth").set(depth)
        if not admitted:
            telemetry.counter("serve.rejected", tenant=tenant).inc()
            raise ResourceExhausted(
                f"serve queue full: {depth} live requests >= cap "
                f"{self.policy.max_queue} (tenant {tenant!r}); "
                "back off and retry")

    def release(self) -> None:
        with self._mu:
            self._live = max(self._live - 1, 0)
            depth = self._live
        telemetry.gauge("serve.queue_depth").set(depth)
