"""Admission control for the always-on engine: load-shed fast, never
pile up.

The serving contract "millions of users" fails first at the front
door: an engine that accepts every request under overload turns one
slow query into unbounded queue growth, memory pressure and a p99 that
never recovers. The reference has no serving layer at all (one mpirun
= one query); the closest production analog is gRPC's
RESOURCE_EXHAUSTED discipline, which this module adopts:

* a **queue-depth cap** (``max_queue``) on live (queued + running)
  requests — a submit over the cap raises
  :class:`~cylon_tpu.errors.ResourceExhausted` *immediately* (a dict
  check under one lock, no device work, no blocking), so the client
  learns to back off in microseconds instead of timing out minutes
  later;
* a **circuit breaker** (:class:`CircuitBreaker`): under a sustained
  storm of ``DeadlineExceeded``/``ResourceExhausted`` request failures
  (a wedged mesh, an HBM-exhaustion cascade), the engine stops
  admitting NEW work — fast rejection, counted as
  ``serve.shed{reason="breaker"}`` — while in-flight requests keep
  draining on the scheduler. After ``breaker_cooldown`` seconds the
  breaker half-opens and admissions probe through again. Degrading
  gracefully beats dying: the engine stays up, sheds, recovers;
* a **default SLO** (``default_slo``) stamped on every admitted
  request that doesn't bring its own — the per-request
  :func:`cylon_tpu.watchdog.deadline` budget the scheduler enforces at
  every step;
* the **schedule policy** (``roundrobin`` fair-share default, or
  ``priority`` weighted by tenant priority) the scheduler drives
  through the :mod:`cylon_tpu.ops_graph.execution` strategies.

Knobs (all env-overridable — the ``CYLON_TPU_SERVE_*`` family, read at
engine construction; see ``docs/serving.md``):

================================== ============================ =========
env                                meaning                      default
================================== ============================ =========
``CYLON_TPU_SERVE_MAX_QUEUE``      live-request cap             ``64``
``CYLON_TPU_SERVE_SLO``            default per-request SLO (s;
                                   ``0`` = unbounded)           ``0``
``CYLON_TPU_SERVE_SCHEDULE``       ``roundrobin`` | ``priority``
                                                                roundrobin
``CYLON_TPU_SERVE_BREAKER_FAILS``  breaker trip threshold
                                   (failures in window; ``0``
                                   disables)                    ``5``
``CYLON_TPU_SERVE_BREAKER_WINDOW`` failure-counting window (s)  ``30``
``CYLON_TPU_SERVE_BREAKER_COOLDOWN`` open→half-open delay (s)   ``5``
``CYLON_TPU_SERVE_MEMORY_BUDGET``  predicted-bytes admission
                                   cap (bytes; ``0`` disables)  ``0``
``CYLON_TPU_SERVE_SLO_TARGET``     per-tenant success objective
                                   for burn-rate accounting
                                   (e.g. ``0.99``; ``0``
                                   disables)                    ``0``
``CYLON_TPU_SERVE_SLO_LATENCY``    latency objective (s): a
                                   completion slower than this
                                   counts BAD toward the burn
                                   (``0`` = success-only SLO)   ``0``
``CYLON_TPU_SERVE_SLO_WINDOWS``    comma-separated burn windows
                                   (s), short first             ``60,300``
``CYLON_TPU_SERVE_BURN_CRITICAL``  burn rate at which /health
                                   turns unhealthy              ``10``
================================== ============================ =========

Two admission *bypasses* ride in front of this module (ISSUE 19; see
``docs/serving.md`` → "Coalescing & the result cache"): a versioned
result-cache hit (``CYLON_TPU_SERVE_RESULT_CACHE_BYTES``) and a
coalesced attach to an identical in-flight request
(``CYLON_TPU_SERVE_COALESCE``). Neither takes an admission slot,
feeds the breaker, nor observes ``serve.queue_wait_seconds`` — a
dedup'd request carries no signal about engine health. The split is
labeled ``serve.admitted{path=executed|cache_hit|coalesced}``.
"""

import dataclasses
import os
import threading
import time

from cylon_tpu import telemetry
from cylon_tpu.errors import InvalidArgument, ResourceExhausted
from cylon_tpu.telemetry import events as _events
from cylon_tpu.telemetry.timeseries import EventWindow

__all__ = ["ServePolicy", "default_policy", "AdmissionController",
           "CircuitBreaker"]

_SCHEDULES = ("roundrobin", "priority")


@dataclasses.dataclass(frozen=True)
class ServePolicy:
    """Engine-level admission/scheduling knobs (see module docstring)."""

    max_queue: int = 64
    default_slo: "float | None" = None
    schedule: str = "roundrobin"
    breaker_fails: int = 5
    breaker_window: float = 30.0
    breaker_cooldown: float = 5.0
    #: memory-aware admission (bytes; None/0 disables): a submit whose
    #: ``predicted_bytes`` exceeds this budget sheds immediately with
    #: ``serve.shed{reason="memory"}`` — the front-door twin of the
    #: OOM→spill fallback's pre-flight (``CYLON_TPU_SERVE_MEMORY_BUDGET``)
    memory_budget: "int | None" = None
    #: SLO burn-rate accounting (ISSUE 14; None disables — the
    #: default, so an unarmed engine allocates no windows): the
    #: per-tenant SUCCESS objective (e.g. 0.99 = 1% error budget)
    #: retirements are scored against
    slo_target: "float | None" = None
    #: latency objective (seconds; None = success-only SLO): a request
    #: that completes but slower than this counts BAD toward the burn
    slo_latency: "float | None" = None
    #: burn windows (seconds, short first): the multi-window pair the
    #: SRE recipe reads together — short for fast detection, long for
    #: de-flapping
    slo_windows: "tuple" = (60.0, 300.0)
    #: burn rate at which the /health verdict flags a tenant's SLO as
    #: unhealthy (>= 1 is already "burning too fast"; this is the
    #: page-now threshold)
    burn_critical: float = 10.0

    def __post_init__(self):
        if self.max_queue < 1:
            raise InvalidArgument(
                f"max_queue must be >= 1, got {self.max_queue}")
        if self.schedule not in _SCHEDULES:
            raise InvalidArgument(
                f"unknown schedule {self.schedule!r}; valid: "
                f"{_SCHEDULES}")
        if self.default_slo is not None and self.default_slo <= 0:
            raise InvalidArgument(
                f"default_slo must be > 0 seconds or None, got "
                f"{self.default_slo}")
        if self.breaker_fails < 0:
            raise InvalidArgument(
                f"breaker_fails must be >= 0 (0 disables), got "
                f"{self.breaker_fails}")
        if self.breaker_window <= 0 or self.breaker_cooldown <= 0:
            raise InvalidArgument(
                "breaker_window/breaker_cooldown must be > 0 seconds")
        if self.memory_budget is not None and self.memory_budget < 0:
            raise InvalidArgument(
                f"memory_budget must be >= 0 bytes (0/None disables), "
                f"got {self.memory_budget}")
        if self.slo_target is not None and not 0 < self.slo_target < 1:
            raise InvalidArgument(
                f"slo_target must be in (0, 1) or None, got "
                f"{self.slo_target}")
        if self.slo_latency is not None and self.slo_latency <= 0:
            raise InvalidArgument(
                f"slo_latency must be > 0 seconds or None, got "
                f"{self.slo_latency}")
        if not self.slo_windows or \
                any(w <= 0 for w in self.slo_windows):
            raise InvalidArgument(
                f"slo_windows must be non-empty positive seconds, got "
                f"{self.slo_windows}")
        if self.burn_critical <= 0:
            raise InvalidArgument(
                f"burn_critical must be > 0, got {self.burn_critical}")


def default_policy() -> ServePolicy:
    """The process :class:`ServePolicy` with ``CYLON_TPU_SERVE_*`` env
    overrides (read per call so tests can flip them)."""
    e = os.environ
    slo = float(e.get("CYLON_TPU_SERVE_SLO", "0"))
    mem = int(e.get("CYLON_TPU_SERVE_MEMORY_BUDGET", "0"))
    target = float(e.get("CYLON_TPU_SERVE_SLO_TARGET", "0"))
    latency = float(e.get("CYLON_TPU_SERVE_SLO_LATENCY", "0"))
    windows = tuple(
        float(w) for w in
        e.get("CYLON_TPU_SERVE_SLO_WINDOWS", "60,300").split(",")
        if w.strip())
    return ServePolicy(
        max_queue=int(e.get("CYLON_TPU_SERVE_MAX_QUEUE", "64")),
        default_slo=slo if slo > 0 else None,
        schedule=e.get("CYLON_TPU_SERVE_SCHEDULE", "roundrobin"),
        breaker_fails=int(e.get("CYLON_TPU_SERVE_BREAKER_FAILS", "5")),
        breaker_window=float(
            e.get("CYLON_TPU_SERVE_BREAKER_WINDOW", "30")),
        breaker_cooldown=float(
            e.get("CYLON_TPU_SERVE_BREAKER_COOLDOWN", "5")),
        memory_budget=mem if mem > 0 else None,
        slo_target=target if target > 0 else None,
        slo_latency=latency if latency > 0 else None,
        slo_windows=windows or (60.0, 300.0),
        burn_critical=float(
            e.get("CYLON_TPU_SERVE_BURN_CRITICAL", "10")),
    )


class CircuitBreaker:
    """Failure-storm gate: open = shed new admissions, drain in-flight.

    ``record_failure(kind)`` feeds request retirements whose error
    class signals systemic overload (:data:`BREAKING_KINDS` — SLO
    storms and resource exhaustion, NOT per-request bugs); when
    ``threshold`` such failures land within ``window`` seconds the
    breaker OPENS. While open, :meth:`allow` is False — the admission
    controller sheds with a fast ResourceExhausted — until ``cooldown``
    seconds pass, when the breaker half-opens: the failure ledger
    clears and admissions probe through (a fresh storm re-trips it). A
    success in the closed state clears the ledger — only *sustained*
    storms trip. ``threshold <= 0`` disables the breaker entirely.

    The failure window rides the shared sliding-window machinery
    (:class:`~cylon_tpu.telemetry.timeseries.EventWindow` — ISSUE 14),
    and the breaker's state is OBSERVABLE instead of private:
    :meth:`snapshot` reports state (``closed``/``open``/``half_open``
    — half-open = cooldown elapsed, next admission probes through),
    cooldown remaining and the windowed failure count; ``/healthz``
    and the ``/health`` verdict both read it, and open/close
    transitions land in the structured event journal
    (``breaker_open``/``breaker_close``)."""

    #: error type names that count toward tripping: the systemic-
    #: overload classes (a deadline storm from a wedged mesh, resource
    #: exhaustion from an HBM cascade). Per-request failures
    #: (InvalidArgument, a query bug) never trip the breaker.
    BREAKING_KINDS = frozenset({"DeadlineExceeded", "ResourceExhausted"})

    def __init__(self, threshold: int = 5, window: float = 30.0,
                 cooldown: float = 5.0):
        self.threshold = int(threshold)
        self.window = float(window)
        self.cooldown = float(cooldown)
        self._mu = threading.Lock()
        #: windowed failure ledger — O(slots) memory however large the
        #: storm (the old deque of timestamps grew with it)
        self._failures = EventWindow(self.window)
        self._opened_at: "float | None" = None

    def _state_locked(self, now: float) -> str:
        if self._opened_at is None:
            return "closed"
        if now - self._opened_at < self.cooldown:
            return "open"
        return "half_open"  # next allow() probes through

    @property
    def state(self) -> str:
        with self._mu:
            return self._state_locked(time.monotonic())

    def snapshot(self) -> dict:
        """Observable breaker state (the ``/healthz`` + ``/health``
        payload): state, seconds of cooldown remaining (0 unless
        open), and the current windowed failure count."""
        now = time.monotonic()
        with self._mu:
            state = self._state_locked(now)
            remaining = (max(self.cooldown - (now - self._opened_at),
                             0.0) if self._opened_at is not None
                         else 0.0)
            failures = self._failures.count(now)
        return {"state": state,
                "cooldown_remaining_s": round(remaining, 3),
                "window_failures": failures,
                "threshold": self.threshold,
                "window_s": self.window,
                "cooldown_s": self.cooldown}

    def record_failure(self, kind: str) -> None:
        if self.threshold <= 0 or kind not in self.BREAKING_KINDS:
            return
        now = time.monotonic()
        with self._mu:
            self._failures.add(1, now=now)
            n = self._failures.count(now)
            if self._opened_at is None and n >= self.threshold:
                self._opened_at = now
                telemetry.counter("serve.breaker_trips").inc()
                telemetry.gauge("serve.breaker_open").set(1)
                tripped = True
            else:
                tripped = False
        if tripped:
            _events.emit("breaker_open", failures=n,
                         window_s=self.window,
                         cooldown_s=self.cooldown)

    def record_success(self) -> None:
        """A completed request in the closed state clears the streak
        (the storm was not sustained)."""
        with self._mu:
            if self._opened_at is None:
                self._failures.clear()

    def allow(self) -> bool:
        """May a new request be admitted right now? Transitions
        open → half-open after ``cooldown`` (ledger cleared, admissions
        probe through)."""
        if self.threshold <= 0:
            return True
        now = time.monotonic()
        with self._mu:
            if self._opened_at is None:
                return True
            if now - self._opened_at < self.cooldown:
                return False
            # half-open: let traffic probe; a fresh storm re-trips
            open_s = now - self._opened_at
            self._opened_at = None
            self._failures.clear()
            telemetry.gauge("serve.breaker_open").set(0)
        _events.emit("breaker_close", open_s=round(open_s, 3))
        return True


class AdmissionController:
    """The queue-depth gate in front of the scheduler.

    ``admit(tenant)`` either takes one live slot or raises
    :class:`~cylon_tpu.errors.ResourceExhausted` naming the depth and
    cap (counted per tenant as ``serve.rejected{tenant=}``); every
    admit is balanced by exactly one ``release()`` when the request
    retires (done, failed, or expired). ``serve.queue_depth`` gauges
    the live count after every transition."""

    def __init__(self, policy: "ServePolicy | None" = None):
        self.policy = policy or default_policy()
        self._mu = threading.Lock()
        self._live = 0
        self.breaker = CircuitBreaker(
            threshold=self.policy.breaker_fails,
            window=self.policy.breaker_window,
            cooldown=self.policy.breaker_cooldown)

    @property
    def live(self) -> int:
        with self._mu:
            return self._live

    def admit(self, tenant: str,
              predicted_bytes: "int | None" = None) -> None:
        budget = self.policy.memory_budget
        if (budget and predicted_bytes is not None
                and predicted_bytes > budget):
            # memory-aware shed: a request PREDICTED not to fit is
            # refused at the front door (microseconds) instead of
            # dying minutes later in an HBM cascade — the admission
            # twin of the fallback executor's pre-flight
            telemetry.counter("serve.shed", reason="memory",
                              tenant=tenant).inc()
            telemetry.counter("serve.rejected", tenant=tenant).inc()
            _events.emit("shed", tenant=tenant, reason="memory")
            raise ResourceExhausted(
                f"predicted memory {predicted_bytes} bytes exceeds "
                f"the serve memory budget {budget} (tenant "
                f"{tenant!r}); shed — submit with a fallback= spill "
                "path, reduce the working set, or raise "
                "CYLON_TPU_SERVE_MEMORY_BUDGET")
        if not self.breaker.allow():
            # open breaker: shed BEFORE taking a slot — in-flight work
            # keeps draining, new work is refused in microseconds
            telemetry.counter("serve.shed", reason="breaker",
                              tenant=tenant).inc()
            telemetry.counter("serve.rejected", tenant=tenant).inc()
            _events.emit("shed", tenant=tenant, reason="breaker")
            raise ResourceExhausted(
                f"serve circuit breaker open (sustained "
                f"DeadlineExceeded/ResourceExhausted storm; tenant "
                f"{tenant!r}): shedding new admissions while in-flight "
                f"work drains; retry after "
                f"{self.policy.breaker_cooldown:.1f}s")
        with self._mu:
            if self._live >= self.policy.max_queue:
                depth = self._live
                admitted = False
            else:
                self._live += 1
                depth = self._live
                admitted = True
        telemetry.gauge("serve.queue_depth").set(depth)
        if not admitted:
            telemetry.counter("serve.shed", reason="queue_full",
                              tenant=tenant).inc()
            telemetry.counter("serve.rejected", tenant=tenant).inc()
            _events.emit("shed", tenant=tenant, reason="queue_full")
            raise ResourceExhausted(
                f"serve queue full: {depth} live requests >= cap "
                f"{self.policy.max_queue} (tenant {tenant!r}); "
                "back off and retry")

    def release(self) -> None:
        with self._mu:
            self._live = max(self._live - 1, 0)
            depth = self._live
        telemetry.gauge("serve.queue_depth").set(depth)
