"""Error model.

The reference threads a ``cylon::Status{code, msg}`` value through every
call (``cpp/src/cylon/status.hpp:1-66``, codes in ``cpp/src/cylon/code.hpp:20-40``).
A TPU/JAX rebuild is Python-first, so statuses become exceptions; the
:class:`Code` enum is preserved for parity so callers can still switch on
machine-readable codes (``exc.code``).
"""

import enum


class Code(enum.IntEnum):
    """Parity with ``cpp/src/cylon/code.hpp:20-40``."""

    OK = 0
    OutOfMemory = 1
    KeyError = 2
    TypeError = 3
    Invalid = 4
    IOError = 5
    CapacityError = 6
    IndexError = 7
    UnknownError = 9
    NotImplemented = 10
    SerializationError = 11
    GpuMemoryError = 12  # kept for numeric parity; unused on TPU
    RError = 13
    # 14/15/16 are unused by the reference enum; 14/15 take the gRPC
    # UNAVAILABLE / DATA_LOSS numbers for the resilience layer
    # (cylon_tpu.resilience) — the reference has no recovery story to
    # mirror, so these are TPU-rebuild extensions, not parity codes.
    # gRPC's DEADLINE_EXCEEDED number (4) is already the reference's
    # Invalid, so the deadline/watchdog layer (cylon_tpu.watchdog)
    # takes the next free slot instead.
    Unavailable = 14
    DataLoss = 15
    DeadlineExceeded = 16
    # serving-layer extensions (cylon_tpu.serve): 8 takes gRPC's
    # RESOURCE_EXHAUSTED number (free in the reference enum); gRPC's
    # FAILED_PRECONDITION number (9) is already the reference's
    # UnknownError, so it takes the next free slot after the deadline
    # code instead.
    ResourceExhausted = 8
    FailedPrecondition = 17
    CodeGenError = 40
    ExpressionValidationError = 41
    ExecutionError = 42
    AlreadyExists = 45


class CylonError(Exception):
    """Base class; carries a :class:`Code` like ``cylon::Status``."""

    code: Code = Code.UnknownError

    def __init__(self, msg: str = "", code: "Code | None" = None):
        super().__init__(msg)
        if code is not None:
            self.code = code


class InvalidArgument(CylonError):
    code = Code.Invalid


class KeyError_(CylonError):
    code = Code.KeyError


class TypeError_(CylonError):
    code = Code.TypeError


class IndexError_(CylonError):
    code = Code.IndexError


class IOError_(CylonError):
    code = Code.IOError


class NotImplemented_(CylonError):
    code = Code.NotImplemented


class TransientError(CylonError):
    """A failure that retrying is expected to fix: worker preemption,
    flaky IO, an injected fault. :func:`cylon_tpu.resilience.is_retryable`
    keys on this class (and on ``Code.Unavailable`` generally) — raise it
    from any source that wants the retry engine to re-attempt."""

    code = Code.Unavailable


class DataLossError(CylonError):
    """A row-accounting invariant failed: a multi-pass pipeline saw a
    different number of rows going in than coming out. This converts
    silent truncation (an exhausted iterator, a dropped spill bucket, a
    lossy exchange) into a loud failure. Never retryable — the data is
    already gone; the source or manifest must be repaired."""

    code = Code.DataLoss


class DeadlineExceeded(CylonError):
    """A named blocking section (``cylon_tpu.watchdog``) stalled past
    its deadline: a barrier no peer completed, a multihost bootstrap
    whose coordinator never answered, a device fetch against a wedged
    chip, spill IO against a hung filesystem. The watchdog dumps
    all-thread stacks to stderr before this is raised, so the stall
    site is diagnosable post-mortem.

    ``retryable`` is classified per section
    (:data:`cylon_tpu.watchdog.SECTIONS`): bootstrap/IO deadlines may
    heal on retry (a preempted peer rejoins, a mount recovers);
    mid-collective deadlines never do — the mesh state is
    unrecoverable, a re-issued collective would deadlock against the
    half-completed one. :func:`cylon_tpu.resilience.is_retryable`
    consults this flag."""

    code = Code.DeadlineExceeded

    def __init__(self, msg: str = "", *, section: "str | None" = None,
                 elapsed: "float | None" = None, retryable: bool = False):
        super().__init__(msg)
        self.section = section
        self.elapsed = elapsed
        self.retryable = bool(retryable)


class FailedPrecondition(CylonError):
    """The operation is valid in general but not against the current
    state of the system: dropping a catalog table that an in-flight
    query still pins (:func:`cylon_tpu.catalog.drop` names the
    holders), closing a session with live requests. Not retryable as-is
    — the caller must change the state (unpin, drain) first. Without
    this the failure surfaced as a confusing late ``KeyError`` deep in
    whichever query lost the race."""

    code = Code.FailedPrecondition


class ResourceExhausted(CylonError):
    """A bounded serving resource is at capacity — the admission queue
    of :class:`cylon_tpu.serve.ServeEngine` is full. Raised FAST at
    submit time (the serving layer's load-shedding contract: reject in
    microseconds instead of piling requests onto a saturated mesh).
    Retryable from the *client's* side after backoff, but never
    auto-retried by the engine — re-queueing internally would just
    rebuild the pile-up the cap exists to prevent."""

    code = Code.ResourceExhausted


class OutOfCapacity(CylonError):
    """A capacity-bounded kernel produced more rows than its static bound.

    No reference analog: XLA requires static shapes, so data-dependent
    result sizes (joins, filters) are materialised into caller-bounded
    buffers; overflowing the bound raises this (host-side check).
    """

    code = Code.CapacityError
