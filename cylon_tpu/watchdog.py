"""Deadline & watchdog layer: bounded blocking, hang detection, stall
diagnostics.

Every host-side blocking point in the engine used to wait forever —
``CylonEnv.barrier``, the multihost bootstrap, the batched
``jax.device_get`` overflow fetch, spill IO, the out-of-core passes and
the mesh exchange dispatch. A single hung peer or wedged device turned
a distributed query into a silent, diagnostics-free stall; the
resilience layer's retries (:mod:`cylon_tpu.resilience`) only fire on
*raised* errors, and a hang never raises. This module closes that gap
with three primitives threaded through every blocking layer:

1. :func:`deadline` — a contextvar-propagated scope: every named
   blocking section entered while it is active is bounded by it.
   Nesting takes the minimum (an inner, tighter deadline wins; an
   inner, looser one cannot extend the outer budget).

2. :func:`bounded` — run a blocking callable under the section's
   effective deadline. Fast path first: with no ambient scope, no
   explicit timeout and no ``CYLON_TPU_DEADLINE_<SECTION>`` env
   default, the callable runs inline with zero bookkeeping — no
   monitor thread exists, no worker thread is spawned. Under a
   deadline the callable runs on a daemon worker thread and the caller
   waits at most the remaining budget; on expiry the watchdog dumps
   all-thread stacks (section label + elapsed time in the header), and
   a :class:`~cylon_tpu.errors.DeadlineExceeded` naming the section is
   raised. The stalled worker thread is abandoned — by definition it
   cannot be interrupted, and leaking it is the price of unblocking
   the caller.

3. :func:`watched_section` / :func:`watched` / :func:`check` — for
   regions that must run on the calling thread (a dispatched
   collective cannot be cancelled or moved): the monitor still detects
   the stall and dumps stacks *while it is stuck*, the region raises
   on exit if the deadline passed, and :func:`check` checkpoints
   inside chunked loops raise promptly between units of work.

Classification hooks into the retry engine:
:data:`SECTIONS` maps each section to whether its deadline is
retryable — ``bootstrap``/``spill_io`` are (a preempted peer rejoins,
a mount recovers), mid-collective sections are not (the mesh state is
unrecoverable) — and ``resilience.is_retryable`` consults the flag, so
``retrying(lambda: bounded(fn, "bootstrap"))`` re-attempts a bounded
bootstrap exactly like a raised connection error.

Section completions feed the telemetry registry
(:mod:`cylon_tpu.telemetry`: per-section latency histograms, expiry
counters and a bounded raw-record history) — always for
:func:`watched_section` regions, and for :func:`bounded` ones whenever
a deadline was in play (the no-deadline fast path stays record-free by
design); :func:`timings` / :func:`straggler_report` are views over
that registry (``clear_timings()`` is the registry reset scoped to
the ``watchdog.`` namespace — no second store exists) for straggler
analysis — the host-side twin of the reference exchange's
``isComplete()`` progress visibility.

Hangs are injectable deterministically: ``FaultRule(point,
delay=0.25)`` (or the ``FaultRule.hang`` alias) makes
:func:`cylon_tpu.resilience.inject` sleep at a fault point instead of
raising, so the whole layer is testable at tier-1 with millisecond
thresholds.
"""

import contextlib
import contextvars
import dataclasses
import functools
import os
import sys
import threading
import time
import traceback

from cylon_tpu import telemetry
from cylon_tpu.config import DEADLINE_SECTIONS, DeadlinePolicy
from cylon_tpu.errors import DeadlineExceeded, InvalidArgument

__all__ = [
    "SECTIONS", "deadline", "active_deadline", "remaining",
    "default_deadline_policy", "section_default", "bounded",
    "watched_section", "watched", "check", "dump_stacks",
    "active_sections", "timings", "clear_timings", "straggler_report",
]

#: Named blocking sections -> is a deadline there retryable?
#: ``bootstrap`` and ``spill_io`` deadlines retry (the peer may rejoin,
#: the mount may recover — same failure domain the retry engine already
#: wraps); ``barrier`` / ``overflow_fetch`` / ``exchange`` / ``ooc_pass``
#: never do: a collective that stalled left the mesh in an unknowable
#: half-completed state, and re-issuing it deadlocks against the first.
SECTIONS: "dict[str, bool]" = {
    "barrier": False,
    "bootstrap": True,
    "overflow_fetch": False,
    "spill_io": True,
    "ooc_pass": False,
    # one unit of pipelined ingest on a prefetch worker
    # (cylon_tpu.pipeline) — never retryable on its own: the expiry
    # surfaces on the consuming pass, whose ooc_pass section already
    # says the mesh/pass state is unrecoverable
    "ooc_prefetch": False,
    "exchange": False,
    # one admitted serve request's execution step (cylon_tpu.serve) —
    # never engine-retryable: re-running a half-executed query after
    # its SLO passed only deepens the pile-up; the retry decision
    # belongs to the client
    "serve_request": False,
    # one fleet-router poll of one engine's /health + /events cursor
    # (cylon_tpu.serve.fleet) — retryable: a poll is a read against a
    # possibly-dying HTTP endpoint, and the router's whole failure
    # model is "retry, then declare the engine dead"
    "router_poll": True,
    # the two-phase fallback's global merge (cylon_tpu.fallback):
    # the blocking scalar between the partial pass and the apply
    # pass — never retryable on its own: the merge is deterministic
    # host compute over durable partials, so a deadline there means
    # the partials (or the journal write) are wedged, and a blind
    # re-merge would just wedge again; resume via the checkpoint
    "fallback_merge": False,
}

# the retryability registry here and the budget-defaults registry in
# config must cover the same sections — a key added to one but not the
# other would silently mean "unbounded"/"non-retryable" for it
if set(SECTIONS) != set(DEADLINE_SECTIONS):  # pragma: no cover
    raise AssertionError(
        "watchdog.SECTIONS and config.DEADLINE_SECTIONS diverged: "
        f"{sorted(set(SECTIONS) ^ set(DEADLINE_SECTIONS))}")


def default_deadline_policy() -> DeadlinePolicy:
    """The process :class:`~cylon_tpu.config.DeadlinePolicy`, with env
    overrides (read per call so tests can flip them)."""
    e = os.environ
    return DeadlinePolicy(
        poll_interval=float(e.get("CYLON_TPU_WATCHDOG_POLL", "0.05")),
        action=e.get("CYLON_TPU_DEADLINE_ACTION", "raise"),
        dump_stacks=e.get("CYLON_TPU_DEADLINE_DUMP", "1")
        not in ("0", "off"),
    )


def section_default(section: str) -> "float | None":
    """Default budget for ``section``: ``CYLON_TPU_DEADLINE_<SECTION>``
    if set (``<= 0`` = unbounded), else the
    :data:`cylon_tpu.config.DEADLINE_SECTIONS` table."""
    v = os.environ.get(f"CYLON_TPU_DEADLINE_{section.upper()}")
    if v is not None:
        try:
            f = float(v)
        except ValueError:
            raise InvalidArgument(
                f"CYLON_TPU_DEADLINE_{section.upper()}={v!r} is not a "
                "number of seconds") from None
        return f if f > 0 else None
    return DEADLINE_SECTIONS.get(section)


# -------------------------------------------------------- deadline scope
class Deadline:
    """An absolute expiry on the monotonic clock (scope-internal)."""

    __slots__ = ("expires_at", "label")

    def __init__(self, expires_at: float, label: str):
        self.expires_at = expires_at
        self.label = label

    def remaining(self) -> float:
        return self.expires_at - time.monotonic()

    def __repr__(self):
        return f"Deadline({self.label!r}, {self.remaining():.3f}s left)"


_SCOPE: contextvars.ContextVar = contextvars.ContextVar(
    "cylon_deadline", default=None)

#: innermost live watched_section for this context — lets check()
#: honour a section budget that came from an env default or explicit
#: timeout, not only from an ambient deadline() scope
_ACTIVE_SECTION: contextvars.ContextVar = contextvars.ContextVar(
    "cylon_watched_section", default=None)


@contextlib.contextmanager
def deadline(seconds: float, label: str = "deadline"):
    """Bound every named blocking section entered in this scope.

    Contextvar-propagated (worker threads spawned by :func:`bounded`
    copy the context, so nested sections inside the worker see it too).
    Nested scopes take the minimum absolute expiry: an inner, tighter
    deadline wins; an inner, looser one cannot extend the outer budget.
    """
    exp = time.monotonic() + float(seconds)
    outer = _SCOPE.get()
    if outer is not None:
        exp = min(exp, outer.expires_at)
    tok = _SCOPE.set(Deadline(exp, label))
    try:
        yield _SCOPE.get()
    finally:
        _SCOPE.reset(tok)


def active_deadline() -> "Deadline | None":
    return _SCOPE.get()


def remaining() -> "float | None":
    """Seconds left on the ambient deadline (None = no scope active)."""
    d = _SCOPE.get()
    return None if d is None else d.remaining()


# ------------------------------------------------------ stall diagnostics
def dump_stacks(header: str, file=None) -> None:
    """Write ``header`` plus every thread's current stack to ``file``
    (default stderr). Pure-Python (``sys._current_frames``), so it
    works under captured/redirected stderr where ``faulthandler``'s
    fd-level dump cannot."""
    out = file if file is not None else sys.stderr
    names = {t.ident: t.name for t in threading.enumerate()}
    lines = [f"\n=== {header} ===\n"]
    for tid, frame in sys._current_frames().items():
        lines.append(f"--- thread {names.get(tid, '?')!r} "
                     f"(ident {tid}) ---\n")
        lines.extend(traceback.format_stack(frame))
    lines.append("=== end cylon_tpu watchdog dump ===\n")
    try:
        out.write("".join(lines))
        out.flush()
    except Exception:
        pass  # diagnostics must never mask the stall itself


@dataclasses.dataclass
class SectionTiming:
    """One completed section, queryable via :func:`timings` for
    straggler reporting. ``dump_after`` is seconds from section start
    to the watchdog's stack dump (None = never stalled); ``tenant`` is
    the serve-layer attribution active when the section completed
    (None outside a tenant scope)."""

    section: str
    detail: str
    elapsed: float
    budget: "float | None"
    expired: bool
    dump_after: "float | None" = None
    tenant: "str | None" = None


#: telemetry series section completions feed (the registry is the one
#: source of truth — there is no private deque any more):
#: per-section latency histogram, expiry counter, raw-record history
SECTION_TIMER = "watchdog.section_seconds"
SECTION_EXPIRED = "watchdog.sections_expired"
SECTION_RECORDS = "watchdog.section_timings"


def timings(section: "str | None" = None,
            tenant: "str | None" = None) -> "list[SectionTiming]":
    """Completed-section timing records, newest last (bounded history,
    read from the telemetry registry's record store), optionally
    filtered to one section and/or one serve-layer tenant."""
    recs = telemetry.get_records(SECTION_RECORDS)
    if section is not None:
        recs = [r for r in recs if r.section == section]
    if tenant is not None:
        recs = [r for r in recs
                if getattr(r, "tenant", None) == str(tenant)]
    return recs


def clear_timings() -> None:
    """Clear the section history: ONE registry operation —
    ``telemetry.reset("watchdog.")`` — because the history lives only
    in the telemetry registry (no private deque to clear separately,
    so the two can never diverge; a full ``telemetry.reset()`` clears
    it too). Scoped to the ``watchdog.`` namespace so an operator
    resetting straggler stats between query phases does not destroy
    the run's exchange/spill/plan counters."""
    telemetry.reset("watchdog.")


def straggler_report(timeline: "list | None" = None,
                     tenant: "str | None" = None) -> dict:
    """Per-section aggregate: count, mean/max elapsed, and how many
    expired — the quickest way to see which blocking layer is the
    straggler. A pure view over the telemetry registry (the
    :data:`SECTION_TIMER` histograms and :data:`SECTION_EXPIRED`
    counters), not a second accumulation. Sections split across
    tenant-labeled series merge per section; ``tenant=`` restricts the
    report to one serve-layer tenant's sections — isolating its
    stragglers from a mixed multi-tenant workload.

    **Fleet-aware form**: pass ``timeline`` — a merged multi-rank event
    list from :func:`cylon_tpu.telemetry.trace.merge_timelines` (per-
    rank buffers via ``trace.rank_buffers`` / ``gather_traces``) — and
    the report instead walks the timeline and NAMES the straggler:
    ``{"straggler_rank", "dominant_stage", "excess_seconds",
    "rank_walls", "stage_seconds", ...}``
    (:func:`cylon_tpu.telemetry.trace.critical_path`); ``tenant=``
    first slices the timeline to that tenant's events
    (:func:`cylon_tpu.telemetry.trace.filter_tenant`). The local form
    can only say which *section* is slow on this host; the fleet form
    says which *rank* is slow and in which stage."""
    if timeline is not None:
        if tenant is not None:
            timeline = telemetry.trace.filter_tenant(timeline, tenant)
        return telemetry.trace.critical_path(timeline)
    agg: dict[str, dict] = {}
    for _, labels, inst in telemetry.instruments(SECTION_TIMER):
        sec = labels.get("section", "?")
        if not inst.count:
            continue
        if tenant is not None and labels.get("tenant") != str(tenant):
            continue
        a = agg.setdefault(sec, {"count": 0, "total_s": 0.0,
                                 "max_s": 0.0, "expired": 0})
        a["count"] += inst.count
        a["total_s"] += inst.sum
        a["max_s"] = max(a["max_s"],
                         inst.max if inst.max is not None else 0.0)
    for _, labels, inst in telemetry.instruments(SECTION_EXPIRED):
        sec = labels.get("section", "?")
        if sec not in agg:
            continue
        if tenant is not None and labels.get("tenant") != str(tenant):
            continue
        agg[sec]["expired"] += inst.value
    for a in agg.values():
        a["mean_s"] = a["total_s"] / a["count"]
    return agg


class _Section:
    """A live blocking section the monitor watches."""

    __slots__ = ("section", "detail", "started", "expires_at", "budget",
                 "thread_name", "dumped", "dump_after", "dump_event")

    def __init__(self, section, detail, started, expires_at, budget):
        self.section = section
        self.detail = detail
        self.started = started
        self.expires_at = expires_at
        self.budget = budget
        self.thread_name = threading.current_thread().name
        self.dumped = False
        self.dump_after: "float | None" = None
        self.dump_event = threading.Event()


def _finish(rec: _Section, expired: bool) -> None:
    elapsed = time.monotonic() - rec.started
    tl = telemetry.tenant_labels()
    telemetry.timer(SECTION_TIMER, section=rec.section,
                    **tl).observe(elapsed)
    if expired:
        telemetry.counter(SECTION_EXPIRED, section=rec.section,
                          **tl).inc()
    telemetry.add_record(SECTION_RECORDS, SectionTiming(
        rec.section, rec.detail, elapsed, rec.budget, expired,
        rec.dump_after, tenant=tl.get("tenant")))
    # flight recorder: one complete slice per section, cat="stage" — the
    # unit trace.critical_path attributes straggler wall time to (the
    # section start exists only in monotonic time, so the recorder
    # back-dates it from the elapsed duration)
    telemetry.trace.complete(rec.section, elapsed, cat="stage",
                             detail=rec.detail, expired=expired)


# ------------------------------------------------------------- the monitor
class _Monitor:
    """Lazily-started daemon thread watching live sections. Event-driven:
    sleeps until the earliest undumped expiry (clamped by the policy
    poll interval), indefinitely when nothing is registered — a process
    that never enters a deadline scope never starts it at all."""

    def __init__(self):
        self._cond = threading.Condition()
        self._live: "dict[int, _Section]" = {}
        self._thread: "threading.Thread | None" = None

    @property
    def thread(self) -> "threading.Thread | None":
        return self._thread

    def register(self, rec: _Section) -> None:
        with self._cond:
            self._live[id(rec)] = rec
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, name="cylon-tpu-watchdog",
                    daemon=True)
                self._thread.start()
            self._cond.notify()

    def unregister(self, rec: _Section) -> None:
        with self._cond:
            self._live.pop(id(rec), None)

    def ensure_fired(self, rec: _Section) -> None:
        """Dump for ``rec`` if the monitor has not yet (closes the race
        between a bounded call's own join timeout and the monitor's
        wake-up, so the stacks are always on stderr BEFORE the caller's
        DeadlineExceeded propagates)."""
        with self._cond:
            if rec.dumped:
                claimed = False
            else:
                rec.dumped = claimed = True
        if claimed:
            self._fire(rec)
        else:
            rec.dump_event.wait(timeout=5.0)

    def _loop(self):
        while True:
            due = []
            with self._cond:
                if not self._live:
                    self._cond.wait()
                    continue
                now = time.monotonic()
                nxt = None
                for rec in self._live.values():
                    if rec.dumped:
                        continue
                    if now >= rec.expires_at:
                        rec.dumped = True
                        due.append(rec)
                    else:
                        nxt = rec.expires_at if nxt is None \
                            else min(nxt, rec.expires_at)
                if not due:
                    if nxt is not None:
                        # expiries are immutable and new registrations
                        # notify the condition, so sleeping exactly to
                        # the earliest expiry is safe — no periodic
                        # polling while sections are merely in flight
                        wait = max(0.001, nxt - now)
                    else:
                        # only already-dumped (still-stalled) sections
                        # remain: re-scan at the policy poll interval
                        # as a belt-and-braces fallback
                        wait = max(
                            0.001,
                            default_deadline_policy().poll_interval)
                    self._cond.wait(timeout=wait)
                    continue
            for rec in due:
                self._fire(rec)

    def _fire(self, rec: _Section) -> None:
        now = time.monotonic()
        rec.dump_after = now - rec.started
        telemetry.trace.instant("watchdog.expired", cat="watchdog",
                                section=rec.section, detail=rec.detail,
                                elapsed=rec.dump_after,
                                budget=rec.budget)
        telemetry.events.emit("watchdog_expired", section=rec.section,
                              detail=rec.detail,
                              elapsed_s=rec.dump_after,
                              budget_s=rec.budget)
        pol = default_deadline_policy()
        header = (
            f"cylon_tpu watchdog: section {rec.section!r}"
            + (f" ({rec.detail})" if rec.detail else "")
            + f" stalled {now - rec.started:.3f}s"
            + (f" (budget {rec.budget:.3f}s)" if rec.budget is not None
               else "")
            + f", entered on thread {rec.thread_name!r}"
        )
        if pol.dump_stacks:
            dump_stacks(header)
        if pol.action == "abort":
            try:
                sys.stderr.write(
                    "cylon_tpu watchdog: abort policy — exiting 70\n")
                sys.stderr.flush()
            finally:
                os._exit(70)
        # set LAST: the event means "firing (incl. any abort action)
        # is complete", so ensure_fired waiters cannot race ahead of a
        # test-patched os._exit
        rec.dump_event.set()


_MONITOR = _Monitor()


def active_sections() -> "list[tuple[str, str, float]]":
    """(section, detail, elapsed) for every currently-registered live
    section — what the process is blocked on right now."""
    now = time.monotonic()
    with _MONITOR._cond:
        return [(r.section, r.detail, now - r.started)
                for r in _MONITOR._live.values()]


# --------------------------------------------------------- the primitives
def _require_section(section: str) -> None:
    if section not in SECTIONS:
        raise InvalidArgument(
            f"unknown watchdog section {section!r}; valid: "
            f"{tuple(SECTIONS)}")


def _effective(section: str, timeout: "float | None"):
    """(absolute expiry | None, budget seconds | None): the minimum of
    the explicit timeout, the ambient deadline scope, and the section's
    env/config default."""
    now = time.monotonic()
    exp = None if timeout is None else now + float(timeout)
    d = _SCOPE.get()
    if d is not None:
        exp = d.expires_at if exp is None else min(exp, d.expires_at)
    sd = section_default(section)
    if sd is not None:
        e2 = now + sd
        exp = e2 if exp is None else min(exp, e2)
    return exp, (None if exp is None else max(0.0, exp - now))


def _exceeded(section: str, detail: str, elapsed: float,
              budget: "float | None",
              retryable: "bool | None" = None) -> DeadlineExceeded:
    if retryable is None:
        retryable = SECTIONS.get(section, False)
    msg = (
        f"deadline exceeded in section {section!r}"
        + (f" ({detail})" if detail else "")
        + f": {elapsed:.3f}s elapsed"
        + (f", budget {budget:.3f}s" if budget is not None else "")
        + ("; retryable" if retryable
           else "; not retryable")
    )
    return DeadlineExceeded(msg, section=section, elapsed=elapsed,
                            retryable=retryable)


def bounded(fn, section: str, *, timeout: "float | None" = None,
            detail: str = ""):
    """Call ``fn()`` bounded by ``section``'s effective deadline.

    Fast path: with no ambient :func:`deadline` scope, no ``timeout``
    and no env default for the section, ``fn`` runs inline — no
    threads, no records, byte-for-byte the old unbounded behaviour.

    Bounded path: ``fn`` runs on a daemon worker thread (with the
    caller's contextvars copied in) and the caller waits at most the
    remaining budget. On expiry the watchdog dumps all-thread stacks —
    including the stuck worker's, which is the diagnostic payload —
    and :class:`~cylon_tpu.errors.DeadlineExceeded` naming the section
    is raised (or the process aborts, per
    :class:`~cylon_tpu.config.DeadlinePolicy`). The stalled worker is
    abandoned: it cannot be interrupted, and unblocking the caller is
    the contract."""
    _require_section(section)
    exp, budget = _effective(section, timeout)
    if exp is None:
        return fn()
    now = time.monotonic()
    if exp <= now:
        # out of budget before starting: never retryable — the expiry
        # is absolute, so a re-attempt gets zero budget too. Recorded
        # in the timing history; no dump (nothing stalled)
        _finish(_Section(section, detail, now, exp, budget), True)
        raise _exceeded(section, detail, 0.0, budget, retryable=False)
    rec = _Section(section, detail, now, exp, budget)
    _MONITOR.register(rec)
    box: dict = {}
    ctx = contextvars.copy_context()

    def _run():
        try:
            box["r"] = ctx.run(fn)
        except BaseException as e:  # rethrown on the caller thread
            box["e"] = e

    worker = threading.Thread(target=_run, daemon=True,
                              name=f"cylon-bounded-{section}")
    expired = False
    try:
        worker.start()
        worker.join(exp - time.monotonic())
        if worker.is_alive() and "r" not in box and "e" not in box:
            expired = True
            _MONITOR.ensure_fired(rec)  # stacks hit stderr before raise
            raise _exceeded(section, detail,
                            time.monotonic() - rec.started, budget)
    finally:
        _MONITOR.unregister(rec)
        _finish(rec, expired)
    if "e" in box:
        raise box["e"]
    return box.get("r")


@contextlib.contextmanager
def watched_section(section: str, *, timeout: "float | None" = None,
                    detail: str = ""):
    """Detection-only scope for blocking regions that must run on the
    calling thread (a dispatched collective cannot be cancelled or
    moved to a worker). The watchdog dumps all-thread stacks while the
    region is stuck past its deadline; if the deadline passed by the
    time the region completes, exit raises
    :class:`~cylon_tpu.errors.DeadlineExceeded` (a late raise — pair
    with :func:`check` checkpoints inside chunked loops for prompt
    ones). Always records a timing entry, deadline or not."""
    _require_section(section)
    exp, budget = _effective(section, timeout)
    rec = _Section(section, detail, time.monotonic(), exp, budget)
    if exp is not None and exp <= rec.started:
        # already out of budget on entry: refuse to start the region —
        # nothing stalled (no dump), and never retryable (the expiry
        # is absolute; a re-attempt gets zero budget too)
        _finish(rec, True)
        raise _exceeded(section, detail, 0.0, budget, retryable=False)
    if exp is not None:
        _MONITOR.register(rec)
    err = None
    tok = _ACTIVE_SECTION.set(rec)
    try:
        yield rec
    except Exception as e:
        err = e  # deadline verdict decided below; body error chained
    finally:
        _ACTIVE_SECTION.reset(tok)
        expired = exp is not None and time.monotonic() > exp
        if exp is not None:
            _MONITOR.unregister(rec)
        _finish(rec, expired)
    if expired and not isinstance(err, DeadlineExceeded):
        # the deadline is the operative failure: work past it is moot
        # whether it completed or broke (the body error stays chained)
        raise _exceeded(section, detail,
                        time.monotonic() - rec.started, budget) from err
    if err is not None:
        raise err


def watched(section: str, detail: str = ""):
    """Decorator form of :func:`watched_section`."""

    def deco(fn):
        lbl = detail or fn.__name__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with watched_section(section, detail=lbl):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def check(section: "str | None" = None, detail: str = "") -> None:
    """Cooperative checkpoint: raise
    :class:`~cylon_tpu.errors.DeadlineExceeded` if the ambient
    :func:`deadline` scope — or the enclosing
    :func:`watched_section`'s budget, however it was set (scope, env
    default, explicit timeout) — has expired. Two contextvar reads on
    the fast path — cheap enough for per-chunk/per-bucket loops.

    ``section=None`` (for checkpoints in caller-agnostic utilities,
    e.g. ``ops_graph.chunk_stream``) attributes the failure to the
    ENCLOSING live :func:`watched_section` when one exists — so the
    same checkpoint reports ``serve_request`` under the serving layer
    and ``ooc_pass`` inside an out-of-core pass — and to the generic
    non-retryable label ``"deadline"`` under a bare scope."""
    d = _SCOPE.get()
    exp = None if d is None else d.expires_at
    rec = _ACTIVE_SECTION.get()
    if rec is not None and rec.expires_at is not None:
        exp = rec.expires_at if exp is None \
            else min(exp, rec.expires_at)
    if exp is None:
        return
    now = time.monotonic()
    if now > exp:
        if section is None:
            section = rec.section if rec is not None else "deadline"
        else:
            _require_section(section)
        # report the enclosing section's true elapsed/budget when one
        # is live; bare-scope checkpoints can only report the overrun
        elapsed = now - rec.started if rec is not None else now - exp
        budget = rec.budget if rec is not None else None
        raise _exceeded(section, detail or "cooperative checkpoint",
                        elapsed, budget)
