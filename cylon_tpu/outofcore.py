"""Out-of-core relational ops: host-partitioned spill + per-partition
device compute.

The reference completes at every scale because its exchange allocates
receives dynamically as counts arrive (``net/ops/all_to_all.hpp:65-73``)
and it weak-scales by adding ranks (``docs/docs/arch.md:148-162``). A
single chip's HBM is a hard static ceiling instead — so beyond it, the
TPU-native answer is the classic grace-join structure the streaming
engine (:mod:`cylon_tpu.ops_graph`) already models, with the partition
buffers spilled to HOST memory:

- **partition phase**: stream fixed-size chunks (host numpy or a
  :func:`cylon_tpu.io.read_parquet_chunks` iterator); hash-split each
  chunk's rows into ``n_partitions`` host buckets (the same
  murmur-derived row hash every device shuffle uses, so the partition
  boundary is identical to a mesh shuffle's);
- **compute phase**: per partition, move ONE bucket pair onto the
  device, run the normal fused join/groupby program, spill the result
  back to host.

Device memory never holds more than one partition's working set, host
memory holds the spilled partitions (DRAM is ~8x HBM on this class of
host, and the buffers are dense numpy — no serialisation). This is
deliberately the moral twin of ``DisJoinOp``'s
partition→shuffle→join graph (``ops/dis_join_op.cpp:21-72``): same
three stages, with "another rank's memory" replaced by "host DRAM".
"""

from typing import Callable, Iterable, Mapping, Sequence

import numpy as np

from cylon_tpu import pipeline, resilience, telemetry, watchdog
from cylon_tpu.telemetry import memory as _memory
from cylon_tpu.errors import DataLossError, InvalidArgument
from cylon_tpu.utils.tracing import span as _span

__all__ = ["host_partition_chunks", "ooc_join", "ooc_groupby", "ooc_sort"]


def _hash_u64(a: np.ndarray) -> np.ndarray:
    """Vectorised 64-bit mix (splitmix64 finalizer) — host twin of the
    device row hash: only cross-side CONSISTENCY matters (both sides of
    a join partition with the same function), not equality with the
    device murmur."""
    x = a.astype(np.uint64, copy=True)
    x ^= x >> np.uint64(30)
    x *= np.uint64(0xBF58476D1CE4E5B9)
    x ^= x >> np.uint64(27)
    x *= np.uint64(0x94D049BB133111EB)
    x ^= x >> np.uint64(31)
    return x


def _row_hash(cols: Sequence[np.ndarray]) -> np.ndarray:
    h = np.zeros(len(cols[0]), np.uint64)
    for c in cols:
        if c.dtype.kind in ("U", "O", "S"):
            # string keys: stable per-value hash via factorize-like map
            uniq, inv = np.unique(np.asarray(c, dtype=str),
                                  return_inverse=True)
            vh = np.array([hash(v) & 0xFFFFFFFFFFFFFFFF for v in uniq],
                          np.uint64)
            k = vh[inv]
        elif c.dtype.kind == "f":
            k = c.view(np.uint64) if c.dtype.itemsize == 8 \
                else c.astype(np.float64).view(np.uint64)
        else:
            k = c.astype(np.int64).view(np.uint64)
        h = _hash_u64(h ^ _hash_u64(k))
    return h


def host_partition_chunks(chunks: Iterable[Mapping[str, np.ndarray]],
                          key_cols: Sequence[str],
                          n_partitions: int) -> list[dict]:
    """Partition phase: hash-split every chunk's rows into
    ``n_partitions`` host buckets. Returns one ``{col: np.ndarray}``
    dict per partition (dense concatenated spill buffers)."""
    def pid_of(cols):
        return (_row_hash([cols[k] for k in key_cols])
                % np.uint64(n_partitions)).astype(np.int64)

    return _scatter_chunks(chunks, pid_of, n_partitions)


def _resolve_source(src, op: str, chunk_rows: int):
    """Normalise ``src`` into a zero-arg factory of FRESH chunk
    iterators — the replayable-source contract every out-of-core pass
    needs (two-pass algorithms, retry after a transient fault, resume
    after a hard kill). Accepts a host column ``Mapping`` (sliced into
    chunks), a re-iterable of chunk dicts/Tables, or a zero-arg
    callable returning a fresh iterator. One-shot
    iterators/generators are REJECTED up front: a second iteration
    would silently see 0 rows and the pass would produce short output
    (``ooc_sort`` has had this guard since PR 1; ``ooc_join``/
    ``ooc_groupby`` route through it now too).

    Every factory routes its chunk stream through the SHARED prefetcher
    (:func:`cylon_tpu.pipeline.prefetched`): chunk k+1's pull — IO
    read, parquet decode, ``Table.to_pandas`` — runs on a
    watchdog-abandonable worker while chunk k is scattered/computed
    (``CYLON_TPU_OOC_PREFETCH_DEPTH``; 0 = sequential). The bench
    guard lints that all ``ooc_*`` entrypoints ingest through here —
    no sequential side-doors."""
    if isinstance(src, Mapping):
        return lambda: pipeline.prefetched(
            _as_chunks(src, chunk_rows), op=op)
    if callable(src):
        return lambda: pipeline.prefetched(
            _as_chunks(src(), chunk_rows), op=op)
    try:
        probe = iter(src)
    except TypeError:
        raise InvalidArgument(
            f"{op} source must be a column Mapping, a re-iterable of "
            "chunks, or a zero-arg callable returning a fresh chunk "
            f"iterator; got {type(src).__name__}") from None
    if probe is src:
        raise InvalidArgument(
            f"{op} needs a REPLAYABLE source (a retry or a "
            "resume_dir= rerun re-iterates it), but a one-shot "
            "iterator/generator was passed — a second iteration would "
            "silently yield 0 rows and produce short output. Wrap it "
            "in a zero-arg callable returning a fresh iterator, e.g. "
            "lambda: read_parquet_chunks(path, chunk_rows)")
    return lambda: pipeline.prefetched(
        _as_chunks(src, chunk_rows), op=op)


def _as_chunks(src, chunk_rows: int):
    """Accept a dict of host arrays (sliced into chunks), or any
    iterable of dicts / Tables (used as-is). Every chunk passes the
    ``chunk_source`` injection point — the place a streaming source
    (tunneled parquet reader, network stream) fails in production."""
    from cylon_tpu.table import Table

    if isinstance(src, Mapping):
        n = len(next(iter(src.values())))
        for lo in range(0, n, chunk_rows):
            watchdog.check("ooc_pass", "chunk source")
            resilience.inject("chunk_source")
            telemetry.counter("ooc.chunks").inc()
            yield {k: np.asarray(v)[lo:lo + chunk_rows]
                   for k, v in src.items()}
        return
    for c in src:
        # cooperative deadline checkpoint per chunk: an ooc pass under
        # a deadline raises promptly BETWEEN chunks (the watched
        # section around the whole pass only raises on exit)
        watchdog.check("ooc_pass", "chunk source")
        resilience.inject("chunk_source")
        telemetry.counter("ooc.chunks").inc()
        if isinstance(c, Table):
            # to_pandas decodes dictionary columns to values — codes
            # are TABLE-LOCAL and must not cross the host spill raw
            pdf = c.to_pandas()
            yield {k: pdf[k].to_numpy() for k in pdf.columns}
        else:
            yield c


@watchdog.watched("ooc_pass", "ooc_join")
def ooc_join(left, right, on, how: str = "inner",
             n_partitions: int = 8, chunk_rows: int = 1 << 22,
             sink: Callable | None = None,
             suffixes=("_x", "_y"),
             resume_dir: str | None = None,
             algorithm: str = "sort") -> int:
    """Out-of-core equi-join. ``left``/``right``: host column dicts,
    re-iterables of chunks, or zero-arg callables returning fresh
    chunk iterators (one-shot iterators are rejected — see
    :func:`_resolve_source`). Each of the ``n_partitions`` bucket
    pairs joins on device with the normal fused program; results spill
    to host via ``sink(partition_pandas_df)`` — or are only counted
    when ``sink`` is None. Returns total result rows.

    ``resume_dir`` makes the pass RESUMABLE: every completed
    partition's joined output checkpoints to a
    :class:`cylon_tpu.resilience.CheckpointedRun` there (manifest
    updated atomically per partition), so a killed run re-invoked with
    the same arguments replays completed partitions from the store and
    recomputes only the rest — output identical to a fault-free run.
    The fingerprint (op + keys + how + partition plan) guards against
    resuming the wrong plan; recorded per-partition input sizes guard
    against a source that changed underneath the checkpoint.

    Parity: completes the 100M x 100M config that exceeds one chip's
    HBM in-core (the reference finishes it by spreading over ranks —
    ``docs/docs/arch.md:148-162``; one chip finishes it by spilling
    partitions to DRAM)."""
    import jax

    from cylon_tpu.ops.join import join as dev_join
    from cylon_tpu.table import Table
    from cylon_tpu.utils import pow2_bucket

    keys = [on] if isinstance(on, str) else list(on)
    if how not in ("inner", "left", "right", "fullouter", "outer"):
        raise InvalidArgument(f"unsupported how={how!r}")
    lchunks = _resolve_source(left, "ooc_join", chunk_rows)
    rchunks = _resolve_source(right, "ooc_join", chunk_rows)
    ckpt = None
    if resume_dir is not None:
        # the local-join kernel (sort vs bucketed hash) changes the
        # ordered=False row ORDER, so it is part of the partition plan:
        # a resume under a different EFFECTIVE kernel must recompute,
        # not mix — and the effective kernel is decided by the env
        # overrides (CYLON_TPU_JOIN_ALGORITHM / _HASH_IMPL / the chain
        # budget), not just the param, so fingerprint those
        from cylon_tpu.ops import hash_join
        from cylon_tpu.ops.join import _env_algorithm

        eff = _env_algorithm() or algorithm
        fp_alg = () if eff == "sort" else (
            (eff, hash_join.hash_impl(), hash_join.bucket_width()),)
        ckpt = resilience.CheckpointedRun(
            resume_dir, "join",
            (tuple(keys), how, int(n_partitions), tuple(suffixes))
            + fp_alg)
    lparts = host_partition_chunks(lchunks(), keys, n_partitions)
    rparts = host_partition_chunks(rchunks(), keys, n_partitions)
    from cylon_tpu.errors import OutOfCapacity

    # resume decisions are fixed at manifest load; snapshotting here
    # keeps the prefetch worker and the async writer off the live
    # manifest dict (only the writer mutates it during the pass)
    done_map = ckpt.completed if ckpt is not None else {}

    def _ingest(p):
        """Pipelined ingest of partition p (prefetch worker): host
        sizes always; the device tables only for fresh, non-empty
        partitions — overlapped with partition p-1's compute. The
        host spill buckets are freed as soon as they are ingested.
        NOTE the tables are DEVICE-resident: the prefetcher's
        depth+1-unit bound is an HBM bound here (depth 1 = two
        partitions' tables live at once — set
        CYLON_TPU_OOC_PREFETCH_DEPTH=0 or raise n_partitions when one
        bucket pair barely fits). Power-of-2 capacities bound the
        compiled-shape count to O(log(rows)) across partitions."""
        lp, rp = lparts[p], rparts[p]
        ln = len(next(iter(lp.values()))) if lp else 0
        rn = len(next(iter(rp.values()))) if rp else 0
        skip = (p in done_map or (ln == 0 and rn == 0)
                or ((ln == 0 or rn == 0) and how == "inner"))
        lt = rt = None
        if not skip:
            lt = Table.from_pydict(lp, capacity=pow2_bucket(max(ln, 1)))
            rt = Table.from_pydict(rp, capacity=pow2_bucket(max(rn, 1)))
        lparts[p] = rparts[p] = None  # free the spill as we go
        return ln, rn, lt, rt

    total = 0
    with pipeline.committer("join") as com:
        for p, (ln, rn, lt, rt) in pipeline.prefetch_map(
                range(n_partitions), _ingest, op="join"):
            watchdog.check("ooc_pass", f"join partition {p}")
            done = done_map.get(p)
            if done is not None:
                # completed partition: verify the re-scattered source
                # still matches, then replay the durable output
                # (identical bytes, no device work). The spill READ +
                # sink call ride the writer thread — FIFO submission
                # order keeps replayed and fresh partitions in
                # partition order, so a resumed run's sink stream is
                # byte-identical to a fault-free run's
                ckpt.verify_meta(p, "ooc_join", ln=ln, rn=rn)
                # count the resume always; read the spill only when a
                # sink needs the bytes (a count-only run must not pay
                # the IO)
                ckpt.note_resumed(p)
                if done and sink is not None:
                    import pandas as pd

                    com.submit(lambda p=p: sink(
                        pd.DataFrame(ckpt.load_unit(p))))
                total += done
                telemetry.counter("ooc.rows_out", op="join").inc(done)
                continue
            if ln == 0 or rn == 0:
                if (ln == 0 and rn == 0) or how == "inner":
                    if ckpt is not None:
                        com.submit(lambda p=p, ln=ln, rn=rn:
                                   ckpt.complete(p, {}, 0,
                                                 meta={"ln": ln,
                                                       "rn": rn}))
                    continue
                # outer semantics with an empty side still need the pass
            # one trace slice per device pass: on the merged timeline
            # the OOC join reads as n_partitions back-to-back bucket
            # slices, so a slow bucket (skewed partition, deep regrow
            # ladder) is visible by eye instead of buried in the pass
            # total
            with _span("ooc_join.partition", cat="stage", partition=p,
                       rows_left=ln, rows_right=rn):
                # stage-boundary HBM sample: the live-bytes gauge the
                # in-core-vs-spill decision (ROADMAP item 1) will read
                _memory.sample(op="ooc_join")
                # ~1 output row per probe row is the expected shape of
                # an equi-join on hash-partitioned keys; pow2 rounding
                # plus the doubling ladder below absorbs fan-out, and
                # starting tight matters — at 12.5M-row partitions a
                # 4x(ln+rn) start is a multi-GB output buffer that can
                # itself OOM the pass.
                # ladder depth 12: the tight start shifts the ceiling
                # down 4x vs the old 4x(ln+rn) start, and hot-key
                # fan-out inside ONE partition cannot be relieved by
                # more partitions — keep the reachable maximum at
                # least where it was (a device OOM during a deep
                # regrow raises through, which is the honest limit)
                cap = pow2_bucket(2 * max(ln, rn, 1))
                with _span("ooc.compute", cat="stage", op="join",
                           unit=p):
                    for _ in range(12):
                        try:
                            res = dev_join(lt, rt,
                                           on=keys if len(keys) > 1
                                           else keys[0], how=how,
                                           suffixes=suffixes,
                                           out_capacity=cap,
                                           ordered=False,
                                           algorithm=algorithm)
                            nrows = int(res.nrows)
                        except OutOfCapacity:
                            nrows = cap + 1
                        if nrows <= cap:
                            break
                        cap *= 2
                    else:
                        raise OutOfCapacity(
                            f"ooc_join partition {p}: output exceeds "
                            f"{cap} rows — raise n_partitions")
                    pdf = (res.to_pandas()
                           if ckpt is not None or sink is not None
                           else None)
                total += nrows
                telemetry.counter("ooc.rows_out", op="join").inc(nrows)
                if pdf is not None:
                    cols = ({c: pdf[c].to_numpy()
                             for c in pdf.columns}
                            if ckpt is not None else None)

                    def _commit(p=p, cols=cols, pdf=pdf, nrows=nrows,
                                ln=ln, rn=rn):
                        # checkpoint BEFORE the sink sees the
                        # partition (both on the one writer thread, in
                        # order): a kill between the two replays it on
                        # resume, so acknowledged output is never
                        # recomputed and unacknowledged output is
                        # never lost
                        if ckpt is not None:
                            ckpt.complete(p, cols, nrows,
                                          meta={"ln": ln, "rn": rn})
                        if sink is not None:
                            sink(pdf)

                    com.submit(_commit)
                    del pdf
                del res, lt, rt
    return total


@watchdog.watched("ooc_pass", "ooc_groupby")
def ooc_groupby(src, by: Sequence[str], aggs,
                chunk_rows: int = 1 << 22,
                transform: Callable | None = None,
                resume_dir: str | None = None):
    """Out-of-core decomposable groupby: per chunk, a device
    pre-combine shrinks the chunk to its partial aggregates (tiny for
    low-cardinality groups); partials accumulate on host and one final
    device combine produces the result Table. ``aggs``: (src, op[,
    out]) with op in sum/count/min/max (decompose mean as sum+count —
    :mod:`cylon_tpu.tpch.streaming` shows the pattern). ``src``: a
    host column Mapping, a re-iterable of chunks, or a zero-arg
    callable returning a fresh chunk iterator (one-shot iterators are
    rejected — see :func:`_resolve_source`).

    ``transform(chunk_dict) -> Table`` optionally maps each raw chunk
    to the table the pre-combine consumes (filters, derived columns,
    probe-side joins — the TPC-H streaming queries are exactly this
    hook); default is a plain ingest.

    ``resume_dir`` makes the pass RESUMABLE at chunk granularity:
    every chunk's partial aggregate checkpoints to a
    :class:`cylon_tpu.resilience.CheckpointedRun` (manifest updated
    atomically per chunk), so a killed run re-invoked with the same
    arguments replays completed partials from the store — the chunk
    source is re-iterated, but the transform + device pre-combine are
    skipped for every completed chunk, and the final combine (cheap —
    one row per group per chunk) produces output identical to a
    fault-free run. The fingerprint covers keys, aggs, chunking and
    the transform's identity; the recorded per-chunk source rows guard
    against a source that changed underneath the checkpoint.

    Parity: the chunked pre-combine -> final combine structure of
    ``DistributedHashGroupBy`` (groupby/groupby.cpp:62-78) applied to
    the chunk dimension, partials living on host between chunks."""
    from cylon_tpu.ops.groupby import groupby_aggregate
    from cylon_tpu.table import Table

    merge = {"sum": "sum", "count": "sum", "size": "sum",
             "min": "min", "max": "max"}
    aggs = [(a[0], a[1], a[2] if len(a) > 2 else f"{a[0]}_{a[1]}")
            for a in (tuple(x) for x in aggs)]
    bad = [op for _, op, _ in aggs if op not in merge]
    if bad:
        raise InvalidArgument(
            f"non-decomposable ops {bad}; decompose (mean = sum+count) "
            "or use the in-core path")
    chunks = _resolve_source(src, "ooc_groupby", chunk_rows)
    import pandas as pd

    ckpt = None
    if resume_dir is not None:
        # the transform is part of the plan: two passes differing only
        # in their transform must never share partials. Its code
        # identity (module + qualname) is the best cheap stand-in for
        # semantic identity; a renamed/relocated transform re-runs.
        tf = (None if transform is None else
              (getattr(transform, "__module__", None),
               getattr(transform, "__qualname__", repr(transform))))
        ckpt = resilience.CheckpointedRun(
            resume_dir, "groupby",
            (tuple(by), tuple(tuple(a) for a in aggs),
             int(chunk_rows), tf))
    done_map = ckpt.completed if ckpt is not None else {}
    partials: list = []
    # pipelined: the chunk source arrives through the shared prefetcher
    # (chunk i+1 pulls/decodes on a worker while chunk i pre-combines
    # on-device — see _resolve_source), and each chunk's checkpoint
    # commit overlaps the next chunk's compute on the async writer
    with pipeline.committer("groupby") as com:
        for i, chunk in enumerate(chunks()):
            src_rows = len(next(iter(chunk.values()))) if chunk else 0
            done = done_map.get(i)
            if done is not None:
                ckpt.verify_meta(i, "ooc_groupby", src_rows=src_rows)
                cols = ckpt.resume_unit(i)
                if done:
                    partials.append(pd.DataFrame(cols))
                continue
            with _span("ooc_groupby.chunk", cat="stage", chunk=i):
                _memory.sample(op="ooc_groupby")
                with _span("ooc.compute", cat="stage", op="groupby",
                           unit=i):
                    t = (Table.from_pydict(chunk) if transform is None
                         else transform(chunk))
                    part = groupby_aggregate(
                        t, list(by), [(s, op, o) for s, op, o in aggs])
                    # partials hop through pandas: tiny (one row per
                    # group), and dictionary key columns decode to
                    # values (codes are chunk-local)
                    pdf = part.to_pandas()
                if ckpt is not None:
                    cols = {c: pdf[c].to_numpy() for c in pdf.columns}
                    com.submit(lambda i=i, cols=cols, n=len(pdf),
                               sr=src_rows: ckpt.complete(
                                   i, cols, n, meta={"src_rows": sr}))
                partials.append(pdf)
                del t, part
    if not partials:
        raise InvalidArgument("ooc_groupby: empty input")

    merged_df = pd.concat(partials, ignore_index=True)
    final = Table.from_pydict(
        {c: merged_df[c].to_numpy() for c in merged_df.columns})
    return groupby_aggregate(final, list(by),
                             [(o, merge[op], o) for _, op, o in aggs])


def _lex_gt(cols: Sequence[np.ndarray], split) -> np.ndarray:
    """Vectorised lexicographic ``row > split`` over parallel
    partition-encoded key columns (see :func:`_sortable`; each column
    compares in its own dtype — no cross-column promotion)."""
    gt = np.zeros(len(cols[0]), bool)
    eq = np.ones(len(cols[0]), bool)
    for c, s in zip(cols, split):
        gt |= eq & (c > s)
        eq &= c == s
    return gt


def _sortable(a: np.ndarray) -> np.ndarray:
    """Key column encoded for partition comparisons. Ints pass through
    in their own dtype (no precision loss). Floats map to
    order-preserving uint64 (the sign-flip bit trick), with NaN
    canonicalised to a pattern ABOVE +inf — so NaNs range-partition
    strictly last, after real infinities, matching the device sort's
    (and pandas') inf-before-NaN placement. Datetimes likewise map to
    order-preserving uint64 with NaT canonicalised to the TOP pattern:
    the raw int64 NaT sentinel is INT64_MIN, and NaT comparisons are
    always-False in numpy, so passing the dtype through would silently
    route every NaT row to bucket 0 while the per-bucket device sort
    (null validity) and pandas both place them last."""
    a = np.asarray(a)
    if a.dtype.kind not in "iufM":
        raise InvalidArgument(
            f"ooc_sort keys must be numeric/datetime, got {a.dtype}")
    if a.dtype.kind == "M":
        u = a.view(np.int64).astype(np.uint64) ^ np.uint64(1 << 63)
        return np.where(np.isnat(a), np.uint64(0xFFFFFFFFFFFFFFFF), u)
    if a.dtype.kind != "f":
        return a
    f = np.ascontiguousarray(a, np.float64)
    u = f.view(np.uint64)
    u = np.where(np.isnan(f), np.uint64(0x7FF8000000000000), u)
    return np.where(u >> np.uint64(63) == 1, ~u,
                    u | np.uint64(1 << 63))


def _scatter_chunks(chunks, pid_fn, n_partitions: int) -> list[dict]:
    """Shared partition scatter: route every chunk's rows into
    ``n_partitions`` host buckets by ``pid_fn(cols) -> int64[n]``,
    returning one dense ``{col: np.ndarray}`` per partition (empty
    partitions keep the schema). Rows-in vs rows-out is verified — a
    ``pid_fn`` straying outside ``[0, n_partitions)`` (or any scatter
    bug) raises :class:`~cylon_tpu.errors.DataLossError` instead of
    silently shrinking the spill."""
    acct = resilience.RowAccount("host_partition_chunks")
    parts: list[dict[str, list]] = [{} for _ in range(n_partitions)]
    schema: dict[str, np.dtype] = {}
    for chunk in chunks:
        cols = {k: np.asarray(v) for k, v in chunk.items()}
        acct.add_in(len(next(iter(cols.values()))) if cols else 0)
        pid = pid_fn(cols)
        order = np.argsort(pid, kind="stable")
        bounds = np.searchsorted(pid[order], np.arange(n_partitions + 1))
        for name, arr in cols.items():
            arr = arr[order]
            schema.setdefault(name, arr.dtype)
            for p in range(n_partitions):
                lo, hi = bounds[p], bounds[p + 1]
                if hi > lo:
                    parts[p].setdefault(name, []).append(arr[lo:hi])
        del cols
    out = []
    for p in parts:
        full = {name: (np.concatenate(p[name]) if len(p[name]) > 1
                       else p[name][0]) if name in p
                else np.empty(0, dt)  # keep schema on empty partitions
                for name, dt in schema.items()}
        acct.add_out(len(next(iter(full.values()))) if full else 0)
        out.append(full)
    acct.verify()
    return out


@watchdog.watched("ooc_pass", "ooc_sort")
def ooc_sort(src, by, n_partitions: int = 8, chunk_rows: int = 1 << 22,
             sink: Callable | None = None,
             sample_stride: int = 8192,
             resume_dir: str | None = None) -> int:
    """Out-of-core sort: the host-DRAM twin of ``dist_sort``'s
    sample-sort (sample -> splitters -> range partition -> per-range
    device sort), completing sorts whose in-core working set exceeds
    one chip's HBM. Two passes over ``src`` (a host column dict, a
    re-iterable of chunks, or a zero-arg callable returning a FRESH
    chunk iterator — e.g. ``lambda: read_parquet_chunks(path, 1 <<
    22)``; one-shot iterators/generators are REJECTED up front, since
    pass 1 would exhaust them and pass 2 would silently sort nothing):
    pass 1 strided-samples the keys and picks ``n_partitions - 1``
    splitter tuples; pass 2 range-partitions every chunk into host
    buckets by vectorised lexicographic compare. Each bucket then
    device-sorts with the normal fused program and spills via
    ``sink(pandas_df)`` IN RANGE ORDER — the concatenation of the sink
    calls is the globally sorted table. Returns total rows.

    Loss accounting: pass-1 and pass-2 row counts must agree (a source
    that yields fewer rows on its second iteration raises
    :class:`~cylon_tpu.errors.DataLossError`), and the spilled bucket
    total must equal the pass-2 count.

    ``resume_dir`` makes pass 2 RESUMABLE: every completed bucket's
    sorted output checkpoints to a
    :class:`cylon_tpu.resilience.CheckpointedRun`
    there (manifest updated atomically per bucket), so a killed run
    re-invoked with the same arguments replays completed buckets from
    the store and recomputes only from the first incomplete one — the
    output is identical to a fault-free run. A manifest whose
    fingerprint (keys + splitters) does not match is discarded, never
    resumed against the wrong plan.

    Parity: ``dist_sort``'s sample-sort structure
    (``table.cpp DistributedSort`` -> sample + SortImpl) with "another
    rank's memory" replaced by host DRAM, like :func:`ooc_join`."""
    from cylon_tpu.ops.selection import sort_table
    from cylon_tpu.table import Table
    from cylon_tpu.utils import pow2_bucket

    keys = [by] if isinstance(by, str) else list(by)
    chunks = _resolve_source(src, "ooc_sort", chunk_rows)

    # pass 1: strided per-column key samples (each keeps its own
    # dtype) -> equi-spaced splitter tuples; rows counted for the
    # pass-1 vs pass-2 conservation check
    rows_pass1 = 0
    samples: list[list[np.ndarray]] = [[] for _ in keys]
    for chunk in chunks():
        kc = [_sortable(np.asarray(chunk[k])) for k in keys]
        rows_pass1 += len(kc[0])
        if len(kc[0]):
            for i, c in enumerate(kc):
                samples[i].append(c[::sample_stride])
    if not samples[0]:
        return 0
    scols = [np.concatenate(s) for s in samples]
    order = np.lexsort(tuple(reversed(scols)))
    pos = (np.arange(1, n_partitions)
           * (len(order) / n_partitions)).astype(np.int64)
    pos = np.clip(pos, 0, len(order) - 1)
    splitters = [tuple(c[order[p]] for c in scols) for p in pos]

    ckpt = None
    if resume_dir is not None:
        ckpt = resilience.CheckpointedRun(
            resume_dir, "sort", (tuple(keys), n_partitions, splitters))

    # pass 2: range-partition every chunk into host buckets
    def pid_of(cols_dict):
        kc = [_sortable(cols_dict[k]) for k in keys]
        pid = np.zeros(len(kc[0]), np.int64)
        for s in splitters:
            pid += _lex_gt(kc, s)
        return pid

    parts = _scatter_chunks(chunks(), pid_of, n_partitions)
    # _scatter_chunks verifies chunk rows == bucket rows internally, so
    # the bucket sizes ARE the pass-2 row count
    sizes = [len(next(iter(p.values()))) if p else 0 for p in parts]
    rows_pass2 = sum(sizes)
    if rows_pass2 != rows_pass1:
        raise DataLossError(
            f"ooc_sort: pass 1 saw {rows_pass1} rows but pass 2 saw "
            f"{rows_pass2} — the source is not replayable (an "
            "exhausted/truncating iterator?); pass a zero-arg callable "
            "that returns a fresh iterator each call")

    # range order: per-bucket device sort, spill in splitter order.
    # With a store, completed buckets replay from their durable spill
    # (identical bytes, no recompute) and each fresh bucket is spilled
    # + recorded BEFORE its sink call — both on the ONE async-writer
    # thread, in bucket order — so a kill between buckets never loses
    # acknowledged work and the sink stream keeps range order.
    done_map = ckpt.completed if ckpt is not None else {}

    def _ingest(p):
        """Pipelined ingest of bucket p (prefetch worker): the
        host→device ``from_pydict`` of bucket p+1 overlaps bucket p's
        device sort; the host bucket is freed as soon as ingested.
        Device-resident lookahead — same HBM note as ooc_join's
        ingest: depth+1 buckets live at once, depth 0 restores the
        one-bucket footprint."""
        full, n = parts[p], sizes[p]
        t = None
        if p not in done_map and n > 0:
            t = Table.from_pydict(full, capacity=pow2_bucket(n))
        parts[p] = None  # free the spill as we go
        return t

    total = 0
    with pipeline.committer("sort") as com:
        for p, t in pipeline.prefetch_map(range(n_partitions), _ingest,
                                          op="sort"):
            watchdog.check("ooc_pass", f"sort bucket {p}")
            n = sizes[p]
            done = done_map.get(p)
            if done is not None:
                if done != n:
                    raise DataLossError(
                        f"ooc_sort: resume manifest records {done} "
                        f"rows for bucket {p} but the re-scattered "
                        f"source has {n} — the source changed since "
                        "the manifest was written; clear the "
                        "resume_dir")
                ckpt.note_resumed(p)
                if n and sink is not None:
                    import pandas as pd

                    com.submit(lambda p=p: sink(
                        pd.DataFrame(ckpt.load_unit(p))))
                total += n
                # replayed rows count toward rows_out too: a resumed
                # run produces identical output to a clean one, and
                # must not read as a row deficit on any dashboard
                telemetry.counter("ooc.rows_out", op="sort").inc(n)
                continue
            if n == 0:
                if ckpt is not None:
                    com.submit(lambda p=p: ckpt.complete(p, {}, 0))
                continue
            with _span("ooc_sort.bucket", cat="stage", bucket=p,
                       rows=n):
                _memory.sample(op="ooc_sort")
                with _span("ooc.compute", cat="stage", op="sort",
                           unit=p):
                    res = sort_table(t, keys)
                    pdf = res.to_pandas()
                cols = ({c: pdf[c].to_numpy() for c in pdf.columns}
                        if ckpt is not None else None)

                def _commit(p=p, cols=cols, pdf=pdf, n=n):
                    if ckpt is not None:
                        ckpt.complete(p, cols, n)
                    if sink is not None:
                        sink(pdf)

                total += n
                telemetry.counter("ooc.rows_out", op="sort").inc(n)
                if ckpt is not None or sink is not None:
                    com.submit(_commit)
                del res, t, pdf
    resilience.check_conservation("ooc_sort", rows_pass2, total)
    return total
