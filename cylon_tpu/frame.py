"""Pandas-like DataFrame facade.

Parity target: ``python/pycylon/frame.py`` (2082 LoC) — ``DataFrame``
(:183) with ``merge`` (:1516), ``join`` (:1387), ``groupby`` (:1813 →
``GroupByDataFrame`` :120), ``sort_values`` (:1272), ``drop_duplicates``
(:1743), ``concat`` (:1956), math/compare dunders, ``isin/fillna/
isnull/rename/set_index``; and the env-dispatch convention — **ops take
``env=None`` for local execution or ``env=CylonEnv`` for distributed**
(``frame.py:1728-1743``). PyCylon scripts port by changing the import.

The DataFrame wraps a :class:`cylon_tpu.table.Table` that is either
local (scalar nrows) or mesh-distributed (vector nrows); distributed
results stay distributed until materialised (``to_pandas``).
"""

from __future__ import annotations

from typing import Mapping, Sequence

import jax.numpy as jnp
import numpy as np

from cylon_tpu import dtypes
from cylon_tpu.column import Column
from cylon_tpu.config import CSVReadOptions, JoinConfig
from cylon_tpu.context import CylonEnv
from cylon_tpu.errors import InvalidArgument, KeyError_, NotImplemented_
from cylon_tpu.ops import aggregates as _aggregates
from cylon_tpu.ops import groupby as _groupby_mod
from cylon_tpu.ops import selection as _selection
from cylon_tpu.ops import setops as _setops
from cylon_tpu.ops.join import join as _join
from cylon_tpu.parallel import (
    dist_aggregate,
    dist_groupby,
    dist_join,
    dist_num_rows,
    dist_sort,
    dist_to_pandas,
    dist_unique,
    gather_table,
    is_distributed,
    scatter_table,
)
from cylon_tpu.table import Table

import os as _os

_NO_SHRINK = bool(_os.environ.get("CYLON_TPU_NO_SHRINK"))


def _shrink(t: Table) -> Table:
    """Capacity shrink-to-fit after selective local ops (see
    ``Table.shrink_to_fit``). Distributed tables keep their layout —
    per-shard counts differ and the shard shape is the mesh contract."""
    if _NO_SHRINK or is_distributed(t):
        return t
    return t.shrink_to_fit()


class DataFrame:
    """Columnar dataframe on device (parity: pycylon ``DataFrame``)."""

    def __init__(self, data=None, env: CylonEnv | None = None,
                 capacity: int | None = None, string_storage="dict"):
        index = None
        if isinstance(data, DataFrame):
            self._table = data._table
            index = data._index
        elif isinstance(data, Table):
            self._table = data
        elif data is None:
            self._table = Table({}, 0)
        elif isinstance(data, Mapping):
            self._table = Table.from_pydict(data, capacity, string_storage)
        else:
            import pandas as pd

            if isinstance(data, pd.DataFrame):
                self._table = Table.from_pandas(data, capacity,
                                                string_storage)
            elif isinstance(data, np.ndarray):
                names = [f"c{i}" for i in range(data.shape[1])]
                self._table = Table.from_numpy(names, list(data.T), capacity)
            else:
                try:
                    import pyarrow as pa

                    if isinstance(data, pa.Table):
                        self._table = Table.from_arrow(data, capacity,
                                                       string_storage)
                    else:
                        raise TypeError
                except TypeError:
                    raise InvalidArgument(
                        f"cannot build DataFrame from {type(data)}")
        if env is not None:
            self._table = scatter_table(env, self._table)
        self._index = index

    # -- construction helpers -------------------------------------------
    @staticmethod
    def _wrap(table: Table, index=None) -> "DataFrame":
        df = object.__new__(DataFrame)
        df._table = table
        df._index = index
        return df

    # -- schema / introspection -----------------------------------------
    @property
    def table(self) -> Table:
        return self._table

    @property
    def columns(self) -> list[str]:
        return self._table.column_names

    @property
    def shape(self):
        return (len(self), self._table.num_columns)

    @property
    def dtypes(self) -> dict:
        return {n: c.dtype for n, c in self._table.columns.items()}

    @property
    def is_distributed(self) -> bool:
        return is_distributed(self._table)

    def __len__(self):
        if self.is_distributed:
            return dist_num_rows(self._table)
        return self._table.num_rows

    # -- indexing (parity: indexing/ + table.hpp:183 SetArrowIndex) ------
    def _materialized(self) -> "DataFrame":
        """Local (gathered) view; the index — always built on the local
        layout, see set_index — rides along."""
        if self.is_distributed:
            return DataFrame._wrap(gather_table(None, self._table),
                                   self._index)
        return self

    @property
    def index(self):
        from cylon_tpu.indexing import RangeIndex

        if self._index is None:
            return RangeIndex(len(self))
        return self._index

    def set_index(self, key: str, indexing_type=None, drop: bool = True,
                  ) -> "DataFrame":
        """Build a value index on ``key`` (parity: pycylon
        ``DataFrame.set_index`` / ``Table::SetArrowIndex``, table.hpp:183;
        ``indexing_type`` mirrors ``IndexingType``, default HASH)."""
        from cylon_tpu.indexing import IndexingType, build_index

        if indexing_type is None:
            indexing_type = IndexingType.HASH
        df = self._materialized()
        t = df.table
        idx = build_index(t.column(key), t.nrows, indexing_type, name=key)
        if drop:
            t = t.drop([key])
        return DataFrame._wrap(t, index=idx)

    def reset_index(self, drop: bool = False) -> "DataFrame":
        """Drop the value index, materialising it back as a leading column
        unless ``drop`` (pandas semantics: a default RangeIndex becomes an
        ``index`` column of positions; a name collision raises)."""
        df = self._materialized()
        t = df.table
        idx = df._index
        if not drop:
            vc = idx.values_column() if idx is not None else None
            if vc is None:
                name = "index"
                pos = jnp.arange(t.capacity, dtype=jnp.int64)
                vc = Column(pos, None, dtypes.int64)
            else:
                name = idx.name or "index"
            if name in t:
                raise InvalidArgument(
                    f"cannot insert {name}, already exists")
            cols = {name: vc}
            cols.update(t.columns)
            t = Table(cols, t.nrows)
        return DataFrame._wrap(t)

    @property
    def loc(self):
        from cylon_tpu.indexing import LocIndexer

        return LocIndexer(self)

    @property
    def iloc(self):
        from cylon_tpu.indexing import ILocIndexer

        return ILocIndexer(self)

    def __repr__(self):
        try:
            return f"DataFrame({self.to_pandas().__repr__()})"
        except Exception:
            return f"DataFrame({self._table!r})"

    # -- selection -------------------------------------------------------
    def __getitem__(self, key):
        # pure column selection keeps rows, so the value index rides along
        if isinstance(key, str):
            return DataFrame._wrap(self._table.select([key]), self._index)
        if isinstance(key, (list, tuple)):
            return DataFrame._wrap(self._table.select(list(key)), self._index)
        from cylon_tpu.series import Series

        if isinstance(key, (DataFrame, Series, Column,
                            jnp.ndarray, np.ndarray)):
            if self.is_distributed:
                # a mask is always built on the PADDED shard layout;
                # gathering first would compact rows out from under it
                # and silently select the wrong ones — the layout-safe
                # path is the shard-local filter
                raise InvalidArgument(
                    "boolean-mask selection on a distributed frame: use "
                    ".filter(mask, env=env) (shard-local, no gather)")
            return self.filter(key)
        # no repr(key): a Series/DataFrame repr host-syncs, which under
        # whole-query tracing raises ConcretizationTypeError and masks
        # this KeyError
        raise KeyError_(
            f"bad key of type {type(key).__name__}; expected a column "
            f"name, list of names, or boolean mask "
            f"(columns: {list(self._table.column_names)!r})")

    def __setitem__(self, name, value):
        if self.is_distributed:
            # positional assignment is defined on the compacted local
            # layout; re-scatter (with env=) afterwards if needed
            self._table = gather_table(None, self._table)
        if isinstance(value, DataFrame):
            col = value._single_column()
        elif isinstance(value, Column):
            col = value
        elif np.isscalar(value):
            cap = self._table.capacity
            arr = jnp.full(cap, value)
            col = Column(arr, None, dtypes.from_numpy_dtype(np.asarray(value).dtype))
        else:
            col = Column.from_numpy(np.asarray(value), self._table.capacity)
        self._table = self._table.add_column(name, col)

    def _single_column(self) -> Column:
        if self._table.num_columns != 1:
            raise InvalidArgument("expected a single-column frame")
        return next(iter(self._table.columns.values()))

    # -- core relational ops (env dispatch, frame.py:1728) ---------------
    def merge(self, right: "DataFrame", how: str = "inner", on=None,
              left_on=None, right_on=None, suffixes=("_x", "_y"),
              env: CylonEnv | None = None,
              out_capacity: int | None = None,
              algorithm: str = "sort") -> "DataFrame":
        """Parity: ``DataFrame.merge`` (frame.py:1516). ``algorithm``
        mirrors pycylon's sort/hash choice ("hash" = the bucketed O(n)
        build/probe with sort fallback, see ``ops.join.join`` and
        ``docs/joins.md``; ``CYLON_TPU_JOIN_ALGORITHM`` overrides)."""
        if env is not None:
            t = dist_join(env, self._table, right._table, on=on,
                          left_on=left_on, right_on=right_on, how=how,
                          suffixes=suffixes, out_capacity=out_capacity,
                          algorithm=algorithm)
            return DataFrame._wrap(t)
        # local eager path regrows a defaulted capacity like the
        # distributed ops do (an N:M key blowup past the 1:N default
        # re-dispatches at 2x; the row-count check is the same sync
        # _shrink pays anyway). An explicit out_capacity keeps the
        # raise-on-overflow contract; under whole-query tracing the
        # enclosing CompiledQuery ladder takes over.
        from cylon_tpu import plan

        t = plan.regrow_eager(
            lambda: _join(self._gathered(), right._gathered(), on=on,
                          left_on=left_on, right_on=right_on, how=how,
                          suffixes=suffixes, out_capacity=out_capacity,
                          algorithm=algorithm),
            bounded=out_capacity is not None)
        return DataFrame._wrap(_shrink(t))

    def join(self, right: "DataFrame", on=None, how: str = "left",
             lsuffix: str = "_l", rsuffix: str = "_r",
             env: CylonEnv | None = None, **kw) -> "DataFrame":
        """Parity: ``DataFrame.join`` (frame.py:1387)."""
        return self.merge(right, how=how, on=on,
                          suffixes=(lsuffix, rsuffix), env=env, **kw)

    def groupby(self, by, env: CylonEnv | None = None) -> "GroupByDataFrame":
        """Parity: ``DataFrame.groupby`` (frame.py:1813)."""
        by = [by] if isinstance(by, str) else list(by)
        return GroupByDataFrame(self, by, env)

    def sort_values(self, by, ascending=True, env: CylonEnv | None = None,
                    **kw) -> "DataFrame":
        """Parity: ``DataFrame.sort_values`` (frame.py:1272); distributed
        = sample-sort (``DistributedSort``)."""
        by = [by] if isinstance(by, str) else list(by)
        if env is not None:
            return DataFrame._wrap(dist_sort(env, self._table, by,
                                             ascending=ascending, **kw))
        return DataFrame._wrap(
            _selection.sort_table(self._gathered(), by, ascending=ascending))

    def drop_duplicates(self, subset=None, keep: str = "first",
                        env: CylonEnv | None = None,
                        out_capacity: int | None = None) -> "DataFrame":
        """Parity: ``DataFrame.drop_duplicates`` (frame.py:1743) /
        ``DistributedUnique`` (table.cpp:977)."""
        subset = [subset] if isinstance(subset, str) else subset
        if env is not None:
            return DataFrame._wrap(
                dist_unique(env, self._table, subset,
                            out_capacity=out_capacity, keep=keep))
        return DataFrame._wrap(
            _shrink(_setops.unique(self._gathered(), subset, keep=keep)))

    def head(self, n: int = 5) -> "DataFrame":
        if self.is_distributed:
            from cylon_tpu.parallel import dist_head

            # no gather, no data movement: only the [W] count vector
            # changes (rows keep shard order, = the gathered order)
            return DataFrame._wrap(dist_head(self._table, n))
        return DataFrame._wrap(_selection.head(self._table, n))

    def filter(self, mask=None, env: CylonEnv | None = None,
               items: Sequence[str] | None = None) -> "DataFrame":
        """Row filter / column selection.

        With ``items=`` (or a list of column names) this is pandas
        ``DataFrame.filter``: select columns by label. With a bool
        array / Series / single-column DataFrame it is a row filter
        that preserves the table's layout: on a mesh-distributed frame
        each shard compacts its own rows — no gather (parity:
        rank-local filters, ``compute.pyx:212``). Null mask entries
        filter as False (SQL/pandas semantics)."""
        from cylon_tpu.series import Series

        if items is not None:
            return self[list(items)]
        if isinstance(mask, (list, tuple)) and all(
                isinstance(x, str) for x in mask):
            return self[list(mask)]  # pandas filter(items) shorthand
        if isinstance(mask, DataFrame):
            mask = mask._single_column()
        if isinstance(mask, Series):
            mask = mask.column
        if isinstance(mask, Column):
            m = mask.data.astype(bool)
            if mask.validity is not None:
                m = m & mask.validity
            mask = m
        mask = jnp.asarray(mask)
        if self.is_distributed:
            from cylon_tpu.parallel import dist_filter

            if env is None:
                raise InvalidArgument(
                    "filter on a distributed frame needs env= (the mesh)")
            return DataFrame._wrap(dist_filter(env, self._table, mask))
        t = _selection.filter_table(self._table, mask)
        return DataFrame._wrap(_shrink(t))

    def sample_rows(self, n: int) -> "DataFrame":
        return DataFrame._wrap(_selection.sample(self._gathered(), n))

    def add_prefix(self, prefix: str) -> "DataFrame":
        return DataFrame._wrap(self._table.add_prefix(prefix), self._index)

    def add_suffix(self, suffix: str) -> "DataFrame":
        return DataFrame._wrap(self._table.add_suffix(suffix), self._index)

    def to_csv(self, path, **kw) -> None:
        """Parity: pycylon ``DataFrame.to_csv`` / ``WriteCSV``
        (table.cpp:243)."""
        from cylon_tpu.io import write_csv

        write_csv(self, path, **kw)

    def rename(self, columns: Mapping[str, str]) -> "DataFrame":
        return DataFrame._wrap(self._table.rename(columns), self._index)

    def drop(self, columns: Sequence[str]) -> "DataFrame":
        columns = [columns] if isinstance(columns, str) else list(columns)
        return DataFrame._wrap(self._table.drop(columns), self._index)

    def astype(self, mapping: Mapping[str, dtypes.DType]) -> "DataFrame":
        t = self._table
        for name, dt in mapping.items():
            t = t.add_column(name, t.column(name).astype(dt))
        return DataFrame._wrap(t, self._index)

    # -- elementwise / predicates ----------------------------------------
    def _binop(self, other, fn) -> "DataFrame":
        t = self._table
        cols = {}
        for name, c in t.columns.items():
            if isinstance(other, DataFrame):
                o = other._table.column(name).data
            else:
                o = other
            data = fn(c.data, o)
            cols[name] = Column(data, c.validity,
                                dtypes.from_numpy_dtype(data.dtype))
        return DataFrame._wrap(Table(cols, t.nrows))

    def _unop(self, fn) -> "DataFrame":
        return self._binop(0, lambda a, _: fn(a))

    def __add__(self, o): return self._binop(o, jnp.add)
    def __radd__(self, o): return self._binop(o, lambda a, b: jnp.add(b, a))
    def __sub__(self, o): return self._binop(o, jnp.subtract)
    def __rsub__(self, o): return self._binop(o, lambda a, b: jnp.subtract(b, a))
    def __mul__(self, o): return self._binop(o, jnp.multiply)
    def __rmul__(self, o): return self._binop(o, lambda a, b: jnp.multiply(b, a))
    def __truediv__(self, o): return self._binop(o, jnp.true_divide)
    def __rtruediv__(self, o): return self._binop(o, lambda a, b: jnp.true_divide(b, a))
    def __floordiv__(self, o): return self._binop(o, jnp.floor_divide)
    def __mod__(self, o): return self._binop(o, jnp.mod)
    def __pow__(self, o): return self._binop(o, jnp.power)
    def __neg__(self): return self._unop(jnp.negative)
    def __abs__(self): return self._unop(jnp.abs)
    # bitwise on ints, logical on bools — numpy/pandas semantics
    def __invert__(self): return self._unop(jnp.invert)
    def __and__(self, o): return self._binop(o, jnp.bitwise_and)
    def __or__(self, o): return self._binop(o, jnp.bitwise_or)
    def __xor__(self, o): return self._binop(o, jnp.bitwise_xor)
    def __eq__(self, o): return self._binop(o, jnp.equal)          # noqa: E501
    def __ne__(self, o): return self._binop(o, jnp.not_equal)
    def __lt__(self, o): return self._binop(o, jnp.less)
    def __le__(self, o): return self._binop(o, jnp.less_equal)
    def __gt__(self, o): return self._binop(o, jnp.greater)
    def __ge__(self, o): return self._binop(o, jnp.greater_equal)

    def __hash__(self):  # __eq__ is elementwise; identity hashing
        return id(self)

    def abs(self) -> "DataFrame":
        return self._unop(jnp.abs)

    def applymap(self, fn) -> "DataFrame":
        """Elementwise map over every column (parity: frame.py applymap /
        ``compute.pyx`` infer_map). Traceable fns fuse into XLA; others
        fall back to a host loop per column."""
        from cylon_tpu.ops.dictenc import reencode_values

        t = self._materialized().table
        cols = {}
        nrows = t.nrows
        # bytes columns need host values; fetch them all in ONE batched
        # transfer (per-column fetches pay a ~100 ms RPC each on a
        # tunneled device)
        host_cols = (t._host_columns()
                     if any(c.dtype.is_bytes for c in t.columns.values())
                     else {})
        for name, c in t.columns.items():
            if c.dtype.is_bytes:
                host = np.array([fn(v) for v in host_cols[name]], object)
                st = ("bytes" if all(isinstance(v, str) or v is None
                                     for v in host) else "dict")
                cols[name] = Column.from_numpy(host, t.capacity,
                                               string_storage=st)
                continue
            if c.dtype.is_dictionary:
                cols[name] = reencode_values(
                    c, [fn(v) for v in c.dictionary.values])
                continue
            try:
                data = jnp.asarray(jnp.vectorize(fn)(c.data))
                cols[name] = Column(data, c.validity,
                                    dtypes.from_numpy_dtype(np.dtype(data.dtype)))
            except Exception:
                host = np.array([fn(v) for v in c.to_numpy(int(nrows))])
                cols[name] = Column.from_numpy(host, t.capacity)
        return DataFrame._wrap(Table(cols, nrows), self._index)

    map = applymap  # pandas 2.x name

    def series(self, name: str):
        """Single column as a :class:`cylon_tpu.series.Series`.

        Layout-preserving: on a distributed frame the Series wraps the
        sharded column directly (elementwise ops — arithmetic, isin,
        str predicates — never move data, so they stay shard-local);
        reductions on such a Series raise, use ``df.sum(env=...)`` /
        ``dist_aggregate`` instead."""
        from cylon_tpu.series import Series

        t = self._table
        return Series._wrap(t.column(name), t.nrows, name)

    def isnull(self) -> "DataFrame":
        """Parity: frame.py isnull."""
        t = self._table
        cols = {}
        for name, c in t.columns.items():
            flags = _selection._null_flags(c)
            data = (jnp.zeros(t.capacity, bool) if flags is None
                    else flags.astype(bool))
            cols[name] = Column(data, None, dtypes.bool_)
        return DataFrame._wrap(Table(cols, t.nrows))

    def notnull(self) -> "DataFrame":
        inv = self.isnull()
        return inv._binop(True, jnp.not_equal)

    isna = isnull
    notna = notnull

    def fillna(self, value) -> "DataFrame":
        """Parity: frame.py fillna."""
        from cylon_tpu.ops.dictenc import encode_fill_value

        t = self._table
        cols = {}
        for name, c in t.columns.items():
            if c.dtype.is_bytes:
                from cylon_tpu.ops import bytescol

                cols[name] = bytescol.fill_value(c, value)
                continue
            if c.dtype.is_dictionary:
                if c.validity is None:
                    cols[name] = c
                    continue
                c2, code = encode_fill_value(c, value)
                data = jnp.where(c2.validity, c2.data, jnp.int32(code))
                cols[name] = Column(data, None, c2.dtype, c2.dictionary)
                continue
            data, validity = c.data, c.validity
            if jnp.issubdtype(data.dtype, jnp.floating):
                data = jnp.where(jnp.isnan(data), value, data)
            if validity is not None:
                data = jnp.where(validity, data, jnp.asarray(value, data.dtype))
                validity = None
            cols[name] = Column(data, validity, c.dtype, c.dictionary)
        return DataFrame._wrap(Table(cols, t.nrows))

    def dropna(self, axis: int = 0, how: str = "any", subset=None,
               ) -> "DataFrame":
        """Drop rows (axis=0) or columns (axis=1) with missing values
        (parity: ``compute.pyx`` drop_na :728)."""
        from cylon_tpu.ops import kernels

        df = self._materialized()
        t = df.table
        names = ([subset] if isinstance(subset, str) else list(subset)
                 ) if subset is not None else t.column_names
        flags = []
        for name in names:
            f = _selection._null_flags(t.column(name))
            flags.append(jnp.zeros(t.capacity, bool) if f is None
                         else f.astype(bool))
        if not flags:
            return df
        stack = jnp.stack(flags)
        if axis == 1:
            rm = t.row_mask()
            bad = [bool((f & rm).any()) if how == "any"
                   else bool((f | ~rm).all()) for f in stack]
            keep = [n for n, b in zip(names, bad) if not b]
            keep += [n for n in t.column_names if n not in names]
            ordered = [n for n in t.column_names if n in set(keep)]
            return DataFrame._wrap(t.select(ordered), df._index)
        null_row = stack.all(axis=0) if how == "all" else stack.any(axis=0)
        perm, count = kernels.compact_mask(~null_row, t.nrows)
        out = _selection.take_columns(t, perm, count)
        idx = df.index.take(perm, count) if df._index is not None else None
        return DataFrame._wrap(out, idx)

    def where(self, cond: "DataFrame", other=np.nan) -> "DataFrame":
        """Keep values where ``cond`` holds, else ``other`` (parity:
        frame.py where/mask). ``cond`` is a boolean frame (same shape) or
        single boolean column applied to every column."""
        import math

        nan_fill = other is None or (isinstance(other, float)
                                     and math.isnan(other))
        t = self._materialized().table
        cols = {}
        for name, c in t.columns.items():
            if isinstance(cond, DataFrame):
                cc = (cond._table.column(name) if name in cond._table
                      else cond._single_column())
                m = cc.data.astype(bool)
            else:
                m = jnp.asarray(cond, bool)
            base = (jnp.ones(t.capacity, bool) if c.validity is None
                    else c.validity)
            if c.dtype.is_bytes:
                if nan_fill:
                    cols[name] = Column(c.data, base & m, c.dtype)
                else:
                    from cylon_tpu.ops import bytescol

                    validity = None if c.validity is None else (base | ~m)
                    cols[name] = bytescol.replace_where(c, m, other,
                                                        validity)
            elif c.dtype.is_dictionary:
                if nan_fill:
                    cols[name] = Column(c.data, base & m, c.dtype,
                                        c.dictionary)
                else:
                    from cylon_tpu.ops.dictenc import encode_fill_value

                    c2, code = encode_fill_value(c, other)
                    data = jnp.where(m, c2.data, jnp.int32(code))
                    # cond False takes `other` even over a prior null
                    validity = None if c.validity is None else (base | ~m)
                    cols[name] = Column(data, validity, c2.dtype,
                                        c2.dictionary)
            elif not jnp.issubdtype(jnp.asarray(c.data).dtype,
                                    jnp.floating):
                if nan_fill:
                    # non-float columns take NaN through the validity
                    # mask (null), matching Arrow semantics
                    cols[name] = Column(c.data, base & m, c.dtype)
                else:
                    data = jnp.where(m, c.data,
                                     jnp.asarray(other, c.data.dtype))
                    validity = None if c.validity is None else (base | ~m)
                    cols[name] = Column(data, validity, c.dtype)
            else:
                data = jnp.where(m, c.data,
                                 jnp.nan if nan_fill
                                 else jnp.asarray(other, c.data.dtype))
                validity = (c.validity if nan_fill or c.validity is None
                            else (base | ~m))
                cols[name] = Column(data, validity, c.dtype)
        return DataFrame._wrap(Table(cols, t.nrows), self._index)

    def mask(self, cond: "DataFrame", other=np.nan) -> "DataFrame":
        inv = (~cond) if isinstance(cond, DataFrame) else ~jnp.asarray(cond, bool)
        return self.where(inv, other)

    def equals(self, other: "DataFrame") -> bool:
        """Exact frame equality (schema + values; NaN == NaN).

        Runs device-side (``ops.setops.equal_tables(ordered=True)`` —
        one fused compare + one scalar fetch) instead of materialising
        both frames; frames carrying a value index keep the pandas
        path, since the index participates in pandas equality."""
        if not isinstance(other, DataFrame):
            return False
        if self._index is not None or other._index is not None:
            a, b = self.to_pandas(), other.to_pandas()
            return bool(a.equals(b))
        import numpy as np

        from cylon_tpu.ops.setops import (align_for_equal,
                                          dist_ordered_equal_compiled,
                                          equal_tables)
        from cylon_tpu.parallel import dtable

        ta, tb = self._table, other._table
        if ta.column_names != tb.column_names:
            return False
        for n in ta.column_names:
            da, db = ta.column(n).dtype, tb.column(n).dtype
            stringish = ((da.is_bytes or da.is_dictionary)
                         and (db.is_bytes or db.is_dictionary))
            if da != db and not stringish:
                # framework dtype mismatch (e.g. a nullable-int column
                # vs its to_pandas round trip, re-ingested as strings):
                # pandas decides value equality, not the device layout
                # (ADVICE r3)
                return bool(self.to_pandas().equals(other.to_pandas()))
        if (dtable.is_distributed(ta) and dtable.is_distributed(tb)
                and ta.capacity == tb.capacity
                and dtable.num_shards(ta) == dtable.num_shards(tb)):
            # same shard layout: compare SHARD-LOCAL — elementwise on
            # the sharded arrays, one scalar reduce, no gather. ONE
            # count fetch per table (each RPC is ~100 ms tunneled)
            # serves the overflow check and the layout decision, and
            # string-storage alignment waits until the compare is
            # actually going to run on these layouts.
            ca, cb = dtable.host_counts(ta), dtable.host_counts(tb)
            cap_l = dtable.local_capacity(ta)
            if (ca > cap_l).any() or (cb > cap_l).any():
                dtable.dist_num_rows(ta)  # raises with the poisoned
                dtable.dist_num_rows(tb)  # shard's counts
            if ca.sum() != cb.sum():
                return False
            if (ca == cb).all():
                aligned = align_for_equal(ta, tb)
                if aligned is None:
                    return False
                return bool(np.asarray(
                    dist_ordered_equal_compiled(*aligned)))
            # equal totals but different shard boundaries: positional
            # equality needs the concatenated view — gather fallback
        ta = dtable.gather_table(None, ta)
        tb = dtable.gather_table(None, tb)
        return equal_tables(ta, tb, ordered=True)

    def isin(self, values: Sequence) -> "DataFrame":
        """Parity: frame.py isin (membership per element). Delegates to
        :meth:`Series.isin` per column — one implementation of the
        null-probe / type-mismatch semantics for both surfaces."""
        t = self._table
        vals = list(values)
        cols = {name: self.series(name).isin(vals).column
                for name in t.column_names}
        return DataFrame._wrap(Table(cols, t.nrows))

    # -- reductions ------------------------------------------------------
    def _reduce(self, op: str, env: CylonEnv | None = None,
                quantile: float = 0.5):
        out = {}
        local = None if env is not None else self._gathered()
        for name, c in self._table.columns.items():
            if not (c.dtype.is_numeric or op in ("count", "nunique")):
                continue
            if env is not None:
                out[name] = dist_aggregate(env, self._table, name, op,
                                           quantile=quantile)
            else:
                out[name] = _aggregates.table_aggregate(local, name, op,
                                                        quantile=quantile)
        return {k: np.asarray(v)[()] for k, v in out.items()}

    def sum(self, env=None): return self._reduce("sum", env)
    def count(self, env=None): return self._reduce("count", env)
    def min(self, env=None): return self._reduce("min", env)
    def max(self, env=None): return self._reduce("max", env)
    def mean(self, env=None): return self._reduce("mean", env)
    def var(self, env=None): return self._reduce("var", env)
    def std(self, env=None): return self._reduce("std", env)
    def nunique(self, env=None): return self._reduce("nunique", env)
    def median(self, env=None): return self._reduce("median", env)

    def quantile(self, q: float = 0.5, env=None):
        return self._reduce("quantile", env, quantile=q)

    # -- materialisation -------------------------------------------------
    def _gathered(self) -> Table:
        if self.is_distributed:
            return gather_table(None, self._table)
        return self._table

    def to_pandas(self):
        if self.is_distributed:
            return dist_to_pandas(None, self._table)
        return self._table.to_pandas()

    def to_dict(self):
        return self._gathered().to_pydict()

    def to_numpy(self):
        return self._gathered().to_numpy()

    def to_arrow(self):
        return self._gathered().to_arrow()

    def to_table(self) -> Table:
        return self._table


class GroupByDataFrame:
    """Parity: pycylon ``GroupByDataFrame`` (frame.py:120-180)."""

    def __init__(self, df: DataFrame, by: Sequence[str],
                 env: CylonEnv | None = None):
        self._df = df
        self._by = list(by)
        self._env = env

    def agg(self, spec=None, out_capacity: int | None = None,
            **named) -> DataFrame:
        """spec: {col: op | [ops]} (pandas style), [(col, op[, name])],
        or pandas named aggregation — ``agg(out=("col", "op"), ...)``."""
        aggs = []
        if spec is None and not named:
            raise InvalidArgument(
                "agg() needs a spec ({col: op}, [(col, op[, name])]) or "
                "named aggregations (out=(col, op))")
        if named:
            if spec is not None:
                raise InvalidArgument(
                    "pass either a spec or named aggregations, not both")
            for name, co in named.items():
                if not isinstance(co, (tuple, list)) or len(co) != 2:
                    raise InvalidArgument(
                        f"named aggregation {name}=... must be a "
                        f"(column, op) pair, got {type(co).__name__}")
                col, op = co
                aggs.append((col, op, name))
        elif isinstance(spec, Mapping):
            for col, ops in spec.items():
                ops = [ops] if isinstance(ops, str) else list(ops)
                for op in ops:
                    aggs.append((col, op, f"{col}_{op}"))
        else:
            aggs = [tuple(a) for a in spec]
        if self._env is not None:
            t = dist_groupby(self._env, self._df.table, self._by, aggs,
                             out_capacity=out_capacity)
        else:
            t = _groupby_mod.groupby_aggregate(self._df._gathered(),
                                               self._by, aggs,
                                               out_capacity=out_capacity)
            t = _shrink(t)
        return DataFrame._wrap(t)

    def _all_value_cols(self, op):
        cols = [c for c in self._df.columns if c not in self._by]
        return self.agg([(c, op, c) for c in cols])

    def sum(self): return self._all_value_cols("sum")
    def count(self): return self._all_value_cols("count")
    def min(self): return self._all_value_cols("min")
    def max(self): return self._all_value_cols("max")
    def mean(self): return self._all_value_cols("mean")
    def std(self): return self._all_value_cols("std")
    def var(self): return self._all_value_cols("var")
    def nunique(self): return self._all_value_cols("nunique")
    def median(self): return self._all_value_cols("median")


def merge(left: DataFrame, right: DataFrame, **kw) -> DataFrame:
    """Module-level merge (pandas style)."""
    return left.merge(right, **kw)


# DataFrame rides jit boundaries as a pytree (whole-query compilation,
# cylon_tpu.plan): the wrapped Table is the traced child; the index is
# treedef metadata (value indexes are host-built and rarely cross a
# compiled query).
import jax as _jax  # noqa: E402

_jax.tree_util.register_pytree_node(
    DataFrame,
    lambda df: ((df._table,), df._index),
    lambda idx, children: DataFrame._wrap(children[0], idx),
)


def concat(frames: Sequence[DataFrame], env: CylonEnv | None = None,
           out_capacity: int | None = None) -> DataFrame:
    """Parity: pycylon ``concat`` (frame.py:1956) / ``distributed_concat``
    (``table.pyx:2398``). With an ``env``, every shard concatenates its
    local blocks in place — no gather, no shuffle (rank-local order,
    like the reference's distributed_concat); locally, frame-major
    pandas order."""
    if env is not None and out_capacity is None:
        from cylon_tpu.parallel import dist_concat

        return DataFrame._wrap(dist_concat(env, [f._table for f in frames]))
    # an explicit out_capacity needs one global buffer of that size —
    # concatenate locally at that capacity, then lay out on the mesh
    tables = [f._gathered() for f in frames]
    t = _selection.concat_tables(tables, capacity=out_capacity)
    if env is not None:
        t = scatter_table(env, t)
    return DataFrame._wrap(t)


def read_csv(path, options: CSVReadOptions | None = None,
             env: CylonEnv | None = None, **kw) -> DataFrame:
    """CSV ingest (parity: ``FromCSV``; full IO lives in cylon_tpu.io)."""
    from cylon_tpu.io import read_csv as _read_csv

    return _read_csv(path, options, env=env, **kw)
