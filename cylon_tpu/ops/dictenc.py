"""Dictionary (string) column alignment.

Device tables hold int32 codes; the values live host-side
(:class:`cylon_tpu.column.Dictionary`). Any op that compares string
columns *across* tables (concat, join keys, set ops) first re-encodes
them onto one shared sorted dictionary — a host-side metadata step whose
device part is a single gather (``new_code = remap[old_code]``).

This replaces the reference's byte-level binary comparators
(``arrow/arrow_comparator.cpp`` binary specialisations): on TPU we never
compare strings on device, only their order-preserving codes.
"""

import jax.numpy as jnp
import numpy as np

from cylon_tpu.column import Column, Dictionary
from cylon_tpu.table import Table


def unify_dictionaries(cols: list[Column]) -> list[Column]:
    """Re-encode dictionary columns onto one merged sorted dictionary.
    Non-dictionary columns pass through unchanged (all must agree)."""
    dict_cols = [c for c in cols if c.dtype.is_dictionary]
    if not dict_cols:
        return cols
    dicts = [c.dictionary for c in dict_cols]
    first = dicts[0]
    # content equality (Dictionary.__eq__), not identity: independently
    # ingested tables over the same value set share codes already and
    # need no remap
    if first is not None and all(d == first for d in dicts):
        return cols
    # ONE factorize merges + remaps: uniques come back in pandas
    # safe-sorted order — the same ordering ingest uses
    # (column.from_numpy's factorize(sort=True)) — which, unlike
    # np.unique/searchsorted, also handles mixed-type object values
    # (e.g. an int column that picked up Nones and ingested as a
    # dictionary of ints + the "" null placeholder)
    import pandas as pd

    vals = [(d.values if d is not None else np.asarray([], object))
            for d in dicts]
    # use_na_sentinel=False: a NaN dictionary VALUE (reachable via
    # Series.map producing NaN) must stay a real code — the default -1
    # sentinel would wrap on the next gather and read as another value
    flat_codes, merged = pd.factorize(np.concatenate(vals), sort=True,
                                      use_na_sentinel=False)
    merged = np.asarray(merged, dtype=object)
    flat_codes = np.asarray(flat_codes)
    na = np.asarray(pd.isna(merged))
    if na.any():
        # keep the order-preserving invariant (code order == value
        # order, NA last — where ingest's "" placeholder and np.unique
        # both rank missing)
        order = np.concatenate([np.flatnonzero(~na), np.flatnonzero(na)])
        inv = np.empty(len(order), np.int64)
        inv[order] = np.arange(len(order))
        merged = merged[order]
        flat_codes = inv[flat_codes]
    shared = Dictionary(merged)
    offsets = np.cumsum([0] + [len(v) for v in vals])
    out = []
    di = 0
    for c in cols:
        if not c.dtype.is_dictionary:
            out.append(c)
            continue
        remap = flat_codes[offsets[di]:offsets[di + 1]].astype(np.int32)
        di += 1
        if len(remap):
            codes = jnp.asarray(remap)[jnp.clip(c.data, 0, len(remap) - 1)]
        else:
            codes = c.data
        out.append(Column(codes, c.validity, c.dtype, shared))
    return out


def unify_table_dictionaries(tables: list[Table]) -> list[Table]:
    """Column-name-wise dictionary unification across tables."""
    if len(tables) < 2:
        return list(tables)
    names = tables[0].column_names
    new_cols = {t_i: {} for t_i in range(len(tables))}
    for name in names:
        cols = [t.column(name) for t in tables]
        unified = unify_dictionaries(cols)
        for i, c in enumerate(unified):
            new_cols[i][name] = c
    return [Table(new_cols[i], t.nrows) for i, t in enumerate(tables)]


def reencode_values(col: Column, new_values) -> Column:
    """Replace the dictionary's values with ``new_values`` (one per old
    code, e.g. after an elementwise map), restoring the sorted-unique
    invariant (code order == value order) via a device code remap."""
    vals = np.asarray(new_values, dtype=object)
    uniq, inverse = np.unique(vals, return_inverse=True)
    remap = inverse.astype(np.int32)
    if len(remap):
        codes = jnp.asarray(remap)[jnp.clip(col.data, 0, len(remap) - 1)]
    else:
        codes = col.data
    return Column(codes, col.validity, col.dtype, Dictionary(uniq))


def encode_fill_value(col: Column, value):
    """Resolve ``value`` to a code of ``col``'s dictionary, extending and
    re-sorting the dictionary (with a device-side code remap) when the
    value is absent. Used by fillna on string columns."""
    values = col.dictionary.values if col.dictionary is not None \
        else np.array([], dtype=object)
    hit = np.where(values == value)[0]
    if len(hit):
        return col, int(hit[0])
    merged = np.unique(np.concatenate([values, np.array([value], object)]))
    remap = np.searchsorted(merged, values).astype(np.int32)
    code = int(np.searchsorted(merged, np.array([value], object))[0])
    if len(remap):
        codes = jnp.asarray(remap)[jnp.clip(col.data, 0, len(remap) - 1)]
    else:
        codes = jnp.zeros_like(col.data)
    return Column(codes, col.validity, col.dtype, Dictionary(merged)), code
